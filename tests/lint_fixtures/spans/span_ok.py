"""Minimal-fix sibling for span-force.  MUST produce no findings."""

import jax

from ccsx_tpu.utils import trace


def dispatch(step, big, small, group):
    with trace.device_span("dispatch", group=group) as sp:
        return sp.force(step(big, small))


def warmup(step, args, group):
    with trace.device_span("warmup", group=group, warmup=True):
        jax.block_until_ready(step(*args))


def dispatch_deadline(runner, step, big, small, group):
    # the deadline-runner shape: the forcing closure is handed off,
    # but it lives inside the span body
    with trace.device_span("dispatch", group=group) as sp:
        return runner(lambda: sp.force(step(big, small)))
