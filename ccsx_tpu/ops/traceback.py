"""Device traceback: move matrix -> star-MSA projection.

Converts the packed move bytes emitted by ``banded_align(mode='global',
with_moves=True)`` into the template-anchored projection used by the
consensus vote (the same representation oracle.project_to_template builds):

  aligned[j]   query code aligned to template column j (0-3), 4 = deletion
  ins_cnt[j]   number of query bases inserted after template column j
  ins_b[j, r]  the last ``max_ins`` inserted bases after column j, in
               forward order, left-justified (PAD=5 elsewhere)
  lead_ins     query bases consumed before template column 0 (counted for
               cursor bookkeeping; not voted)

The walk is a ``lax.while_loop`` from (qlen, tlen) back to (0, 0); batched
with vmap it advances all alignments in lockstep, so each step is a batched
gather from the move matrices (HBM) plus masked scatters into the
projection arrays.  This replaces the role of bsalign's MSA materialization
(tidy_msa_bspoa, main.c:572) — our "MSA" is the stack of these projections.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ccsx_tpu.ops.banded import EBIT_EXT, FBIT_EXT, MOVE_UP

GAP = 4
PAD = 5

_H, _E, _F = 0, 1, 2


def make_projector(tmax: int, max_ins: int = 4):
    """Build a jitted projector for templates padded to ``tmax`` columns."""

    @jax.jit
    def project(moves, offs, q, qlen, tlen):
        qmax = q.shape[0]
        B = moves.shape[1]
        aligned = jnp.full((tmax,), PAD, jnp.uint8)
        # slot s+1 holds insertions after template column s; slot 0 holds
        # the leading insertions (query bases before template column 0),
        # which cursor bookkeeping must still count (main.c:622-638 walks
        # every MSA cell)
        ins_cnt = jnp.zeros((tmax + 1,), jnp.int32)
        ins_b = jnp.full((tmax + 1, max_ins), PAD, jnp.uint8)

        def cond(st):
            i, j, state, *_ = st
            return (i > 0) | (j > 0)

        def body(st):
            i, j, state, aligned, ins_cnt, ins_b = st
            # move byte of cell (i, j); rows are 1-indexed: row i at moves[i-1]
            row = jnp.clip(i - 1, 0, qmax - 1)
            lane = jnp.clip(j - offs[row], 0, B - 1)
            m = moves[row, lane].astype(jnp.int32)
            choice = m & 3

            def do_diag(st):
                i, j, state, aligned, ins_cnt, ins_b = st
                aligned = aligned.at[j - 1].set(q[i - 1])
                return (i - 1, j - 1, jnp.int32(_H), aligned, ins_cnt, ins_b)

            def do_up(st):
                # consume one query base as an insertion after column j-1
                # (slot j in the shifted ins arrays; j == 0 -> leading slot)
                i, j, state, aligned, ins_cnt, ins_b = st
                slot = j
                cnt = ins_cnt[slot]
                pos = max_ins - 1 - cnt
                ins_b = jax.lax.cond(
                    pos >= 0,
                    lambda b: b.at[slot, jnp.maximum(pos, 0)].set(q[i - 1]),
                    lambda b: b,
                    ins_b,
                )
                ins_cnt = ins_cnt.at[slot].add(1)
                nxt = jnp.where((m & EBIT_EXT) != 0, _E, _H)
                # boundary: column 0 of the DP is a forced vertical run
                nxt = jnp.where(j == 0, _E, nxt).astype(jnp.int32)
                return (i - 1, j, nxt, aligned, ins_cnt, ins_b)

            def do_left(st):
                i, j, state, aligned, ins_cnt, ins_b = st
                aligned = aligned.at[j - 1].set(GAP)
                nxt = jnp.where((m & FBIT_EXT) != 0, _F, _H)
                nxt = jnp.where(i == 0, _F, nxt).astype(jnp.int32)
                return (i, j - 1, nxt, aligned, ins_cnt, ins_b)

            # boundary overrides: off the matrix edges the op is forced
            forced_up = (j == 0) & (i > 0)
            forced_left = (i == 0) & (j > 0)
            op = jnp.where(
                forced_up, 1,
                jnp.where(
                    forced_left, 2,
                    jnp.where(
                        state == _E, 1,
                        jnp.where(
                            state == _F, 2,
                            jnp.where(choice == 0, 0,
                                      jnp.where(choice == MOVE_UP, 1, 2)),
                        ),
                    ),
                ),
            )
            return jax.lax.switch(op, [do_diag, do_up, do_left], st)

        i0 = qlen.astype(jnp.int32)
        j0 = tlen.astype(jnp.int32)
        st = (i0, j0, jnp.int32(_H), aligned, ins_cnt, ins_b)
        _, _, _, aligned, ins_cnt, ins_b = jax.lax.while_loop(cond, body, st)

        # left-justify the right-aligned insertion cells
        used = jnp.minimum(ins_cnt, max_ins)
        shift = (max_ins - used)[:, None]
        cols = jnp.arange(max_ins)[None, :] + shift
        ins_b = jnp.take_along_axis(
            ins_b, jnp.clip(cols, 0, max_ins - 1), axis=1
        )
        ins_b = jnp.where(jnp.arange(max_ins)[None, :] < used[:, None],
                          ins_b, PAD)
        # split the leading slot back out: index j = insertions after
        # template column j; lead_ins = query bases before column 0
        return aligned, ins_cnt[1:], ins_b[1:], ins_cnt[0]

    return project
