"""Honest CPU yardstick for the bench.py round metric.

The north star (BASELINE.md) is >=8x vs 64-thread CPU ccsx, but the
reference binary is not buildable offline (its bsalign dependency is
cloned at build time, reference README.md:11).  This script measures the
CPU side of the comparison on the workload the reference actually runs
— bsalign's banded SIMD fill (band=128, reference main.c:849) — using
two builds of IDENTICAL native source (native/baseline_simd.cpp,
Makefile): one vectorized (-O3 -march=native), one scalar control
(-O2 -fno-tree-vectorize).  The artifact records:

  per_core_cells_per_sec        measured, VECTORIZED banded fill
  per_core_scalar_cells_per_sec measured, scalar control, same source
  simd_factor                   MEASURED vec/scalar ratio (replaces the
                                r1-r4 artifacts' guessed 8.0 credit;
                                VERDICT r4 item 4)
  gotoh_full_cells_per_sec      the old full-matrix scalar Gotoh
                                (align_native.cpp) for artifact
                                continuity with r1-r4
  thread_scaling                pairs/s at 1/2/4/8 threads over the
                                kthread-shaped pair pool (on a 1-core
                                host this measures the host; recorded
                                with host_cores so nobody reads it as
                                the pool)
  cells_per_sec_64core          per-core VECTORIZED x 64 (linear-
                                scaling credit, the one remaining
                                projection, stated as such)
  zmw_windows_per_sec_*         the same numbers in bench.py round
                                units (one zmw-window = P x W x band)

bench.py reports vs_baseline against the 64-core VECTORIZED projection
— the strongest defensible CPU number — so the north-star margin no
longer rests on an unfalsifiable 8x guess.

Usage: python benchmarks/cpu_baseline.py [--write]
"""

import argparse
import ctypes
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# bench.py round-unit geometry — imported, not duplicated, so the
# artifact's cells_per_zmw_window can never drift from the bench shapes
# (bench.py refuses vs_baseline when it detects a mismatch anyway)
import bench as _bench  # noqa: E402  (repo root is on sys.path above)

P, W = _bench.P, _bench.W
BAND = 128  # AlignParams().band == the bench round's band
CELLS_PER_ZMW_WINDOW = P * W * BAND
PROJECTED_CORES = 64


def _lib():
    from ccsx_tpu import native

    L = native.lib()
    if L is None:
        raise RuntimeError("native library unavailable (build failed?)")
    L.ccsx_banded_fill_many.restype = ctypes.c_int64
    return L


def measure_banded(L, vectorized, seconds=2.0, qlen=1000, tlen=1000,
                   npairs=64):
    """Best-of-windows banded-fill cells/s (single thread).

    Best-of-3 windows: the measurement host is shared, and the scalar/
    vec ratio must compare two best-cases, not one best-case against
    one noise-hit (same protocol as the TPU round metric)."""
    rng = np.random.default_rng(0)
    qs = np.ascontiguousarray(rng.integers(0, 4, (npairs, qlen)), np.uint8)
    ts = np.ascontiguousarray(rng.integers(0, 4, (npairs, tlen)), np.uint8)
    pq = qs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    pt = ts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    best = 0.0
    for _ in range(3):
        done, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < seconds / 3:
            cells = L.ccsx_banded_fill_many(
                pq, pt, qlen, tlen, npairs, 1, int(vectorized),
                2, -6, -3, -2, None)
            assert cells > 0
            done += cells
        best = max(best, done / (time.perf_counter() - t0))
    return best


def measure_threads(L, qlen=1000, tlen=1000, npairs=256):
    """Pair-pool throughput at 1/2/4/8 threads (kthread.c:48-65 shape)."""
    rng = np.random.default_rng(1)
    qs = np.ascontiguousarray(rng.integers(0, 4, (npairs, qlen)), np.uint8)
    ts = np.ascontiguousarray(rng.integers(0, 4, (npairs, tlen)), np.uint8)
    pq = qs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    pt = ts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    out = {}
    for nt in (1, 2, 4, 8):
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            cells = L.ccsx_banded_fill_many(
                pq, pt, qlen, tlen, npairs, nt, 1, 2, -6, -3, -2, None)
            dt = time.perf_counter() - t0
            best = max(best, cells / dt)
        out[f"t{nt}"] = best
    return out


def measure_gotoh_full(seconds=2.0, qlen=1000, tlen=1000):
    """The r1-r4 artifact's metric (full-matrix scalar Gotoh), kept for
    continuity so the old and new baselines are comparable."""
    from ccsx_tpu.native.align import align_scalar_native

    rng = np.random.default_rng(0)
    q = rng.integers(0, 4, qlen).astype(np.uint8)
    t = rng.integers(0, 4, tlen).astype(np.uint8)
    if align_scalar_native(q, t) is None:
        raise RuntimeError("native aligner unavailable (build failed?)")
    count = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        align_scalar_native(q, t)
        count += 1
    return count * qlen * tlen / (time.perf_counter() - t0)


def build_baseline():
    L = _lib()
    vec = measure_banded(L, vectorized=True)
    scal = measure_banded(L, vectorized=False)
    gotoh = measure_gotoh_full()
    threads = measure_threads(L)
    c64 = vec * PROJECTED_CORES
    return {
        "per_core_cells_per_sec": vec,
        "per_core_scalar_cells_per_sec": scal,
        "simd_factor": round(vec / scal, 2),
        "gotoh_full_cells_per_sec": gotoh,
        "measured_cores": 1,
        "host_cores": os.cpu_count(),
        "thread_scaling_pairs_pool_cells_per_sec": threads,
        "cells_per_sec_64core": c64,
        "zmw_windows_per_sec": c64 / CELLS_PER_ZMW_WINDOW,
        "cells_per_zmw_window": CELLS_PER_ZMW_WINDOW,
        "projected_cores": PROJECTED_CORES,
        "note": "banded SIMD fill (native/baseline_simd.cpp, band=128, "
                "the bsalign-fill workload): per-core cells/s MEASURED "
                "on the vectorized build; simd_factor is the MEASURED "
                "vec/scalar ratio of identical source (replaces the "
                "r1-r4 guessed 8x credit); the only remaining "
                "projection is x64 linear core scaling, and "
                "thread_scaling on this host measures the host "
                f"({os.cpu_count()} core(s)), not the pool",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true",
                    help="write bench_baseline.json at the repo root")
    a = ap.parse_args()
    b = build_baseline()
    print(json.dumps(b, indent=1))
    if a.write:
        path = os.path.join(_REPO, "bench_baseline.json")
        with open(path, "w") as f:
            json.dump(b, f, indent=1)
        print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
