"""`ccsx-tpu report`: a self-contained static HTML run report.

Every bench round ships JSONL artifacts (``--trace`` spans, ``--metrics``
events); this renders one human-readable page next to them — the
artifact an operator actually opens before JSONL archaeology:

* run header + health banner (degraded mark, stalls, fallbacks);
* a timeline strip of the trace spans (one lane per thread, colored by
  span category, compile calls hatched out by a marker) — the
  Chrome-export view without needing Perfetto;
* the per-shape-group compile/execute table and the per-category stage
  self-time breakdown (both re-derived through utils/trace.summarize,
  the SAME finalizer the stats subcommand and metrics events use);
* occupancy / fill stat tiles;
* the stall + recovery incident log;
* the ETA-vs-actual curve from the progress estimator's periodic
  events, with a median-error recap (how trustworthy was the live ETA).

Self-contained: inline CSS, inline SVG, zero JS, zero external fetches
— the file can be committed, mailed, or served from a dumb bucket.
Light and dark mode both render from the palette below (selected steps,
not an automatic flip).  No jax import, no backend init — safe on a
host whose accelerator is hung (same discipline as `stats`).

Streaming bounds: span rectangles are capped to the MAX_TIMELINE
longest (a million-hole trace renders the load-bearing spans, with the
drop counted in the caption — no silent truncation), incidents to
MAX_INCIDENTS, and the second pass reuses summarize()'s own streaming
discipline.
"""

from __future__ import annotations

import heapq
import html
import json
import os
import sys
from typing import List, Optional

from ccsx_tpu.utils import trace as trace_mod

MAX_TIMELINE = 4000     # span rects kept (longest win); rest counted
MAX_INCIDENTS = 300
MAX_LANES = 16          # timeline thread lanes

# span categories in fixed categorical-slot order (identity colors are
# assigned by this order, never cycled — the palette below validates
# adjacency in this order in both modes)
CAT_ORDER = ("device", "compute", "ingest", "prep", "write", "journal",
             "host", "recover")
# categorical slots 1..8 (light, dark) — validated reference palette
_SLOTS = (("#2a78d6", "#3987e5"), ("#eb6834", "#d95926"),
          ("#1baf7a", "#199e70"), ("#eda100", "#c98500"),
          ("#e87ba4", "#d55181"), ("#008300", "#008300"),
          ("#4a3aa7", "#9085e9"), ("#e34948", "#e66767"))

# snapshot keys the occupancy/fill tiles render (schema-drift guard:
# tests cross-check these against Metrics.snapshot())
REPORT_TILE_KEYS = (
    "zmws_per_sec", "dp_occupancy", "dp_row_fill",
    "packed_holes_per_dispatch", "fused_slot_fill", "compile_share",
    "prep_share", "prep_overlap_share",
    "distinct_slab_shapes", "holes_filtered",
)
# final-event counters the header table renders (device_hangs /
# breaker_* are the resilient-execution story: abandoned dispatches and
# the circuit breaker's verdict ride every run report)
REPORT_HEADER_KEYS = (
    "holes_in", "holes_out", "holes_failed", "holes_filtered",
    "holes_corrupt",
    "windows", "device_dispatches", "oom_resplits", "host_fallbacks",
    "device_hangs", "breaker_trips", "breaker_state",
    "stalls", "elapsed_s", "ingest_bytes",
)


def collect(paths: List[str]) -> dict:
    """One streaming pass over mixed trace/metrics JSONL: bounded span
    set for the timeline, progress-event series for the ETA curve,
    incident log, and the last/final metrics snapshot."""
    spans_heap: list = []    # min-heap of (dur, seq, lite-span)
    seq = 0
    n_spans = 0
    t_end = 0.0
    progress: list = []      # (elapsed_s, eta_s, pct, done)
    incidents: list = []
    meta = None
    final = None
    last_metrics = None
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                ev = rec.get("ev")
                if ev == "meta":
                    meta = rec
                elif ev == "span":
                    n_spans += 1
                    t_end = max(t_end, rec["mono"] + rec["dur"])
                    args = rec.get("args", {})
                    lite = {"name": rec["name"], "cat": rec["cat"],
                            "mono": rec["mono"], "dur": rec["dur"],
                            "tid": rec.get("tid", "main"),
                            "compile": bool(rec.get("compile")),
                            "warmup": bool(rec.get("warmup")),
                            "group": args.get("group")}
                    seq += 1
                    if len(spans_heap) < MAX_TIMELINE:
                        heapq.heappush(spans_heap,
                                       (rec["dur"], seq, lite))
                    elif rec["dur"] > spans_heap[0][0]:
                        heapq.heapreplace(spans_heap,
                                          (rec["dur"], seq, lite))
                    if args.get("error") and len(incidents) < MAX_INCIDENTS:
                        incidents.append(
                            (rec["mono"], "error",
                             f"dispatch {rec['name']} "
                             f"group={args.get('group')} failed after "
                             f"{rec['dur']:.3f}s"))
                elif ev == "instant":
                    if (rec.get("cat") == "recover"
                            and len(incidents) < MAX_INCIDENTS):
                        incidents.append(
                            (rec["mono"], "recover",
                             f"{rec['name']} "
                             f"{json.dumps(rec.get('args', {}))}"))
                elif ev == "stall":
                    if len(incidents) < MAX_INCIDENTS:
                        incidents.append(
                            (rec.get("mono", 0.0), "stall",
                             f"STALL: {rec.get('name')} "
                             f"group={rec.get('group')} open "
                             f"{rec.get('open_s')}s"
                             + (" (repeat)" if rec.get("repeat")
                                else "")))
                elif "event" in rec:
                    last_metrics = rec
                    if rec["event"] == "final":
                        final = rec
                    prog = rec.get("progress")
                    if prog and prog.get("elapsed_s") is not None:
                        progress.append((prog["elapsed_s"],
                                         prog.get("eta_s"),
                                         prog.get("pct"),
                                         prog.get("done")))
    spans = [s for _, _, s in
             sorted(spans_heap, key=lambda t: t[2]["mono"])]
    incidents.sort(key=lambda t: t[0])
    return {"spans": spans, "n_spans": n_spans, "t_end": t_end,
            "progress": progress, "incidents": incidents, "meta": meta,
            "final": final, "last_metrics": last_metrics}


def collect_fleet(d: str) -> dict:
    """Stitch a FLEET's per-process JSONL files (a spool/fleet dir and
    its immediate subdirs — fan-out dirs, worker trace files) into
    per-JOB span sets keyed by correlation id.

    Per-process monotonic clocks do not compose, so cross-process
    alignment uses the WALL timestamp every span record carries
    (``ts``, stamped at span open); within one fleet the boxes are
    NTP-close and the render granularity is milliseconds.  Spans
    without a ``cid`` belong to no job (server warmup, idle scans) and
    are left out of the per-job timelines."""
    import glob as globmod

    paths = sorted(set(
        globmod.glob(os.path.join(d, "*.jsonl"))
        + globmod.glob(os.path.join(d, "*", "*.jsonl"))))
    jobs: dict = {}
    for path in paths:
        src = os.path.basename(path)
        if src.endswith(".jsonl"):
            src = src[:-len(".jsonl")]
        try:
            f = open(path, encoding="utf-8")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                cid = rec.get("cid")
                if (rec.get("ev") != "span" or not cid
                        or rec.get("ts") is None):
                    continue
                args = rec.get("args", {})
                # parse BEFORE creating the job entry: a cid whose
                # every record is malformed/torn must not leave an
                # empty-span job that crashes the alignment below
                try:
                    span = {
                        "name": rec["name"],
                        "cat": rec.get("cat", "host"),
                        "ts": float(rec["ts"]),
                        "dur": float(rec["dur"]),
                        "tid": f"{src}:{rec.get('tid', 'main')}",
                        "compile": bool(rec.get("compile")),
                        "warmup": bool(rec.get("warmup")),
                        "group": args.get("group")}
                except (KeyError, TypeError, ValueError):
                    continue
                j = jobs.setdefault(cid, {"spans": [],
                                          "sources": set()})
                j["spans"].append(span)
                j["sources"].add(src)
    for j in jobs.values():
        spans = j["spans"]
        t0 = min(s["ts"] for s in spans)
        for s in spans:
            s["mono"] = s["ts"] - t0   # job-relative wall offset
        spans.sort(key=lambda s: s["mono"])
        j["t0"] = t0
        j["t_end"] = max(s["mono"] + s["dur"] for s in spans)
    return {"paths": paths, "jobs": jobs}


# ---- SVG helpers ----------------------------------------------------------

def _esc(v) -> str:
    return html.escape(str(v), quote=True)


def _timeline_svg(spans: List[dict], t_end: float, n_spans: int) -> str:
    """Per-thread lanes of category-colored span rects, with native
    <title> hover tooltips (the no-JS hover layer)."""
    if not spans or t_end <= 0:
        return "<p class='muted'>no trace spans in the input " \
               "(metrics-only report)</p>"
    lanes: dict = {}
    for s in spans:
        if s["tid"] not in lanes and len(lanes) < MAX_LANES:
            lanes[s["tid"]] = len(lanes)
    width, lane_h, pad_l = 1000, 20, 150
    height = lane_h * len(lanes) + 24
    out = [f"<svg viewBox='0 0 {width + pad_l} {height}' "
           f"role='img' aria-label='span timeline' "
           f"style='width:100%;height:auto'>"]
    # x-axis ticks (recessive)
    for i in range(5):
        x = pad_l + width * i / 4
        t = t_end * i / 4
        out.append(f"<line x1='{x:.1f}' y1='0' x2='{x:.1f}' "
                   f"y2='{height - 16}' class='grid'/>")
        anchor = "end" if i == 4 else "middle" if i else "start"
        out.append(f"<text x='{x:.1f}' y='{height - 4}' "
                   f"class='tick' text-anchor='{anchor}'>"
                   f"{t:.1f}s</text>")
    for tid, lane in lanes.items():
        y = lane * lane_h
        out.append(f"<text x='{pad_l - 8}' y='{y + 14}' class='tick' "
                   f"text-anchor='end'>{_esc(tid[:22])}</text>")
    dropped = 0
    for s in spans:
        lane = lanes.get(s["tid"])
        if lane is None:
            dropped += 1
            continue
        x = pad_l + s["mono"] / t_end * width
        w = max(s["dur"] / t_end * width, 0.75)
        y = lane * lane_h + 3
        cls = f"c-{s['cat']}" if s["cat"] in CAT_ORDER else "c-host"
        tip = (f"{s['name']} [{s['cat']}] {s['dur'] * 1e3:.2f} ms "
               f"@{s['mono']:.3f}s"
               + (f" group={s['group']}" if s["group"] else "")
               + (" COMPILE" if s["compile"] else "")
               + (" warmup" if s["warmup"] else ""))
        extra = " stroke='var(--ink)' stroke-width='0.6'" \
            if s["compile"] else ""
        out.append(f"<rect x='{x:.2f}' y='{y}' width='{w:.2f}' "
                   f"height='{lane_h - 6}' rx='2' class='{cls}'"
                   f"{extra}><title>{_esc(tip)}</title></rect>")
    out.append("</svg>")
    cap = ""
    if n_spans > len(spans) or dropped:
        cap = (f"<p class='muted'>showing the {len(spans) - dropped} "
               f"longest of {n_spans} spans"
               + (f"; {dropped} on threads beyond the first "
                  f"{MAX_LANES} lanes omitted" if dropped else "")
               + "</p>")
    return "".join(out) + cap


def _eta_svg(progress: list, actual_total: Optional[float]) -> str:
    """Predicted remaining (live ETA) vs actual remaining over elapsed
    time — two lines, direct-labeled."""
    pts = [(e, eta) for e, eta, _pct, _d in progress if eta is not None]
    if not pts or not actual_total:
        return ("<p class='muted'>no ETA samples (unknown-total run, "
                "or no periodic progress events in the metrics "
                "input)</p>")
    width, height, pad_l, pad_b = 640, 220, 56, 28
    xmax = max(actual_total, max(e for e, _ in pts)) or 1.0
    ymax = max(max(eta for _, eta in pts),
               max(actual_total - e for e, _ in pts), 1.0)

    def xy(e, v):
        x = pad_l + e / xmax * (width - pad_l - 8)
        y = 8 + (1 - v / ymax) * (height - pad_b - 16)
        return f"{x:.1f},{y:.1f}"

    pred = " ".join(xy(e, eta) for e, eta in pts)
    act = " ".join(xy(e, max(actual_total - e, 0.0)) for e, _ in pts)
    out = [f"<svg viewBox='0 0 {width} {height}' role='img' "
           f"aria-label='ETA vs actual' "
           f"style='max-width:{width}px;width:100%;height:auto'>"]
    for i in range(4):
        y = 8 + i * (height - pad_b - 16) / 3
        v = ymax * (1 - i / 3)
        out.append(f"<line x1='{pad_l}' y1='{y:.1f}' x2='{width - 8}' "
                   f"y2='{y:.1f}' class='grid'/>")
        out.append(f"<text x='{pad_l - 6}' y='{y + 4:.1f}' class='tick' "
                   f"text-anchor='end'>{v:.0f}s</text>")
    for i in range(5):
        x = pad_l + i * (width - pad_l - 8) / 4
        out.append(f"<text x='{x:.1f}' y='{height - 8}' class='tick' "
                   f"text-anchor='middle'>{xmax * i / 4:.0f}s</text>")
    out.append(f"<polyline points='{pred}' class='line-pred'/>")
    out.append(f"<polyline points='{act}' class='line-act'/>")
    # direct labels (identity never color-alone)
    out.append(f"<text x='{pad_l + 6}' y='20' class='lbl-pred'>"
               f"predicted remaining (live ETA)</text>")
    out.append(f"<text x='{pad_l + 6}' y='36' class='lbl-act'>"
               f"actual remaining</text>")
    out.append("</svg>")
    errs = [abs((e + eta) - actual_total) / actual_total
            for e, eta in pts]
    errs.sort()
    med = errs[len(errs) // 2] * 100
    out.append(f"<p class='muted'>{len(pts)} ETA samples; median "
               f"|predicted finish − actual| = {med:.1f}% of the "
               f"{actual_total:.0f}s wall</p>")
    return "".join(out)


def _stage_bars(stage_seconds: dict) -> str:
    if not stage_seconds:
        return "<p class='muted'>no span input — stage breakdown " \
               "needs a trace file</p>"
    total = sum(stage_seconds.values()) or 1.0
    rows = []
    for cat in sorted(stage_seconds, key=stage_seconds.get,
                      reverse=True):
        v = stage_seconds[cat]
        pct = v / total * 100
        cls = f"c-{cat}" if cat in CAT_ORDER else "c-host"
        rows.append(
            "<div class='bar-row'>"
            f"<span class='bar-lbl'>{_esc(cat)}</span>"
            f"<span class='bar-track'><span class='bar-fill {cls}' "
            f"style='width:{max(pct, 0.5):.2f}%'></span></span>"
            f"<span class='bar-val'>{v:.2f}s ({pct:.1f}%)</span>"
            "</div>")
    return ("<div class='bars'>" + "".join(rows)
            + "</div><p class='muted'>span self-seconds by category; "
              "nested children excluded (same sums as `ccsx-tpu "
              "stats`)</p>")


def _group_table(groups: dict, forced) -> str:
    if not groups:
        return "<p class='muted'>no shape groups in the input</p>"
    head = ("<tr><th>group</th><th>compiles</th><th>compile_s</th>"
            "<th>execute_s</th><th>dispatches</th><th>dp_cells</th>"
            "<th>dp_cells/s</th></tr>")
    rows = []
    for key, st in sorted(groups.items()):
        warn = " class='warn'" if st.get("compiles", 0) > 2 else ""
        cps = st.get("dp_cells_per_sec")
        rows.append(
            f"<tr{warn}><td class='mono'>{_esc(key)}</td>"
            f"<td>{st['compiles']}</td><td>{st['compile_s']}</td>"
            f"<td>{st['execute_s']}</td><td>{st['dispatches']}</td>"
            f"<td>{st['dp_cells']}</td>"
            f"<td>{cps if cps is not None else '—'}</td></tr>")
    note = ""
    if forced is False:
        note = ("<p class='warn-text'>⚠ UNFORCED timing (no --trace): "
                "per-group seconds are dispatch-queue bookkeeping on "
                "an async backend — counts exact, rates unreliable</p>")
    return note + "<table>" + head + "".join(rows) + "</table>"


def _tiles(snap: dict) -> str:
    tiles = []
    for k in REPORT_TILE_KEYS:
        v = snap.get(k)
        if v is None:
            continue
        tiles.append(f"<div class='tile'><div class='tile-v'>{_esc(v)}"
                     f"</div><div class='tile-k'>{_esc(k)}</div></div>")
    if not tiles:
        return "<p class='muted'>no metrics snapshot in the input</p>"
    return "<div class='tiles'>" + "".join(tiles) + "</div>"


def _incident_log(incidents: list, degraded) -> str:
    if not incidents and not degraded:
        return "<p class='muted'>no stalls, recoveries, or failed " \
               "dispatches recorded — clean run</p>"
    rows = []
    for mono, kind, text in incidents:
        cls = {"stall": "crit", "error": "crit",
               "recover": "warn-text"}.get(kind, "")
        rows.append(f"<li class='{cls}'><span class='mono'>"
                    f"{mono:9.3f}s</span> [{kind}] {_esc(text)}</li>")
    return "<ul class='log'>" + "".join(rows) + "</ul>"


# ---- page assembly --------------------------------------------------------

_CSS_TMPL = """
:root { color-scheme: light dark; }
body { margin: 2rem auto; max-width: 1080px; padding: 0 1rem;
  font-family: system-ui, -apple-system, "Segoe UI", sans-serif;
  background: light-dark(#f9f9f7, #0d0d0d);
  color: light-dark(#0b0b0b, #ffffff); }
section { background: light-dark(#fcfcfb, #1a1a19);
  border: 1px solid light-dark(rgba(11,11,11,.10), rgba(255,255,255,.10));
  border-radius: 8px; padding: 1rem 1.25rem; margin: 1rem 0; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; }
.muted { color: #898781; font-size: .85rem; }
.mono { font-family: ui-monospace, monospace; font-size: .85em; }
.banner { border-radius: 6px; padding: .6rem 1rem; font-weight: 600; }
.banner.ok { background: color-mix(in srgb, #0ca30c 12%, transparent);
  color: light-dark(#006300, #0ca30c); }
.banner.bad { background: color-mix(in srgb, #d03b3b 14%, transparent);
  color: #d03b3b; }
table { border-collapse: collapse; width: 100%; font-size: .85rem;
  font-variant-numeric: tabular-nums; }
th, td { text-align: right; padding: .25rem .6rem;
  border-bottom: 1px solid light-dark(#e1e0d9, #2c2c2a); }
th:first-child, td:first-child { text-align: left; }
tr.warn td { color: #d03b3b; }
.warn-text { color: light-dark(#b87700, #fab219); }
.crit { color: #d03b3b; }
.tiles { display: flex; flex-wrap: wrap; gap: .75rem; }
.tile { border: 1px solid light-dark(#e1e0d9, #2c2c2a);
  border-radius: 6px; padding: .5rem .9rem; min-width: 7rem; }
.tile-v { font-size: 1.25rem; font-weight: 650; }
.tile-k { color: #898781; font-size: .72rem; }
.bars { display: grid; gap: .3rem; }
.bar-row { display: grid; grid-template-columns: 6rem 1fr 10rem;
  align-items: center; gap: .6rem; font-size: .85rem; }
.bar-track { background: light-dark(#e1e0d9, #2c2c2a);
  border-radius: 4px; height: 12px; overflow: hidden; display: block; }
.bar-fill { display: block; height: 100%; border-radius: 4px; }
.bar-val { font-variant-numeric: tabular-nums; color:
  light-dark(#52514e, #c3c2b7); }
.log { font-size: .85rem; list-style: none; padding-left: 0; }
.log li { padding: .12rem 0; }
.grid { stroke: light-dark(#e1e0d9, #2c2c2a); stroke-width: 1; }
.tick { fill: #898781; font-size: 11px; }
svg { --ink: light-dark(#0b0b0b, #ffffff); }
.line-pred { fill: none; stroke: light-dark(#2a78d6, #3987e5);
  stroke-width: 2; }
.line-act { fill: none; stroke: light-dark(#eb6834, #d95926);
  stroke-width: 2; }
.lbl-pred { fill: light-dark(#1c5cab, #86b6ef); font-size: 12px; }
.lbl-act { fill: light-dark(#b84f20, #e8824f); font-size: 12px; }
.legend { display: flex; flex-wrap: wrap; gap: .9rem;
  font-size: .8rem; margin: .4rem 0; }
.legend .sw { display: inline-block; width: 10px; height: 10px;
  border-radius: 2px; margin-right: .3rem; }
%CATS%
"""


def _cat_css() -> str:
    rules = []
    for cat, (lt, dk) in zip(CAT_ORDER, _SLOTS):
        rules.append(f".c-{cat} {{ fill: light-dark({lt}, {dk}); "
                     f"background: light-dark({lt}, {dk}); }}")
    return "\n".join(rules)


def render_html(paths: List[str], title: Optional[str] = None) -> str:
    data = collect(paths)
    summary = trace_mod.summarize(paths)
    snap = data["final"] or data["last_metrics"] or {}
    degraded = snap.get("degraded") or summary.get("degraded")
    prog = snap.get("progress") or {}
    actual_total = prog.get("elapsed_s") or snap.get("elapsed_s")
    title = title or f"ccsx-tpu run report — {os.path.basename(paths[0])}"
    banner = (f"<div class='banner bad'>DEGRADED: {_esc(degraded)}"
              "</div>" if degraded else
              "<div class='banner ok'>healthy run — no watchdog "
              "stalls</div>")
    hdr_rows = "".join(
        f"<tr><td>{_esc(k)}</td><td>{_esc(snap.get(k))}</td></tr>"
        for k in REPORT_HEADER_KEYS if snap.get(k) is not None)
    legend = "<div class='legend'>" + "".join(
        f"<span><span class='sw c-{c}'></span>{c}</span>"
        for c in CAT_ORDER) + "</div>"
    gauges = "".join(
        f"<tr><td>{_esc(k)}</td><td>{_esc(snap[k])}</td></tr>"
        for k in ("peak_rss_bytes", "device_buffer_bytes")
        if snap.get(k) is not None)
    css = _CSS_TMPL.replace("%CATS%", _cat_css())
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{_esc(title)}</title>
<style>{css}</style></head><body>
<h1>{_esc(title)}</h1>
<p class='muted'>inputs: {_esc(' '.join(paths))} &middot;
{data['n_spans']} spans &middot; generated by `ccsx-tpu report`</p>
{banner}
<section><h2>Run summary</h2>
<table>{hdr_rows or "<tr><td class='muted'>no metrics input</td></tr>"}
{gauges}</table></section>
<section><h2>Timeline</h2>{legend}
{_timeline_svg(data['spans'], data['t_end'], data['n_spans'])}</section>
<section><h2>Stage self-time breakdown</h2>
{_stage_bars(summary.get('stage_seconds') or {})}</section>
<section><h2>Shape-group compile/execute table</h2>
{_group_table(summary.get('groups') or {}, summary.get('groups_forced'))}
</section>
<section><h2>Occupancy &amp; fill</h2>{_tiles(snap)}</section>
<section><h2>Progress: ETA vs actual</h2>
{_eta_svg(data['progress'], actual_total)}</section>
<section><h2>Stall &amp; recovery log</h2>
{_incident_log(data['incidents'], degraded)}</section>
</body></html>
"""


def render_fleet_html(d: str, title: Optional[str] = None) -> str:
    """`report --fleet`: one page, ONE merged timeline per job —
    every process that touched the job (holder replica, helper
    replicas, fan-out workers) interleaved on wall-aligned lanes,
    stitched by the correlation id the gateway minted at submission."""
    data = collect_fleet(d)
    jobs = data["jobs"]
    name = os.path.basename(os.path.normpath(d)) or d
    title = title or f"ccsx-tpu fleet report — {name}"
    legend = "<div class='legend'>" + "".join(
        f"<span><span class='sw c-{c}'></span>{c}</span>"
        for c in CAT_ORDER) + "</div>"
    sections = []
    for cid in sorted(jobs, key=lambda c: jobs[c]["t0"]):
        j = jobs[cid]
        n = len(j["spans"])
        spans = j["spans"]
        if n > MAX_TIMELINE:
            spans = sorted(spans, key=lambda s: s["dur"],
                           reverse=True)[:MAX_TIMELINE]
            spans.sort(key=lambda s: s["mono"])
        srcs = ", ".join(sorted(j["sources"]))
        sections.append(
            f"<section><h2>Job <span class='mono'>{_esc(cid)}</span>"
            f"</h2><p class='muted'>{n} spans across "
            f"{len(j['sources'])} source(s): {_esc(srcs)}</p>"
            f"{legend}{_timeline_svg(spans, j['t_end'], n)}</section>")
    if not sections:
        sections = [
            "<section><p class='muted'>no correlated spans found — "
            "fleet timelines need per-process --trace JSONL carrying "
            "correlation ids (jobs submitted through the gateway or "
            "serve API)</p></section>"]
    css = _CSS_TMPL.replace("%CATS%", _cat_css())
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{_esc(title)}</title>
<style>{css}</style></head><body>
<h1>{_esc(title)}</h1>
<p class='muted'>fleet dir: {_esc(d)} &middot;
{len(data['paths'])} JSONL file(s) &middot; {len(jobs)} correlated
job(s) &middot; generated by `ccsx-tpu report --fleet`</p>
{"".join(sections)}
</body></html>
"""


def default_out_path(first_input: str) -> str:
    base = (first_input[:-6] if first_input.endswith(".jsonl")
            else first_input)
    return base + ".report.html"


def report_main(argv) -> int:
    """The `ccsx-tpu report` subcommand (dispatched from cli.main)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="ccsx-tpu report",
        description="Render a self-contained HTML run report from "
                    "--trace / --metrics JSONL artifacts (any mix): "
                    "timeline strip, group compile/execute table, "
                    "stage breakdown, occupancy tiles, stall/recovery "
                    "log, ETA-vs-actual curve.")
    ap.add_argument("paths", nargs="*",
                    help="trace and/or metrics JSONL files")
    ap.add_argument("--fleet", default=None, metavar="DIR",
                    help="stitch a fleet/spool directory's per-process "
                         "JSONL into one merged per-job timeline page "
                         "keyed by correlation id (ignores positional "
                         "paths)")
    ap.add_argument("-o", "--out", default=None,
                    help="output HTML path "
                         "[<first input minus .jsonl>.report.html, or "
                         "<fleet dir>/fleet.report.html]")
    ap.add_argument("--title", default=None)
    a = ap.parse_args(argv)
    if not a.fleet and not a.paths:
        ap.error("need JSONL paths or --fleet DIR")
    if a.fleet:
        out = a.out or os.path.join(a.fleet, "fleet.report.html")
        try:
            page = render_fleet_html(a.fleet, title=a.title)
        except OSError as e:
            print(f"Error: report: {e}", file=sys.stderr)
            return 1
        try:
            with open(out, "w", encoding="utf-8") as f:
                f.write(page)
        except OSError as e:
            print(f"Error: report: cannot write {out!r}: {e}",
                  file=sys.stderr)
            return 1
        print(f"[ccsx-tpu] report: {out}", file=sys.stderr)
        return 0
    out = a.out or default_out_path(a.paths[0])
    try:
        page = render_html(a.paths, title=a.title)
    except OSError as e:
        print(f"Error: report: {e}", file=sys.stderr)
        return 1
    try:
        with open(out, "w", encoding="utf-8") as f:
            f.write(page)
    except OSError as e:
        print(f"Error: report: cannot write {out!r}: {e}",
              file=sys.stderr)
        return 1
    print(f"[ccsx-tpu] report: {out}", file=sys.stderr)
    return 0
