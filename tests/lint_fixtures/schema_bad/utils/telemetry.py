"""Schema-drift bad twin, consumer side: PROM_COUNTERS names a key
snapshot() never emits ('missing_key')."""

PROM_COUNTERS = ("holes_in", "missing_key")
PROM_GAUGES = ("elapsed_s",)
PROM_STRUCTURED = ("progress",)
