"""Serving-plane chaos soak: multi-tenant blast-radius + steady-state.

The resident server's claim (pipeline/serve.py) is two-sided:

* **Isolation** — a fault INSIDE one tenant's job (a wedged device
  dispatch, classified input corruption, a cancelled upload, ENOSPC on
  its output) stays inside that job's fault domain: the job degrades,
  retries, or fails by ITS budget/deadline, the concurrent sibling's
  bytes match the solo CLI run exactly, and /readyz keeps answering
  ready (the server never stops taking traffic because one tenant is
  having a bad day).
* **Steady state** — after the warm wave, a sustained stream of jobs
  books ZERO new XLA compiles in the server tracer's cumulative group
  table and holds a sustained zmws/s (the number bench.py's SERVE leg
  gates round-over-round with the 20% rule).

This soak drives both through one live ServeCore per process phase:

  warm wave        2 concurrent clean jobs -> byte-identical, records
                   the warm compile table
  cancel_mid       a stalled job is cancelled mid-flight (rc 75);
                   its sibling's bytes are untouched
  device_hang      a tenant wedges its dispatch under its OWN 1.5 s
                   dispatch deadline -> host-rung replay, byte-exact,
                   hang counters booked ONLY in that job
  corrupt_salvage  classified corruption under --salvage drops the
                   damaged hole in THAT job only (rc 0 degraded)
  disk_full_retry  injected ENOSPC fails the attempt rc 1; the serve
                   retry RESUMES from the job journal to the
                   byte-identical output (attempts == 2)
  steady wave      N clean jobs timed -> sustained zmws/s, ZERO new
                   compiles vs the warm table
  drain_restart    SIGTERM semantics: drain with an in-flight job
                   (rc 75, state "interrupted"), then a NEW core on
                   the same spool requeues it from state.json and its
                   journal resumes it byte-identically

Schedules are pure functions of ``--seed`` (replayable); the corpus
builder and reference runner are benchmarks/chaos.py's.  The fast
deterministic slice of this story is tier-1 (tests/test_serve.py);
this soak is the composition proof:

    python benchmarks/serve_chaos.py --seed 0 --holes 6 \
        --json benchmarks/serve_rNN.json        (`make serve-chaos`)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# unit-scale fault budgets: eager journal settles (the disk_full retry
# must resume, not recompute), short injected stalls, no first-of-shape
# deadline grace, bounded hang parks
os.environ["CCSX_JOURNAL_FSYNC_S"] = "0"
os.environ["CCSX_FAULT_STALL_S"] = "2"
os.environ["CCSX_DEADLINE_GRACE"] = "1"
os.environ["CCSX_FAULT_HANG_S"] = "60"

from ccsx_tpu import cli, exitcodes                          # noqa: E402
from ccsx_tpu.pipeline.serve import ServeCore                # noqa: E402
from benchmarks.chaos import make_corpus, run_reference      # noqa: E402


def _cfg():
    return cli.config_from_args(
        cli.build_parser().parse_args(["-A", "-m", "1000"]))


def _compiles(core) -> int:
    groups = core.metrics.snapshot().get("groups") or {}
    return sum(g["compiles"] for g in groups.values())


def _bytes(path: str) -> bytes:
    try:
        return open(path, "rb").read()
    except OSError:
        return b""


def _pair(core, in_fa: str, ref: bytes, overrides: dict, kind: str):
    """One faulted job + one clean sibling, concurrently.  The
    sibling's byte identity + clean counters IS the blast-radius
    oracle; readiness is sampled while both run."""
    bad = core.submit(input_path=in_fa, overrides=overrides)
    good = core.submit(input_path=in_fa)
    ready_during = core.readiness()[0]
    t = {"kind": kind, "bad": core.wait(bad.id, 300),
         "good": core.wait(good.id, 300)}
    snaps = core.job_snapshots()
    t["bad_job"], t["good_job"] = bad.id, good.id
    t["bad_metrics"] = {k: snaps.get(bad.id, {}).get(k) for k in
                        ("holes_out", "holes_corrupt", "device_hangs",
                         "host_fallbacks", "breaker_trips")}
    t["sibling_identical"] = _bytes(good.out_path) == ref
    t["sibling_clean"] = (snaps.get(good.id, {}).get("device_hangs")
                          == 0 and
                          snaps.get(good.id, {}).get("holes_corrupt")
                          == 0)
    t["ready_during"] = ready_during
    t["ready_after"] = core.readiness()[0]
    return bad, good, snaps, t


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--holes", type=int, default=6)
    ap.add_argument("--steady-jobs", type=int, default=6)
    ap.add_argument("--json", default=None,
                    help="write the artifact here "
                         "(benchmarks/serve_rNN.json)")
    a = ap.parse_args(argv)
    rng = np.random.default_rng(a.seed)
    t_start = time.time()
    trials = []

    with tempfile.TemporaryDirectory() as tmp:
        in_fa = make_corpus(tmp, rng, a.holes)
        # the solo-CLI reference MUST run before the core exists (the
        # server owns the installed tracer for its process lifetime)
        ref = run_reference(in_fa, tmp)
        spool = os.path.join(tmp, "spool")
        core = ServeCore(_cfg(), spool=spool, max_active=3,
                         retries=2, backoff_s=0.1)
        try:
            # ---- warm wave ----
            warm = [core.submit(input_path=in_fa) for _ in range(2)]
            states = [core.wait(j.id, 300) for j in warm]
            ident = [_bytes(j.out_path) == ref for j in warm]
            warm_compiles = _compiles(core)
            trials.append({"kind": "warm_wave", "states": states,
                           "identical": ident,
                           "compiles": warm_compiles,
                           "ok": states == ["done"] * 2 and all(ident)})

            # ---- cancel mid-flight ----
            bad = core.submit(input_path=in_fa,
                              overrides={"faults": "stall@1"})
            good = core.submit(input_path=in_fa)
            time.sleep(0.5)          # inside the stalled dispatch
            core.cancel(bad.id)
            t = {"kind": "cancel_mid",
                 "bad": core.wait(bad.id, 300),
                 "good": core.wait(good.id, 300),
                 "bad_rc": core.job(bad.id).rc,
                 "sibling_identical": _bytes(good.out_path) == ref,
                 "ready_after": core.readiness()[0]}
            t["ok"] = (t["bad"] == "cancelled"
                       and t["bad_rc"] == exitcodes.RC_INTERRUPTED
                       and t["good"] == "done"
                       and t["sibling_identical"] and t["ready_after"])
            trials.append(t)

            # ---- device hang, isolated by the tenant's own deadline --
            bad, good, snaps, t = _pair(
                core, in_fa, ref,
                {"faults": "device_hang@1",
                 "dispatch_deadline_s": 1.5}, "device_hang")
            t["bad_identical"] = _bytes(bad.out_path) == ref
            t["ok"] = (t["bad"] == "done" and t["good"] == "done"
                       and t["bad_identical"] and t["sibling_identical"]
                       and t["sibling_clean"]
                       and t["bad_metrics"]["device_hangs"] >= 1
                       and t["bad_metrics"]["host_fallbacks"] >= 1
                       and t["ready_after"])
            trials.append(t)

            # ---- classified corruption under salvage ----
            n = int(rng.integers(2, a.holes))
            bad, good, snaps, t = _pair(
                core, in_fa, ref,
                {"faults": f"input_corrupt@{n}", "salvage": True},
                "corrupt_salvage")
            t["spec"] = f"input_corrupt@{n}"
            corrupt = t["bad_metrics"]["holes_corrupt"] or 0
            t["ok"] = (t["bad"] == "done" and t["good"] == "done"
                       and corrupt >= 1
                       and t["bad_metrics"]["holes_out"]
                       == a.holes - corrupt
                       and t["sibling_identical"] and t["sibling_clean"]
                       and t["ready_after"])
            trials.append(t)

            # ---- ENOSPC -> rc 1 -> serve retry RESUMES the journal --
            # the fault index must sit past the resume's write count:
            # attempt 1 journals holes 1..n-1, the re-armed scope's
            # attempt 2 only writes holes n..H (H-n+1 < n calls)
            n = a.holes - 1
            bad, good, snaps, t = _pair(
                core, in_fa, ref, {"faults": f"disk_full@{n}"},
                "disk_full_retry")
            t["spec"] = f"disk_full@{n}"
            t["attempts"] = core.job(bad.id).attempts
            t["bad_identical"] = _bytes(bad.out_path) == ref
            t["ok"] = (t["bad"] == "done" and t["attempts"] == 2
                       and t["bad_identical"] and t["good"] == "done"
                       and t["sibling_identical"] and t["ready_after"])
            trials.append(t)

            # ---- steady wave: sustained rate, zero new compiles ----
            pre = _compiles(core)
            t0 = time.monotonic()
            jobs = [core.submit(input_path=in_fa)
                    for _ in range(a.steady_jobs)]
            states = [core.wait(j.id, 600) for j in jobs]
            wall = time.monotonic() - t0
            ident = [_bytes(j.out_path) == ref for j in jobs]
            recompiles = _compiles(core) - pre
            steady = {"kind": "steady_wave", "jobs": a.steady_jobs,
                      "wall_s": round(wall, 2),
                      "zmws_per_sec":
                      round(a.steady_jobs * a.holes / wall, 3),
                      "recompiles": recompiles,
                      "ok": (states == ["done"] * a.steady_jobs
                             and all(ident) and recompiles == 0)}
            trials.append(steady)

            # ---- SIGTERM drain with in-flight work ----
            j = core.submit(input_path=in_fa,
                            overrides={"faults": "stall@1",
                                       "inflight": 1})
            time.sleep(0.5)
            rc = core.drain(timeout=120)
            t = {"kind": "drain_restart", "drain_rc": rc,
                 "state_at_exit": core.job(j.id).state}
        finally:
            core.close()

        # ---- restart: state.json requeues, the journal resumes ----
        core2 = ServeCore(_cfg(), spool=spool, max_active=1)
        try:
            t["resume_state"] = core2.wait(j.id, 300)
            t["identical"] = _bytes(core2.job(j.id).out_path) == ref
        finally:
            core2.close()
        t["ok"] = (t["drain_rc"] == exitcodes.RC_INTERRUPTED
                   and t["state_at_exit"] == "interrupted"
                   and t["resume_state"] == "done" and t["identical"])
        trials.append(t)

    n_failed = sum(1 for t in trials if not t.get("ok"))
    out = {"seed": a.seed, "holes": a.holes,
           "steady": next(t for t in trials
                          if t["kind"] == "steady_wave"),
           "trials": trials, "n_trials": len(trials),
           "n_failed": n_failed, "ok": n_failed == 0,
           "elapsed_s": round(time.time() - t_start, 1)}
    blob = json.dumps(out, indent=1)
    print(blob)
    if a.json:
        with open(a.json, "w") as f:
            f.write(blob)
    return 0 if n_failed == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
