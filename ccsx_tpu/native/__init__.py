"""Native (C++) IO layer loader.

Builds ``libccsx_io.so`` from io_native.cpp on first use if a compiler is
present, loads it via ctypes, and exposes ``lib()``.  Import never fails:
callers check ``available()`` and fall back to the pure-Python parsers
(ccsx_tpu.io.fastx / ccsx_tpu.io.bam) when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libccsx_io.so")
_LOG = os.path.join(_DIR, "build.log")
_lock = threading.Lock()
_lib = None
_tried = False
_build_error: "str | None" = None


def _note_failure(summary: str, output: str) -> None:
    """A failed/stale auto-rebuild used to be SILENT (the native path
    just disappeared and ingest got mysteriously slow): persist the
    compiler output, print one loud line with the path, and remember
    the summary for Metrics (booked as native_build_error in every
    metrics event)."""
    global _build_error
    log_hint = ""
    if output:
        try:
            with open(_LOG, "w", encoding="utf-8") as f:
                f.write(output)
            log_hint = f"; compiler log: {_LOG}"
        except OSError:
            pass
    _build_error = summary
    print(f"[ccsx-tpu] WARNING: native IO rebuild FAILED — falling back "
          f"to the pure-Python parsers (same bytes, slower ingest): "
          f"{summary}{log_hint}", file=sys.stderr)


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-s", "-C", _DIR],
            check=False, capture_output=True, timeout=120, text=True,
        )
    except (OSError, subprocess.SubprocessError) as e:
        _note_failure(f"{type(e).__name__}: {e}", "")
        return False
    if r.returncode != 0:
        err = (r.stderr or r.stdout or "").strip()
        first = next((ln for ln in err.splitlines() if ln.strip()),
                     f"make rc {r.returncode}")
        _note_failure(first[:200], (r.stdout or "") + (r.stderr or ""))
        return False
    if not os.path.exists(_SO):
        _note_failure("make succeeded but libccsx_io.so is missing", "")
        return False
    return True


def build_error() -> "str | None":
    """One-line summary of a failed native auto-rebuild this process
    observed (None when the native path loaded or was never needed).
    Read by Metrics.snapshot() so every metrics event carries the
    degradation."""
    return _build_error


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.ccsx_open.restype = c.c_void_p
    lib.ccsx_open.argtypes = [c.c_char_p, c.c_int]
    lib.ccsx_set_filter.restype = None
    lib.ccsx_set_filter.argtypes = [c.c_void_p, c.c_int32, c.c_int64,
                                    c.c_int64]
    lib.ccsx_next_zmw.restype = c.c_int
    lib.ccsx_next_zmw.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
        c.POINTER(c.POINTER(c.c_int32)), c.POINTER(c.c_int32),
    ]
    lib.ccsx_next_record.restype = c.c_int
    lib.ccsx_next_record.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
    ]
    lib.ccsx_error.restype = c.c_char_p
    lib.ccsx_error.argtypes = [c.c_void_p]
    # filter accounting (guarded: a stale prebuilt .so without the
    # symbols must degrade to "counts unavailable", not fail to load)
    for name in ("ccsx_filter_counts", "ccsx_prefetch_filter_counts"):
        try:
            fn = getattr(lib, name)
        except AttributeError:
            continue
        fn.restype = None
        fn.argtypes = [c.c_void_p] + [c.POINTER(c.c_int64)] * 3
    # salvage-mode ingest (same stale-.so guard: native/io.py falls
    # back to the pure-Python salvage readers when these are absent)
    try:
        lib.ccsx_set_salvage.restype = None
        lib.ccsx_set_salvage.argtypes = [c.c_void_p, c.c_int, c.c_int64]
        lib.ccsx_prefetch_open_s.restype = c.c_void_p
        lib.ccsx_prefetch_open_s.argtypes = [
            c.c_char_p, c.c_int, c.c_int32, c.c_int64, c.c_int64,
            c.c_int32, c.c_int, c.c_int64]
        for name in ("ccsx_error_reason", "ccsx_prefetch_error_reason",
                     "ccsx_corrupt_summary",
                     "ccsx_prefetch_corrupt_summary"):
            fn = getattr(lib, name)
            fn.restype = c.c_char_p
            fn.argtypes = [c.c_void_p]
        for name in ("ccsx_corrupt_events",
                     "ccsx_prefetch_corrupt_events",
                     "ccsx_corrupt_exempt",
                     "ccsx_prefetch_corrupt_exempt"):
            fn = getattr(lib, name)
            fn.restype = c.c_int64
            fn.argtypes = [c.c_void_p]
    except AttributeError:
        pass
    lib.ccsx_close.restype = None
    lib.ccsx_close.argtypes = [c.c_void_p]
    for name in ("ccsx_encode", "ccsx_revcomp_ascii", "ccsx_revcomp_codes"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_uint8)]
    lib.ccsx_prefetch_open.restype = c.c_void_p
    lib.ccsx_prefetch_open.argtypes = [c.c_char_p, c.c_int, c.c_int32,
                                       c.c_int64, c.c_int64, c.c_int32]
    lib.ccsx_prefetch_next.restype = c.c_int
    lib.ccsx_prefetch_next.argtypes = lib.ccsx_next_zmw.argtypes
    lib.ccsx_prefetch_error.restype = c.c_char_p
    lib.ccsx_prefetch_error.argtypes = [c.c_void_p]
    lib.ccsx_prefetch_close.restype = None
    lib.ccsx_prefetch_close.argtypes = [c.c_void_p]
    lib.ccsx_writer_open.restype = c.c_void_p
    lib.ccsx_writer_open.argtypes = [c.c_char_p, c.c_int]
    lib.ccsx_writer_put_fasta.restype = c.c_int
    lib.ccsx_writer_put_fasta.argtypes = [c.c_void_p, c.c_char_p,
                                          c.POINTER(c.c_uint8), c.c_int64]
    lib.ccsx_writer_put_fastq.restype = c.c_int
    lib.ccsx_writer_put_fastq.argtypes = [c.c_void_p, c.c_char_p,
                                          c.POINTER(c.c_uint8),
                                          c.POINTER(c.c_uint8), c.c_int64]
    lib.ccsx_writer_close.restype = c.c_int
    lib.ccsx_writer_close.argtypes = [c.c_void_p]
    lib.ccsx_bgzf_pool_bench.restype = c.c_double
    lib.ccsx_bgzf_pool_bench.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.ccsx_align_scalar.restype = c.c_int
    lib.ccsx_align_scalar.argtypes = [
        c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_uint8), c.c_int64,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_int64), c.POINTER(c.c_uint8), c.c_int64,
        c.POINTER(c.c_int64),
    ]
    return lib


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        import glob

        srcs = glob.glob(os.path.join(_DIR, "*.cpp"))
        if not os.path.exists(_SO) or any(
            os.path.getmtime(_SO) < os.path.getmtime(s) for s in srcs
        ):
            if not _build():
                return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:
            # a built .so that will not load (e.g. a leftover TSAN/ASAN
            # instrumented build, static-TLS failures) is the same
            # silent degradation as a failed compile — say so
            _note_failure(f"libccsx_io.so failed to load: {e}", "")
            _lib = None
    return _lib


def available() -> bool:
    return lib() is not None
