"""Benchmark: batched star-MSA consensus round throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured unit is ZMW-windows consensed per second by the batched device
round (banded DP fill + traceback projection + column vote over a
(Z, P, W) batch) — the hot compute of the pipeline (reference: the bsalign
POA inside ccs_for2's window loop, main.c:552-572, where ~all CPU time
goes; SURVEY.md §3.3).

vs_baseline compares against bench_baseline.json: the native C++ banded
SIMD fill (native/baseline_simd.cpp — the bsalign-fill workload, band=128,
vectorized build MEASURED, SIMD factor MEASURED vec/scalar on identical
source) per-core, projected x64 linearly to the BASELINE.md target
machine.  The reference binary itself is not buildable here (its bsalign
dependency is cloned at build time, README.md:11 — no network), so the
one remaining projection — linear core scaling — is explicit; the old
guessed 8x SIMD credit is gone (VERDICT r4 item 4).
Recalibrate with:  python bench.py --calibrate
"""

import json
import os
import shutil
import sys
import time

# benchmark shapes (kept canonical so compiles cache): Z zmws x P passes x W window
Z, P, W, TLEN = 16, 8, 1024, 1000
ITERS, WINDOWS = 25, 8
_HERE = os.path.dirname(os.path.abspath(__file__))
BASELINE_PATH = os.path.join(_HERE, "bench_baseline.json")

# >20% drop vs the previous bench artifact prints the loud warning and
# sets the top-level "regressed" field
REGRESSION_DROP = 0.8


def _load_bench_line(path):
    """Extract the bench JSON line from an artifact: the driver's
    BENCH_r*.json wraps it under "parsed"; a raw `python bench.py`
    capture IS the line.  None when unusable."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    line = d.get("parsed") if isinstance(d.get("parsed"), dict) else d
    if not isinstance(line, dict) or "dp_cells_per_sec" not in line:
        return None
    return line


def find_prev_bench(root=_HERE):
    """The most recent prior bench artifact to gate against: the
    highest-numbered usable BENCH_r*.json.  (bench_baseline.json is the
    NATIVE-fill yardstick and already reported as vs_baseline — it is
    not a prior bench line, so it never backs vs_prev.)  Returns
    (artifact_name, line) or (None, None)."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    for _, p in sorted(cands, reverse=True):
        line = _load_bench_line(p)
        if line is not None:
            return os.path.basename(p), line
    return None, None


def latest_quality_artifacts(root=_HERE, n=2):
    """The ``n`` highest-numbered usable benchmarks/quality_r*.json
    artifacts, newest first, as (name, summary) pairs.  A usable one
    carries a gate_biased Q20 yield (the realistic-error regime,
    ROADMAP item 5 — the product-defining number the bench trajectory
    must gate alongside the perf ones)."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "benchmarks",
                                    "quality_r*.json")):
        m = re.search(r"quality_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    out = []
    for _, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        gb = d.get("gate_biased")
        g1 = d.get("gate_1")
        gb_y = gb.get("q20_yield") if isinstance(gb, dict) else None
        iid_y = g1.get("q20_yield") if isinstance(g1, dict) else None
        if gb_y is None:
            continue
        out.append((os.path.basename(p),
                    {"gate_biased_q20_yield": gb_y,
                     "iid_q20_yield": iid_y}))
        if len(out) >= n:
            break
    return out


def compare_quality(line, prev, vp, regressed):
    """The quality leg of the vs_prev gate: gate_biased Q20 yield from
    the newest quality artifact vs the prior bench line's (or, before
    bench lines carried one, the second-newest quality artifact).  A
    >20% relative drop flags ``regressed`` exactly like a perf drop —
    quality backsliding must trip the same wire (ROADMAP item 5 tail).
    Yield is a bytes-level property, so no backend gating applies."""
    quals = latest_quality_artifacts()
    if quals:
        name, summary = quals[0]
        line["quality"] = {"artifact": name, **summary}
    cur = (line.get("quality") or {}).get("gate_biased_q20_yield")
    prev_q = ((prev or {}).get("quality")
              or {}).get("gate_biased_q20_yield")
    prev_src = "prev bench line"
    if prev_q is None and len(quals) > 1:
        prev_src, prev_q = quals[1][0], \
            quals[1][1]["gate_biased_q20_yield"]
    if cur is None or prev_q is None:
        return
    vp["gate_biased_q20_yield"] = {"prev": prev_q, "cur": cur,
                                   "prev_source": prev_src}
    if prev_q > 0 and cur < prev_q * REGRESSION_DROP:
        regressed.append(
            f"gate_biased q20_yield {prev_q}->{cur} (quality "
            "regression, realistic-error regime)")


def latest_fleet_artifacts(root=_HERE, n=2):
    """The ``n`` highest-numbered usable benchmarks/fleet_r*.json
    artifacts (the elastic-fleet churn soak, benchmarks/fleet.py),
    newest first, as (name, summary) pairs.  Usable = carries the
    derived scale-out ratio; the summary also keeps the one-bit
    byte-identity verdict and the killed-at-halfway overhead."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "benchmarks",
                                    "fleet_r*.json")):
        m = re.search(r"fleet_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    out = []
    for _, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        derived = d.get("derived") or {}
        if derived.get("scaleout_k4") is None:
            continue
        out.append((os.path.basename(p),
                    {"scaleout_k4": derived["scaleout_k4"],
                     "kill_overhead_x": derived.get("kill_overhead_x"),
                     "ok": d.get("ok")}))
        if len(out) >= n:
            break
    return out


def compare_fleet(line, prev, vp, regressed):
    """The fleet leg of the vs_prev gate: scale-out efficiency (K=1
    wall / K=4 wall) from the newest fleet_r*.json artifact vs the
    prior bench line's (or the second-newest artifact).  A >20%
    relative drop in scale-out — or ANY non-byte-identical trial in
    the newest soak — trips ``regressed`` exactly like a perf drop:
    elastic scheduling that stops scaling (or stops being exact) is a
    regression of the whole plane.  Wall ratios of a CPU-hosted soak
    compare fine across rounds (same harness, same corpus), so no
    backend gating applies."""
    arts = latest_fleet_artifacts()
    if arts:
        name, summary = arts[0]
        line["fleet"] = {"artifact": name, **summary}
        if summary.get("ok") is False:
            regressed.append(
                f"fleet soak {name} has non-byte-identical trials "
                "(fleet churn changed the output bytes)")
    cur = (line.get("fleet") or {}).get("scaleout_k4")
    prev_s = ((prev or {}).get("fleet") or {}).get("scaleout_k4")
    prev_src = "prev bench line"
    if prev_s is None and len(arts) > 1:
        prev_src, prev_s = arts[1][0], arts[1][1]["scaleout_k4"]
    if cur is None or prev_s is None:
        return
    vp["fleet_scaleout_k4"] = {"prev": prev_s, "cur": cur,
                               "prev_source": prev_src}
    if prev_s > 0 and cur < prev_s * REGRESSION_DROP:
        regressed.append(
            f"fleet scaleout_k4 {prev_s}->{cur} (elastic scheduling "
            "regression)")


def latest_serve_artifacts(root=_HERE, n=2):
    """The ``n`` highest-numbered usable benchmarks/serve_r*.json
    artifacts (the serving-plane chaos soak, benchmarks/serve_chaos.py),
    newest first, as (name, summary) pairs.  Usable = carries the
    steady-wave record (sustained zmws/s through the resident server
    plus its steady-state recompile count); the summary also keeps the
    one-bit all-trials verdict."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "benchmarks",
                                    "serve_r*.json")):
        m = re.search(r"serve_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    out = []
    for _, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        steady = d.get("steady") or {}
        if steady.get("zmws_per_sec") is None:
            continue
        out.append((os.path.basename(p),
                    {"zmws_per_sec": steady["zmws_per_sec"],
                     "recompiles": steady.get("recompiles"),
                     "ok": d.get("ok")}))
        if len(out) >= n:
            break
    return out


def compare_serve(line, prev, vp, regressed):
    """The serving leg of the vs_prev gate: sustained steady-wave
    zmws/s through the resident server from the newest serve_r*.json
    artifact vs the prior bench line's (or the second-newest artifact).
    A >20% relative drop — or ANY failed trial in the newest soak, or
    a NONZERO steady-state recompile count — trips ``regressed``: a
    server that stops isolating tenants, stops being byte-exact, or
    starts recompiling in steady state has lost the whole point of
    residency.  CPU-hosted soak rates compare fine across rounds (same
    harness, same corpus), so no backend gating applies."""
    arts = latest_serve_artifacts()
    if arts:
        name, summary = arts[0]
        line["serve"] = {"artifact": name, **summary}
        if summary.get("ok") is False:
            regressed.append(
                f"serve soak {name} has failed trials (tenant "
                "isolation / byte identity broke)")
        if summary.get("recompiles"):
            regressed.append(
                f"serve soak {name} booked {summary['recompiles']} "
                "steady-state recompiles (warm residency broke)")
    cur = (line.get("serve") or {}).get("zmws_per_sec")
    prev_s = ((prev or {}).get("serve") or {}).get("zmws_per_sec")
    prev_src = "prev bench line"
    if prev_s is None and len(arts) > 1:
        prev_src, prev_s = arts[1][0], arts[1][1]["zmws_per_sec"]
    if cur is None or prev_s is None:
        return
    vp["serve_zmws_per_sec"] = {"prev": prev_s, "cur": cur,
                                "prev_source": prev_src}
    if prev_s > 0 and cur < prev_s * REGRESSION_DROP:
        regressed.append(
            f"serve steady zmws_per_sec {prev_s}->{cur} (resident-"
            "server throughput regression)")


def latest_serve_fleet_artifacts(root=_HERE, n=2):
    """The ``n`` highest-numbered usable benchmarks/serve_fleet_r*.json
    artifacts (the replica-fleet churn soak,
    benchmarks/serve_fleet_chaos.py), newest first, as (name, summary)
    pairs.  Usable = carries the steady fleet record (sustained zmws/s
    across the replica fleet plus the per-replica steady-state
    recompile total); the summary also keeps the job-accounting
    verdicts (lost / duplicated / byte identity)."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "benchmarks",
                                    "serve_fleet_r*.json")):
        m = re.search(r"serve_fleet_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    out = []
    for _, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        steady = d.get("steady") or {}
        if steady.get("zmws_per_sec") is None:
            continue
        out.append((os.path.basename(p),
                    {"zmws_per_sec": steady["zmws_per_sec"],
                     "recompiles": steady.get("recompiles"),
                     "lost_jobs": d.get("lost_jobs"),
                     "duplicated_jobs": d.get("duplicated_jobs"),
                     "byte_identical": d.get("byte_identical"),
                     "ok": d.get("ok")}))
        if len(out) >= n:
            break
    return out


def compare_serve_fleet(line, prev, vp, regressed):
    """The replica-fleet leg of the vs_prev gate: sustained fleet-wide
    zmws/s under replica churn (SIGKILL mid-wave + mid-run join) from
    the newest serve_fleet_r*.json artifact vs the prior bench line's
    (or the second-newest artifact).  A >20% relative drop trips
    ``regressed`` — and so, OUTRIGHT, does any lost or duplicated job,
    any non-byte-identical output, any failed trial, or a nonzero
    per-replica steady-state recompile count: a fleet that loses jobs
    under churn (or double-emits them past the exclusive retirement
    fence) has lost the whole point of the lease domain."""
    arts = latest_serve_fleet_artifacts()
    if arts:
        name, summary = arts[0]
        line["serve_fleet"] = {"artifact": name, **summary}
        if summary.get("ok") is False:
            regressed.append(
                f"serve-fleet soak {name} has failed trials")
        if summary.get("lost_jobs") or summary.get("duplicated_jobs"):
            regressed.append(
                f"serve-fleet soak {name} lost "
                f"{summary.get('lost_jobs')} / duplicated "
                f"{summary.get('duplicated_jobs')} job(s) under churn "
                "(the zero-lost-jobs invariant broke)")
        if summary.get("byte_identical") is False:
            regressed.append(
                f"serve-fleet soak {name} produced non-byte-identical "
                "job outputs")
        if summary.get("recompiles"):
            regressed.append(
                f"serve-fleet soak {name} booked "
                f"{summary['recompiles']} steady-state recompiles "
                "across its replicas (warm residency broke)")
    cur = (line.get("serve_fleet") or {}).get("zmws_per_sec")
    prev_s = ((prev or {}).get("serve_fleet") or {}).get("zmws_per_sec")
    prev_src = "prev bench line"
    if prev_s is None and len(arts) > 1:
        prev_src, prev_s = arts[1][0], arts[1][1]["zmws_per_sec"]
    if cur is None or prev_s is None:
        return
    vp["serve_fleet_zmws_per_sec"] = {"prev": prev_s, "cur": cur,
                                      "prev_source": prev_src}
    if prev_s > 0 and cur < prev_s * REGRESSION_DROP:
        regressed.append(
            f"serve-fleet steady zmws_per_sec {prev_s}->{cur} "
            "(fleet throughput regression under churn)")


def latest_pallas_ab_artifacts(root=_HERE, n=2):
    """The ``n`` highest-numbered usable benchmarks/pallas_ab*_r*.json
    artifacts (the scan / Pallas v1 / rotband v2 promotion harness,
    benchmarks/pallas_ab.py), newest first, as (name, summary) pairs.
    Usable = carries a "decision" record (winner, margin, per-arm
    rates), i.e. a --mode time run that produced a verdict; pure
    --mode check artifacts are skipped."""
    import glob
    import re

    cands = []
    for p in glob.glob(os.path.join(root, "benchmarks",
                                    "pallas_ab*_r*.json")):
        m = re.search(r"pallas_ab.*_r(\d+)\.json$", p)
        if m:
            cands.append((int(m.group(1)), p))
    out = []
    for _, p in sorted(cands, reverse=True):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        dec = d.get("decision")
        if not isinstance(dec, dict) or not dec.get("winner"):
            continue
        out.append((os.path.basename(p),
                    {"winner": dec.get("winner"),
                     "margin": dec.get("margin"),
                     "metric": dec.get("metric"),
                     "round_rates": dec.get("round_rates"),
                     "backend": dec.get("backend"),
                     "interpret": dec.get("interpret")}))
        if len(out) >= n:
            break
    return out


def compare_dp_kernel(line, prev, vp, regressed):
    """The DP-kernel leg of the vs_prev gate: the three-arm promotion
    record (scan vs Pallas v1 vs rotband v2, marginal-fetch timed)
    from the newest pallas_ab artifact vs the prior bench line's (or
    the second-newest artifact).  Absolute rates only compare within
    the same backend — an interpret-mode CPU record never gates a TPU
    one.  A winner FLIP is informational (logged into vs_prev, the
    promotion protocol decides what to do with it); what trips
    ``regressed`` is the winning arm's throughput dropping >20% on
    the same backend — the promoted kernel itself got slower."""
    arts = latest_pallas_ab_artifacts()
    if arts:
        name, summary = arts[0]
        line["dp_kernel"] = {"artifact": name, **summary}
    cur = line.get("dp_kernel")
    prev_d = (prev or {}).get("dp_kernel")
    prev_src = "prev bench line"
    if prev_d is None and len(arts) > 1:
        prev_src, prev_d = arts[1]
    if not cur or not prev_d:
        return
    ent = {"prev_winner": prev_d.get("winner"),
           "cur_winner": cur.get("winner"),
           "prev_source": prev_src}
    if cur.get("winner") != prev_d.get("winner"):
        ent["winner_flipped"] = True
        print(f"[bench] dp-kernel winner flipped "
              f"{prev_d.get('winner')} -> {cur.get('winner')} "
              "(informational; see the promotion protocol in "
              "ccsx_tpu/consensus/star.py)", file=sys.stderr)
    if cur.get("backend") == prev_d.get("backend"):
        w = cur.get("winner")
        cur_r = (cur.get("round_rates") or {}).get(w)
        prev_r = (prev_d.get("round_rates") or {}).get(w)
        if cur_r and prev_r:
            ent["winner_rate"] = {"prev": prev_r, "cur": cur_r}
            if cur_r < prev_r * REGRESSION_DROP:
                regressed.append(
                    f"dp-kernel winning arm '{w}' "
                    f"{prev_r:.0f}->{cur_r:.0f} zmw_windows/s "
                    f"({cur.get('backend')} backend)")
    vp["dp_kernel"] = ent


def compare_with_prev(line, prev, artifact):
    """Mutates ``line``: adds "vs_prev" (ratios vs the prior artifact
    for dp_cells_per_sec and per-config e2e zmws_per_sec) and, on a
    >20% drop in either, the top-level "regressed" field + a loud
    stderr warning — the self-comparing trajectory VERDICT asked for.
    Only same-backend artifacts are compared (an XLA:CPU run against a
    TPU number is not a regression signal), and only e2e configs run
    at the same hole count (zmws_per_sec is hole-count sensitive)."""
    vp = {"artifact": artifact, "prev_backend": prev.get("backend")}
    if prev.get("degraded"):
        vp["prev_degraded"] = prev["degraded"]
    regressed = []
    if prev.get("backend") != line.get("backend"):
        vp["skipped"] = (f"prev backend {prev.get('backend')!r} != "
                         f"{line.get('backend')!r}; not comparable")
    else:
        if prev.get("dp_cells_per_sec") and line.get("dp_cells_per_sec"):
            r = line["dp_cells_per_sec"] / prev["dp_cells_per_sec"]
            vp["dp_cells_per_sec"] = round(r, 3)
            if r < REGRESSION_DROP:
                regressed.append(f"dp_cells_per_sec x{r:.2f}")
        prev_e2e = {e.get("config"): e for e in prev.get("e2e", [])
                    if isinstance(e, dict)}
        ratios = {}
        # per-group compile counts (the r7 storm gate): compiles are
        # exact counts even untraced, so every same-config pair
        # compares.  Flag a regression when any config's worst packed
        # group now compiles more than the prior artifact's worst AND
        # is past the canonical-ladder budget of 2 — growth within the
        # ladder is legitimate tail variation, a return to 4-5 is the
        # storm.
        def _max_compiles(entry):
            groups = entry.get("groups") or {}
            packed = [st.get("compiles", 0) for k, st in groups.items()
                      if str(k).startswith("packed:")]
            return max(packed) if packed else None

        compiles_cmp = {}
        for e in line.get("e2e", []):
            pe = prev_e2e.get(e.get("config"))
            if not pe:
                continue
            cur_c, prev_c = _max_compiles(e), _max_compiles(pe)
            if cur_c is not None and prev_c is not None:
                compiles_cmp[str(e["config"])] = {"prev": prev_c,
                                                  "cur": cur_c}
                if cur_c > max(prev_c, 2):
                    regressed.append(
                        f"e2e c{e['config']} packed group compiles "
                        f"{prev_c}->{cur_c} (compile storm)")
        if compiles_cmp:
            vp["group_compiles_max"] = compiles_cmp
        # prep-share gate (ISSUE 8): the prep plane keeps host prep off
        # the critical path, so a config whose blocked-prep share climbs
        # back above the acceptance ceiling AND clearly above the prior
        # artifact's is a regression of the overlap itself.  The 0.10
        # floor keeps small-number noise (tiny e2e configs, ~seconds of
        # wall) from tripping it; prior artifacts without the counter
        # simply don't compare.
        prep_cmp = {}
        for e in line.get("e2e", []):
            pe = prev_e2e.get(e.get("config"))
            cur_p = (e or {}).get("prep_share")
            prev_p = (pe or {}).get("prep_share") if pe else None
            if cur_p is None or prev_p is None:
                continue
            prep_cmp[str(e["config"])] = {"prev": prev_p, "cur": cur_p}
            if cur_p > 0.10 and cur_p > prev_p * 1.5:
                regressed.append(
                    f"e2e c{e['config']} prep_share "
                    f"{prev_p}->{cur_p} (prep back on the critical "
                    "path)")
        if prep_cmp:
            vp["prep_share"] = prep_cmp
        # breaker/hang-rescued runs are not perf numbers: a config that
        # completed via an open circuit breaker (or abandoned, host-
        # replayed dispatches) measured the HOST path's wall, not the
        # device's — flag it and keep it out of the ratio geomean
        rescued = []
        for e in line.get("e2e", []):
            pe = prev_e2e.get(e.get("config"))
            cur_rescued = bool(e.get("breaker_trips")
                               or e.get("device_hangs"))
            prev_rescued = bool(pe and (pe.get("breaker_trips")
                                        or pe.get("device_hangs")))
            if cur_rescued:
                rescued.append(str(e.get("config")))
            if (not pe or not pe.get("zmws_per_sec")
                    or not e.get("zmws_per_sec")
                    or pe.get("holes_in") != e.get("holes_in")
                    # traced runs force per-dispatch execution; their
                    # wall numbers are a different discipline than the
                    # untraced async overlap — never cross-compare
                    or bool(pe.get("traced")) != bool(e.get("traced"))
                    or cur_rescued or prev_rescued):
                continue
            ratios[str(e["config"])] = round(
                e["zmws_per_sec"] / pe["zmws_per_sec"], 3)
        if rescued:
            vp["breaker_rescued_configs"] = rescued
            print("[bench] WARNING: e2e config(s) "
                  + ",".join(rescued) + " completed only via the "
                  "resilience layer (open breaker / abandoned "
                  "dispatches); their wall times measure the host "
                  "path and are excluded from vs_prev",
                  file=sys.stderr)
        if ratios:
            import math

            g = math.exp(sum(math.log(r) for r in ratios.values())
                         / len(ratios))
            vp["zmws_per_sec"] = round(g, 3)
            vp["zmws_per_sec_configs"] = ratios
            if g < REGRESSION_DROP:
                regressed.append(f"e2e zmws_per_sec x{g:.2f}")
    # the quality, fleet, serve, and dp-kernel legs ride every
    # comparison (all gate off committed artifacts; the dp-kernel leg
    # does its own backend gating internally)
    compare_quality(line, prev, vp, regressed)
    compare_fleet(line, prev, vp, regressed)
    compare_serve(line, prev, vp, regressed)
    compare_serve_fleet(line, prev, vp, regressed)
    compare_dp_kernel(line, prev, vp, regressed)
    line["vs_prev"] = vp
    if regressed:
        line["regressed"] = regressed
        print("[bench] " + "!" * 20 + " REGRESSION vs " + str(artifact)
              + ": " + "; ".join(regressed) + " (>20% drop) "
              + "!" * 20, file=sys.stderr)
    return vp


def device_attempt_report(err: str, report_path=None):
    """BENCH_r05 was a CPU fallback stamped 'tpu attempt hung' with NO
    diagnostics — the whole artifact trail went dark.  Persist the
    failed device attempt's stderr (which, since r7, carries the stall
    watchdog's thread stacks + in-flight shape group) next to the
    artifacts and embed the pointer + the last in-flight group in the
    JSON line, so a degraded run is diagnosable from the artifact
    alone."""
    import re

    report = {"stall_report": None, "last_inflight_group": None,
              "stall_dumps": 0}
    if not err:
        return report
    groups = re.findall(
        r"STALL WATCHDOG: device dispatch '[^']*' group='([^']*)'", err)
    report["stall_dumps"] = len(groups)
    if groups:
        report["last_inflight_group"] = groups[-1]
    path = report_path or os.path.join(_HERE, "benchmarks",
                                       "bench_stall_report.txt")
    try:
        with open(path, "w", encoding="utf-8") as f:
            f.write(err[-200000:])
        report["stall_report"] = os.path.relpath(path, _HERE)
    except OSError as e:
        report["stall_report_error"] = str(e)
    return report


def measure():
    import jax
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import star
    from ccsx_tpu.ops import msa, traceback
    import __graft_entry__ as ge

    params = AlignParams()
    projector = traceback.make_projector(W, 4)
    voter = msa.make_voter(4)
    # the production aligner dispatch: the vmapped lax.scan fill by
    # default on every backend (it beat the Pallas kernel 183k vs 142k
    # zmw-windows/s on v5e, 2026-07-29 — see consensus/star.use_pallas);
    # CCSX_BANDED_IMPL=pallas selects the kernel for A/B runs
    aligner = star._aligner(params)

    def round_core(qs, qlens, ts, tlens, row_mask):
        Zb, Pb, qmax = qs.shape
        ts_b = jax.numpy.broadcast_to(ts[:, None, :], (Zb, Pb, ts.shape[-1]))
        tl_b = jax.numpy.broadcast_to(tlens[:, None], (Zb, Pb))
        _, moves, offs = aligner(
            qs.reshape(Zb * Pb, qmax), qlens.reshape(Zb * Pb),
            ts_b.reshape(Zb * Pb, -1), tl_b.reshape(Zb * Pb))
        moves = moves.reshape(Zb, Pb, qmax, -1)
        offs = offs.reshape(Zb, Pb, qmax)
        proj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                        in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, _lead = proj(moves, offs, qs, qlens, tlens)
        cons, ins_base, ins_votes, ncov, match, nwin = jax.vmap(voter)(
            aligned, ins_cnt, ins_b, row_mask)
        return cons, ncov

    # Forced-execution marginal timing — the ONE method all benches
    # share (full rationale in benchmarks/marginal_time.py: the lazy
    # axon runtime neither waits in block_until_ready nor executes
    # unfetched dispatches, so r2-r4's blocking loops measured the
    # ~0.7-1 ms RPC ping and dispatch-queue timing measures
    # bookkeeping).  The trade: no cross-round overlap is counted — a
    # round is itself a (Z*P)-problem batch, so the chip is already
    # saturated within one round.
    # APPEND, never insert(0): the benchmarks dir holds generically
    # named modules (e2e, quality, ...) that would otherwise shadow
    # same-named imports resolved later in this process
    sys.path.append(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from marginal_time import marginal_time

    args = [jax.device_put(a) for a in
            ge._example_batch(Z=Z, P=P, W=W, tlen=TLEN)]
    # on an accelerator a round is sub-ms: raise the loop count so the
    # marginal (iters-1) x round signal clears the +-ms jitter of the
    # two checksum fetches (CPU rounds are ~0.5 s; ITERS=25 is plenty).
    # CCSX_BENCH_ITERS/WINDOWS exist for the watchdog's budgeted CPU
    # retry, which must fit a full measure in half the watchdog
    iters = ITERS if jax.default_backend() == "cpu" else 200

    def env_int(name, default, lo):
        try:
            return max(int(os.environ.get(name, "") or default), lo)
        except ValueError:
            return default

    iters = env_int("CCSX_BENCH_ITERS", iters, 2)
    windows = env_int("CCSX_BENCH_WINDOWS", WINDOWS, 1)
    runs = marginal_time(round_core, *args, iters=iters,
                         repeats=windows, settle=0.2)
    return Z / min(runs)  # best window, ZMW-windows per second


def main():
    """Watchdog wrapper: the tunnelled dev chip can hang mid-run even
    after a healthy startup probe (observed 2026-07-30: ~2h outage where
    enumeration worked but every dispatch hung).  The measurement runs
    in a subprocess with a deadline; on timeout/failure it is retried
    once on CPU (reduced e2e), and the last resort is an honest error
    line — the driver must always receive its ONE JSON line."""
    if ("--calibrate" in sys.argv
            or os.environ.get("CCSX_BENCH_INNER") == "1"
            or os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"):
        # XLA:CPU cannot hang like the tunnel; run unwrapped
        return _inner_main()
    import subprocess

    budget = float(os.environ.get("CCSX_BENCH_WATCHDOG", "720"))
    here = os.path.abspath(__file__)

    def attempt(extra_env, timeout):
        env = dict(os.environ, CCSX_BENCH_INNER="1", **extra_env)
        try:
            r = subprocess.run([sys.executable, here], env=env,
                               timeout=timeout, capture_output=True,
                               text=True)
            err = r.stderr or ""
        except subprocess.TimeoutExpired as e:
            # stderr captured so far still holds any stall-watchdog
            # dump the hung attempt produced — that is the diagnosis
            err = e.stderr or ""
            if isinstance(err, bytes):
                err = err.decode("utf-8", "replace")
            print("[bench] attempt timed out; backend hung mid-run",
                  file=sys.stderr)
            return None, err
        sys.stderr.write(err[-2000:])
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                return line, err
        return None, err

    # arm the flight recorder for the device attempt: a hung-then-
    # killed attempt leaves DIR/blackbox.<pid>.bin (mmap ring, survives
    # SIGKILL) naming the in-flight dispatch — the artifact the hang
    # branch below links next to device_attempt_report.  Per-run
    # subdirectory, cleared first: the base dir persists across runs,
    # and a stale ring from an earlier bench (or a concurrent one)
    # must not be linked as THIS attempt's forensics
    bb_base = os.environ.get("CCSX_BLACKBOX") or os.path.join(
        _HERE, "benchmarks", "bench_blackbox")
    bb_dir = os.path.join(bb_base, f"run.{os.getpid()}")
    shutil.rmtree(bb_dir, ignore_errors=True)
    line, dev_err = attempt({"CCSX_BLACKBOX": bb_dir}, budget)
    if line is None:
        print("[bench] retrying on CPU with reduced e2e", file=sys.stderr)
        line, _ = attempt({"JAX_PLATFORMS": "cpu",
                           "CCSX_BENCH_E2E_HOLES": "4",
                           # the budgeted retry must fit compile +
                           # measure + e2e in watchdog/2: 3 windows x
                           # (1+10) CPU rounds ~ 20 s of measurement
                           "CCSX_BENCH_ITERS": "10",
                           "CCSX_BENCH_WINDOWS": "3",
                           "CCSX_BENCH_DEADLINE": "180"}, budget / 2)
        if line is not None:
            # mark the fallback so downstream consumers can't mistake
            # XLA:CPU throughput for a TPU measurement/regression —
            # and attach the device attempt's post-mortem
            try:
                d = json.loads(line)
                d["degraded"] = "tpu attempt hung; CPU-fallback numbers"
                d["device_attempt"] = device_attempt_report(dev_err)
                import glob as globmod

                try:
                    rings = sorted(
                        globmod.glob(os.path.join(bb_dir,
                                                  "blackbox.*.bin")),
                        key=os.path.getmtime)
                except OSError:
                    # a ring vanished between glob and stat — forensics
                    # are best-effort, never bench-fatal
                    rings = []
                if rings:
                    # the hung attempt's black-box ring: render with
                    # `ccsx-tpu blackbox <path>`
                    d["device_attempt"]["blackbox"] = os.path.relpath(
                        rings[-1], _HERE)
                line = json.dumps(d)
            except ValueError:
                pass
    if line is None:
        line = json.dumps({
            "metric": "consensus round throughput",
            "value": None, "unit": "zmw_windows/s", "vs_baseline": None,
            "error": "backend hung on both TPU and CPU attempts"})
    print(line)


def _inner_main():
    calibrate = "--calibrate" in sys.argv
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if calibrate:
        # re-measure the native CPU yardstick and store the projections
        # (append, not insert(0) — see the note in measure())
        sys.path.append(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        import cpu_baseline

        b = cpu_baseline.build_baseline()
        with open(BASELINE_PATH, "w") as f:
            json.dump(b, f, indent=1)
        print(json.dumps({"calibrated": b}))
        return

    # the tunnelled TPU can hang on init; probe out-of-process and
    # fall back to CPU so the bench always produces its JSON line
    from ccsx_tpu.utils.device import resolve_device

    resolve_device("auto")
    value = measure()

    baseline = simd_factor = None
    cells_per_zw = P * W * 128  # fallback geometry
    if os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            b = json.load(f)
        baseline = b.get("zmw_windows_per_sec")
        simd_factor = b.get("simd_factor")
        if simd_factor is None:
            # old-schema artifact (r1-r4: guessed 8x credit, full-matrix
            # Gotoh baseline): its zmw_windows_per_sec is NOT the
            # measured vectorized fill this field now claims — refuse
            # the ratio until `python bench.py --calibrate` regenerates
            print("[bench] baseline artifact predates the measured-SIMD "
                  "schema; re-run `python bench.py --calibrate` "
                  "(vs_baseline suppressed)", file=sys.stderr)
            baseline = None
        # the unit conversion must match the baseline's, or the ratio
        # silently compares mismatched units; if the bench geometry has
        # drifted from the artifact, refuse the ratio until --calibrate
        stored = b.get("cells_per_zmw_window")
        if stored is not None and stored != cells_per_zw:
            print(f"[bench] geometry drift: baseline artifact has "
                  f"{stored} cells/zmw-window, bench shapes give "
                  f"{cells_per_zw}; re-run `python bench.py --calibrate` "
                  "(vs_baseline suppressed)", file=sys.stderr)
            baseline = None

    import jax

    backend = jax.default_backend()
    # Like-for-like baseline scope: the 64-core linear projection is the
    # yardstick for DEVICE runs only.  An XLA:CPU run on this host is a
    # 1-ish-core measurement — dividing it by a 64-core projection
    # reports a meaningless 0.001 that pollutes the trajectory (r5 TPU
    # hang -> CPU fallback did exactly that), so CPU runs compare
    # against the measured PER-CORE native fill instead.
    baseline_scope = None
    if baseline:
        if backend == "cpu":
            cores = b.get("projected_cores") or 64
            baseline = baseline / cores
            baseline_scope = "per_core_cpu"
        else:
            baseline_scope = "64core_projection"
    line = {
        "metric": "consensus round throughput "
                  f"(Z={Z} zmw x P={P} passes x W={W} window, "
                  f"backend={backend})",
        "backend": backend,
        "value": round(value, 3),
        "unit": "zmw_windows/s",
        # vs the MEASURED vectorized banded fill
        # (benchmarks/cpu_baseline.py) at the scope above;
        # baseline_simd_factor echoes the measured vec/scalar ratio
        "vs_baseline": round(value / baseline, 3) if baseline else None,
        "vs_baseline_scope": baseline_scope,
        "baseline_simd_factor": simd_factor,
        # one zmw-window = P x W x band DP cells (geometry taken from
        # the baseline artifact so the two sides can't diverge)
        "dp_cells_per_sec": round(value * cells_per_zw),
    }
    if backend == "cpu" and os.environ.get(
            "JAX_PLATFORMS", "").strip().lower() != "cpu":
        # an auto-resolved run that LANDED on CPU (device probe failed /
        # no accelerator): mark it so downstream trajectory parsing
        # never mistakes XLA:CPU throughput for a device regression.
        # The watchdog's hang-retry path sets its own degraded marker.
        line["degraded"] = ("no usable accelerator; CPU numbers at "
                            "per-core baseline scope")

    # e2e holes/sec over the five BASELINE configs (full CLI: ingest,
    # prep, consensus, write) on the same resolved backend.  Runs AFTER
    # the round metric: the e2e path transfers results to the host, which
    # flips the axon dev tunnel into ~80ms-RTT sync dispatch (see
    # ARCHITECTURE.md perf notes) — ordering keeps the round metric
    # honest; on direct (non-tunnel) TPU hardware there is no such mode.
    # CCSX_BENCH_E2E=0 skips; CCSX_BENCH_E2E_HOLES resizes (default 16 —
    # the fused window refinement makes dispatch count ~independent of
    # the hole count, so more holes amortize the per-dispatch cost).
    if os.environ.get("CCSX_BENCH_E2E", "1") != "0":
        holes = int(os.environ.get("CCSX_BENCH_E2E_HOLES", "16"))
        # soft deadline: cold compiles through a remote-compile tunnel
        # can take minutes per config; losing the whole JSON line to a
        # driver timeout is worse than skipping tail configs
        deadline = time.monotonic() + float(
            os.environ.get("CCSX_BENCH_DEADLINE", "420"))
        # append, not insert(0) — see the note in measure()
        sys.path.append(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        import e2e as e2e_mod

        # flight-recorder passthrough (utils/trace.py): CCSX_BENCH_TRACE
        # is a path prefix — each config's span JSONL + Chrome export
        # lands at <prefix>.c<N>.jsonl, and the per-shape-group
        # compile/execute table rides each e2e entry below, so the
        # bench artifact carries its own attribution evidence
        trace_prefix = os.environ.get("CCSX_BENCH_TRACE")
        # CCSX_BENCH_TELEMETRY=<port>: serve the live telemetry plane
        # during each e2e config, so a long battery is watchable with
        # `ccsx-tpu top host:<port>` instead of being a black box until
        # its JSON line lands (configs run sequentially, so one port
        # serves them all; the server auto-bumps if it is held)
        try:
            telemetry_port = int(
                os.environ.get("CCSX_BENCH_TELEMETRY", "0") or 0)
        except ValueError:
            telemetry_port = 0
        results = []
        for cfg in (1, 2, 3, 4, 5):
            if time.monotonic() > deadline:
                results.append({"config": cfg,
                                "skipped": "bench deadline exceeded"})
                continue
            try:
                r = e2e_mod.run_config(
                    cfg, holes, "auto",
                    trace_path=(f"{trace_prefix}.c{cfg}.jsonl"
                                if trace_prefix else None),
                    telemetry_port=telemetry_port)
                results.append({k: r.get(k) for k in (
                    "config", "backend", "holes_in", "holes_out",
                    "zmws_per_sec", "dp_row_fill",
                    "packed_holes_per_dispatch", "prep_share",
                    "prep_overlap_share", "groups", "degraded",
                    "traced", "mean_identity")})
            except Exception as exc:  # keep the primary metric alive
                results.append({"config": cfg, "error": repr(exc)[:200]})
        line["e2e"] = results

    # bench regression gate: self-compare against the most recent prior
    # BENCH_r*.json so the trajectory stops being write-only
    prev_art, prev = find_prev_bench()
    if prev is not None:
        compare_with_prev(line, prev, prev_art)
    else:
        vp = {"artifact": None,
              "note": "no prior BENCH_r*.json artifact; vs_baseline "
                      "reports the native yardstick"}
        regressed = []
        # the quality, fleet, serve, and dp-kernel gates still apply:
        # two artifacts can exist before any bench artifact does
        compare_quality(line, None, vp, regressed)
        compare_fleet(line, None, vp, regressed)
        compare_serve(line, None, vp, regressed)
        compare_serve_fleet(line, None, vp, regressed)
        compare_dp_kernel(line, None, vp, regressed)
        line["vs_prev"] = vp
        if regressed:
            line["regressed"] = regressed
            print("[bench] " + "!" * 20 + " ARTIFACT REGRESSION: "
                  + "; ".join(regressed) + " " + "!" * 20,
                  file=sys.stderr)

    print(json.dumps(line))


if __name__ == "__main__":
    main()
