"""Fault-tolerance layer (ARCHITECTURE.md "Failure domains"): failure
taxonomy + adaptive OOM resplit in the batched executor, crash-safe
journal v2 (torn-tail truncation, fingerprint compatibility), the
deterministic fault-injection harness, and per-shard completion markers.

The load-bearing guarantees pinned here: an injected device OOM degrades
to a resplit (or, persistent, to the host path) with BYTE-IDENTICAL
output; a kill between a flushed write and the journal update resumes to
byte-identical output with no duplicated or dropped holes; a dead shard
is named by merge_shards instead of silently shortening the merge.

All CLI tests share ONE synthetic corpus and ONE no-fault reference run
(module-scoped fixture): every recovery path must reproduce those exact
bytes, and sharing the compiled shapes keeps the file cheap in tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.io import fastx
from ccsx_tpu.parallel import distributed as dist
from ccsx_tpu.pipeline.batch import classify_failure
from ccsx_tpu.utils import faultinject, synth
from ccsx_tpu.utils.journal import Journal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(input fasta, no-fault reference output) — 3 holes, one shape
    bucket, batched pipeline.  Every fault test must reproduce the
    reference bytes exactly."""
    tmp = tmp_path_factory.mktemp("faults")
    rng = np.random.default_rng(0)
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(3)]
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    return fa, ref


def _names(path):
    return [r.name for r in fastx.read_fastx(str(path))]


def _records(path):
    """FASTA text split into whole records (header + one seq line)."""
    lines = path.read_text().splitlines(keepends=True)
    return ["".join(lines[i:i + 2]) for i in range(0, len(lines), 2)]


# ---------- taxonomy + harness units ----------

def test_classify_failure():
    assert classify_failure(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes")) == "oom"
    assert classify_failure(RuntimeError("Failed to allocate device "
                                         "buffer")) == "oom"
    assert classify_failure(RuntimeError(
        "Mosaic failed to compile TPU kernel")) == "compile"
    assert classify_failure(NotImplementedError(
        "pallas lowering rule for foo not found")) == "compile"
    assert classify_failure(ValueError("draft longer than tmax")) == "data"
    assert classify_failure(IndexError("oops")) == "data"
    # broad compiler-ish words in ordinary errors must NOT pin the
    # process-wide scan fallback (the markers are deliberately narrow)
    assert classify_failure(TypeError(
        "unsupported operand type(s) for -: 'str' and 'int'")) == "data"
    assert classify_failure(RuntimeError(
        "compilation of x failed")) == "data"
    # our own kernel-config ValueErrors name the kernel but are
    # per-group data conditions, never toolchain failures
    assert classify_failure(ValueError(
        "qmax=2048 exceeds PALLAS_MAX_QMAX; use the scan aligner"
    )) == "data"


def test_faultinject_spec_and_schedule():
    assert faultinject.parse_spec("device_oom@2,write") == {
        "device_oom": [2, False], "write": [1, False]}
    assert faultinject.parse_spec("compute@3+") == {"compute": [3, True]}
    with pytest.raises(ValueError, match="unknown fault point"):
        faultinject.parse_spec("frobnicate@1")
    with pytest.raises(ValueError, match=">= 1"):
        faultinject.parse_spec("write@0")
    with pytest.raises(ValueError, match="bad fault schedule"):
        faultinject.parse_spec("write@x")
    # once-schedule fires exactly on the Nth call
    faultinject.arm("compute@2")
    faultinject.fire("compute")  # call 1: no-op
    with pytest.raises(RuntimeError, match="injected compute fault"):
        faultinject.fire("compute")
    faultinject.fire("compute")  # call 3: past the schedule, no-op
    # repeat-schedule keeps firing
    faultinject.arm("device_oom@1+")
    for _ in range(3):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            faultinject.fire("device_oom")


def test_bad_env_spec_fails_attributed(monkeypatch):
    """A typo'd CCSX_FAULTS must fail naming the env var (SystemExit),
    not leak a ValueError into the first pipeline stage that fires —
    the drivers would misreport that as an input-stream error."""
    monkeypatch.setenv("CCSX_FAULTS", "wrte@2")
    faultinject._plan = faultinject._UNSET  # force re-init from env
    with pytest.raises(SystemExit, match="CCSX_FAULTS"):
        faultinject.fire("ingest")
    faultinject.fire("ingest")  # after the report: disarmed, no-op


def test_cli_rejects_bad_fault_spec(tmp_path, capsys):
    rc = cli.main(["--inject-faults", "bogus@1", "x.fa",
                   str(tmp_path / "y.fa")])
    assert rc == 1
    assert "--inject-faults" in capsys.readouterr().err


def test_force_scan_fallback_is_one_time():
    from ccsx_tpu.consensus import star

    assert star._FORCE_SCAN is False
    try:
        assert star.force_scan_fallback("test reason") is True
        assert star.use_pallas() is False          # even if env asks for it
        assert star.force_scan_fallback("again") is False
    finally:
        star._FORCE_SCAN = False


def test_journal_v1_still_accepted(tmp_path):
    """Legacy journals (no version/offsets) keep their cursor and skip
    the v2 verifications."""
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"input_id": "in.fa", "holes_done": 5}))
    j = Journal.load_or_create(str(jp), input_id="in.fa",
                               fingerprint="abc-def")
    assert j.holes_done == 5 and j.out_bytes is None
    out = tmp_path / "o.fa"
    out.write_text("anything\n")
    j.verify_output(str(out))  # no offsets recorded: must be a no-op
    assert j.holes_done == 5
    assert out.read_text() == "anything\n"


# ---------- quarantine ----------

def test_compute_fault_quarantines_one_hole(corpus, tmp_path, capsys):
    """One injected per-hole failure costs that hole, never the run —
    in both drivers."""
    fa, _ = corpus
    for batch in ("on", "off"):
        out = tmp_path / f"o_{batch}.fa"
        faultinject.arm("compute@2")
        rc = cli.main(["-A", "-m", "1000", "--batch", batch,
                       str(fa), str(out)])
        assert rc == 0
        assert _names(out) == ["mv/100/ccs", "mv/102/ccs"]
        assert "failed" in capsys.readouterr().err


def test_ingest_fault_clean_rc1(corpus, tmp_path, capsys):
    fa, _ = corpus
    out = tmp_path / "o.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--inject-faults", "ingest@1", str(fa), str(out)])
    assert rc == 1
    assert "invalid input stream" in capsys.readouterr().err


# ---------- OOM resplit / host-fallback ladder ----------

def test_injected_oom_resplit_output_identical(corpus, tmp_path, capsys):
    """A device OOM on a multi-request shape group bisects and retries
    at smaller Z; the output must be byte-identical to the no-fault run
    (per-request results are Z-invariant: padding is masked).

    Inline prep + a pinned admission window: with the background prep
    pool, the first sweep dispatches however many holes prep delivered
    in time — sometimes ONE, whose group cannot resplit (it goes
    straight to host replay) — so the multi-request-group premise was
    a coin flip.  Inline admission fills the window before the first
    sweep, deterministically."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    faultinject.arm("device_oom@1")
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--inflight", "8", "--prep-threads", "0",
                     str(fa), str(out)]) == 0
    assert out.read_bytes() == ref.read_bytes()
    assert "resplitting" in capsys.readouterr().err


def test_persistent_oom_falls_back_to_host(corpus, tmp_path, capsys):
    """Every device dispatch OOMing rides the whole ladder down to the
    per-request host replay — and still produces byte-identical output
    (the host path is the spec the fused step mirrors)."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    m = tmp_path / "m.jsonl"
    faultinject.arm("device_oom@1+")
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--metrics", str(m), str(fa), str(out)]) == 0
    faultinject.disarm()
    assert out.read_bytes() == ref.read_bytes()
    err = capsys.readouterr().err
    assert "replaying on the host path" in err
    final = [json.loads(line) for line in m.read_text().splitlines()][-1]
    assert final["host_fallbacks"] >= 1
    assert final["oom_resplits"] >= 1
    assert final["holes_out"] == 3 and final["holes_failed"] == 0


def test_compile_failure_pins_scan_and_retries(corpus, tmp_path, capsys,
                                               monkeypatch):
    """A Pallas/Mosaic-looking compile failure forces the scan spec
    (one-time) and retries the same group — no output change, no
    aborted run."""
    from ccsx_tpu.consensus import star
    from ccsx_tpu.pipeline import batch as batch_mod

    fa, ref = corpus
    calls = {"n": 0}

    def fake_fire(point):
        if point == "device_oom":
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("Mosaic lowering failed (injected)")

    monkeypatch.setattr(batch_mod.faultinject, "fire", fake_fire)
    assert star._FORCE_SCAN is False
    out = tmp_path / "o.fa"
    try:
        assert cli.main(["-A", "-m", "1000", "--batch", "on",
                         str(fa), str(out)]) == 0
        assert star._FORCE_SCAN is True
    finally:
        star._FORCE_SCAN = False
    assert out.read_bytes() == ref.read_bytes()
    assert "falling back to the banded-scan spec" in capsys.readouterr().err


# ---------- journal v2: crash-safe resume ----------

def _run_cli_subprocess(args, env_extra):
    """Run the CLI in its own OS process (the write/journal faults
    os._exit; in-process would kill pytest).  Same CPU-pinning idiom as
    tests/test_distributed.py."""
    runner = ("import sys, jax; jax.config.update('jax_platforms', 'cpu'); "
              "from ccsx_tpu.cli import main; sys.exit(main(sys.argv[1:]))")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CCSX_SKIP_PROBE="1",
               XLA_FLAGS="", **env_extra)
    return subprocess.run([sys.executable, "-c", runner, *args], env=env,
                          cwd=_REPO, capture_output=True, text=True,
                          timeout=300)


def test_kill_between_write_and_journal_then_resume(corpus, tmp_path):
    """THE acceptance case: a hard kill after a record is flushed but
    before the journal advances leaves the output AHEAD of the journal;
    a --journal resume truncates the torn tail, recomputes the
    interrupted hole, and finishes byte-identical to an uninterrupted
    run — no duplicated, no dropped holes."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    args = ["-A", "-m", "1000", "--batch", "on", "--journal", str(jp),
            str(fa), str(out)]
    # CCSX_JOURNAL_FSYNC_S=0: every advance hits disk, so the crashed
    # journal's cursor is deterministic (the rate limit would otherwise
    # make it timing-dependent)
    r = _run_cli_subprocess(args, {"CCSX_FAULTS": "write@2",
                                   "CCSX_JOURNAL_FSYNC_S": "0"})
    assert r.returncode == faultinject.EXIT_CODE, (r.stdout, r.stderr)
    j = json.loads(jp.read_text())
    assert j["version"] == 2 and j["holes_done"] == 1
    # the torn state: record 2 hit the disk, the journal never saw it
    assert os.path.getsize(out) > j["out_bytes"]
    assert len(_names(out)) == 2

    assert cli.main(args) == 0  # resume, no faults
    assert out.read_text() == ref.read_text()
    assert json.loads(jp.read_text())["holes_done"] == 3


@pytest.mark.slow
def test_kill_inside_journal_replace_then_resume(corpus, tmp_path):
    """A kill between the fsynced tmp journal and the atomic replace
    leaves the OLD journal intact (never a torn one); resume repairs
    the output tail exactly as in the write-kill case.  (slow: a second
    cold CLI subprocess.)"""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    args = ["-A", "-m", "1000", "--batch", "on", "--journal", str(jp),
            str(fa), str(out)]
    # fsync rate limit off: the journal fault point fires per-advance
    # (disk updates), so @2 lands deterministically on hole 2's update
    r = _run_cli_subprocess(args, {"CCSX_FAULTS": "journal@2",
                                   "CCSX_JOURNAL_FSYNC_S": "0"})
    assert r.returncode == faultinject.EXIT_CODE, (r.stdout, r.stderr)
    j = json.loads(jp.read_text())   # the OLD journal, still valid JSON
    assert j["holes_done"] == 1
    assert cli.main(args) == 0
    assert out.read_text() == ref.read_text()
    assert json.loads(jp.read_text())["holes_done"] == 3


def test_torn_partial_record_tail_truncated(corpus, tmp_path, capsys):
    """A tail torn MID-RECORD (half a FASTA line) is truncated back to
    the journaled offset and the hole recomputed."""
    fa, ref = corpus
    recs = _records(ref)
    out = tmp_path / "o.fa"
    out.write_text(recs[0] + recs[1][: len(recs[1]) // 2])  # torn rec 2
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"version": 2, "input_id": str(fa),
                              "holes_done": 1,
                              "out_bytes": len(recs[0])}))
    assert cli.main(["-A", "-m", "1000", "--batch", "on", "--journal",
                     str(jp), str(fa), str(out)]) == 0
    assert "truncating torn tail" in capsys.readouterr().err
    assert out.read_text() == ref.read_text()


def test_output_behind_journal_refuses_resume(corpus, tmp_path, capsys):
    """A file SHORTER than the journal means journaled output was lost
    (nothing durable to trust): the resume is refused and the run
    recomputes from scratch — still byte-identical at the end."""
    fa, ref = corpus
    recs = _records(ref)
    out = tmp_path / "o.fa"
    out.write_text(recs[0])
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"version": 2, "input_id": str(fa),
                              "holes_done": 2,
                              "out_bytes": len(recs[0]) + len(recs[1])}))
    assert cli.main(["-A", "-m", "1000", "--batch", "on", "--journal",
                     str(jp), str(fa), str(out)]) == 0
    assert "refusing to resume" in capsys.readouterr().err
    assert out.read_text() == ref.read_text()


def test_fingerprint_mismatch_refuses_resume(corpus, tmp_path, capsys):
    """A journal cut by different code/config must not be resumed into
    this run's artifact."""
    fa, ref = corpus
    recs = _records(ref)
    out = tmp_path / "o.fa"
    out.write_text(recs[0])
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"version": 2, "input_id": str(fa),
                              "holes_done": 1, "out_bytes": len(recs[0]),
                              "fingerprint": "stale-code-stale-cfg"}))
    assert cli.main(["-A", "-m", "1000", "--batch", "on", "--journal",
                     str(jp), str(fa), str(out)]) == 0
    assert "fingerprint mismatch" in capsys.readouterr().err
    assert out.read_text() == ref.read_text()
    # the rewritten journal carries THIS run's fingerprint
    assert json.loads(jp.read_text())["fingerprint"] != "stale-code-stale-cfg"


# ---------- shard failure visibility ----------

def test_merge_refuses_dead_shard_and_names_it(corpus, tmp_path):
    fa, ref = corpus
    out = tmp_path / "dist.fa"
    assert cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "0",
                     str(fa), str(out)]) == 0
    # rank 0 completed and says so
    marker = json.loads((tmp_path / "dist.fa.shard0.done").read_text())
    assert marker["rank"] == 0 and marker["records"] == len(_names(
        tmp_path / "dist.fa.shard0"))
    # rank 1 never ran: the merge must refuse and name it, not emit a
    # silently short output
    with pytest.raises(ValueError, match="shard1"):
        dist.merge_shards(str(out), 2)
    assert not out.exists()
    # after the dead rank reruns, the merge equals the single-host run
    assert cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "1",
                     str(fa), str(out)]) == 0
    assert dist.merge_shards(str(out), 2) == 3
    assert out.read_text() == ref.read_text()
    assert not (tmp_path / "dist.fa.shard0.done").exists()  # cleaned up


def test_all_unmarked_set_refused_unless_allowed(tmp_path):
    """ALL ranks unmarked is indistinguishable from a node-wide kill, so
    it refuses too (hinting at allow_unmarked for true legacy sets)."""
    out = str(tmp_path / "o.fa")
    for r in range(2):
        w = dist.ShardWriter(out, r, 2, append=False)
        w.put_at(0, f"mv/{r}/ccs", b"ACGT")
        w.close()
    with pytest.raises(ValueError, match="allow_unmarked"):
        dist.merge_shards(out, 2)
    assert dist.merge_shards(out, 2, allow_unmarked=True) == 2
    names = [r.name for r in fastx.read_fastx(out)]
    assert names == ["mv/0/ccs", "mv/1/ccs"]


def test_merge_wrong_host_count_refused(tmp_path):
    """Markers record the run's host count; merging a 4-host set with
    --merge-shards 2 would silently drop shards 2-3 — refused."""
    out = str(tmp_path / "o.fa")
    for r in range(2):
        w = dist.ShardWriter(out, r, 4, append=False)
        w.put_at(0, f"mv/{r}/ccs", b"ACGT")
        w.close()
        dist._write_done_marker(out, r, 4, 1)
    with pytest.raises(ValueError, match="4 hosts"):
        dist.merge_shards(out, 2)


def test_dead_shard_with_partial_output_reports_progress(corpus, tmp_path):
    """A shard that died mid-run (partial shard + idx, no marker) is
    reported with how far it got."""
    fa, _ = corpus
    out = tmp_path / "dist.fa"
    assert cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "0",
                     str(fa), str(out)]) == 0
    # simulate rank 1 dying mid-run: partial files, no .done marker
    (tmp_path / "dist.fa.shard1").write_text(">mv/101/ccs\nACGT\n")
    (tmp_path / "dist.fa.shard1.idx").write_text("#mode=rr\n1\n")
    with pytest.raises(ValueError, match=r"shard1 \(died after 1 durable"):
        dist.merge_shards(str(out), 2)
