"""Resume journal for long runs (v2: crash-safe, torn-tail aware).

The reference has no checkpointing (SURVEY.md §5.4): a crash means a full
rerun.  Because output is strictly input-ordered, resumability needs one
cursor — how many filtered holes have been fully retired — plus, in v2,
enough context to prove the cursor still describes the bytes on disk:

  * ``out_bytes`` / ``idx_bytes``: the output file size(s) at the cursor.
    A crash between a record write and the journal update leaves the file
    AHEAD of the journal (a torn tail); on resume ``verify_output``
    truncates the file back to the journaled offset, so the interrupted
    hole is recomputed instead of duplicated.  A file SHORTER than the
    journal means journaled work never became durable (the journal cannot
    be trusted at all) — the resume is refused and the run restarts.
  * ``fingerprint``: a config/code fingerprint (utils/fingerprint.py).
    Resuming across a change to the consensus code or an output-shaping
    config field would silently mix old-code output into a new-run
    artifact — refused instead.

Durability of the journal itself: every DISK update is a fully-fsynced
atomic replace (write_json_atomic: tmp write + fsync + ``os.replace`` +
directory fsync), so a crash at any instant — process kill or power
loss — leaves either the old or the new journal, never a torn or
unsynced one.  Disk updates are rate-limited to once per
``fsync_interval_s`` (env ``CCSX_JOURNAL_FSYNC_S``): between updates
the cursor advances in memory only, which is always safe — the output
file merely runs ahead of the journal, exactly the torn-tail state
resume repairs — and ``close()`` settles the final state.  Per-hole
fsyncs would buy nothing but a throughput floor on slow filesystems.
The drivers flush the output writer BEFORE
each advance (journaled runs use a synchronous writer, pipeline/run.py
``open_writer(journaled=True)``), preserving the invariant that the
journal never runs ahead of durable output.

v1 journals (no ``version`` field) are still accepted: the cursor is
honored and the v2 verifications are skipped — the legacy behavior.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Optional

from ccsx_tpu.utils import faultinject
from ccsx_tpu.utils import trace

VERSION = 2


def write_json_atomic(path: str, obj: dict, pre_replace_hook=None) -> None:
    """THE crash-safe small-JSON write (shared by the journal and the
    shard completion markers — one copy of the idiom, one place to fix
    it): tmp write + flush + fsync, optional hook (fault injection),
    atomic replace, then best-effort directory fsync so the rename
    itself survives power loss."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    if pre_replace_hook is not None:
        pre_replace_hook()
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def write_json_exclusive(path: str, obj: dict) -> bool:
    """Crash-safe EXCLUSIVE small-JSON commit (the fleet plane's
    done-marker fence, pipeline/fleet.py): like write_json_atomic, but
    the publish step is ``os.link`` — which fails with EEXIST instead
    of replacing — so exactly ONE of any number of racing writers can
    ever commit ``path``.  Returns True when this caller committed,
    False when someone else already had (the loser must treat the
    existing marker as authoritative, not overwrite it).

    The tmp name carries the pid so two racers never collide on the
    staging file either."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
        committed = True
    except FileExistsError:
        committed = False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return committed


@dataclasses.dataclass
class Journal:
    path: str
    input_id: str
    holes_done: int = 0
    # failed (quarantined) and emitted holes among the retired ones:
    # restored into Metrics on resume so a --max-failed-holes budget is
    # judged over the WHOLE logical run — without the failure count,
    # every resume would silently grant a fresh budget, and without the
    # emitted count a fraction budget would judge prior failures
    # against THIS session's successes only (spurious rc-2 aborts on
    # short resume tails)
    holes_failed: int = 0
    holes_emitted: int = 0
    out_bytes: Optional[int] = None   # output file size at the cursor
    idx_bytes: Optional[int] = None   # shard .idx sidecar size (sharded runs)
    fingerprint: Optional[str] = None  # config/code compat key for THIS run
    # Disk-update rate limit: paying a fully-fsynced atomic replace per
    # retired hole would floor per-hole throughput on slow filesystems
    # for nothing the design needs — a LAGGING journal is always safe
    # (file ahead of journal -> torn tail truncated, holes recomputed),
    # while a lagging-but-UNSYNCED journal is not (a power cut during
    # an unfsynced replace can zero the good journal on e.g. XFS).  So
    # every disk update is fully fsynced, and updates happen at most
    # once per this many seconds (0 = every advance); close() settles
    # the final cursor.  Env override: CCSX_JOURNAL_FSYNC_S.
    fsync_interval_s: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("CCSX_JOURNAL_FSYNC_S", "1.0")))
    _last_fsync: float = dataclasses.field(default=float("-inf"),
                                           repr=False)
    _pending: bool = dataclasses.field(default=False, repr=False)

    @classmethod
    def for_run(cls, path: Optional[str], input_id: str, cfg,
                out_path: Optional[str] = None,
                idx_path: Optional[str] = None) -> "Journal":
        """THE journal-setup entry all three drivers share: load (or
        create) under this run's config/code fingerprint, then reconcile
        the output file(s) with the cursor (verify_output) BEFORE any
        writer opens for append.  Paths of "-" (stdout) are skipped."""
        fingerprint = None
        if path:
            from ccsx_tpu.utils.fingerprint import run_fingerprint

            fingerprint = run_fingerprint(cfg)
        j = cls.load_or_create(path, input_id=input_id,
                               fingerprint=fingerprint)
        if path and out_path and out_path != "-":
            j.verify_output(out_path, idx_path)
        return j

    @classmethod
    def load_or_create(cls, path: Optional[str], input_id: str,
                       fingerprint: Optional[str] = None) -> "Journal":
        j = cls(path=path or "", input_id=input_id, fingerprint=fingerprint)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                return j  # unreadable journal: start over
            if d.get("input_id") != input_id:
                return j
            stored = d.get("fingerprint")
            if (stored is not None and fingerprint is not None
                    and stored != fingerprint):
                # the checkpoint was cut by different code or an
                # output-shaping config change: resuming would mix
                # incompatible sections into one artifact — recompute
                print(f"[ccsx-tpu] journal {path}: fingerprint mismatch "
                      f"(journal {stored}, run {fingerprint}); refusing "
                      "to resume — recomputing from scratch",
                      file=sys.stderr)
                return j
            j.holes_done = int(d.get("holes_done", 0))
            j.holes_failed = int(d.get("holes_failed", 0))
            j.holes_emitted = int(d.get("holes_emitted", 0))
            ob, ib = d.get("out_bytes"), d.get("idx_bytes")
            j.out_bytes = int(ob) if ob is not None else None
            j.idx_bytes = int(ib) if ib is not None else None
        return j

    def reset(self) -> None:
        """Discard the resume state (the caller recomputes from scratch)."""
        self.holes_done = 0
        self.holes_failed = 0
        self.holes_emitted = 0
        self.out_bytes = None
        self.idx_bytes = None

    def verify_output(self, out_path: str,
                      idx_path: Optional[str] = None) -> None:
        """Reconcile the output file(s) with the journaled offsets before
        a resume: truncate a torn tail (file ahead of journal — the
        crash-between-write-and-journal case), or refuse the resume
        entirely (file behind journal: journaled work was lost, nothing
        on disk can be trusted).  No-op for v1 journals (no offsets) and
        fresh journals."""
        if not self.holes_done:
            return
        targets = [(out_path, self.out_bytes)]
        if idx_path is not None:
            targets.append((idx_path, self.idx_bytes))
        sizes = []
        for path, want in targets:
            if want is None:
                sizes.append(None)
                continue
            have = os.path.getsize(path) if os.path.exists(path) else 0
            if have < want:
                print(f"[ccsx-tpu] journal {self.path}: {path} is {have} "
                      f"bytes but the journal recorded {want} — journaled "
                      "output was lost; refusing to resume, recomputing "
                      "from scratch", file=sys.stderr)
                self.reset()
                return
            sizes.append(have)
        for (path, want), have in zip(targets, sizes):
            if want is None or have is None or have == want:
                continue
            print(f"[ccsx-tpu] journal {self.path}: truncating torn tail "
                  f"of {path} ({have} -> {want} bytes; the interrupted "
                  "hole will be recomputed)", file=sys.stderr)
            with open(path, "rb+") as f:
                f.truncate(want)

    def retire(self, writer, wrote: bool, metrics=None) -> None:
        """Retire ONE emitted hole — the single home of the crash
        invariant both drivers share: the record is flushed durable
        BEFORE the cursor claims it (journaled writers are synchronous,
        run.open_writer journaled=True), then the 'write' fault point
        (the canonical torn-tail kill instant), then the cursor advance
        carrying the writer's byte accounting."""
        if wrote and self.path:
            flush = getattr(writer, "flush", None)
            if flush is not None:
                with trace.span("writer_flush", cat="write"):
                    if metrics is not None:
                        with metrics.timer("write"):
                            flush()
                    else:
                        flush()
            faultinject.fire("write")
        if wrote:
            self.holes_emitted += 1
        if metrics is not None:
            # carried so a resume restores the failure count (the
            # --max-failed-holes budget survives restarts)
            self.holes_failed = metrics.holes_failed
        self.advance(out_bytes=getattr(writer, "bytes_out", None),
                     idx_bytes=getattr(writer, "idx_bytes_out", None))

    def advance(self, n: int = 1, out_bytes: Optional[int] = None,
                idx_bytes: Optional[int] = None) -> None:
        self.holes_done += n
        if out_bytes is not None:
            self.out_bytes = out_bytes
        if idx_bytes is not None:
            self.idx_bytes = idx_bytes
        if not self.path:
            return
        if (time.monotonic() - self._last_fsync) < self.fsync_interval_s:
            # cursor lags on disk (safe: resume truncates the file tail
            # back to it and recomputes); close() settles the final state
            self._pending = True
            return
        self._write()

    def close(self) -> None:
        """Settle any in-memory cursor progress onto disk (drivers call
        this at run end, after the writer closes).

        A failed settle (ENOSPC on a full disk — the very failure that
        may have ended the run) is a WARNING, not a raise: the
        on-disk journal is merely further behind the durable output,
        which is exactly the torn-tail state resume repairs.  Raising
        from the drivers' ``finally`` would replace the real rc with a
        traceback."""
        if self.path and self._pending:
            try:
                self._write()
            except OSError as e:
                print(f"[ccsx-tpu] journal {self.path}: final settle "
                      f"failed ({e}); the on-disk cursor lags the "
                      "output — resume will truncate and recompute the "
                      "tail", file=sys.stderr)

    def _write(self) -> None:
        # the injected crash fires between the fsynced tmp and the
        # atomic replace: the OLD journal must survive intact
        with trace.span("journal_update", cat="journal",
                        holes_done=self.holes_done):
            self._write_disk()

    def _write_disk(self) -> None:
        write_json_atomic(
            self.path,
            {"version": VERSION,
             "input_id": self.input_id,
             "holes_done": self.holes_done,
             "holes_failed": self.holes_failed,
             "holes_emitted": self.holes_emitted,
             "out_bytes": self.out_bytes,
             "idx_bytes": self.idx_bytes,
             "fingerprint": self.fingerprint},
            pre_replace_hook=lambda: faultinject.fire("journal"))
        self._last_fsync = time.monotonic()
        self._pending = False
