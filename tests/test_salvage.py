"""Hostile-input ingest plane (ISSUE 10): the pinned corruption
taxonomy, --salvage mode end-to-end, graceful drain on SIGTERM/SIGINT,
and disk-full hardening — the input leg of the resilience triad
(device + rank closed in PR 7).

Contracts pinned here:
  * the reason-code taxonomy cannot drift (REASONS is frozen);
  * --salvage OFF preserves fail-fast byte-identically, and --salvage
    ON over a CLEAN input is also byte-identical (zero overhead when
    healthy);
  * a corrupt input under --salvage completes rc 0 marked degraded,
    books holes_corrupt + per-reason buckets, and emits every
    UNDAMAGED hole byte-identical to the clean run;
  * corrupt holes spend the --max-failed-holes budget (rc 2);
  * SIGTERM mid-run drains (admission stops, in-flight finishes,
    journal settles, rc 75) and a resume reaches byte-identity;
  * injected ENOSPC exits the clean rc-1 path with a consistent
    journal, and a resume reaches byte-identity.
"""

import json
import os
import signal
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.io import corruption
from ccsx_tpu.utils import faultinject, synth
from ccsx_tpu.utils.drain import DrainGuard


@pytest.fixture(autouse=True)
def _disarm():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """6-hole FASTA corpus + its clean-run reference bytes (one
    consensus run shared by every test in this module)."""
    tmp = tmp_path_factory.mktemp("salvage")
    rng = np.random.default_rng(0)
    zs = [synth.make_zmw(rng, template_len=500, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(6)]
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", str(fa),
                   str(ref)])
    assert rc == 0
    return fa, ref.read_bytes()


def _by_hole(b: bytes) -> dict:
    return {c.split("\n", 1)[0]: c for c in b.decode().split(">")[1:]}


# ---------- taxonomy pinned ----------


def test_reason_codes_pinned():
    """The stable reason codes both reader stacks report — a rename or
    removal is a cross-stack contract break and must fail loudly."""
    assert corruption.REASONS == (
        "bam_bad_header", "bgzf_bad_block", "bgzf_bad_deflate",
        "bgzf_torn_tail", "bgzf_missing_eof", "gzip_truncated",
        "bam_bad_record", "bam_record_oversize", "fastx_qual_mismatch",
        "fastx_truncated", "zmw_bad_name", "injected")
    assert corruption.NON_BUDGET_REASONS == ("bgzf_missing_eof",)
    assert corruption.DEFAULT_MAX_RECORD_BYTES == 256 * 1024 * 1024
    # the config default must agree with the taxonomy's bound (the CLI
    # help and the native kDefaultMaxRecordBytes both quote it)
    from ccsx_tpu.config import CcsConfig

    assert CcsConfig().max_record_bytes == \
        corruption.DEFAULT_MAX_RECORD_BYTES


def test_corruption_error_is_value_error():
    """Pre-taxonomy handlers (except ValueError / except BamError)
    must keep catching classified errors."""
    from ccsx_tpu.io.bam import BamError
    from ccsx_tpu.io.fastx import FastxError
    from ccsx_tpu.io.zmw import InvalidZmwName

    for exc in (corruption.CorruptionError("injected", "x"),
                BamError("x"), FastxError("fastx_truncated", "x"),
                InvalidZmwName("x")):
        assert isinstance(exc, ValueError)
        assert exc.reason in corruption.REASONS


def test_allocation_bound_rejects_before_allocating(tmp_path):
    """A corrupt int32 record length past --max-record-bytes must
    classify bam_record_oversize BEFORE any allocation happens."""
    import struct

    from ccsx_tpu.io import bam as bam_mod

    recs = [(f"mv/1/{i}_{i+50}", b"ACGT" * 16, b"I" * 64)
            for i in range(6)]
    p = tmp_path / "t.bam"
    bam_mod.write_bam(str(p), recs, bgzf=False)
    import gzip

    payload = bytearray(gzip.decompress(p.read_bytes()))
    (l_text,) = struct.unpack_from("<i", payload, 4)
    off = 8 + l_text + 4   # through n_ref (0 refs)
    payload[off:off + 4] = struct.pack("<i", 1 << 30)  # 1 GiB "record"
    p.write_bytes(gzip.compress(bytes(payload)))
    with pytest.raises(bam_mod.BamError) as ei:
        list(bam_mod.read_bam_records(str(p)))
    assert ei.value.reason == "bam_record_oversize"
    # salvage classifies the same way and survives
    sink = corruption.SalvageSink()
    got = list(bam_mod.read_bam_records(str(p), salvage=sink))
    assert sink.reasons.get("bam_record_oversize", 0) >= 1
    assert len(got) <= len(recs)


def test_missing_eof_marker_is_budget_exempt(tmp_path):
    """A healthy BGZF BAM that merely lost its EOF marker: salvage
    emits every hole, books bgzf_missing_eof, and a --max-failed-holes
    0 budget must NOT rc-2 the complete output (the reviewer-found
    zero-loss trap).  Both stacks classify it the same way."""
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.io import bam as bam_mod, zmw as zmw_mod
    from ccsx_tpu.io.corruption import SalvageSink
    from ccsx_tpu.native.io import stream_zmws_native
    from ccsx_tpu.utils.metrics import (Metrics, check_failure_budget)

    recs = [(f"mv/1/{i}_{i+80}", b"ACGT" * 20, b"I" * 80)
            for i in range(6)]
    p = tmp_path / "t.bam"
    bam_mod.write_bam(str(p), recs, bgzf=True)
    data = p.read_bytes()
    p.write_bytes(data[:-len(bam_mod.BGZF_EOF)])

    cfg = CcsConfig(min_subread_len=1, is_bam=True, salvage=True,
                    max_failed_holes=0.0)
    m = Metrics()
    sink = SalvageSink(m)
    py = list(zmw_mod.stream_zmws(
        bam_mod.read_bam_records(str(p), salvage=sink), cfg, metrics=m,
        salvage=sink))
    assert len(py) == 1 and py[0].n_passes == 6   # nothing lost
    assert m.corrupt_reasons == {"bgzf_missing_eof": 1}
    check_failure_budget(m, cfg)                  # must NOT raise
    check_failure_budget(m, cfg, final=True)
    m2 = Metrics()
    nat = list(stream_zmws_native(str(p), cfg, metrics=m2))
    assert [(z.hole, z.n_passes) for z in nat] == \
        [(z.hole, z.n_passes) for z in py]
    assert m2.corrupt_reasons == {"bgzf_missing_eof": 1}


def test_max_record_bytes_applies_without_salvage(tmp_path):
    """The allocation bound is live on BOTH stacks with salvage OFF:
    a record larger than --max-record-bytes classifies
    bam_record_oversize instead of being allocated."""
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.io import bam as bam_mod
    from ccsx_tpu.native.io import (NativeStreamError,
                                    stream_zmws_native)

    seq = b"ACGT" * 4000   # 16 kB record > the 8 kB bound
    recs = [(f"mv/1/{i}_{i+80}", seq, b"I" * len(seq))
            for i in range(6)]
    p = tmp_path / "t.bam"
    bam_mod.write_bam(str(p), recs, bgzf=True)
    cfg = CcsConfig(min_subread_len=1, is_bam=True,
                    max_record_bytes=8192)
    with pytest.raises(NativeStreamError) as ei:
        list(stream_zmws_native(str(p), cfg))
    assert ei.value.reason == "bam_record_oversize"
    with pytest.raises(bam_mod.BamError) as ei:
        list(bam_mod.read_bam_records(
            str(p), max_record_bytes=cfg.max_record_bytes))
    assert ei.value.reason == "bam_record_oversize"


# ---------- salvage end-to-end through the CLI ----------


def test_salvage_clean_input_byte_identical(corpus, tmp_path):
    """Zero overhead when healthy: --salvage over a clean input is
    byte-identical to the fail-fast run."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", "--salvage",
                   str(fa), str(out)])
    assert rc == 0
    assert out.read_bytes() == ref


def test_salvage_corrupt_input_emits_undamaged_holes(corpus, tmp_path):
    """A poisoned record: fail-fast dies rc 1; --salvage completes rc 0
    degraded with the damaged hole's event booked and every undamaged
    hole byte-identical."""
    fa, ref = corpus
    data = fa.read_bytes()
    idx = data.find(b">mv/102/")
    mut = data[:idx] + data[idx:].replace(b"/", b"x", 2)
    bad = tmp_path / "bad.fa"
    bad.write_bytes(mut)

    out = tmp_path / "ff.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", str(bad),
                   str(out)])
    assert rc == exitcodes.RC_FATAL

    m = tmp_path / "m.jsonl"
    out = tmp_path / "sv.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", "--salvage",
                   "--metrics", str(m), str(bad), str(out)])
    assert rc == exitcodes.RC_OK
    final = [json.loads(line) for line in open(m)][-1]
    assert final["holes_corrupt"] == 1
    assert final["corrupt_reasons"] == {"zmw_bad_name": 1}
    assert final.get("degraded")
    r, s = _by_hole(ref), _by_hole(out.read_bytes())
    for name, rec in r.items():
        if "/102/" not in name:
            assert s.get(name) == rec, f"undamaged {name} changed"


def test_corrupt_holes_spend_failure_budget(corpus, tmp_path):
    """--max-failed-holes 0 + one salvaged corruption = rc 2: salvage
    must not become a silent data-loss mode with a budget set."""
    fa, _ = corpus
    out = tmp_path / "o.fa"
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", "--salvage",
                   "--max-failed-holes", "0",
                   "--inject-faults", "input_corrupt@2",
                   str(fa), str(out)])
    assert rc == exitcodes.RC_FAILED_HOLES


def test_salvage_knob_is_resume_compatible():
    """'It died on a corrupt block — re-run WITH --salvage and resume'
    must not be refused as a config change (fingerprint invariance) —
    but changing --max-record-bytes redefines which healthy records
    are ACCEPTED, so it must invalidate a resume."""
    import dataclasses

    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.utils.fingerprint import run_fingerprint

    base = CcsConfig()
    assert run_fingerprint(base) == run_fingerprint(
        dataclasses.replace(base, salvage=True))
    assert run_fingerprint(base) != run_fingerprint(
        dataclasses.replace(base, max_record_bytes=1 << 20))


# ---------- graceful drain (SIGTERM/SIGINT) ----------


def test_sigterm_drain_then_resume_byte_identical(corpus, tmp_path,
                                                  monkeypatch):
    """SIGTERM at the first retirement (small pinned window, inline
    prep => admission genuinely stops early): rc 75, journal
    consistent and PARTIAL, resume completes byte-identical."""
    fa, ref = corpus
    out, jp = tmp_path / "o.fa", tmp_path / "j.json"
    monkeypatch.setenv("CCSX_JOURNAL_FSYNC_S", "0")
    args = ["-A", "-m", "1000", "--batch", "on", "--inflight", "2",
            "--prep-threads", "0", "--journal", str(jp), str(fa),
            str(out)]
    faultinject.arm("sigterm@1")
    rc = cli.main(args)
    faultinject.disarm()
    assert rc == exitcodes.RC_INTERRUPTED == 75
    j = json.loads(jp.read_text())
    assert 0 < j["holes_done"] < 6, "drain should leave work behind"
    rc = cli.main(args)
    assert rc == 0
    assert out.read_bytes() == ref


def test_sigterm_drain_per_hole_driver(corpus, tmp_path, monkeypatch):
    """The same contract on the per-hole (--batch off) driver."""
    fa, ref = corpus
    out, jp = tmp_path / "o.fa", tmp_path / "j.json"
    monkeypatch.setenv("CCSX_JOURNAL_FSYNC_S", "0")
    args = ["-A", "-m", "1000", "--batch", "off", "--journal", str(jp),
            str(fa), str(out)]
    faultinject.arm("sigterm@2")
    rc = cli.main(args)
    faultinject.disarm()
    assert rc == exitcodes.RC_INTERRUPTED
    assert 0 < json.loads(jp.read_text())["holes_done"] < 6
    rc = cli.main(args)
    assert rc == 0
    assert out.read_bytes() == ref


def test_drain_guard_sigint_and_restore():
    """SIGINT sets the flag without raising KeyboardInterrupt, and
    restore() reinstates the previous handlers."""
    before = signal.getsignal(signal.SIGINT)
    g = DrainGuard.install()
    try:
        signal.raise_signal(signal.SIGINT)   # handler, no KeyboardInterrupt
        assert g.requested
    finally:
        g.restore()
    assert signal.getsignal(signal.SIGINT) is before


def test_drain_guard_second_signal_restores():
    """A second signal during the drain hands control back to the
    previous handlers (the operator's escape hatch)."""
    before = signal.getsignal(signal.SIGTERM)
    g = DrainGuard.install()
    try:
        signal.raise_signal(signal.SIGTERM)
        assert g.requested
        signal.raise_signal(signal.SIGTERM)  # restores previous handlers
        assert signal.getsignal(signal.SIGTERM) is before
    finally:
        g.restore()


def test_drain_guard_noop_off_main_thread():
    """install() off the main thread degrades to an inert guard (signal
    handlers are main-thread-only) instead of raising."""
    import threading

    res = {}

    def t():
        res["g"] = DrainGuard.install()

    th = threading.Thread(target=t)
    th.start()
    th.join()
    assert res["g"].requested is False
    res["g"].restore()   # no-op, must not raise


# ---------- disk-full hardening ----------


@pytest.mark.slow  # ~5s: chaos fast slice keeps a disk_full_resume trial (r11 audit)
def test_enospc_clean_rc1_then_resume(corpus, tmp_path, monkeypatch,
                                      capsys):
    """Injected ENOSPC at the writer: clean rc 1 (no traceback), the
    journal never claims the unwritten record, and the resume
    completes byte-identical."""
    fa, ref = corpus
    out, jp = tmp_path / "o.fa", tmp_path / "j.json"
    monkeypatch.setenv("CCSX_JOURNAL_FSYNC_S", "0")
    args = ["-A", "-m", "1000", "--batch", "on", "--journal", str(jp),
            str(fa), str(out)]
    faultinject.arm("disk_full@3")
    rc = cli.main(args)
    faultinject.disarm()
    err = capsys.readouterr().err
    assert rc == exitcodes.RC_FATAL
    assert "No space left on device" in err
    assert "Traceback" not in err
    j = json.loads(jp.read_text())
    assert j["holes_done"] < 6
    # the journaled offset points at durable bytes only
    assert j["out_bytes"] <= out.stat().st_size
    rc = cli.main(args)
    assert rc == 0
    assert out.read_bytes() == ref


def test_enospc_in_journal_settle_warns_not_raises(tmp_path, capsys):
    """A failed final journal settle (disk still full in the drivers'
    finally) must warn, not traceback — the on-disk cursor merely lags
    the durable output."""
    from ccsx_tpu.utils.journal import Journal

    jp = tmp_path / "j.json"
    j = Journal(path=str(jp), input_id="x", fsync_interval_s=3600.0)
    j.advance()          # first advance writes (cold rate limiter)
    j.advance()          # second is rate-limited: pending in memory
    assert j._pending

    def boom():
        raise OSError(28, "No space left on device")

    j._write_disk = boom
    j.close()            # must not raise
    assert "final settle failed" in capsys.readouterr().err
