"""`ccsx-tpu shepherd`: a rank supervisor for sharded runs.

Until now a dead rank in a sharded run was merely *visible*: the rank
never wrote its completion marker, ``merge_shards`` refused the merge,
and the operator was told to re-run the dead rank by hand
(parallel/distributed.py).  The ROADMAP north star is production-scale
serving, where "a human re-runs rank 3 at 2am" is not a failure story.
The shepherd turns that manual instruction into a supervised loop:

* **Launch** — the N ranks run as subprocesses of one supervisor
  process (`python -c` runners invoking the ordinary CLI with
  ``--hosts N --host-id r``), each with a per-rank log file
  (``<out>.shard<r>.log``) and — unless the caller provided one — a
  shepherd-owned journal (``<out>.shepherd.journal``; the sharded
  driver suffixes ``.shard<r>``), because the journal is what makes a
  restart a RESUME instead of a recompute.

* **Monitor** — liveness is the rank's *progress heartbeat*: the
  newest mtime across its shard journal, shard output, and ordinal
  sidecar (the journal is fsynced at least once a second while holes
  retire).  With ``--telemetry-port`` the per-rank ``/healthz``
  endpoints (base port + rank, parallel/distributed.py) are polled too
  — a 503/degraded rank is reported in the shepherd log; an
  *unreachable* endpoint is only informational (the process poll is
  the authority on death).  A rank whose heartbeat goes stale past
  ``--rank-stall-timeout`` (0 = disabled; size it above your worst
  cold-compile time, or serve telemetry and rely on the rank's own
  ``--dispatch-deadline`` instead) is SIGKILLed and treated as dead.

* **Restart** — a dead rank (nonzero exit, or killed as stalled) is
  relaunched with exponential backoff (``--rank-backoff`` x 2^attempt)
  up to ``--max-rank-restarts`` times; it resumes from its shard
  journal, so already-durable records are never recomputed.
  ``CCSX_FAULTS`` is stripped from restart environments — injected
  faults model the FIRST failure, and a restarted rank must run clean
  (the chaos harness depends on this).  A rank that exhausts its
  restarts fails the whole run (rc 1) — the remaining ranks are still
  driven to completion so their journals are warm for a later retry.

* **Merge** — when every rank has exited 0 (completion markers in
  place), the shepherd runs the ordinary ``merge_shards`` and exits 0.
  Output is byte-identical to an unsharded run by the existing merge
  invariants, restarts included (pinned by tests/test_supervisor.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ccsx_tpu import exitcodes

# the subprocess runner body; a PRELUDE (backend pinning for tests /
# CPU-forced environments) may be prepended
_RUNNER = ("import sys; from ccsx_tpu.cli import main; "
           "sys.exit(main(sys.argv[1:]))")

# shepherd-only flags stripped from the forwarded rank command line
_SHEPHERD_FLAGS = ("--max-rank-restarts", "--rank-backoff",
                   "--rank-stall-timeout")


def default_prelude() -> str:
    """Backend pinning for the rank runners: when this process is
    itself forced onto CPU (JAX_PLATFORMS=cpu — the test suite, `make
    chaos`, CI), the ranks must be too; some accelerator plugins
    override the env var at import time, so the pin must be an explicit
    jax.config call before the CLI imports (the same idiom as
    tests/test_faults._run_cli_subprocess)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return ("import jax; "
                "jax.config.update('jax_platforms', 'cpu'); ")
    return ""


def strip_shepherd_flags(argv: List[str],
                         flags=_SHEPHERD_FLAGS) -> List[str]:
    """Remove shepherd-only options (+ their values) from an argv so
    the remainder forwards verbatim to the rank command lines."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in flags:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in flags):
            continue
        out.append(a)
    return out


@dataclasses.dataclass
class _Rank:
    rank: int
    proc: Optional[subprocess.Popen] = None
    log: Optional[object] = None
    attempts: int = 0          # restarts used (0 = first launch)
    beat: float = 0.0          # monotonic time of last progress sign
    last_mtime: Optional[float] = None  # newest observed shard mtime
    relaunch_at: Optional[float] = None
    done: bool = False
    failed: Optional[str] = None
    failed_rc: Optional[int] = None
    last_health: Optional[str] = None


def _beat_paths(out_path: str, journal: str, rank: int) -> List[str]:
    return [f"{journal}.shard{rank}",
            f"{out_path}.shard{rank}",
            f"{out_path}.shard{rank}.idx"]


def _latest_mtime(paths: List[str]) -> Optional[float]:
    best = None
    for p in paths:
        try:
            m = os.stat(p).st_mtime
        except OSError:
            continue
        best = m if best is None or m > best else best
    return best


def _poll_healthz(port: int, timeout: float = 0.5) -> Optional[str]:
    """'ok' | 'degraded' | None (unreachable).  Best effort only — the
    endpoint auto-bumps when its port is taken, so unreachable is
    informational, never a death verdict."""
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{port}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode()).get("status", "ok")
    except urllib.error.HTTPError as e:  # 503 carries the body
        try:
            return json.loads(e.read().decode()).get("status",
                                                     "degraded")
        except (ValueError, OSError):
            return "degraded"
    except (OSError, ValueError):
        return None


def shepherd_run(in_path: str, out_path: str, hosts: int,
                 forward_args: List[str],
                 journal: Optional[str] = None,
                 max_restarts: int = 2,
                 backoff_s: float = 1.0,
                 rank_stall_timeout: float = 0.0,
                 telemetry_port: int = 0,
                 env: Optional[dict] = None,
                 first_launch_env: Optional[Dict[int, dict]] = None,
                 poll_s: float = 0.25,
                 merge: bool = True,
                 runner_prelude: Optional[str] = None) -> int:
    """Supervise a sharded run end to end; returns a process rc
    (exitcodes.py: 0 = merged, 1 = a rank exhausted its restarts or
    the merge was refused).

    ``forward_args`` is the full rank CLI argv (flags + INPUT OUTPUT,
    including ``--hosts``) WITHOUT ``--host-id`` — the shepherd
    appends it per rank.  ``first_launch_env`` maps rank -> extra env
    for attempt 0 only (the fault-injection hook: restarts run clean).
    """
    from ccsx_tpu.parallel.distributed import merge_shards

    if hosts < 1:
        print("Error: shepherd needs --hosts >= 1", file=sys.stderr)
        return exitcodes.RC_FATAL
    base_env = dict(os.environ if env is None else env)
    prelude = (default_prelude() if runner_prelude is None
               else runner_prelude)
    first_launch_env = first_launch_env or {}
    # a journal is what makes a restart a resume; inject one when the
    # caller didn't ask for their own
    fwd = list(forward_args)
    if journal is None and "--journal" not in fwd:
        journal = f"{out_path}.shepherd.journal"
        fwd += ["--journal", journal]
    elif journal is None:
        journal = fwd[fwd.index("--journal") + 1]

    def launch(st: _Rank) -> None:
        e = dict(base_env)
        rank_fwd = fwd
        if st.attempts == 0:
            e.update(first_launch_env.get(st.rank, {}))
        else:
            # restarts run clean: injected faults model the FIRST
            # failure (a re-armed rank_death would die forever) — both
            # the env form AND the forwarded CLI flag
            e.pop("CCSX_FAULTS", None)
            rank_fwd = strip_shepherd_flags(fwd,
                                            flags=("--inject-faults",))
        cmd = [sys.executable, "-c", prelude + _RUNNER, *rank_fwd,
               "--host-id", str(st.rank)]
        log_path = f"{out_path}.shard{st.rank}.log"
        try:
            st.log = open(log_path, "a", encoding="utf-8")
            st.log.write(f"\n=== shepherd launch rank {st.rank} attempt "
                         f"{st.attempts} @ {time.strftime('%H:%M:%S')} "
                         f"===\n")
            st.log.flush()
            sink = st.log
        except OSError as e_log:
            # an unwritable log (e.g. the output dir itself is the
            # problem) must not crash the supervisor — the rank will
            # fail with the real error on its own
            print(f"[ccsx-tpu] shepherd: cannot open {log_path} "
                  f"({e_log}); rank {st.rank} output discarded",
                  file=sys.stderr)
            st.log = None
            sink = subprocess.DEVNULL
        st.proc = subprocess.Popen(cmd, env=e, stdout=sink,
                                   stderr=subprocess.STDOUT)
        st.beat = time.monotonic()
        st.relaunch_at = None
        print(f"[ccsx-tpu] shepherd: rank {st.rank} up (pid "
              f"{st.proc.pid}, attempt {st.attempts}, log {log_path})",
              file=sys.stderr)

    def close_log(st: _Rank) -> None:
        if st.log is not None:
            try:
                st.log.close()
            except OSError:
                pass
            st.log = None

    def schedule_restart(st: _Rank, reason: str) -> None:
        close_log(st)
        st.proc = None
        if st.attempts >= max_restarts:
            st.failed = (f"rank {st.rank} {reason} and exhausted its "
                         f"{max_restarts} restart(s)")
            st.done = True
            print(f"[ccsx-tpu] shepherd: {st.failed}", file=sys.stderr)
            return
        st.attempts += 1
        delay = backoff_s * (2 ** (st.attempts - 1))
        st.relaunch_at = time.monotonic() + delay
        print(f"[ccsx-tpu] shepherd: rank {st.rank} {reason}; "
              f"restarting in {delay:g}s (attempt {st.attempts}/"
              f"{max_restarts}; resumes from its shard journal)",
              file=sys.stderr)

    ranks = [_Rank(rank=r) for r in range(hosts)]
    for st in ranks:
        launch(st)
    last_health_poll = 0.0
    try:
        while not all(st.done for st in ranks):
            now = time.monotonic()
            poll_health = (telemetry_port
                           and now - last_health_poll >= 2.0)
            if poll_health:
                last_health_poll = now
            for st in ranks:
                if st.done:
                    continue
                if st.proc is None:
                    if st.relaunch_at is not None and now >= st.relaunch_at:
                        launch(st)
                    continue
                rc = st.proc.poll()
                if rc is not None:
                    if rc == 0:
                        st.done = True
                        close_log(st)
                        print(f"[ccsx-tpu] shepherd: rank {st.rank} "
                              "completed", file=sys.stderr)
                    elif rc == exitcodes.RC_FAILED_HOLES:
                        # a failed-hole budget abort is DETERMINISTIC:
                        # the journal carries the failure count across
                        # resumes, so a restart would re-abort — fail
                        # the rank immediately instead of burning the
                        # restart budget on it
                        close_log(st)
                        st.proc = None
                        st.failed = (f"rank {st.rank} exceeded its "
                                     "--max-failed-holes budget (rc "
                                     f"{rc}); not restartable")
                        st.failed_rc = rc
                        st.done = True
                        print(f"[ccsx-tpu] shepherd: {st.failed}",
                              file=sys.stderr)
                    else:
                        schedule_restart(st, f"died (rc {rc})")
                    continue
                # progress heartbeat: journal/shard mtimes (fsynced at
                # least once a second while holes retire).  A CHANGED
                # mtime stamps the beat on OUR monotonic clock —
                # comparing wall-clock mtimes against monotonic time
                # would let an NTP step mark every healthy rank stale
                m = _latest_mtime(_beat_paths(out_path, journal,
                                              st.rank))
                if m is not None and m != st.last_mtime:
                    st.last_mtime = m
                    st.beat = now
                if poll_health:
                    h = _poll_healthz(telemetry_port + st.rank)
                    if h != st.last_health and h is not None:
                        st.last_health = h
                        if h != "ok":
                            print(f"[ccsx-tpu] shepherd: rank "
                                  f"{st.rank} /healthz reports {h}",
                                  file=sys.stderr)
                if (rank_stall_timeout > 0
                        and now - st.beat > rank_stall_timeout):
                    print(f"[ccsx-tpu] shepherd: rank {st.rank} "
                          f"heartbeat stale for >{rank_stall_timeout:g}s"
                          " — killing the wedged rank", file=sys.stderr)
                    try:
                        st.proc.send_signal(signal.SIGKILL)
                        st.proc.wait(timeout=10.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    schedule_restart(st, "stalled")
            time.sleep(poll_s)
    finally:
        for st in ranks:
            if st.proc is not None and st.proc.poll() is None:
                st.proc.kill()
                try:
                    st.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            close_log(st)
    failed = [st for st in ranks if st.failed]
    if failed:
        print("Error: shepherd run failed: "
              + "; ".join(st.failed for st in failed)
              + " — surviving ranks completed and their journals are "
              "intact; fix the cause and re-run the shepherd to resume",
              file=sys.stderr)
        # preserve the exit-code taxonomy through supervision: when
        # every failure is the deterministic failed-hole budget abort,
        # the shepherd reports rc 2 like an unsharded run would; any
        # other failure class stays the generic rc 1
        rcs = {st.failed_rc for st in failed}
        if rcs == {exitcodes.RC_FAILED_HOLES}:
            return exitcodes.RC_FAILED_HOLES
        return exitcodes.RC_FATAL
    if not merge:
        return exitcodes.RC_OK
    try:
        n = merge_shards(out_path, hosts)
    except (OSError, ValueError) as e:
        print(f"Error: shepherd merge refused: {e}", file=sys.stderr)
        return exitcodes.RC_FATAL
    print(f"[ccsx-tpu] shepherd: merged {n} records from {hosts} "
          "ranks", file=sys.stderr)
    return exitcodes.RC_OK


def shepherd_main(argv) -> int:
    """The `ccsx-tpu shepherd` subcommand (dispatched from cli.main):
    the ordinary CLI grammar plus the supervisor knobs; everything
    except the shepherd-only flags forwards verbatim to the ranks."""
    from ccsx_tpu import cli as cli_mod

    p = cli_mod.build_parser()
    p.prog = "ccsx-tpu shepherd"
    p.add_argument("--max-rank-restarts", type=int, default=2,
                   dest="max_rank_restarts", metavar="N",
                   help="restarts allowed per rank before the run "
                        "fails [2]")
    p.add_argument("--rank-backoff", type=float, default=1.0,
                   dest="rank_backoff", metavar="SEC",
                   help="restart backoff base (doubles per attempt) "
                        "[1.0]")
    p.add_argument("--rank-stall-timeout", type=float, default=0.0,
                   dest="rank_stall_timeout", metavar="SEC",
                   help="SIGKILL + restart a rank whose progress "
                        "heartbeat (shard journal/output mtimes) goes "
                        "stale this long; 0 disables — size it above "
                        "your worst cold compile, or prefer the "
                        "rank-level --dispatch-deadline [0]")
    args = p.parse_args(argv)
    if args.help:
        return cli_mod.usage()
    if args.hosts is None or args.hosts < 1:
        print("Error: shepherd requires --hosts N (>= 1)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.host_id is not None:
        print("Error: shepherd owns --host-id; do not pass it",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.merge_shards is not None or args.make_index:
        print("Error: shepherd cannot combine with --merge-shards/"
              "--make-index", file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.bam_out:
        print("Error: --bam is not supported with --hosts "
              "(use --fastq and convert the merged output)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.batch == "off":
        # refused up front: each rank would refuse it anyway, and the
        # shepherd would burn its restart budget on a config error
        print("Error: --batch off is not supported with --hosts",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.input == "-" or args.output == "-":
        print("Error: shepherd needs real INPUT/OUTPUT paths (ranks "
              "re-read the input; shards merge into the output)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    # validate the shared config once up front (same errors the ranks
    # would produce N times over)
    try:
        cli_mod.config_from_args(args)
    except SystemExit as e:
        return int(e.code or 0)
    forward = strip_shepherd_flags(list(argv))
    return shepherd_run(
        args.input, args.output, args.hosts, forward,
        journal=args.journal,
        max_restarts=args.max_rank_restarts,
        backoff_s=args.rank_backoff,
        rank_stall_timeout=args.rank_stall_timeout,
        telemetry_port=args.telemetry_port or 0)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(shepherd_main(sys.argv[1:]))
