"""BGZF hole index: byte-range sharded multi-host BAM ingest.

The round-robin multi-host design (parallel/distributed.py) has every
host decode the FULL input and keep 1/N of the holes — zero
coordination, but N x redundant parsing (SURVEY §5.8 wants "each host
reads its own input shard").  This module removes the redundancy for
BGZF BAM inputs using the container's block structure (the same
structure the native reader's parallel inflate exploits,
io_native.cpp):

* ``build_index`` — ONE sequential indexing pass (run once per input,
  ``ccsx --make-index``) records the BGZF virtual offset
  (compressed block offset, offset within the inflated block) of every
  K-th hole boundary plus the total raw hole count, into a JSON
  sidecar ``<in>.bam.ccsx_idx`` fingerprinted by file size+mtime.
* sharded runs split the RAW hole ordinal space contiguously —
  rank r owns [r*H/N, (r+1)*H/N) — and each rank seeks to the nearest
  indexed boundary at or before its range, inflates only its ~1/N of
  the compressed bytes (plus at most K holes of lead-in), and streams
  records through the SAME filters as a single-host run.
* output ordering: contiguous ranges make ``start_ordinal +
  local_filtered_idx`` a globally monotone merge key (a range's
  filtered hole count never exceeds its raw count, so keys never reach
  the next rank's start), so ``merge_shards`` reproduces the
  single-host byte-identical output with no new merge machinery.

Reference mapping: the reference is single-host and reads sequentially
(bamlite.h:13-19, no random access); this is the distributed-ingest
capability SURVEY §5.8 adds on top.  Virtual offsets follow the BGZF
convention (coffset<<16 | uoffset) so the sidecar is interoperable
with htslib-style tooling expectations.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

from ccsx_tpu.io.bam import (BamError, check_record_length,
                             read_bam_header)
from ccsx_tpu.io.fastx import FastxRecord

INDEX_SUFFIX = ".ccsx_idx"
INDEX_VERSION = 1


class BgzfBlockReader:
    """Sequential reader over BGZF blocks that tracks virtual offsets.

    ``read(n)`` returns inflated bytes; ``voffset()`` reports the
    (coffset, uoffset) of the NEXT unread byte — exactly what the index
    stores for a record boundary.  Raises BamError on a non-BGZF
    member (sharding requires real BGZF; the plain-gzip fallback path
    keeps using the sequential reader)."""

    def __init__(self, f, coffset: int = 0):
        self._f = f
        f.seek(coffset)
        # spans: (start_pos_in_buf_stream, coffset, ulen) per loaded block
        self._buf = bytearray()
        self._pos = 0            # read cursor within _buf
        self._spans: List[Tuple[int, int, int]] = []
        self._consumed = 0       # bytes compacted away from _buf's front
        self.compressed_bytes = 0   # total compressed bytes inflated

    def _load_block(self) -> bool:
        coffset = self._f.tell()
        head = self._f.read(18)
        if len(head) == 0:
            return False
        if len(head) < 18 or head[:4] != b"\x1f\x8b\x08\x04":
            raise BamError("not a BGZF block (sharded ingest requires "
                           "a real BGZF container)", "bgzf_bad_block")
        (xlen,) = struct.unpack_from("<H", head, 10)
        extra = head[12:18]
        # walk the extra subfields for BC (usually first)
        bsize = None
        off = 0
        extra += self._f.read(max(0, xlen - 6))
        while off + 4 <= len(extra):
            si1, si2, slen = extra[off], extra[off + 1], struct.unpack_from(
                "<H", extra, off + 2)[0]
            if si1 == 0x42 and si2 == 0x43 and slen == 2:
                (bsize,) = struct.unpack_from("<H", extra, off + 4)
                break
            off += 4 + slen
        if bsize is None:
            raise BamError("BGZF block missing BC subfield",
                           "bgzf_bad_block")
        payload_len = bsize + 1 - 12 - xlen - 8
        if payload_len < 0:
            raise BamError(f"BGZF block BSIZE {bsize} smaller than its "
                           "own header", "bgzf_bad_block")
        comp = self._f.read(payload_len)
        tail = self._f.read(8)
        if len(comp) < payload_len or len(tail) < 8:
            raise BamError("truncated BGZF block", "bgzf_torn_tail")
        try:
            data = zlib.decompress(comp, -15)
        except zlib.error as e:
            raise BamError(f"BGZF block inflate failed: {e}",
                           "bgzf_bad_deflate") from None
        crc, isize = struct.unpack("<II", tail)
        if isize != len(data) & 0xFFFFFFFF or zlib.crc32(data) != crc:
            raise BamError("BGZF block CRC/ISIZE mismatch",
                           "bgzf_bad_deflate")
        self.compressed_bytes += bsize + 1
        if data:
            self._spans.append(
                (self._consumed + len(self._buf), coffset, len(data)))
            self._buf += data
        return True

    def read(self, n: int) -> bytes:
        while len(self._buf) - self._pos < n:
            if not self._load_block():
                break
        take = self._buf[self._pos:self._pos + n]
        self._pos += len(take)
        self._compact()
        return bytes(take)

    def skip(self, n: int) -> None:
        self.read(n)

    def _compact(self) -> None:
        # drop fully-consumed leading blocks so memory stays ~2 blocks
        while len(self._spans) > 1 and (
                self._spans[1][0] - self._consumed) <= self._pos:
            start = self._spans[1][0] - self._consumed
            del self._buf[:start]
            self._pos -= start
            self._consumed += start
            self._spans.pop(0)

    def voffset(self) -> Tuple[int, int]:
        """(coffset, uoffset) of the next unread byte."""
        if not self._spans:
            if self._load_block():
                return self.voffset()
            return self._f.tell(), 0   # empty/at-EOF stream
        abs_pos = self._consumed + self._pos
        cur = None
        for start, coffset, ulen in self._spans:
            if start <= abs_pos < start + ulen:
                return coffset, abs_pos - start
            if start + ulen == abs_pos:
                cur = (coffset, ulen)
        if cur is not None:
            # cursor sits exactly at a block end: the next byte is the
            # start of the next (not yet loaded) block
            if self._load_block():
                return self.voffset()
            return cur  # EOF: report end-of-last-block
        raise BamError("virtual offset outside loaded spans")


def _hole_key(name: str) -> Tuple[str, str]:
    """(movie, hole) from a subread name movie/hole/qs_qe — the same
    grouping key the ZMW streamer uses (io/zmw.py)."""
    parts = name.split("/")
    return (parts[0], parts[1]) if len(parts) >= 2 else (name, "")


def _records_with_boundaries(r: BgzfBlockReader,
                             max_record_bytes: int = 0):
    """Yield (voffset_before_record, name) for each alignment record.

    Only the name is decoded — the indexing pass does not touch seq or
    qual bytes, so it runs at near-inflate speed."""
    while True:
        voff = r.voffset()
        head = r.read(4)
        if len(head) == 0:
            return
        if len(head) < 4:
            raise BamError("truncated BAM: partial block size")
        (block_size,) = struct.unpack("<i", head)
        # allocation bound, shared classify-split (io/bam.py)
        check_record_length(block_size, max_record_bytes)
        block = r.read(block_size)
        if len(block) < block_size:
            raise BamError("truncated BAM: short alignment block")
        l_read_name = block[8]
        name = block[32:32 + l_read_name - 1].decode(errors="replace")
        yield voff, name


def build_index(path: str, every: int = 64,
                max_record_bytes: int = 0) -> dict:
    """Index a BGZF BAM's hole boundaries; writes ``<path>.ccsx_idx``.

    Entries: [raw_hole_ordinal, coffset, uoffset] for every ``every``-th
    hole boundary (ordinal 0 always present).  Returns the index dict."""
    st = os.stat(path)
    with open(path, "rb") as f:
        r = BgzfBlockReader(f)
        read_bam_header(r)
        entries = []
        n_holes = 0
        n_records = 0
        prev_key = None
        for voff, name in _records_with_boundaries(r, max_record_bytes):
            key = _hole_key(name)
            if key != prev_key:
                if n_holes % every == 0:
                    entries.append([n_holes, voff[0], voff[1]])
                n_holes += 1
                prev_key = key
            n_records += 1
    idx = {
        "version": INDEX_VERSION,
        "every": every,
        "size": st.st_size,
        "mtime_ns": st.st_mtime_ns,
        "n_holes": n_holes,
        "n_records": n_records,
        "entries": entries,
    }
    with open(path + INDEX_SUFFIX, "w") as f:
        json.dump(idx, f)
    return idx


def load_index(path: str) -> Optional[dict]:
    """The sidecar index, or None when absent/stale/unreadable."""
    try:
        with open(path + INDEX_SUFFIX) as f:
            idx = json.load(f)
        st = os.stat(path)
        if (idx.get("version") != INDEX_VERSION
                or idx.get("size") != st.st_size
                or idx.get("mtime_ns") != st.st_mtime_ns):
            return None
        return idx
    except (OSError, ValueError):
        return None


def hole_range(n_holes: int, rank: int, n: int) -> Tuple[int, int]:
    """Contiguous raw-hole range [lo, hi) owned by ``rank`` of ``n``."""
    return (rank * n_holes) // n, ((rank + 1) * n_holes) // n


def split_ranges(n_holes: int, m: int) -> List[Tuple[int, int]]:
    """The raw-hole ordinal space as M contiguous ranges — the fleet
    scheduler's work-unit table (pipeline/fleet.py).  Same arithmetic
    as hole_range, so a fleet run with M == N degenerates to exactly
    the static shard split; empty ranges (m > n_holes) are kept so the
    table always has m rows and range i's identity never depends on the
    corpus size."""
    return [hole_range(n_holes, i, m) for i in range(max(1, m))]


def read_hole_range(path: str, idx: dict, lo: int, hi: int,
                    counter=None,
                    max_record_bytes: int = 0) -> Iterator[FastxRecord]:
    """Stream the records of raw holes [lo, hi) as FastxRecords.

    Seeks to the nearest indexed boundary <= lo (at most ``every``-1
    holes of lead-in are parsed and dropped), decodes records through
    the end of hole hi-1, and stops — inflating only this range's
    compressed bytes.  ``counter`` (optional callable) receives the
    total compressed bytes inflated, for metrics.ingest_bytes."""
    if lo >= hi:
        if counter is not None:
            counter(0)
        return
    # nearest indexed entry at or before lo
    base_ord, coffset, uoffset = 0, None, None
    for e_ord, e_coff, e_uoff in idx["entries"]:
        if e_ord <= lo:
            base_ord, coffset, uoffset = e_ord, e_coff, e_uoff
        else:
            break
    with open(path, "rb") as f:
        if coffset is None:
            # defensive: no entry (empty file) — parse from the top
            r = BgzfBlockReader(f)
            read_bam_header(r)
            base_ord = 0
        else:
            r = BgzfBlockReader(f, coffset)
            r.skip(uoffset)
        holes_seen = base_ord - 1   # ordinal of prev_key's hole
        prev_key = None
        try:
            yield from _range_records(r, lo, hi, holes_seen, prev_key,
                                      max_record_bytes)
        finally:
            # fires even when the consumer abandons the generator, so
            # metrics.ingest_bytes is counted for partial consumption
            if counter is not None:
                counter(r.compressed_bytes)


def _range_records(r, lo, hi, holes_seen, prev_key,
                   max_record_bytes: int = 0):
    from ccsx_tpu.io.bam import decode_record

    while True:
        head = r.read(4)
        if len(head) == 0:
            return
        if len(head) < 4:
            raise BamError("truncated BAM: partial block size")
        (block_size,) = struct.unpack("<i", head)
        check_record_length(block_size, max_record_bytes)
        block = r.read(block_size)
        if len(block) < block_size:
            raise BamError("truncated BAM: short alignment block")
        l_read_name = block[8]
        name = block[32:32 + l_read_name - 1].decode(errors="replace")
        key = _hole_key(name)
        if key != prev_key:
            holes_seen += 1
            prev_key = key
            if holes_seen >= hi:
                return
        if holes_seen < lo:
            continue   # lead-in hole: name-only parse, no seq decode
        # full decode shared with the sequential reader (bam.py) so the
        # range-sharded stream can never diverge from it
        yield decode_record(block)[0]
