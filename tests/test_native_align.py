"""Differential test: native C++ scalar aligner vs the NumPy oracle.

Exact equality required — same DP, same tie-breaking, same traceback —
so either implementation can serve as the spec for the device kernels.
"""

import numpy as np
import pytest

from ccsx_tpu import native
from ccsx_tpu.ops import oracle
from ccsx_tpu.utils import synth

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def _check(q, t, mode, **scores):
    from ccsx_tpu.native.align import align_scalar_native
    want = oracle.align(q, t, mode=mode, **scores)
    got = align_scalar_native(q, t, mode=mode, **scores)
    assert got is not None
    assert got.score == want.score
    assert (got.qb, got.qe, got.tb, got.te) == (
        want.qb, want.qe, want.tb, want.te), mode
    assert (got.aln, got.mat, got.mis, got.ins, got.del_) == (
        want.aln, want.mat, want.mis, want.ins, want.del_)
    assert got.cigar == want.cigar


@pytest.mark.parametrize("mode", ["global", "qfree", "local"])
def test_random_pairs(mode, rng):
    for trial in range(8):
        tlen = int(rng.integers(5, 120))
        t = rng.integers(0, 4, tlen).astype(np.uint8)
        q = synth.mutate(rng, t, 0.05, 0.08, 0.08)
        _check(q, t, mode)


@pytest.mark.parametrize("mode", ["global", "qfree", "local"])
def test_unrelated_and_edge(mode, rng):
    q = rng.integers(0, 4, 40).astype(np.uint8)
    t = rng.integers(0, 4, 55).astype(np.uint8)
    _check(q, t, mode)
    _check(np.array([0], np.uint8), np.array([3], np.uint8), mode)
    # N bases never match
    _check(np.full(10, 4, np.uint8), np.full(10, 4, np.uint8), mode)


def test_clipping_qfree(rng):
    t = rng.integers(0, 4, 60).astype(np.uint8)
    junk = rng.integers(0, 4, 25).astype(np.uint8)
    q = np.concatenate([junk, synth.mutate(rng, t, 0.02, 0.02, 0.02), junk])
    _check(q, t, "qfree")


def test_alt_scores(rng):
    t = rng.integers(0, 4, 80).astype(np.uint8)
    q = synth.mutate(rng, t, 0.1, 0.05, 0.05)
    _check(q, t, "global", match=1, mismatch=-4, gap_open=-6, gap_extend=-1)


def test_size_cap_returns_none():
    from ccsx_tpu.native.align import align_scalar_native
    q = np.zeros(1 << 14, np.uint8)
    t = np.zeros(1 << 13, np.uint8)
    assert align_scalar_native(q, t) is None


def test_banded_fill_vec_equals_scalar(rng):
    """The two builds of native/baseline_simd.cpp (vectorized vs
    -fno-tree-vectorize, identical source) must agree bit-for-bit on the
    final band row — the precondition for reading their speed ratio as
    a SIMD factor (bench_baseline.json, VERDICT r4 item 4)."""
    import ctypes

    from ccsx_tpu import native

    L = native.lib()
    if L is None:
        import pytest

        pytest.skip("native library unavailable")

    def run(fn, q, t):
        h = np.zeros(128, np.int16)
        rc = fn(q.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(q),
                t.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(t),
                2, -6, -3, -2,
                h.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)))
        assert rc == 0
        return h

    for _ in range(8):
        ql = int(rng.integers(50, 2500))
        tl = int(rng.integers(50, 2500))
        q = rng.integers(0, 4, ql).astype(np.uint8)
        t = rng.integers(0, 4, tl).astype(np.uint8)
        hv = run(L.ccsx_banded_fill_vec, q, t)
        hs = run(L.ccsx_banded_fill_scalar, q, t)
        np.testing.assert_array_equal(hv, hs)
    # identity alignment: the band covers the main diagonal end-to-end,
    # so the best final-row cell is the perfect-match global score
    q = rng.integers(0, 4, 1000).astype(np.uint8)
    assert run(L.ccsx_banded_fill_vec, q, q).max() == 2 * 1000
