"""Minimal BAM reader over a plain gzip stream (Python fallback path).

Replicates the semantics of the reference's bamlite (bamlite.c:78-165):
BAM-through-gzip — BGZF files are valid multi-member gzip streams, so
sequential reading works without BGZF block handling (bamlite.h:13-19 makes
the same choice; no random access).  Per record we decode the read name,
the 4-bit packed sequence via the =ACMGRSVTWYHKDBN table (seqio.h:92,
bamlite.h:86) and qualities as phred+33 clamped at 126 (seqio.h:113).

Truncated-stream handling mirrors bamlite: a clean EOF at a record boundary
ends the stream; a partial record raises.
"""

from __future__ import annotations

import gzip
import io
import os
import struct
from typing import Iterator

import numpy as np

from ccsx_tpu.io.fastx import FastxRecord

SEQ_NT16 = b"=ACMGRSVTWYHKDBN"

# 2x256 lookup: byte -> two ASCII bases (high nibble first, bamlite.h:86)
_NIB = np.empty((256, 2), dtype=np.uint8)
for _b in range(256):
    _NIB[_b, 0] = SEQ_NT16[_b >> 4]
    _NIB[_b, 1] = SEQ_NT16[_b & 0xF]


class BamError(ValueError):
    pass


def _read_exact(f, n: int, what: str) -> bytes:
    buf = f.read(n)
    if len(buf) != n:
        raise BamError(f"truncated BAM: short read in {what}")
    return buf


def read_bam_header(f) -> dict:
    magic = _read_exact(f, 4, "magic")
    if magic != b"BAM\x01":
        raise BamError("invalid BAM header")  # bamlite.c:84
    (l_text,) = struct.unpack("<i", _read_exact(f, 4, "l_text"))
    text = _read_exact(f, l_text, "text").rstrip(b"\x00").decode(
        errors="replace")
    (n_ref,) = struct.unpack("<i", _read_exact(f, 4, "n_ref"))
    refs = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack("<i", _read_exact(f, 4, "ref name len"))
        name = _read_exact(f, l_name, "ref name")[:-1].decode(errors="replace")
        (l_ref,) = struct.unpack("<i", _read_exact(f, 4, "ref len"))
        refs.append((name, l_ref))
    return {"text": text, "refs": refs}


def read_bam_records(path_or_file, with_aux: bool = False):
    """Stream BAM alignment records as FastxRecords (name/seq/qual).

    With ``with_aux``, yields (FastxRecord, aux_dict) pairs instead,
    where aux_dict is parse_aux of the record's tag region
    (bamlite.c:215-290 equivalent; ccsx's hot path never reads tags)."""
    bgzf_path = None
    if hasattr(path_or_file, "read"):
        raw = path_or_file
    else:
        raw = open(path_or_file, "rb")
        bgzf_path = path_or_file
    # transparent gzip/BGZF
    if not hasattr(raw, "peek"):
        raw = io.BufferedReader(raw)
    if raw.peek(2)[:2] == b"\x1f\x8b":
        head = raw.peek(14)
        # BGZF = FEXTRA set (byte 3 bit 2) AND a leading BC subfield; a
        # plain-gzip member whose stored FNAME happens to contain "BC"
        # at offset 12 must NOT be treated as BGZF
        if bgzf_path is not None and not (
                len(head) >= 14 and head[3] & 0x04
                and head[12:14] == b"BC"):
            bgzf_path = None    # plain gzip, no EOF-marker contract
        f = io.BufferedReader(gzip.GzipFile(fileobj=raw))
    else:
        f = raw
        bgzf_path = None

    def check_eof_marker():
        # a BGZF file must end with the 28-byte empty EOF block; a file
        # cut exactly at a member boundary otherwise reads as a clean
        # (shorter) stream.  Same check as the native reader (BgzfMT),
        # so pipeline behavior doesn't depend on which backend loaded.
        if bgzf_path is None:
            return
        with open(bgzf_path, "rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            fh.seek(max(0, size - len(BGZF_EOF)))
            if fh.read() != BGZF_EOF:
                raise BamError("BGZF stream missing EOF marker "
                               "(truncated at a block boundary?)")

    read_bam_header(f)
    while True:
        head = f.read(4)
        if len(head) == 0:
            check_eof_marker()
            return  # clean EOF (bamlite.c:141 returns -1)
        if len(head) < 4:
            raise BamError("truncated BAM: partial block size")
        (block_size,) = struct.unpack("<i", head)
        block = _read_exact(f, block_size, "alignment block")
        rec, aux_buf = decode_record(block)
        if with_aux:
            yield rec, parse_aux(aux_buf)
        else:
            yield rec


def decode_record(block: bytes):
    """One alignment block -> (FastxRecord, aux_region_bytes).

    THE record decode — name, 4-bit packed sequence via the
    =ACMGRSVTWYHKDBN table (seqio.h:92), qualities phred+33 clamped at
    126 (seqio.h:113).  Shared by the sequential reader above and the
    byte-range sharded reader (io/bamindex.py) so the two streams can
    never diverge in decode semantics."""
    (refid, pos, l_read_name, mapq, bin_, n_cigar, flag, l_seq,
     next_ref, next_pos, tlen) = struct.unpack("<iiBBHHHiiii", block[:32])
    off = 32
    name = block[off:off + l_read_name - 1].decode(errors="replace")
    off += l_read_name
    off += 4 * n_cigar
    nseq_bytes = (l_seq + 1) // 2
    packed = np.frombuffer(block, dtype=np.uint8,
                           count=nseq_bytes, offset=off)
    seq = _NIB[packed].reshape(-1)[:l_seq].tobytes()
    off += nseq_bytes
    qual_raw = np.frombuffer(block, dtype=np.uint8, count=l_seq,
                             offset=off)
    # phred+33 clamped at 126 (seqio.h:113)
    qual = np.minimum(qual_raw.astype(np.int16) + 33, 126).astype(
        np.uint8).tobytes()
    return (FastxRecord(name=name, comment="", seq=seq, qual=qual),
            block[off + l_seq:])


# ---- aux-tag walk (bamlite.c:215-290) ------------------------------------
#
# ccsx itself never reads aux tags, but bamlite ships the full walk +
# typed getters; parity keeps them available (real subreads.bam carries
# np/rq/sn/... tags a downstream user may want).

_AUX_SCALAR = {"c": "<b", "C": "<B", "s": "<h", "S": "<H",
               "i": "<i", "I": "<I", "f": "<f", "d": "<d"}


def parse_aux(buf: bytes) -> dict:
    """Walk an alignment record's aux region into {tag: (type, value)}.

    Mirrors bam_aux_get/skip_aux (bamlite.c:192-241): scalar types
    c/C/s/S/i/I/f/d, char A, NUL-terminated Z/H, and B arrays."""
    out = {}
    off, n = 0, len(buf)
    try:
        while off + 3 <= n:
            tag = buf[off:off + 2].decode("ascii", errors="replace")
            typ = chr(buf[off + 2])
            off += 3
            if typ in _AUX_SCALAR:
                fmt = _AUX_SCALAR[typ]
                val = struct.unpack_from(fmt, buf, off)[0]
                off += struct.calcsize(fmt)
            elif typ == "A":
                val = chr(buf[off])
                off += 1
            elif typ in "ZH":
                end = buf.index(b"\x00", off)
                val = buf[off:end].decode(errors="replace")
                off = end + 1
            elif typ == "B":
                sub = chr(buf[off])
                (cnt,) = struct.unpack_from("<i", buf, off + 1)
                if sub not in _AUX_SCALAR:
                    raise BamError(f"bad B-array sub-type {sub!r}")
                fmt = _AUX_SCALAR[sub]
                size = struct.calcsize(fmt)
                off += 5
                # a negative/oversized count is corruption; without the
                # guard `off += cnt * size` could walk backwards and
                # loop forever
                if cnt < 0 or off + cnt * size > n:
                    raise BamError(f"bad B-array count {cnt} for {tag}")
                val = [struct.unpack_from(fmt, buf, off + i * size)[0]
                       for i in range(cnt)]
                off += cnt * size
            else:
                raise BamError(f"unknown aux type {typ!r} for tag {tag}")
            out[tag] = (typ, val)
    except (ValueError, IndexError, struct.error) as e:
        if isinstance(e, BamError):
            raise
        raise BamError(f"corrupt aux data: {e}") from e
    return out


def _aux_tv(aux: dict, tag: str):
    return aux.get(tag, ("", None))


def aux2i(aux: dict, tag: str) -> int:
    """Integer getter: c/C/s/S/i/I else 0 (bam_aux2i, bamlite.c:243-252)."""
    typ, val = _aux_tv(aux, tag)
    return int(val) if typ in tuple("cCsSiI") else 0


def aux2f(aux: dict, tag: str) -> float:
    """Float getter: f else 0.0 (bam_aux2f, bamlite.c:254-260)."""
    typ, val = _aux_tv(aux, tag)
    return float(val) if typ == "f" else 0.0


def aux2d(aux: dict, tag: str) -> float:
    """Double getter: d else 0.0 (bam_aux2d, bamlite.c:262-268)."""
    typ, val = _aux_tv(aux, tag)
    return float(val) if typ == "d" else 0.0


def aux2A(aux: dict, tag: str) -> str:
    """Char getter: A else '\\0' (bam_aux2A, bamlite.c:270-276)."""
    typ, val = _aux_tv(aux, tag)
    return val if typ == "A" else "\x00"


def aux2Z(aux: dict, tag: str):
    """String getter: Z/H else None (bam_aux2Z, bamlite.c:278-285)."""
    typ, val = _aux_tv(aux, tag)
    return val if typ in ("Z", "H") else None


# BGZF framing (the real subreads.bam container): gzip members <=64KB
# with a "BC" extra subfield holding the compressed block size, ending in
# a fixed 28-byte empty EOF block.  Valid multi-member gzip, so every
# plain-gzip reader (incl. this module's read path and the reference's
# bamlite, bamlite.h:13-19) still reads it; the native reader additionally
# exploits the block structure for parallel inflate (io_native.cpp).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")
BGZF_BLOCK_PAYLOAD = 0xFF00      # htslib's default uncompressed chunk


def _bgzf_block(data: bytes) -> bytes:
    import zlib

    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    bsize = 18 + len(comp) + 8 - 1          # total block size minus 1
    header = (b"\x1f\x8b\x08\x04" + b"\x00" * 4 + b"\x00\xff"
              + struct.pack("<H", 6) + b"BC" + struct.pack("<HH", 2, bsize))
    return (header + comp + struct.pack("<II", zlib.crc32(data),
                                        len(data) & 0xFFFFFFFF))


def write_bgzf(path, data: bytes) -> None:
    """Write `data` as a BGZF stream (blocked gzip + EOF marker)."""
    with open(path, "wb") as fh:
        for i in range(0, len(data), BGZF_BLOCK_PAYLOAD):
            fh.write(_bgzf_block(data[i:i + BGZF_BLOCK_PAYLOAD]))
        fh.write(BGZF_EOF)


class BamWriter:
    """Ordered unaligned-BAM output writer (CLI --bam).

    Buffers records and writes the BGZF container at close() — CCS
    output is orders of magnitude smaller than the subread input, so
    buffering is fine at real run sizes, and it keeps the writer a thin
    shim over write_bam.  Each record carries the consensus sequence,
    the vote-margin qualities (phred+33 in, raw phred in BAM), and an
    ``rq`` float aux tag (predicted read accuracy = 1 - mean per-base
    error), the tag HiFi consumers expect.  The reference has no BAM
    output (FASTA only, main.c:714)."""

    def __init__(self, path: str):
        self.path = path
        # fail fast on an unwritable path (the container itself is
        # written at close, after hours of compute on real inputs);
        # the container goes to a temp path and is renamed into place
        # at close so a crash mid-run can't leave a zero-byte,
        # EOF-marker-less file at the final path that downstream tools
        # would read as a complete-but-empty run.  The temp name is
        # unique (mkstemp in the target dir, same filesystem for the
        # rename): a fixed path+'.tmp' would leak forever after a crash
        # and let two writers on the same output silently clobber each
        # other's temp before the atomic rename
        import tempfile

        fd, self._tmp = tempfile.mkstemp(
            prefix=os.path.basename(path) + ".tmp.",
            dir=os.path.dirname(os.path.abspath(path)))
        os.close(fd)
        # mkstemp creates 0600; the final BAM must honor the umask like
        # any normally-open()ed output (os.replace preserves the mode)
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(self._tmp, 0o666 & ~umask)
        self._records = []
        self._closed = False

    def put(self, name: str, seq: bytes, qual: bytes | None = None) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        aux = ()
        if qual is not None:
            import numpy as np

            q = np.frombuffer(qual, np.uint8).astype(np.float64) - 33
            rq = 1.0 - float(np.mean(10.0 ** (-q / 10.0))) if len(q) else 0.0
            aux = (("rq", "f", rq),)
        self._records.append((name, seq, qual, aux))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        write_bam(self._tmp, self._records)
        os.replace(self._tmp, self.path)
        self._records = []


def write_bam(path, records, refs=(), bgzf: bool = True) -> None:
    """Tiny BAM writer for tests/fixtures (unmapped records only).

    BGZF container by default, like real subreads.bam; ``bgzf=False``
    writes one plain gzip member (also valid BAM-through-gzip, and
    exercises the native reader's non-BGZF fallback)."""
    import zlib

    out = io.BytesIO()
    text = b"@HD\tVN:1.6\n"
    out.write(b"BAM\x01")
    out.write(struct.pack("<i", len(text)))
    out.write(text)
    out.write(struct.pack("<i", len(refs)))
    for name, ln in refs:
        nm = name.encode() + b"\x00"
        out.write(struct.pack("<i", len(nm)))
        out.write(nm)
        out.write(struct.pack("<i", ln))
    rev = {v: i for i, v in enumerate(SEQ_NT16)}
    for rec in records:
        name, seq, qual = rec[:3]
        aux = rec[3] if len(rec) > 3 else ()   # (tag, type, value) triples
        nm = name.encode() + b"\x00"
        l_seq = len(seq)
        packed = bytearray((l_seq + 1) // 2)
        for i, b in enumerate(seq):
            code = rev.get(b, 15)
            if i % 2 == 0:
                packed[i // 2] |= code << 4
            else:
                packed[i // 2] |= code
        q = bytes((min(max(x - 33, 0), 93) for x in qual)) if qual \
            else b"\xff" * l_seq
        body = struct.pack("<iiBBHHHiiii", -1, -1, len(nm), 255, 0, 0, 4,
                           l_seq, -1, -1, 0)
        body += nm + bytes(packed) + q
        for tag, typ, val in aux:
            tb = tag.encode("ascii")
            if len(tb) != 2:
                raise BamError(f"aux tag must be 2 ASCII chars: {tag!r}")
            body += tb + typ.encode("ascii")
            if typ in _AUX_SCALAR:
                body += struct.pack(_AUX_SCALAR[typ], val)
            elif typ == "A":
                vb = val.encode("ascii")
                if len(vb) != 1:
                    raise BamError(f"aux A value must be 1 char: {val!r}")
                body += vb
            elif typ in "ZH":
                body += val.encode() + b"\x00"
            else:
                raise BamError(f"unsupported aux write type {typ!r}")
        out.write(struct.pack("<i", len(body)))
        out.write(body)
    data = out.getvalue()
    if bgzf:
        write_bgzf(path, data)
    else:
        with open(path, "wb") as fh:
            fh.write(gzip.compress(data))
