"""Batched device pipeline: many holes per TPU dispatch.

The per-hole path (pipeline/run.py) dispatches one star-MSA round per hole
per window — correct, but each dispatch is a small (P, W) problem that
leaves the chip mostly idle.  This runner multiplexes the consensus
generators (windowed_gen / consensus_gen) of many in-flight holes and
executes their pending RefineRequests together:

  admit holes ──> per-hole generator (host state machine)
                    │ yields RefineRequest (one window's refinement)
                    ▼
  group by (qmax, tmax, iters) ──> flatten each hole's passes into
  (hole, pass) ROWS and pack rows from many holes into fixed (R, qmax)
  slabs, first-fit-decreasing by hole (pipeline/pack.py); a row->hole
  segment-id vector rides along.  [--pass-buckets restores the older
  (P, qmax, tmax, iters) bucketed grouping as the A/B control, and a
  device mesh keeps it — the (data, pass) shardings need the fixed
  (Z, P) layout.]
                    ▼
  ONE fused jitted dispatch per slab (_refine_step_packed; _refine_step
  for the bucketed control): the speculative refinement rounds loop on
  device (banded DP fill + traceback projection + segment-id column
  vote + draft re-materialization), then the final round + breakpoint
  scan — intermediate drafts never leave the chip
                    ▼
  RefineResults routed back into each generator; finished holes emit
  consensus to the order-preserving writer.

This is the TPU analog of the reference's kt_for over a chunk's ZMWs
(main.c:702-704): the chunk becomes a device batch, the work-stealing
becomes shape-bucketed batching (SURVEY.md §2.2).  Output order is input
order, like the reference's ordered pipeline (kthread.c:202-213).
"""

from __future__ import annotations

import dataclasses
import functools
import sys
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax
import numpy as np

from ccsx_tpu.config import AlignParams, CcsConfig
from ccsx_tpu.consensus import prepare as prep_mod
from ccsx_tpu.consensus.align_host import MatchResult
from ccsx_tpu.consensus.hole import full_gen_for_zmw
from ccsx_tpu.consensus.star import (
    RefineRequest, RefineResult, RoundRequest, RoundResult, StarMsa,
    banded_impl_effective, bucket_len, pad_to, refine_host,
)
from ccsx_tpu.ops import banded
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.ops import traceback
from ccsx_tpu.pipeline import pack as pack_mod
from ccsx_tpu.pipeline import resilience as resil_mod
from ccsx_tpu.utils import faultinject
from ccsx_tpu.utils import trace
from ccsx_tpu.utils.journal import Journal
from ccsx_tpu.utils.metrics import (FailureBudgetExceeded, Metrics,
                                    check_failure_budget)


# ---- failure taxonomy (the fault-tolerance layer's classification of
# ---- exceptions escaping a jitted device dispatch; ARCHITECTURE.md
# ---- "Failure domains") ---------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "FAILED TO ALLOCATE")
# DELIBERATELY narrow: only the TPU-kernel toolchain's own names.  Broad
# words ("compile", "unsupported", "lowering") also appear in ordinary
# Python/data errors — e.g. TypeError "unsupported operand" — and a
# false 'compile' here would pin the process-wide scan fallback and
# misdiagnose a single bad hole.  A kernel compile failure that slips
# past these markers still lands safely: classified 'data', replayed on
# the host path (which is the scan spec anyway).
_COMPILE_MARKERS = ("MOSAIC", "PALLAS")
# deliberate validation errors in our own code (e.g. banded_pallas's
# "qmax exceeds PALLAS_MAX_QMAX" / "CCSX_PALLAS_GBLOCK" ValueErrors)
# mention the kernel by name but are per-group DATA conditions — the
# compiler toolchain never raises these builtin types
_DATA_EXC_TYPES = (ValueError, TypeError, KeyError, IndexError,
                   AssertionError)


def classify_failure(exc: BaseException) -> str:
    """'hang' | 'oom' | 'compile' | 'data' for an exception from a
    device dispatch.

    'hang' (DeviceHang class) is a dispatch deadline expiry
    (resilience.DeadlineExpired): the call was ABANDONED, so there is
    nothing to retry — re-dispatching onto a wedged backend would burn
    another deadline — and the group goes straight down the host-replay
    rung (and strikes the circuit breaker).  The rest are string-matched
    on the message (+ exception type name): XLA surfaces
    both allocator exhaustion and compiler failures as XlaRuntimeError
    subclasses whose types differ across jaxlib versions, but whose
    status-code prefixes (RESOURCE_EXHAUSTED, ...) are stable.  'oom'
    and 'compile' are TRANSIENT-DEVICE failures with a recovery ladder
    (resplit / scan fallback / host replay); 'data' means the inputs or
    our own code are at fault — replayed per-hole on the host path so
    the blast radius is one quarantined hole, never the run."""
    if isinstance(exc, resil_mod.DeadlineExpired):
        return "hang"
    msg = f"{type(exc).__name__}: {exc}".upper()
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if (any(m in msg for m in _COMPILE_MARKERS)
            and not isinstance(exc, _DATA_EXC_TYPES)):
        return "compile"
    return "data"


# ---- failure recovery (shared by BatchExecutor and PairExecutor) ---------

def _out_shape_tag(out):
    """Shape signature of a dispatch's output pytree — the materialize
    span's compile-grace key.  jit recompiles per distinct shape, and on
    a fully lazy runtime the compile can block at MATERIALIZATION rather
    than at dispatch, so the first wait on each (group, output-shape)
    must get the watchdog's compile grace or a healthy cold recompile is
    stamped degraded.  Output shapes change exactly when the compiled
    signature does (the batch dim rides every output), so this is a
    faithful per-executable key — and unlike the dispatch key it is
    computable here, in the executor-generic wait path.  The key also
    carries the output's device id(s): jit compiles one executable PER
    DEVICE, and round-robined slabs materialize on different chips, so
    each chip's first same-shape wait must get its own compile grace
    (same rule as the dispatch span's :d{i} tag)."""
    try:
        leaves = jax.tree_util.tree_leaves(out)
        tag = ",".join("x".join(str(d) for d in getattr(l, "shape", ()))
                       for l in leaves)
        for l in leaves:
            devs = getattr(l, "devices", None)
            if callable(devs):
                tag += ":d" + "-".join(
                    str(i) for i in sorted(d.id for d in devs()))
                break
        return tag
    except Exception:
        return None


def _bounded(resil, label_str, phase, fn):
    """Deadline-bound ``fn`` through the run's Resilience object (a
    plain call when deadlines are off / no resilience is wired)."""
    if resil is None or not resil.enabled:
        return fn()
    return resil.call(fn, label_str, phase)


def _host_replay_all(idxs, key, host_one, results, metrics, label,
                     reason) -> None:
    """The ladder bottom (and the breaker's open-state route): replay each
    request on the bit-exact host path; a host failure becomes that
    request's result (an Exception the driver quarantines per hole)."""
    for i in idxs:
        if metrics is not None:
            metrics.bump(host_fallbacks=1)
        try:
            with trace.span("host_replay", cat="recover",
                            group=label(key), reason=reason):
                results[i] = host_one(i)
        except Exception as he:  # quarantined per hole by the driver
            results[i] = he


def _run_group_sync(idxs, key, dispatch, finish, host_one, results,
                    metrics, depth, max_resplits, backoff_s,
                    compile_retried=False, label=str, resil=None,
                    probe=False) -> None:
    """Dispatch+materialize one (sub)group synchronously, recovering
    from failures (used on the resplit/retry paths, where the happy
    path's dispatch-all-then-materialize overlap no longer applies).
    ``probe``: this episode carries the breaker's half-open probe
    token — its success/failure (and only its) settles the probe."""
    try:
        out = _bounded(resil, label(key), "dispatch",
                       lambda: dispatch(idxs, key))
        # same watchdog coverage as the happy path: on an async runtime
        # a hang in a RETRIED dispatch would otherwise surface inside
        # finish()'s materialization, invisible to the stall watchdog —
        # exactly on the flaky-device runs most likely to be mid-recovery
        with trace.device_span("materialize", group=label(key),
                               shape=_out_shape_tag(out),
                               attribute=False, n=len(idxs)):
            out = _bounded(resil, label(key), "materialize",
                           lambda: jax.block_until_ready(out))
        finish(idxs, key, out)
        if probe and resil is not None:
            resil.breaker.probe_succeeded()
    except Exception as e:
        _recover_group(e, idxs, key, dispatch, finish, host_one, results,
                       metrics, depth, max_resplits, backoff_s,
                       compile_retried, label=label, resil=resil,
                       probe=probe)


def _recover_group(exc, idxs, key, dispatch, finish, host_one, results,
                   metrics, depth, max_resplits, backoff_s,
                   compile_retried=False, label=str, resil=None,
                   probe=False) -> None:
    """The adaptive-retry ladder for one failed shape group.

    hang    -> (DeviceHang: the dispatch deadline abandoned a wedged
               call) no retry — the backend just proved it can wedge —
               straight to the host replay below; books device_hangs +
               the degraded mark and strikes the circuit breaker
    oom     -> bisect idxs (halves run at half the Z/N bucket), with
               exponential backoff and capped depth; the ladder BOTTOM
               (no more halving) strikes the breaker
    compile -> pin the banded fill to the scan spec (one-time per
               process), strike the breaker, and retry THIS group once.
               The once-per-group
               retry is tracked separately from the once-per-process
               pin: in a dispatch-all sweep every group may have failed
               BEFORE the first recovery pinned the scan, and each
               deserves its one batched scan retry rather than the far
               slower per-request host replay
    data / ladder bottom -> replay each request on the host path;
               a host failure becomes that request's result (an
               Exception the driver quarantines per hole).  'data'
               never strikes the breaker: a bad hole says nothing
               about backend health
    """
    kind = classify_failure(exc)
    trace.instant("recover", cat="recover", kind=kind, group=label(key),
                  n=len(idxs), depth=depth)
    if kind == "hang" and resil is not None:
        resil.note_hang(label(key), exc, probe=probe)
    if kind == "compile" and not compile_retried:
        from ccsx_tpu.consensus import star as star_mod

        if resil is not None:
            resil.breaker.strike("compile", label(key), probe=probe)
        if star_mod.force_scan_fallback(f"{type(exc).__name__}: {exc}") \
                and metrics is not None:
            metrics.bump(compile_fallbacks=1)
        return _run_group_sync(idxs, key, dispatch, finish, host_one,
                               results, metrics, depth, max_resplits,
                               backoff_s, compile_retried=True,
                               label=label, resil=resil, probe=probe)
    if kind == "oom" and depth < max_resplits and len(idxs) > 1:
        if metrics is not None:
            metrics.bump(oom_resplits=1)
        print(f"[ccsx-tpu] device OOM on a {len(idxs)}-request group "
              f"{key}: resplitting (depth {depth + 1}): {exc}",
              file=sys.stderr)
        time.sleep(backoff_s * (2 ** depth))
        mid = (len(idxs) + 1) // 2
        for part in (idxs[:mid], idxs[mid:]):
            _run_group_sync(part, key, dispatch, finish, host_one,
                            results, metrics, depth + 1, max_resplits,
                            backoff_s, compile_retried, label=label,
                            resil=resil, probe=probe)
        return
    if kind == "oom" and resil is not None:
        # the OOM ladder bottomed out (depth cap or single request):
        # that is a backend-health strike, unlike a recoverable resplit
        resil.breaker.strike("oom", label(key), probe=probe)
    if kind == "data" and resil is not None and probe:
        # a per-hole data error never strikes — but THE probe's token
        # must still be released or the breaker wedges half-open
        # forever (admit() refuses all dispatch while a probe is
        # outstanding); non-probe data failures leave the probe alone
        resil.breaker.settle_probe()
    print(f"[ccsx-tpu] device dispatch failed ({kind}) for a "
          f"{len(idxs)}-request group {key}; replaying on the host "
          f"path: {exc}", file=sys.stderr)
    _host_replay_all(idxs, key, host_one, results, metrics, label, kind)


def _run_groups_recovering(groups, dispatch, finish, host_one, results,
                           metrics, max_resplits=3,
                           backoff_s=0.05, label=str, resil=None) -> None:
    """Happy path: dispatch every group's device work before
    materializing any result (jit dispatch is async, so group B's
    compute overlaps group A's d2h transfer); failures at either
    phase drop that one group into the recovery ladder.  ``label``
    maps a group key to the STABLE trace-group string the dispatch
    spans use (e.g. dropping the packed path's per-slab ordinal), so
    materialize spans share the dispatch namespace and the watchdog's
    per-(group, shape) compile grace neither re-arms on every slab nor
    misses a fresh shape's cold compile.

    Resilience (pipeline/resilience.py, ``resil``): an OPEN circuit
    breaker routes whole groups to the host path without touching the
    device (one probe group per --breaker-probe-s interval when
    half-open); a configured --dispatch-deadline bounds both the
    dispatch call and the materialize wait, abandoning wedged calls
    into the ladder's ``hang`` class."""
    _OPEN = object()   # sentinel: breaker refused this group's dispatch
    pending = []
    for key, idxs in groups.items():
        mode = resil.admit() if resil is not None else "closed"
        if mode == "host":
            pending.append((idxs, key, _OPEN, None, False))
            continue
        probe = mode == "probe"
        try:
            out = _bounded(resil, label(key), "dispatch",
                           lambda k=key, i=idxs: dispatch(i, k))
            pending.append((idxs, key, None, out, probe))
        except Exception as e:
            pending.append((idxs, key, e, None, probe))
    for idxs, key, exc, out, probe in pending:
        if exc is _OPEN:
            trace.instant("recover", cat="recover", kind="breaker_open",
                          group=label(key), n=len(idxs))
            _host_replay_all(idxs, key, host_one, results, metrics,
                             label, "breaker_open")
            continue
        try:
            if exc is not None:
                raise exc
            # watchdog coverage for the UNFORCED (untraced) case: on an
            # async runtime the dispatch span closes in ~1 ms and a hung
            # device surfaces HERE, when the outputs materialize — so
            # the blocking wait alone is its own device span
            # (attribute=False: it is wait, not chip work, and must not
            # pollute the compile/execute group table; shape keys the
            # compile grace — a lazy runtime may pay the cold compile in
            # this wait, not at dispatch).  finish() stays OUTSIDE: its
            # host work (overflow replays) is legitimately slow and must
            # not trip the watchdog
            with trace.device_span("materialize", group=label(key),
                                   shape=_out_shape_tag(out),
                                   attribute=False, n=len(idxs)):
                out = _bounded(resil, label(key), "materialize",
                               lambda o=out: jax.block_until_ready(o))
            finish(idxs, key, out)
            # only THE probe's own completion settles the breaker — a
            # concurrent pre-trip group finishing must not close it on
            # stale evidence (the admit() token carries the identity)
            if probe and resil is not None:
                resil.breaker.probe_succeeded()
        except Exception as e:
            _recover_group(e, idxs, key, dispatch, finish, host_one,
                           results, metrics, 0, max_resplits, backoff_s,
                           label=label, resil=resil, probe=probe)


@functools.lru_cache(maxsize=128)
def _round_body(params: AlignParams, max_ins: int, tmax: int):
    """The ONE star-round body both jitted steps build on: align every
    (hole, pass) window to its hole's draft (banded DP), project onto
    draft coordinates, vote per column.  _round_step and _refine_step
    share this function so the fused loop cannot drift from the
    single-round spec the differential tests pin."""
    from ccsx_tpu.consensus import star as star_mod
    from ccsx_tpu.ops import msa as msa_mod

    aligner = star_mod._aligner(params)  # scan default; env-gated Pallas
    projector = traceback.make_projector(tmax, max_ins)
    voter = msa_mod.make_voter(max_ins)

    def body(qs, qlens, row_mask, draft, dlen):
        Z, P, qmax = qs.shape
        ts_b = jax.numpy.broadcast_to(draft[:, None, :], (Z, P, tmax))
        tl_b = jax.numpy.broadcast_to(dlen[:, None], (Z, P))
        _, moves, offs = aligner(
            qs.reshape(Z * P, qmax), qlens.reshape(Z * P),
            ts_b.reshape(Z * P, tmax), tl_b.reshape(Z * P))
        moves = moves.reshape(Z, P, qmax, -1)
        offs = offs.reshape(Z, P, qmax)
        proj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                        in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, lead_ins = proj(
            moves, offs, qs, qlens, dlen)
        cons, ins_base, ins_votes, ncov, match, nwin = jax.vmap(voter)(
            aligned, ins_cnt, ins_b, row_mask)
        return (cons, ins_base, ins_votes, ncov, nwin, match, aligned,
                ins_cnt, lead_ins)

    return body


@functools.lru_cache(maxsize=128)
def _round_step(params: AlignParams, max_ins: int, tmax: int,
                bp_consts: tuple, pack: tuple | None = None):
    """Jitted batched star round: (Z, P, qmax) passes vs (Z, tmax) drafts.

    Z/P/qmax shape specialization is left to jit's trace cache; tmax,
    max_ins (projector output shape) and the breakpoint constants key
    the cache here.  The breakpoint scan + cursor advance run on-device
    (ops/breakpoint.py), so only small per-hole outputs cross to the
    host — not the (Z, P, tmax) match/aligned/ins_cnt tensors.

    pack=(P, qmax) selects the TRANSFER-PACKED variant for single-device
    runs: inputs arrive as ONE (Z, P*qmax + tmax) uint8 buffer + ONE
    (Z, 2P+1) int32 buffer and outputs leave as one uint8 + one int32
    buffer (see _pack_args/_unpack_round).  Host<->device transfer cost
    is dominated by a fixed per-transfer latency, not bandwidth
    (measured r5: ~30-100 ms per transfer through the axon tunnel vs
    ~70 MB/s streaming; on real PCIe the same fixed DMA/launch overhead
    applies at smaller scale), so 5 h2d + 7 d2h per dispatch costs ~12
    latencies where 2 + 2 cost 4.  The multi-device path keeps separate
    arrays — they carry per-argument NamedShardings (_shard_args)."""
    import jax.numpy as jnp

    from ccsx_tpu.ops import breakpoint as bp_mod

    body = _round_body(params, max_ins, tmax)
    bp_advance = bp_mod.make_bp_advance(tmax, *bp_consts)

    def core(qs, qlens, ts, tlens, row_mask):
        (cons, ins_base, ins_votes, ncov, nwin, match, aligned, ins_cnt,
         lead_ins) = body(qs, qlens, row_mask, ts, tlens)
        bp, advance = jax.vmap(bp_advance)(
            match, cons, aligned, ins_cnt, lead_ins, row_mask, tlens)
        # compact the d2h payload: votes/coverage are bounded by the pass
        # count (<= 64 with the largest pass bucket), so uint8 halves the
        # transfer; the host casts back before arithmetic
        # (msa.emit_insertions)
        return (cons, ins_base, ins_votes.astype(jnp.uint8),
                ncov.astype(jnp.uint8),
                nwin.astype(jnp.uint8), bp, advance)

    if pack is None:
        return jax.jit(core)
    P, qmax = pack

    @jax.jit
    def step(big, small):
        qs, qlens, ts, tlens, row_mask = _unpack_args_jax(
            big, small, P, qmax, tmax)
        cons, ins_base, ins_votes, ncov, nwin, bp, advance = core(
            qs, qlens, ts, tlens, row_mask)
        Z = big.shape[0]
        big_out = jnp.concatenate([
            cons.astype(jnp.uint8),
            ins_base.reshape(Z, tmax * max_ins).astype(jnp.uint8),
            ins_votes.reshape(Z, tmax * max_ins),
            ncov, nwin], axis=1)
        small_out = jnp.concatenate(
            [bp[:, None], advance], axis=1).astype(jnp.int32)
        return big_out, small_out

    return step


def _pack_args(args):
    """Host side of the packed single-device transfer protocol: the 5
    round/refine inputs become one uint8 and one int32 buffer (one h2d
    latency each instead of five)."""
    qs, qlens, ts, tlens, row_mask = args
    Z, P, qmax = qs.shape
    big = np.concatenate([qs.reshape(Z, P * qmax), ts], axis=1)
    small = np.concatenate(
        [qlens, tlens[:, None], row_mask.astype(np.int32)], axis=1)
    return big, small


def _unpack_args_jax(big, small, P: int, qmax: int, tmax: int):
    """Device side of _pack_args (slices compile to views/copies that
    cost nothing next to the transfer latencies they replace)."""
    Z = big.shape[0]
    qs = big[:, :P * qmax].reshape(Z, P, qmax)
    ts = big[:, P * qmax:P * qmax + tmax]
    qlens = small[:, :P]
    tlens = small[:, P]
    row_mask = small[:, P + 1:2 * P + 1] != 0
    return qs, qlens, ts, tlens, row_mask


def _unpack_round(big, small, max_ins: int, tmax: int):
    """Host-side split of a packed round result back into the 7-tuple
    (cons, ins_base, ins_votes, ncov, nwin, bp, advance) with the same
    dtypes the unpacked path ships."""
    Z = big.shape[0]
    R = max_ins
    cons = big[:, :tmax]
    ins_base = big[:, tmax:tmax * (1 + R)].reshape(Z, tmax, R)
    ins_votes = big[:, tmax * (1 + R):tmax * (1 + 2 * R)].reshape(
        Z, tmax, R)
    ncov = big[:, tmax * (1 + 2 * R):tmax * (2 + 2 * R)]
    nwin = big[:, tmax * (2 + 2 * R):tmax * (3 + 2 * R)]
    bp = small[:, 0]
    advance = small[:, 1:]
    return cons, ins_base, ins_votes, ncov, nwin, bp, advance


def _z_bucket(n: int) -> int:
    """Pad the batch Z to the next power of two (bounds jit retraces)."""
    z = 1
    while z < n:
        z *= 2
    return z


def _fused_tmax(tlen: int, quant: int) -> int:
    """Draft capacity for the fused refinement step: one geometric bucket
    above the request's own, so the speculative rounds' liberal inserts
    (msa.emit_insertions) stay on device in the overwhelmingly common
    case.  A draft outgrowing even that is flagged by the step and
    replayed exactly on the host (refine_host)."""
    b = bucket_len(tlen, quant)
    return bucket_len(b + 1, quant)


@functools.lru_cache(maxsize=128)
def _refine_step(params: AlignParams, max_ins: int, tmax: int, iters: int,
                 bp_consts: tuple, pack: tuple | None = None):
    """ONE jitted dispatch for a window's whole refinement loop.

    pack=(P, qmax) selects the transfer-packed single-device variant
    (same protocol and rationale as _round_step; small_out additionally
    carries dlen and ovf).

    Runs `iters` speculative star rounds in a device while_loop —
    realign to draft, vote, emit insertions liberally, re-materialize
    the draft ON DEVICE (msa.emit_insertions_jax / make_materializer) —
    then the final round with the device breakpoint scan.  Per-hole
    fixpoint masking mirrors refine_host's early-exit bit-exactly: a
    hole whose speculative draft stops changing is frozen (re-rounds on
    a fixed draft are no-ops, so freezing == the host's skip), and the
    loop exits early once every hole is frozen.  This cuts the batched
    pipeline's device dispatches per window from iters+1 to 1 — the
    reference pays no such per-round launch cost (its POA rounds are
    function calls, main.c:486-492), so this is where the TPU pipeline
    wins back launch overhead.
    """
    import jax.numpy as jnp

    from ccsx_tpu.ops import breakpoint as bp_mod
    from ccsx_tpu.ops import msa as msa_mod

    one_round = _round_body(params, max_ins, tmax)
    bp_advance = bp_mod.make_bp_advance(tmax, *bp_consts)
    mat_v = jax.vmap(msa_mod.make_materializer(tmax, tmax, max_ins))
    spec_emit = jax.vmap(
        lambda ib, iv, nc: msa_mod.emit_insertions_jax(ib, iv, nc, True))

    def core(qs, qlens, ts, tlens, row_mask):
        Z, P, _ = qs.shape

        def body(carry):
            it, draft, dlen, fixed, ovf, outs = carry
            new = one_round(qs, qlens, row_mask, draft, dlen)
            # a frozen hole keeps its LAST live round's outputs — for a
            # fixpoint hole that round IS the host loop's final round
            # (re-rounding an unchanged draft is a no-op), so carrying
            # the outputs here is what lets the separate final round be
            # folded away entirely
            outs = tuple(
                jnp.where(fixed.reshape((Z,) + (1,) * (n.ndim - 1)), o, n)
                for o, n in zip(outs, new))
            cons, ins_base, ins_votes, ncov = outs[:4]
            ins_out = spec_emit(ins_base, ins_votes, ncov)
            nd, nl, o = mat_v(cons, ins_out, dlen)
            # fixpoint: same length AND same padded cells == the host's
            # np.array_equal on the exact-length drafts (pads are PAD on
            # both sides, and a length change forces a cell change)
            now_fixed = (nl == dlen) & (nd == draft).all(axis=1)
            # the round at it == iters is the host loop's mandatory final
            # round: its outputs are kept and nobody grows past it
            last = it >= iters
            # overflow only matters when the speculative draft would be
            # consumed (it < iters); an overflowed hole keeps its
            # in-range draft/dlen and is FROZEN — its device result is
            # discarded for a host replay, and freezing keeps the carry
            # valid for the static shapes and stops it holding the loop
            # open
            o = ~fixed & o & ~last
            grow = ~fixed & ~o & ~now_fixed & ~last
            draft = jnp.where(grow[:, None], nd, draft)
            dlen = jnp.where(grow, nl, dlen)
            return (it + 1, draft, dlen, fixed | now_fixed | o | last,
                    ovf | o, outs)

        def cond(carry):
            return ~carry[3].all()

        # Memory note: carrying the full outs tuple (incl. the (Z,P,tmax)
        # match/aligned/ins_cnt tensors needed only by the post-loop
        # bp_advance) keeps those buffers live across every iteration,
        # roughly tripling the fused step's large per-pass buffers vs the
        # unfused round.  The alternative — carry only (draft, dlen) and
        # recompute the kept round once after the loop (one_round is pure,
        # and a frozen hole's draft/dlen stop changing, so the recompute
        # reproduces the kept outputs exactly) — costs one extra full
        # round of compute per window (~1/(iters+1) e2e).  On v5e the Z
        # buckets fit comfortably, so we spend the memory; flip to the
        # recompute form if a larger chip/bucket ever OOMs here.  (Since
        # the fault-tolerance layer, an OOM here no longer kills the
        # run: BatchExecutor._recover bisects the Z batch and retries —
        # the recompute form remains the right STRUCTURAL fix if
        # resplits ever show up in metrics.oom_resplits at steady state.)
        # pad holes (all-False row_mask) start frozen so they can't keep
        # the while_loop alive
        fixed0 = ~row_mask.any(axis=1)
        ovf0 = jnp.zeros((Z,), bool)
        outs0 = (
            jnp.zeros((Z, tmax), jnp.uint8),            # cons
            jnp.zeros((Z, tmax, max_ins), jnp.uint8),   # ins_base
            jnp.zeros((Z, tmax, max_ins), jnp.int32),   # ins_votes
            jnp.zeros((Z, tmax), jnp.int32),            # ncov
            jnp.zeros((Z, tmax), jnp.int32),            # nwin
            jnp.zeros((Z, P, tmax), bool),              # match
            jnp.zeros((Z, P, tmax), jnp.uint8),         # aligned
            jnp.zeros((Z, P, tmax), jnp.int32),         # ins_cnt
            jnp.zeros((Z, P), jnp.int32),               # lead_ins
        )
        _, _, dlen, _, ovf, outs = jax.lax.while_loop(
            cond, body, (jnp.int32(0), ts, tlens, fixed0, ovf0, outs0))
        (cons, ins_base, ins_votes, ncov, nwin, match, aligned, ins_cnt,
         lead_ins) = outs
        bp, advance = jax.vmap(bp_advance)(
            match, cons, aligned, ins_cnt, lead_ins, row_mask, dlen)
        # uint8 vote/coverage compaction, as in _round_step
        return (cons, ins_base, ins_votes.astype(jnp.uint8),
                ncov.astype(jnp.uint8), nwin.astype(jnp.uint8),
                bp, advance, dlen, ovf)

    if pack is None:
        return jax.jit(core)
    P, qmax = pack

    @jax.jit
    def step(big, small):
        args = _unpack_args_jax(big, small, P, qmax, tmax)
        (cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen,
         ovf) = core(*args)
        Z = big.shape[0]
        big_out = jnp.concatenate([
            cons.astype(jnp.uint8),
            ins_base.reshape(Z, tmax * max_ins).astype(jnp.uint8),
            ins_votes.reshape(Z, tmax * max_ins),
            ncov, nwin], axis=1)
        small_out = jnp.concatenate(
            [bp[:, None], advance, dlen[:, None],
             ovf[:, None].astype(jnp.int32)], axis=1).astype(jnp.int32)
        return big_out, small_out

    return step


def _unpack_refine(big, small, max_ins: int, tmax: int):
    """Host-side split of a packed refine result back into the 9-tuple
    (cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen, ovf)."""
    cons, ins_base, ins_votes, ncov, nwin, bp, rest = _unpack_round(
        big, small, max_ins, tmax)
    return (cons, ins_base, ins_votes, ncov, nwin, bp, rest[:, :-2],
            rest[:, -2], rest[:, -1] != 0)


# ---- ragged pass-packed dispatch (pipeline/pack.py plans the slabs;
# ---- these are the device steps and the slab transfer protocol) ----------

@functools.lru_cache(maxsize=128)
def _round_body_packed(params: AlignParams, max_ins: int, tmax: int,
                       nseg: int):
    """One star round over a packed slab: (R, qmax) rows from up to
    ``nseg`` holes, each row aligned to ITS hole's draft (a per-row
    gather replaces the bucketed path's per-hole broadcast), voted by
    segment id (msa.make_segment_voter).  Per-row alignment and
    projection are the same pure functions as _round_body's, so a row's
    tensors do not depend on which slab it rides in — the keystone of
    the packed path's byte-identity."""
    from ccsx_tpu.consensus import star as star_mod
    from ccsx_tpu.ops import msa as msa_mod

    aligner = star_mod._aligner(params)  # scan default; env-gated Pallas
    projector = traceback.make_projector(tmax, max_ins)
    voter = msa_mod.make_segment_voter(max_ins, nseg)

    def body(qs, qlens, row_mask, seg, draft, dlen):
        ts_r = draft[seg]          # (R, tmax) per-row targets
        tl_r = dlen[seg]           # (R,)
        _, moves, offs = aligner(qs, qlens, ts_r, tl_r)
        proj = jax.vmap(projector, in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, lead_ins = proj(
            moves, offs, qs, qlens, tl_r)
        cons, ins_base, ins_votes, ncov, match, nwin = voter(
            aligned, ins_cnt, ins_b, row_mask, seg)
        return (cons, ins_base, ins_votes, ncov, nwin, match, aligned,
                ins_cnt, lead_ins)

    return body


@functools.lru_cache(maxsize=128)
def _refine_core_packed(params: AlignParams, max_ins: int, tmax: int,
                        iters: int, nseg: int, bp_consts: tuple):
    """The fused whole-window refinement loop over ONE packed slab —
    _refine_step's ragged twin.  The while_loop carries per-SEGMENT
    (hole-slot) fixpoint state instead of per-Z-slot state: hole-shaped
    carries (draft/dlen/fixed/ovf and the vote outputs) are (H, ...)
    with H = nseg, the per-row tensors the post-loop breakpoint needs
    are (R, ...), and freezing broadcasts hole state onto rows through
    the segment vector.  Same fixpoint/overflow semantics as the
    bucketed step (which tests pin against refine_host, the spec).

    This is the UNJITTED core; _refine_step_packed wraps it in the
    single-device slab wire protocol and _refine_step_packed_fused in
    the multi-chip (D, slab) shard_map — both compile the same
    computation, which is what keeps single-chip and multi-chip output
    byte-identical."""
    import jax.numpy as jnp

    from ccsx_tpu.ops import breakpoint as bp_mod
    from ccsx_tpu.ops import msa as msa_mod

    one_round = _round_body_packed(params, max_ins, tmax, nseg)
    bp_advance = bp_mod.make_bp_advance_packed(tmax, nseg, *bp_consts)
    mat_v = jax.vmap(msa_mod.make_materializer(tmax, tmax, max_ins))
    spec_emit = jax.vmap(
        lambda ib, iv, nc: msa_mod.emit_insertions_jax(ib, iv, nc, True))
    H = nseg

    def core(qs, qlens, row_mask, seg, ts, tlens):
        R = qs.shape[0]

        def body(carry):
            it, draft, dlen, fixed, ovf, outs = carry
            new = one_round(qs, qlens, row_mask, seg, draft, dlen)
            # frozen holes keep their LAST live round's outputs (same
            # final-round folding as _refine_step); outs[:5] are
            # hole-shaped, outs[5:] row-shaped — rows freeze with their
            # hole via the segment gather
            fix_r = fixed[seg]
            outs = tuple(
                jnp.where(fixed.reshape((H,) + (1,) * (n.ndim - 1)), o, n)
                for o, n in zip(outs[:5], new[:5])
            ) + tuple(
                jnp.where(fix_r.reshape((R,) + (1,) * (n.ndim - 1)), o, n)
                for o, n in zip(outs[5:], new[5:])
            )
            cons, ins_base, ins_votes, ncov = outs[:4]
            ins_out = spec_emit(ins_base, ins_votes, ncov)
            nd, nl, o = mat_v(cons, ins_out, dlen)
            now_fixed = (nl == dlen) & (nd == draft).all(axis=1)
            last = it >= iters
            o = ~fixed & o & ~last
            grow = ~fixed & ~o & ~now_fixed & ~last
            draft = jnp.where(grow[:, None], nd, draft)
            dlen = jnp.where(grow, nl, dlen)
            return (it + 1, draft, dlen, fixed | now_fixed | o | last,
                    ovf | o, outs)

        def cond(carry):
            return ~carry[3].all()

        # empty hole slots (no real rows — slab tail capacity) start
        # frozen, as pad holes do in _refine_step; the executor never
        # reads them back
        nrows = jax.ops.segment_sum(row_mask.astype(jnp.int32), seg,
                                    num_segments=H,
                                    indices_are_sorted=True)
        fixed0 = nrows == 0
        ovf0 = jnp.zeros((H,), bool)
        outs0 = (
            jnp.zeros((H, tmax), jnp.uint8),            # cons
            jnp.zeros((H, tmax, max_ins), jnp.uint8),   # ins_base
            jnp.zeros((H, tmax, max_ins), jnp.int32),   # ins_votes
            jnp.zeros((H, tmax), jnp.int32),            # ncov
            jnp.zeros((H, tmax), jnp.int32),            # nwin
            jnp.zeros((R, tmax), bool),                 # match
            jnp.zeros((R, tmax), jnp.uint8),            # aligned
            jnp.zeros((R, tmax), jnp.int32),            # ins_cnt
            jnp.zeros((R,), jnp.int32),                 # lead_ins
        )
        _, _, dlen, _, ovf, outs = jax.lax.while_loop(
            cond, body, (jnp.int32(0), ts, tlens, fixed0, ovf0, outs0))
        (cons, ins_base, ins_votes, ncov, nwin, match, aligned, ins_cnt,
         lead_ins) = outs
        bp, advance = bp_advance(match, cons, aligned, ins_cnt, lead_ins,
                                 row_mask, seg, dlen)
        # uint8 vote/coverage compaction, as in _round_step (bounded by
        # the hole's real row count <= max_passes)
        return (cons, ins_base, ins_votes.astype(jnp.uint8),
                ncov.astype(jnp.uint8), nwin.astype(jnp.uint8),
                bp, advance, dlen, ovf)

    return core


def _slab_wire_sizes(R: int, qmax: int, H: int, tmax: int,
                     max_ins: int) -> tuple:
    """(Lbig, Lsmall) — the COMMON padded lengths of the slab wire
    protocol's uint8 and int32 buffers, covering both the input and the
    output payload.  Padding the smaller side to the larger one costs a
    few KB of zeros on latency-dominated transfers (measured r5: the
    fixed ~30-100 ms per-transfer latency dwarfs bandwidth at slab
    sizes) and buys REAL buffer donation: with in/out avals identical,
    XLA aliases each output onto its donated input buffer, so the
    fixpoint loop's dispatch allocates no fresh output HBM and the r7
    per-dispatch alloc/free churn on the packed path disappears.
    (Donation with mismatched sizes is silently dropped by XLA — a
    warning, not an alias — so the padding is what makes
    donate_argnums mean anything.)"""
    big_in = R * qmax + H * tmax
    big_out = H * tmax * (3 + 2 * max_ins)
    small_in = 3 * R + H
    small_out = 3 * H + R
    return max(big_in, big_out), max(small_in, small_out)


def _packed_wire_step(params: AlignParams, max_ins: int, tmax: int,
                      iters: int, nseg: int, bp_consts: tuple,
                      R: int, qmax: int):
    """Unjitted slab wire step: ONE 1-D uint8 + ONE 1-D int32 buffer in
    (see _pack_slab_args; rationale in _round_step), one of each out,
    both at the common _slab_wire_sizes lengths so donation aliases.
    _refine_step_packed jits it per slab shape; the fused multi-chip
    variant vmaps it over a leading device dimension."""
    import jax.numpy as jnp

    core = _refine_core_packed(params, max_ins, tmax, iters, nseg,
                               bp_consts)
    H = nseg
    Lbig, Lsmall = _slab_wire_sizes(R, qmax, H, tmax, max_ins)

    def step(big, small):
        args = _unpack_slab_args_jax(big, small, R, qmax, H, tmax)
        (cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen,
         ovf) = core(*args)
        big_out = jnp.concatenate([
            cons.reshape(-1), ins_base.reshape(-1),
            ins_votes.reshape(-1), ncov.reshape(-1), nwin.reshape(-1)])
        small_out = jnp.concatenate(
            [bp, dlen, ovf.astype(jnp.int32), advance]).astype(jnp.int32)
        big_out = jnp.pad(big_out, (0, Lbig - big_out.shape[0]))
        small_out = jnp.pad(small_out, (0, Lsmall - small_out.shape[0]))
        return big_out, small_out

    return step


@functools.lru_cache(maxsize=128)
def _refine_step_packed(params: AlignParams, max_ins: int, tmax: int,
                        iters: int, nseg: int, bp_consts: tuple,
                        pack: tuple):
    """Jitted single-device packed refine step at pack=(R, qmax), with
    both wire buffers DONATED: the input slab is dead the moment the
    step owns it, and at the common wire sizes XLA aliases the outputs
    onto it in place (_slab_wire_sizes) — no fresh output allocation
    per dispatch."""
    R, qmax = pack
    step = _packed_wire_step(params, max_ins, tmax, iters, nseg,
                             bp_consts, R, qmax)
    return jax.jit(step, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=64)
def _refine_step_packed_fused(params: AlignParams, max_ins: int,
                              tmax: int, iters: int, nseg: int,
                              bp_consts: tuple, pack: tuple, mesh):
    """ONE fused multi-chip packed dispatch: same-shape slabs stacked
    into a leading device dimension (Dstack, Lbig)/(Dstack, Lsmall) and
    shard_mapped over the 1-D local ('slab',) mesh — one transfer and
    ONE executable call per group per wave, where the r7 round-robin
    issued one device_put + one dispatch per slab per chip and jit
    compiled one executable PER chip (the :d{i} shape tags the flight
    recorder surfaced).  Each chip runs the identical per-slab wire
    step on its own slab with no cross-chip traffic; a dummy (all-zero)
    slab freezes every segment at iteration 0, so padding a tail wave
    up to D costs that chip ~a breakpoint scan on zeros.  Dstack is
    normally D; an OOM-resplit re-plan can exceed D slabs, in which
    case the local leading dim K = Dstack/D > 1 and the vmap carries K
    slabs per chip — still one executable call.  Wire buffers donated,
    as in the single-device step."""
    from jax.sharding import PartitionSpec as PS

    from ccsx_tpu.parallel.mesh import shard_map_compat

    R, qmax = pack
    step = _packed_wire_step(params, max_ins, tmax, iters, nseg,
                             bp_consts, R, qmax)
    sh = shard_map_compat(
        lambda bigs, smalls: jax.vmap(step)(bigs, smalls), mesh,
        in_specs=(PS("slab", None), PS("slab", None)),
        out_specs=(PS("slab", None), PS("slab", None)))
    return jax.jit(sh, donate_argnums=(0, 1))


def _pack_slab_args(args, max_ins: int):
    """Host side of the slab transfer protocol: the 6 packed-refine
    inputs become one 1-D uint8 and one 1-D int32 buffer (one h2d
    latency each — same fixed-latency rationale as _pack_args), zero-
    padded to the common _slab_wire_sizes lengths so the device step
    can write its outputs in place over the donated inputs."""
    qs, qlens, row_mask, seg, ts, tlens = args
    R, qmax = qs.shape
    H, tmax = ts.shape
    Lbig, Lsmall = _slab_wire_sizes(R, qmax, H, tmax, max_ins)
    big = np.zeros(Lbig, np.uint8)
    big[:R * qmax] = qs.reshape(-1)
    big[R * qmax:R * qmax + H * tmax] = ts.reshape(-1)
    small = np.zeros(Lsmall, np.int32)
    small[:R] = qlens
    small[R:2 * R] = row_mask
    small[2 * R:3 * R] = seg
    small[3 * R:3 * R + H] = tlens
    return big, small


def _unpack_slab_args_jax(big, small, R: int, qmax: int, H: int,
                          tmax: int):
    """Device side of _pack_slab_args (explicit slice ends: the wire
    buffers carry alignment padding past the payload)."""
    qs = big[:R * qmax].reshape(R, qmax)
    ts = big[R * qmax:R * qmax + H * tmax].reshape(H, tmax)
    qlens = small[:R]
    row_mask = small[R:2 * R] != 0
    seg = small[2 * R:3 * R]
    tlens = small[3 * R:3 * R + H]
    return qs, qlens, row_mask, seg, ts, tlens


def _unpack_slab_refine(big, small, max_ins: int, tmax: int, H: int,
                        R: int):
    """Host-side split of a packed-slab refine result back into the
    9-tuple (cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen,
    ovf) — hole-shaped fields (H, ...), advance per row (R,)."""
    T, M = tmax, max_ins
    sizes = [H * T, H * T * M, H * T * M, H * T, H * T]
    offs = np.cumsum([0] + sizes)
    cons = big[offs[0]:offs[1]].reshape(H, T)
    ins_base = big[offs[1]:offs[2]].reshape(H, T, M)
    ins_votes = big[offs[2]:offs[3]].reshape(H, T, M)
    ncov = big[offs[3]:offs[4]].reshape(H, T)
    nwin = big[offs[4]:offs[5]].reshape(H, T)
    bp = small[:H]
    dlen = small[H:2 * H]
    ovf = small[2 * H:3 * H] != 0
    advance = small[3 * H:3 * H + R]
    return cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen, ovf


@functools.lru_cache(maxsize=8)
def _pair_fill(params: AlignParams):
    """Jitted batched local fill with per-pair line hints — the device
    half of strand_match (main.c:255-290), batched across holes."""
    from ccsx_tpu.ops import banded as banded_mod

    return banded_mod.make_batched("local", params, with_line=True)


@functools.lru_cache(maxsize=32)
def _pair_fill_packed(params: AlignParams, qmax: int, tmax: int):
    """Transfer-packed pair fill: one (N, qmax+tmax) uint8 + one (N, 6)
    int32 in, one (N, 7) int32 out — 3 transfer latencies per dispatch
    instead of 12 (5 h2d + 7 scalar-array d2h; the per-transfer latency
    dominates at these sizes, see _round_step)."""
    import jax.numpy as jnp

    fill = _pair_fill(params)

    @jax.jit
    def step(big, small):
        qs = big[:, :qmax]
        ts = big[:, qmax:qmax + tmax]
        qlens, tlens, ls = small[:, 0], small[:, 1], small[:, 2:6]
        r = fill(qs, qlens, ts, tlens, ls)
        return jnp.stack(
            [r.score, r.qb, r.qe, r.tb, r.te, r.aln, r.mat],
            axis=1).astype(jnp.int32)

    return step


class PairExecutor:
    """Batches prep PairRequests (strand_match pairs) across holes.

    One pair per dispatch leaves prep at ~95% of wall time at device-
    round speed (benchmarks/prep_share.py); here pairs from many holes
    are seeded on the host (ops/seed.py), grouped by padded (qmax, tmax)
    bucket, and filled in ONE batched local-mode banded DP per group —
    the same shape-bucketing discipline as the consensus rounds.

    The pre-alignment plane (ISSUE 11, ROADMAP item 4) adds a filter
    and a device seeding stage in front of the DP, both off by knob and
    byte-invariant on:

    * ``prefilter`` — hopeless candidate pairs are rejected BEFORE the
      DP by the sketch rules (seed-gate parity / noise gate /
      band-overlap geometry — every rule only rejects pairs whose
      strand_match acceptance would fail, see ops/sketch.py), in two
      forms by size: pairs at or above ``screen_min_device``
      (sketch.SPECULATE_MIN_QT) are scored by ONE batched
      similarity-sketch dispatch per (qmax, tmax) bucket
      (sketch.screen_step) before even seeding — the long-template
      regime where a doomed arm's seeding sort + DP are worth a
      dedicated wave — while smaller pairs (down to
      sketch.SCREEN_MIN_QT, below which the rules degenerate to the
      legacy gate) get the SAME rules applied for free from their seed
      computation (sketch.reject_from_hit), no extra dispatch.  The
      screen is ADVISORY: a failed screen (device + host rung both
      down) keeps the pair alive rather than quarantining the hole.
      Device-SEEDED pairs never pay a dedicated screen dispatch at
      all: the seed rows are a superset of the screen triple, so the
      rules fire post-seeding from those statistics — one dispatch
      does both jobs.
    * ``seed_device_min_t`` — surviving pairs whose template is at
      least this long seed on the device (ops/seed_device.seed_step,
      bit-equal to seed_diagonal); shorter ones keep the cached host
      sort-join.  0 keeps everything on the host.

    Shares the failure-containment ladder with BatchExecutor
    (_run_groups_recovering) at all three dispatch sites (screen, seed,
    fill): an OOM bisects and retries, and the last resort replays on
    the host twin (screen_host / seed_diagonal /
    HostAligner.strand_match — the per-hole spec paths, so results stay
    identical).

    PairRequest lists may also carry prepare.PairBatch entries (the
    walk's fwd+RC speculation): the batch's arms are evaluated
    SPECULATIVELY in the same wave — the wrong-strand arm dies in the
    screen — and the result slot is the aligned list of (ok, rs) the
    first-accept contract requires.
    """

    # bounded LRU of per-template sorted k-mer indexes (keyed by
    # PairRequest.t_token): the orientation walk pairs MANY passes
    # against one hole's template across successive sweeps, and the
    # token lets those sweeps share one sort (ops/seed.py)
    seed_cache_max = 128

    def __init__(self, params: AlignParams, quant: int = 512,
                 metrics=None, warmup=None, resil=None,
                 prefilter: bool = True, seed_device_min_t: int = 16384,
                 warm_cache: Optional[set] = None):
        self.params = params
        self.quant = quant
        self.metrics = metrics
        # shared Resilience object (pipeline/resilience.py): pair fills
        # ride the same dispatch deadline + circuit breaker as the
        # refine dispatches — a wedged chip wedges both
        self._resil = resil
        self._warmup = warmup      # AOT precompiler (pipeline/warmup.py)
        # inline-warm dedupe (no compiler).  ``warm_cache`` lets a
        # resident server pass ONE set shared by every job's executor:
        # the jit caches behind these keys are process-wide (module-
        # level lru_cache factories), so job 2 re-warming job 1's
        # (qmax, tmax, N) bucket would pay a pointless zero-slab pass
        self._warmed: set = warm_cache if warm_cache is not None \
            else set()
        self._host_aligner = None  # built lazily, on first fallback
        self.prefilter = bool(prefilter)
        self.seed_device_min_t = max(0, int(seed_device_min_t))
        # device-screen floor: below it the filter rides the seed
        # computation instead (reject_from_hit) — an attribute so tests
        # can drive the dispatch site at small shapes
        from ccsx_tpu.ops import sketch as sketch_mod

        self.screen_min_device = sketch_mod.SPECULATE_MIN_QT
        from collections import OrderedDict

        self._seed_cache: "OrderedDict" = OrderedDict()

    # ---- pre-alignment plane routing rules --------------------------------

    def _screens(self, pr) -> bool:
        return (self.prefilter
                and min(len(pr.q), len(pr.t)) >= self.screen_min_device)

    def _seeds_on_device(self, pr) -> bool:
        return (self.seed_device_min_t > 0
                and len(pr.t) >= self.seed_device_min_t)

    @staticmethod
    def _flatten(pairs):
        """Expand PairBatch entries into a flat request list plus the
        (start, count, is_batch) spans to fold results back."""
        flat: List["prep_mod.PairRequest"] = []
        spans: List[tuple] = []
        for pr in pairs:
            if isinstance(pr, prep_mod.PairBatch):
                spans.append((len(flat), len(pr.requests), True))
                flat.extend(pr.requests)
            else:
                spans.append((len(flat), 1, False))
                flat.append(pr)
        return flat, spans

    def warm(self, pairs) -> None:
        """Precompile the padded pair-fill executables this pair list
        will need, through the SAME factory + dispatch path run() uses
        (benchmarks/prep_share.py warms through this instead of its old
        hand-rolled double-run, so its timings and production compile
        through one code path).  Asynchronous with a WarmupCompiler
        (drain() to sync), inline without one.  The predicted N is an
        upper bound — a pair that fails seeding drops out of its bucket
        and can shrink N to a smaller (also canonical pow2) batch,
        which run() then compiles as usual.  Pre-alignment shapes
        (screen + device-seed steps) warm through the same discipline
        so a long-pair wave's first screen books no inline compile."""
        pairs, _ = self._flatten(pairs)
        buckets: Dict[tuple, int] = defaultdict(int)
        screens: Dict[tuple, int] = defaultdict(int)
        seeds: Dict[tuple, int] = defaultdict(int)
        for pr in pairs:
            key = (bucket_len(len(pr.q), self.quant),
                   bucket_len(len(pr.t), self.quant))
            buckets[key] += 1
            if self._screens(pr) and not self._seeds_on_device(pr):
                screens[key] += 1
            if self._seeds_on_device(pr):
                seeds[key] += 1
        for kind, table in (("pair_fill", buckets),
                            ("sketch_screen", screens),
                            ("seed_device", seeds)):
            for (qmax, tmax), n in table.items():
                N = _z_bucket(n)
                key = (kind, qmax, tmax, N)
                build = functools.partial(self._warm_build, kind, qmax,
                                          tmax, N)
                if self._warmup is not None:
                    self._warmup.submit(key, build)
                elif key not in self._warmed:
                    self._warmed.add(key)
                    build()

    def _warm_build(self, kind, qmax, tmax, N) -> None:
        big = np.full((N, qmax + tmax), banded.PAD, np.uint8)
        if kind == "pair_fill":
            step = _pair_fill_packed(self.params, qmax, tmax)
            args = (big, np.zeros((N, 6), np.int32))
            group = f"pair:q{qmax}:t{tmax}"
        elif kind == "sketch_screen":
            from ccsx_tpu.ops import sketch as sketch_mod

            step = sketch_mod.screen_step(qmax, tmax)
            args = (big, np.zeros((N, 2), np.int32))
            group = f"sketch:q{qmax}:t{tmax}"
        else:
            from ccsx_tpu.ops import seed_device as sd_mod

            step = sd_mod.seed_step(qmax, tmax)
            args = (big, np.zeros((N, 2), np.int32))
            group = f"seed:q{qmax}:t{tmax}"
        with trace.device_span("warmup", group=group,
                               shape=f"N{N}", warmup=True):
            jax.block_until_ready(step(*args))

    def _seed_indexes(self, pairs):
        """Per-pair sorted template k-mer indexes for this batch: cache
        hits (token-keyed, LRU) cost nothing, misses are sorted in ONE
        vectorized argsort over the whole batch
        (seed.batch_sorted_indexes), and tokened misses enter the cache
        for the walk's next pairing of the same template."""
        from ccsx_tpu.ops import seed as seed_mod

        indexes: Dict[int, tuple] = {}
        need: List[int] = []          # pair idx needing a fresh sort
        need_owner: Dict[object, int] = {}  # token -> representative idx
        shared: List[tuple] = []      # (pair idx, token) cache/batch share
        for i, pr in enumerate(pairs):
            tok = getattr(pr, "t_token", None)
            if tok is not None:
                hit = self._seed_cache.get(tok)
                if hit is not None:
                    self._seed_cache.move_to_end(tok)
                    indexes[i] = hit
                    continue
                if tok in need_owner:
                    shared.append((i, tok))
                    continue
                need_owner[tok] = i
            need.append(i)
        if need:
            for i, idx in zip(need, seed_mod.batch_sorted_indexes(
                    [pairs[i].t for i in need])):
                indexes[i] = idx
                tok = getattr(pairs[i], "t_token", None)
                if tok is not None:
                    self._seed_cache[tok] = idx
                    while len(self._seed_cache) > self.seed_cache_max:
                        self._seed_cache.popitem(last=False)
        for i, tok in shared:
            indexes[i] = indexes[need_owner[tok]]
        return indexes

    def _pad_pair(self, pairs, idxs, key):
        """(N, qmax+tmax) PAD-filled codes + (N, 2) int32 lengths — the
        shared wire layout of the screen and device-seed dispatches
        (padded tails are inert by construction: PAD >= 4 makes every
        window touching them a bad k-mer, ops/sketch._codes_dev)."""
        qmax, tmax = key
        N = _z_bucket(len(idxs))
        big = np.full((N, qmax + tmax), banded.PAD, np.uint8)
        small = np.zeros((N, 2), np.int32)
        for z, i in enumerate(idxs):
            big[z, :qmax] = pad_to(pairs[i].q, qmax)
            big[z, qmax:] = pad_to(pairs[i].t, tmax)
            small[z, 0] = len(pairs[i].q)
            small[z, 1] = len(pairs[i].t)
        return big, small, N

    def _screen_wave(self, pairs, idxs, results) -> int:
        """The prefilter dispatch site: one batched sketch screen per
        (qmax, tmax) bucket over ``idxs``; rejected pairs get their
        final (False, empty MatchResult) — the same payload the walk
        discards for any failed pair — and the count is returned.
        Screen failures are ADVISORY (pair stays alive): the filter is
        an optimization, never a correctness gate."""
        from ccsx_tpu.ops import sketch as sketch_mod

        triples: List = [None] * len(pairs)
        groups: Dict[tuple, List[int]] = defaultdict(list)
        for i in idxs:
            groups[(bucket_len(len(pairs[i].q), self.quant),
                    bucket_len(len(pairs[i].t), self.quant))].append(i)

        def dispatch(gidxs, key):
            qmax, tmax = key
            big, small, N = self._pad_pair(pairs, gidxs, key)
            faultinject.fire("device_oom")
            if self._warmup is not None:
                ev = self._warmup.claim(("sketch_screen", qmax, tmax, N))
                if ev is not None:
                    ev.wait()
            step = sketch_mod.screen_step(qmax, tmax)
            with trace.device_span(
                    "sketch_screen", group=f"sketch:q{qmax}:t{tmax}",
                    shape=f"N{N}", n=len(gidxs)) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                return sp.force(step(big, small))

        def finish(gidxs, key, out):
            out = np.asarray(out)
            for z, i in enumerate(gidxs):
                triples[i] = tuple(int(v) for v in out[z])

        def host_one(i):
            return sketch_mod.screen_host(pairs[i].q, pairs[i].t)

        if self.metrics is not None:
            self.metrics.bump(device_dispatches=len(groups))
        _run_groups_recovering(
            groups, dispatch, finish, host_one, triples, self.metrics,
            label=lambda k: f"sketch:q{k[0]}:t{k[1]}", resil=self._resil)
        rejected = 0
        for i in idxs:
            tr = triples[i]
            if not isinstance(tr, tuple):
                continue   # screen failed for this pair: keep it alive
            pr = pairs[i]
            reason = sketch_mod.reject_reason(
                tr[0], tr[1], tr[2], len(pr.q), len(pr.t), pr.pct,
                self.params.band)
            if reason:
                results[i] = (False,
                              MatchResult(False, 0, 0, 0, 0, 0, 0, 0))
                rejected += 1
        return rejected

    def _seed_wave(self, pairs, idxs, hits, results) -> None:
        """The device k-mer seeding dispatch site: one batched seed per
        (qmax, tmax) bucket; rows fold back into ``hits`` as the same
        SeedHit-or-None the host path produces (bit-equal,
        ops/seed_device.py).  A pair whose seed failed on BOTH rungs
        carries its Exception into ``results`` — the per-request
        quarantine the pair-fill ladder already has."""
        from ccsx_tpu.ops import seed as seed_mod
        from ccsx_tpu.ops import seed_device as sd_mod

        rows: List = [None] * len(pairs)
        groups: Dict[tuple, List[int]] = defaultdict(list)
        for i in idxs:
            groups[(bucket_len(len(pairs[i].q), self.quant),
                    bucket_len(len(pairs[i].t), self.quant))].append(i)

        def dispatch(gidxs, key):
            qmax, tmax = key
            big, small, N = self._pad_pair(pairs, gidxs, key)
            faultinject.fire("device_oom")
            if self._warmup is not None:
                ev = self._warmup.claim(("seed_device", qmax, tmax, N))
                if ev is not None:
                    ev.wait()
            step = sd_mod.seed_step(qmax, tmax)
            with trace.device_span(
                    "seed_device", group=f"seed:q{qmax}:t{tmax}",
                    shape=f"N{N}", n=len(gidxs)) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                return sp.force(step(big, small))

        def finish(gidxs, key, out):
            out = np.asarray(out)
            for z, i in enumerate(gidxs):
                rows[i] = [int(v) for v in out[z]]

        def host_one(i):
            hit = seed_mod.seed_diagonal(pairs[i].q, pairs[i].t)
            if hit is None:
                return [0] * 8
            return [1, hit.diag, hit.votes, *(int(v) for v in hit.line),
                    0]

        if self.metrics is not None:
            self.metrics.bump(device_dispatches=len(groups))
        _run_groups_recovering(
            groups, dispatch, finish, host_one, rows, self.metrics,
            label=lambda k: f"seed:q{k[0]}:t{k[1]}", resil=self._resil)
        for i in idxs:
            r = rows[i]
            if isinstance(r, Exception):
                results[i] = r   # quarantines the calling hole
            elif r is not None:
                hits[i] = sd_mod.hit_from_row(r)

    def run(self, pairs):
        """Satisfy all pair requests; results align index-for-index —
        (ok, MatchResult) tuples for PairRequests (the strand_match
        contract), lists of them for PairBatch entries (the
        first-accept contract; speculative arms are all evaluated)."""
        flat, spans = self._flatten(pairs)
        results = self._run_flat(flat)
        out = []
        for start, n, is_batch in spans:
            out.append(list(results[start:start + n]) if is_batch
                       else results[start])
        return out

    def _run_flat(self, pairs: List["prep_mod.PairRequest"]):
        from ccsx_tpu.ops import seed as seed_mod

        results = [None] * len(pairs)
        groups: Dict[tuple, List[int]] = defaultdict(list)
        lines: Dict[int, np.ndarray] = {}

        # stage 1 — the batched device screen, but ONLY for big pairs
        # that will NOT device-seed: the seed dispatch (stage 2) is a
        # superset of the screen (its rows carry total+votes+the median
        # line), so a device-seeded pair gets the same rejection rules
        # for free in stage 3 (reject_from_hit) and a dedicated screen
        # wave would be a second dispatch computing the same hits.
        # Smaller pairs likewise ride their (host) seed statistics.
        from ccsx_tpu.ops import sketch as sketch_mod

        screen_ids = [i for i, pr in enumerate(pairs)
                      if self._screens(pr)
                      and not self._seeds_on_device(pr)]
        rejected = 0
        if screen_ids:
            with trace.span("prefilter", cat="prep", n=len(screen_ids)):
                rejected = self._screen_wave(pairs, screen_ids, results)

        # stage 2 — seeding for the survivors: device for long
        # templates (>= seed_device_min_t), cached host sort-join below
        hits: Dict[int, object] = {}
        dev_ids = [i for i, pr in enumerate(pairs)
                   if results[i] is None and self._seeds_on_device(pr)]
        dev_set = set(dev_ids)
        host_ids = [i for i, pr in enumerate(pairs)
                    if results[i] is None and i not in dev_set]
        sub = [pairs[i] for i in host_ids]
        seed_idx = self._seed_indexes(sub)
        for pos, i in enumerate(host_ids):
            hits[i] = seed_mod.seed_diagonal(pairs[i].q, pairs[i].t,
                                             t_index=seed_idx.get(pos))
        if dev_ids:
            self._seed_wave(pairs, dev_ids, hits, results)
        if self.metrics is not None and (dev_ids or host_ids):
            self.metrics.bump(pairs_seeded_device=len(dev_ids),
                              pairs_seeded_host=len(host_ids))

        # stage 3 — the zero-dispatch filter rung, then the banded fill
        # for every surviving pair.  Every prefilter-eligible pair that
        # did not go through the stage-1 screen — host-seeded pairs
        # above SCREEN_MIN_QT and ALL device-seeded pairs — gets rules
        # (b)/(c) from its seed statistics here (reject_from_hit, at
        # the true median line); stage-1-screened pairs were already
        # filtered pre-seeding and just pass through.
        screen_set = set(screen_ids)
        screened = len(screen_ids)
        for i, pr in enumerate(pairs):
            if results[i] is not None:
                continue
            hit = hits.get(i)
            if hit is None:
                # no shared 13-mers: unalignable at >=60% identity
                results[i] = (False, MatchResult(False, 0, 0, 0, 0, 0, 0, 0))
                continue
            if (self.prefilter and i not in screen_set
                    and min(len(pr.q), len(pr.t))
                    >= sketch_mod.SCREEN_MIN_QT):
                screened += 1
                if sketch_mod.reject_from_hit(hit, len(pr.q), len(pr.t),
                                              pr.pct, self.params.band):
                    results[i] = (False, MatchResult(False, 0, 0, 0, 0,
                                                     0, 0, 0))
                    rejected += 1
                    continue
            if abs(hit.diag) > self.params.band // 4:
                lines[i] = np.asarray(hit.line, np.int32)
            else:
                # near-diagonal: the default corner-to-corner line
                lines[i] = np.array(
                    [0, 0, len(pr.q), len(pr.t)], np.int32)
            groups[(bucket_len(len(pr.q), self.quant),
                    bucket_len(len(pr.t), self.quant))].append(i)

        if self.metrics is not None:
            padded = real = 0
            for (qmax, tmax), idxs in groups.items():
                N = _z_bucket(len(idxs))
                padded += N * qmax * self.params.band
                real += self.params.band * int(
                    sum(len(pairs[i].q) for i in idxs))
            # bump(): the pair gate's pump thread runs this concurrently
            # with the driver's refine sweeps (pipeline/prep_pool.py).
            # pairs_screened counts every pair the filter EXAMINED
            # (device screen + the zero-dispatch seed-statistics rung);
            # pairs_prefiltered the ones it rejected pre-DP.
            self.metrics.bump(pair_alignments=len(lines),
                              device_dispatches=len(groups),
                              pairs_screened=screened,
                              pairs_prefiltered=rejected,
                              dp_cells_padded=padded,
                              dp_cells_real=real)

        def dispatch(idxs, key):
            qmax, tmax = key
            N = _z_bucket(len(idxs))
            # PAD-filled so the dummy tail slots look exactly like the
            # old pad_to(empty) rows (qlen/tlen stay 0 in `small`)
            big = np.full((N, qmax + tmax), banded.PAD, np.uint8)
            small = np.zeros((N, 6), np.int32)
            for z, i in enumerate(idxs):
                big[z, :qmax] = pad_to(pairs[i].q, qmax)
                big[z, qmax:] = pad_to(pairs[i].t, tmax)
                small[z, 0] = len(pairs[i].q)
                small[z, 1] = len(pairs[i].t)
                small[z, 2:6] = lines[i]
            faultinject.fire("device_oom")
            if self._warmup is not None:
                # cancel a queued warmup of this shape / wait out an
                # in-flight one (same discipline as the refine path)
                ev = self._warmup.claim(("pair_fill", qmax, tmax, N))
                if ev is not None:
                    ev.wait()
            step = _pair_fill_packed(self.params, qmax, tmax)
            with trace.device_span(
                    "pair_fill", group=f"pair:q{qmax}:t{tmax}",
                    cells=N * qmax * self.params.band,
                    shape=f"N{N}", n=len(idxs)) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                return sp.force(step(big, small))

        def finish(idxs, key, res):
            res = np.asarray(res)
            for z, i in enumerate(idxs):
                score, qb, qe, tb, te, aln, mat = (
                    int(v) for v in res[z])
                rs = MatchResult(
                    ok=False, score=score, qb=qb,
                    qe=qe, tb=tb, te=te,
                    aln=aln, mat=mat)
                pr = pairs[i]
                # acceptance rule, main.c:280
                rs.ok = (rs.aln * 2 > min(len(pr.q), len(pr.t))) and (
                    rs.mat * 100 >= rs.aln * pr.pct)
                results[i] = (rs.ok, rs)

        def host_one(i):
            if self._host_aligner is None:
                from ccsx_tpu.consensus.align_host import HostAligner

                self._host_aligner = HostAligner(self.params)
            pr = pairs[i]
            return self._host_aligner.strand_match(pr.q, pr.t, pr.pct)

        _run_groups_recovering(groups, dispatch, finish, host_one,
                               results, self.metrics,
                               label=lambda k: f"pair:q{k[0]}:t{k[1]}",
                               resil=self._resil)
        return results


class BatchExecutor:
    """Groups refine/round requests by shape, one device dispatch per
    group (fused refinement for RefineRequests — the production window
    protocol — and a single star round for bare RoundRequests).

    With more than one local device, batches are laid out over a 1-D
    ``data`` mesh (ZMW axis sharded, SURVEY.md §5.8): the jitted round is
    pure vmap, so XLA partitions it across the chips of a slice with no
    cross-device traffic in the DP itself.

    Failure containment (per shape group; see classify_failure): a
    device OOM bisects the group and retries the halves at half the Z
    batch (with capped depth and exponential backoff) — memory pressure
    scales with Z, so one oversized bucket costs a resplit instead of
    the run; a Pallas lowering/compile failure pins the fill to the
    banded-scan spec (star.force_scan_fallback, one-time warning) and
    retries; anything else — and the bottom of both ladders — replays
    each request on the exact host path (bit-identical by the
    differential tests), with per-request host failures returned as
    Exception results the driver quarantines per hole.
    """

    # OOM resplit ladder: up to Z/8 before the per-request host replay
    max_oom_resplits = 3
    oom_backoff_s = 0.05

    def __init__(self, cfg: CcsConfig, metrics=None, warmup=None,
                 devices=None, resil=None):
        self.cfg = cfg
        self.len_quant = cfg.len_bucket_quant
        self.metrics = metrics
        # shared Resilience object (pipeline/resilience.py): dispatch
        # deadline + backend circuit breaker; None = legacy callers
        self._resil = resil
        # AOT warmup precompiler (pipeline/warmup.py), shared with the
        # driver's PairExecutor; None = --no-warmup / legacy callers
        self._warmup = warmup
        # host-replay spec for fused-refine overflows (rare): the exact
        # per-hole loop the fused step mirrors
        self._sm = StarMsa(cfg.align, cfg.max_ins_per_col,
                           cfg.len_bucket_quant)
        self._mesh = None
        # LOCAL devices only: hosts in a distributed run are share-nothing
        # (round-robin hole ownership, distributed.py), so each host's
        # mesh spans its own chips (ICI); a global mesh would make every
        # jit a cross-host SPMD program requiring identical inputs on all
        # processes.  Single-process: local == global, nothing changes.
        # ``devices`` narrows the set (tests pin the single-chip vs
        # multi-chip byte identity with it).
        self.slab_rows = pack_mod.pow2(max(1, cfg.slab_rows))
        self.slab_ladder = max(1, int(getattr(cfg, "slab_shape_ladder",
                                              pack_mod.DEFAULT_LADDER)))
        self._devices = (list(devices) if devices is not None
                         else jax.local_devices())
        self._shape_seen: set = set()  # distinct packed (R,q,t,i) shapes
        # warm_refine's per-group row accumulator: group -> (rows_seen
        # capped at budget, predicted canonical R, submitted warm key),
        # with each hole counted once per group (_group_holes)
        self._group_pred: Dict[tuple, tuple] = {}
        self._group_holes: Dict[tuple, set] = {}
        n_dev = len(self._devices)
        # ragged pass-packing (pipeline/pack.py) replaces the per-P
        # shape grouping for the production RefineRequest path, and
        # scales across local chips with ONE fused multi-chip dispatch
        # per group per wave (same-shape slabs stacked on a leading
        # device dim under a ('slab',) shard_map — see
        # _refine_step_packed_fused) instead of GSPMD-sharding one big
        # dispatch.  An explicit --mesh selects the bucketed
        # (Z, P)-sharded layout instead — packed slab rows cross hole
        # boundaries, which the (data, pass) shardings cannot express.
        # Output is byte-identical either way (tests/test_packing.py).
        # A single-device host genuinely IGNORES --mesh (as it always
        # has), so packing stays on there — "--mesh ignored" must not
        # silently mean "and the bucketed grouping took over".
        self._packing = bool(cfg.pass_packing) and (
            cfg.mesh_shape is None or n_dev == 1)
        self._slab_mesh = None
        if self._packing and n_dev > 1:
            from ccsx_tpu.parallel.mesh import build_slab_mesh

            self._slab_mesh = build_slab_mesh(self._devices)
        if cfg.pass_packing and cfg.mesh_shape is not None and n_dev > 1:
            print("[ccsx-tpu] pass packing disabled under --mesh "
                  "(bucketed (Z, P) grouping carries the shardings)",
                  file=sys.stderr)
        if n_dev > 1 and not self._packing:
            # (data, pass) mesh: ZMWs shard over 'data'; MSA rows of each
            # hole shard over 'pass' when the pass bucket divides (GSPMD
            # partitions the jitted round from the input shardings alone —
            # the vote's column reductions become psums over 'pass', the
            # same collectives tests/test_sharded_round.py pins bit-exact).
            # cfg.mesh_shape overrides the default pure-data split; a
            # 1-tuple means pure data parallelism; extra devices idle.
            shape = self.validate_mesh(cfg.mesh_shape, n_dev)
            ndev_used = int(np.prod(shape))
            from ccsx_tpu.parallel.mesh import build_mesh

            self._mesh = build_mesh(shape=shape,
                                    devices=self._devices[:ndev_used])
            self._data_dim, self._pass_dim = shape
            if (self._pass_dim > 1
                    and all(b % self._pass_dim for b in cfg.pass_buckets)):
                print(f"[ccsx-tpu] mesh pass dim {self._pass_dim} divides "
                      f"no pass bucket {tuple(cfg.pass_buckets)}: pass "
                      "axis will be replicated (no pass parallelism)",
                      file=sys.stderr)
        elif cfg.mesh_shape is not None:
            print("[ccsx-tpu] --mesh ignored: single device visible",
                  file=sys.stderr)

    @staticmethod
    def normalize_mesh_shape(shape, n_dev: int):
        if shape is None:
            return (n_dev, 1)
        shape = tuple(int(x) for x in shape)
        if len(shape) == 1:
            shape = (shape[0], 1)
        if len(shape) != 2:
            raise ValueError(f"mesh_shape must be (data,) or (data, pass), "
                             f"got {shape}")
        if min(shape) < 1:
            raise ValueError(f"mesh dims must be >= 1: {shape}")
        return shape

    @classmethod
    def validate_mesh(cls, mesh_shape, n_dev: int):
        """Normalize + feasibility-check a mesh shape; ValueError on a
        bad one.  THE single validation point — __init__ and both
        pipeline drivers call this (before any output file opens)."""
        shape = cls.normalize_mesh_shape(mesh_shape, n_dev)
        need = int(np.prod(shape))
        if n_dev > 1 and need > n_dev:
            raise ValueError(
                f"mesh {shape} needs {need} devices, host has {n_dev}")
        return shape

    def _bp_consts(self):
        cfg = self.cfg
        return (cfg.bp_window, cfg.bp_minwin, cfg.bp_rowrate,
                cfg.bp_colrate, cfg.bp_colrate_lowpass)

    def _shard_args(self, args, P: int):
        """device_put the 5 round/refine inputs with the (data, pass)
        NamedShardings (GSPMD partitions the jitted step from these)."""
        if self._mesh is None:
            return args
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as PS

        # replicate the pass axis when the bucket doesn't divide
        pax = "pass" if P % self._pass_dim == 0 else None
        specs = (PS("data", pax, None), PS("data", pax),
                 PS("data", None), PS("data"), PS("data", pax))
        return tuple(jax.device_put(a, NamedSharding(self._mesh, s))
                     for a, s in zip(args, specs))

    def _round_z(self, n: int) -> int:
        Z = _z_bucket(n)
        if self._mesh is not None:
            # the data-axis sharding needs Z divisible by the data
            # dimension (power-of-two Z alone is not enough when it
            # isn't a power of two, e.g. 6 or 12 devices)
            Z = -(-Z // self._data_dim) * self._data_dim
        return Z

    def _count_cells(self, reqs, idxs, P, qmax, Z, iters: int = 1):
        """Padding accounting (metrics.dp_cells_*): real DP fill cells
        (true qlen of real pass-rows) vs dispatched cells (the full
        Z x P x qmax x band x iters block).  The ratio is the device
        occupancy that bucket tuning (pass/length/Z buckets) controls —
        SURVEY §7.3 item 2's named throughput risk, now measured."""
        if self.metrics is None:
            return
        band = self.cfg.align.band
        padded = Z * P * qmax * band * iters
        real = band * iters * int(
            sum(int(reqs[i].qlens[reqs[i].row_mask].sum()) for i in idxs))
        # round-only counters, all in CELL units (x qmax x band x iters)
        # so the length/pass/Z factorization is exact in aggregate
        # across heterogeneous shape groups (metrics.py); bump() — the
        # pair gate's pump thread updates the shared dp_cells_* family
        # concurrently (pipeline/prep_pool.py)
        rows_real = int(sum(int(reqs[i].row_mask.sum()) for i in idxs))
        scale = qmax * band * iters
        self.metrics.bump(dp_cells_padded=padded, dp_cells_real=real,
                          dp_round_cells_padded=padded,
                          dp_round_cells_real=real,
                          dp_rowcells_real=rows_real * scale,
                          dp_rowcells_cap=len(idxs) * P * scale)

    def _count_cells_packed(self, reqs, idxs, qmax: int, R: int,
                            iters: int):
        """Padding accounting for one packed slab.  The slab IS the
        dispatch (no Z axis), so rowcells_cap == round_cells_padded and
        the factorized identity degenerates to z_fill = 1 with pass_fill
        carrying the whole row-fill story; dp_rows_* feed the
        dp_row_fill / packed_holes_per_dispatch counters the packing win
        is read from (metrics.py)."""
        if self.metrics is None:
            return
        band = self.cfg.align.band
        scale = qmax * band * iters
        rows_real = int(sum(int(reqs[i].row_mask.sum()) for i in idxs))
        real = band * iters * int(
            sum(int(reqs[i].qlens[reqs[i].row_mask].sum()) for i in idxs))
        self.metrics.bump(dp_cells_padded=R * scale, dp_cells_real=real,
                          dp_round_cells_padded=R * scale,
                          dp_round_cells_real=real,
                          dp_rowcells_real=rows_real * scale,
                          dp_rowcells_cap=R * scale,
                          dp_rows_real=rows_real, dp_rows_dispatched=R,
                          packed_dispatches=1, packed_holes=len(idxs))

    def _count_cells_packed_fused(self, reqs, idxs, qmax: int, iters: int,
                                  R: int, n_slabs: int, n_slots: int):
        """Padding accounting for one fused multi-chip WAVE (n_slabs
        real slabs at uniform R, padded with dummy slabs to n_slots
        chip-slots).  Dummy slabs freeze every segment at iteration 0 —
        their chips idle rather than fill padding — so dispatched DP
        cells count the REAL slabs only and the dummy-slot idleness is
        read from fused_slot_fill instead of dp_row_fill."""
        if self.metrics is None:
            return
        band = self.cfg.align.band
        scale = qmax * band * iters
        rows_real = int(sum(int(reqs[i].row_mask.sum()) for i in idxs))
        real = band * iters * int(
            sum(int(reqs[i].qlens[reqs[i].row_mask].sum()) for i in idxs))
        padded = n_slabs * R * scale
        self.metrics.bump(dp_cells_padded=padded, dp_cells_real=real,
                          dp_round_cells_padded=padded,
                          dp_round_cells_real=real,
                          dp_rowcells_real=rows_real * scale,
                          dp_rowcells_cap=n_slabs * R * scale,
                          dp_rows_real=rows_real,
                          dp_rows_dispatched=n_slabs * R,
                          packed_dispatches=1, packed_holes=len(idxs),
                          fused_waves=1, fused_slabs_real=n_slabs,
                          fused_slots=n_slots)

    # ---- AOT warmup (pipeline/warmup.py): predict + precompile the
    # ---- canonical packed executables concurrently with ingest/prep ----

    def _warm_key(self, qmax, tmax, iters, R, dstack):
        return ("refine_packed", qmax, tmax, iters, R, dstack)

    def _warm_wait(self, key) -> None:
        """Dispatch-side sync: cancel a still-queued warmup of this
        shape (we compile inline, as without warmup) or wait out an
        in-flight one (the compile is already running on the warmup
        thread; waiting avoids a duplicate).  The builder's finally
        guarantees the event fires."""
        if self._warmup is not None:
            ev = self._warmup.claim(key)
            if ev is not None:
                ev.wait()

    def _note_shape(self, R, qmax, tmax, iters) -> None:
        key = (R, qmax, tmax, iters)
        if key not in self._shape_seen:
            self._shape_seen.add(key)
            if self.metrics is not None:
                self.metrics.distinct_slab_shapes = len(self._shape_seen)

    def warm_refine(self, req: RefineRequest, hole_id=None) -> None:
        """Enqueue an AOT compile for the canonical executable this
        request's (qmax, tmax, iters) group is predicted to need —
        called by the driver the moment prep yields the request, so
        cold XLA compiles overlap ingest/prep instead of stalling the
        group's first dispatch.

        The predicted R is the smallest canonical height covering the
        group's ACCUMULATED predicted rows (capped at the budget — the
        steady-state shape): warming every ladder height would book
        compiles for programs never dispatched, which is exactly the
        waste the canonical ladder exists to kill.  When accumulation
        pushes the prediction up a height, the stale queued warm is
        CANCELLED (WarmupCompiler.claim) — during an admission burst
        the queue usually hasn't reached it yet, so most groups build
        exactly one program.  No-op without a warmup compiler, under
        --pass-buckets bucketed grouping or a GSPMD --mesh (their Z
        bucket depends on the sweep size, unknowable at admission —
        canonical slab shapes are what make the packed path
        predictable)."""
        if self._warmup is None or not self._packing:
            return
        qmax = req.qs.shape[1]
        tmax = _fused_tmax(len(req.draft), self.len_quant)
        gk = (qmax, tmax, req.iters)
        rows = max(int(req.row_mask.sum()), pack_mod.SEG_DIV)
        acc, old_r, old_key = self._group_pred.get(gk, (0, None, None))
        # each hole counts ONCE per group: the driver re-warms every
        # still-active hole after every sweep (a hole's next window is
        # a fresh request), and re-adding the same hole's rows each
        # sweep would walk a one-hole group's prediction up to the full
        # budget — warming (and possibly cancelling/churning) programs
        # its slabs never reach.  A hole entering a NEW group (its
        # draft grew a bucket) legitimately counts there too.
        if hole_id is not None:
            seen = self._group_holes.setdefault(gk, set())
            if hole_id in seen:
                return
            seen.add(hole_id)
        acc = min(acc + rows, self.slab_rows)
        R = self.slab_rows
        for h in pack_mod.canonical_heights(self.slab_rows,
                                            self.slab_ladder):
            if h >= acc:
                R = h
            else:
                break
        dstack = (len(self._devices)
                  if self._slab_mesh is not None else 1)
        key = old_key
        if R != old_r:
            if old_key is not None:
                self._warmup.claim(old_key)  # cancel the stale warm
            H = max(1, R // pack_mod.SEG_DIV)
            key = self._warm_key(qmax, tmax, req.iters, R, dstack)
            self._warmup.submit(
                key, functools.partial(self._warm_build, qmax, tmax,
                                       req.iters, R, H, dstack))
        if acc >= self.slab_rows:
            # a group that fills its row budget lives long enough to
            # DRIBBLE: late in the run the admission batch's windows
            # finish in near-lockstep, sweeps shrink, and the group's
            # tail waves snap to the lower canonical heights — each a
            # fresh executable.  Warm those now (r08 scale trace:
            # every group that crossed the budget later dispatched at
            # budget/2), so the endgame transition books no inline
            # compile.  Sweep-time warming cannot catch these — the
            # dribble wave is planned microseconds before its own
            # dispatch claims the key back.  Submit dedupes by key.
            for h in pack_mod.canonical_heights(self.slab_rows,
                                                self.slab_ladder):
                if h != R:
                    hH = max(1, h // pack_mod.SEG_DIV)
                    self._warmup.submit(
                        self._warm_key(qmax, tmax, req.iters, h, dstack),
                        functools.partial(self._warm_build, qmax, tmax,
                                          req.iters, h, hH, dstack))
        self._group_pred[gk] = (acc, R, key)

    def _warm_sweep_shapes(self, shapes) -> None:
        """Sweep-time exact warming: by group-construction time the
        sweep's slab plans are known EXACTLY, so submit any shape not
        yet compiled before the dispatch-all loop starts — the warmup
        thread then builds upcoming shapes (late-run dribble waves at
        the lower canonical heights, mostly) while earlier groups
        dispatch.  Unlike admission-time prediction this can never
        build a program that is not about to be used; a shape whose
        build has not started when its own dispatch arrives is claimed
        back and compiled inline, exactly as without warmup."""
        if self._warmup is None:
            return
        for qmax, tmax, iters, R, dstack in shapes:
            H = max(1, R // pack_mod.SEG_DIV)
            self._warmup.submit(
                self._warm_key(qmax, tmax, iters, R, dstack),
                functools.partial(self._warm_build, qmax, tmax, iters,
                                  R, H, dstack),
                urgent=True)

    def _warm_build(self, qmax, tmax, iters, R, H, dstack) -> None:
        """Warmup-thread builder: run the REAL jitted step on an all-
        zero slab and block — the zero row mask freezes every segment,
        so the while_loop exits at iteration 0 and the execution costs
        ~a breakpoint scan; what it buys is the exact jit fast path
        primed (fn.lower().compile() shares the XLA compile but leaves
        a retrace + dispatch-cache miss on the first real call, which
        would then book as execute time).  The warmup=True span books
        the (group, shape)'s compile, so the first real dispatch books
        as execute — the trace-visible proof the overlap worked."""
        cfg = self.cfg
        Lbig, Lsmall = _slab_wire_sizes(R, qmax, H, tmax,
                                        cfg.max_ins_per_col)
        # same :b<impl> suffix as the real dispatch's span — the warmup
        # compile and the first execute must book under ONE group key or
        # the compile-storm accounting splits across two rows
        group = (f"packed:q{qmax}:t{tmax}:i{iters}"
                 f":b{banded_impl_effective(qmax)}")
        if dstack > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PS

            step = _refine_step_packed_fused(
                cfg.align, cfg.max_ins_per_col, tmax, iters, H,
                self._bp_consts(), (R, qmax), self._slab_mesh)
            sharding = NamedSharding(self._slab_mesh, PS("slab", None))
            with trace.device_span("warmup", group=group,
                                   shape=f"D{dstack}:R{R}:S{H}",
                                   warmup=True):
                big = jax.device_put(
                    np.zeros((dstack, Lbig), np.uint8), sharding)
                small = jax.device_put(
                    np.zeros((dstack, Lsmall), np.int32), sharding)
                jax.block_until_ready(step(big, small))
        else:
            step = _refine_step_packed(
                cfg.align, cfg.max_ins_per_col, tmax, iters, H,
                self._bp_consts(), pack=(R, qmax))
            with trace.device_span("warmup", group=group,
                                   shape=f"R{R}:S{H}", warmup=True):
                jax.block_until_ready(step(np.zeros(Lbig, np.uint8),
                                           np.zeros(Lsmall, np.int32)))

    def _stack_slab(self, reqs, idxs, qmax, tmax, shape=None):
        """Pack the real pass-rows of the given requests into ONE slab:
        (R, qmax) rows + (H, tmax) per-hole drafts + the row->hole
        segment vector.  Row order is idxs order (the packing plan's
        placement order — or a bisected half of it on the OOM-resplit
        ladder, which re-packs at the smaller covering canonical
        slab).  ``shape`` forces (R, H) — the fused multi-chip path
        stacks every slab of a wave at the wave's uniform shape."""
        rows = [int(reqs[i].row_mask.sum()) for i in idxs]
        R, H = shape if shape is not None else pack_mod.slab_shape(
            rows, self.slab_rows, ladder=self.slab_ladder)
        qs = np.zeros((R, qmax), np.uint8)
        qlens = np.zeros((R,), np.int32)
        row_mask = np.zeros((R,), bool)
        seg = pack_mod.segment_ids(rows, R)
        # empty hole slots: 1-col no-op drafts, like pad holes in
        # _stack_group (pad rows gather a real slot's draft and are
        # masked, so these are only ever the while_loop's frozen slots)
        ts = np.full((H, tmax), banded.PAD, np.uint8)
        ts[:, 0] = 0
        tlens = np.ones((H,), np.int32)
        r0 = 0
        for s, i in enumerate(idxs):
            req = reqs[i]
            m = req.row_mask
            n = rows[s]
            qs[r0:r0 + n] = req.qs[m]
            qlens[r0:r0 + n] = req.qlens[m]
            row_mask[r0:r0 + n] = True
            ts[s] = pad_to(req.draft, tmax)
            tlens[s] = len(req.draft)
            r0 += n
        return qs, qlens, row_mask, seg, ts, tlens

    def _stack_group(self, reqs, idxs, P, qmax, tmax):
        """Pad + stack a shape group's requests into device inputs."""
        Z = self._round_z(len(idxs))
        qs = np.zeros((Z, P, qmax), np.uint8)
        qlens = np.zeros((Z, P), np.int32)
        ts = np.full((Z, tmax), banded.PAD, np.uint8)
        ts[:, 0] = 0                     # pad holes: 1-col no-op drafts
        tlens = np.ones((Z,), np.int32)
        row_mask = np.zeros((Z, P), bool)
        for z, i in enumerate(idxs):
            req = reqs[i]
            qs[z] = req.qs
            qlens[z] = req.qlens
            ts[z] = pad_to(req.draft, tmax)
            tlens[z] = len(req.draft)
            row_mask[z] = req.row_mask
        return qs, qlens, ts, tlens, row_mask

    def run(self, requests) -> list:
        """Satisfy all requests (RefineRequest — the production window
        protocol — and/or bare RoundRequest); results align
        index-for-index (RefineResult / RoundResult respectively)."""
        results: List[object] = [None] * len(requests)
        refine = [i for i, r in enumerate(requests)
                  if isinstance(r, RefineRequest)]
        rounds = [i for i, r in enumerate(requests)
                  if not isinstance(r, RefineRequest)]
        if refine:
            for i, res in zip(refine,
                              self._run_refine([requests[i]
                                                for i in refine])):
                results[i] = res
        if rounds:
            for i, res in zip(rounds,
                              self._run_rounds([requests[i]
                                                for i in rounds])):
                results[i] = res
        return results

    def _run_groups(self, groups, dispatch, finish, host_one, results,
                    label=str):
        _run_groups_recovering(groups, dispatch, finish, host_one,
                               results, self.metrics,
                               self.max_oom_resplits, self.oom_backoff_s,
                               label=label, resil=self._resil)

    def _run_rounds(self, requests: List[RoundRequest]) -> List[RoundResult]:
        cfg = self.cfg
        groups: Dict[tuple, List[int]] = defaultdict(list)
        for i, req in enumerate(requests):
            P, qmax = req.qs.shape
            tmax = bucket_len(len(req.draft), self.len_quant)
            groups[(P, qmax, tmax)].append(i)

        results: List[Optional[RoundResult]] = [None] * len(requests)
        if self.metrics is not None:
            # bare rounds (legacy/test path) count as dispatches only —
            # 'windows' counts RefineRequests (one per window attempt)
            self.metrics.bump(device_dispatches=len(groups))

        def dispatch(idxs, key):
            P, qmax, tmax = key
            args = self._stack_group(requests, idxs, P, qmax, tmax)
            faultinject.fire("device_oom")
            Z = self._round_z(len(idxs))
            # :b<impl> suffix + labeled counter: per-implementation
            # dispatch attribution (scan / pallas / rotband), resolved
            # at dispatch time so a compile-forced scan pin shows up
            bimpl = banded_impl_effective(qmax)
            if self.metrics is not None:
                self.metrics.bump_banded(bimpl)
            with trace.device_span(
                    "round", group=f"round:P{P}:q{qmax}:t{tmax}:b{bimpl}",
                    cells=Z * P * qmax * cfg.align.band,
                    shape=f"Z{Z}", n=len(idxs), Z=Z) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                if self._mesh is None:
                    # packed single-device transfers, as in _run_refine
                    step = _round_step(cfg.align, cfg.max_ins_per_col,
                                       tmax, self._bp_consts(),
                                       pack=(P, qmax))
                    return sp.force(step(*_pack_args(args)))
                step = _round_step(cfg.align, cfg.max_ins_per_col, tmax,
                                   self._bp_consts())
                return sp.force(step(*self._shard_args(args, P)))

        def finish(idxs, key, out):
            P, qmax, tmax = key
            out = tuple(np.asarray(o) for o in out)
            if self._mesh is None:
                (cons, ins_base, ins_votes, ncov, nwin, bp,
                 advance) = _unpack_round(
                    out[0], out[1], cfg.max_ins_per_col, tmax)
            else:
                (cons, ins_base, ins_votes, ncov, nwin, bp, advance) = out
            for z, i in enumerate(idxs):
                results[i] = RoundResult(
                    cons=cons[z], ins_base=ins_base[z],
                    ins_votes=ins_votes[z], ncov=ncov[z], nwin=nwin[z],
                    tlen=len(requests[i].draft),
                    bp=int(bp[z]), advance=advance[z],
                )

        def host_one(i):
            req = requests[i]
            return self._sm.round(req.qs, req.qlens, req.row_mask,
                                  req.draft)

        for (P, qmax, tmax), idxs in groups.items():
            self._count_cells(requests, idxs, P, qmax,
                              self._round_z(len(idxs)))
        self._run_groups(
            groups, dispatch, finish, host_one, results,
            label=lambda k: (f"round:P{k[0]}:q{k[1]}:t{k[2]}"
                             f":b{banded_impl_effective(k[1])}"))
        return results

    def _run_refine(self, requests: List[RefineRequest]) -> List[RefineResult]:
        """One fused device dispatch per shape group for whole-window
        refinement loops (see _refine_step).  A hole whose speculative
        draft outgrows the fused capacity (_fused_tmax) is replayed
        exactly on the host — the overflow flag makes the fallback
        bit-faithful, and the counter records how rare it is."""
        if self._packing:
            return self._run_refine_packed(requests)
        cfg = self.cfg
        groups: Dict[tuple, List[int]] = defaultdict(list)
        for i, req in enumerate(requests):
            P, qmax = req.qs.shape
            tmax = _fused_tmax(len(req.draft), self.len_quant)
            groups[(P, qmax, tmax, req.iters)].append(i)

        results: List[Optional[RefineResult]] = [None] * len(requests)
        if self.metrics is not None:
            self.metrics.bump(windows=len(requests),
                              device_dispatches=len(groups))

        def dispatch(idxs, key):
            P, qmax, tmax, iters = key
            args = self._stack_group(requests, idxs, P, qmax, tmax)
            faultinject.fire("device_oom")
            Z = self._round_z(len(idxs))
            bimpl = banded_impl_effective(qmax)
            if self.metrics is not None:
                self.metrics.bump_banded(bimpl)
            with trace.device_span(
                    "refine",
                    group=f"refine:P{P}:q{qmax}:t{tmax}:i{iters}:b{bimpl}",
                    cells=Z * P * qmax * cfg.align.band * iters,
                    shape=f"Z{Z}", n=len(idxs), Z=Z) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                if self._mesh is None:
                    # single device: packed transfer protocol (2 h2d +
                    # 2 d2h latencies per dispatch instead of 5 + 9)
                    step = _refine_step(cfg.align, cfg.max_ins_per_col,
                                        tmax, iters, self._bp_consts(),
                                        pack=(P, qmax))
                    return sp.force(step(*_pack_args(args)))
                step = _refine_step(cfg.align, cfg.max_ins_per_col, tmax,
                                    iters, self._bp_consts())
                return sp.force(step(*self._shard_args(args, P)))

        def finish(idxs, key, out):
            P, qmax, tmax, iters = key
            out = tuple(np.asarray(o) for o in out)
            if self._mesh is None:
                (cons, ins_base, ins_votes, ncov, nwin, bp, advance,
                 dlen, ovf) = _unpack_refine(
                    out[0], out[1], cfg.max_ins_per_col, tmax)
            else:
                (cons, ins_base, ins_votes, ncov, nwin, bp, advance,
                 dlen, ovf) = out
            for z, i in enumerate(idxs):
                req = requests[i]
                if ovf[z]:
                    if self.metrics is not None:
                        self.metrics.bump(refine_overflows=1)
                    with trace.span("host_replay", cat="recover",
                                    reason="refine_overflow"):
                        results[i] = host_one(i)
                    continue
                rr = RoundResult(
                    cons=cons[z], ins_base=ins_base[z],
                    ins_votes=ins_votes[z], ncov=ncov[z], nwin=nwin[z],
                    tlen=int(dlen[z]), bp=int(bp[z]), advance=advance[z],
                )
                results[i] = RefineResult(rr=rr)

        def host_one(i):
            req = requests[i]
            return refine_host(self._sm.round, req.qs, req.qlens,
                               req.row_mask, req.draft, req.iters)

        for (P, qmax, tmax, iters), idxs in groups.items():
            self._count_cells(requests, idxs, P, qmax,
                              self._round_z(len(idxs)), iters)
        self._run_groups(
            groups, dispatch, finish, host_one, results,
            label=lambda k: (f"refine:P{k[0]}:q{k[1]}:t{k[2]}:i{k[3]}"
                             f":b{banded_impl_effective(k[1])}"))
        return results

    def _run_refine_packed(
            self, requests: List[RefineRequest]) -> List[RefineResult]:
        """Ragged pass-packed refinement: requests group only by
        (qmax, tmax, iters) — the pass dimension is packed away — and
        each group's (hole, pass) rows are laid into fixed (R, qmax)
        slabs first-fit-decreasing by hole (pipeline/pack.py), one fused
        dispatch per slab.  The recovery ladder is inherited unchanged:
        a slab's idxs are its HOLES, so the OOM rung bisects by hole and
        each half re-packs into a smaller covering slab, and the ladder
        bottom replays per hole on refine_host, exactly as the bucketed
        path does."""
        cfg = self.cfg
        nrows = [int(r.row_mask.sum()) for r in requests]
        results: List[Optional[RefineResult]] = [None] * len(requests)
        if self.metrics is not None:
            self.metrics.bump(windows=len(requests))

        def host_one(i):
            req = requests[i]
            return refine_host(self._sm.round, req.qs, req.qlens,
                               req.row_mask, req.draft, req.iters)

        shape_groups: Dict[tuple, List[int]] = defaultdict(list)
        for i, req in enumerate(requests):
            if nrows[i] == 0:
                # a request with no live pass-rows (degenerate; the
                # windowed driver never produces one) has no rows to
                # pack — the host path is its spec
                if self.metrics is not None:
                    self.metrics.bump(host_fallbacks=1)
                try:
                    with trace.span("host_replay", cat="recover",
                                    reason="no_rows"):
                        results[i] = host_one(i)
                except Exception as e:  # quarantined per hole
                    results[i] = e
                continue
            qmax = req.qs.shape[1]
            tmax = _fused_tmax(len(req.draft), self.len_quant)
            shape_groups[(qmax, tmax, req.iters)].append(i)

        # one fused multi-chip dispatch per group per WAVE when >1 local
        # device: D consecutive slabs of the plan stack on a leading
        # device dim and run as ONE executable call over the ('slab',)
        # mesh (_refine_step_packed_fused) — one transfer + one dispatch
        # where the r7 round-robin issued one of each per slab per chip
        # (and compiled one executable per chip).  Single device: one
        # dispatch per slab, as before.  A wave (or slab) is also the
        # recovery unit: its idxs are its HOLES, so the OOM rung bisects
        # by hole and each half re-plans at the smaller covering
        # canonical slab.
        D = len(self._devices)
        fused = self._slab_mesh is not None

        def _plan_wave(idxs):
            """Deterministic (plan, R, H) for a wave's holes — dispatch
            and finish both re-derive it, so OOM-bisected halves stay
            self-consistent.  All slabs of a wave share the wave's
            largest canonical R (one executable per wave)."""
            rows = [nrows[i] for i in idxs]
            plan = pack_mod.plan_slabs(rows, self.slab_rows)
            R = max(pack_mod.slab_shape([rows[j] for j in s],
                                        self.slab_rows,
                                        ladder=self.slab_ladder)[0]
                    for s in plan)
            return plan, R, max(1, R // pack_mod.SEG_DIV)

        groups: Dict[tuple, List[int]] = {}
        sweep_shapes = set()
        for key, idxs in shape_groups.items():
            slabs = pack_mod.plan_slabs([nrows[i] for i in idxs],
                                        self.slab_rows)
            if fused:
                for w in range(0, len(slabs), D):
                    chunk = slabs[w:w + D]
                    wave = [idxs[j] for s in chunk for j in s]
                    wkey = key + (w // D,)
                    groups[wkey] = wave
                    _, R, _ = _plan_wave(wave)
                    sweep_shapes.add(key + (R, D))
                    self._count_cells_packed_fused(
                        requests, wave, key[0], key[2], R,
                        len(chunk), D)
            else:
                for s_no, slab in enumerate(slabs):
                    sl_idxs = [idxs[j] for j in slab]
                    groups[key + (s_no,)] = sl_idxs
                    R, _ = pack_mod.slab_shape(
                        [nrows[i] for i in sl_idxs], self.slab_rows,
                        ladder=self.slab_ladder)
                    sweep_shapes.add(key + (R, 1))
                    self._count_cells_packed(requests, sl_idxs, key[0],
                                             R, key[2])
        self._warm_sweep_shapes(sweep_shapes)

        if self.metrics is not None:
            self.metrics.bump(device_dispatches=len(groups))

        def dispatch(idxs, key):
            qmax, tmax, iters, _ = key
            faultinject.fire("device_oom")
            band = cfg.align.band
            bimpl = banded_impl_effective(qmax)
            if self.metrics is not None:
                self.metrics.bump_banded(bimpl)
            if not fused:
                args = self._stack_slab(requests, idxs, qmax, tmax)
                R = args[0].shape[0]
                H = args[4].shape[0]
                big, small = _pack_slab_args(args, cfg.max_ins_per_col)
                self._warm_wait(self._warm_key(qmax, tmax, iters, R, 1))
                self._note_shape(R, qmax, tmax, iters)
                step = _refine_step_packed(
                    cfg.align, cfg.max_ins_per_col, tmax, iters, H,
                    self._bp_consts(), pack=(R, qmax))
                with trace.device_span(
                        "refine_packed",
                        group=f"packed:q{qmax}:t{tmax}:i{iters}:b{bimpl}",
                        cells=R * qmax * band * iters,
                        shape=f"R{R}:S{H}",
                        plan={"slab": key[3], "rows": R,
                              "holes": len(idxs)}) as sp:
                    faultinject.fire("stall")
                    faultinject.fire("device_hang")
                    return sp.force(step(big, small))
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PS

            plan, R, H = _plan_wave(idxs)
            # an OOM-resplit re-plan can exceed D slabs; K > 1 then
            # carries K slabs per chip — still one executable call
            K = -(-len(plan) // D)
            Lbig, Lsmall = _slab_wire_sizes(R, qmax, H, tmax,
                                            cfg.max_ins_per_col)
            bigs = np.zeros((K * D, Lbig), np.uint8)
            smalls = np.zeros((K * D, Lsmall), np.int32)
            for d, s in enumerate(plan):
                args = self._stack_slab(requests, [idxs[j] for j in s],
                                        qmax, tmax, shape=(R, H))
                bigs[d], smalls[d] = _pack_slab_args(
                    args, cfg.max_ins_per_col)
            # dummy tail slabs stay all-zero: an empty row mask freezes
            # every segment, so that chip exits the while_loop at
            # iteration 0
            self._warm_wait(self._warm_key(qmax, tmax, iters, R, K * D))
            self._note_shape(R, qmax, tmax, iters)
            step = _refine_step_packed_fused(
                cfg.align, cfg.max_ins_per_col, tmax, iters, H,
                self._bp_consts(), (R, qmax), self._slab_mesh)
            sharding = NamedSharding(self._slab_mesh, PS("slab", None))
            with trace.device_span(
                    "refine_packed",
                    group=f"packed:q{qmax}:t{tmax}:i{iters}:b{bimpl}",
                    cells=len(plan) * R * qmax * band * iters,
                    shape=f"D{K * D}:R{R}:S{H}",
                    plan={"wave": key[3], "slabs": len(plan),
                          "chips": D, "rows": R,
                          "holes": len(idxs)}) as sp:
                faultinject.fire("stall")
                faultinject.fire("device_hang")
                big = jax.device_put(bigs, sharding)
                small = jax.device_put(smalls, sharding)
                return sp.force(step(big, small))

        def _finish_slab(sl_idxs, tmax, big, small, R, H):
            (cons, ins_base, ins_votes, ncov, nwin, bp, advance, dlen,
             ovf) = _unpack_slab_refine(big, small,
                                        cfg.max_ins_per_col, tmax, H, R)
            r0 = 0
            for s, i in enumerate(sl_idxs):
                req = requests[i]
                n = nrows[i]
                rows = slice(r0, r0 + n)
                r0 += n
                if ovf[s]:
                    if self.metrics is not None:
                        self.metrics.bump(refine_overflows=1)
                    with trace.span("host_replay", cat="recover",
                                    reason="refine_overflow"):
                        results[i] = host_one(i)
                    continue
                # scatter row advances back into the request's (P,)
                # pass order; masked pass rows consumed nothing — the
                # same 0 the fixed-P device path computes for them
                adv = np.zeros(req.qs.shape[0], np.int32)
                adv[req.row_mask] = advance[rows]
                rr = RoundResult(
                    cons=cons[s], ins_base=ins_base[s],
                    ins_votes=ins_votes[s], ncov=ncov[s], nwin=nwin[s],
                    tlen=int(dlen[s]), bp=int(bp[s]), advance=adv,
                )
                results[i] = RefineResult(rr=rr)

        def finish(idxs, key, out):
            qmax, tmax, iters, _ = key
            big, small = np.asarray(out[0]), np.asarray(out[1])
            if not fused:
                R, H = pack_mod.slab_shape(
                    [nrows[i] for i in idxs], self.slab_rows,
                    ladder=self.slab_ladder)
                _finish_slab(idxs, tmax, big, small, R, H)
                return
            plan, R, H = _plan_wave(idxs)
            for d, s in enumerate(plan):
                _finish_slab([idxs[j] for j in s], tmax,
                             big[d], small[d], R, H)

        self._run_groups(
            groups, dispatch, finish, host_one, results,
            label=lambda k: (f"packed:q{k[0]}:t{k[1]}:i{k[2]}"
                             f":b{banded_impl_effective(k[0])}"))
        return results


@dataclasses.dataclass
class _Hole:
    idx: int
    zmw: object
    gen: object = None         # consensus generator (None => skipped)
    req: object = None         # pending PairRequest | RefineRequest
    done: bool = False
    resumed: bool = False      # written by a previous run; skip + no journal
    cns: Optional[tuple] = None  # (seq_bytes, qual_bytes|None)
    err: Optional[Exception] = None


def _start_hole(hole: _Hole, cfg: CcsConfig) -> None:
    """Start the combined prep+consensus generator (first step only;
    PairRequests and RefineRequests both flow through the driver)."""
    try:
        faultinject.fire("compute")
        hole.gen = full_gen_for_zmw(hole.zmw, cfg)
        hole.req = next(hole.gen)
    except StopIteration as e:
        # skipped (<3 passes -> None) or consensus without device work
        hole.done, hole.cns = True, _finish(e.value)
    except Exception as e:  # quarantine: one bad hole must not kill the run
        hole.done, hole.err = True, e


def _advance_hole(hole: _Hole, rr) -> None:
    """Feed the matching result (MatchResult / RefineResult) back in."""
    try:
        hole.req = hole.gen.send(rr)
    except StopIteration as e:
        hole.done, hole.req, hole.cns = True, None, _finish(e.value)
    except Exception as e:
        hole.done, hole.req, hole.err = True, None, e


def _feed_hole(hole: _Hole, result) -> None:
    """Route an executor result back into a hole's generator — unless it
    is an Exception (an executor's last-resort host replay failed for
    this one request), which quarantines the hole, not the run.  A
    PairBatch result (a list) quarantines on its first embedded
    Exception the same way."""
    if isinstance(result, list):
        exc = next((r for r in result if isinstance(r, Exception)), None)
        if exc is not None:
            result = exc
    if isinstance(result, Exception):
        hole.done, hole.req, hole.err = True, None, result
        try:
            hole.gen.close()
        except Exception:
            pass
    else:
        _advance_hole(hole, result)


def _finish(result):
    """Generator result -> (seq_bytes, qual|None) or None (skipped)."""
    return enc.to_record(result)


def _grow_window(window: int, cap: int, growth: int) -> int:
    """One step of the reference's adaptive chunk policy scaled to the
    admission window (main.c:686-691: 1024 -> x4 -> cap 16384, i.e.
    start at cap/growth^2 and multiply by growth until the cap)."""
    return min(window * max(2, int(growth)), cap)


def drive_batched(stream, writer, cfg: CcsConfig, journal: Journal,
                  metrics: Metrics, inflight: Optional[int] = None,
                  shared=None) -> int:
    """The batched scheduler loop over an open ZMW stream and writer.

    Shared by the single-process driver (run_pipeline_batched) and the
    multi-host sharded driver (parallel/distributed.py).  If the writer
    exposes ``put_at(idx, name, seq, qual)`` it receives each record's
    hole ordinal too (the distributed shard writer needs it to restore
    global order at merge time).

    ``shared``: the resident server's runtime (pipeline/serve.py
    SharedRuntime) when this driver runs as ONE TENANT JOB of a
    ``ccsx-tpu serve`` process instead of owning the process.  Duck-
    typed attributes, all optional:

    * ``warm`` — a server-lifetime WarmupCompiler (not closed here;
      its key-dedup makes job N+1 skip every executable job 1 built)
    * ``warm_cache`` — one set shared by every job's PairExecutor for
      the inline-warm dedupe (the no-compiler path)
    * ``guard`` — a drain surrogate (utils/drain.FlagGuard) the server
      raises on cancel / deadline / server drain; replaces the
      process-signal DrainGuard (signal handlers belong to the
      server's main thread, not to a job thread)
    * ``admission`` — a per-job handle on the server's fair shared
      admission window (serve.JobAdmission): a slot is acquired per
      hole admitted and released when the hole finishes computing, so
      N tenants split the device window instead of stacking N windows

    With ``shared`` set the driver also does NOT install a tracer or
    start telemetry — the server owns the process-global tracer (one
    compile table across jobs is exactly the zero-recompile criterion)
    and the HTTP stack.

    ``inflight``: an EXPLICIT admission window pins it (the old fixed
    behavior); None selects the reference's adaptive chunk-growth
    policy (main.c:686-691 scaled to cfg.zmw_microbatch as the cap:
    start at cap/growth^2, multiply by cfg.chunk_growth per filled
    admission round) so small inputs skip full-window admission latency
    while big ones stay bounded.

    Host prep runs on the background prep plane
    (pipeline/prep_pool.py) unless cfg.prep_threads == 0: ingest +
    the orientation walk + its pair alignments happen on pool threads
    concurrently with this loop's device sweeps, and the driver only
    pays ``t_prep_blocked`` when it has nothing dispatchable.  Output
    bytes, ordered emission, and the journal invariant are identical
    either way (tests/test_prep_overlap.py).
    """
    from ccsx_tpu.io import bam as bam_mod
    from ccsx_tpu.io import zmw as zmw_mod
    from ccsx_tpu.pipeline.prep_pool import (PrepPool,
                                             resolve_prep_threads)
    from ccsx_tpu.pipeline.run import guarded_stream
    from ccsx_tpu.utils.drain import DrainGuard

    # non-positive --inflight keeps its historical meaning of "use the
    # default" (which is now the adaptive window), rather than pinning
    # a degenerate 1-hole window
    explicit_window = inflight is not None and int(inflight) > 0
    cap = max(1, int(inflight) if explicit_window
              else int(cfg.zmw_microbatch))
    growth = max(2, int(getattr(cfg, "chunk_growth", 4)))
    window = cap if explicit_window else max(1, cap // (growth * growth))
    n_prep = resolve_prep_threads(cfg)
    # AOT warmup precompiler (--no-warmup disables): as soon as prep
    # yields a hole's first RefineRequest, the group's canonical
    # executables compile on this background thread, concurrently with
    # ingest/prep — the first dispatch of a warmed shape then runs at
    # steady-state speed (and books as execute in the tracer)
    warm = None
    own_warm = True
    if shared is not None and getattr(shared, "warm", None) is not None:
        warm = shared.warm
        own_warm = False       # server-lifetime: never closed here
    elif getattr(cfg, "warmup_compile", True):
        from ccsx_tpu.pipeline.warmup import WarmupCompiler

        warm = WarmupCompiler()
    # the fair shared-admission handle (serve.JobAdmission), None for
    # a process-owning run: one slot per admitted-and-still-computing
    # hole, released the moment the hole finishes
    adm = getattr(shared, "admission", None)
    # resilient execution (pipeline/resilience.py): one dispatch-
    # deadline runner + circuit breaker shared by BOTH executors, so
    # pair-fill and refine failures count against the same backend.
    # Deliberately PER JOB under serve: a tenant that wedges the chip
    # trips only its own breaker to the host rung
    resil = resil_mod.Resilience(cfg, metrics=metrics)
    executor = BatchExecutor(cfg, metrics=metrics, warmup=warm,
                             resil=resil)
    pair_executor = PairExecutor(cfg.align, quant=cfg.len_bucket_quant,
                                 metrics=metrics, warmup=warm,
                                 resil=resil,
                                 prefilter=cfg.prefilter,
                                 seed_device_min_t=cfg.seed_device_min_t,
                                 warm_cache=getattr(shared, "warm_cache",
                                                    None))

    def warm_hole(h) -> None:
        if warm is not None and isinstance(h.req, RefineRequest):
            executor.warm_refine(h.req, hole_id=h.idx)
    resume = journal.holes_done
    # restore the journaled failure count: a --max-failed-holes budget
    # is judged over the whole logical run, resumes included (journaled
    # failures are skipped as done and would otherwise never re-count)
    metrics.holes_failed = journal.holes_failed
    metrics.holes_prior_emitted = journal.holes_emitted
    put_at = getattr(writer, "put_at", None)

    active: List[_Hole] = []
    finished: Dict[int, _Hole] = {}
    next_idx = 0       # next hole index to admit (inline-prep mode)
    next_emit = 0      # next hole index to write
    exhausted = False
    pool = None        # PrepPool, constructed inside the try below
    rc = 0

    def emit_ready():
        nonlocal next_emit
        while next_emit in finished:
            h = finished.pop(next_emit)
            if h.resumed:
                next_emit += 1
                if pool is not None:
                    pool.release()
                continue
            wrote = False
            if h.err is not None:
                metrics.holes_failed += 1
                print(f"[ccsx-tpu] hole {h.zmw.movie}/{h.zmw.hole} "
                      f"failed: {h.err}", file=sys.stderr)
                # failure-rate abort (--max-failed-holes): quarantine
                # is no longer unbounded — a count budget aborts here,
                # a fraction budget at end of run (metrics.py)
                check_failure_budget(metrics, cfg)
            elif h.cns is not None and h.cns[0]:
                name = f"{h.zmw.movie}/{h.zmw.hole}/ccs"
                seq, qual = h.cns
                with metrics.timer("write"), \
                        trace.span("write_record", cat="write"):
                    if put_at is not None:
                        put_at(h.idx, name, seq, qual)
                    else:
                        writer.put(name, seq, qual)
                metrics.holes_out += 1
                wrote = True
            # flush-before-cursor + write fault point + advance: the
            # shared crash invariant lives in Journal.retire
            journal.retire(writer, wrote, metrics)
            # rank_death models a sharded rank SIGKILLed mid-run (the
            # shepherd's restart-and-resume acceptance case): fired at
            # a retirement point so the dead rank leaves a valid
            # journal + durable records behind, exactly like a real
            # OOM-kill between holes
            faultinject.fire("rank_death")
            # sigterm delivers a REAL signal at the same point — the
            # graceful-drain path, made deterministic
            faultinject.fire("sigterm")
            metrics.tick()
            next_emit += 1
            if pool is not None:
                pool.release()  # free one slot of ingest-ahead budget

    def admit(h):
        if h.done:
            finished[h.idx] = h
            if adm is not None:
                adm.release()  # never computed: free the slot at once
        else:
            warm_hole(h)
            active.append(h)

    # graceful drain (utils/drain.py) + the input_corrupt/salvage
    # ingest rungs: every ingestion path — inline admission AND the
    # prep pool's background workers — consumes the wrapped stream.
    # Installed HERE, immediately before the try whose finally restores
    # the handlers: installing any earlier would leak them if an
    # executor/resilience constructor above raised.  A serve job gets
    # its owner's FlagGuard instead — the server's main thread owns
    # the real signal handlers
    if shared is not None and getattr(shared, "guard", None) is not None:
        guard = shared.guard
    else:
        guard = DrainGuard.install()
    stream = guarded_stream(stream, cfg, metrics, guard)
    # the flight recorder (utils/trace.py): span JSONL under --trace,
    # and the stall watchdog + group attribution regardless — the
    # watchdog must be live on every batched run, or the next hang is
    # another diagnostics-free dead tunnel.  Constructed INSIDE the try
    # (finally tolerates tracer=None) so neither a watchdog thread nor
    # an open trace file can leak, and an unwritable --trace path gets
    # the same polite rc-1 refusal as an unwritable output path
    tracer = None
    telem = None
    try:
        if shared is None:
            try:
                tracer = trace.Tracer(cfg.trace_path,
                                      stall_timeout=cfg.stall_timeout_s,
                                      metrics=metrics)
            except OSError as e:
                print(f"Cannot open trace file for write! ({e})",
                      file=sys.stderr)
                return 1
            trace.install(tracer)
            # live telemetry endpoints (--telemetry-port; sharded runs
            # arrive here with the port already rank-offset).  None
            # when off; a bind failure degrades to a warning, never
            # kills a run
            if cfg.telemetry_port:
                from ccsx_tpu.utils import telemetry

                telem = telemetry.start(metrics, cfg.telemetry_port)
        if n_prep > 0:
            # the overlapped prep plane: ingest + the orientation walk
            # move to background threads (constructed after the tracer
            # so its spans record, inside the try so its threads cannot
            # leak past the finally)
            pool = PrepPool(stream, cfg, pair_executor, metrics,
                            threads=n_prep, max_outstanding=4 * cap,
                            resume=resume)
        while True:
            admitted_full = False
            if pool is not None:
                # drain whatever prep has finished, up to the window —
                # NEVER blocking here: with device work pending, the
                # sweep must run while prep keeps working in background
                while len(active) < window:
                    if adm is not None and not adm.try_acquire():
                        break  # at fair share; sweep what we hold
                    h = pool.poll()
                    if h is None:
                        if adm is not None:
                            adm.release()  # nothing arrived for it
                        break
                    admit(h)
                admitted_full = len(active) >= window
            else:
                # inline prep (--prep-threads 0): admit up to the
                # window; bound TOTAL outstanding holes (incl.
                # instantly-finished ones parked for ordered emission)
                # so a filtered run can't grow memory unboundedly
                while (not exhausted and len(active) < window
                       and next_idx - next_emit < 4 * cap):
                    if adm is not None and not adm.try_acquire():
                        break  # at fair share; sweep what we hold
                    try:
                        with metrics.timer("ingest"), \
                                trace.span("ingest_hole", cat="ingest"):
                            z = next(stream)
                            faultinject.fire("ingest")
                    except StopIteration:
                        if adm is not None:
                            adm.release()
                        exhausted = True
                        break
                    metrics.holes_in += 1
                    h = _Hole(idx=next_idx, zmw=z)
                    next_idx += 1
                    if metrics.holes_in <= resume:
                        h.done = h.resumed = True
                    else:
                        # prep host work (grouping + first generator
                        # step) timed as its own stage AND as driver-
                        # blocked prep (inline prep is all critical
                        # path); the walk's pair alignments are batched
                        # below (benchmarks/prep_share.py is the
                        # criterion that forced this)
                        with metrics.timer("prep"), \
                                metrics.timer("prep_blocked"), \
                                trace.span("prep_hole", cat="prep",
                                           hole=str(z.hole)):
                            _start_hole(h, cfg)
                    admit(h)
                admitted_full = len(active) >= window
            emit_ready()
            if not active:
                if pool is None:
                    if exhausted:
                        break
                    continue
                if pool.drained():
                    break
                # nothing dispatchable: the driver is genuinely blocked
                # on prep — the critical-path seconds prep_share reads.
                # Accumulate while prep keeps DELIVERING (sweeping the
                # first hole the instant it appears would fragment the
                # sweep into near-empty slabs and per-hole dispatches);
                # the moment prep pauses with work in hand — or the
                # window fills — sweep what we have.
                while len(active) < window and not pool.drained():
                    if adm is not None and not adm.try_acquire():
                        # at fair share while another tenant wants the
                        # window: wait on the admission condition (a
                        # release anywhere re-checks), not on the pool
                        adm.wait(0.05 if active else 0.2)
                        emit_ready()
                        if active:
                            break
                        metrics.heartbeat()
                        continue
                    # only the wait itself books as blocked — emission
                    # (write + journal fsync) has its own stage, and
                    # prep_share is the acceptance counter
                    with metrics.timer("prep_blocked"):
                        h = pool.get(timeout=0.05 if active else 1.0)
                    # emit as we accumulate: instantly-done holes
                    # (resumed/skipped) must retire HERE to keep
                    # releasing ingest budget, or a done stretch longer
                    # than the 4x bound live-locks against the pool
                    emit_ready()
                    if h is None:
                        if adm is not None:
                            adm.release()
                        if active:
                            break
                        metrics.heartbeat()
                        continue
                    admit(h)
                # a window filled while blocked still earns growth
                admitted_full = len(active) >= window
                metrics.heartbeat()
                if not active:
                    continue
            # one batched sweep over every pending request, split by
            # kind: prep pair alignments (strand_match walks) and
            # consensus rounds each batch across holes
            pair_holes = [h for h in active
                          if isinstance(h.req, (prep_mod.PairRequest,
                                                prep_mod.PairBatch))]
            round_holes = [h for h in active
                           if not isinstance(h.req,
                                             (prep_mod.PairRequest,
                                              prep_mod.PairBatch))]
            if pair_holes:
                # inline-mode only in practice (the pool finishes the
                # walk before handing a hole over); this sweep blocks
                # the driver, so it books as prep_blocked as well
                with metrics.timer("prep"), \
                        metrics.timer("prep_blocked"), \
                        trace.span("pair_sweep", cat="prep",
                                   n=len(pair_holes)):
                    pres = pair_executor.run([h.req for h in pair_holes])
                    for h, r in zip(pair_holes, pres):
                        _feed_hole(h, r)
            if round_holes:
                with metrics.timer("compute"), \
                        trace.span("refine_sweep", cat="compute",
                                   n=len(round_holes)):
                    rres = executor.run([h.req for h in round_holes])
                    for h, rr in zip(round_holes, rres):
                        _feed_hole(h, rr)
            still: List[_Hole] = []
            for h in active:
                if h.done:
                    finished[h.idx] = h
                    if adm is not None:
                        adm.release()  # finished computing: free the
                        # slot before emission (which can lag on an
                        # out-of-order tail) so a sibling job's denied
                        # admission unblocks now
                else:
                    # a sweep can grow a hole's draft into a fresh
                    # (qmax, tmax) group — predict next wave's shapes
                    warm_hole(h)
                    still.append(h)
            active = still
            emit_ready()
            if not explicit_window and admitted_full and window < cap:
                # adaptive chunk growth (main.c:686-691 semantics): a
                # filled admission round earns the next window size
                window = _grow_window(window, cap, growth)
            # interval-driven progress events even while nothing has
            # retired yet (a holes<=inflight run drains at the very end)
            metrics.heartbeat()
        # fraction-form --max-failed-holes settles at end of run, when
        # the processed-hole denominator is final (metrics.py) — but
        # not on a drain, whose denominator is a partial run's
        if not guard.requested:
            check_failure_budget(metrics, cfg, final=True)
    except FailureBudgetExceeded as e:
        from ccsx_tpu import exitcodes

        print(f"Error: {e}; aborting instead of emitting a degraded "
              "output at rc 0", file=sys.stderr)
        rc = exitcodes.RC_FAILED_HOLES
    except (bam_mod.BamError, zmw_mod.InvalidZmwName, ValueError) as e:
        print(f"Error: invalid input stream: {e}", file=sys.stderr)
        rc = 1
    except OSError as e:
        print(f"Error: write failed: {e}", file=sys.stderr)
        rc = 1
    finally:
        guard.restore()
        # settle this job's admission slots whatever the exit path —
        # a crashed tenant must not strand capacity the fair window
        # still counts against its share
        if adm is not None:
            adm.reset()
        try:
            writer.close()
        except OSError as e:
            print(f"Error: write failed! ({e})", file=sys.stderr)
            rc = 1
        # settle the (possibly rate-limit-lagging) cursor AFTER the
        # writer has made the records durable
        journal.close()
        # stop the prep plane first (its workers/pump write prep spans
        # and metrics): error paths may leave in-prep holes — dropped,
        # the rc already reflects the failure
        if pool is not None:
            pool.close()
        # stop the warmup thread (drops queued compiles; an in-flight
        # build finishes) BEFORE the tracer closes, so no warmup span
        # outlives the trace file.  A server-lifetime compiler stays
        # up — its queue is the next job's head start
        if warm is not None and own_warm:
            warm.close()
        # stop the watchdog + export the trace BEFORE the final metrics
        # event, so a degraded mark set mid-run is in the "final".
        # Under serve the PROCESS-GLOBAL tracer is the server's (one
        # compile table across jobs); uninstalling it here would blind
        # every sibling job's attribution
        if shared is None:
            trace.uninstall()
        if tracer is not None:
            tracer.close()
        # endpoints down BEFORE the final event: a scraper must never
        # see a half-closed Metrics object
        if telem is not None:
            telem.close()
        metrics.report()
    if rc == 0 and guard.requested:
        from ccsx_tpu import exitcodes

        print("[ccsx-tpu] drained cleanly; resume with the same "
              "command to continue", file=sys.stderr)
        rc = exitcodes.RC_INTERRUPTED
    return rc


def mesh_precheck(cfg: CcsConfig) -> int:
    """0 when cfg.mesh_shape is feasible (or unset); 1 with a stderr
    message otherwise.  Shared by both pipeline drivers — call after
    resolve_device and BEFORE opening any output file."""
    if cfg.mesh_shape is None:
        return 0
    import jax

    try:
        # local devices: the per-host mesh never spans hosts (see
        # BatchExecutor.__init__)
        BatchExecutor.validate_mesh(cfg.mesh_shape,
                                    len(jax.local_devices()))
    except ValueError as e:
        print(f"Error: invalid --mesh: {e}", file=sys.stderr)
        return 1
    return 0


def run_pipeline_batched(in_path: str, out_path: str, cfg: CcsConfig,
                         journal_path: Optional[str] = None,
                         inflight: Optional[int] = None,
                         metrics: Optional[Metrics] = None,
                         shared=None) -> int:
    """Batched end-to-end driver (CLI --batch; default on TPU backends).

    ``metrics``/``shared``: the serving plane (pipeline/serve.py) runs
    each tenant job through this exact entry point, handing in the
    job-labelled Metrics it scrapes for /jobs/<id> and the server's
    SharedRuntime (see drive_batched) — so a served job and a CLI run
    are the same code path end to end, which is what makes the
    byte-identity acceptance test meaningful."""
    from ccsx_tpu.pipeline.run import (holes_total_hint, open_writer,
                                       open_zmw_stream)
    from ccsx_tpu.utils.device import resolve_device

    # metrics constructed before the stream so both ingest paths can
    # book their filtered-hole accounting into it
    if metrics is None:
        metrics = Metrics(verbose=cfg.verbose,
                          stream=cfg.metrics_stream())
    metrics.holes_total = holes_total_hint(in_path, cfg)
    try:
        stream = open_zmw_stream(in_path, cfg, metrics=metrics)
    except (OSError, RuntimeError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        metrics.close_stream()  # no final event for a non-run
        return 1

    # resolve the backend and validate the mesh BEFORE the writer opens:
    # a bad --mesh must not truncate an existing output file
    resolve_device(cfg.device)
    if mesh_precheck(cfg):
        metrics.close_stream()
        return 1

    # load under this run's fingerprint + reconcile the output tail with
    # the cursor (truncate a torn tail / refuse an untrustworthy resume)
    # BEFORE the writer opens for append
    journal = Journal.for_run(journal_path, in_path, cfg, out_path)
    try:
        writer = open_writer(out_path, append=bool(journal.holes_done),
                             bam=cfg.bam_out,
                             journaled=bool(journal_path))
    except OSError as e:
        print(f"Cannot open file for write! ({e})", file=sys.stderr)
        metrics.close_stream()
        return 1
    # None = the adaptive admission window (explicit --inflight pins it)
    return drive_batched(stream, writer, cfg, journal, metrics, inflight,
                         shared=shared)
