"""Synthetic ZMW/subread generator for tests and benchmarks.

Models the PacBio data the reference consumes: a circular template read many
times with alternating strand per pass (main.c:374-375 walks outward from the
template alternating expected strand), each pass an independently noisy copy
(mismatches + insertions + deletions).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ccsx_tpu.ops import encode as enc


@dataclasses.dataclass
class SynthZmw:
    movie: str
    hole: str
    template: np.ndarray          # 2-bit codes
    passes: List[np.ndarray]      # 2-bit codes, oriented as sequenced
    strands: List[int]            # 0 fwd / 1 rev per pass

    @property
    def names(self) -> List[str]:
        out = []
        off = 0
        for p in self.passes:
            out.append(f"{self.movie}/{self.hole}/{off}_{off + len(p)}")
            off += len(p)
        return out

    def fasta(self) -> str:
        recs = []
        for name, p in zip(self.names, self.passes):
            recs.append(f">{name}\n{enc.decode(p)}\n")
        return "".join(recs)


def _run_lengths(seq: np.ndarray) -> np.ndarray:
    """len of the maximal homopolymer run containing each position."""
    n = len(seq)
    runs = np.empty(n, np.int32)
    i = 0
    while i < n:
        j = i
        while j < n and seq[j] == seq[i]:
            j += 1
        runs[i:j] = j - i
        i = j
    return runs


def mutate(
    rng: np.random.Generator,
    seq: np.ndarray,
    sub_rate: float,
    ins_rate: float,
    del_rate: float,
    hp_factor: float = 0.0,
    hp_ins_same: float = 0.0,
    context_sub: Optional[tuple] = None,
) -> np.ndarray:
    """Apply per-base errors to a 2-bit sequence.

    Defaults are the i.i.d. model (and consume the identical rng
    stream, so seeded fixtures are unchanged).  The optional knobs
    model where real CCS consensus and QV calibration actually get
    stressed — errors CORRELATED across passes at the same template
    loci, so unanimous columns can be unanimously wrong:

    * ``hp_factor`` — indel rates scale by (1 + hp_factor*min(run-1, 4))
      inside homopolymer runs (PacBio's dominant error mode).
    * ``hp_ins_same`` — probability an inserted base copies the current
      base (homopolymer extension) instead of being uniform.
    * ``context_sub`` — per-base (A,C,G,T) multiplier on sub_rate.
    """
    biased = hp_factor or context_sub is not None
    runs = _run_lengths(seq) if hp_factor else None
    out = []
    for i, b in enumerate(seq):
        dr, sr, ir = del_rate, sub_rate, ins_rate
        if biased:
            if hp_factor:
                m = 1.0 + hp_factor * min(int(runs[i]) - 1, 4)
                dr, ir = dr * m, ir * m
            if context_sub is not None:
                sr = sr * context_sub[int(b)]
        r = rng.random()
        if r < dr:
            continue
        if r < dr + sr:
            out.append((int(b) + 1 + rng.integers(3)) % 4)
        else:
            out.append(int(b))
        while rng.random() < ir:
            if hp_ins_same and rng.random() < hp_ins_same:
                out.append(int(b))
            else:
                out.append(int(rng.integers(4)))
    return np.array(out, dtype=np.uint8)


def make_zmw(
    rng: np.random.Generator,
    template_len: int = 1000,
    n_passes: int = 5,
    sub_rate: float = 0.02,
    ins_rate: float = 0.04,
    del_rate: float = 0.04,
    movie: str = "m0",
    hole: str = "1",
    first_strand: int = 0,
    template: Optional[np.ndarray] = None,
    partial_ends: bool = False,
    hp_factor: float = 0.0,
    hp_ins_same: float = 0.0,
    context_sub: Optional[tuple] = None,
) -> SynthZmw:
    """With ``partial_ends``, the first and last passes are truncated
    fragments (the polymerase starts/ends mid-molecule on real ZMWs) —
    these fall outside the dominant length group, forcing the prepare
    stage through its alignment-verified strand walk (main.c:392-406)
    instead of the trusted-parity shortcut."""
    if template is None:
        template = rng.integers(0, 4, size=template_len).astype(np.uint8)
    passes, strands = [], []
    for k in range(n_passes):
        strand = (first_strand + k) % 2
        p = mutate(rng, template, sub_rate, ins_rate, del_rate,
                   hp_factor=hp_factor, hp_ins_same=hp_ins_same,
                   context_sub=context_sub)
        if strand:
            p = enc.revcomp_codes(p)
        if partial_ends and n_passes >= 5 and k in (0, n_passes - 1):
            frac = 0.3 + 0.3 * rng.random()  # keep 30-60%
            keep = max(int(len(p) * frac), 50)
            # first pass keeps its tail (run-up), last keeps its head
            p = p[-keep:] if k == 0 else p[:keep]
        passes.append(p)
        strands.append(strand)
    return SynthZmw(movie=movie, hole=hole, template=template,
                    passes=passes, strands=strands)


def read_through(
    rng: np.random.Generator,
    template: np.ndarray,
    sub_rate: float = 0.02,
    ins_rate: float = 0.04,
    del_rate: float = 0.04,
) -> np.ndarray:
    """A missed-adapter ("read-through") pass: template ++
    revcomp(template), each half independently noisy.  ~2x the template
    group length, so the reference's prepare stage aligns and clips it
    to one template span (main.c:392-406) instead of trusting strand
    parity."""
    return np.concatenate([
        mutate(rng, template, sub_rate, ins_rate, del_rate),
        enc.revcomp_codes(mutate(rng, template, sub_rate, ins_rate,
                                 del_rate)),
    ])


def make_fasta(zmws: List[SynthZmw]) -> str:
    return "".join(z.fasta() for z in zmws)


def identity(a: np.ndarray, b: np.ndarray) -> float:
    """Global-alignment identity between two code sequences (oracle-based)."""
    from ccsx_tpu.ops import oracle

    rs = oracle.align(a, b, mode="global")
    return rs.identity


def identity_either(a: np.ndarray, b: np.ndarray) -> float:
    """Identity of a vs b in the better of the two orientations.

    Consensus strand follows the chosen template pass (an arbitrary strand,
    in the reference as here), so template comparisons must accept either.
    """
    return max(identity(a, b), identity(enc.revcomp_codes(a), b))
