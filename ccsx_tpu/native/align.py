"""ctypes wrapper for the native scalar aligner (align_native.cpp).

Returns the same AlnResult the NumPy oracle produces, so the two are
drop-in interchangeable and differentially testable.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from ccsx_tpu import native
from ccsx_tpu.ops.oracle import AlnResult

_MODES = {"global": 0, "qfree": 1, "local": 2}


def _runs(ops: bytes) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for ch in ops.decode():
        if out and out[-1][0] == ch:
            out[-1] = (ch, out[-1][1] + 1)
        else:
            out.append((ch, 1))
    return out


def align_scalar_native(
    q: np.ndarray,
    t: np.ndarray,
    mode: str = "global",
    match: int = 2,
    mismatch: int = -6,
    gap_open: int = -3,
    gap_extend: int = -2,
) -> Optional[AlnResult]:
    """Native scalar Gotoh alignment; None when the library is unavailable
    or the problem exceeds the native path's size cap."""
    L = native.lib()
    if L is None:
        return None
    c = ctypes
    q = np.ascontiguousarray(q, dtype=np.uint8)
    t = np.ascontiguousarray(t, dtype=np.uint8)
    out = (c.c_int64 * 10)()
    cap = len(q) + len(t) + 2
    cigar = (c.c_uint8 * cap)()
    n = c.c_int64()
    rc = L.ccsx_align_scalar(
        q.ctypes.data_as(c.POINTER(c.c_uint8)), len(q),
        t.ctypes.data_as(c.POINTER(c.c_uint8)), len(t),
        _MODES[mode], match, mismatch, gap_open, gap_extend,
        out, cigar, cap, c.byref(n))
    if rc != 0:
        return None
    ops = bytes(cigar[: n.value]) if n.value >= 0 else b""
    return AlnResult(
        score=out[0], qb=out[1], qe=out[2], tb=out[3], te=out[4],
        aln=out[5], mat=out[6], mis=out[7], ins=out[8], del_=out[9],
        cigar=_runs(ops))
