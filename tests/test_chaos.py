"""Chaos soak harness (benchmarks/chaos.py): seeded randomized fault
schedules through the full CLI with the byte-identity oracle.

The FAST deterministic slice runs in tier-1 (`make chaos` runs exactly
this file's not-slow tests): in-process faults only — device OOMs,
storms, transient stalls, permanent hangs under a dispatch deadline —
every trial asserting bytes equal to the fault-free run.  The full
soak (kill/resume subprocesses + a shepherded rank death on top) is
the `slow` mark and the benchmarks/chaos.py CLI.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import chaos  # noqa: E402

from ccsx_tpu.utils import faultinject  # noqa: E402


@pytest.fixture(autouse=True)
def _chaos_env(monkeypatch):
    faultinject.disarm()
    # unit-scale hang budgets: grace x1 (the chaos corpus compiles in
    # seconds on CPU) and a bounded hang sleep so abandoned daemon
    # threads don't linger an hour
    monkeypatch.setenv("CCSX_DEADLINE_GRACE", "1")
    monkeypatch.setenv("CCSX_FAULT_HANG_S", "60")
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "0.3")
    yield
    faultinject.disarm()


def test_chaos_fast_slice(tmp_path):
    """The deterministic tier-1 slice: 3 seeded in-process fault trials
    plus the input-plane pair (disk_full ENOSPC + resume,
    input_corrupt under --salvage) on a 3-hole corpus, every one
    holding its oracle.  Failures print the full per-trial detail
    (seeded: any red trial is replayable with the same seed)."""
    summary = chaos.run_trials(seed=0, trials=2, holes=3,
                               include_kills=False,
                               include_shepherd=False,
                               tmp=str(tmp_path))
    assert summary["n_trials"] == 4
    kinds = {t["kind"] for t in summary["trials"]}
    assert "disk_full_resume" in kinds and "input_corrupt" in kinds
    assert summary["ok"], summary["trials"]
    # replayability is the seeded np.random.default_rng stream (version-
    # stable): same seed, same schedule — the slow-tier soak runs the
    # schedule twice to assert it; re-executing every trial here doubled
    # the tier-1 slice's wall for no new coverage (r11 duration audit)


@pytest.mark.slow  # ~9s: serve's device-hang degradation pin and the
# seeded chaos fast slice stay tier-1 (r16 budget audit)
def test_chaos_hang_trial_directly(tmp_path):
    """The permanent-hang trial in isolation (the seeded menu draw
    above may or may not include it): device_hang under a dispatch
    deadline must complete byte-identical with the hang counted."""
    import numpy as np

    rng = np.random.default_rng(7)
    in_fa = chaos.make_corpus(str(tmp_path), rng, 3)
    ref = chaos.run_reference(in_fa, str(tmp_path))
    r = chaos.trial_inproc(in_fa, str(tmp_path), ref, "device_hang",
                           "device_hang@1",
                           ("--dispatch-deadline", "2"))
    assert r["ok"], r
    assert r["counters"]["device_hangs"] >= 1
    assert r["degraded"]


@pytest.mark.slow
def test_chaos_soak_with_kills_and_shepherd(tmp_path):
    """The full composition: randomized in-process faults + write/
    journal kill-and-resume subprocesses + a shepherded rank death —
    all byte-identical.  (slow: multiple cold CLI subprocesses.)"""
    summary = chaos.run_trials(seed=1, trials=4, holes=4,
                               include_kills=True,
                               include_shepherd=True,
                               tmp=str(tmp_path))
    assert summary["ok"], summary["trials"]
    kinds = {t["kind"] for t in summary["trials"]}
    assert "kill_write" in kinds and "kill_journal" in kinds
    assert "shepherd_rank_death" in kinds
