// Scalar affine-gap pairwise aligner (C++), the native reference
// implementation for differential testing (SURVEY.md §7.2 step 2).
//
// Semantics are pinned to the NumPy oracle (ccsx_tpu/ops/oracle.py), which
// itself replicates what ccsx consumes from bsalign's
// kmer_striped_seqedit_pairwise (main.c:264, result fields main.c:272-280):
// Gotoh affine-gap DP, modes global / qfree (query ends free) / local,
// traceback preferring diagonal, then vertical (E), then horizontal (F) on
// ties; first-occurrence argmax for free end cells.  The differential test
// (tests/test_native_align.py) requires exact equality of score, spans,
// counts and cigar against the oracle.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t kNeg = -(1 << 29);

enum Mode { kGlobal = 0, kQfree = 1, kLocal = 2 };

struct Dp {
  int64_t Q, T, W;  // W = T + 1 row stride
  std::vector<int32_t> H, E, F;
  int32_t& h(int64_t i, int64_t j) { return H[i * W + j]; }
  int32_t& e(int64_t i, int64_t j) { return E[i * W + j]; }
  int32_t& f(int64_t i, int64_t j) { return F[i * W + j]; }
};

}  // namespace

extern "C" {

// out[10] = score qb qe tb te aln mat mis ins del.
// cigar (optional, may be null): expanded per-column ops 'M'/'I'/'D';
// *cigar_n receives the op count, or -1 when cigar_cap was too small
// (stats in `out` remain valid).
// Returns 0 ok, -1 bad args / problem too large for the scalar path.
int ccsx_align_scalar(const uint8_t* q, int64_t qlen, const uint8_t* t,
                      int64_t tlen, int mode, int match, int mismatch,
                      int gap_open, int gap_ext, int64_t* out, uint8_t* cigar,
                      int64_t cigar_cap, int64_t* cigar_n) {
  if (qlen < 0 || tlen < 0 || !out) return -1;
  if ((qlen + 1) * (tlen + 1) > (int64_t)1 << 26) return -1;  // 3x256MB cap
  const int oe = gap_open + gap_ext;
  Dp dp;
  dp.Q = qlen;
  dp.T = tlen;
  dp.W = tlen + 1;
  size_t cells = (size_t)((qlen + 1) * (tlen + 1));
  dp.H.assign(cells, kNeg);
  dp.E.assign(cells, kNeg);
  dp.F.assign(cells, kNeg);

  dp.h(0, 0) = 0;
  if (mode == kGlobal) {
    for (int64_t i = 1; i <= qlen; i++)
      dp.h(i, 0) = dp.e(i, 0) = gap_open + (int32_t)i * gap_ext;
    for (int64_t j = 1; j <= tlen; j++)
      dp.h(0, j) = dp.f(0, j) = gap_open + (int32_t)j * gap_ext;
  } else if (mode == kQfree) {
    for (int64_t i = 1; i <= qlen; i++) dp.h(i, 0) = 0;
    for (int64_t j = 1; j <= tlen; j++)
      dp.h(0, j) = dp.f(0, j) = gap_open + (int32_t)j * gap_ext;
  } else if (mode == kLocal) {
    for (int64_t i = 1; i <= qlen; i++) dp.h(i, 0) = 0;
    for (int64_t j = 1; j <= tlen; j++) dp.h(0, j) = 0;
  } else {
    return -1;
  }

  auto subst = [&](int64_t i, int64_t j) -> int32_t {
    // N (code >= 4) never matches anything, including itself
    return (q[i] == t[j] && q[i] < 4 && t[j] < 4) ? match : mismatch;
  };

  for (int64_t i = 1; i <= qlen; i++) {
    for (int64_t j = 0; j <= tlen; j++) {
      int32_t e1 = dp.h(i - 1, j) + oe, e2 = dp.e(i - 1, j) + gap_ext;
      dp.e(i, j) = e1 > e2 ? e1 : e2;
    }
    for (int64_t j = 1; j <= tlen; j++) {
      int32_t f1 = dp.h(i, j - 1) + oe, f2 = dp.f(i, j - 1) + gap_ext;
      int32_t f = f1 > f2 ? f1 : f2;
      dp.f(i, j) = f;
      int32_t h = dp.h(i - 1, j - 1) + subst(i - 1, j - 1);
      if (dp.e(i, j) > h) h = dp.e(i, j);
      if (f > h) h = f;
      if (mode == kLocal && h < 0) h = 0;
      if (h > dp.h(i, j)) dp.h(i, j) = h;
    }
  }

  // --- end cell (first-occurrence argmax, matching numpy) ---
  int64_t ei = qlen, ej = tlen;
  if (mode == kQfree) {
    int32_t best = kNeg - 1;
    for (int64_t i = 0; i <= qlen; i++)
      if (dp.h(i, tlen) > best) { best = dp.h(i, tlen); ei = i; }
    ej = tlen;
  } else if (mode == kLocal) {
    int32_t best = kNeg - 1;
    for (int64_t i = 0; i <= qlen; i++)
      for (int64_t j = 0; j <= tlen; j++)
        if (dp.h(i, j) > best) { best = dp.h(i, j); ei = i; ej = j; }
  }
  int32_t score = dp.h(ei, ej);

  // --- traceback (diag > E > F on ties, like the oracle) ---
  int64_t i = ei, j = ej;
  int64_t mat = 0, mis = 0, ins = 0, del = 0;
  std::vector<uint8_t> ops;  // reversed
  char state = 'H';
  for (;;) {
    if (state == 'H') {
      if (mode == kLocal && dp.h(i, j) == 0) break;
      if (mode == kQfree && j == 0) break;
      if (mode == kGlobal && i == 0 && j == 0) break;
      if (i > 0 && j > 0 &&
          dp.h(i, j) == dp.h(i - 1, j - 1) + subst(i - 1, j - 1)) {
        ops.push_back('M');
        if (q[i - 1] == t[j - 1] && q[i - 1] < 4) mat++; else mis++;
        i--; j--;
      } else if (i > 0 && dp.h(i, j) == dp.e(i, j)) {
        state = 'E';
      } else if (j > 0 && dp.h(i, j) == dp.f(i, j)) {
        state = 'F';
      } else {
        state = i > 0 ? 'E' : 'F';
      }
    } else if (state == 'E') {
      ops.push_back('I');
      ins++;
      if (dp.e(i, j) == dp.e(i - 1, j) + gap_ext && i > 1) { i--; }
      else { i--; state = 'H'; }
    } else {
      ops.push_back('D');
      del++;
      if (dp.f(i, j) == dp.f(i, j - 1) + gap_ext && j > 1) { j--; }
      else { j--; state = 'H'; }
    }
  }

  out[0] = score;
  out[1] = i;   // qb
  out[2] = ei;  // qe
  out[3] = j;   // tb
  out[4] = ej;  // te
  out[5] = mat + mis + ins + del;
  out[6] = mat;
  out[7] = mis;
  out[8] = ins;
  out[9] = del;
  if (cigar_n) {
    if (cigar && (int64_t)ops.size() <= cigar_cap) {
      for (size_t k = 0; k < ops.size(); k++)
        cigar[k] = ops[ops.size() - 1 - k];
      *cigar_n = (int64_t)ops.size();
    } else {
      *cigar_n = -1;
    }
  }
  return 0;
}

}  // extern "C"
