"""THE forced-execution marginal timing helper — the one implementation
all benches share (bench.py, round_profile.py, pallas_ab.py), so a fix
to the method lands everywhere at once.

Why this exists (r5 discovery, 2026-07-31): the tunnelled axon runtime
is LAZY — ``jax.block_until_ready`` returns in ~0.02–1 ms regardless of
queued work, and unfetched dispatches may never execute — so both
per-iteration blocking loops and dispatch-queue timing measure RPC
bookkeeping, not the chip.  The only synchronization that provably
waits is materializing output bytes.  Method: run ``iters`` calls of
``fn`` inside ONE jitted ``lax.fori_loop`` whose body (a) perturbs the
first argument with the loop index — defeats loop-invariant hoisting —
and (b) folds every output leaf into an int32 checksum — defeats DCE;
fetch the scalar checksum, and report the MARGINAL time between an
``iters``-loop and a 1-loop fetch, which cancels the fixed ~30–100 ms
d2h latency.  The trip count is a TRACED argument: one compiled
program serves both loops (one compile through the tunnel, and XLA
cannot unroll/specialize).  Validated on CPU (agrees with synchronous
timing) and against known-FLOPs matmuls (~147 TFLOPs bf16 on v5e).

Nonpositive marginals (baseline fetch noise exceeding the iters run)
are DISCARDED, never clamped — a clamped sample becomes an absurdly
fast reading that can settle an A/B by noise.
"""

from __future__ import annotations

import time


def marginal_time(fn, *args, iters: int = 100, repeats: int = 3,
                  settle: float = 0.1):
    """List of up to ``repeats`` positive marginal seconds-per-call of
    ``fn(*args)``.  May return fewer (noisy windows are discarded, with
    up to 2x``repeats`` attempts); raises RuntimeError if every attempt
    was nonpositive — a sign the runtime/clock is broken, not the chip.

    FIRST-ARGUMENT CONTRACT: the anti-hoisting perturbation writes
    ``i % 4`` into element [0, 0, ...] of ``args[0]`` each loop
    iteration, so args[0] must tolerate arbitrary values in {0, 1, 2, 3}
    at that position — same dtype, same output shapes, no control-flow
    change.  True of the code tensors every ccsx bench passes first
    (0..3 are the valid bases; lengths/masks ride in later arguments).
    Callers whose natural first argument cannot absorb that (a length,
    a scalar, a one-hot) must reorder arguments so a value-tolerant
    tensor comes first — the perturbed value feeds ``fn``, so a
    corrupted length would time a DIFFERENT workload, not just add
    noise.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    args = tuple(jnp.asarray(a) for a in args)

    @jax.jit
    def run(n, *a):
        def body(i, acc):
            a0 = a[0].at[(0,) * a[0].ndim].set(
                jnp.mod(i, 4).astype(a[0].dtype))
            out = fn(a0, *a[1:])
            return acc + sum(
                jnp.sum(leaf.astype(jnp.int32))
                for leaf in jax.tree_util.tree_leaves(out))
        return jax.lax.fori_loop(0, n, body, jnp.int32(0))

    np.asarray(run(np.int32(1), *args))     # compile before timing
    out = []
    for _ in range(2 * repeats):
        if len(out) >= repeats:
            break
        t0 = time.perf_counter()
        np.asarray(run(np.int32(1), *args))
        base = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(np.int32(iters), *args))
        d = (time.perf_counter() - t0 - base) / (iters - 1)
        if d > 0:
            out.append(d)
        time.sleep(settle)
    if not out:
        raise RuntimeError(
            "every marginal-timing window was nonpositive: the runtime "
            "or clock is lying; no honest sample to report")
    return out
