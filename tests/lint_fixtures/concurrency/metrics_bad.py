"""Known-bad twin for metrics-lock + contextvar-restore."""

import contextvars

_cid = contextvars.ContextVar("ccsx_cid", default=None)


def ingest(metrics, n):
    # racy read-modify-write: a prep-pool bump() between the read and
    # the write silently loses counts
    metrics.holes_in += n


class Watchdog:
    def fire(self):
        self.metrics.stalls += 1


def enter_job(cid):
    # token dropped: the cid leaks into every later job on this
    # thread (the r17 cross-stamp)
    _cid.set(cid)
