"""Minimal-fix sibling for the int32-overflow checker: the same
arithmetic through the sanctioned idioms.  MUST produce no findings."""

import jax.numpy as jnp

LIMB_BITS = 8


def _line_interp_limbed(ip, span, denom):
    # the shipped fix (ops/banded._line_interp): slope + 8-bit-limb
    # remainder keeps every partial product under 2**31
    slope = span // denom
    s2 = span - slope * denom
    neg = ip < 0
    aa = jnp.where(neg, -ip, ip)
    hi = (aa >> 8) * s2
    lo = (aa & 255) * s2
    q1 = hi // denom
    num = (hi - q1 * denom) * 256 + lo
    q2 = num // denom
    mag = q1 * 256 + q2
    return jnp.where(neg, -mag, mag) + ip * slope


def interp_promoted(ip, span, denom):
    # the other sanctioned fix: explicit int64 promotion
    wide = (ip.astype(jnp.int64) * span.astype(jnp.int64)) // denom
    return wide.astype(jnp.int32)


def static_shapes(n, votes):
    # literal/constant factors and shift amounts are static python
    # ints under trace — no wrap hazard
    npad = -(-n // 128) * 128
    key = votes << LIMB_BITS
    return npad, key >> 4
