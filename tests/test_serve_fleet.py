"""Serve replica fleet (r16): N warm servers over ONE job spool as a
lease domain (pipeline/gateway.py spool protocol + pipeline/serve.py
fleet mode + the `ccsx-tpu gateway` balancer).

Load-bearing guarantees pinned here:

* A job submitted into the shared spool is completed by a DIFFERENT
  replica than the submitter, byte-identical to the sequential CLI
  reference, with exactly one exclusive done marker.
* A dead replica's job lease (stale heartbeat, dead pid) is expired by
  a survivor — kill-before-steal, host-guarded — and the job completes
  with zero loss.
* Cross-replica cancel: a cancel marked on the spool record (the
  gateway's DELETE path) is observed at the holder's next heartbeat
  renewal and aborts ONLY that job (the PR 15 blast radius), rc 75.
* The exclusive retirement fence admits exactly one emitter — a
  zombie replica cannot double-emit a finished job.
* The gateway health-routes on /readyz, answers 503 + Retry-After when
  no replica is ready, serves fleet-aggregate ``ccsx_fleet_*`` gauges
  on /metrics (schema cross-checked against the telemetry tuples both
  directions), and discovers replicas through their slot leases —
  deterministic base+slot ports, never guessing.
* `ccsx-tpu top` expands a spool directory into its replica endpoints.
* bench.py's serve-fleet vs_prev leg gates lost/duplicated jobs, byte
  identity, steady-state recompiles, and the 20% throughput rule.

The corpus reuses the 700 bp / 5-pass geometry of tests/test_serve.py
so tier-1's process-wide jit cache is shared across the files.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.pipeline import gateway as spoolproto
from ccsx_tpu.pipeline import supervisor
from ccsx_tpu.pipeline.gateway import Gateway, _gateway_handler
from ccsx_tpu.pipeline.serve import ServeCore, _serve_handler
from ccsx_tpu.utils import faultinject, lease as leaselib, synth, telemetry
from ccsx_tpu.utils.journal import write_json_atomic


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(autouse=True)
def _fast_grace(monkeypatch):
    monkeypatch.setenv("CCSX_DEADLINE_GRACE", "1")
    monkeypatch.setenv("CCSX_FAULT_HANG_S", "60")
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "4")


def _cfg(extra=()):
    args = cli.build_parser().parse_args(["-A", "-m", "1000", *extra])
    return cli.config_from_args(args)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(3-hole input, its CLI reference output, 8-hole input, its CLI
    reference output) — references computed by the plain CLI BEFORE
    any ServeCore exists."""
    tmp = tmp_path_factory.mktemp("serve_fleet")
    rng = np.random.default_rng(0)

    def make(n, path):
        zs = [synth.make_zmw(rng, template_len=700, n_passes=5,
                             movie="mv", hole=str(100 + h))
              for h in range(n)]
        path.write_text(synth.make_fasta(zs))

    fa3, fa8 = tmp / "in3.fa", tmp / "in8.fa"
    make(3, fa3)
    make(8, fa8)
    ref3, ref8 = tmp / "ref3.fa", tmp / "ref8.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa3), str(ref3)]) == 0
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa8), str(ref8)]) == 0
    return (str(fa3), ref3.read_bytes(), str(fa8), ref8.read_bytes())


@pytest.fixture
def fleet_factory(tmp_path):
    """Replica cores over one shared spool, torn down after the test."""
    cores = []
    spool = str(tmp_path / "spool")

    def make(name, extra=(), **kw):
        kw.setdefault("lease_timeout", 1.2)
        kw.setdefault("poll_s", 0.1)
        c = ServeCore(_cfg(extra), spool=spool, fleet=True,
                      replica=name, **kw)
        cores.append(c)
        return c

    yield spool, make
    for c in cores:
        c.close()


def _wait_done(spool, jid, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        view = spoolproto.job_view(spool, jid)
        if view and view["state"] in ("done", "failed", "cancelled",
                                      "interrupted"):
            return view
        time.sleep(0.1)
    raise AssertionError(
        f"job {jid} not terminal: {spoolproto.job_view(spool, jid)}")


# ---------- the spool protocol (no jax, no cores) ----------

def test_submit_allocates_sequential_ids_and_spools_body(tmp_path):
    spool = str(tmp_path)
    j1 = spoolproto.submit_job(spool, input_path="/x/in.fa",
                               overrides={})
    j2 = spoolproto.submit_job(spool, input_path="/x/in2.fa",
                               overrides={"deadline_s": 5})
    assert (j1, j2) == ("j00001", "j00002")
    assert spoolproto.job_view(spool, j1)["state"] == "queued"
    assert spoolproto.spool_counts(spool)["queued"] == 2
    # a request body is spooled + fsynced BEFORE the record exists
    import io
    j3 = spoolproto.submit_job(spool, body_stream=io.BytesIO(b">a\nACGT\n"),
                               body_len=8, overrides={"format": "fasta"})
    rec = spoolproto.read_job_record(spool, j3)
    assert open(rec["input"], "rb").read() == b">a\nACGT\n"


def test_exclusive_retirement_admits_one_emitter(tmp_path):
    """The zombie double-emit guard: two replicas racing to retire one
    job — the marker fence admits exactly one, and the loser can see
    it lost (the signal to yield, not overwrite)."""
    spool = str(tmp_path)
    jid = spoolproto.submit_job(spool, input_path="/x/in.fa",
                                overrides={})
    assert spoolproto.retire_job(spool, jid, "done", 0, "A",
                                 output="/x/a.fa") is True
    assert spoolproto.retire_job(spool, jid, "done", 0, "B",
                                 output="/x/b.fa") is False
    view = spoolproto.job_view(spool, jid)
    assert view["state"] == "done" and view["replica"] == "A"
    assert view["output"] == "/x/a.fa"


def test_cancel_and_deadline_marks(tmp_path):
    spool = str(tmp_path)
    jid = spoolproto.submit_job(spool, input_path="/x/in.fa",
                                overrides={})
    state, changed = spoolproto.mark_cancel(spool, jid)
    assert changed and state == "cancelled"   # queued: dies unstarted
    # idempotent: a second cancel reports unchanged
    _, changed = spoolproto.mark_cancel(spool, jid)
    assert not changed
    with pytest.raises(KeyError):
        spoolproto.mark_cancel(spool, "j99999")
    assert spoolproto.mark_deadline(spool, jid, 3.5)
    rec = spoolproto.read_job_record(spool, jid)
    assert rec["overrides"]["deadline_s"] == 3.5


def test_replica_slots_are_deterministic_and_discoverable(tmp_path):
    """First-free-slot assignment + base-port arithmetic: the slot
    lease IS the discovery record, so the gateway and `top` never
    guess ports."""
    spool = str(tmp_path)
    k0, rec0 = spoolproto.acquire_replica_slot(
        spool, "A", extra={"addr": "127.0.0.1", "port": 8850,
                           "replica": "A", "ready": True})
    k1, rec1 = spoolproto.acquire_replica_slot(
        spool, "B", extra={"addr": "127.0.0.1", "port": 8851,
                           "replica": "B", "ready": False})
    assert (k0, k1) == (0, 1)
    reps = spoolproto.discover_replicas(spool)
    assert [r["name"] for r in reps] == ["A", "B"]
    assert spoolproto.replica_endpoints(spool) == ["127.0.0.1:8850",
                                                  "127.0.0.1:8851"]
    # a dead replica's stale slot is reclaimed by the next joiner
    write_json_atomic(leaselib.lease_path(spool, "r0"),
                      dict(rec0, pid=987654,
                           renewed=time.time() - 999))
    k2, _ = spoolproto.acquire_replica_slot(spool, "C",
                                            extra={"port": 8850},
                                            lease_timeout=10.0)
    assert k2 == 0
    # `top` expands a spool directory into its slot-lease endpoints
    assert telemetry.expand_sources([spool]) == ["127.0.0.1:8850",
                                                 "127.0.0.1:8851"]


def test_expand_sources_empty_fleet_renders_unreachable(tmp_path):
    spool = str(tmp_path)
    srcs = telemetry.expand_sources([spool])
    assert len(srcs) == 1 and "<no-replicas>" in srcs[0]
    # non-directory sources pass through untouched
    assert telemetry.expand_sources(["127.0.0.1:9999"]) == [
        "127.0.0.1:9999"]


def test_fleet_series_schema_cross_check(tmp_path):
    """Every FLEET_SERVE_GAUGES / FLEET_REPLICA_GAUGES name renders
    exactly once as a ccsx_-prefixed family with one TYPE line — and
    nothing renders that the schema tuples do not declare."""
    spool = str(tmp_path)
    spoolproto.submit_job(spool, input_path="/x/in.fa", overrides={})
    spoolproto.acquire_replica_slot(
        spool, "A", extra={"addr": "127.0.0.1", "port": 8850,
                           "replica": "A", "ready": True,
                           "pressure": 0.25, "leases": 1})
    text = telemetry.render_fleet_series(
        spoolproto.fleet_summary(spool))
    declared = set(telemetry.FLEET_SERVE_GAUGES +
                   telemetry.FLEET_REPLICA_GAUGES)
    rendered = set()
    for ln in text.splitlines():
        if ln.startswith("# TYPE "):
            name = ln.split()[2]
            assert name.startswith("ccsx_")
            rendered.add(name[len("ccsx_"):])
    assert rendered == declared
    for g in telemetry.FLEET_REPLICA_GAUGES:
        assert f'ccsx_{g}{{replica="A"}}' in text


def test_bench_compare_serve_fleet_gates(monkeypatch):
    """The vs_prev serve-fleet leg: lost/duplicated jobs, byte
    identity, and steady recompiles regress OUTRIGHT; throughput obeys
    the 20% rule."""
    import bench

    def arts(cur, prev=None):
        out = [("serve_fleet_r90.json", cur)]
        if prev is not None:
            out.append(("serve_fleet_r89.json", prev))
        return out

    good = {"zmws_per_sec": 10.0, "recompiles": 0, "lost_jobs": 0,
            "duplicated_jobs": 0, "byte_identical": True, "ok": True}

    def run(cur, prev=None):
        monkeypatch.setattr(bench, "latest_serve_fleet_artifacts",
                            lambda *a, **k: arts(cur, prev))
        line, vp, reg = {}, {}, []
        bench.compare_serve_fleet(line, None, vp, reg)
        return line, vp, reg

    _, _, reg = run(good, good)
    assert reg == []
    _, _, reg = run(dict(good, lost_jobs=1), good)
    assert any("lost" in r for r in reg)
    _, _, reg = run(dict(good, duplicated_jobs=2), good)
    assert any("duplicated" in r for r in reg)
    _, _, reg = run(dict(good, byte_identical=False), good)
    assert any("byte-identical" in r for r in reg)
    _, _, reg = run(dict(good, recompiles=3), good)
    assert any("recompiles" in r for r in reg)
    _, _, reg = run(dict(good, ok=False), good)
    assert any("failed trials" in r for r in reg)
    _, vp, reg = run(dict(good, zmws_per_sec=7.9), good)
    assert any("throughput regression" in r for r in reg)
    assert vp["serve_fleet_zmws_per_sec"]["prev"] == 10.0
    _, _, reg = run(dict(good, zmws_per_sec=8.1), good)
    assert reg == []


def test_serve_replicas_flag_validation(capsys):
    assert supervisor.shepherd_main(["--serve-replicas", "2"]) == 1
    assert "--fleet SPOOL" in capsys.readouterr().err


# ---------- cross-replica handoff (two warm cores, one spool) ----------

def test_job_crosses_replicas_byte_identical(corpus, fleet_factory):
    """THE tentpole pin: submit through replica A with A's admission
    closed — B must lease the job from the shared spool, run it warm,
    and retire it with exactly one done marker, byte-identical to the
    CLI reference."""
    fa3, ref3, _, _ = corpus
    spool, make = fleet_factory
    # A's scan tick is pushed past the test horizon: it accepts the
    # submit but never leases work; B is the only puller
    a = make("A", poll_s=30.0)
    b = make("B")
    h = a.submit(input_path=fa3, overrides={})
    view = _wait_done(spool, h.id)
    assert view["state"] == "done" and view["replica"] == "B"
    assert open(view["output"], "rb").read() == ref3
    # exactly one done marker; the lease was released after it
    assert os.path.exists(spoolproto.done_marker_path(spool, h.id))
    assert leaselib.read_lease(spool, h.id) is None
    # the submitter's view agrees (spool-wide state, not local memory)
    assert a.wait(h.id, timeout=10) == "done"


def test_dead_replica_job_requeues_to_survivor(corpus, fleet_factory):
    """Replica death = requeue by construction: a job leased by a dead
    pid (stale heartbeat) is expired by the survivor's scan —
    kill-before-steal with the dead-pid SIGKILL a no-op — and
    completes with zero loss."""
    fa3, ref3, _, _ = corpus
    spool, make = fleet_factory
    jid = spoolproto.submit_job(spool, input_path=fa3, overrides={})
    # forge the dead replica's leavings: lease held by pid 987654,
    # heartbeat long stale (own host, so the kill path is exercised
    # against a pid that does not exist)
    rec = leaselib.try_acquire(spool, jid, "dead-replica",
                               extra={"host": "nosuchhost.invalid"})
    assert rec is not None
    write_json_atomic(leaselib.lease_path(spool, jid),
                      dict(rec, pid=987654, renewed=time.time() - 999))
    s = make("survivor")
    view = _wait_done(spool, jid)
    assert view["state"] == "done" and view["replica"] == "survivor"
    assert open(view["output"], "rb").read() == ref3
    # the dead holder's lease went through the graveyard, not deletion
    assert os.listdir(os.path.join(spool, leaselib.GRAVEYARD))
    del s


def test_cross_replica_cancel_lands_at_renewal(corpus, fleet_factory):
    """The gateway cancel path: a cancel marked on the SPOOL RECORD
    (not the holder's HTTP API) is observed at the holder's next
    heartbeat renewal, aborts rc 75 through the job's own guard, and
    leaves the sibling job untouched (PR 15 blast radius)."""
    fa3, ref3, _, _ = corpus
    spool, make = fleet_factory
    c = make("A", max_active=2)
    victim = c.submit(input_path=fa3,
                      overrides={"faults": "stall@1"})
    sibling = c.submit(input_path=fa3, overrides={})
    deadline = time.monotonic() + 60
    while (leaselib.read_lease(spool, victim.id) is None
           and time.monotonic() < deadline):
        time.sleep(0.05)  # wait for A to lease the victim
    state, changed = spoolproto.mark_cancel(spool, victim.id)
    assert changed
    view = _wait_done(spool, victim.id)
    assert view["state"] == "cancelled"
    assert view["rc"] == exitcodes.RC_INTERRUPTED
    sview = _wait_done(spool, sibling.id)
    assert sview["state"] == "done"
    assert open(sview["output"], "rb").read() == ref3


def test_cancel_queued_job_retired_without_running(fleet_factory):
    """A job cancelled while still queued is retired 'cancelled' by
    whichever replica sees it first — it never runs."""
    spool, make = fleet_factory
    os.makedirs(spool, exist_ok=True)
    jid = spoolproto.submit_job(spool, input_path="/nonexistent.fa",
                                overrides={})
    spoolproto.mark_cancel(spool, jid)
    make("A", max_active=1)   # the scan retires it before any run
    view = _wait_done(spool, jid, timeout=30)
    assert view["state"] == "cancelled"
    assert view["rc"] == exitcodes.RC_INTERRUPTED


# ---------- the gateway (HTTP balancer over the spool) ----------

def _http(port):
    base = f"http://127.0.0.1:{port}"

    def req(method, path, data=None):
        r = urllib.request.Request(base + path, data=data,
                                   method=method)
        if data is not None:
            r.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(r, timeout=30) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    return req


def test_gateway_503_retry_after_when_no_replica_ready(tmp_path):
    """An empty fleet (or all replicas draining) answers POST /jobs
    with 503 + Retry-After, never enqueueing into a spool nobody
    serves."""
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    gw = Gateway(spool, probe_s=0.05)
    srv = telemetry.TelemetryServer(
        None, 0, host="127.0.0.1", handler=_gateway_handler(),
        attrs={"ccsx_gateway": gw, "ccsx_ready": gw.readiness})
    try:
        req = _http(srv.port)
        code, body, hdrs = req("POST", "/jobs",
                               json.dumps({"input": "/x.fa"}).encode())
        assert code == 503
        assert hdrs.get("Retry-After") == "5"
        assert spoolproto.list_job_ids(spool) == []
        code, body, _ = req("GET", "/readyz")
        assert code == 503 and json.loads(body)["ready"] is False
        # liveness stays 200 (the gateway itself is up)
        code, _, _ = req("GET", "/healthz")
        assert code == 200
    finally:
        srv.close()


def test_gateway_routes_submit_to_ready_replica(corpus, fleet_factory):
    """End to end through HTTP: replica serves /readyz, gateway
    discovers it via its slot lease, accepts the POST, the replica
    completes it, the gateway serves the output bytes and the
    ccsx_fleet_* gauges."""
    fa3, ref3, _, _ = corpus
    spool, make = fleet_factory
    core = make("A")
    rsrv = telemetry.TelemetryServer(
        core.metrics, 0, host="127.0.0.1", handler=_serve_handler(),
        attrs={"ccsx_core": core, "ccsx_ready": core.readiness})
    core.register_replica()
    core.set_advertised(rsrv.port)
    gw = Gateway(spool, probe_s=0.05)
    gsrv = telemetry.TelemetryServer(
        None, 0, host="127.0.0.1", handler=_gateway_handler(),
        attrs={"ccsx_gateway": gw, "ccsx_ready": gw.readiness})
    try:
        req = _http(gsrv.port)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, body, _ = req("GET", "/readyz")
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200, body
        code, body, _ = req("POST", "/jobs",
                            json.dumps({"input": fa3}).encode())
        assert code == 201, body
        jid = json.loads(body)["id"]
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            code, body, _ = req("GET", f"/jobs/{jid}")
            if json.loads(body).get("state") == "done":
                break
            time.sleep(0.2)
        assert json.loads(body)["state"] == "done", body
        code, out, _ = req("GET", f"/jobs/{jid}/output")
        assert code == 200 and out == ref3
        # discovery + autoscale gauges
        code, body, _ = req("GET", "/replicas")
        reps = json.loads(body)["replicas"]
        assert [r["name"] for r in reps] == ["A"]
        assert reps[0]["port"] == rsrv.port
        code, body, _ = req("GET", "/metrics")
        text = body.decode()
        for g in telemetry.FLEET_SERVE_GAUGES:
            assert f"ccsx_{g}" in text
        assert "ccsx_fleet_jobs_retired 1" in text
        # DELETE of a retired job conflicts (409), unknown is 404
        code, _, _ = req("DELETE", f"/jobs/{jid}")
        assert code == 409
        code, _, _ = req("DELETE", "/jobs/j99999")
        assert code == 404
    finally:
        gsrv.close()
        rsrv.close()


# ---------- fan-out (slow: full e2e through the range queue) ----------

@pytest.mark.slow  # ~30s: cross-replica fan-out e2e; the handoff,
# requeue and cancel pins above keep the lease domain tier-1
def test_fanout_job_splits_and_merges_byte_identical(corpus,
                                                     fleet_factory):
    """A job above --fanout-holes splits through the PR 13 range queue
    under the holder's warm runtime, helpers pull ranges from sibling
    replicas, and the merged output is byte-identical to the CLI
    reference."""
    _, _, fa8, ref8 = corpus
    spool, make = fleet_factory
    a = make("A", fanout_holes=4, fanout_ranges=3)
    b = make("B", fanout_holes=4, fanout_ranges=3)
    h = a.submit(input_path=fa8, overrides={})
    view = _wait_done(spool, h.id, timeout=300)
    assert view["state"] == "done", view
    assert open(view["output"], "rb").read() == ref8
    # the fan-out scratch dir is cleaned up after the merge
    assert not os.path.exists(os.path.join(spool, f"fanout.{h.id}"))
    # the spool record advertised the split (helper discovery channel)
    assert (spoolproto.read_job_record(spool, h.id) or {}).get("fanout")
    del b
