"""Per-base quality output (--fastq): vote-margin Phred qualities.

An extension over the reference, which writes FASTA only (main.c:714) —
so there is no reference behavior to match; these tests pin internal
consistency instead: FASTQ well-formedness, seq==FASTA-seq invariance,
batched==per-hole byte parity, and the quality semantics (higher pass
count / unanimity => higher Q; disagreement lowers Q).
"""

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus.star import StarMsa
from ccsx_tpu.io import fastx
from ccsx_tpu.utils import synth


def _write_fasta(tmp_path, rng, n_holes=3, tlen=700, n_passes=5):
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=n_passes + (h % 3),
                         movie="mv", hole=str(h)) for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def test_fastq_well_formed_and_seq_matches_fasta(tmp_path, rng):
    """--fastq output: 4-line records, qual length == seq length, and the
    sequences byte-equal the FASTA run's."""
    zs, fa = _write_fasta(tmp_path, rng)
    ofa, ofq = tmp_path / "o.fa", tmp_path / "o.fq"
    assert cli.main(["-A", "-m", "1000", str(fa), str(ofa)]) == 0
    assert cli.main(["-A", "-m", "1000", "--fastq", str(fa), str(ofq)]) == 0
    fq = list(fastx.read_fastx(str(ofq)))
    fa_recs = list(fastx.read_fastx(str(ofa)))
    assert len(fq) == len(fa_recs) == len(zs)
    for a, q in zip(fa_recs, fq):
        assert a.name == q.name
        assert a.seq == q.seq
        assert q.qual is not None and len(q.qual) == len(q.seq)
        # phred+33, within the configured cap
        arr = np.frombuffer(q.qual, np.uint8) - 33
        assert arr.min() >= 1 and arr.max() <= CcsConfig.qv_cap


@pytest.mark.parametrize("batch", [
    "on",
    # "off" is the legacy-path arm of the same FASTQ A/B; "on" keeps
    # the batched FASTQ identity tier-1 (r16 budget audit)
    pytest.param("off", marks=pytest.mark.slow),
])
def test_fastq_batched_equals_per_hole(tmp_path, rng, batch):
    """--fastq byte parity between the fused batched path and the
    per-hole path (qualities derive from transferred nwin/votes)."""
    zs, fa = _write_fasta(tmp_path, rng, n_holes=3)
    o1, o2 = tmp_path / "a.fq", tmp_path / "b.fq"
    assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", "off",
                     str(fa), str(o1)]) == 0
    assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", batch,
                     str(fa), str(o2)]) == 0
    assert o1.read_text() == o2.read_text()


@pytest.mark.slow  # ~26s: long-molecule FASTQ run in both drivers
def test_fastq_multiwindow_stitching_batched_parity(tmp_path, rng):
    """A >1-window molecule: per-window qual slices (materialize upto
    the breakpoint) must stitch to the same FASTQ in the per-hole and
    fused batched paths."""
    zs = [synth.make_zmw(rng, template_len=2600, n_passes=6,
                         movie="mv", hole=str(h)) for h in range(2)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    o1, o2 = tmp_path / "a.fq", tmp_path / "b.fq"
    win = ["--refine-iters", "2"]
    base = ["-A", "-m", "1000", "--fastq"] + win
    assert cli.main(base + ["--batch", "off", str(fa), str(o1)]) == 0
    assert cli.main(base + ["--batch", "on", str(fa), str(o2)]) == 0
    assert o1.read_text() == o2.read_text()
    for r in fastx.read_fastx(str(o1)):
        assert len(r.qual) == len(r.seq) > 2000


@pytest.mark.slow  # ~27s: FASTQ twin of the journal-resume A/B;
# test_batch's test_cli_batched_journal_resume and the FASTQ
# well-formedness pin stay tier-1 (r16 budget audit)
def test_fastq_journal_resume(tmp_path, rng):
    """Resuming a --fastq run appends well-formed FASTQ records."""
    import json

    zs, fa = _write_fasta(tmp_path, rng, n_holes=3)
    full = tmp_path / "full.fq"
    assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", "on",
                     str(fa), str(full)]) == 0
    out = tmp_path / "o.fq"
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"input_id": str(fa), "holes_done": 2}))
    recs = list(fastx.read_fastx(str(full)))
    out.write_text("".join(
        f"@{r.name}\n{r.seq.decode()}\n+\n{r.qual.decode()}\n"
        for r in recs[:2]))
    assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", "on",
                     "--journal", str(jp), str(fa), str(out)]) == 0
    assert out.read_text() == full.read_text()


def test_fastq_whole_read_mode(tmp_path, rng):
    zs, fa = _write_fasta(tmp_path, rng, n_holes=2)
    out = tmp_path / "o.fq"
    assert cli.main(["-A", "-P", "-m", "1000", "--fastq",
                     str(fa), str(out)]) == 0
    recs = list(fastx.read_fastx(str(out)))
    assert len(recs) == 2
    for r in recs:
        assert len(r.qual) == len(r.seq)


def test_bam_output_roundtrip(tmp_path, rng):
    """--bam: unaligned BAM whose seq/qual round-trip through the BAM
    reader equal the --fastq run's records, plus a sane rq aux tag."""
    from ccsx_tpu.io import bam as bam_mod

    zs, fa = _write_fasta(tmp_path, rng)
    ofq, obam = tmp_path / "o.fq", tmp_path / "o.bam"
    assert cli.main(["-A", "-m", "1000", "--fastq", str(fa), str(ofq)]) == 0
    assert cli.main(["-A", "-m", "1000", "--bam", str(fa), str(obam)]) == 0
    fq = {r.name: r for r in fastx.read_fastx(str(ofq))}
    n = 0
    for rec, aux in bam_mod.read_bam_records(str(obam), with_aux=True):
        want = fq[rec.name]
        assert rec.seq == want.seq
        assert rec.qual == want.qual  # phred+33, identical to FASTQ
        rq = bam_mod.aux2f(aux, "rq")
        # predicted accuracy from the (conservative) vote-margin quals
        assert 0.8 < rq < 1.0
        n += 1
    assert n == len(fq) == len(zs)


def test_bam_output_flag_guards(tmp_path, rng, capsys):
    """--bam rejects --journal (unresumable container), --fastq
    (conflicting formats), and an unwritable path — all up front,
    before any compute."""
    zs, fa = _write_fasta(tmp_path, rng, n_holes=2)
    rc = cli.main(["-A", "-m", "1000", "--bam", "--journal",
                   str(tmp_path / "j.json"), str(fa),
                   str(tmp_path / "o.bam")])
    assert rc == 1 and "--journal" in capsys.readouterr().err
    rc = cli.main(["-A", "--bam", "--fastq", str(fa),
                   str(tmp_path / "o.bam")])
    assert rc == 1 and "mutually exclusive" in capsys.readouterr().err
    rc = cli.main(["-A", "-m", "1000", "--bam", str(fa),
                   str(tmp_path / "no" / "dir" / "o.bam")])
    assert rc == 1 and "write" in capsys.readouterr().err.lower()


def test_quality_rises_with_pass_count(rng):
    """Mean vote-margin Q must increase with coverage (the whole point)."""
    from ccsx_tpu.consensus import whole_read

    tpl = rng.integers(0, 4, 600).astype(np.uint8)
    means = []
    for n in (4, 8, 16):
        cfg = CcsConfig(is_bam=False, emit_quality=True)
        ps = [synth.mutate(rng, tpl, 0.02, 0.04, 0.04) for _ in range(n)]
        codes, quals = whole_read.consensus_passes(ps, cfg)
        assert len(quals) == len(codes)
        means.append(float(np.mean(quals)))
    assert means[0] < means[1] < means[2], means


@pytest.mark.slow  # ~14s calibration sweep; quality_rises_with_pass_count
# and quality_drops_at_disputed_columns stay tier-1 (r16 budget audit)
def test_quality_calibration_monotone(rng):
    """Observed per-base error must fall as predicted Q rises — at the
    5-Q bin granularity (VERDICT r3 weak 7: the old single net-vote
    slope dipped in [15,20) vs [10,15); the coverage-conditioned
    qv_coeffs model must not).  Adjacent well-populated 5-Q bins must be
    non-increasing in observed error, and the coarse 3-way split
    strictly decreasing."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks"))
    import quality as qmod

    # (a) the committed full-size calibration artifact must be monotone
    # at 5-Q granularity for well-populated bins — the strong gate, at a
    # sample size where 2-3 Poisson errors can't fake an inversion.  The
    # artifact is regenerated every round by benchmarks/quality.py.
    import glob
    import json
    import re

    # newest artifact by NUMERIC round (lexicographic sort breaks at
    # r100: quality_r100 < quality_r11)
    arts = sorted(
        glob.glob(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "quality_r*.json")),
        key=lambda p: int(re.search(r"quality_r(\d+)", p).group(1)))
    with open(arts[-1]) as f:
        art = json.load(f)
    # the gate is only meaningful if the artifact was generated under
    # the CURRENT qv model — a coefficient change without regeneration
    # must fail here, not pass vacuously against stale data.  r05+
    # artifacts always record qv_coeffs (benchmarks/quality.py).
    from ccsx_tpu.config import CcsConfig
    assert art.get("qv_coeffs") == list(CcsConfig(is_bam=False).qv_coeffs), (
        "stale calibration artifact: regenerate benchmarks/quality_r*.json "
        "after changing qv coefficients")
    table = art["quality_calibration"]
    pop = [b for b in table if b["bases"] >= 500]
    assert len(pop) >= 5, "artifact calibration table too thin"
    for a, b in zip(pop, pop[1:]):
        assert a["observed_error_rate"] >= b["observed_error_rate"], (a, b)
    # (b) live smoke at small sample: coarse 3-way split must still be
    # strictly decreasing (small-sample noise can't invert bins this wide)
    bins = qmod.quality_calibration(rng, n_holes=8, tlen=400)
    rates = {}
    for b in bins:
        lo = int(b["predicted_q"].split(",")[0][1:])
        coarse = 0 if lo < 10 else (1 if lo < 20 else 2)
        e, n = rates.get(coarse, (0, 0))
        rates[coarse] = (e + b["observed_error_rate"] * b["bases"],
                         n + b["bases"])
    assert set(rates) == {0, 1, 2}
    r = [rates[k][0] / rates[k][1] for k in (0, 1, 2)]
    assert r[0] > r[1] > r[2], r


def test_quality_drops_at_disputed_columns(rng):
    """A column where passes split must score lower than unanimous ones."""
    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    tpl = rng.integers(0, 4, 400).astype(np.uint8)
    ps = [tpl.copy() for _ in range(8)]
    # half the passes disagree at one column
    disputed = 200
    for p in ps[:4]:
        p[disputed] = (p[disputed] + 1) % 4
    qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
    rr = sm.round(qs, qlens, row_mask, tpl)
    codes, quals = rr.materialize_with_qual()
    np.testing.assert_array_equal(codes, tpl)  # 4-4 tie keeps a base
    assert quals[disputed] < quals[disputed - 1]
    assert quals[disputed] <= 2  # 4 dissenters -> floor (8+12-24 < 1)
    # unanimous 8-pass columns: 8 + 3*5 + 1*3 = 26 (qv_coeffs default,
    # knee at 5 supporters)
    assert quals[disputed - 1] == 26


def test_apply_hp_penalty_final_assembly():
    """The hp penalty runs on the FINAL assembled consensus: a run that
    a window boundary would split must be penalized at its true length
    (r5 code-review finding), and a 5-tuple (r4 coeffs) is a no-op."""
    from ccsx_tpu.consensus.star import apply_hp_penalty

    # AAAAA CG: run of 5 (capped at 4 units), then runs of 1
    codes = np.array([0, 0, 0, 0, 0, 1, 2], np.uint8)
    quals = np.full(7, 30, np.uint8)
    coeffs = (8.0, 3.0, 6.0, 5, 1.0, 7.0, 4)
    out = apply_hp_penalty(codes, quals, coeffs)
    np.testing.assert_array_equal(out[:5], 30 - 28)   # 7*min(4,4)
    np.testing.assert_array_equal(out[5:], 30)
    # floor at 1
    out2 = apply_hp_penalty(codes, np.full(7, 5, np.uint8), coeffs)
    assert out2[:5].max() == 1
    # r4-compatible 5-tuple: untouched
    np.testing.assert_array_equal(
        apply_hp_penalty(codes, quals, coeffs[:5]), quals)
    # the regression shape: two chunks of the same run scored separately
    # (2+3 split: 7*1 and 7*2) under-penalize vs the assembled run
    split = np.concatenate([
        apply_hp_penalty(codes[:2], quals[:2], coeffs),
        apply_hp_penalty(codes[2:], quals[2:], coeffs)])
    assert (split[:5] > out[:5]).all()
