"""NumPy oracle: full (unbanded) affine-gap pairwise alignment with traceback.

This is the executable *spec* against which the batched/banded device kernels
(ops/banded.py, ops/pallas/*) are differentially tested, and the scalar
reference implementation of the consensus algorithm (SURVEY.md §7.2 step 2).
It replicates the alignment semantics ccsx consumes from bsalign
(kmer_striped_seqedit_pairwise at main.c:264; result fields per
seqalign_result_t, main.c:272-280) without reusing its implementation:
a plain Gotoh affine-gap DP.

Modes
-----
  global : both sequences end-to-end (Needleman-Wunsch/Gotoh).
  qfree  : query prefix/suffix free, template end-to-end — used by
           strand_match-style orientation tests where a longer pass is
           clipped to the template span [qb, qe) (main.c:392-394).
  local  : Smith-Waterman (both-ends-free), closest to the reference's
           seeded pairwise behavior on diverged ends.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

NEG = -(10 ** 9)


@dataclasses.dataclass
class AlnResult:
    """Mirrors the fields ccsx reads from seqalign_result_t (main.c:272-280)."""

    score: int
    qb: int
    qe: int
    tb: int
    te: int
    aln: int          # alignment columns
    mat: int          # exact matches
    mis: int
    ins: int          # query-only bases (gap in template)
    del_: int         # template-only bases (gap in query)
    cigar: List[Tuple[str, int]]  # ops over [qb,qe)x[tb,te), 'M','I','D'

    @property
    def identity(self) -> float:
        return self.mat / self.aln if self.aln else 0.0


def _push(cigar: List[Tuple[str, int]], op: str):
    if cigar and cigar[-1][0] == op:
        cigar[-1] = (op, cigar[-1][1] + 1)
    else:
        cigar.append((op, 1))


def align(
    q: np.ndarray,
    t: np.ndarray,
    mode: str = "global",
    match: int = 2,
    mismatch: int = -6,
    gap_open: int = -3,
    gap_extend: int = -2,
) -> AlnResult:
    """Affine-gap DP; a gap of length L costs gap_open + L*gap_extend."""
    q = np.asarray(q, dtype=np.int32)
    t = np.asarray(t, dtype=np.int32)
    Q, T = len(q), len(t)
    oe = gap_open + gap_extend

    H = np.full((Q + 1, T + 1), NEG, dtype=np.int64)
    E = np.full((Q + 1, T + 1), NEG, dtype=np.int64)  # gap in template (up moves)
    F = np.full((Q + 1, T + 1), NEG, dtype=np.int64)  # gap in query (left moves)

    H[0, 0] = 0
    if mode == "global":
        for i in range(1, Q + 1):
            E[i, 0] = gap_open + i * gap_extend
            H[i, 0] = E[i, 0]
        for j in range(1, T + 1):
            F[0, j] = gap_open + j * gap_extend
            H[0, j] = F[0, j]
    elif mode == "qfree":
        H[1:, 0] = 0
        for j in range(1, T + 1):
            F[0, j] = gap_open + j * gap_extend
            H[0, j] = F[0, j]
    elif mode == "local":
        H[:, 0] = 0
        H[0, :] = 0
    else:
        raise ValueError(mode)

    sub = np.where(q[:, None] == t[None, :], match, mismatch)
    # N (code 4) never matches anything, including itself
    sub[(q >= 4)[:, None] | (t >= 4)[None, :]] = mismatch

    for i in range(1, Q + 1):
        Erow = np.maximum(H[i - 1, :] + oe, E[i - 1, :] + gap_extend)
        E[i, :] = Erow
        Hrow = H[i, :]
        Frow = F[i, :]
        diag = H[i - 1, :-1] + sub[i - 1]
        for j in range(1, T + 1):
            f = max(Hrow[j - 1] + oe, Frow[j - 1] + gap_extend)
            Frow[j] = f
            h = max(diag[j - 1], Erow[j], f)
            if mode == "local":
                h = max(h, 0)
            if h > Hrow[j]:
                Hrow[j] = h

    # --- pick the end cell ---
    if mode == "global":
        ei, ej = Q, T
    elif mode == "qfree":
        ei = int(np.argmax(H[:, T]))
        ej = T
    else:
        ei, ej = np.unravel_index(int(np.argmax(H)), H.shape)
    score = int(H[ei, ej])

    # --- traceback ---
    cigar: List[Tuple[str, int]] = []
    i, j = ei, ej
    state = "H"
    mat = mis = ins = dl = 0
    while True:
        if state == "H":
            if mode == "local" and H[i, j] == 0:
                break
            if mode == "qfree" and j == 0:
                break
            if mode == "global" and i == 0 and j == 0:
                break
            if i > 0 and j > 0 and H[i, j] == H[i - 1, j - 1] + sub[i - 1, j - 1]:
                _push(cigar, "M")
                if q[i - 1] == t[j - 1] and q[i - 1] < 4:
                    mat += 1
                else:
                    mis += 1
                i -= 1
                j -= 1
            elif i > 0 and H[i, j] == E[i, j]:
                state = "E"
            elif j > 0 and H[i, j] == F[i, j]:
                state = "F"
            else:  # boundary rows in global mode
                if i > 0:
                    state = "E"
                else:
                    state = "F"
        elif state == "E":
            _push(cigar, "I")
            ins += 1
            if E[i, j] == (E[i - 1, j] + gap_extend) and i > 1:
                i -= 1
            else:
                i -= 1
                state = "H"
        else:  # F
            _push(cigar, "D")
            dl += 1
            if F[i, j] == (F[i, j - 1] + gap_extend) and j > 1:
                j -= 1
            else:
                j -= 1
                state = "H"

    cigar.reverse()
    qb, tb = i, j
    return AlnResult(
        score=score, qb=qb, qe=ei, tb=tb, te=ej,
        aln=mat + mis + ins + dl, mat=mat, mis=mis, ins=ins, del_=dl,
        cigar=cigar,
    )


def strand_match_oracle(q, t, similarity_pct: int, **scores) -> Tuple[bool, AlnResult]:
    """Acceptance rule of strand_match (main.c:280):
    aln*2 > min(qlen, tlen) and mat*100 >= aln*similarity_pct."""
    rs = align(q, t, mode="local", **scores)
    ok = (rs.aln * 2 > min(len(q), len(t))) and (rs.mat * 100 >= rs.aln * similarity_pct)
    return ok, rs


def project_to_template(
    rs: AlnResult, q: np.ndarray, tlen: int, max_ins: int = 4
) -> tuple:
    """Convert a traceback into the star-MSA projection used by consensus.

    Returns (aligned, ins_len, ins_bases, covered):
      aligned[j]  : query code (0-3) aligned to template position j, 4 if the
                    alignment deletes j, 5 if j is outside [tb, te).
      ins_len[j]  : number of query bases inserted AFTER template position j
                    (insertions before tb are credited to slot tb-1; an
                    insertion before template position 0 is dropped).
      ins_bases[j]: first max_ins inserted base codes after j (5-padded).
      covered[j]  : True for tb <= j < te.
    """
    aligned = np.full(tlen, 5, dtype=np.uint8)
    ins_len = np.zeros(tlen, dtype=np.int32)
    ins_bases = np.full((tlen, max_ins), 5, dtype=np.uint8)
    covered = np.zeros(tlen, dtype=bool)
    covered[rs.tb:rs.te] = True

    qi, tj = rs.qb, rs.tb
    for op, ln in rs.cigar:
        if op == "M":
            aligned[tj:tj + ln] = q[qi:qi + ln]
            qi += ln
            tj += ln
        elif op == "D":
            aligned[tj:tj + ln] = 4
            tj += ln
        else:  # I — insertion after template position tj-1
            slot = tj - 1
            if slot >= 0:
                base = ins_len[slot]
                take = min(ln, max(0, max_ins - base))
                if take > 0:
                    ins_bases[slot, base:base + take] = q[qi:qi + take]
                ins_len[slot] += ln
            qi += ln
    return aligned, ins_len, ins_bases, covered
