#!/bin/sh
# End-of-round TPU measurement battery.  Run when the tunnel is healthy;
# each step is its own process (the axon tunnel flips to sync dispatch
# after any d2h transfer, so round metrics must be taken in a fresh
# process before e2e-style transfers — see memory/axon notes).
#
#   sh benchmarks/tpu_battery.sh            # full battery
#
# Order: (1) bench.py — also re-warms the persistent compile cache for
# the driver's end-of-round bench; (2) Pallas A/B hardware check +
# timing; (3) per-stage round profile + jax.profiler trace; (4) e2e at
# scale (256 holes, inflight 64).
set -x
cd "$(dirname "$0")/.."

# priority order for a short recovery window: the round number + cache
# warm first, then the scale evidence (VERDICT r3 item 2), then A/B and
# profiles
python bench.py | tee benchmarks/bench_tpu_r05.json

python benchmarks/e2e_scale.py --holes 256 --inflight 64 \
    --json benchmarks/e2e_scale_r05.json

python benchmarks/pallas_ab.py --mode check
python benchmarks/pallas_ab.py --mode time --gblocks 8,16,32 \
    --json benchmarks/pallas_ab_tpu_r05.json

python benchmarks/round_profile.py --trace-dir benchmarks/trace_r05 \
    --json benchmarks/round_profile_r05.json
CCSX_PROJECTOR=scan python benchmarks/round_profile.py \
    --json benchmarks/round_profile_r05_scanproj.json
