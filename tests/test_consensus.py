"""Tests for prepare (grouping/orientation) and whole-read star consensus."""

import numpy as np
import pytest

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import prepare as prep
from ccsx_tpu.consensus import whole_read
from ccsx_tpu.consensus.align_host import HostAligner
from ccsx_tpu.io import zmw as zmw_mod
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.utils import synth

CFG = CcsConfig(is_bam=False, len_bucket_quant=512)


# ---------- length grouping ----------

def test_group_lens_basic():
    lens = [1000, 1010, 990, 5000, 1005, 4990]
    groups = prep.group_lens(lens, 10)
    assert groups[0].size == 4           # the ~1000 cluster is biggest
    assert sorted(groups[0].ids) == [0, 1, 2, 4]
    assert sorted(groups[1].ids) == [3, 5]


def test_group_lens_transitive_merge():
    # 110 cannot join {100} directly (|110-100|*100 == 1000 is not < 1000),
    # so it forms its own group; once 105 joins {100} the means are within
    # tolerance and the merge phase unifies them (main.c:169-195)
    lens = [100, 110, 105]
    groups = prep.group_lens(lens, 10)
    assert len(groups) == 1
    assert groups[0].size == 3


def test_group_lens_singletons():
    lens = [100, 500, 2500]
    groups = prep.group_lens(lens, 10)
    assert len(groups) == 3
    assert all(g.size == 1 for g in groups)


def test_len_in_group_integer_rule():
    g = prep.LenGroup([0, 1], 2000)       # mean 1000
    assert prep.len_in_group(g, 1049, 10)   # |1049*2-2000|=98 < 0.1*2000=200
    assert not prep.len_in_group(g, 1100, 10)
    assert not prep.len_in_group(g, 900, 10)


# ---------- prepare / orientation walk ----------

def _zmw_from_synth(z):
    seqs = b"".join(enc.decode(p).encode() for p in z.passes)
    lens = np.array([len(p) for p in z.passes], np.int32)
    offs = np.zeros(len(lens), np.int32)
    np.cumsum(lens[:-1], out=offs[1:])
    return zmw_mod.Zmw(z.movie, z.hole, seqs, lens, offs)


@pytest.mark.parametrize("n_passes,first_strand", [(5, 0), (6, 1)])
def test_prepare_orientation_parity(n_passes, first_strand, rng):
    z = synth.make_zmw(rng, template_len=1000, n_passes=n_passes,
                       first_strand=first_strand)
    zz = _zmw_from_synth(z)
    codes = enc.encode(zz.seqs)
    aligner = HostAligner(CFG.align)
    segs = prep.ccs_prepare(codes, zz.lens, zz.offs, aligner, CFG)
    assert len(segs) == n_passes          # all passes kept
    template_i = n_passes // 2            # ids in insertion order
    # template first, not reversed (it defines the frame)
    assert segs[0].offs == int(zz.offs[template_i])
    assert not segs[0].reverse
    # every segment's reverse flag must match ground truth relative strand
    t_strand = z.strands[template_i]
    seg_by_offs = {s.offs: s for s in segs}
    for k in range(n_passes):
        s = seg_by_offs[int(zz.offs[k])]
        assert s.reverse == (z.strands[k] != t_strand), k


def test_prepare_drops_unalignable_pass(rng):
    z = synth.make_zmw(rng, template_len=1000, n_passes=5)
    # replace last pass with random junk of in-group length
    junk = rng.integers(0, 4, 1000).astype(np.uint8)
    z.passes[-1] = junk
    zz = _zmw_from_synth(z)
    codes = enc.encode(zz.seqs)
    aligner = HostAligner(CFG.align)
    segs = prep.ccs_prepare(codes, zz.lens, zz.offs, aligner, CFG)
    # junk is in the length group and parity-trusted *until* a mismatch event;
    # at minimum the first 4 passes survive and junk is never *aligned* in
    assert len(segs) >= 4


def test_prepare_clips_double_length_pass(rng):
    """A pass of ~2x template length (missed adapter) must be clipped to
    one template span (main.c:392-394)."""
    tpl = rng.integers(0, 4, 1000).astype(np.uint8)
    z = synth.make_zmw(rng, n_passes=5, template=tpl)
    z.passes.append(synth.read_through(rng, tpl))
    z.strands.append(0)
    zz = _zmw_from_synth(z)
    codes = enc.encode(zz.seqs)
    aligner = HostAligner(CFG.align)
    segs = prep.ccs_prepare(codes, zz.lens, zz.offs, aligner, CFG)
    clipped = [s for s in segs if s.offs >= int(zz.offs[5])]
    if clipped:  # if kept, it must be clipped to ~template length
        assert abs(clipped[0].length - 1000) < 150


# ---------- whole-read consensus ----------

@pytest.mark.parametrize("n_passes,min_identity", [(5, 0.98), (8, 0.992)])
def test_whole_read_consensus_identity(n_passes, min_identity, rng):
    z = synth.make_zmw(rng, template_len=800, n_passes=n_passes,
                       sub_rate=0.02, ins_rate=0.04, del_rate=0.04)
    zz = _zmw_from_synth(z)
    aligner = HostAligner(CFG.align)
    cns, _ = whole_read.ccs_whole_read(zz, aligner, CFG)
    assert cns is not None
    idy = synth.identity(enc.encode(cns), z.template)
    assert idy >= min_identity, f"consensus identity {idy:.4f}"


def test_whole_read_too_few_passes(rng):
    z = synth.make_zmw(rng, template_len=800, n_passes=2)
    zz = _zmw_from_synth(z)
    aligner = HostAligner(CFG.align)
    assert whole_read.ccs_whole_read(zz, aligner, CFG) is None


@pytest.mark.slow  # ~25s: consensus at four pass depths
def test_quality_scales_with_passes(rng):
    """CCS signature: consensus accuracy must rise with pass count
    (>=Q20 by ~6 passes, >=Q25 by 10 at the default noise profile)."""
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.consensus.whole_read import consensus_passes
    from ccsx_tpu.ops import encode as enc
    from ccsx_tpu.utils import synth

    cfg = CcsConfig(is_bam=False)

    def run(n):
        idys = []
        for _ in range(3):
            z = synth.make_zmw(rng, template_len=700, n_passes=n)
            ps = [enc.revcomp_codes(p) if s else p
                  for p, s in zip(z.passes, z.strands)]
            cns = consensus_passes(ps, cfg)
            idys.append(synth.identity_either(cns, z.template))
        return float(np.mean(idys))

    i6, i10 = run(6), run(10)
    assert i6 > 0.99, i6
    assert i10 > 0.995, i10
    assert i10 >= i6 - 1e-6


def test_refine_fixpoint_early_exit_identical(rng):
    """The fixpoint early-exit must be invisible in the output and must
    actually save dispatches on a converged hole."""
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.consensus.hole import _counted
    from ccsx_tpu.consensus.star import StarMsa, run_rounds
    from ccsx_tpu.utils import synth

    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    tpl = rng.integers(0, 4, 400).astype(np.uint8)
    ps = [synth.mutate(rng, tpl, 0.005, 0.01, 0.01) for _ in range(8)]

    # reference driver WITHOUT the skip: always iters+1 rounds
    qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
    draft = ps[0]
    for it in range(cfg.refine_iters + 1):
        rr = sm.round(qs, qlens, row_mask, draft)
        draft = rr.materialize(speculative=(it < cfg.refine_iters))
    want = draft

    stats = {}
    gen = _counted(sm.consensus_gen(ps, cfg.refine_iters, cfg.pass_buckets,
                                    cfg.max_passes), stats)
    got = run_rounds(gen, sm)
    np.testing.assert_array_equal(want, got)
    # 8 nearly-clean passes converge after one speculative round
    assert stats["windows"] < cfg.refine_iters + 1, stats
