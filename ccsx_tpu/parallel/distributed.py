"""Multi-host distribution (SURVEY.md §5.8).

The reference is strictly single-host (no MPI/NCCL/sockets anywhere in the
repo; its "communication backend" is pthread mutex/condvar + atomics,
kthread.c:30-223).  The TPU framework scales across hosts the JAX way:

  * control plane — ``jax.distributed.initialize`` over DCN (one process
    per host); collectives inside jitted steps ride ICI within a slice via
    the mesh in parallel/mesh.py.
  * input sharding — every host reads the same input stream and owns the
    holes with ``global_index % num_processes == process_index``
    (round-robin over the *filtered* hole stream, so the assignment is a
    pure function of the input and needs no coordination).  ZMWs are
    independent, so the hot path has zero cross-host traffic.
  * output — each host writes ``<out>.shard<r>`` plus a sidecar index of
    the global hole ordinal per record; ``merge_shards`` restores the
    reference's input-ordered single FASTA exactly (kthread.c:202-213
    ordering invariant, across hosts).

The round-robin-over-one-stream design trades redundant parsing (every
host decodes the full input) for zero coordination; with the native C++
reader parsing is far faster than consensus, so this is the right trade
until per-host byte-range BAM splitting (BGZF chunking) is worth it.
"""

from __future__ import annotations

import heapq
import os
import sys
from typing import Iterator, Optional

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.utils.journal import Journal
from ccsx_tpu.utils.metrics import Metrics


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> tuple:
    """Initialize JAX's distributed runtime; returns (process_id, n).

    With no arguments, relies on the environment (TPU pod metadata or
    JAX_* env vars).  Safe to call once per process before any backend
    use.  Single-process callers should not call this at all.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index(), jax.process_count()


def shard_stream(stream, rank: int, n: int) -> Iterator:
    """Round-robin hole ownership: yields this rank's holes (the local
    ordinal k maps to global ordinal rank + k*n)."""
    for i, z in enumerate(stream):
        if i % n == rank:
            yield z


def shard_path(out_path: str, rank: int) -> str:
    return f"{out_path}.shard{rank}"


class ShardWriter:
    """FASTA shard + sidecar of global hole ordinals, for exact merge.

    Local hole ordinal k (what drive_batched passes to put_at) maps to
    global ordinal rank + k*n under round-robin sharding.
    """

    def __init__(self, out_path: str, rank: int, n: int, append: bool):
        self.rank, self.n = rank, n
        mode = "a" if append else "w"
        self.path = shard_path(out_path, rank)
        self._f = open(self.path, mode)
        self._idx = open(self.path + ".idx", mode)

    def put_at(self, local_idx: int, name: str, seq: bytes,
               qual: bytes | None = None) -> None:
        if qual is None:
            self._f.write(f">{name}\n{seq.decode()}\n")
        else:
            self._f.write(f"@{name}\n{seq.decode()}\n+\n{qual.decode()}\n")
        self._idx.write(f"{self.rank + local_idx * self.n}\n")

    def put(self, name: str, seq: bytes,
            qual: bytes | None = None) -> None:  # pragma: no cover
        raise RuntimeError("ShardWriter requires put_at")

    def close(self) -> None:
        self._f.close()
        self._idx.close()


def run_pipeline_sharded(in_path: str, out_path: str, cfg: CcsConfig,
                         rank: int, n: int,
                         journal_path: Optional[str] = None,
                         inflight: Optional[int] = None) -> int:
    """One host's share of a distributed run.

    Writes <out>.shard<rank> (+ .idx).  After all ranks finish, any one
    process calls merge_shards(out_path, n) to produce the final FASTA.
    """
    from ccsx_tpu.pipeline.batch import drive_batched
    from ccsx_tpu.pipeline.run import open_zmw_stream
    from ccsx_tpu.utils.device import resolve_device

    if not (0 <= rank < n):
        raise ValueError(f"rank {rank} outside [0, {n})")
    try:
        stream = open_zmw_stream(in_path, cfg)
    except (OSError, RuntimeError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        return 1
    # validate the mesh BEFORE the shard writer truncates its file
    # (same single validation point as the single-host driver)
    resolve_device(cfg.device)
    from ccsx_tpu.pipeline.batch import mesh_precheck

    if mesh_precheck(cfg):
        return 1
    jp = f"{journal_path}.shard{rank}" if journal_path else None
    journal = Journal.load_or_create(jp, input_id=f"{in_path}#{rank}/{n}")
    try:
        writer = ShardWriter(out_path, rank, n,
                             append=bool(journal.holes_done))
    except OSError:
        print("Cannot open file for write!", file=sys.stderr)
        return 1

    metrics = Metrics(verbose=cfg.verbose, stream=cfg.metrics_stream())
    import contextlib

    import jax

    # Under a live jax.distributed control plane the default sharding
    # spans ALL processes' devices, which would turn every jit dispatch
    # into a cross-host SPMD program (and device_put would require
    # identical inputs on every host).  The hosts here are share-nothing
    # (round-robin hole ownership), so pin this host's dispatch to its
    # own devices; the per-host mesh already spans local chips only
    # (BatchExecutor.__init__).
    ctx = (jax.default_device(jax.local_devices()[0])
           if jax.process_count() > 1 else contextlib.nullcontext())
    with ctx:
        return drive_batched(shard_stream(stream, rank, n), writer, cfg,
                             journal, metrics,
                             inflight or cfg.zmw_microbatch)


def merge_shards(out_path: str, n: int, cleanup: bool = True) -> int:
    """K-way merge of <out>.shard0..n-1 by global hole ordinal into
    out_path; returns the record count.  Restores exactly the single-host
    output order."""

    def records(rank: int):
        p = shard_path(out_path, rank)
        with open(p) as f, open(p + ".idx") as fi:
            while True:
                header = f.readline()
                if not header:
                    return
                # FASTA record = 2 lines, FASTQ = 4 (seq, '+', qual)
                lines = 1 if header[0] == ">" else 3
                rec = header + "".join(f.readline() for _ in range(lines))
                idx = int(fi.readline())
                yield idx, rec

    count = 0
    with open(out_path, "w") as out:
        for _, rec in heapq.merge(*[records(r) for r in range(n)]):
            out.write(rec)
            count += 1
    if cleanup:
        for r in range(n):
            p = shard_path(out_path, r)
            os.unlink(p)
            os.unlink(p + ".idx")
    return count
