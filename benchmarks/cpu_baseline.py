"""Honest CPU yardstick for the bench.py round metric.

The north star (BASELINE.md) is >=8x vs 64-thread CPU ccsx, but the
reference binary is not buildable offline (its bsalign dependency is
cloned at build time, reference README.md:11).  The best CPU
implementation available in-repo is the native C++ scalar Gotoh aligner
(native/align_native.cpp) — the same recurrence the TPU fill computes.
This script measures its DP cells/s single-threaded (the projection is
linear; a threaded measure would be GIL-skewed) and writes
bench_baseline.json with EXPLICIT projections:

  per_core_cells_per_sec      measured, scalar C++ (-O2), this machine
  measured_cores              always 1 (single-threaded measurement)
  cells_per_sec_64core        per-core x 64 (linear-scaling credit)
  cells_per_sec_64core_simd   x8 further SIMD credit — bsalign's
                              banded-striped SSE/AVX2 lanes (reference
                              Makefile:6-17); 8x is a generous uplift
                              for 16-lane int8 striping after banding
                              and dependency overhead
  zmw_windows_per_sec_*       the same numbers in bench.py round units
                              (one zmw-window = P x W x band DP cells)

bench.py reports vs_baseline against the 64-core scalar projection and
also emits the SIMD-credited ratio, so neither a strawman nor an
unfalsifiable claim survives in the artifact.

Usage: python benchmarks/cpu_baseline.py [--write]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# bench.py round-unit geometry — imported, not duplicated, so the
# artifact's cells_per_zmw_window can never drift from the bench shapes
# (bench.py refuses vs_baseline when it detects a mismatch anyway)
import bench as _bench  # noqa: E402  (repo root is on sys.path above)

P, W = _bench.P, _bench.W
BAND = 128  # AlignParams().band == the bench round's band
CELLS_PER_ZMW_WINDOW = P * W * BAND

SIMD_CREDIT = 8.0
PROJECTED_CORES = 64


def measure_native(seconds: float = 2.0, qlen: int = 1000, tlen: int = 1000):
    """Per-core DP cells/s of the native scalar aligner.

    Measured SINGLE-threaded on purpose: the projection to 64 cores is
    linear anyway, and a threaded measurement would be skewed by the
    GIL-held Python fraction of each call (buffer setup + cigar decode),
    understating the true per-core scalar rate on multi-core hosts —
    the exact strawman effect this script exists to remove."""
    from ccsx_tpu.native.align import align_scalar_native

    rng = np.random.default_rng(0)
    q = rng.integers(0, 4, qlen).astype(np.uint8)
    t = rng.integers(0, 4, tlen).astype(np.uint8)
    if align_scalar_native(q, t) is None:
        raise RuntimeError("native aligner unavailable (build failed?)")

    count = 0
    stop = time.perf_counter() + seconds
    t0 = time.perf_counter()
    while time.perf_counter() < stop:
        align_scalar_native(q, t)
        count += 1
    dt = time.perf_counter() - t0
    return count * qlen * tlen / dt, 1


def build_baseline():
    per_core, ncores = measure_native()
    c64 = per_core * PROJECTED_CORES
    c64s = c64 * SIMD_CREDIT
    return {
        "per_core_cells_per_sec": per_core,
        "measured_cores": ncores,
        "cells_per_sec_64core": c64,
        "cells_per_sec_64core_simd": c64s,
        "zmw_windows_per_sec": c64 / CELLS_PER_ZMW_WINDOW,
        "zmw_windows_per_sec_simd": c64s / CELLS_PER_ZMW_WINDOW,
        "cells_per_zmw_window": CELLS_PER_ZMW_WINDOW,
        "simd_credit": SIMD_CREDIT,
        "projected_cores": PROJECTED_CORES,
        "note": "native scalar Gotoh (align_native.cpp) measured on "
                f"{ncores} core(s); 64-core and SIMD numbers are "
                "EXPLICIT linear projections, not measurements; "
                "zmw_windows_per_sec is the bench.py round unit "
                "(P=8 x W=1024 x band=128 cells)",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true",
                    help="write bench_baseline.json at the repo root")
    a = ap.parse_args()
    b = build_baseline()
    print(json.dumps(b, indent=1))
    if a.write:
        path = os.path.join(_REPO, "bench_baseline.json")
        with open(path, "w") as f:
            json.dump(b, f, indent=1)
        print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
