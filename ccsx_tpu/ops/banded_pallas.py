"""Pallas TPU kernel for the banded affine-gap DP fill (global+moves mode).

This is the hot op of the framework: every consensus round aligns each pass
window against the draft (star.round), which the reference does inside
bsalign's banded-striped SIMD POA (end_bspoa, main.c:492; band=128 at
main.c:849).  The semantics here are *identical* to the lax.scan
implementation in ops/banded.py (mode='global', with_moves=True) — that
version remains the spec and the differential-test oracle; this one maps the
fill onto a single Pallas kernel so the whole DP runs out of VMEM with no
per-row HLO overhead.

Design notes (why the kernel looks like this):

* The band-offset schedule ``offs`` is data-INdependent — it is a pure
  function of (qlen, tlen, line) — so it is computed outside the kernel
  with a tiny vectorized ``lax.scan`` (compute_offsets) and fed to the
  kernel through SMEM.  The traceback needs the same array, so nothing is
  wasted.
* The only per-cell input the recurrence needs from (q, t) is the match
  indicator; ``ismatch[i-1, k] = q[i-1] == t[offs[i]+k-1]`` is precomputed
  as a (Qmax, B) int8 gather outside the kernel.  Inside, each row is a
  dynamic *sublane* read — cheap — whereas gathering t by a dynamic lane
  offset in-kernel would be a lane-rotate per row.
* The previous-row band must be shifted by d = offs[i] - offs[i-1] ∈
  [0, maxshift].  d is tiny, so the kernel computes all maxshift+2 static
  lane shifts of the carry block and picks with a select chain — static
  shifts vectorize on the VPU; a dynamic lane shift would not.
* The horizontal (within-row) affine gap F is an associative max-plus
  prefix scan (see ops/banded.py); here it is a log2(B)-step Hillis-Steele
  scan of static lane shifts.
* Outputs: the packed move byte per cell (uint8, written row-by-row into
  the VMEM output block) and the final H/mat/aln bands; score extraction
  happens outside.

The kernel is gated to Qmax <= PALLAS_MAX_QMAX (VMEM/SMEM budget); the
windowed consensus path (the default) always fits.  Callers use
ops/banded.select_aligner-style dispatch in consensus/star.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops.banded import (
    BandedResult, EBIT_EXT, FBIT_EXT, MOVE_DIAG, MOVE_LEFT, MOVE_UP, NEG, PAD,
)

# rows of the carry block: H, E, mat, aln, Emat, Ealn
_CH = 6
_ROW_H, _ROW_E, _ROW_MAT, _ROW_ALN, _ROW_EMAT, _ROW_EALN = range(_CH)

PALLAS_MAX_QMAX = 4096  # beyond this fall back to the scan implementation


def compute_offsets(qlen, tlen, qmax: int, band: int, maxshift: int,
                    line=None):
    """The band-offset schedule for rows 1..qmax (shape (qmax,) int32).

    Bit-exact replica of the offset recurrence in ops/banded.py's scan body
    (global mode), including the freeze beyond qlen.  Vectorize over a batch
    with jax.vmap.
    """
    qlen = qlen.astype(jnp.int32)
    tlen = tlen.astype(jnp.int32)
    tcap = jnp.maximum(tlen - band + 1, 0)
    if line is None:
        li0, lj0, li1, lj1 = (jnp.int32(0), jnp.int32(0), qlen, tlen)
    else:
        line = jnp.asarray(line, jnp.int32)
        li0, lj0, li1, lj1 = line[0], line[1], line[2], line[3]

    def body(off_prev, i):
        nom_j = lj0 + ((i - li0) * (lj1 - lj0)) // jnp.maximum(li1 - li0, 1)
        desired = nom_j - band // 2
        lo = jnp.maximum(0, tcap - (qlen - i) * maxshift)
        off = jnp.clip(
            jnp.maximum(desired, lo), off_prev,
            jnp.minimum(off_prev + maxshift, tcap),
        )
        off = jnp.maximum(off, off_prev)
        off = jnp.where(i <= qlen, off, off_prev)
        return off, off

    _, offs = jax.lax.scan(
        body, jnp.int32(0), jnp.arange(1, qmax + 1, dtype=jnp.int32))
    return offs


def compute_ismatch(q, t, offs, band: int, maxshift: int):
    """(Qmax, band) int8 match indicators: row i-1 lane k compares q[i-1]
    with the base entering column offs[i]+k (PAD-safe)."""
    qmax = q.shape[0]
    tpad = jnp.concatenate([
        jnp.full((1,), PAD, jnp.uint8), t.astype(jnp.uint8),
        jnp.full((band + maxshift,), PAD, jnp.uint8),
    ])
    j = offs[:, None] + jnp.arange(band, dtype=jnp.int32)[None, :]
    tb = tpad[j]
    qi = q[:, None]
    ismatch = (qi == tb) & (qi < 4) & (tb < 4)
    return ismatch.astype(jnp.int8)


ROWBLOCK = 8  # rows per grid step: aligned sublane tiles for loads/stores


def _kernel(offs_ref, qlen_ref, tlen_ref, ismatch_ref, moves_ref, fin_ref,
            ch_ref, *, qmax: int, band: int, maxshift: int,
            params: AlignParams):
    M, X = params.match, params.mismatch
    O, E = params.gap_open, params.gap_extend
    B = band
    r = pl.program_id(1)
    qlen = qlen_ref[0, 0, 0]
    tlen = tlen_ref[0, 0, 0]
    karr = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    negf = jnp.full((_CH, 1), NEG, jnp.int32)

    def shift_ch(ch, s):
        """Static lane shift: out[:, k] = ch[:, k+s], NEG fill (matches
        _pad_prev in ops/banded.py, which pads NEG on both sides)."""
        if s == 0:
            return ch
        if s > 0:
            return jnp.concatenate(
                [ch[:, s:], jnp.broadcast_to(negf, (_CH, s))], axis=1)
        return jnp.concatenate(
            [jnp.broadcast_to(negf, (_CH, -s)), ch[:, :s]], axis=1)

    def shift_row(x, s, fill):
        if s == 0:
            return x
        f = jnp.full((x.shape[0], abs(s)), fill, x.dtype)
        if s > 0:
            return jnp.concatenate([x[:, s:], f], axis=1)
        return jnp.concatenate([f, x[:, :s]], axis=1)

    # ---- row 0 init (off = 0), exactly ops/banded.py carry0 ----
    @pl.when(r == 0)
    def _():
        j0 = karr
        H0 = jnp.where(j0 <= tlen, jnp.where(j0 == 0, 0, O + E * j0), NEG)
        E0 = jnp.full((1, B), NEG, jnp.int32)
        mat0 = jnp.zeros((1, B), jnp.int32)
        aln0 = j0
        ch_ref[:] = jnp.concatenate([H0, E0, mat0, aln0, mat0, aln0], axis=0)

    # int32 throughout: sublane slices of i1 vectors hit Mosaic relayout
    # limits, so the match indicator stays arithmetic (0/1)
    ismatch_tile = ismatch_ref[0].astype(jnp.int32)  # (ROWBLOCK, B)
    ch = ch_ref[:]
    moves_rows = []
    for s in range(ROWBLOCK):
        i = r * ROWBLOCK + s + 1
        off = offs_ref[0, 0, i - 1]
        off_prev = jnp.where(i == 1, 0, offs_ref[0, 0, jnp.maximum(i - 2, 0)])
        d = off - off_prev

        # select the d-shifted views of the carry (diag wants shift d-1)
        s_diag = shift_ch(ch, -1)
        s_up = shift_ch(ch, 0)
        for dd in range(1, maxshift + 1):
            s_diag = jnp.where(d == dd, shift_ch(ch, dd - 1), s_diag)
            s_up = jnp.where(d == dd, shift_ch(ch, dd), s_up)

        Hd_diag = s_diag[_ROW_H:_ROW_H + 1]
        mat_diag = s_diag[_ROW_MAT:_ROW_MAT + 1]
        aln_diag = s_diag[_ROW_ALN:_ROW_ALN + 1]
        H_up = s_up[_ROW_H:_ROW_H + 1]
        E_up = s_up[_ROW_E:_ROW_E + 1]
        mat_up = s_up[_ROW_MAT:_ROW_MAT + 1]
        aln_up = s_up[_ROW_ALN:_ROW_ALN + 1]
        Emat_up = s_up[_ROW_EMAT:_ROW_EMAT + 1]
        Ealn_up = s_up[_ROW_EALN:_ROW_EALN + 1]

        im = ismatch_tile[s:s + 1, :]  # (1, B) int32 0/1
        sub = X + (M - X) * im
        j = off + karr

        # E (vertical)
        e_ext = E_up + E
        e_open = H_up + O + E
        e_is_open = e_open >= e_ext
        Enew = jnp.maximum(e_ext, e_open)
        Emat = jnp.where(e_is_open, mat_up, Emat_up)
        Ealn = jnp.where(e_is_open, aln_up, Ealn_up) + 1

        # Hd = best of diag / E
        diag_term = Hd_diag + sub
        d_wins = diag_term >= Enew
        Hd = jnp.maximum(diag_term, Enew)
        Hmat = jnp.where(d_wins, mat_diag + im, Emat)
        Haln = jnp.where(d_wins, aln_diag, Ealn - 1) + 1

        # boundary lane j == 0 (global mode)
        at0 = j == 0
        b_H = O + E * i
        Hd = jnp.where(at0, b_H, Hd)
        Enew = jnp.where(at0, b_H, Enew)
        Hmat = jnp.where(at0, 0, Hmat)
        Haln = jnp.where(at0, i, Haln)
        Emat = jnp.where(at0, 0, Emat)
        Ealn = jnp.where(at0, i, Ealn)

        # invalid lanes beyond the template
        invalid = j > tlen
        Hd = jnp.where(invalid, NEG, Hd)
        Enew = jnp.where(invalid, NEG, Enew)

        # F (horizontal) max-plus prefix scan, Hillis-Steele over lanes.
        # combine(left, right) keeps right on ties (ops/banded.py
        # _combine_rightmax); shifted-in identity = NEG score.
        v = Hd + O - E * karr
        fm = Hmat
        fa = Haln - karr
        step = 1
        while step < B:
            vs = shift_row(v, -step, NEG)
            ms = shift_row(fm, -step, NEG)
            as_ = shift_row(fa, -step, NEG)
            keep = v >= vs
            v = jnp.where(keep, v, vs)
            fm = jnp.where(keep, fm, ms)
            fa = jnp.where(keep, fa, as_)
            step *= 2
        # exclusive: shift right by one (score fill NEG, stats fill 0)
        v = shift_row(v, -1, NEG)
        fm = shift_row(fm, -1, 0)
        fa = shift_row(fa, -1, 0)
        F = v + E * karr
        Fmat = fm
        Faln = fa + karr

        hd_wins = Hd >= F
        Hnew = jnp.maximum(Hd, F)
        mat_new = jnp.where(hd_wins, Hmat, Fmat)
        aln_new = jnp.where(hd_wins, Haln, Faln)

        # moves byte
        choice = jnp.where(
            hd_wins & d_wins, MOVE_DIAG,
            jnp.where(hd_wins, MOVE_UP, MOVE_LEFT)).astype(jnp.uint8)
        ebit = jnp.where(e_is_open, 0, EBIT_EXT).astype(jnp.uint8)
        H_left = shift_row(Hnew, -1, NEG)
        f_is_open = F == (H_left + O + E)
        fbit = jnp.where(f_is_open, 0, FBIT_EXT).astype(jnp.uint8)
        moves_rows.append(choice | ebit | fbit)

        ch_new = jnp.concatenate(
            [Hnew, Enew, mat_new, aln_new, Emat, Ealn], axis=0)
        live = i <= qlen
        ch = jnp.where(live, ch_new, ch)

    moves_ref[0] = jnp.concatenate(moves_rows, axis=0)
    ch_ref[:] = ch

    @pl.when(r == pl.num_programs(1) - 1)
    def _():
        fin_ref[0, 0:1, :] = ch[_ROW_H:_ROW_H + 1]
        fin_ref[0, 1:2, :] = ch[_ROW_MAT:_ROW_MAT + 1]
        fin_ref[0, 2:3, :] = ch[_ROW_ALN:_ROW_ALN + 1]
        fin_ref[0, 3:8, :] = jnp.zeros((5, band), jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("params", "band", "maxshift", "interpret"))
def batched_align_global_moves(
    qs: jnp.ndarray,
    qlens: jnp.ndarray,
    ts: jnp.ndarray,
    tlens: jnp.ndarray,
    params: AlignParams = AlignParams(),
    band: int | None = None,
    maxshift: int = 4,
    interpret: bool = False,
):
    """Batched global banded alignment with move emission (Pallas).

    Drop-in for the vmapped scan aligner used by the consensus rounds
    (consensus/star.py): same argument shapes — (..., Qmax) uint8 queries,
    (...,) lengths, (..., Tmax) uint8 templates — and the same
    (BandedResult, moves, offs) result tuple.
    """
    B = band if band is not None else params.band
    lead = qs.shape[:-1]
    qmax = qs.shape[-1]
    if qmax > PALLAS_MAX_QMAX:
        raise ValueError(
            f"qmax={qmax} exceeds PALLAS_MAX_QMAX={PALLAS_MAX_QMAX}; "
            "use the scan aligner")
    n = 1
    for s in lead:
        n *= s
    qs_f = qs.reshape(n, qmax)
    qlens_f = qlens.reshape(n).astype(jnp.int32)
    ts_f = ts.reshape(n, ts.shape[-1])
    tlens_f = tlens.reshape(n).astype(jnp.int32)

    offs = jax.vmap(
        lambda ql, tl: compute_offsets(ql, tl, qmax, B, maxshift)
    )(qlens_f, tlens_f)
    ismatch = jax.vmap(
        lambda q, t, o: compute_ismatch(q, t, o, B, maxshift)
    )(qs_f, ts_f, offs)

    if qmax % ROWBLOCK != 0:
        raise ValueError(f"qmax={qmax} must be a multiple of {ROWBLOCK}")
    kern = functools.partial(
        _kernel, qmax=qmax, band=B, maxshift=maxshift, params=params)
    nb = qmax // ROWBLOCK
    moves, fin = pl.pallas_call(
        kern,
        grid=(n, nb),
        in_specs=[
            pl.BlockSpec((1, 1, qmax), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, 1), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, 1), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, B), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, qmax, B), jnp.uint8),
            jax.ShapeDtypeStruct((n, 8, B), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((_CH, B), jnp.int32)],
        interpret=interpret,
    )(offs[:, None, :], qlens_f[:, None, None], tlens_f[:, None, None],
      ismatch)

    # final-row extraction (mirrors ops/banded.py global-mode epilogue)
    off_fin = offs[:, -1]
    laneT = tlens_f - off_fin
    reachable = (laneT >= 0) & (laneT < B)
    lane = jnp.clip(laneT, 0, B - 1)
    take = jax.vmap(lambda f, l: f[:, l])(fin, lane)  # (n, 8)
    res = BandedResult(
        score=jnp.where(reachable, take[:, 0], NEG).reshape(lead),
        qb=jnp.zeros(lead, jnp.int32),
        qe=qlens_f.reshape(lead),
        tb=jnp.zeros(lead, jnp.int32),
        te=tlens_f.reshape(lead),
        aln=jnp.where(reachable, take[:, 2], 0).reshape(lead),
        mat=jnp.where(reachable, take[:, 1], 0).reshape(lead),
    )
    moves = moves.reshape(lead + (qmax, B))
    offs = offs.reshape(lead + (qmax,))
    return res, moves, offs
