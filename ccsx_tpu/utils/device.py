"""Backend selection.

The runtime environment may register a TPU plugin that is not always
reachable (tunnelled).  Resolve the backend once, up front, with a clean
CPU fallback — a backend-init failure must abort clearly (or fall back),
not surface as a per-hole error storm in the quarantine path.
"""

from __future__ import annotations

import os
import sys


def enable_compile_cache(path: str | None = None) -> str | None:
    """Persistent XLA compilation cache (on by default).

    Batched-round shapes recur across runs ((Z, P, qmax, tmax) buckets),
    and a TPU compile costs 10-40s — without this cache every CLI
    invocation repays the full compile bill.  CCSX_COMPILE_CACHE=off
    disables; any other value overrides the default directory.
    """
    import jax

    env = os.environ.get("CCSX_COMPILE_CACHE", "")
    if env.lower() == "off":
        return None
    cache = path or env or os.path.expanduser("~/.cache/ccsx_tpu/xla")
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError) as e:  # unwritable dir / old jax
        print(f"[ccsx-tpu] compile cache disabled ({e})", file=sys.stderr)
        return None
    return cache


def probe_default_backend(timeout: float | None = None,
                          retries: int | None = None) -> bool:
    """True if the default JAX backend initializes in a fresh subprocess.

    The tunnelled TPU plugin can HANG on device init (not just fail), and
    an in-process hang cannot be timed out — so the probe runs out of
    process.  The tunnel is also *flaky*: the same shell can get a real
    TPU on one attempt and an init failure on the next, so a failed
    probe is retried with backoff before giving up.  Knobs:
    CCSX_PROBE_TIMEOUT (seconds per attempt, default 120),
    CCSX_PROBE_RETRIES (extra attempts after the first, default 1),
    CCSX_SKIP_PROBE (skip entirely, treat backend as usable).
    """
    import subprocess
    import time

    global _probe_result
    if os.environ.get("CCSX_SKIP_PROBE"):
        return True
    if _probe_result is not None:
        return _probe_result
    if timeout is None:
        timeout = float(os.environ.get("CCSX_PROBE_TIMEOUT", "120"))
    if retries is None:
        retries = int(os.environ.get("CCSX_PROBE_RETRIES", "1"))
    # the probe must EXECUTE on the device AND materialize the result,
    # not just enumerate or block: jax.devices() has been observed
    # healthy while every dispatch hangs, and on the lazy axon runtime
    # block_until_ready returns without waiting (r5, memory/axon notes)
    # — only fetching bytes proves a live round-trip
    probe_src = ("import sys, jax, numpy; "
                 "v = numpy.asarray(jax.jit(lambda a: a + 1)"
                 "(numpy.ones(8))); "
                 "sys.exit(0 if v[0] == 2 else 1)")
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe_src],
                timeout=timeout, capture_output=True,
            )
            ok = r.returncode == 0
        except (OSError, subprocess.SubprocessError):
            ok = False
        if ok:
            _probe_result = True
            return True
        if attempt < retries:
            backoff = 5.0 * (attempt + 1)
            print(f"[ccsx-tpu] backend probe attempt {attempt + 1} failed; "
                  f"retrying in {backoff:.0f}s", file=sys.stderr)
            time.sleep(backoff)
    _probe_result = False
    return False


_probe_result = None


def resolve_device(requested: str = "auto") -> str:
    """Initialize JAX's backend per the request; returns the backend name.

    requested: 'auto' (prefer the default, fall back to CPU),
               'tpu' (require an accelerator), 'cpu' (force CPU).
    """
    import jax

    if requested == "auto" and os.environ.get(
            "JAX_PLATFORMS", "").strip().lower() == "cpu":
        # the axon TPU plugin overrides JAX_PLATFORMS at import time;
        # re-assert the user's explicit env choice here
        requested = "cpu"
    # the persistent cache is enabled only on accelerator paths: XLA:CPU
    # AOT entries embed machine features and can be unsafe to reload
    # (observed "+prefer-no-scatter not supported on host" E-logs)
    if requested == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
    if not probe_default_backend():
        if requested == "tpu":
            raise RuntimeError(
                "accelerator requested but backend init failed or hung")
        print("[ccsx-tpu] accelerator unavailable (init failed or hung); "
              "using CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
    try:
        backend = jax.default_backend()
        jax.devices()
        if backend != "cpu":
            enable_compile_cache()
        return backend
    except RuntimeError as e:
        if requested == "tpu":
            raise
        print(f"[ccsx-tpu] accelerator unavailable ({e}); using CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
