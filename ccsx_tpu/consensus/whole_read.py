"""Whole-read consensus — the reference's primitive `-P` path (ccs_for,
main.c:455-508), redesigned as a template-anchored star MSA.

The reference pushes all oriented passes into one POA graph and calls the
graph consensus (beg/push/end_bspoa, main.c:486-492).  Here the template
pass anchors a star MSA (consensus/star.py): banded global DP batched over
passes, traceback projection onto anchor coordinates, column vote, and
liberal-insert/strict-delete refinement rounds that recover the
cross-pass insertion reinforcement a POA graph provides natively.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import prepare as prep
from ccsx_tpu.consensus.star import StarMsa
from ccsx_tpu.ops import encode as enc


def consensus_passes(passes: List[np.ndarray], cfg: CcsConfig):
    """Consensus of oriented pass code arrays; passes[0] is the anchor.
    Returns codes, or (codes, phred_quals) under cfg.emit_quality."""
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    return sm.consensus(passes, cfg.refine_iters, cfg.pass_buckets,
                        cfg.max_passes,
                        quality=((cfg.qv_coeffs, cfg.qv_cap)
                                 if cfg.emit_quality else None))


def ccs_whole_read(zmw, aligner, cfg: CcsConfig):
    """Full `-P` path for one ZMW (ccs_for, main.c:455-508): prepare ->
    orient -> star-MSA consensus.  Returns (seq_bytes, qual_bytes|None)
    per encode.to_record — the same contract as hole.ccs_hole — or
    None."""
    passes = prep.oriented_passes(zmw, aligner, cfg)
    if passes is None:  # main.c:460
        return None
    return enc.to_record(consensus_passes(passes, cfg))
