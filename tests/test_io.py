import gzip
import io

import numpy as np
import pytest

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io import fastx, zmw


FASTA = b""">m0/1/0_10 comment here
ACGTACGTAC
>m0/1/10_15
ACG
TA
>m0/2/0_4
GGGG
>m1/2/0_4
TTTT
"""

FASTQ = b"""@m0/1/0_10
ACGTACGTAC
+
IIIIIIIIII
@m0/1/10_14
ACGT
+anything
IIII
"""


def test_fasta_records():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTA))))
    assert [r.name for r in recs] == ["m0/1/0_10", "m0/1/10_15", "m0/2/0_4", "m1/2/0_4"]
    assert recs[0].comment == "comment here"
    assert recs[0].seq == b"ACGTACGTAC"
    assert recs[1].seq == b"ACGTA"  # multi-line sequence
    assert recs[0].qual is None


def test_fastq_records():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTQ))))
    assert len(recs) == 2
    assert recs[0].qual == b"IIIIIIIIII"
    assert recs[1].seq == b"ACGT" and recs[1].qual == b"IIII"


def test_fastq_bad_quality_length():
    bad = b"@m0/1/0_4\nACGT\n+\nII\n"
    with pytest.raises(ValueError):
        list(fastx.read_fastx(io.BufferedReader(io.BytesIO(bad))))


def test_gzip_transparent(tmp_path):
    p = tmp_path / "x.fa.gz"
    p.write_bytes(gzip.compress(FASTA))
    recs = list(fastx.read_fastx(p))
    assert len(recs) == 4


def test_group_zmws():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTA))))
    zs = list(zmw.group_zmws(recs))
    # same hole id '2' under different movies must NOT merge (seqio.h:183)
    assert [(z.movie, z.hole) for z in zs] == [("m0", "1"), ("m0", "2"), ("m1", "2")]
    z0 = zs[0]
    assert z0.n_passes == 2
    assert z0.seqs == b"ACGTACGTACACGTA"
    assert z0.lens.tolist() == [10, 5]
    assert z0.offs.tolist() == [0, 10]
    assert z0.subread(1) == b"ACGTA"


def test_invalid_name_raises():
    recs = [fastx.FastxRecord("badname", "", b"ACGT", None)]
    with pytest.raises(zmw.InvalidZmwName):
        list(zmw.group_zmws(recs))
    recs = [fastx.FastxRecord("a/b/c/d", "", b"ACGT", None)]
    with pytest.raises(zmw.InvalidZmwName):
        list(zmw.group_zmws(recs))


def _mk(n_passes, total=6000, hole="7"):
    per = total // n_passes
    seqs = b"A" * total
    lens = np.full(n_passes, per, dtype=np.int32)
    lens[-1] += total - per * n_passes
    offs = np.zeros(n_passes, dtype=np.int32)
    np.cumsum(lens[:-1], out=offs[1:])
    return zmw.Zmw("m0", hole, seqs, lens, offs)


def test_zmw_filter_count_and_len():
    cfg = CcsConfig()
    # count >= min_fulllen_count + 2 == 5 (main.c:659)
    assert not zmw.zmw_filter(_mk(4), cfg)
    assert zmw.zmw_filter(_mk(5), cfg)
    # total length window [5000, 500000] (main.c:662-664)
    assert not zmw.zmw_filter(_mk(5, total=4999), cfg)
    assert zmw.zmw_filter(_mk(5, total=5000), cfg)
    assert not zmw.zmw_filter(_mk(5, total=500001), cfg)


def test_zmw_filter_exclusion():
    cfg = CcsConfig(exclude_holes=frozenset({"7"}))
    assert not zmw.zmw_filter(_mk(5, hole="7"), cfg)
    assert zmw.zmw_filter(_mk(5, hole="8"), cfg)


def test_gzip_bytesio_stream():
    """Regression: raw BytesIO (no peek()) carrying gzip data must be
    detected and decompressed, not silently parsed as binary junk."""
    import io as _io
    recs = list(fastx.read_fastx(_io.BytesIO(gzip.compress(FASTA))))
    assert len(recs) == 4


def test_plus_line_after_fasta_record():
    """kseq parity: '+' after a '>' record starts a quality section (kseq.h:196)
    — it must not yield a phantom empty-name record."""
    import io as _io
    data = b">r/1/0_4\nACGT\n+\nIIII\n>r/2/0_4\nTTTT\n"
    recs = list(fastx.read_fastx(_io.BytesIO(data)))
    assert [r.name for r in recs] == ["r/1/0_4", "r/2/0_4"]
    assert recs[0].qual is None  # quality consumed but not reported for FASTA
