"""Live telemetry plane: /metrics, /healthz, /progress + `ccsx-tpu top`.

The r7 flight recorder made runs auditable AFTER the fact; this module
makes them observable WHILE they run — the r5 dead-tunnel incident
(BENCH_r05: a CPU fallback stamped "tpu attempt hung" with zero live
signal) is exactly the gap.  Three pieces:

* **TelemetryServer** (``--telemetry-port``, 0 = off): a daemon thread
  serving, straight off the run's live ``Metrics`` object,

  - ``GET /metrics``  — Prometheus text format rendered from
    ``Metrics.snapshot()`` (every numeric counter, the per-shape-group
    compile/execute table as labeled series, the progress/ETA
    estimate, and the resource gauges);
  - ``GET /healthz``  — JSON ``ok`` (HTTP 200) or ``degraded`` (HTTP
    503, wired to the stall watchdog's mark) with the rc-relevant
    detail: stalls, oom_resplits, host_fallbacks, holes_failed;
  - ``GET /progress`` — the full snapshot as JSON (what ``top`` polls).

  The port auto-bumps when taken (up to ``PORT_TRIES`` upward probes —
  several ranks or runs on one host each get the next free port, and
  sharded runs additionally offset by rank, parallel/distributed.py).
  Serving is pull-only: no scrape, no work — the <1%-overhead
  acceptance bar is held by doing nothing until a request arrives.

* **`ccsx-tpu top`** — a curses-free ANSI live dashboard over one or
  more sources, each either a telemetry endpoint (``host:port`` /
  ``http://...``) or a ``--metrics`` JSONL path tailed for the last
  event (endpoint-less runs).  Multi-rank aggregation: counters SUM,
  progress is the MINIMUM rank pct (the merge waits for the slowest
  shard), rates sum, and one degraded rank degrades the aggregate.

* **Schema contract**: the module-level key tuples below are the ONE
  declaration of which ``Metrics.snapshot()`` keys the telemetry plane
  consumes; ``tests/test_telemetry.py`` cross-checks them against a
  populated snapshot in both directions, so a renamed counter cannot
  silently zero a dashboard column (or vanish from /metrics).

No third-party dependencies: http.server + urllib only.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ccsx_tpu.utils.metrics import (HIST_BUCKETS, Metrics, hist_quantile,
                                    merge_hist, resource_gauges)

# upward probes for a taken port: rank offsets + parallel runs on one
# host land on distinct ports without operator bookkeeping
PORT_TRIES = 32

# ---- the schema contract (see module docstring) ---------------------------
# snapshot keys exported to Prometheus as monotone counters
PROM_COUNTERS = (
    "holes_in", "holes_out", "holes_failed", "holes_filtered",
    "holes_corrupt", "stalls",
    "windows", "pair_alignments", "device_dispatches", "refine_overflows",
    # pre-alignment plane (ops/sketch.py + ops/seed_device.py): screen
    # coverage/rejections and the device-vs-host seeding split
    "pairs_screened", "pairs_prefiltered",
    "pairs_seeded_device", "pairs_seeded_host",
    "oom_resplits", "host_fallbacks", "compile_fallbacks",
    # resilient execution (pipeline/resilience.py): abandoned
    # dispatches + circuit-breaker trips and half-open probes
    "device_hangs", "breaker_trips", "breaker_probes",
    "dp_cells_real", "dp_cells_padded", "distinct_slab_shapes",
    "fused_waves", "ingest_bytes",
    # elastic fleet plane (pipeline/fleet.py): retired ranges, expired/
    # reclaimed leases, and reap-time rebalance sweeps
    "fleet_ranges_retired", "fleet_steals", "fleet_rebalances",
)
# snapshot keys exported as gauges (ratios, seconds, rates)
PROM_GAUGES = (
    "dp_occupancy", "dp_round_occupancy", "dp_length_fill",
    "dp_pass_fill", "dp_z_fill", "dp_row_fill", "prefilter_share",
    "packed_holes_per_dispatch", "fused_slot_fill",
    "ingest_s", "prep_s", "compute_s", "write_s", "elapsed_s",
    "zmws_per_sec", "compile_s", "compile_share",
    # prep plane (pipeline/prep_pool.py): critical-path prep exposure,
    # overlap quality, and the live ready-queue gauges
    "prep_blocked_s", "prep_share", "prep_overlap_share",
    "prep_queue_depth", "prep_queue_peak", "prep_threads",
    # elastic fleet plane: live leased-range queue + fleet membership
    "fleet_ranges_total", "fleet_ranges_queued", "fleet_ranges_leased",
    "fleet_ranks_alive",
    # static-analysis plane (ccsx_tpu/lint/): unsuppressed findings a
    # supervisor published via `ccsx-tpu lint --gauge-file`; None
    # (unpopulated) in runs that never lint
    "lint_findings",
)
# snapshot keys with dedicated (non-scalar) renderings
PROM_STRUCTURED = ("groups", "groups_forced", "degraded", "progress",
                   "filtered_reasons", "corrupt_reasons",
                   # per-implementation banded DP-fill attribution
                   # (ccsx_banded_impl{impl=...}): scan/pallas/rotband
                   "banded_dispatches",
                   "breaker_state", "breaker_strike_log",
                   # failed native .so auto-rebuild (string detail;
                   # rendered as a 0/1 gauge like degraded)
                   "native_build_error",
                   # multi-tenant/fleet identity labels (serve plane):
                   # the job id and the fleet-wide correlation id ride
                   # snapshots as strings, never as scalar samples
                   "job", "cid",
                   # latency histograms (HIST_FAMILIES below renders
                   # them as _bucket/_sum/_count families)
                   "hist")

# latency-histogram families (ISSUE 18): (snapshot family name, label
# key, Prometheus family name).  The snapshot side lives under
# snap["hist"][<family>][<label>] (Metrics.observe); the exposition
# side renders cumulative `le` buckets + +Inf + _sum/_count per label.
# Schema-guarded BOTH directions (tests/test_telemetry.py): a family
# renamed in Metrics cannot silently vanish from /metrics, and a new
# snapshot family cannot ship unrendered.
HIST_FAMILIES = (
    ("queue_wait_s", "size", "queue_wait_seconds"),
    ("job_wall_s", "size", "job_wall_seconds"),
    ("first_dispatch_s", "size", "first_dispatch_seconds"),
    ("device_execute_s", "group", "device_execute_seconds"),
    ("lease_acquire_s", "kind", "lease_acquire_seconds"),
)

# derived SLO burn gauges: (gauge name, histogram family, threshold
# seconds — MUST be one of metrics.HIST_BUCKETS so the "fraction over
# threshold" is exact, not interpolated — and the objective).  burn =
# (fraction of observations over threshold) / (1 - objective): 1.0
# means the error budget is being spent exactly at the sustainable
# rate, >1 means the SLO is burning down.  Served from every /metrics
# that renders histograms, most usefully the gateway's fleet-merged
# view (alongside the ccsx_fleet_* autoscale set).
SLO_BURN_GAUGES = (
    ("slo_queue_wait_burn", "queue_wait_s", 1.0, 0.95),
    ("slo_job_wall_burn", "job_wall_s", 60.0, 0.99),
)
# per-group table fields exported as ccsx_group_<field>{group="..."}
GROUP_FIELDS = ("compiles", "compile_s", "execute_s", "dispatches",
                "dp_cells", "dp_cells_per_sec")
# progress-estimator fields (Metrics.progress_snapshot)
PROGRESS_KEYS = ("done", "total", "rate_zmws_per_sec", "elapsed_s",
                 "pct", "eta_s")
# snapshot counters `top` SUMS across ranks
TOP_SUM_KEYS = (
    "holes_in", "holes_out", "holes_failed", "holes_filtered",
    "holes_corrupt", "stalls",
    "windows", "device_dispatches", "oom_resplits", "host_fallbacks",
    "refine_overflows", "device_hangs", "breaker_trips", "ingest_bytes",
    "fleet_ranges_total", "fleet_ranges_queued", "fleet_ranges_leased",
    "fleet_ranges_retired", "fleet_ranks_alive", "fleet_steals",
    "fleet_rebalances",
)
# /healthz detail fields (rc-relevant: what an operator triages by)
HEALTH_DETAIL_KEYS = ("stalls", "oom_resplits", "host_fallbacks",
                      "holes_failed", "holes_corrupt",
                      "compile_fallbacks",
                      "refine_overflows", "device_hangs",
                      "breaker_trips", "breaker_state")
# per-job labeled series the serving plane (pipeline/serve.py) exports
# as ccsx_job_<key>{job="..."} from each job's own Metrics snapshot —
# the fault-domain counters an operator triages a tenant by.  Schema-
# guarded like the tuples above (tests/test_serve.py cross-checks them
# against a populated snapshot).
JOB_PROM_COUNTERS = (
    "holes_in", "holes_out", "holes_failed", "holes_filtered",
    "holes_corrupt", "device_hangs", "breaker_trips", "oom_resplits",
    "host_fallbacks",
)
JOB_PROM_GAUGES = ("zmws_per_sec", "elapsed_s")
# serve-fleet autoscale gauges the gateway exports (ccsx_fleet_*):
# fleet-wide scalars from the job spool + replica slot leases
# (pipeline/gateway.py fleet_summary), and per-replica labeled gauges
# ({replica="..."}).  Schema-guarded like the tuples above
# (tests/test_serve_fleet.py cross-checks the renderer both ways).
FLEET_SERVE_GAUGES = (
    "fleet_spool_depth", "fleet_jobs_leased", "fleet_jobs_retired",
    "fleet_replicas", "fleet_replicas_ready",
)
FLEET_REPLICA_GAUGES = ("fleet_window_pressure", "fleet_leases_held")


# ---- Prometheus text rendering --------------------------------------------

def _prom_escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _num(v):
    """Prometheus sample value, or None to skip (snapshot ratios are
    None until their denominators move)."""
    if v is None or isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return v


def _fmt_le(b: float) -> str:
    return format(b, "g")


def hist_lines(hist: dict) -> List[str]:
    """Render snap["hist"] (family -> label -> {counts, sum, count})
    into well-formed Prometheus histogram families: ONE TYPE line per
    family, cumulative `le` buckets ending in +Inf, and _sum/_count per
    label — the exposition shape promtool and histogram_quantile()
    expect.  Families are emitted in HIST_FAMILIES order; snapshot
    families outside the contract are skipped (the schema guard keeps
    that set empty)."""
    lines: List[str] = []
    for fam, label_key, prom in HIST_FAMILIES:
        series = (hist or {}).get(fam)
        if not series:
            continue
        lines.append(f"# TYPE ccsx_{prom} histogram")
        for label, h in sorted(series.items()):
            counts = h.get("counts") or []
            if len(counts) != len(HIST_BUCKETS) + 1:
                continue
            base = (f'{label_key}="{_prom_escape(label)}",'
                    if label else "")
            cum = 0
            for i, b in enumerate(HIST_BUCKETS):
                cum += int(counts[i])
                lines.append(f'ccsx_{prom}_bucket{{{base}le="{_fmt_le(b)}"}}'
                             f" {cum}")
            cum += int(counts[-1])
            lines.append(f'ccsx_{prom}_bucket{{{base}le="+Inf"}} {cum}')
            lab = f'{{{base[:-1]}}}' if label else ""
            lines.append(f"ccsx_{prom}_sum{lab} {h.get('sum', 0)}")
            lines.append(f"ccsx_{prom}_count{lab} {cum}")
    return lines


def merged_family(hist: dict, fam: str) -> dict:
    """One family's label series merged into a single histogram
    snapshot (summing per-`le` counts — the only legal merge)."""
    return merge_hist(list((hist or {}).get(fam, {}).values()))


def slo_burn_lines(hist: dict) -> List[str]:
    """The derived SLO burn gauges over a (possibly fleet-merged)
    histogram snapshot.  A family with no observations emits nothing —
    an idle fleet has no burn, not burn 0 vs NaN ambiguity."""
    lines: List[str] = []
    for gauge, fam, threshold, objective in SLO_BURN_GAUGES:
        m = merged_family(hist, fam)
        total = m["count"]
        if not total:
            continue
        cum = 0
        for i, b in enumerate(HIST_BUCKETS):
            cum += m["counts"][i]
            if b >= threshold:
                break
        frac_over = (total - cum) / total
        burn = frac_over / (1.0 - objective)
        lines.append(f"# TYPE ccsx_{gauge} gauge")
        lines.append(f"ccsx_{gauge} {round(burn, 6)}")
    return lines


def render_prometheus(snap: dict, gauges: Optional[dict] = None) -> str:
    """Metrics.snapshot() -> Prometheus text exposition format."""
    lines: List[str] = []
    typed: set = set()

    def sample(name, value, typ, labels=""):
        v = _num(value)
        if v is None:
            return
        if name not in typed:
            # exactly ONE TYPE line per metric family: strict
            # exposition-format parsers reject a scrape with a second
            # TYPE line, which labeled families (groups, reasons)
            # would otherwise emit per sample
            typed.add(name)
            lines.append(f"# TYPE ccsx_{name} {typ}")
        lines.append(f"ccsx_{name}{labels} {v}")

    for key in PROM_COUNTERS:
        sample(key, snap.get(key), "counter")
    for key in PROM_GAUGES:
        sample(key, snap.get(key), "gauge")
    prog = snap.get("progress") or {}
    for key in PROGRESS_KEYS:
        sample(f"progress_{key}", prog.get(key), "gauge")
    for reason, n in sorted((snap.get("filtered_reasons") or {}).items()):
        sample("filtered_reason", n, "counter",
               labels=f'{{reason="{_prom_escape(reason)}"}}')
    # salvage-mode input corruption, bucketed by the pinned taxonomy
    # (io/corruption.py REASONS)
    for reason, n in sorted((snap.get("corrupt_reasons") or {}).items()):
        sample("corrupt_reason", n, "counter",
               labels=f'{{reason="{_prom_escape(reason)}"}}')
    # banded DP-fill dispatches by implementation (consensus/star.
    # banded_impl three-way: scan / pallas / rotband)
    for impl, n in sorted((snap.get("banded_dispatches") or {}).items()):
        sample("banded_impl", n, "counter",
               labels=f'{{impl="{_prom_escape(impl)}"}}')
    for gkey, st in sorted((snap.get("groups") or {}).items()):
        labels = f'{{group="{_prom_escape(gkey)}"}}'
        for f in GROUP_FIELDS:
            sample(f"group_{f}", st.get(f), "counter"
                   if f in ("compiles", "dispatches", "dp_cells")
                   else "gauge", labels=labels)
    if "groups_forced" in snap:
        sample("groups_forced", int(bool(snap["groups_forced"])), "gauge")
    sample("degraded", int(bool(snap.get("degraded"))), "gauge")
    sample("native_build_error",
           int(bool(snap.get("native_build_error"))), "gauge")
    # circuit-breaker state as a labeled gauge: exactly one sample, its
    # label naming the current state (closed / open / half-open) — the
    # alerting-friendly rendering (breaker_strike_log stays JSON-only:
    # /progress carries it verbatim)
    state = snap.get("breaker_state")
    if state:
        sample("breaker_state", 1, "gauge",
               labels=f'{{state="{_prom_escape(state)}"}}')
    for key, v in sorted((gauges or {}).items()):
        sample(key, v, "gauge")
    hist = snap.get("hist")
    if hist:
        lines.extend(hist_lines(hist))
        lines.extend(slo_burn_lines(hist))
    return "\n".join(lines) + "\n"


def health_payload(snap: dict) -> dict:
    """The /healthz body: ok/degraded + the rc-relevant detail."""
    degraded = snap.get("degraded")
    return {
        "status": "degraded" if degraded else "ok",
        "degraded": degraded,
        "detail": {k: snap.get(k, 0) for k in HEALTH_DETAIL_KEYS},
    }


def render_job_series(jobs: dict) -> str:
    """Per-job labeled Prometheus series for the serving plane:
    ``jobs`` maps job id -> that job's ``Metrics.snapshot()``.  Every
    family is declared once (TYPE line) then sampled per job — the
    multi-tenant view of the same counters render_prometheus exports
    for a single run."""
    lines: List[str] = []
    typed: set = set()

    def sample(name, value, typ, labels):
        v = _num(value)
        if v is None:
            return
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE ccsx_job_{name} {typ}")
        lines.append(f"ccsx_job_{name}{labels} {v}")

    for jid, snap in sorted(jobs.items()):
        labels = f'{{job="{_prom_escape(jid)}"}}'
        for key in JOB_PROM_COUNTERS:
            sample(key, (snap or {}).get(key), "counter", labels)
        for key in JOB_PROM_GAUGES:
            sample(key, (snap or {}).get(key), "gauge", labels)
        if (snap or {}).get("degraded"):
            sample("degraded", 1, "gauge", labels)
    return ("\n".join(lines) + "\n") if lines else ""


def render_fleet_series(summary: dict) -> str:
    """The serve-fleet autoscale gauges (``summary`` is pipeline/
    gateway.fleet_summary's output): fleet-wide scalars from
    FLEET_SERVE_GAUGES, then the per-replica FLEET_REPLICA_GAUGES
    labeled ``{replica="..."}`` — the signals an autoscaler sizes the
    replica count by."""
    lines: List[str] = []
    for key in FLEET_SERVE_GAUGES:
        v = _num(summary.get(key))
        if v is None:
            continue
        lines.append(f"# TYPE ccsx_{key} gauge")
        lines.append(f"ccsx_{key} {v}")
    typed: set = set()
    for name, per in sorted((summary.get("replicas") or {}).items()):
        labels = f'{{replica="{_prom_escape(name)}"}}'
        for key in FLEET_REPLICA_GAUGES:
            v = _num((per or {}).get(key))
            if v is None:
                continue
            if key not in typed:
                typed.add(key)
                lines.append(f"# TYPE ccsx_{key} gauge")
            lines.append(f"ccsx_{key}{labels} {v}")
    return ("\n".join(lines) + "\n") if lines else ""


# ---- the endpoint server --------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # one scrape must never block the next: each request runs on its
    # own daemon thread (ThreadingHTTPServer below)
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server API)
        metrics: Metrics = self.server.ccsx_metrics  # type: ignore
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200,
                           render_prometheus(metrics.snapshot(),
                                             resource_gauges()),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                h = health_payload(metrics.snapshot())
                self._send(200 if h["status"] == "ok" else 503,
                           json.dumps(h), "application/json")
            elif path == "/readyz":
                # liveness-vs-readiness split: /readyz answers "route
                # traffic here?" — the serving plane hangs its warmup/
                # drain state on ``ccsx_ready`` (a () -> (bool, reason)
                # attribute on the server); a plain run's readiness is
                # its health (degraded = do not route)
                ready_fn = getattr(self.server, "ccsx_ready", None)
                if ready_fn is not None:
                    ready, reason = ready_fn()
                else:
                    snap = metrics.snapshot()
                    ready = not snap.get("degraded")
                    reason = snap.get("degraded")
                self._send(200 if ready else 503,
                           json.dumps({"ready": bool(ready),
                                       "reason": reason}),
                           "application/json")
            elif path in ("/progress", "/"):
                snap = metrics.snapshot()
                snap["status"] = ("degraded" if snap.get("degraded")
                                  else "ok")
                self._send(200, json.dumps(snap, default=str),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path", "paths":
                     ["/metrics", "/healthz", "/readyz", "/progress"]}),
                    "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # detect taken ports honestly: SO_REUSEADDR would bind "over" a
    # live sibling server and silently steal/merge scrapes instead of
    # auto-bumping to the next port
    allow_reuse_address = False


class TelemetryServer:
    """The live endpoint daemon for one run's Metrics object.

    Binds the first free port in [port, port + PORT_TRIES); raises
    OSError when all are taken (callers should prefer ``start()``,
    which degrades to a warning — telemetry must never kill a run).
    """

    def __init__(self, metrics: Metrics, port: int, host: str = "",
                 handler=None, attrs: Optional[dict] = None):
        self.host = host or os.environ.get("CCSX_TELEMETRY_HOST",
                                           "0.0.0.0")
        err: Optional[Exception] = None
        self._srv = None
        # clamp the probe window to valid ports: a rank-offset base near
        # the top (distributed.py adds rank) must degrade, not crash —
        # socket raises OverflowError (not OSError) past 65535.
        # ``handler``/``attrs`` are the serving plane's extension point
        # (pipeline/serve.py mounts its job API on this same stack);
        # port 0 binds one ephemeral port, for embedded/test servers.
        handler = handler or _Handler
        for p in range(min(port, 65536),
                       min(max(port + PORT_TRIES, 1), 65536)):
            try:
                self._srv = _Server((self.host, p), handler)
                break
            except (OSError, OverflowError) as e:
                err = e
        if self._srv is None:
            raise OSError(
                f"telemetry: no free port in [{port}, "
                f"{min(port + PORT_TRIES, 65536)}): {err}")
        self._srv.ccsx_metrics = metrics  # type: ignore[attr-defined]
        for k, v in (attrs or {}).items():
            setattr(self._srv, k, v)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="ccsx-telemetry",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        srv, self._srv = self._srv, None
        if srv is None:
            return
        srv.shutdown()
        srv.server_close()
        self._thread.join(timeout=10.0)


def start(metrics: Metrics, port: int) -> Optional[TelemetryServer]:
    """Start the endpoint server (None when port is 0/None, or — with a
    stderr warning — when no port could be bound: observability must
    never take the run down with it)."""
    if not port:
        return None
    try:
        srv = TelemetryServer(metrics, int(port))
    except OSError as e:
        print(f"[ccsx-tpu] telemetry disabled: {e}", file=sys.stderr)
        return None
    print(f"[ccsx-tpu] telemetry: http://{srv.host}:{srv.port} "
          "(/metrics /healthz /readyz /progress)", file=sys.stderr)
    return srv


# ---- source reading (`top`) -----------------------------------------------

def _fetch_endpoint(url: str, timeout: float) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def tail_metrics_jsonl(path: str, max_bytes: int = 262144):
    """Last parseable metrics event of a JSONL file (None when none):
    the endpoint-less source mode.  Reads only the file tail, so
    tailing a million-hole stream costs one seek, not one parse."""
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(size - max_bytes, 0))
        chunk = f.read().decode("utf-8", "replace")
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn first line of the tail window / mid-write
        if isinstance(rec, dict) and "event" in rec:
            return rec
    return None


def expand_sources(sources: List[str]) -> List[str]:
    """A DIRECTORY source is a serve-fleet spool: expand it to the
    replica endpoints advertised in its slot leases (pipeline/
    gateway.replica_endpoints), re-discovered on every refresh so a
    replica join/death shows up within one frame.  A spool with no
    live replicas contributes a sentinel source that renders
    unreachable — an empty fleet must look DOWN, not like an empty
    argument list."""
    out: List[str] = []
    for src in sources:
        if os.path.isdir(src):
            from ccsx_tpu.pipeline.gateway import replica_endpoints

            eps = replica_endpoints(src)
            out.extend(eps if eps else [os.path.join(src, "<no-replicas>")])
        else:
            out.append(src)
    return out


def read_source(src: str, timeout: float = 2.0) -> dict:
    """One `top` source -> {source, status, snap, event?, error?}.

    ``src`` is a telemetry endpoint (``host:port`` or an http URL) or a
    path to a ``--metrics`` JSONL file.  status: ok | degraded |
    unreachable (endpoint down / file unreadable — rendered loudly, a
    dead rank is exactly what the operator must see).
    """
    out = {"source": src, "status": "unreachable", "snap": None}
    if "://" in src or (":" in src and not os.path.exists(src)):
        url = src if "://" in src else f"http://{src}"
        try:
            snap = _fetch_endpoint(url.rstrip("/") + "/progress", timeout)
        except (OSError, ValueError) as e:
            out["error"] = str(e)
            return out
    else:
        try:
            snap = tail_metrics_jsonl(src)
        except OSError as e:
            out["error"] = str(e)
            return out
        if snap is None:
            out["error"] = "no metrics events yet"
            return out
        out["event"] = snap.get("event")
    out["snap"] = snap
    out["status"] = "degraded" if snap.get("degraded") else "ok"
    if out.get("event") == "final":
        out["status"] = ("finished-degraded" if snap.get("degraded")
                         else "finished")
    return out


def aggregate(sources: List[dict]) -> dict:
    """Multi-rank aggregate over read_source() results: counters SUM,
    progress pct is the MIN across ranks (the merge waits for the
    slowest shard), rates sum, ETA is the max, and any degraded or
    unreachable rank degrades the whole."""
    live = [s for s in sources if s.get("snap")]
    agg = {"sources": len(sources), "live": len(live),
           "unreachable": len(sources) - len(live)}
    for k in TOP_SUM_KEYS:
        agg[k] = sum(int(s["snap"].get(k) or 0) for s in live)
    agg["zmws_per_sec"] = round(
        sum(float(s["snap"].get("zmws_per_sec") or 0.0) for s in live), 3)
    progs = [s["snap"].get("progress") or {} for s in live]
    agg["rate_zmws_per_sec"] = round(
        sum(float(p.get("rate_zmws_per_sec") or 0.0) for p in progs), 3)
    agg["done"] = sum(int(p.get("done") or 0) for p in progs)
    totals = [p.get("total") for p in progs]
    agg["total"] = (sum(totals) if progs and all(totals) else None)
    pcts = [p["pct"] for p in progs if p.get("pct") is not None]
    agg["pct"] = min(pcts) if pcts and len(pcts) == len(live) else None
    etas = [p["eta_s"] for p in progs if p.get("eta_s") is not None]
    agg["eta_s"] = max(etas) if etas else None
    degraded = [s for s in live if s["snap"].get("degraded")]
    agg["any_degraded"] = bool(degraded) or agg["unreachable"] > 0
    agg["degraded_sources"] = [s["source"] for s in degraded]
    finished = [s for s in live
                if str(s.get("status", "")).startswith("finished")]
    agg["finished"] = bool(sources) and len(finished) == len(sources)
    # latency histograms: merge per-(family, label) by SUMMING per-`le`
    # bucket counts — never by averaging per-source quantiles, which do
    # not compose (two sources at p95=1s can have a fleet p95 of 10s)
    hists = [s["snap"].get("hist") or {} for s in live]
    merged: dict = {}
    for fam, _label_key, _prom in HIST_FAMILIES:
        labels = set()
        for h in hists:
            labels.update(h.get(fam) or {})
        if labels:
            merged[fam] = {
                lbl: merge_hist([(h.get(fam) or {}).get(lbl)
                                 for h in hists
                                 if (h.get(fam) or {}).get(lbl)])
                for lbl in sorted(labels)}
    agg["hist"] = merged
    for fam, key in (("queue_wait_s", "queue_wait"),
                     ("job_wall_s", "job_wall")):
        m = merged_family(merged, fam)
        agg[f"{key}_p50"] = hist_quantile(m, 0.5)
        agg[f"{key}_p95"] = hist_quantile(m, 0.95)
    return agg


# ---- `ccsx-tpu top` rendering ---------------------------------------------

_RED, _GREEN, _YELLOW, _DIM, _BOLD, _RESET = (
    "\x1b[31m", "\x1b[32m", "\x1b[33m", "\x1b[2m", "\x1b[1m", "\x1b[0m")


def _fmt_eta(s) -> str:
    if s is None:
        return "-"
    s = int(s)
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    if s >= 60:
        return f"{s // 60}m{s % 60:02d}s"
    return f"{s}s"


def _fmt_q(v) -> str:
    """Compact quantile seconds for the top table ('-' when absent)."""
    if v is None:
        return "-"
    return f"{v:.2f}" if v < 10 else f"{v:.0f}"


def _source_quantiles(snap: dict, fam: str):
    """(p50, p95) of one source's family, labels merged (None, None
    when the source has no observations — plain runs, gateways)."""
    m = merged_family(snap.get("hist") or {}, fam)
    if not m["count"]:
        return None, None
    return hist_quantile(m, 0.5), hist_quantile(m, 0.95)


def _bar(pct, width: int = 24) -> str:
    if pct is None:
        return "[" + "?" * width + "]"
    filled = int(round(pct / 100.0 * width))
    return "[" + "#" * filled + "." * (width - filled) + "]"


def render_top(sources: List[dict], agg: dict, color: bool = True) -> str:
    """One dashboard frame (plain ANSI, no curses)."""
    def c(code, s):
        return f"{code}{s}{_RESET}" if color else str(s)

    now = time.strftime("%H:%M:%S")
    if agg["any_degraded"]:
        # degraded outranks finished: a run that completed with a
        # tripped watchdog must not headline green
        state = c(_RED + _BOLD, "FINISHED DEGRADED"
                  if agg.get("finished") else "DEGRADED")
    elif agg.get("finished"):
        state = c(_GREEN, "FINISHED")
    else:
        state = c(_GREEN, "RUNNING ok")
    lines = [
        c(_BOLD, f"ccsx-tpu top — {agg['sources']} source(s) — {now}")
        + f"   {state}",
        f"  holes: in {agg['holes_in']}  out {agg['holes_out']}  "
        f"failed {agg['holes_failed']}  filtered {agg['holes_filtered']}"
        f"   windows {agg['windows']}  dispatches "
        f"{agg['device_dispatches']}",
        f"  rate {agg['rate_zmws_per_sec']} zmw/s   "
        + _bar(agg["pct"])
        + (f" {agg['pct']:.1f}%  of {agg['total']}  "
           f"eta {_fmt_eta(agg['eta_s'])}" if agg["pct"] is not None
           else " total unknown — rate only"),
    ]
    if agg.get("fleet_ranges_total"):
        lines.append(
            f"  fleet: ranges {agg['fleet_ranges_retired']}"
            f"/{agg['fleet_ranges_total']} retired  "
            f"queued {agg['fleet_ranges_queued']}  "
            f"leased {agg['fleet_ranges_leased']}  "
            f"ranks {agg['fleet_ranks_alive']}  "
            f"steals {agg['fleet_steals']}  "
            f"rebalances {agg['fleet_rebalances']}")
    if (agg["stalls"] or agg["oom_resplits"] or agg["host_fallbacks"]
            or agg["holes_failed"] or agg["device_hangs"]
            or agg["breaker_trips"]):
        lines.append(c(_YELLOW,
                       f"  incidents: stalls {agg['stalls']}  "
                       f"oom_resplits {agg['oom_resplits']}  "
                       f"host_fallbacks {agg['host_fallbacks']}  "
                       f"holes_failed {agg['holes_failed']}  "
                       f"device_hangs {agg['device_hangs']}  "
                       f"breaker_trips {agg['breaker_trips']}"))
    if (agg.get("queue_wait_p50") is not None
            or agg.get("job_wall_p50") is not None):
        # fleet latency headline: quantiles of the SUMMED-bucket merge
        lines.append(
            f"  latency: queue-wait p50 {_fmt_q(agg['queue_wait_p50'])}s"
            f" p95 {_fmt_q(agg['queue_wait_p95'])}s   "
            f"job-wall p50 {_fmt_q(agg['job_wall_p50'])}s"
            f" p95 {_fmt_q(agg['job_wall_p95'])}s")
    lines.append(c(_DIM, f"  {'source':<32} {'status':<18} "
                         f"{'out':>8} {'rate':>8} {'pct':>6} "
                         f"{'qw50/95':>11} {'wall50/95':>11}"))
    for s in sources:
        snap = s.get("snap") or {}
        prog = snap.get("progress") or {}
        status = s["status"]
        if status in ("degraded", "unreachable", "finished-degraded"):
            status_c = c(_RED, f"{status:<18}")
        elif status.startswith("finished"):
            status_c = c(_GREEN, f"{status:<18}")
        else:
            status_c = f"{status:<18}"
        pct = prog.get("pct")
        qw = _source_quantiles(snap, "queue_wait_s")
        jw = _source_quantiles(snap, "job_wall_s")
        lines.append(
            f"  {s['source']:<32} {status_c} "
            f"{snap.get('holes_out', '-'):>8} "
            f"{prog.get('rate_zmws_per_sec', '-'):>8} "
            f"{pct if pct is not None else '-':>6} "
            f"{_fmt_q(qw[0]) + '/' + _fmt_q(qw[1]):>11} "
            f"{_fmt_q(jw[0]) + '/' + _fmt_q(jw[1]):>11}")
        if snap.get("degraded"):
            lines.append(c(_RED, f"      {snap['degraded']}"))
        if s.get("error"):
            lines.append(c(_DIM, f"      {s['error']}"))
    return "\n".join(lines)


def top_main(argv) -> int:
    """The `ccsx-tpu top` subcommand (dispatched from cli.main).  No
    jax import, no backend init — safe on a host whose accelerator is
    hung (same discipline as `stats`)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="ccsx-tpu top",
        description="Live dashboard over running ccsx-tpu telemetry "
                    "endpoints (host:port) and/or --metrics JSONL "
                    "files; multi-rank sources aggregate (counters "
                    "sum, min progress, any-degraded).")
    ap.add_argument("sources", nargs="+",
                    help="telemetry endpoints (host:port or http URLs), "
                         "--metrics JSONL paths, and/or serve-fleet "
                         "spool DIRECTORIES (expanded to the replica "
                         "endpoints in their slot leases), any mix")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds [2.0]")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripts/tests)")
    ap.add_argument("--no-color", action="store_true")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint fetch timeout seconds [2.0]")
    a = ap.parse_args(argv)
    color = not a.no_color and (a.once or sys.stdout.isatty())
    try:
        while True:
            sources = [read_source(s, timeout=a.timeout)
                       for s in expand_sources(a.sources)]
            agg = aggregate(sources)
            frame = render_top(sources, agg, color=color)
            if a.once:
                print(frame)
                return 0
            # home + clear-to-end keeps the frame flicker-free without
            # curses; \x1b[J clears any taller previous frame
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
            if agg.get("finished"):
                return 0
            time.sleep(max(a.interval, 0.2))
    except KeyboardInterrupt:
        return 0
