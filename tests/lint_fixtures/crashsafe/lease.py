"""Known-bad twin for the bare-write checker: a lease-domain module
writing state with bare open + json.dump, no atomic publish."""

import json


def renew_lease(path, obj):
    # torn on SIGKILL between truncate and the last write: a reader
    # (or the crash-recovery scan) sees half a lease record
    with open(path, "w") as f:
        json.dump(obj, f)
