"""Dispatch flight recorder: span tracing, compile/execute attribution,
and a hang watchdog.

Why this exists (VERDICT r5): the framework had zero trustworthy TPU
throughput numbers — `BENCH_r05.json` is a CPU fallback stamped "tpu
attempt hung" with no diagnostics, and every earlier TPU figure was an
~1 ms RPC-ping reading of a lazy runtime.  Credible DP-throughput claims
need kernel-execute time separated from launch/compile overhead (the
gpuPairHMM discipline, PAPERS.md), and a hang needs to leave a report
behind, not a dead tunnel.  Three pieces:

* **Span tracer** (``--trace <path>``): thread-safe; every unit of work
  — ingest hole, prep batch, device dispatch, recovery rung, host
  replay, writer flush, journal update — is one JSONL record with wall
  ``ts``, run-relative ``mono``, ``dur`` seconds, thread, and args.  At
  close the JSONL is additionally exported as Chrome trace-event format
  (``<path minus .jsonl>.chrome.json``), loadable in Perfetto /
  chrome://tracing.  Device spans use the FORCED-EXECUTION close
  discipline: the span closes only after ``jax.block_until_ready`` on
  the dispatch outputs (``Span.force``), because the lazy axon runtime
  otherwise "completes" dispatches in ~1 ms without executing them
  (ARCHITECTURE.md measurement-quirk note).  The force applies only
  when a trace file is being written — an untraced run keeps the
  dispatch-all-then-materialize overlap untouched.

* **Per-shape-group attribution**: the first device span of each
  (group key, batch-dim shape) is a COMPILE call (XLA traces + compiles
  on first execution of a shape — including recompiles when a group's
  bucketed batch dim changes), later spans are steady-state EXECUTE.  The table — compiles,
  compile_s, execute_s, dispatches, dp_cells, dp_cells/s (steady-state
  cells over execute seconds) per group — accumulates into
  ``Metrics.group_stats`` and rides every metrics event via
  ``Metrics.snapshot()``, so recompile storms and slow groups are
  visible in any metrics JSONL.  Without ``--trace`` the spans are not
  forced, so on an async backend the per-group times degrade to
  dispatch-queue bookkeeping; the counts stay exact.

* **Stall watchdog** (``--stall-timeout``, default 120 s, 0 disables):
  a daemon thread that fires when a device-dispatch span stays open
  longer than the timeout (first-of-shape spans get ``COMPILE_GRACE`` x
  the budget — cold compiles are not hangs), and dumps — to stderr, the trace file, and
  the metrics stream — every Python thread stack, the in-flight shape
  group / slab plan, and a metrics snapshot, then marks the run
  degraded (``Metrics.degraded``, carried by every later event incl.
  final).  The watchdog needs no trace file: span open/close tracking
  around dispatches is always on (two perf_counter reads), and since
  an UNFORCED dispatch span closes in ~1 ms on an async runtime with
  the hang surfacing later, the executors' finish phase runs inside a
  watchdog-visible ``materialize`` device span (``attribute=False`` —
  timeline-only, never in the group table) — so the next "tpu attempt
  hung" produces an actionable report whichever side it hangs on.
  Deterministically testable via the ``stall`` fault-injection point
  (utils/faultinject.py), which sleeps inside a device dispatch.

``ccsx-tpu stats <trace/metrics JSONL>...`` summarizes artifacts into
the group table, a per-category stage breakdown, an occupancy recap,
and the top-N slowest dispatches (``stats_main`` below).

Wiring: the drivers construct a Tracer next to their Metrics and
``install()`` it process-globally for the run; call sites use the
module-level ``span`` / ``device_span`` / ``instant`` helpers, which
no-op (cheaply) when nothing is installed.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import json
import os
import sys
import threading
import time
import traceback
from typing import Dict, Optional

from ccsx_tpu.utils import blackbox

# span taxonomy (ARCHITECTURE.md "Observability"): every span carries
# one of these categories, which the stats stage-breakdown sums over
CATEGORIES = ("ingest", "prep", "compute", "device", "recover", "write",
              "journal", "host")

# metrics-snapshot keys the stats occupancy recap consumes — a module
# constant so the telemetry schema-drift guard (tests/test_telemetry.py)
# can prove a Metrics rename cannot silently zero a stats column
OCCUPANCY_KEYS = ("dp_occupancy", "dp_round_occupancy", "dp_length_fill",
                  "dp_pass_fill", "dp_z_fill", "dp_row_fill",
                  "packed_holes_per_dispatch", "prep_share",
                  "prep_overlap_share", "zmws_per_sec",
                  "device_dispatches", "holes_out", "elapsed_s")

# metrics-snapshot keys the stats resilience recap consumes (the
# dispatch-deadline / circuit-breaker / recovery story of a run) —
# schema-guarded like OCCUPANCY_KEYS (tests/test_telemetry.py)
RESILIENCE_KEYS = ("device_hangs", "breaker_state", "breaker_trips",
                   "breaker_probes", "host_fallbacks", "oom_resplits",
                   "compile_fallbacks", "holes_failed", "holes_corrupt",
                   "stalls")

_current: Optional["Tracer"] = None

# ---- correlation ids (ISSUE 18) --------------------------------------------
#
# The fleet-wide correlation id: minted once at job submission
# (gateway.submit_job / serve's solo submit) and entered here by
# whichever thread is currently working that job (serve's per-job
# thread, a fleet range worker, a helper pulling a sibling's range).
# Scope is a ContextVar, NOT a process global: serve runs jobs
# CONCURRENTLY (--max-active), so a process-wide cid would stamp one
# job's spans with another's id and unbalanced scope exits would leak
# a finished job's cid onto everything after it.  The job's device
# work fans across executor/prep/pump threads, which plain
# threading.Thread starts in a fresh context — those spawns go through
# ``faultinject.inherit()`` (the prep pool and deadline runner
# already do, for exactly this reason), which copies the spawning
# context and therefore carries the cid.  Spans additionally CAPTURE
# the cid at open, so a record written later from another thread (the
# stall watchdog's dump) still names the right job.  Every trace
# record and blackbox mirror written while a scope is open carries
# {"cid": ...}.

_cid_var: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("ccsx_cid", default=None)


def current_cid() -> Optional[str]:
    return _cid_var.get()


@contextlib.contextmanager
def cid_scope(cid: Optional[str]):
    """Stamp ``cid`` on every trace/blackbox record emitted by this
    context (and threads spawned through ``faultinject.inherit()``-
    wrapped targets) for the duration of the with-block (None =
    no-op: the ambient scope, if any, stays in force).  Token-based
    restore: overlapping scopes on concurrent job threads cannot
    clobber each other or leave a stale cid behind."""
    if cid is None:
        yield
        return
    token = _cid_var.set(cid)
    try:
        yield
    finally:
        _cid_var.reset(token)

# the stall watchdog multiplies its timeout by this for the FIRST
# device span of each (group, shape): first calls pay the XLA compile
# (through a remote-compile tunnel, minutes — bench.py's own deadline
# comment), and a healthy cold run must not be stamped degraded.
# Steady-state spans get the bare --stall-timeout.
COMPILE_GRACE = 10.0

# stall-report rate limit: the FIRST report is the full dump (all
# thread stacks + plan + metrics snapshot, can be megabytes with many
# threads); later reports within this window are compact one-liners —
# a long genuine hang stalls span after span, and without the limit it
# floods stderr/trace/metrics with identical stacks.  After the window
# a fresh full dump is allowed (a second, later hang deserves stacks).
FULL_DUMP_EVERY_S = 600.0


def install(tracer: "Tracer") -> None:
    """Make ``tracer`` the process-global target of span()/device_span()
    for the duration of a run (drivers pair this with uninstall() +
    close() in their finally blocks)."""
    global _current
    _current = tracer


def uninstall() -> None:
    global _current
    _current = None


def current() -> Optional["Tracer"]:
    return _current


class _NullSpan:
    """The no-op span: force() is the identity, so call sites can write
    ``return sp.force(step(...))`` unconditionally."""

    __slots__ = ()

    def force(self, out):
        return out


_NULL_SPAN = _NullSpan()


@contextlib.contextmanager
def _null_ctx():
    yield _NULL_SPAN


class Span:
    __slots__ = ("tracer", "sid", "name", "cat", "args", "t0", "ts",
                 "tid", "cid", "reported", "grace")

    def __init__(self, tracer, sid, name, cat, args):
        self.tracer = tracer
        self.sid = sid
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = time.perf_counter()
        self.ts = time.time()
        self.tid = threading.current_thread().name
        # captured at open: records derived from this span later, on
        # OTHER threads (watchdog stall dumps), still name the right
        # job even while concurrent jobs hold different ambient cids
        self.cid = _cid_var.get()
        self.reported = False   # watchdog: this span already dumped
        self.grace = 1.0        # stall-timeout multiplier (COMPILE_GRACE
        #   for first-of-shape device spans; set by device_span)

    def force(self, out):
        """Forced-execution close: block until the device work of this
        span's dispatch actually ran (lazy runtimes otherwise return
        unexecuted handles; see module docstring).  Applied only when a
        trace file is recording — watchdog-only runs keep the async
        dispatch overlap."""
        if self.tracer is not None and self.tracer.forced:
            import jax

            jax.block_until_ready(out)
        return out


class Tracer:
    """Thread-safe span recorder + group attribution + stall watchdog.

    ``path=None`` runs watchdog/attribution only (no records written);
    ``stall_timeout=0`` disables the watchdog.  ``metrics`` (optional)
    receives the group table (``metrics.group_stats``), the degraded
    mark, and a "stall" event when the watchdog fires.
    """

    def __init__(self, path: Optional[str] = None,
                 stall_timeout: float = 0.0, metrics=None):
        self.path = path or None
        self.stall_timeout = max(float(stall_timeout or 0.0), 0.0)
        self.metrics = metrics
        self.forced = self.path is not None
        # the group table lives on the Metrics object when there is one,
        # so Metrics.snapshot() carries it without a back-reference
        self.group_stats: Dict[str, dict] = (
            metrics.group_stats if metrics is not None else {})
        if metrics is not None:
            # published alongside the table: unforced per-group seconds
            # are dispatch-queue bookkeeping on an async backend, and a
            # consumer must be able to tell that from forced evidence
            metrics.groups_forced = self.forced
        self.stalled = False
        self._stall_dumps = 0      # reports so far (rate-limit state)
        self._last_full_dump = -float("inf")
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._t0_wall = time.time()
        self._seen: set = set()
        # (group, shape) pairs whose first span has OPENED — drives the
        # watchdog's compile grace, so it is tracked at open (attribution
        # _seen is tracked at close, and only for attributed successes)
        self._grace_seen: set = set()
        self._open: Dict[int, Span] = {}
        self._sid = 0
        # per-thread open-span stack: nested child seconds accumulate
        # here so records can carry "self" (dur minus children) and the
        # stats stage breakdown does not double-count a device span
        # inside its enclosing sweep span
        self._tls = threading.local()
        self._f = open(self.path, "w", encoding="utf-8") \
            if self.path else None
        if self._f is not None or blackbox.get() is not None:
            # the meta record also opens the blackbox ring's story for
            # file-less tracers (serve's Tracer(None, ...))
            self._write({"ev": "meta", "pid": os.getpid(),
                         "ts": self._t0_wall,
                         "stall_timeout_s": self.stall_timeout})
        self._stop = threading.Event()
        self._wd: Optional[threading.Thread] = None
        if self.stall_timeout > 0:
            self._wd = threading.Thread(target=self._watch, daemon=True,
                                        name="ccsx-stall-watchdog")
            self._wd.start()

    # ---- record plumbing -------------------------------------------------

    def _write(self, rec: dict) -> None:
        if "cid" not in rec:
            cid = _cid_var.get()
            if cid is not None:
                rec["cid"] = cid
        # mirror into the crash-persistent ring (no-op when
        # CCSX_BLACKBOX is unset): the mmap'd copy is what survives a
        # SIGKILL that the per-record flush below cannot outrun
        blackbox.record(rec)
        f = self._f
        if f is None:
            return
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            # flushed per record so a killed/hung run still leaves a
            # readable trace behind — the whole point of the recorder
            self._f.flush()

    def _push(self) -> None:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        st.append(0.0)

    def _pop(self, dur: float) -> float:
        """Close the top of this thread's span stack: credit ``dur`` to
        the parent, return the self time (dur minus nested children)."""
        st = self._tls.stack
        child = st.pop()
        if st:
            st[-1] += dur
        return dur - child

    def _span_rec(self, sp: Span, dur: float, **extra) -> dict:
        rec = {"ev": "span", "name": sp.name, "cat": sp.cat,
               "ts": round(sp.ts, 6),
               "mono": round(sp.t0 - self._t0, 6),
               "dur": round(dur, 6), "tid": sp.tid}
        if sp.cid is not None:
            rec["cid"] = sp.cid
        rec.update(extra)
        if sp.args:
            rec["args"] = sp.args
        return rec

    # ---- public span API -------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """A plain (non-device) span; records only when a trace file is
        open or the blackbox ring is armed (CCSX_BLACKBOX)."""
        if self._f is None and blackbox.get() is None:
            yield _NULL_SPAN
            return
        sp = Span(self, -1, name, cat, args)
        self._push()
        try:
            yield sp
        except StopIteration:
            # generator-protocol control flow (a driver's span around
            # next(stream) hitting EOF), not an error
            raise
        except BaseException:
            sp.args = dict(sp.args, error=True)
            raise
        finally:
            dur = time.perf_counter() - sp.t0
            self_s = self._pop(dur)
            rec = self._span_rec(sp, dur)
            if self_s < dur - 1e-9:    # had children: carry self time
                rec["self"] = round(self_s, 6)
            self._write(rec)

    @contextlib.contextmanager
    def device_span(self, name: str, group: Optional[str] = None,
                    cells: int = 0, plan=None, shape=None,
                    attribute: bool = True, warmup: bool = False,
                    **args):
        """A device-dispatch span: watchdog-registered while open,
        compile/execute-attributed at close.  ``group`` keys the
        attribution table; ``cells`` is the dispatched DP cell count
        (feeds dp_cells/s); ``plan`` is the free-form slab/shape plan
        the watchdog dumps when the span stalls.  ``shape`` is the part
        of the dispatched shape the group key does NOT carry (e.g. the
        bucketed batch dim Z/R/N): jit recompiles per distinct shape,
        so compile-vs-execute is detected per (group, shape) — a group
        whose batch dim oscillates shows compiles > 1 instead of
        booking the recompiles as execute time.  A dispatch that raises
        is recorded (error=true) but NOT attributed: the recovery
        ladder re-dispatches the work, and counting both the failed
        attempt and its retried halves would double-count cells.

        ``attribute=False`` makes a watchdog-visible span that stays
        OUT of the group table — the finish-phase materialization span:
        on an async runtime an untraced (unforced) dispatch span closes
        in ~1 ms and the actual hang surfaces later, when the finish
        callback blocks materializing the outputs, so that blocking
        wait must itself be a device span or the watchdog is blind to
        exactly the r5 dead-tunnel hang.  Attribution convention: only
        records carrying a "compile" key (true or false) enter group
        tables — failed and attribute=False spans carry none.

        ``warmup=True`` marks an AOT precompile span (pipeline/
        warmup.py): it consumes the (group, shape)'s compile slot — so
        the first REAL dispatch of a warmed shape books as execute,
        the trace-visible proof the compile overlapped the stream —
        and books compiles/compile_s in the group table WITHOUT
        counting a dispatch or cells (nothing was dispatched for a
        consumer).  A warmup span for an already-seen shape books
        nothing.  Warmup records carry top-level "warmup": true next
        to the "compile" key; the stats re-derivation applies the same
        rule (summarize)."""
        a = dict(args)
        key = group or name
        a["group"] = key
        if cells:
            a["cells"] = int(cells)
        if shape is not None:
            a["shape"] = shape
        if plan is not None:
            a["plan"] = plan
        with self._lock:
            self._sid += 1
            sid = self._sid
        sp = Span(self, sid, name, "device", a)
        with self._lock:
            # first span of a (group, shape) is the compile candidate:
            # it gets COMPILE_GRACE x the stall timeout (a cold compile
            # through a remote tunnel takes minutes and is not a hang)
            gkey = (key, shape)
            if gkey not in self._grace_seen:
                self._grace_seen.add(gkey)
                sp.grace = COMPILE_GRACE
            self._open[sid] = sp
        # span-BEGIN mirror, ring only: a SIGKILL mid-dispatch never
        # reaches the close record below, so the begin entry is the
        # ONLY evidence of what was in flight — inflight() pairs it
        # with the close by (tid, name)
        bb = blackbox.get()
        if bb is not None:
            brec = {"ev": "begin", "name": name, "group": key,
                    "ts": round(sp.ts, 6), "tid": sp.tid}
            if shape is not None:
                brec["shape"] = str(shape)
            if sp.cid is not None:
                brec["cid"] = sp.cid
            bb.record(brec)
        pushed = self._f is not None
        if pushed:
            self._push()
        failed = False
        try:
            yield sp
        except BaseException:
            failed = True
            sp.args = dict(sp.args, error=True)
            raise
        finally:
            dur = time.perf_counter() - sp.t0
            # device spans are normally leaves (self == dur), but keep
            # the accounting honest if one ever acquires children
            self_s = self._pop(dur) if pushed else dur
            first = False
            executed = False
            with self._lock:
                self._open.pop(sid, None)
                if attribute and not failed:
                    skey = (key, shape)
                    first = skey not in self._seen
                    self._seen.add(skey)
                    st = self.group_stats.setdefault(key, {
                        "compiles": 0, "compile_s": 0.0,
                        "execute_s": 0.0, "dispatches": 0,
                        "dp_cells": 0, "exec_cells": 0})
                    if warmup:
                        # AOT precompile: books the shape's one compile,
                        # no dispatch/cells; a redundant warmup of a
                        # seen shape books nothing at all
                        if first:
                            st["compiles"] += 1
                            st["compile_s"] += dur
                    else:
                        st["dispatches"] += 1
                        st["dp_cells"] += int(cells or 0)
                        if first:
                            # first call of a (group, shape) = XLA trace
                            # + compile + execute; later calls are
                            # steady-state execute
                            st["compiles"] += 1
                            st["compile_s"] += dur
                        else:
                            st["execute_s"] += dur
                            st["exec_cells"] += int(cells or 0)
                            executed = True
            if executed and self.metrics is not None:
                # per-group device-execute latency distribution
                # (steady-state only: compile calls would put the XLA
                # compile wall in the execute histogram)
                self.metrics.observe("device_execute_s", dur, key)
            if failed or not attribute:
                rec = self._span_rec(sp, dur)
            elif warmup:
                rec = self._span_rec(sp, dur, compile=first, warmup=True)
            else:
                rec = self._span_rec(sp, dur, compile=first)
            if self_s < dur - 1e-9:
                rec["self"] = round(self_s, 6)
            self._write(rec)

    def instant(self, name: str, cat: str = "host", **args) -> None:
        """A zero-duration marker (Chrome 'instant' event)."""
        if self._f is None and blackbox.get() is None:
            return
        rec = {"ev": "instant", "name": name, "cat": cat,
               "ts": round(time.time(), 6),
               "mono": round(time.perf_counter() - self._t0, 6),
               "tid": threading.current_thread().name}
        if args:
            rec["args"] = args
        self._write(rec)

    # ---- stall watchdog --------------------------------------------------

    def _watch(self) -> None:
        # check at timeout/4 so a stall is reported within one timeout
        # interval of exceeding it (bounded below for tiny test timeouts)
        interval = max(0.05, min(self.stall_timeout / 4.0, 5.0))
        while not self._stop.wait(interval):
            now = time.perf_counter()
            with self._lock:
                stalled = [s for s in self._open.values()
                           if not s.reported
                           and now - s.t0 > self.stall_timeout * s.grace]
                for s in stalled:
                    s.reported = True
            for s in stalled:
                self._stall_dump(s, now - s.t0)

    def _stall_dump(self, sp: Span, age: float) -> None:
        """The actionable hang report — stderr + trace file + metrics
        stream, then the run is marked degraded.  Rate-limited: the
        first report is the FULL dump (all thread stacks, the in-flight
        shape group/plan, a metrics snapshot); reports within
        FULL_DUMP_EVERY_S of the last full dump are compact one-liners
        (a long genuine hang stalls span after span, and megabytes of
        identical stacks help nobody)."""
        self.stalled = True
        now = time.perf_counter()
        full = now - self._last_full_dump >= FULL_DUMP_EVERY_S
        self._stall_dumps += 1
        if self.metrics is not None:
            # the watchdog thread runs concurrently with driver/pool
            # bump()s — take the counter lock like every other writer
            self.metrics.bump(stalls=1)
        if full:
            self._last_full_dump = now
            names = {t.ident: t.name for t in threading.enumerate()}
            stacks = {}
            for tid, frame in sys._current_frames().items():
                label = f"{names.get(tid, '?')}({tid})"
                stacks[label] = "".join(traceback.format_stack(frame))
            snap = (self.metrics.snapshot()
                    if self.metrics is not None else {})
            out = [
                f"[ccsx-tpu] STALL WATCHDOG: device dispatch {sp.name!r} "
                f"group={sp.args.get('group')!r} open for {age:.1f}s "
                f"(> {self.stall_timeout * sp.grace:g}s stall budget"
                + (f" = {sp.grace:g}x compile grace"
                   if sp.grace > 1 else "")
                + ") — dumping state",
                f"[ccsx-tpu]   in-flight: "
                f"args={json.dumps(sp.args, default=str)}",
            ]
            for label, stack in stacks.items():
                out.append(f"[ccsx-tpu]   -- thread {label} --")
                out.append(stack.rstrip("\n"))
            out.append(f"[ccsx-tpu]   metrics: "
                       f"{json.dumps(snap, default=str)}")
            print("\n".join(out), file=sys.stderr)
        else:
            print(f"[ccsx-tpu] STALL WATCHDOG: dispatch {sp.name!r} "
                  f"group={sp.args.get('group')!r} open {age:.1f}s "
                  f"(report #{self._stall_dumps}; full dump above, "
                  "compact repeat)", file=sys.stderr)
        sys.stderr.flush()
        rec = {"ev": "stall", "name": sp.name,
               "group": sp.args.get("group"),
               "open_s": round(age, 3),
               "ts": round(time.time(), 6),
               "mono": round(time.perf_counter() - self._t0, 6),
               "tid": sp.tid, "args": sp.args}
        if sp.cid is not None:
            # the watchdog thread has no ambient scope: the stalled
            # span's captured cid names the job that hung
            rec["cid"] = sp.cid
        if full:
            rec["stacks"] = {k: v[-4000:] for k, v in stacks.items()}
        else:
            rec["repeat"] = self._stall_dumps
        self._write(rec)
        if self.metrics is not None:
            self.metrics.degraded = (
                f"stall watchdog fired: dispatch {sp.name} "
                f"group={sp.args.get('group')} open > "
                f"{self.stall_timeout * sp.grace:g}s")
            self.metrics.emit("stall", span=sp.name,
                              group=sp.args.get("group"),
                              open_s=round(age, 3),
                              **({} if full
                                 else {"repeat": self._stall_dumps}))

    # ---- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Stop the watchdog, close the JSONL, write the Chrome export."""
        self._stop.set()
        if self._wd is not None:
            self._wd.join(timeout=10.0)
            self._wd = None
        with self._lock:
            f, self._f = self._f, None
        if f is None:
            return
        try:
            f.close()
        except OSError:
            pass
        try:
            export_chrome(self.path)
        except (OSError, ValueError) as e:
            print(f"[ccsx-tpu] trace: Chrome export failed: {e}",
                  file=sys.stderr)


# ---- module-level shims (no-ops when no tracer is installed) --------------

def span(name: str, cat: str = "host", **args):
    t = _current
    if t is None:
        return _null_ctx()
    return t.span(name, cat, **args)


def device_span(name: str, group: Optional[str] = None, cells: int = 0,
                plan=None, warmup: bool = False, **args):
    t = _current
    if t is None:
        return _null_ctx()
    return t.device_span(name, group=group, cells=cells, plan=plan,
                         warmup=warmup, **args)


def instant(name: str, cat: str = "host", **args) -> None:
    t = _current
    if t is not None:
        t.instant(name, cat, **args)


# ---- Chrome trace-event export --------------------------------------------

def chrome_path(path: str) -> str:
    base = path[:-6] if path.endswith(".jsonl") else path
    return base + ".chrome.json"


def export_chrome(path: str) -> str:
    """Convert a span JSONL into Chrome trace-event JSON (the {"
    traceEvents": [...]} object format Perfetto and chrome://tracing
    load).  Streams line by line at BOTH ends — one event in memory at
    a time — so the export of a million-hole trace cannot OOM the
    process after an otherwise-successful run.  Returns the output
    path."""
    out = chrome_path(path)
    pid = os.getpid()
    tids: Dict[str, int] = {}

    with open(path, encoding="utf-8") as f, \
            open(out, "w", encoding="utf-8") as fo:
        fo.write('{"displayTimeUnit": "ms", "traceEvents": [')
        n = 0

        def emit(e):
            nonlocal n
            fo.write(("," if n else "") + json.dumps(e))
            n += 1

        def tid_of(name):
            if name not in tids:
                tids[name] = len(tids) + 1
                emit({"ph": "M", "name": "thread_name", "pid": pid,
                      "tid": tids[name], "args": {"name": name}})
            return tids[name]

        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            ev = rec.get("ev")
            if ev == "meta":
                pid = rec.get("pid", pid)
                emit({"ph": "M", "name": "process_name",
                      "pid": pid, "tid": 0,
                      "args": {"name": "ccsx-tpu"}})
            elif ev == "span":
                args = dict(rec.get("args", {}))
                if rec.get("compile"):
                    args["compile"] = True
                if rec.get("warmup"):
                    args["warmup"] = True
                emit({
                    "ph": "X", "name": rec["name"], "cat": rec["cat"],
                    "ts": round(rec["mono"] * 1e6, 3),
                    "dur": round(rec["dur"] * 1e6, 3),
                    "pid": pid, "tid": tid_of(rec.get("tid", "main")),
                    "args": args})
            elif ev == "instant":
                emit({
                    "ph": "i", "s": "t", "name": rec["name"],
                    "cat": rec.get("cat", "host"),
                    "ts": round(rec["mono"] * 1e6, 3), "pid": pid,
                    "tid": tid_of(rec.get("tid", "main")),
                    "args": rec.get("args", {})})
            elif ev == "stall":
                emit({
                    "ph": "i", "s": "g",
                    "name": f"STALL: {rec.get('group')}", "cat": "device",
                    "ts": round(rec["mono"] * 1e6, 3), "pid": pid,
                    "tid": tid_of(rec.get("tid", "main")),
                    "args": {"open_s": rec.get("open_s")}})
        fo.write("]}")
    return out


def finalize_group_table(raw: Dict[str, dict]) -> dict:
    """Render raw per-group accumulators (compiles/compile_s/execute_s/
    dispatches/dp_cells/exec_cells) for output: rounded seconds plus
    the steady-state dp_cells_per_sec rate (compile-call cells excluded
    — the first call of a shape pays the XLA compile, so dividing its
    cells by its wall time would understate the chip).  THE one
    finalizer: Metrics._group_table (metrics events) and summarize()
    (trace files) both call it, so the 'same' table from either source
    cannot drift."""
    out = {}
    for key, st in sorted(raw.items()):
        ex = st["execute_s"]
        out[key] = {
            "compiles": st["compiles"],
            "compile_s": round(st["compile_s"], 4),
            "execute_s": round(ex, 4),
            "dispatches": st["dispatches"],
            "dp_cells": st["dp_cells"],
            "dp_cells_per_sec": round(st["exec_cells"] / ex)
                                if ex > 0 else None,
        }
    return out


# ---- `ccsx-tpu stats`: summarize trace/metrics JSONL artifacts ------------

def summarize(paths, top: int = 10) -> dict:
    """Digest any mix of trace JSONL and metrics JSONL files (records
    are distinguished per line: trace records carry "ev", metrics
    events carry "event") into the group table, stage breakdown,
    occupancy recap, and top-N slowest device dispatches.  One
    streaming pass — running sums plus a bounded min-heap for the
    slowest list — so summarizing a million-hole trace cannot OOM the
    process (the same discipline export_chrome applies)."""
    stalls = []
    final = None
    last_metrics = None
    n_spans = 0
    groups: Dict[str, dict] = {}
    stages: Dict[str, float] = {}
    slow_heap: list = []    # min-heap of (dur, seq, rendered entry)
    seq = 0
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("ev") == "stall":
                    stalls.append(rec)
                    continue
                if "event" in rec:
                    last_metrics = rec
                    if rec["event"] == "final":
                        final = rec
                    continue
                if rec.get("ev") != "span":
                    continue
                sp = rec
                n_spans += 1
                # "self" (dur minus nested children) keeps the category
                # sums disjoint: a sweep span must not re-count the
                # device spans recorded inside it
                stages[sp["cat"]] = (stages.get(sp["cat"], 0.0)
                                     + sp.get("self", sp["dur"]))
                if sp["cat"] != "device":
                    continue
                entry = {
                    "dur_s": round(sp["dur"], 4),
                    "group": str(sp.get("args", {}).get("group",
                                                        sp["name"])),
                    "compile": bool(sp.get("compile")),
                    "at_s": round(sp["mono"], 3), "tid": sp.get("tid"),
                }
                seq += 1
                if len(slow_heap) < top:
                    heapq.heappush(slow_heap, (sp["dur"], seq, entry))
                elif slow_heap and sp["dur"] > slow_heap[0][0]:
                    heapq.heapreplace(slow_heap, (sp["dur"], seq, entry))
                if "compile" not in sp:
                    # failed or attribute=False (materialize) spans: in
                    # the timeline and the slowest list, NOT in the
                    # group table — the same rule device_span applied
                    # to Metrics.group_stats
                    continue
                key = str(sp.get("args", {}).get("group", sp["name"]))
                st = groups.setdefault(key, {
                    "compiles": 0, "compile_s": 0.0, "execute_s": 0.0,
                    "dispatches": 0, "dp_cells": 0, "exec_cells": 0})
                if sp.get("warmup"):
                    # AOT warmup span (pipeline/warmup.py): the shape's
                    # compile, no dispatch — same rule device_span
                    # applied to Metrics.group_stats
                    if sp["compile"]:
                        st["compiles"] += 1
                        st["compile_s"] += sp["dur"]
                    continue
                st["dispatches"] += 1
                cells = int(sp.get("args", {}).get("cells", 0))
                st["dp_cells"] += cells
                if sp["compile"]:
                    st["compiles"] += 1
                    st["compile_s"] += sp["dur"]
                else:
                    st["execute_s"] += sp["dur"]
                    st["exec_cells"] += cells
    groups = finalize_group_table(groups)

    mrec = final or last_metrics
    occupancy = {}
    resilience = {}
    if mrec:
        for k in OCCUPANCY_KEYS:
            if mrec.get(k) is not None:
                occupancy[k] = mrec[k]
        for k in RESILIENCE_KEYS:
            if mrec.get(k) is not None:
                resilience[k] = mrec[k]
        if mrec.get("breaker_strike_log"):
            resilience["breaker_strike_log"] = \
                mrec["breaker_strike_log"]
    slowest = [e for _, _, e in
               sorted(slow_heap, key=lambda t: (-t[0], t[1]))]
    # a table built from span records came from a forced (--trace) run;
    # one inherited from a metrics file carries that file's discipline
    forced = True if groups else (mrec or {}).get("groups_forced")
    return {
        "paths": list(paths),
        "groups": groups or (mrec or {}).get("groups") or {},
        "groups_forced": forced,
        "stage_seconds": {k: round(v, 4)
                          for k, v in sorted(stages.items())},
        "slowest": slowest,
        "occupancy": occupancy,
        "resilience": resilience,
        "stalls": [{"group": s.get("group"), "open_s": s.get("open_s")}
                   for s in stalls],
        "degraded": (mrec or {}).get("degraded"),
        "n_spans": n_spans,
    }


def format_summary(d: dict) -> str:
    lines = [f"== ccsx-tpu stats: {' '.join(d['paths'])} =="]
    lines.append(f"spans: {d['n_spans']}")
    if d["groups"]:
        lines.append("shape groups:")
        if d.get("groups_forced") is False:
            lines.append("  !! UNFORCED timing (no --trace): per-group "
                         "seconds are dispatch-queue bookkeeping on an "
                         "async backend — counts exact, rates unreliable")
        hdr = (f"  {'group':<40} {'compiles':>8} {'compile_s':>10} "
               f"{'execute_s':>10} {'disp':>6} {'dp_cells':>14} "
               f"{'dp_cells/s':>12}")
        lines.append(hdr)
        for key, st in sorted(d["groups"].items()):
            cps = st.get("dp_cells_per_sec")
            lines.append(
                f"  {key:<40} {st['compiles']:>8} "
                f"{st['compile_s']:>10.4f} {st['execute_s']:>10.4f} "
                f"{st['dispatches']:>6} {st['dp_cells']:>14} "
                f"{cps if cps is not None else '-':>12}")
        # compile-storm guard (the r7 finding: packed groups paying 4-5
        # compiles each, one per distinct tail-slab R, invisible until
        # traced).  Canonical slab shapes bound a packed group to the
        # ladder size (default 2, --slab-shape-ladder); anything above
        # 1 deserves eyes, anything above 2 is the storm come back
        storms = {k: st["compiles"] for k, st in d["groups"].items()
                  if st["compiles"] > 1}
        if storms:
            worst = max(storms.items(), key=lambda kv: kv[1])
            bang = "!!" * 10 if worst[1] > 2 else "!!"
            lines.append(
                f"  {bang} compiles>1 in steady state: {len(storms)} "
                f"group(s) recompiled (worst {worst[0]} x{worst[1]}) — "
                "canonical-ladder budget is 2 (--slab-shape-ladder); "
                f">2 means the r7 compile storm is back {bang}")
    if d["stage_seconds"]:
        lines.append("stage breakdown (span self-seconds by category; "
                     "nested children excluded):")
        lines.append("  " + "  ".join(
            f"{k}={v:.4f}" for k, v in d["stage_seconds"].items()))
    if d["slowest"]:
        lines.append(f"top {len(d['slowest'])} slowest device dispatches:")
        for i, s in enumerate(d["slowest"], 1):
            tag = " (compile)" if s["compile"] else ""
            lines.append(f"  {i:>2}. {s['dur_s']:.4f}s {s['group']}{tag} "
                         f"@{s['at_s']}s [{s['tid']}]")
    if d["occupancy"]:
        lines.append("occupancy recap: " + "  ".join(
            f"{k}={v}" for k, v in d["occupancy"].items()))
    res = d.get("resilience") or {}
    # only worth a line when something actually happened (hangs, trips,
    # fallbacks, quarantines, salvaged input corruption) or the breaker
    # is not in its rest state
    if res and (any(res.get(k) for k in
                    ("device_hangs", "breaker_trips", "host_fallbacks",
                     "oom_resplits", "holes_failed", "holes_corrupt",
                     "stalls"))
                or res.get("breaker_state", "closed") != "closed"):
        lines.append("resilience recap: " + "  ".join(
            f"{k}={v}" for k, v in res.items()
            if k != "breaker_strike_log"))
        for s in res.get("breaker_strike_log", []):
            lines.append(f"  strike: kind={s.get('kind')} "
                         f"group={s.get('group')} ts={s.get('ts')}")
    for s in d["stalls"]:
        lines.append(f"STALL: group={s['group']} open_s={s['open_s']}")
    lines.append(f"degraded: {d['degraded'] or 'none'}")
    return "\n".join(lines)


def stats_main(argv) -> int:
    """The `ccsx-tpu stats` subcommand (dispatched from cli.main)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="ccsx-tpu stats",
        description="Summarize trace/metrics JSONL artifacts: shape-group "
                    "attribution, stage breakdown, occupancy recap, "
                    "slowest dispatches.")
    ap.add_argument("paths", nargs="+",
                    help="trace (--trace) and/or metrics (--metrics) "
                         "JSONL files; any mix")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest dispatches to list [10]")
    ap.add_argument("--json", default=None,
                    help="also write the summary as JSON to this path")
    a = ap.parse_args(argv)
    try:
        d = summarize(a.paths, top=a.top)
    except OSError as e:
        print(f"Error: stats: {e}", file=sys.stderr)
        return 1
    print(format_summary(d))
    if a.json:
        with open(a.json, "w", encoding="utf-8") as f:
            json.dump(d, f, indent=1, default=str)
    return 0
