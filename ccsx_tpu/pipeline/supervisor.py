"""`ccsx-tpu shepherd`: a rank supervisor for sharded runs.

Until now a dead rank in a sharded run was merely *visible*: the rank
never wrote its completion marker, ``merge_shards`` refused the merge,
and the operator was told to re-run the dead rank by hand
(parallel/distributed.py).  The ROADMAP north star is production-scale
serving, where "a human re-runs rank 3 at 2am" is not a failure story.
The shepherd turns that manual instruction into a supervised loop:

* **Launch** — the N ranks run as subprocesses of one supervisor
  process (`python -c` runners invoking the ordinary CLI with
  ``--hosts N --host-id r``), each with a per-rank log file
  (``<out>.shard<r>.log``) and — unless the caller provided one — a
  shepherd-owned journal (``<out>.shepherd.journal``; the sharded
  driver suffixes ``.shard<r>``), because the journal is what makes a
  restart a RESUME instead of a recompute.

* **Monitor** — liveness is the rank's *progress heartbeat*: the
  newest mtime across its shard journal, shard output, and ordinal
  sidecar (the journal is fsynced at least once a second while holes
  retire).  With ``--telemetry-port`` the per-rank ``/healthz``
  endpoints (base port + rank, parallel/distributed.py) are polled too
  — a 503/degraded rank is reported in the shepherd log; an
  *unreachable* endpoint is only informational (the process poll is
  the authority on death).  A rank whose heartbeat goes stale past
  ``--rank-stall-timeout`` (0 = disabled; size it above your worst
  cold-compile time, or serve telemetry and rely on the rank's own
  ``--dispatch-deadline`` instead) is SIGKILLed and treated as dead.

* **Restart** — a dead rank (nonzero exit, or killed as stalled) is
  relaunched with exponential backoff (``--rank-backoff`` x 2^attempt)
  up to ``--max-rank-restarts`` times; it resumes from its shard
  journal, so already-durable records are never recomputed.
  ``CCSX_FAULTS`` is stripped from restart environments — injected
  faults model the FIRST failure, and a restarted rank must run clean
  (the chaos harness depends on this).  A rank that exhausts its
  restarts fails the whole run (rc 1) — the remaining ranks are still
  driven to completion so their journals are warm for a later retry.

* **Merge** — when every rank has exited 0 (completion markers in
  place), the shepherd runs the ordinary ``merge_shards`` and exits 0.
  Output is byte-identical to an unsharded run by the existing merge
  invariants, restarts included (pinned by tests/test_supervisor.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ccsx_tpu import exitcodes

# the subprocess runner body; a PRELUDE (backend pinning for tests /
# CPU-forced environments) may be prepended
_RUNNER = ("import sys; from ccsx_tpu.cli import main; "
           "sys.exit(main(sys.argv[1:]))")

# shepherd-only flags stripped from the forwarded rank command line
_SHEPHERD_FLAGS = ("--max-rank-restarts", "--rank-backoff",
                   "--rank-stall-timeout", "--fleet-ranges",
                   "--lease-timeout", "--join")


def default_prelude() -> str:
    """Backend pinning for the rank runners: when this process is
    itself forced onto CPU (JAX_PLATFORMS=cpu — the test suite, `make
    chaos`, CI), the ranks must be too; some accelerator plugins
    override the env var at import time, so the pin must be an explicit
    jax.config call before the CLI imports (the same idiom as
    tests/test_faults._run_cli_subprocess)."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return ("import jax; "
                "jax.config.update('jax_platforms', 'cpu'); ")
    return ""


def strip_shepherd_flags(argv: List[str],
                         flags=_SHEPHERD_FLAGS) -> List[str]:
    """Remove shepherd-only options (+ their values) from an argv so
    the remainder forwards verbatim to the rank command lines."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in flags:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in flags):
            continue
        out.append(a)
    return out


@dataclasses.dataclass
class _Rank:
    rank: int
    proc: Optional[subprocess.Popen] = None
    log: Optional[object] = None
    attempts: int = 0          # restarts used (0 = first launch)
    beat: float = 0.0          # monotonic time of last progress sign
    last_mtime: Optional[float] = None  # newest observed shard mtime
    relaunch_at: Optional[float] = None
    done: bool = False
    failed: Optional[str] = None
    failed_rc: Optional[int] = None
    last_health: Optional[str] = None
    # rc-75 bookkeeping: a drained rank is VOLUNTARY preemption, not a
    # crash — relaunched immediately without touching the restart
    # budget (preempted suppresses a re-applied first_launch fault);
    # a fleet worker that drains instead LEAVES (drained)
    preempted: bool = False
    drained: bool = False


def _beat_paths(out_path: str, journal: str, rank: int) -> List[str]:
    return [f"{journal}.shard{rank}",
            f"{out_path}.shard{rank}",
            f"{out_path}.shard{rank}.idx"]


def _latest_mtime(paths: List[str]) -> Optional[float]:
    best = None
    for p in paths:
        try:
            m = os.stat(p).st_mtime
        except OSError:
            continue
        best = m if best is None or m > best else best
    return best


def _poll_healthz(port: int, timeout: float = 0.5) -> Optional[str]:
    """'ok' | 'degraded' | None (unreachable).  Best effort only — the
    endpoint auto-bumps when its port is taken, so unreachable is
    informational, never a death verdict."""
    import urllib.error
    import urllib.request

    url = f"http://127.0.0.1:{port}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode()).get("status", "ok")
    except urllib.error.HTTPError as e:  # 503 carries the body
        try:
            return json.loads(e.read().decode()).get("status",
                                                     "degraded")
        except (ValueError, OSError):
            return "degraded"
    except (OSError, ValueError):
        return None


def _blackbox_hint(pid: Optional[int], *dirs: Optional[str]) -> None:
    """Point the reap log at a dead child's black-box ring when one
    exists (the CCSX_BLACKBOX flight recorder, utils/blackbox.py): the
    supervisor is the first reader of a SIGKILL, and this one line is
    the hop from 'pid N died' to WHAT it was doing when it died."""
    if not pid:
        return
    from ccsx_tpu.utils import blackbox

    for bd in (os.environ.get(blackbox.ENV_DIR),) + dirs:
        if not bd:
            continue
        p = blackbox.box_path(bd, pid)
        if os.path.exists(p):
            print(f"[ccsx-tpu] black box for pid {pid}: "
                  f"`ccsx-tpu blackbox {p}`", file=sys.stderr)
            return


def shepherd_run(in_path: str, out_path: str, hosts: int,
                 forward_args: List[str],
                 journal: Optional[str] = None,
                 max_restarts: int = 2,
                 backoff_s: float = 1.0,
                 rank_stall_timeout: float = 0.0,
                 telemetry_port: int = 0,
                 env: Optional[dict] = None,
                 first_launch_env: Optional[Dict[int, dict]] = None,
                 poll_s: float = 0.25,
                 merge: bool = True,
                 runner_prelude: Optional[str] = None) -> int:
    """Supervise a sharded run end to end; returns a process rc
    (exitcodes.py: 0 = merged, 1 = a rank exhausted its restarts or
    the merge was refused).

    ``forward_args`` is the full rank CLI argv (flags + INPUT OUTPUT,
    including ``--hosts``) WITHOUT ``--host-id`` — the shepherd
    appends it per rank.  ``first_launch_env`` maps rank -> extra env
    for attempt 0 only (the fault-injection hook: restarts run clean).
    """
    from ccsx_tpu.parallel.distributed import merge_shards

    if hosts < 1:
        print("Error: shepherd needs --hosts >= 1", file=sys.stderr)
        return exitcodes.RC_FATAL
    base_env = dict(os.environ if env is None else env)
    prelude = (default_prelude() if runner_prelude is None
               else runner_prelude)
    first_launch_env = first_launch_env or {}
    # a journal is what makes a restart a resume; inject one when the
    # caller didn't ask for their own
    fwd = list(forward_args)
    if journal is None and "--journal" not in fwd:
        journal = f"{out_path}.shepherd.journal"
        fwd += ["--journal", journal]
    elif journal is None:
        journal = fwd[fwd.index("--journal") + 1]

    def launch(st: _Rank) -> None:
        e = dict(base_env)
        rank_fwd = fwd
        if st.attempts == 0 and not st.preempted:
            e.update(first_launch_env.get(st.rank, {}))
        else:
            # restarts run clean: injected faults model the FIRST
            # failure (a re-armed rank_death would die forever) — both
            # the env form AND the forwarded CLI flag
            e.pop("CCSX_FAULTS", None)
            rank_fwd = strip_shepherd_flags(fwd,
                                            flags=("--inject-faults",))
        cmd = [sys.executable, "-c", prelude + _RUNNER, *rank_fwd,
               "--host-id", str(st.rank)]
        log_path = f"{out_path}.shard{st.rank}.log"
        try:
            st.log = open(log_path, "a", encoding="utf-8")
            st.log.write(f"\n=== shepherd launch rank {st.rank} attempt "
                         f"{st.attempts} @ {time.strftime('%H:%M:%S')} "
                         f"===\n")
            st.log.flush()
            sink = st.log
        except OSError as e_log:
            # an unwritable log (e.g. the output dir itself is the
            # problem) must not crash the supervisor — the rank will
            # fail with the real error on its own
            print(f"[ccsx-tpu] shepherd: cannot open {log_path} "
                  f"({e_log}); rank {st.rank} output discarded",
                  file=sys.stderr)
            st.log = None
            sink = subprocess.DEVNULL
        st.proc = subprocess.Popen(cmd, env=e, stdout=sink,
                                   stderr=subprocess.STDOUT)
        st.beat = time.monotonic()
        st.relaunch_at = None
        print(f"[ccsx-tpu] shepherd: rank {st.rank} up (pid "
              f"{st.proc.pid}, attempt {st.attempts}, log {log_path})",
              file=sys.stderr)

    def close_log(st: _Rank) -> None:
        if st.log is not None:
            try:
                st.log.close()
            except OSError:
                pass
            st.log = None

    def schedule_restart(st: _Rank, reason: str) -> None:
        pid = st.proc.pid if st.proc is not None else None
        close_log(st)
        st.proc = None
        _blackbox_hint(pid)
        if st.attempts >= max_restarts:
            st.failed = (f"rank {st.rank} {reason} and exhausted its "
                         f"{max_restarts} restart(s)")
            st.done = True
            print(f"[ccsx-tpu] shepherd: {st.failed}", file=sys.stderr)
            return
        st.attempts += 1
        delay = backoff_s * (2 ** (st.attempts - 1))
        st.relaunch_at = time.monotonic() + delay
        print(f"[ccsx-tpu] shepherd: rank {st.rank} {reason}; "
              f"restarting in {delay:g}s (attempt {st.attempts}/"
              f"{max_restarts}; resumes from its shard journal)",
              file=sys.stderr)

    ranks = [_Rank(rank=r) for r in range(hosts)]
    for st in ranks:
        launch(st)
    last_health_poll = 0.0
    try:
        while not all(st.done for st in ranks):
            now = time.monotonic()
            poll_health = (telemetry_port
                           and now - last_health_poll >= 2.0)
            if poll_health:
                last_health_poll = now
            for st in ranks:
                if st.done:
                    continue
                if st.proc is None:
                    if st.relaunch_at is not None and now >= st.relaunch_at:
                        launch(st)
                    continue
                rc = st.proc.poll()
                if rc is not None:
                    if rc == 0:
                        st.done = True
                        close_log(st)
                        print(f"[ccsx-tpu] shepherd: rank {st.rank} "
                              "completed", file=sys.stderr)
                    elif rc == exitcodes.RC_INTERRUPTED:
                        # graceful drain (rc 75, EX_TEMPFAIL) is
                        # VOLUNTARY preemption — the rank made its work
                        # durable and asked to be resumed.  Counting it
                        # against --max-rank-restarts (like a crash)
                        # would fail a run that merely got SIGTERMed N
                        # times by a preemptible-capacity scheduler:
                        # relaunch immediately, no budget spent, no
                        # backoff, and never re-arm a first-launch
                        # fault (st.preempted)
                        close_log(st)
                        st.proc = None
                        st.preempted = True
                        st.relaunch_at = now
                        print(f"[ccsx-tpu] shepherd: rank {st.rank} "
                              "drained (rc 75) — voluntary preemption; "
                              "relaunching without spending the "
                              "restart budget", file=sys.stderr)
                    elif rc == exitcodes.RC_FAILED_HOLES:
                        # a failed-hole budget abort is DETERMINISTIC:
                        # the journal carries the failure count across
                        # resumes, so a restart would re-abort — fail
                        # the rank immediately instead of burning the
                        # restart budget on it
                        close_log(st)
                        st.proc = None
                        st.failed = (f"rank {st.rank} exceeded its "
                                     "--max-failed-holes budget (rc "
                                     f"{rc}); not restartable")
                        st.failed_rc = rc
                        st.done = True
                        print(f"[ccsx-tpu] shepherd: {st.failed}",
                              file=sys.stderr)
                    else:
                        schedule_restart(st, f"died (rc {rc})")
                    continue
                # progress heartbeat: journal/shard mtimes (fsynced at
                # least once a second while holes retire).  A CHANGED
                # mtime stamps the beat on OUR monotonic clock —
                # comparing wall-clock mtimes against monotonic time
                # would let an NTP step mark every healthy rank stale
                m = _latest_mtime(_beat_paths(out_path, journal,
                                              st.rank))
                if m is not None and m != st.last_mtime:
                    st.last_mtime = m
                    st.beat = now
                if poll_health:
                    h = _poll_healthz(telemetry_port + st.rank)
                    if h != st.last_health and h is not None:
                        st.last_health = h
                        if h != "ok":
                            print(f"[ccsx-tpu] shepherd: rank "
                                  f"{st.rank} /healthz reports {h}",
                                  file=sys.stderr)
                if (rank_stall_timeout > 0
                        and now - st.beat > rank_stall_timeout):
                    print(f"[ccsx-tpu] shepherd: rank {st.rank} "
                          f"heartbeat stale for >{rank_stall_timeout:g}s"
                          " — killing the wedged rank", file=sys.stderr)
                    try:
                        st.proc.send_signal(signal.SIGKILL)
                        st.proc.wait(timeout=10.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    schedule_restart(st, "stalled")
            time.sleep(poll_s)
    finally:
        for st in ranks:
            if st.proc is not None and st.proc.poll() is None:
                st.proc.kill()
                try:
                    st.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            close_log(st)
    failed = [st for st in ranks if st.failed]
    if failed:
        print("Error: shepherd run failed: "
              + "; ".join(st.failed for st in failed)
              + " — surviving ranks completed and their journals are "
              "intact; fix the cause and re-run the shepherd to resume",
              file=sys.stderr)
        # preserve the exit-code taxonomy through supervision: when
        # every failure is the deterministic failed-hole budget abort,
        # the shepherd reports rc 2 like an unsharded run would; any
        # other failure class stays the generic rc 1
        rcs = {st.failed_rc for st in failed}
        if rcs == {exitcodes.RC_FAILED_HOLES}:
            return exitcodes.RC_FAILED_HOLES
        return exitcodes.RC_FATAL
    if not merge:
        return exitcodes.RC_OK
    try:
        n = merge_shards(out_path, hosts)
    except (OSError, ValueError) as e:
        print(f"Error: shepherd merge refused: {e}", file=sys.stderr)
        return exitcodes.RC_FATAL
    print(f"[ccsx-tpu] shepherd: merged {n} records from {hosts} "
          "ranks", file=sys.stderr)
    return exitcodes.RC_OK


def _spawn_worker(cmd: List[str], env: dict, log_path: str,
                  banner: str):
    """Launch one fleet worker with a per-worker append log; an
    unwritable log degrades to DEVNULL (same contract as the static
    shepherd's launch)."""
    try:
        log = open(log_path, "a", encoding="utf-8")
        log.write(banner)
        log.flush()
        sink = log
    except OSError as e:
        print(f"[ccsx-tpu] fleet: cannot open {log_path} ({e}); "
              "worker output discarded", file=sys.stderr)
        log = None
        sink = subprocess.DEVNULL
    proc = subprocess.Popen(cmd, env=env, stdout=sink,
                            stderr=subprocess.STDOUT)
    return proc, log


def fleet_run(in_path: str, out_path: str, cfg, hosts: int,
              forward_args: List[str],
              ranges: int = 0,
              lease_timeout: float = 10.0,
              max_restarts: int = 2,
              backoff_s: float = 1.0,
              telemetry_port: int = 0,
              env: Optional[dict] = None,
              first_launch_env: Optional[Dict[int, dict]] = None,
              poll_s: float = 0.25,
              merge: bool = True,
              runner_prelude: Optional[str] = None) -> int:
    """The elastic scheduler (`ccsx-tpu shepherd --fleet-ranges M`):
    split the input into M >> N leased ranges (pipeline/fleet.py),
    launch ``hosts`` pull workers, and supervise the QUEUE rather than
    fixed rank assignments:

    * a worker death immediately requeues its leased range(s) to the
      survivors (fast rebalance — no in-place restart needed; the
      worker is also relaunched while its restart budget lasts, as an
      optimization, never a requirement while others live);
    * leases whose heartbeat goes stale past ``lease_timeout`` are
      expired — local holder SIGKILLed first, then the lease is
      renamed away (kill-before-steal) — covering workers the
      scheduler did not launch (mid-run ``--join``);
    * rc 75 from a worker is a voluntary leave (graceful drain): its
      leases are already released, survivors absorb the queue;
    * when all M range markers are in, the ordinary
      ``merge_shards(out, M)`` restores the byte-identical output and
      the fleet dir is cleaned up.

    Returns 0 on merge, 75 when the whole fleet drained with the queue
    unfinished (re-run the same command to resume), 2/1 on failures
    (taxonomy preserved, like the static shepherd)."""
    import shutil

    from ccsx_tpu.parallel.distributed import merge_shards
    from ccsx_tpu.pipeline import fleet
    from ccsx_tpu.pipeline.run import count_raw_holes
    from ccsx_tpu.utils.metrics import Metrics

    if hosts < 1:
        print("Error: fleet needs --hosts >= 1", file=sys.stderr)
        return exitcodes.RC_FATAL
    base_env = dict(os.environ if env is None else env)
    prelude = (default_prelude() if runner_prelude is None
               else runner_prelude)
    first_launch_env = dict(first_launch_env or {})
    try:
        n_holes = count_raw_holes(in_path, cfg)
    except (OSError, RuntimeError, ValueError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        return exitcodes.RC_FATAL
    # M >> N by default: enough granularity that a lost rank requeues
    # ~one range, not 1/N of the run; explicit --fleet-ranges pins it
    m = ranges if ranges > 0 else max(hosts,
                                      min(max(n_holes, 1), 4 * hosts))
    d = fleet.fleet_dir_for(out_path)
    # workers pull their WHOLE config from the forwarded argv; the
    # scheduler-only topology flags must not reach them (--hosts would
    # trip the static sharded path, --journal the per-rank injection —
    # fleet resume lives in the per-range journals)
    worker_fwd = strip_shepherd_flags(
        list(forward_args), flags=("--hosts", "--journal"))
    try:
        state = fleet.init_fleet(d, in_path, out_path, n_holes, m,
                                 lease_timeout,
                                 forward_args=worker_fwd)
    except (OSError, ValueError) as e:
        print(f"Error: fleet init failed: {e}", file=sys.stderr)
        return exitcodes.RC_FATAL
    m = len(state["ranges"])
    table = state["table"]
    metrics = Metrics(verbose=False)
    telem = None
    if telemetry_port:
        from ccsx_tpu.utils import telemetry as telemetry_mod

        telem = telemetry_mod.start(metrics, telemetry_port)
    steals = 0
    rebalances = 0
    expiry_seq = 0

    def launch(w: _Rank) -> None:
        e = dict(base_env)
        wf = worker_fwd
        if w.attempts == 0 and not w.preempted:
            e.update(first_launch_env.get(w.rank, {}))
        else:
            e.pop("CCSX_FAULTS", None)
            wf = strip_shepherd_flags(worker_fwd,
                                      flags=("--inject-faults",))
        name = f"w{w.rank}"
        cmd = [sys.executable, "-c", prelude + _RUNNER, *wf,
               "--fleet-dir", d, "--fleet-worker", name]
        log_path = f"{out_path}.fleet.{name}.log"
        banner = (f"\n=== fleet launch worker {name} attempt "
                  f"{w.attempts} @ {time.strftime('%H:%M:%S')} ===\n")
        w.proc, w.log = _spawn_worker(cmd, e, log_path, banner)
        w.relaunch_at = None
        print(f"[ccsx-tpu] fleet: worker {name} up (pid {w.proc.pid}, "
              f"attempt {w.attempts}, log {log_path})", file=sys.stderr)

    def close_log(w: _Rank) -> None:
        if w.log is not None:
            try:
                w.log.close()
            except OSError:
                pass
            w.log = None

    workers = [_Rank(rank=i) for i in range(hosts)]
    for w in workers:
        launch(w)
    qs = {"done": 0, "leased": 0, "queued": m}
    try:
        while True:
            now = time.monotonic()
            qs = fleet.queue_state(d, out_path, m)
            if qs["done"] >= m:
                break
            live = pending = 0
            for w in workers:
                if w.done:
                    continue
                if w.proc is None:
                    if w.relaunch_at is not None:
                        if now >= w.relaunch_at:
                            launch(w)
                            live += 1
                        else:
                            pending += 1
                    continue
                rc = w.proc.poll()
                if rc is None:
                    live += 1
                    continue
                pid = w.proc.pid
                close_log(w)
                w.proc = None
                if rc == 0:
                    w.done = True
                    print(f"[ccsx-tpu] fleet: worker w{w.rank} "
                          "completed", file=sys.stderr)
                elif rc == exitcodes.RC_INTERRUPTED:
                    # voluntary leave: the drain released its lease
                    # with the range journal durable — the queue keeps
                    # the work, the survivors absorb it
                    w.done = True
                    w.drained = True
                    print(f"[ccsx-tpu] fleet: worker w{w.rank} drained "
                          "(rc 75) — voluntary leave; its ranges stay "
                          "queued for the survivors", file=sys.stderr)
                elif rc == exitcodes.RC_FAILED_HOLES:
                    w.done = True
                    w.failed = (f"worker w{w.rank} exceeded its "
                                "--max-failed-holes budget (rc "
                                f"{rc}); not restartable")
                    w.failed_rc = rc
                    print(f"[ccsx-tpu] fleet: {w.failed}",
                          file=sys.stderr)
                else:
                    # fast rebalance: the worker is KNOWN dead — free
                    # its leases now, don't wait out the lease timeout
                    freed = fleet.reclaim_worker_leases(d, m, pid)
                    if freed:
                        steals += len(freed)
                        rebalances += 1
                        print(f"[ccsx-tpu] fleet: worker w{w.rank} "
                              f"died (rc {rc}); requeued range(s) "
                              f"{freed} for the survivors",
                              file=sys.stderr)
                    _blackbox_hint(pid, d)
                    if w.attempts >= max_restarts:
                        # out of budget: the worker LEAVES; this only
                        # fails the run if nobody is left to drain the
                        # queue
                        w.done = True
                        w.failed = (f"worker w{w.rank} died (rc {rc}) "
                                    "and exhausted its "
                                    f"{max_restarts} restart(s)")
                        w.failed_rc = rc
                        print(f"[ccsx-tpu] fleet: {w.failed}",
                              file=sys.stderr)
                    else:
                        w.attempts += 1
                        delay = backoff_s * (2 ** (w.attempts - 1))
                        w.relaunch_at = now + delay
                        pending += 1
                        print(f"[ccsx-tpu] fleet: worker w{w.rank} "
                              f"died (rc {rc}); relaunching in "
                              f"{delay:g}s (attempt {w.attempts}/"
                              f"{max_restarts})", file=sys.stderr)
            # timeout expiry: covers holders the scheduler did NOT
            # launch (joined workers, leaked pids) — kill-before-steal
            for i in range(m):
                ev = fleet.expire_lease(d, i, lease_timeout,
                                        seq=expiry_seq)
                expiry_seq += 1
                if ev is not None:
                    steals += 1
                    rebalances += 1
                    print(f"[ccsx-tpu] fleet: lease on range {i} "
                          f"expired (holder "
                          f"{ev.get('worker', '<torn>')}); requeued",
                          file=sys.stderr)
            # fleet gauges: scraped via /metrics and `ccsx-tpu top`
            metrics.fleet_ranges_total = m
            metrics.fleet_ranges_queued = qs["queued"]
            metrics.fleet_ranges_leased = qs["leased"]
            metrics.fleet_ranges_retired = qs["done"]
            metrics.fleet_ranks_alive = live
            metrics.fleet_steals = steals
            metrics.fleet_rebalances = rebalances
            if live == 0 and pending == 0:
                break
            time.sleep(poll_s)
    finally:
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.kill()
                try:
                    w.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            close_log(w)
        if telem is not None:
            telem.close()
    metrics.fleet_ranges_retired = qs["done"]
    if qs["done"] < m:
        failed = [w for w in workers if w.failed]
        if failed:
            print("Error: fleet run failed: "
                  + "; ".join(w.failed for w in failed)
                  + f" — {qs['done']}/{m} ranges retired; their "
                  "journals and markers are intact; fix the cause and "
                  "re-run the shepherd to resume", file=sys.stderr)
            rcs = {w.failed_rc for w in failed}
            if rcs == {exitcodes.RC_FAILED_HOLES}:
                return exitcodes.RC_FAILED_HOLES
            return exitcodes.RC_FATAL
        # nobody failed: the whole fleet drained away (SIGTERM) with
        # the queue unfinished — resumable, rc 75 like a drained rank
        print(f"[ccsx-tpu] fleet: drained with {qs['done']}/{m} ranges "
              "retired; re-run the same command to resume",
              file=sys.stderr)
        return exitcodes.RC_INTERRUPTED
    if not merge:
        return exitcodes.RC_OK
    try:
        n = merge_shards(out_path, m, expect_table=table)
    except (OSError, ValueError) as e:
        print(f"Error: fleet merge refused: {e}", file=sys.stderr)
        return exitcodes.RC_FATAL
    print(f"[ccsx-tpu] fleet: merged {n} records from {m} leased "
          f"ranges ({hosts} worker(s))", file=sys.stderr)
    shutil.rmtree(d, ignore_errors=True)
    return exitcodes.RC_OK


def fleet_join(d: str, hosts: int,
               env: Optional[dict] = None,
               poll_s: float = 0.25,
               runner_prelude: Optional[str] = None) -> int:
    """`ccsx-tpu shepherd --join <out>.fleet --hosts K`: add K pull
    workers to a RUNNING fleet mid-run.  Subordinate by design — the
    primary scheduler owns expiry and the merge; a joiner just pulls
    from the same queue (its workers' argv comes from fleet.json, so
    the config is exactly the primary's).  Exits 0 when its workers
    finish (the queue drained or was finished by others)."""
    from ccsx_tpu.pipeline import fleet

    state = fleet.load_fleet(d)
    if state is None:
        print(f"Error: {d} has no readable fleet state (is the fleet "
              "running? start one with --fleet-ranges)", file=sys.stderr)
        return exitcodes.RC_FATAL
    base_env = dict(os.environ if env is None else env)
    prelude = (default_prelude() if runner_prelude is None
               else runner_prelude)
    out_path = state["output"]
    procs = []
    logs = []
    for k in range(hosts):
        name = f"j{os.getpid()}w{k}"
        cmd = [sys.executable, "-c", prelude + _RUNNER,
               *state.get("forward", []),
               "--fleet-dir", d, "--fleet-worker", name]
        log_path = f"{out_path}.fleet.{name}.log"
        banner = (f"\n=== fleet join worker {name} @ "
                  f"{time.strftime('%H:%M:%S')} ===\n")
        proc, log = _spawn_worker(cmd, base_env, log_path, banner)
        procs.append(proc)
        logs.append(log)
        print(f"[ccsx-tpu] fleet: joined worker {name} (pid "
              f"{proc.pid}, log {log_path})", file=sys.stderr)
    rc = exitcodes.RC_OK
    try:
        while any(p.poll() is None for p in procs):
            time.sleep(poll_s)
        for p in procs:
            prc = p.returncode
            if prc in (0, exitcodes.RC_INTERRUPTED):
                continue
            if fleet.load_fleet(d) is None:
                # the primary retired the queue, merged, and removed
                # the fleet dir while this worker was mid-pull; its
                # crash is the completion race, not a work failure
                print(f"[ccsx-tpu] fleet: joined worker (pid {p.pid}) "
                      f"exited rc {prc} after the primary merged and "
                      "cleaned up; ignoring", file=sys.stderr)
                continue
            rc = exitcodes.RC_FATAL
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            if log is not None:
                try:
                    log.close()
                except OSError:
                    pass
    return rc


def serve_fleet_run(spool: str, n: int, serve_args: List[str],
                    max_restarts: int = 2,
                    backoff_s: float = 1.0,
                    gateway_port: int = 0,
                    env: Optional[dict] = None,
                    poll_s: float = 0.25,
                    drain_grace_s: float = 30.0,
                    runner_prelude: Optional[str] = None) -> int:
    """`ccsx-tpu shepherd --serve-replicas N ...serve flags...`: run N
    warm serve replicas over ONE job spool (the lease domain,
    pipeline/gateway.py), optionally fronted by the thin gateway.

    The spool itself is what makes this supervision loop simple: a
    replica death loses no jobs — its leases age out and the survivors
    re-acquire them — so the shepherd's only duties are capacity
    (relaunch dead replicas, with backoff, while the budget lasts) and
    lifecycle (SIGTERM here fans out as SIGTERM to every child, each
    drains rc 75 releasing its leases, queued jobs stay in the spool
    for the next start).

    * rc 0 / rc 75 from a replica is a clean exit / voluntary leave —
      not restarted (the operator or its own drain asked for it);
    * rc 2 (deterministic budget abort) is not restartable;
    * any other exit restarts with exponential backoff up to
      ``max_restarts``; an exhausted replica fails the run's rc (1)
      but the SURVIVORS keep serving until drained.
    * the gateway child (``gateway_port`` > 0) is stateless and gets
      the same restart budget; losing it degrades ingress only — the
      replicas keep draining the spool.
    """
    from ccsx_tpu.utils.drain import DrainGuard

    if n < 1:
        print("Error: --serve-replicas needs N >= 1", file=sys.stderr)
        return exitcodes.RC_FATAL
    base_env = dict(os.environ if env is None else env)
    prelude = (default_prelude() if runner_prelude is None
               else runner_prelude)
    try:
        os.makedirs(spool, exist_ok=True)
    except OSError as e:
        print(f"Error: cannot create spool {spool}: {e}",
              file=sys.stderr)
        return exitcodes.RC_FATAL

    def launch(w: _Rank) -> None:
        if w.rank < 0:    # the gateway child
            name = "gateway"
            cmd = [sys.executable, "-c", prelude + _RUNNER, "gateway",
                   "--spool", spool, "--port", str(gateway_port)]
        else:
            name = f"s{w.rank}"
            cmd = [sys.executable, "-c", prelude + _RUNNER, "serve",
                   *serve_args, "--replica-name", name]
        log_path = os.path.join(spool, f"{name}.log")
        banner = (f"\n=== serve-fleet launch {name} attempt "
                  f"{w.attempts} @ {time.strftime('%H:%M:%S')} ===\n")
        w.proc, w.log = _spawn_worker(cmd, dict(base_env), log_path,
                                      banner)
        w.relaunch_at = None
        print(f"[ccsx-tpu] serve-fleet: {name} up (pid {w.proc.pid}, "
              f"attempt {w.attempts}, log {log_path})", file=sys.stderr)

    def close_log(w: _Rank) -> None:
        if w.log is not None:
            try:
                w.log.close()
            except OSError:
                pass
            w.log = None

    replicas = [_Rank(rank=k) for k in range(n)]
    children = list(replicas)
    if gateway_port:
        children.append(_Rank(rank=-1))
    guard = DrainGuard.install()
    try:
        for w in children:
            launch(w)
        while not guard.requested:
            now = time.monotonic()
            if all(w.done for w in replicas):
                break
            for w in children:
                if w.done:
                    continue
                if w.proc is None:
                    if w.relaunch_at is not None and now >= w.relaunch_at:
                        launch(w)
                    continue
                rc = w.proc.poll()
                if rc is None:
                    continue
                name = "gateway" if w.rank < 0 else f"s{w.rank}"
                pid = w.proc.pid
                close_log(w)
                w.proc = None
                if rc not in (0, exitcodes.RC_INTERRUPTED):
                    _blackbox_hint(pid, spool)
                if rc in (0, exitcodes.RC_INTERRUPTED):
                    # clean exit or voluntary drain: the replica's
                    # leases are released, its queued work stays in
                    # the spool — the survivors absorb it
                    w.done = True
                    w.drained = rc == exitcodes.RC_INTERRUPTED
                    print(f"[ccsx-tpu] serve-fleet: {name} left "
                          f"(rc {rc}); spool jobs stay with the "
                          "survivors", file=sys.stderr)
                elif rc == exitcodes.RC_FAILED_HOLES:
                    w.done = True
                    w.failed = (f"{name} aborted on a deterministic "
                                f"budget (rc {rc}); not restartable")
                    w.failed_rc = rc
                    print(f"[ccsx-tpu] serve-fleet: {w.failed}",
                          file=sys.stderr)
                elif w.attempts >= max_restarts:
                    w.done = True
                    w.failed = (f"{name} died (rc {rc}) and exhausted "
                                f"its {max_restarts} restart(s)")
                    w.failed_rc = rc
                    print(f"[ccsx-tpu] serve-fleet: {w.failed}; "
                          "its leased jobs requeue by lease timeout",
                          file=sys.stderr)
                else:
                    w.attempts += 1
                    delay = backoff_s * (2 ** (w.attempts - 1))
                    w.relaunch_at = now + delay
                    print(f"[ccsx-tpu] serve-fleet: {name} died "
                          f"(rc {rc}); relaunching in {delay:g}s "
                          f"(attempt {w.attempts}/{max_restarts}; its "
                          "leased jobs requeue by lease timeout)",
                          file=sys.stderr)
            time.sleep(poll_s)
    finally:
        guard.restore()
        # fan the stop out as SIGTERM — every replica drains (finishes
        # in-flight holes, releases its leases, rc 75) before we give
        # up and SIGKILL stragglers
        live = [w for w in children
                if w.proc is not None and w.proc.poll() is None]
        for w in live:
            try:
                w.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + drain_grace_s
        for w in live:
            try:
                w.proc.wait(timeout=max(0.1, deadline
                                        - time.monotonic()))
            except (OSError, subprocess.TimeoutExpired):
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        for w in children:
            close_log(w)
    failed = [w for w in children if w.failed]
    if failed:
        print("Error: serve-fleet run failed: "
              + "; ".join(w.failed for w in failed)
              + " — the spool keeps every queued/leased job; restart "
              "the fleet to resume", file=sys.stderr)
        rcs = {w.failed_rc for w in failed}
        if rcs == {exitcodes.RC_FAILED_HOLES}:
            return exitcodes.RC_FAILED_HOLES
        return exitcodes.RC_FATAL
    return exitcodes.RC_OK


def _serve_fleet_main(argv) -> int:
    """The --serve-replicas spelling of the shepherd: everything that
    is not a supervisor knob forwards verbatim to each `serve` child
    (which is why this branches BEFORE the ordinary CLI parser — serve
    flags like --fleet/--port are not in its grammar)."""
    p = argparse.ArgumentParser(
        prog="ccsx-tpu shepherd --serve-replicas", add_help=False)
    p.add_argument("--serve-replicas", type=int, dest="n")
    p.add_argument("--gateway-port", type=int, default=0,
                   dest="gateway_port")
    p.add_argument("--max-replica-restarts", type=int, default=2,
                   dest="max_replica_restarts")
    p.add_argument("--replica-backoff", type=float, default=1.0,
                   dest="replica_backoff")
    args, serve_args = p.parse_known_args(argv)
    spool = None
    for i, a in enumerate(serve_args):
        if a == "--fleet" and i + 1 < len(serve_args):
            spool = serve_args[i + 1]
        elif a.startswith("--fleet="):
            spool = a.split("=", 1)[1]
    if not spool:
        print("Error: --serve-replicas requires --fleet SPOOL (the "
              "shared job spool every replica serves)", file=sys.stderr)
        return exitcodes.RC_FATAL
    return serve_fleet_run(
        spool, args.n, serve_args,
        max_restarts=args.max_replica_restarts,
        backoff_s=args.replica_backoff,
        gateway_port=args.gateway_port)


def shepherd_main(argv) -> int:
    """The `ccsx-tpu shepherd` subcommand (dispatched from cli.main):
    the ordinary CLI grammar plus the supervisor knobs; everything
    except the shepherd-only flags forwards verbatim to the ranks."""
    from ccsx_tpu import cli as cli_mod

    if any(a == "--serve-replicas" or a.startswith("--serve-replicas=")
           for a in argv):
        return _serve_fleet_main(argv)

    p = cli_mod.build_parser()
    p.prog = "ccsx-tpu shepherd"
    p.add_argument("--max-rank-restarts", type=int, default=2,
                   dest="max_rank_restarts", metavar="N",
                   help="restarts allowed per rank before the run "
                        "fails [2]")
    p.add_argument("--rank-backoff", type=float, default=1.0,
                   dest="rank_backoff", metavar="SEC",
                   help="restart backoff base (doubles per attempt) "
                        "[1.0]")
    p.add_argument("--rank-stall-timeout", type=float, default=0.0,
                   dest="rank_stall_timeout", metavar="SEC",
                   help="SIGKILL + restart a rank whose progress "
                        "heartbeat (shard journal/output mtimes) goes "
                        "stale this long; 0 disables — size it above "
                        "your worst cold compile, or prefer the "
                        "rank-level --dispatch-deadline [0]")
    p.add_argument("--fleet-ranges", type=int, default=0,
                   dest="fleet_ranges", metavar="M",
                   help="elastic fleet mode: split the input into M "
                        "leased work-ranges (M >> --hosts) pulled by "
                        "the ranks; a dead rank's ranges requeue to "
                        "the survivors.  0 = classic static "
                        "shard-per-rank supervision [0]")
    p.add_argument("--lease-timeout", type=float, default=10.0,
                   dest="lease_timeout", metavar="SEC",
                   help="fleet mode: expire (SIGKILL + requeue) a "
                        "leased range whose heartbeat goes stale this "
                        "long [10]")
    p.add_argument("--join", default=None, dest="join", metavar="DIR",
                   help="join a RUNNING fleet: launch --hosts extra "
                        "pull workers against DIR (<out>.fleet); the "
                        "primary shepherd keeps owning expiry and the "
                        "merge")
    args = p.parse_args(argv)
    if args.help:
        return cli_mod.usage()
    if args.hosts is None or args.hosts < 1:
        print("Error: shepherd requires --hosts N (>= 1)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.join:
        # the joiner's workers take their whole argv from fleet.json,
        # so nothing else on this command line applies
        return fleet_join(args.join, args.hosts)
    if args.host_id is not None:
        print("Error: shepherd owns --host-id; do not pass it",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.merge_shards is not None or args.make_index:
        print("Error: shepherd cannot combine with --merge-shards/"
              "--make-index", file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.bam_out:
        print("Error: --bam is not supported with --hosts "
              "(use --fastq and convert the merged output)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.batch == "off":
        # refused up front: each rank would refuse it anyway, and the
        # shepherd would burn its restart budget on a config error
        print("Error: --batch off is not supported with --hosts",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    if args.input == "-" or args.output == "-":
        print("Error: shepherd needs real INPUT/OUTPUT paths (ranks "
              "re-read the input; shards merge into the output)",
              file=sys.stderr)
        return exitcodes.RC_FATAL
    # validate the shared config once up front (same errors the ranks
    # would produce N times over)
    try:
        cfg = cli_mod.config_from_args(args)
    except SystemExit as e:
        return int(e.code or 0)
    forward = strip_shepherd_flags(list(argv))
    if args.fleet_ranges:
        return fleet_run(
            args.input, args.output, cfg, args.hosts, forward,
            ranges=args.fleet_ranges,
            lease_timeout=args.lease_timeout,
            max_restarts=args.max_rank_restarts,
            backoff_s=args.rank_backoff,
            telemetry_port=args.telemetry_port or 0)
    return shepherd_run(
        args.input, args.output, args.hosts, forward,
        journal=args.journal,
        max_restarts=args.max_rank_restarts,
        backoff_s=args.rank_backoff,
        rank_stall_timeout=args.rank_stall_timeout,
        telemetry_port=args.telemetry_port or 0)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(shepherd_main(sys.argv[1:]))
