"""Device-side breakpoint scan + cursor advance (the reference's MSA
backward scan, main.c:580-612, and per-pass cursor bump, main.c:622-638).

The host NumPy implementation (consensus/windowed.find_breakpoint and
_advance) is the SPEC — this module is its jit-compiled equivalent so the
batched pipeline can keep the whole post-vote analysis on-device and
return two small arrays (bp scalar + (P,) advance) instead of shipping
the (Z, P, T) match/aligned/ins_cnt tensors to the host every round
(SURVEY.md §7.1 L2 lists this reduction as a kernel target).
Differential-tested bit-equal against the spec in
tests/test_breakpoint_device.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_bp_advance_packed(tmax: int, num_segments: int, bp_window: int,
                           bp_minwin: int, bp_rowrate: int,
                           bp_colrate: int, bp_colrate_lowpass: int):
    """Segment-id breakpoint + advance for the ragged pass-packed slabs
    (pipeline/pack.py): rows of MANY holes share one slab, ``seg`` maps
    row -> hole slot.

    Inputs: match (R, tmax) bool, cons (H, tmax) uint8, aligned (R,
    tmax) uint8, ins_cnt (R, tmax) int32, lead_ins (R,) int32, row_mask
    (R,) bool, seg (R,) int32 sorted, tlen (H,) int32.

    Returns (bp (H,), advance (R,)): bp as in make_bp_advance, per hole
    slot; advance per ROW — each row's query bases consumed by columns
    [0, bp_eff(seg[row])), which the executor scatters back into the
    request's (P,) pass order (masked pass rows consumed nothing, so
    their 0 matches the fixed-P path's computed 0).

    Bit-parity with make_bp_advance per hole: every per-hole count is a
    masked integer segment sum over exactly the hole's real rows, the
    per-row window sums are unchanged, and the all-rows gate becomes
    "zero masked violations in the segment" — the same predicate the
    fixed-P version evaluates with padding rows forced to pass.  An
    empty hole slot has cons == GAP everywhere (segment vote), so
    isbase is all-False and bp = -1.
    """
    W = bp_window
    H = num_segments

    def f(match, cons, aligned, ins_cnt, lead_ins, row_mask, seg, tlen):
        tlen = jnp.asarray(tlen, jnp.int32)
        col = jnp.arange(tmax, dtype=jnp.int32)

        def ssum(x):
            return jax.ops.segment_sum(x.astype(jnp.int32), seg,
                                       num_segments=H,
                                       indices_are_sorted=True)

        incols = col[None, :] < tlen[:, None]                 # (H, tmax)
        nseq = ssum(row_mask)                                 # (H,)
        isbase = (cons < 4) & incols
        matchcnt = ssum(match)                                # (H, tmax)
        colrate = jnp.where(nseq >= 10, bp_colrate, bp_colrate_lowpass)
        colok = matchcnt * 100 >= colrate[:, None] * nseq[:, None]
        badbase = isbase & ~colok

        def wsum(x):
            c = jnp.cumsum(x.astype(jnp.int32), axis=-1)
            pad = jnp.zeros(x.shape[:-1] + (1,), jnp.int32)
            c = jnp.concatenate([pad, c], axis=-1)
            return c[..., W:] - c[..., :-W]

        nog = wsum(isbase)                                    # (H, ...)
        bad = wsum(badbase)
        rowin = wsum(match & isbase[seg])                     # (R, ...)
        # every real row of the hole must match in >= rowrate% of the
        # window's base columns: count masked violations per segment
        viol = (rowin * 100 < bp_rowrate * nog[seg]) & row_mask[:, None]
        rows_ok = ssum(viol) == 0
        idx = jnp.arange(tmax - W + 1, dtype=jnp.int32)
        valid = (bad == 0) & (nog >= bp_minwin) \
            & isbase[:, : tmax - W + 1] & rows_ok
        valid &= (idx[None, :] >= 1) & (idx[None, :] <= (tlen - W)[:, None])
        bp = jnp.where(valid, idx[None, :], -1).max(axis=1)

        bp_eff = jnp.where(bp >= 1, bp, jnp.maximum(tlen - W, 1))
        ccols = col[None, :] < bp_eff[seg][:, None]           # (R, tmax)
        nongap = ((aligned < 4) & ccols).sum(1)
        ins = (ins_cnt * ccols).sum(1)
        advance = (nongap + ins).astype(jnp.int32) + lead_ins
        return bp.astype(jnp.int32), advance

    return f


def make_bp_advance(tmax: int, bp_window: int, bp_minwin: int,
                    bp_rowrate: int, bp_colrate: int,
                    bp_colrate_lowpass: int):
    """Single-hole (vmap over Z) breakpoint + advance.

    Inputs: match (P, tmax) bool, cons (tmax,) uint8, aligned (P, tmax)
    uint8, ins_cnt (P, tmax) int32, lead_ins (P,) int32, row_mask (P,)
    bool, tlen scalar int32.

    Returns (bp, advance): bp int32 — the highest valid breakpoint
    column in [1, tlen - bp_window], or -1 when none exists (the spec's
    None); advance (P,) int32 — query bases consumed by columns
    [0, bp_eff) where bp_eff = bp if bp >= 1 else max(tlen - W, 1), the
    forced-flush column the windowed driver would use.
    """
    W = bp_window

    def f(match, cons, aligned, ins_cnt, lead_ins, row_mask, tlen):
        tlen = jnp.asarray(tlen, jnp.int32)
        col = jnp.arange(tmax, dtype=jnp.int32)
        incols = col < tlen
        nseq = row_mask.sum().astype(jnp.int32)
        # spec slices [:nseq, :tlen]; here padding rows are already False
        # in match (the voter masks them) and isbase masks the columns
        isbase = (cons < 4) & incols
        matchcnt = match.sum(0).astype(jnp.int32)
        colrate = jnp.where(nseq >= 10, bp_colrate, bp_colrate_lowpass)
        colok = matchcnt * 100 >= colrate * nseq
        badbase = isbase & ~colok

        def wsum(x):
            c = jnp.cumsum(x.astype(jnp.int32), axis=-1)
            pad = jnp.zeros(x.shape[:-1] + (1,), jnp.int32)
            c = jnp.concatenate([pad, c], axis=-1)
            return c[..., W:] - c[..., :-W]       # (… , tmax - W + 1)

        nog = wsum(isbase)
        bad = wsum(badbase)
        rowin = wsum(match & isbase[None, :])
        idx = jnp.arange(tmax - W + 1, dtype=jnp.int32)
        valid = (bad == 0) & (nog >= bp_minwin) & isbase[: tmax - W + 1]
        # every REAL row must match in >= rowrate% of the window's base
        # columns (spec: .all over match[:nseq]); padding rows pass
        rows_ok = ((rowin * 100 >= bp_rowrate * nog[None, :])
                   | ~row_mask[:, None]).all(0)
        valid &= rows_ok
        # spec candidates: i in [1, tlen - W] (it scans valid[1:] of the
        # [:tlen] slice); tlen < W + 1 leaves no candidate -> -1
        valid &= (idx >= 1) & (idx <= tlen - W)
        bp = jnp.where(valid, idx, -1).max()

        bp_eff = jnp.where(bp >= 1, bp, jnp.maximum(tlen - W, 1))
        ccols = col < bp_eff
        nongap = ((aligned < 4) & ccols[None, :]).sum(1)
        ins = (ins_cnt * ccols[None, :]).sum(1)
        advance = (nongap + ins).astype(jnp.int32) + lead_ins
        return bp.astype(jnp.int32), advance

    return f
