"""Host-facing aligner: seeds on the host, fills on the device.

Bridges the irregular, per-pair host logic (ccs_prepare's strand_match calls,
main.c:255-290) and the static-shape device DP: k-mer diagonal seeding
(ops/seed.py) produces the nominal-line hint, sequences are padded to
quantized shapes so XLA compilations are reused, and the acceptance rule is
the reference's (main.c:280).

This is the scalar (one pair per dispatch) path used by the per-hole
pipeline and sync callers.  Measured 2026-07-29 (benchmarks/prep_share.py):
one-pair-per-dispatch prep would be ~95% of wall time at device-round
speed, so the batched pipeline routes these same pair alignments through
pipeline/batch.PairExecutor instead — PairRequests from many holes'
prepare generators are stacked into padded-bucket batched local fills
(measured 4.5x faster on v5e at 64 pairs, bit-identical accept/clip
results).  This class remains the spec the executor must agree with.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from ccsx_tpu.config import AlignParams
from ccsx_tpu.consensus.star import bucket_len, pad_to
from ccsx_tpu.ops import banded, seed


@dataclasses.dataclass
class MatchResult:
    ok: bool
    score: int
    qb: int
    qe: int
    tb: int
    te: int
    aln: int
    mat: int


class HostAligner:
    """strand_match with the reference's acceptance rule (main.c:280):
    accept iff aln*2 > min(qlen, tlen) and mat*100 >= aln*similarity_pct."""

    def __init__(self, params: AlignParams = AlignParams(), quant: int = 512):
        self.params = params
        self.quant = quant

    def _run(self, q: np.ndarray, t: np.ndarray,
             line: Optional[np.ndarray]) -> banded.BandedResult:
        qp = pad_to(q, bucket_len(len(q), self.quant))
        tp = pad_to(t, bucket_len(len(t), self.quant))
        return banded.banded_align(
            qp, np.int32(len(q)), tp, np.int32(len(t)),
            mode="local", params=self.params,
            line=None if line is None else np.asarray(line, np.int32),
        )

    def strand_match(self, q: np.ndarray, t: np.ndarray,
                     similarity_pct: int) -> Tuple[bool, MatchResult]:
        hit = seed.seed_diagonal(q, t)
        if hit is None:
            # no shared 13-mers at all: unalignable at >=60% identity
            return False, MatchResult(False, 0, 0, 0, 0, 0, 0, 0)
        # near-diagonal pairs don't need the hint; off-diagonal ones do
        line = hit.line if abs(hit.diag) > self.params.band // 4 else None
        res = self._run(q, t, line)
        rs = MatchResult(
            ok=False, score=int(res.score), qb=int(res.qb), qe=int(res.qe),
            tb=int(res.tb), te=int(res.te), aln=int(res.aln), mat=int(res.mat),
        )
        rs.ok = (rs.aln * 2 > min(len(q), len(t))) and (
            rs.mat * 100 >= rs.aln * similarity_pct)
        return rs.ok, rs
