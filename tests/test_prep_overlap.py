"""Overlapped prep plane (pipeline/prep_pool.py) + adaptive admission
window + batched seeding (ISSUE 8).

Load-bearing guarantees pinned here:

* Output bytes are IDENTICAL with the prep pool on or off, and across
  every --prep-threads setting (prep is per-hole deterministic and the
  pair/refine executors are batch-composition-invariant).
* The adaptive admission window (reference chunk growth, main.c:686-691
  scaled to --inflight as cap) changes scheduling only — bytes match an
  explicitly pinned window.
* A prep-thread exception quarantines exactly that hole (ordered output
  intact), and a kill-and-resume with --journal works identically with
  prep threads on.
* Batched seeding (ops/seed.batch_sorted_indexes + the per-template
  token cache) reproduces per-pair seed_diagonal exactly.

One module-scoped corpus + one reference run keep the file cheap in
tier-1: every variant must reproduce those exact bytes.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import AlignParams, CcsConfig
from ccsx_tpu.consensus import prepare as prep_mod
from ccsx_tpu.io import fastx
from ccsx_tpu.ops import seed
from ccsx_tpu.pipeline.batch import PairExecutor, _grow_window
from ccsx_tpu.pipeline.prep_pool import resolve_prep_threads
from ccsx_tpu.utils import faultinject, synth

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(input fasta, reference output): 6 holes, one length bucket,
    with adapter read-throughs so the orientation walk actually yields
    pair alignments (the prep plane's whole reason to exist)."""
    tmp = tmp_path_factory.mktemp("prep")
    rng = np.random.default_rng(7)
    zs = []
    for h in range(6):
        z = synth.make_zmw(rng, 600, 5 + (h % 3), movie="mv",
                           hole=str(100 + h), partial_ends=True)
        if h % 3 == 0:
            # longer-than-group pass: the walk must strand_match it
            z.passes.insert(len(z.passes) // 2,
                            synth.read_through(rng, z.template))
            z.strands.insert(len(z.strands) // 2, 0)
        zs.append(z)
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    # reference run: defaults — adaptive window + auto prep threads
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    assert len(_records(ref)) == 6
    return fa, ref


def _records(path):
    lines = path.read_text().splitlines(keepends=True)
    return ["".join(lines[i:i + 2]) for i in range(0, len(lines), 2)]


def _run(fa, out, extra, metrics_path=None):
    args = ["-A", "-m", "1000", "--batch", "on", *extra]
    if metrics_path:
        args += ["--metrics", str(metrics_path)]
    assert cli.main([*args, str(fa), str(out)]) == 0
    if metrics_path:
        return [json.loads(line) for line in open(metrics_path)][-1]
    return None


# ---------- byte identity: pool on/off, thread counts, window modes --------

@pytest.mark.slow  # ~35s: 3-arm width A/B; kill-and-resume with a live
# pool keeps the prep plane's tier-1 byte pin (r13 budget audit)
def test_pool_on_off_byte_identical(corpus, tmp_path):
    """THE acceptance invariant: inline prep (--prep-threads 0) and any
    pool width produce the reference bytes, and the inline run's
    prep-plane counters read unoverlapped (blocked == worked)."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    m = _run(fa, out, ["--prep-threads", "0"], tmp_path / "m0.jsonl")
    assert out.read_bytes() == ref.read_bytes()
    assert m["prep_threads"] == 0
    # inline prep is all critical path (the two nested timers differ by
    # ~context-manager overhead, so "no overlap" reads as ~0, not 0.0)
    assert m["prep_overlap_share"] <= 0.005
    assert m["prep_blocked_s"] == pytest.approx(m["prep_s"], rel=1e-2)

    m = _run(fa, out, ["--prep-threads", "3"], tmp_path / "m3.jsonl")
    assert out.read_bytes() == ref.read_bytes()
    assert m["prep_threads"] == 3
    # the pool never blocks the driver for more than it worked
    assert m["prep_blocked_s"] <= m["prep_s"] + 1e-6

    _run(fa, out, ["--prep-threads", "1"])
    assert out.read_bytes() == ref.read_bytes()


@pytest.mark.slow  # ~20s: admission-window A/B; pool identity stays tier-1 (r11 audit)
def test_adaptive_vs_pinned_window_identical(corpus, tmp_path):
    """An explicit --inflight pins the old fixed window; bytes match
    the adaptive default exactly (scheduling-only change)."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    _run(fa, out, ["--inflight", "64", "--prep-threads", "0"])
    assert out.read_bytes() == ref.read_bytes()
    _run(fa, out, ["--inflight", "2"])
    assert out.read_bytes() == ref.read_bytes()
    # --inflight 0 keeps its historical "use the default" meaning
    # (adaptive), never a pinned 1-hole window
    _run(fa, out, ["--inflight", "0"])
    assert out.read_bytes() == ref.read_bytes()


def test_window_growth_schedule():
    """The reference's chunk policy scaled to the cap: 1024 -> x4 ->
    16384 becomes cap/16 -> x4 -> cap (main.c:686-691 semantics)."""
    w, cap, seen = max(1, 64 // 16), 64, []
    while True:
        seen.append(w)
        if w >= cap:
            break
        w = _grow_window(w, cap, 4)
    assert seen == [4, 16, 64]
    # reference numbers, for the avoidance of doubt
    assert _grow_window(1024, 16384, 4) == 4096
    assert _grow_window(4096, 16384, 4) == 16384
    assert _grow_window(16384, 16384, 4) == 16384


def test_resolve_prep_threads():
    assert resolve_prep_threads(CcsConfig(prep_threads=0)) == 0
    assert resolve_prep_threads(CcsConfig(prep_threads=7)) == 7
    auto = resolve_prep_threads(CcsConfig())
    assert 1 <= auto <= 4


# ---------- fault tolerance through the pool -------------------------------

@pytest.mark.slow  # ~15s: pool-thread fault A/B; the pool blast-radius
# twin below (test_pair_gate_host_replay_failure_quarantines) and the
# inline-path quarantine pins in test_faults.py stay tier-1 (r20
# budget audit)
def test_prep_fault_quarantines_one_hole(corpus, tmp_path):
    """An injected prep-point failure on a pool thread quarantines
    exactly that hole; the remaining output is the reference minus one
    record, still in input order.  (Which hole eats call #2 of the
    compute point depends on thread scheduling — the inline path pins
    that, the pool pins the blast radius.)"""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    faultinject.arm("compute@2")
    m = _run(fa, out, ["--prep-threads", "2"], tmp_path / "m.jsonl")
    assert m["holes_failed"] == 1
    got, want = _records(out), _records(ref)
    assert len(got) == len(want) - 1
    # ordered subsequence: one record dropped, nothing reordered
    it = iter(want)
    assert all(any(r == w for w in it) for r in got)


def test_pair_gate_host_replay_failure_quarantines(corpus, tmp_path,
                                                   monkeypatch):
    """A pair result that is an Exception (the executor's last-resort
    host replay failed) quarantines the calling hole, not the run —
    the pool's twin of the inline _feed_hole contract."""
    fa, ref = corpus
    calls = {"n": 0}
    orig = PairExecutor.run

    def flaky(self, pairs):
        calls["n"] += 1
        if calls["n"] == 1:
            return [RuntimeError("injected pair replay failure")
                    for _ in pairs]
        return orig(self, pairs)

    monkeypatch.setattr(PairExecutor, "run", flaky)
    out = tmp_path / "o.fa"
    m = _run(fa, out, ["--prep-threads", "2"], tmp_path / "m.jsonl")
    assert m["holes_failed"] >= 1
    assert len(_records(out)) == len(_records(ref)) - m["holes_failed"]


def _run_cli_subprocess(args, env_extra):
    runner = ("import sys, jax; jax.config.update('jax_platforms', 'cpu'); "
              "from ccsx_tpu.cli import main; sys.exit(main(sys.argv[1:]))")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CCSX_SKIP_PROBE="1",
               XLA_FLAGS="", **env_extra)
    return subprocess.run([sys.executable, "-c", runner, *args], env=env,
                          cwd=_REPO, capture_output=True, text=True,
                          timeout=300)


@pytest.mark.slow  # ~20s subprocess kill+resume A/B (r15 budget
# audit); tier-1 keeps the kill/resume pins in test_faults.py and the
# serve drain/restart resume in test_serve.py
def test_kill_and_resume_with_prep_threads(corpus, tmp_path):
    """Kill-and-resume with the pool ON: the write-fault hard kill
    leaves a torn tail, and a --journal resume (prep threads still on)
    finishes byte-identical to the uninterrupted reference — the
    flush-before-cursor invariant lives in the driver/writer path the
    pool never touches."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    args = ["-A", "-m", "1000", "--batch", "on", "--prep-threads", "2",
            "--journal", str(jp), str(fa), str(out)]
    r = _run_cli_subprocess(args, {"CCSX_FAULTS": "write@2",
                                   "CCSX_JOURNAL_FSYNC_S": "0"})
    assert r.returncode == faultinject.EXIT_CODE, (r.stdout, r.stderr)
    j = json.loads(jp.read_text())
    assert j["holes_done"] == 1
    assert os.path.getsize(out) > j["out_bytes"]  # the torn tail

    assert cli.main(args) == 0  # resume, pool on, no faults
    assert out.read_bytes() == ref.read_bytes()
    assert json.loads(jp.read_text())["holes_done"] == 6


@pytest.mark.slow  # ~17s: resume-budget livelock guard (r11 duration audit)
def test_resumed_stretch_does_not_stall_pool(corpus, tmp_path):
    """A resume whose already-done stretch exceeds the 4x-inflight
    ingest budget must keep retiring resumed holes while the driver
    waits for real work — the budget is released at EMISSION, and a
    done-hole stretch longer than the bound once live-locked the
    accumulate loop (workers starved of budget, driver polling an
    empty queue forever)."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    args = ["-A", "-m", "1000", "--batch", "on", "--inflight", "1",
            "--prep-threads", "2", "--journal", str(jp),
            str(fa), str(out)]
    assert cli.main(args) == 0
    assert out.read_bytes() == ref.read_bytes()
    # journal-complete resume: all 6 holes arrive resumed-done through
    # a budget of only 4 — must terminate and leave the bytes alone
    assert cli.main(args) == 0
    assert out.read_bytes() == ref.read_bytes()
    assert json.loads(jp.read_text())["holes_done"] == 6


# ---------- overlap evidence (trace) ---------------------------------------

def test_prep_spans_ride_pool_threads(corpus, tmp_path):
    """The flight recorder shows prep where it now runs: prep_hole
    spans on the pool's worker threads, pair sweeps on the pair-gate
    pump — off the MainThread, which is what lets them overlap the
    driver's device sweeps."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    tr = tmp_path / "t.jsonl"
    _run(fa, out, ["--prep-threads", "2", "--trace", str(tr)])
    assert out.read_bytes() == ref.read_bytes()
    spans = [json.loads(line) for line in open(tr)
             if '"ev": "span"' in line]
    prep_tids = {s["tid"] for s in spans if s["name"] == "prep_hole"}
    assert prep_tids and all(t.startswith("ccsx-prep") for t in prep_tids)
    pair_tids = {s["tid"] for s in spans if s["name"] == "pair_sweep"}
    assert pair_tids == {"ccsx-prep-pairs"}
    # device dispatches stay on the driver thread
    dev = [s for s in spans if s["cat"] == "device"
           and s["name"] in ("refine_packed", "refine", "round")]
    assert dev and all(s["tid"] == "MainThread" for s in dev)


# ---------- batched seeding ------------------------------------------------

def test_seed_batch_matches_per_pair(rng):
    """batch_sorted_indexes + t_index-fed seed_diagonal reproduce the
    plain per-pair seeding exactly, incl. N-containing sequences and
    seedless pairs."""
    pairs = []
    for i in range(40):
        t = rng.integers(0, 5, int(rng.integers(30, 500))).astype(np.uint8)
        if i % 3:
            s = int(rng.integers(0, max(len(t) - 20, 1)))
            q = t[s:s + int(rng.integers(15, len(t) - s + 1))].copy()
            mut = rng.random(len(q)) < 0.04
            q[mut] = rng.integers(0, 4, mut.sum())
        else:
            q = rng.integers(0, 5, int(rng.integers(20, 300))).astype(
                np.uint8)
        pairs.append((q, t))
    indexes = seed.batch_sorted_indexes([t for _, t in pairs])
    for (q, t), ti in zip(pairs, indexes):
        a = seed.seed_diagonal(q, t)
        b = seed.seed_diagonal(q, t, t_index=ti)
        assert (a is None) == (b is None)
        if a is not None:
            assert a.diag == b.diag and a.votes == b.votes
            assert (a.line == b.line).all()


def test_seed_token_cache_reuse(rng):
    """PairExecutor's token-keyed sort cache: the second batch carrying
    the same template token reuses the cached index (no re-sort) and
    returns identical results to an uncached executor."""
    t = rng.integers(0, 4, 800).astype(np.uint8)
    tok = object()
    reqs = []
    for _ in range(4):
        s = int(rng.integers(0, 300))
        q = t[s:s + 400].copy()
        mut = rng.random(len(q)) < 0.03
        q[mut] = rng.integers(0, 4, mut.sum())
        reqs.append(prep_mod.PairRequest(q, t, 75, t_token=tok))
    pe = PairExecutor(AlignParams())
    r1 = pe.run(reqs[:2])
    assert tok in pe._seed_cache
    cached = pe._seed_cache[tok]
    r2 = pe.run(reqs[2:])
    assert pe._seed_cache[tok] is cached  # reused, not re-sorted
    fresh = PairExecutor(AlignParams()).run(reqs[2:])
    for (ok_a, a), (ok_b, b) in zip(r2, fresh):
        assert ok_a == ok_b and a.qb == b.qb and a.qe == b.qe \
            and a.score == b.score


def test_seed_cache_bounded(rng):
    pe = PairExecutor(AlignParams())
    pe.seed_cache_max = 8
    for i in range(20):
        t = rng.integers(0, 4, 100).astype(np.uint8)
        q = t[:60].copy()
        pe.run([prep_mod.PairRequest(q, t, 75, t_token=object())])
    assert len(pe._seed_cache) <= 8
