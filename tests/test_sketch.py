"""Pre-alignment plane (ISSUE 11): the batched device sketch screen
(ops/sketch.py) and device k-mer seeding (ops/seed_device.py).

The two contracts pinned here:

* bit-exactness — the device screen reproduces screen_host exactly, and
  the device seeder reproduces seed_diagonal's SeedHit exactly (stable
  sort order, capped first-hits, argmax/median tie-breaks), across
  random AND adversarial (repeat-heavy, N-laden, unrelated) corpora;
* conservativeness — the filter-oracle sweep: every pair the prefilter
  rejects must FAIL strand_match acceptance when force-aligned (0 false
  rejects), so output bytes cannot depend on the filter firing (the
  walk discards a failed pair's payload).
"""

import hashlib

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import AlignParams, CcsConfig
from ccsx_tpu.consensus import prepare as prep_mod
from ccsx_tpu.consensus.align_host import HostAligner
from ccsx_tpu.consensus.star import bucket_len, pad_to
from ccsx_tpu.ops import banded
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.ops import seed as seed_mod
from ccsx_tpu.ops import seed_device, sketch
from ccsx_tpu.pipeline.batch import PairExecutor
from ccsx_tpu.utils import faultinject, synth
from ccsx_tpu.utils.metrics import Metrics

ERR = dict(sub_rate=0.02, ins_rate=0.05, del_rate=0.05)


def _adversarial_pair(rng, kind: int, lo=2000, hi=9000):
    """One (q, t) pair from the fuzz corpus: 0 related, 1 repeat-heavy,
    2 N-laden, 3 unrelated, 4 wrong-strand related."""
    L = int(rng.integers(lo, hi))
    t = rng.integers(0, 4, L).astype(np.uint8)
    if kind == 1:
        unit = rng.integers(0, 4, int(rng.integers(7, 61))).astype(np.uint8)
        t = np.tile(unit, L // len(unit) + 1)[:L].copy()
    if kind == 2:
        t[rng.random(L) < 0.05] = 4
    if kind == 3:
        q = rng.integers(0, 4, int(rng.integers(lo, hi))).astype(np.uint8)
    elif kind == 4:
        q = enc.revcomp_codes(synth.mutate(rng, t, **ERR))
    else:
        q = synth.mutate(rng, t, **ERR)
    if kind == 2:
        q = q.copy()
        q[rng.random(len(q)) < 0.05] = 4
    return q, t


def _device_rows(q, t, quant=512):
    """(screen_row, seed_row) for one pair through the real jitted
    steps, padded exactly as PairExecutor pads."""
    qmax, tmax = bucket_len(len(q), quant), bucket_len(len(t), quant)
    big = np.full((1, qmax + tmax), banded.PAD, np.uint8)
    big[0, :qmax] = pad_to(q, qmax)
    big[0, qmax:] = pad_to(t, tmax)
    small = np.array([[len(q), len(t)]], np.int32)
    srow = np.asarray(sketch.screen_step(qmax, tmax)(big, small))[0]
    drow = np.asarray(seed_device.seed_step(qmax, tmax)(big, small))[0]
    return srow, drow


def test_screen_and_seed_device_match_host(rng):
    """Differential fuzz: device screen == screen_host and device seed
    == seed_diagonal, bit-for-bit, across the adversarial corpus.
    Shapes stay in one (qmax, tmax) family per kind so the jit cache
    amortizes."""
    for trial in range(15):
        q, t = _adversarial_pair(rng, trial % 5, lo=2048, hi=4000)
        srow, drow = _device_rows(q, t)
        assert tuple(int(v) for v in srow) == sketch.screen_host(q, t)
        hit = seed_mod.seed_diagonal(q, t)
        dhit = seed_device.hit_from_row(drow)
        if hit is None:
            assert dhit is None
        else:
            assert dhit is not None
            assert dhit.diag == hit.diag and dhit.votes == hit.votes
            assert (np.asarray(dhit.line)
                    == np.asarray(hit.line)).all()


@pytest.mark.slow  # ~9s boundary A/B; screen_and_seed_device_match_host
# pins the device/host routing parity tier-1 (r16 budget audit)
def test_seed_device_crossover_boundary(rng):
    """PairExecutor routing at the --seed-device-min-t boundary:
    templates one below / at / above the crossover produce identical
    (ok, clip, score) results whichever side seeds them, and the
    seeding-split counters account every pair exactly once."""
    min_t = 2560
    pairs = []
    for tl in (min_t - 1, min_t, min_t + 1):
        t = rng.integers(0, 4, tl).astype(np.uint8)
        pairs.append(prep_mod.PairRequest(synth.mutate(rng, t, **ERR),
                                          t, 75))
    m = Metrics()
    pe = PairExecutor(AlignParams(), metrics=m, prefilter=True,
                      seed_device_min_t=min_t)
    got = pe.run(pairs)
    ha = HostAligner(AlignParams())
    for pr, (ok, rs) in zip(pairs, got):
        ok_w, w = ha.strand_match(pr.q, pr.t, pr.pct)
        assert ok == ok_w
        if ok:
            assert (rs.qb, rs.qe, rs.score) == (w.qb, w.qe, w.score)
    assert m.pairs_seeded_device == 2 and m.pairs_seeded_host == 1
    assert m.pairs_screened == 3  # all above SCREEN_MIN_QT
    snap = m.snapshot()
    assert snap["prefilter_share"] is not None


def test_filter_oracle_no_false_rejects(rng):
    """The conservativeness oracle: every pair the prefilter's
    reject_reason fires on must fail strand_match acceptance when
    force-aligned through the spec aligner — 0 false rejects on the
    corpus.  (A false reject here would change output bytes; the rules'
    provable cases are argued in ops/sketch.py.)"""
    ha = HostAligner(AlignParams())
    band = AlignParams().band
    rejected = accepted_kept = 0
    for trial in range(20):
        q, t = _adversarial_pair(rng, trial % 5, lo=2048, hi=4000)
        total, votes, win_lo = sketch.screen_host(q, t)
        reason = sketch.reject_reason(total, votes, win_lo, len(q),
                                      len(t), 75, band)
        ok, _ = ha.strand_match(q, t, 75)
        if reason:
            rejected += 1
            assert not ok, (
                f"FALSE REJECT ({reason}): trial {trial} kind "
                f"{trial % 5} votes={votes} total={total}")
        elif ok:
            accepted_kept += 1
    # the corpus must actually exercise both sides of the filter
    assert rejected >= 5, f"oracle corpus too soft: {rejected} rejects"
    assert accepted_kept >= 5


def test_reject_reason_rules_unit():
    """Rule boundaries pinned: (a) seed-gate parity at any length, (b)
    the noise gate degenerating to (a) below SCREEN_MIN_QT, (c) the
    band-overlap bound firing only past band//4."""
    band = AlignParams().band
    # rule (a): votes < MIN_VOTES rejects even for tiny pairs
    assert sketch.reject_reason(10, 2, 0, 500, 500, 75, band) \
        == "seed_gate"
    assert sketch.reject_reason(0, 0, 0, 500, 500, 75, band) \
        == "seed_gate"
    # below the screen floor rule (b) cannot fire: votes=3 passes
    assert sketch.reject_reason(10, 3, 0, 1000, 1000, 75, band) == ""
    # above it, 3 votes on a 100k pair is noise
    assert sketch.reject_reason(10, 3, 0, 100000, 100000, 75, band) \
        == "noise_gate"
    # an acceptance-grade vote count sails through
    q = 100000
    assert sketch.reject_reason(q // 50, q // 50, 0, q, q, 75, band) == ""
    # rule (c): a far off-diagonal window with no reachable overlap
    assert sketch.reject_reason(200, 200, 90000, 100000, 100000, 75,
                                band) == "band_overlap"
    # same diag near the corner line threshold: kept
    assert sketch.reject_reason(200, 200, 0, 100000, 100000, 75,
                                band) == ""


def test_pair_batch_lazy_vs_speculative(rng):
    """The PairBatch first-accept contract from both evaluators: the
    lazy driver (drive_pairs semantics) stops at the first accept; the
    speculative executor evaluates every arm; the walk-visible
    precedence is identical."""
    tpl = rng.integers(0, 4, 4096).astype(np.uint8)
    fwd = synth.mutate(rng, tpl, **ERR)
    ha = HostAligner(AlignParams())

    # lazy: fwd accepts -> RC arm must be skipped (None)
    calls = []

    class CountingAligner:
        def strand_match(self, q, t, pct):
            calls.append(len(q))
            return ha.strand_match(q, t, pct)

    def gen():
        res = yield prep_mod.PairBatch(
            [prep_mod.PairRequest(fwd, tpl, 75),
             prep_mod.PairRequest(enc.revcomp_codes(fwd), tpl, 75)])
        assert res[0][0] is True
        assert res[1] is None  # first-accept: never evaluated
        return "done"

    assert prep_mod.drive_pairs(gen(), CountingAligner()) == "done"
    assert len(calls) == 1

    # speculative: both arms real, same precedence
    pe = PairExecutor(AlignParams(), prefilter=True,
                      seed_device_min_t=0)
    [res] = pe.run([prep_mod.PairBatch(
        [prep_mod.PairRequest(fwd, tpl, 75),
         prep_mod.PairRequest(enc.revcomp_codes(fwd), tpl, 75)])])
    assert res[0][0] is True and res[1][0] is False


def _spec_zmws(rng, n=2, tlen=2200):
    """Holes whose walk actually speculates: template >= SCREEN_MIN_QT
    and a read-through pass forcing alignment-verified strand for the
    following passes (the e2e_scale recipe)."""
    zs = []
    for h in range(n):
        z = synth.make_zmw(rng, template_len=tlen, n_passes=5,
                           movie="mv", hole=str(h), partial_ends=True,
                           **ERR)
        z.passes.insert(len(z.passes) // 2,
                        synth.read_through(rng, z.template, **ERR))
        z.strands.insert(len(z.strands) // 2, 0)
        zs.append(z)
    return zs


@pytest.mark.slow  # ~85s: 6-arm CLI A/B; the filter-oracle fuzz and
# counter checks stay tier-1, and the scale-config byte pin rides the
# committed fleet_r13 artifact (r13 budget audit)
def test_cli_byte_identity_prefilter_arms(tmp_path, rng):
    """Output bytes are invariant to the whole pre-alignment plane:
    prefilter on/off, device seeding off/at-crossover, the per-hole
    (--batch off) spec path, and inline (--prep-threads 0) vs the
    background prep pool all emit identical FASTA bytes on a config
    whose walk speculates and screens — and the on-arms' metrics carry
    the new screen/seeding counters."""
    import json

    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(_spec_zmws(rng)))
    sums = {}
    for name, extra in [
            # the full plane: screen on AND device seeding at the
            # crossover the config actually hits (pool prep = default)
            ("on", ["--prefilter", "on", "--seed-device-min-t", "2048"]),
            ("off", ["--prefilter", "off", "--seed-device-min-t", "0"]),
            ("inline", ["--prefilter", "on", "--seed-device-min-t",
                        "2048", "--prep-threads", "0"]),
            ("perhole", ["--prefilter", "on", "--batch", "off"])]:
        out = tmp_path / f"o_{name}.fa"
        mpath = tmp_path / f"m_{name}.jsonl"
        assert cli.main(["-A", "-m", "1000", "--batch", "on",
                         "--metrics", str(mpath), *extra,
                         str(fa), str(out)]) == 0, name
        sums[name] = hashlib.md5(out.read_bytes()).hexdigest()
        final = [json.loads(ln) for ln in open(mpath)][-1]
        if name in ("on", "inline"):
            # the plane actually fired: screens ran (pool or inline)
            # and the crossover routed long templates to the device
            assert final["pairs_screened"] > 0, name
            assert final["pairs_seeded_device"] > 0, name
        if name == "off":
            assert final["pairs_screened"] == 0
            assert final["pairs_seeded_device"] == 0
            assert final["pairs_seeded_host"] > 0
    assert len(set(sums.values())) == 1, sums


def test_injected_oom_on_sketch_wave_recovers(rng):
    """An injected device OOM whose first strike lands on a sketch
    screen wave must ride the recovery ladder (resplit down to the
    host screen rung) and still produce results identical to a clean
    run — the screen stays advisory under failure."""
    tpl = rng.integers(0, 4, 3000).astype(np.uint8)
    pairs = []
    for _ in range(4):
        pairs.append(prep_mod.PairRequest(synth.mutate(rng, tpl, **ERR),
                                          tpl, 75))
    pairs.append(prep_mod.PairRequest(
        enc.revcomp_codes(synth.mutate(rng, tpl, **ERR)), tpl, 75))
    clean = PairExecutor(AlignParams(), prefilter=True,
                         seed_device_min_t=0).run(list(pairs))
    m = Metrics()
    pe = PairExecutor(AlignParams(), metrics=m, prefilter=True,
                      seed_device_min_t=0)
    # drive the device-screen dispatch site at test shapes (the default
    # floor is SPECULATE_MIN_QT; the routing knob is what tests use to
    # land the FIRST device_oom strike on a sketch wave)
    pe.screen_min_device = 2048
    faultinject.arm("device_oom@1")
    try:
        got = pe.run(list(pairs))
    finally:
        faultinject.disarm()
    for (ok_a, a), (ok_b, b) in zip(clean, got):
        assert ok_a == ok_b
        assert (a.qb, a.qe, a.score, a.mat) == (b.qb, b.qe, b.score,
                                                b.mat)
    # the ladder actually ran: the OOM bisected the screen wave (or
    # bottomed out onto the host screen rung)
    assert m.oom_resplits + m.host_fallbacks >= 1
    assert m.pairs_prefiltered >= 1  # the wrong-strand pair still died


@pytest.mark.slow  # ~11s warm-routing A/B; serve's zero-recompile pin and
# screen_and_seed_device_match_host stay tier-1 (r16 budget audit)
def test_warm_covers_prefilter_shapes(rng):
    """PairExecutor.warm precompiles the pre-alignment executables
    alongside the pair fills (inline when no compiler is attached),
    predicting the ROUTING exactly: a device-seeded pair warms only
    the seed step (its seed rows carry the screen statistics — one
    dispatch does both jobs, so warming a screen shape for it would
    compile an executable run() never calls), while a screened
    host-seeded pair warms the screen step.  A warmed run returns
    identical results."""
    tpl = rng.integers(0, 4, 4096).astype(np.uint8)
    pairs = [prep_mod.PairRequest(synth.mutate(rng, tpl, **ERR), tpl, 75)
             for _ in range(3)]
    pe = PairExecutor(AlignParams(), prefilter=True,
                      seed_device_min_t=2048)
    pe.screen_min_device = 2048   # device screen floor at test shapes
    pe.warm(pairs)
    kinds = {k[0] for k in pe._warmed}
    # all pairs device-seed -> the unified path: no screen executable
    assert {"pair_fill", "seed_device"} <= kinds
    assert "sketch_screen" not in kinds
    cold = PairExecutor(AlignParams(), prefilter=True,
                        seed_device_min_t=2048).run(list(pairs))
    warmed = pe.run(list(pairs))
    for (ok_a, a), (ok_b, b) in zip(cold, warmed):
        assert ok_a == ok_b and a.score == b.score and a.qb == b.qb
    # device seeding off -> the same pairs screen instead, and warm
    # predicts that too
    pe2 = PairExecutor(AlignParams(), prefilter=True,
                       seed_device_min_t=0)
    pe2.screen_min_device = 2048
    pe2.warm(pairs)
    kinds2 = {k[0] for k in pe2._warmed}
    assert {"pair_fill", "sketch_screen"} <= kinds2
    assert "seed_device" not in kinds2


