"""Windowed ("shred") consensus — the reference's default path
(ccs_for2, main.c:510-647), the long-context strategy of this framework.

The reference bounds POA size by consensing ~2kb windows per pass and
re-synchronizing cursors at an agreement breakpoint (SURVEY.md §5.7).  We
keep exactly that structure — it is what makes the kernel shapes static:

  window loop (host):
    slice window_size bases from each pass at its cursor
    star-MSA rounds over the windows (anchor = template pass window)
    scan for a breakpoint: `bp_window` consecutive MSA columns where the
      consensus is a base, per-column agreement >= colrate% of passes,
      >= minwin base columns, and EVERY pass matches in >= rowrate% of them
      (main.c:580-612)
    emit consensus columns before the breakpoint; advance each cursor by
      the bases that pass consumed there (main.c:622-638)
    no breakpoint -> grow the window by window_add (main.c:550) up to
      max_window, then force a flush (delta vs the reference's unbounded
      growth; --window-growth grow restores reference behavior — measured
      equivalent either way, BASELINE.md: the draft-anchored star MSA
      always finds breakpoints, so growth never engages in practice)
    any pass nearly exhausted (pos + window + minlen >= len) or <3 passes
      -> final flush of all tails (main.c:555-564)
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import prepare as prep
from ccsx_tpu.consensus.star import (
    RoundResult, StarMsa, apply_hp_penalty, refine_rounds_gen, run_rounds,
)
from ccsx_tpu.ops import encode as enc


def _window_sums(x: np.ndarray, w: int) -> np.ndarray:
    """Sliding sums of width w along the last axis: out[..., i] = sum x[..., i:i+w]."""
    c = np.cumsum(x, axis=-1, dtype=np.int64)
    pad = np.zeros(x.shape[:-1] + (1,), dtype=np.int64)
    c = np.concatenate([pad, c], axis=-1)
    return c[..., w:] - c[..., :-w]


def find_breakpoint(rr: RoundResult, nseq: int, cfg: CcsConfig) -> Optional[int]:
    """Vectorized equivalent of the reference's backward scan
    (main.c:580-612) over template-anchored columns.  Returns the highest
    valid breakpoint column i >= 1, or None."""
    W = cfg.bp_window
    T = rr.tlen
    if T < W + 1:
        return None
    match = rr.match[:nseq, :T]  # real rows only — padding rows never match
    isbase = (rr.cons[:T] < 4)
    matchcnt = match.sum(0)
    colrate = cfg.bp_colrate if nseq >= 10 else cfg.bp_colrate_lowpass
    colok = matchcnt * 100 >= colrate * nseq
    badbase = isbase & ~colok

    nog = _window_sums(isbase.astype(np.int64), W)          # (T-W+1,)
    bad = _window_sums(badbase.astype(np.int64), W)
    rowin = _window_sums((match & isbase[None, :]).astype(np.int64), W)

    valid = (bad == 0) & (nog >= cfg.bp_minwin) & isbase[: T - W + 1]
    rows_ok = (rowin * 100 >= cfg.bp_rowrate * nog[None, :]).all(axis=0)
    valid &= rows_ok
    # candidates are i in [1, T-W] (the reference scans msa_size-W down to 1)
    cand = np.nonzero(valid[1:])[0]
    if len(cand) == 0:
        return None
    return int(cand[-1]) + 1


def _advance(rr: RoundResult, bp: int) -> np.ndarray:
    """Per-pass query bases consumed by columns [0, bp) — non-gap cells,
    all insertions at slots < bp, and the leading insertions before
    column 0 (main.c:622-638 bumps pos through every MSA cell)."""
    nongap = (rr.aligned[:, :bp] < 4).sum(axis=1)
    ins = rr.ins_cnt[:, :bp].sum(axis=1)
    return (nongap + ins + rr.lead_ins).astype(np.int64)


def windowed_gen(passes: List[np.ndarray], cfg: CcsConfig):
    """Generator form of consensus_windowed: yields one RefineRequest per
    window attempt, receives RefineResults, returns the consensus codes
    (or (codes, phred_quals) with cfg.emit_quality) via
    StopIteration.value."""
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    if len(passes) > cfg.max_passes:
        passes = passes[: cfg.max_passes]
    nseq = len(passes)
    pos = np.zeros(nseq, dtype=np.int64)
    lens = np.array([len(p) for p in passes], dtype=np.int64)
    out: List[np.ndarray] = []
    outq: List[np.ndarray] = []

    def emit(rr: RoundResult, upto=None, speculative=False):
        if not cfg.emit_quality:
            out.append(rr.materialize(upto=upto, speculative=speculative))
            return
        c, q = rr.materialize_with_qual(
            upto=upto, speculative=speculative,
            qv_coeffs=cfg.qv_coeffs, qmax=cfg.qv_cap)
        out.append(c)
        outq.append(q)

    flag = True
    while flag:
        window_size = cfg.window_init
        while True:
            fits = bool(
                ((pos + window_size + cfg.window_minlen) < lens).all())
            final = (not fits) or nseq < 3
            if final:
                windows = [p[int(pos[k]):] for k, p in enumerate(passes)]
            else:
                windows = [p[int(pos[k]):int(pos[k]) + window_size]
                           for k, p in enumerate(passes)]
            qs, qlens, row_mask = sm.pack(
                windows, cfg.pass_buckets, cfg.max_passes)
            # one RefineRequest per window attempt; non-final windows
            # consume only rr (materialize(upto=bp) + advance), the
            # final flush materializes the strict draft
            res = yield from refine_rounds_gen(
                qs, qlens, row_mask, windows[0], cfg.refine_iters)
            rr = res.rr

            if final:
                # the strict materialization of the final round — emit()
                # with speculative=False produces exactly `draft`
                emit(rr, speculative=False)
                flag = False
                break

            if rr.bp is not None:
                # device-computed scan (ops/breakpoint.py, batched path):
                # -1 encodes the spec's None
                bp = rr.bp if rr.bp >= 1 else None
            else:
                bp = find_breakpoint(rr, nseq, cfg)
            if cfg.verbose >= 3:
                # per-window breakpoint stats, -v level 3 (main.c:619-620)
                import sys

                print(f"[ccsx-tpu] window size={window_size} "
                      f"msa_cols={rr.tlen} breakpoint={bp}", file=sys.stderr)
            if bp is None and (
                    cfg.window_growth == "grow"
                    or window_size + cfg.window_add <= cfg.max_window):
                # no breakpoint: grow the window (main.c:550).  In "grow"
                # mode this is unbounded like the reference — the fits
                # check above flushes the tails once the window spans the
                # remaining pass lengths, exactly as main.c:555-564 does
                window_size += cfg.window_add
                continue
            if bp is None:
                # growth cap reached: force a flush point (delta vs the
                # reference's unbounded growth; disable via
                # window_growth="grow")
                bp = max(rr.tlen - cfg.bp_window, 1)
            emit(rr, upto=bp)
            if rr.advance is not None:
                # device advance was computed at this same bp_eff, and
                # arrives in THIS request's (P,) pass order whichever
                # executor ran (the pass-packed path scatters its
                # per-row advances back through row_mask; a masked row
                # consumed nothing, matching the fixed-P path's 0)
                pos += rr.advance[:nseq].astype(np.int64)
            else:
                pos += _advance(rr, bp)[:nseq]  # drop pass-bucket padding
            break

    codes = np.concatenate(out) if out else np.zeros(0, np.uint8)
    if not cfg.emit_quality:
        return codes
    quals = np.concatenate(outq) if outq else np.zeros(0, np.uint8)
    # hp penalty AFTER window assembly: a homopolymer run spanning a
    # window breakpoint must be penalized at its true length, not as
    # two split halves (star.apply_hp_penalty)
    return codes, apply_hp_penalty(codes, quals, cfg.qv_coeffs)


def consensus_windowed(passes: List[np.ndarray], cfg: CcsConfig):
    """Windowed consensus over oriented passes; passes[0] anchors.

    Returns consensus codes as an np.ndarray, or a (codes, quals)
    tuple when cfg.emit_quality is set (matching windowed_gen)."""
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    return run_rounds(windowed_gen(passes, cfg), sm)


def ccs_windowed(zmw, aligner, cfg: CcsConfig):
    """Full default path for one ZMW (ccs_for2): prepare -> orient ->
    windowed star consensus.  Returns (seq_bytes, qual_bytes|None) per
    encode.to_record — the same contract as hole.ccs_hole — or None."""
    passes = prep.oriented_passes(zmw, aligner, cfg)
    if passes is None:  # main.c:515
        return None
    return enc.to_record(consensus_windowed(passes, cfg))
