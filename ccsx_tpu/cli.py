"""ccsx-compatible CLI (reference: main.c:723-870).

Same flags and conventions as the reference's getopt loop
("hm:M:c:j:X:PAv", main.c:758): positional INPUT OUTPUT with '-'/stdin/
stdout, -A for FASTA/Q, -P for whole-read (primitive) mode, -X hole
exclusion, -c >= 3 enforced.  TPU-era extensions are long options.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from ccsx_tpu.config import CcsConfig


USAGE = """\
Program: ccsx-tpu
Version: 1.0.0
Usage  : ccsx-tpu  [options] <INPUT> <OUTPUT>
Generate circular consensus sequences (ccs) from subreads.

Options:
-h             Output this help
-v             debug
-m     <int>   Minimum total length of subreads in a hole to use for generating CCS. [5000]
-M     <int>   Maximum total length of subreads in a hole to use for generating CCS. [500000]
-c     <int>   Minimum number of subreads required to generate CCS. [3]
-A             For fasta/fastq input,gzip allowed
-P             primitive bsalign,subread shred by default
-X\t\t<str>   Exclude ZMWs from output file,a comma-separated list of ID
-j     <int>   Number of threads to use. [2]

Arguments:
input          Input file.
output         Output file.

TPU extensions (long options):
--device {auto,tpu,cpu}   --batch {auto,on,off}   --inflight <int>
--mesh D,P                --fastq                 --bam
--refine-iters <int>      --max-passes <int>      --window-growth {flush,grow}
--journal <path>          --metrics <path>        --profile <dir>
--trace <path>            (dispatch flight recorder: span JSONL +
                           Chrome/Perfetto trace export; device spans
                           close only after block_until_ready, and the
                           per-shape-group compile/execute table rides
                           every --metrics event)
--stall-timeout <sec>     (hang watchdog: a device dispatch open this
                           long dumps all thread stacks + the in-flight
                           shape group and marks the run degraded;
                           first-of-shape dispatches get 10x the budget
                           for cold compiles; 0 disables) [120]
--telemetry-port <port>   (live telemetry endpoints for the run: GET
                           /metrics Prometheus text, /healthz
                           ok|degraded incl. stall/fallback detail,
                           /progress JSON with the windowed-rate ETA;
                           auto-bumps when taken, per-rank offset under
                           --hosts; 0 = off) [0]
--dispatch-deadline <sec> (bounded-wait device dispatch: a call open
                           past the deadline is ABANDONED — thread
                           parked, result discarded — and its group
                           replays on the bit-exact host path; first
                           call of a shape gets 10x for cold compiles;
                           0 = off: a wedged dispatch stalls forever,
                           today's behavior) [0]
--breaker-strikes <int>   (backend circuit breaker: this many device
                           failures — hangs, OOM ladder-bottoms,
                           compile failures — within 60s trip the
                           breaker and remaining work runs on the host
                           path; 0 disables) [3]
--breaker-probe-s <sec>   (half-open re-probe interval for a tripped
                           breaker: one group is dispatched as a probe,
                           success closes the breaker; 0 = stay open
                           for the rest of the run) [0]
--max-failed-holes <v>    (failure-rate abort: an integer count >= 0
                           or a fraction in (0,1) of processed holes;
                           exceeding it exits rc 2 instead of emitting
                           a near-empty output at rc 0) [unbounded]
--salvage                 (hostile-input salvage: classified input
                           corruption — torn BGZF blocks, corrupt BAM
                           records, truncated FASTQ, bad ZMW names —
                           is booked, the reader RESYNCS, and every
                           undamaged hole still emits; the run exits 0
                           marked degraded, corrupt holes spend the
                           --max-failed-holes budget.  Off = today's
                           fail-fast rc 1 on the first corrupt byte)
--max-record-bytes <n>    (allocation bound on one BAM record: a
                           corrupt length field larger than this is
                           rejected BEFORE allocating) [268435456]
--hosts <int> --host-id <int> --coordinator <addr> --merge-shards <N>
--merge-unmarked          (merge a legacy shard set without .done markers)
--make-index              (index INPUT for byte-range sharded ingest)
--fleet-dir <dir>         (run as an elastic-fleet pull worker against
                           <out>.fleet: acquire a leased work-range,
                           stream it, retire it with a range .done
                           marker, pull the next; normally launched by
                           `shepherd --fleet-ranges`, not by hand)
--fleet-worker <name>     (worker name recorded in leases/markers,
                           with --fleet-dir) [w<pid>]
--slab-rows <int>         (ragged pass-packing row budget; default 128)
--slab-shape-ladder <int> (canonical tail-slab heights per packed shape
                           group: budget >> k for k < N — bounds each
                           group to N XLA programs; 1 = all slabs
                           full-height) [2]
--no-warmup               (disable the AOT warmup precompiler: cold
                           compiles then stall the first dispatch of
                           each shape instead of overlapping ingest)
--prep-threads <int>      (overlapped prep plane: background threads
                           ingest + run the orientation walk ahead of
                           the admission window so host prep overlaps
                           device compute; 0 = inline prep on the
                           driver thread, the old behavior; output
                           bytes identical either way) [auto]
--banded-impl {scan,pallas,rotband}
                          (banded DP-fill implementation: the lax.scan
                           spec, the v1 band-local Pallas kernel, or
                           the v2 rotating-band kernel — all three
                           bit-identical (the A/B knob the promotion
                           harness benchmarks/pallas_ab.py drives);
                           also settable as CCSX_BANDED_IMPL) [scan]
--prefilter {on,off}      (device pre-alignment screen: one batched
                           dispatch scores each wave of strand_match
                           pair candidates and rejects hopeless ones
                           before the banded DP — conservative by
                           construction, output bytes identical
                           either way; 'off' disables the screen and
                           the walk's fwd+RC speculation — seeding
                           routing stays with --seed-device-min-t)
                           [on]
--seed-device-min-t <n>   (host/device k-mer seeding crossover: pairs
                           whose template is >= n bases seed on the
                           device (ops/seed_device.py, bit-equal to
                           the host sort-join); shorter pairs keep the
                           cached host path.  0 disables device
                           seeding) [16384]
--pass-buckets a,b,...    (bucketed-grouping A/B control: disables pass
                           packing and pads passes to these buckets)
--inject-faults p@N,...   (deterministic fault injection; testing only)

Subcommands:
ccsx-tpu shepherd --hosts N [opts] <INPUT> <OUTPUT>
                          (rank supervisor for sharded runs: launches
                           the N ranks as subprocesses, monitors
                           shard-journal heartbeats + per-rank
                           /healthz, restarts dead or stalled ranks
                           with exponential backoff up to
                           --max-rank-restarts — they resume from
                           their shard journals — then auto-merges;
                           turns merge_shards' "re-run the dead rank"
                           instruction into a supervised loop.
                           With --fleet-ranges M the shepherd becomes
                           the ELASTIC scheduler: the input splits
                           into M >> N leased work-ranges pulled by
                           the ranks; a dead rank's ranges requeue to
                           survivors (no in-place restart needed), a
                           drained rank (rc 75) is a voluntary leave,
                           stale leases expire after --lease-timeout
                           (SIGKILL + requeue), and
                           `shepherd --join <out>.fleet --hosts K`
                           adds K workers to a running fleet mid-run.
                           With --serve-replicas N [--gateway-port P]
                           the shepherd supervises a SERVE fleet
                           instead: N `serve --fleet` replicas + the
                           gateway as children — crashes restart with
                           backoff up to --max-replica-restarts, a
                           drained replica (rc 0/75) is not restarted
                           (its spool jobs stay with the survivors),
                           SIGTERM fans out a bounded-grace drain;
                           flags after the shepherd's own are the
                           serve/compute flags, e.g. `shepherd
                           --serve-replicas 3 --fleet SPOOL -A`)
ccsx-tpu stats <jsonl>... (summarize --trace / --metrics artifacts:
                           shape-group attribution table, stage
                           breakdown, occupancy recap, slowest
                           dispatches; any mix of files)
ccsx-tpu top <src>...     (live ANSI dashboard over telemetry
                           endpoints host:port and/or --metrics JSONL
                           files; multi-rank sources aggregate —
                           counters sum, min progress, any-degraded;
                           --once for one frame)
ccsx-tpu report <jsonl>.. (self-contained HTML run report from trace/
                           metrics JSONL: timeline strip, group
                           compile/execute table, stage breakdown,
                           occupancy tiles, stall/recovery log,
                           ETA-vs-actual curve; -o <out.html>.
                           With --fleet <dir>: stitch a fleet/spool
                           dir's per-process JSONL into ONE merged
                           wall-aligned timeline per job, keyed by
                           the correlation id minted at submission)
ccsx-tpu serve [opts]     (resident multi-tenant consensus server:
                           one warm runtime — executors, warmup
                           compiles, tracer — shared by jobs
                           submitted over HTTP on the telemetry
                           stack: POST /jobs (input path or streamed
                           BAM/FASTQ body), GET /jobs/<id> status,
                           GET /jobs/<id>/output, DELETE cancels;
                           /healthz liveness vs /readyz readiness.
                           Per-job fault isolation: own journal,
                           failure budget, breaker scope, metrics
                           label; fair shared admission window;
                           --job-deadline + bounded retry; queue cap
                           -> 429 + Retry-After; SIGTERM drains to a
                           resumable rc 75 and a restart requeues
                           unfinished jobs from <spool>/state.json.
                           Compute flags after the serve flags are
                           the normal run options.
                           With --fleet <spool> the server is one
                           REPLICA of a fleet sharing <spool> as a
                           job lease domain: jobs are leased
                           (O_EXCL acquire, heartbeat renew,
                           exclusive done marker), replica death
                           requeues them to survivors, jobs with
                           >= --fanout-holes holes fan out across
                           replicas through the range queue, and
                           each replica serves on port+slot)
ccsx-tpu gateway --spool S (thin balancer over a serve fleet: POST
                           /jobs health-routed on replica /readyz
                           — 503 + Retry-After when all drain, 429
                           at the spool cap — fleet job API served
                           from the spool, /replicas discovery from
                           slot leases, and ccsx_fleet_* autoscale
                           gauges — spool depth, leases held, per-
                           replica admission-window pressure — on
                           /metrics; no jax: keeps routing while
                           every replica's accelerator is wedged)
ccsx-tpu blackbox <path>.. (render crash-persistent flight-recorder
                           dumps: each process with CCSX_BLACKBOX=DIR
                           set mirrors its last events into an mmap
                           ring DIR/blackbox.<pid>.bin that survives
                           SIGKILL; headlines the in-flight job/range/
                           span at death, then the event tail.  A
                           directory argument expands to every ring
                           inside it; --tail N)
ccsx-tpu lint [files...]  (repo-native static analysis, pure ast — no
                           jax: int32-overflow hazards in ops/ traced
                           code, bare writes in lease/journal/spool
                           domains, off-lock Metrics mutation,
                           ContextVar set without token restore,
                           device spans closing unforced, and the
                           static telemetry schema cross-check.
                           Suppressions live in lint_baseline.json
                           (committed, every entry justified) or
                           inline `# lint: ok[check] reason`; --json
                           for machine output, --gauge-file to
                           publish the lint_findings dashboard gauge;
                           exit 0 iff clean.  Also: make lint)
"""


def usage() -> int:
    """Reference-parity help text (usage(), main.c:723-749), incl. its
    quirk: the usage text claims `-j [2]` while the code default is 1
    (main.c:740 vs main.c:754) — reproduced faithfully; our default is
    1 like the reference's code.  Returns 1 like the reference."""
    print(USAGE, end="")
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ccsx-tpu",
        description="Generate circular consensus sequences (ccs) from subreads.",
        add_help=False,
    )
    p.add_argument("-h", "--help", action="store_true", dest="help")
    p.add_argument("input", nargs="?", default="-",
                   help="Input file (BAM, or FASTA/Q with -A); '-' = stdin")
    p.add_argument("output", nargs="?", default="-",
                   help="Output FASTA; '-' = stdout")
    p.add_argument("-m", type=int, default=5000, dest="min_len",
                   help="Minimum total length of subreads in a hole [5000]")
    p.add_argument("-M", type=int, default=500000, dest="max_len",
                   help="Maximum total length of subreads in a hole [500000]")
    p.add_argument("-c", type=int, default=3, dest="min_count",
                   help="Minimum number of subreads required [3]")
    p.add_argument("-A", action="store_true", dest="fastx",
                   help="Input is fasta/fastq (gzip allowed)")
    p.add_argument("-P", action="store_true", dest="primitive",
                   help="Whole-read consensus (no windowed shred)")
    p.add_argument("-X", default=None, dest="exclude",
                   help="Exclude ZMWs: comma-separated hole IDs")
    p.add_argument("-j", type=int, default=1, dest="threads",
                   help="Number of host worker threads [1]")
    p.add_argument("-v", action="count", default=0, dest="verbose",
                   help="Debug verbosity (repeatable)")
    # TPU-era extensions
    p.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    p.add_argument("--mesh", default=None, metavar="D,P",
                   help="Batched-pipeline device mesh as data,pass (e.g. "
                        "4,2); default: all devices on the data axis")
    p.add_argument("--refine-iters", type=int, default=2)
    p.add_argument("--max-passes", type=int, default=32)
    p.add_argument("--pass-buckets", default=None, metavar="A,B,...",
                   help="bucketed-grouping A/B control: DISABLES ragged "
                        "pass packing and pads passes to these buckets "
                        "(ascending ints; ARCHITECTURE.md perf notes). "
                        "Output is byte-identical either way")
    p.add_argument("--slab-rows", type=int, default=None, metavar="R",
                   help="pass-packing slab row budget (power of two; "
                        "rows from many holes share one (R, qmax) "
                        "dispatch) [128]")
    p.add_argument("--slab-shape-ladder", type=int, default=None,
                   metavar="N", dest="slab_shape_ladder",
                   help="canonical tail-slab heights per packed shape "
                        "group (budget >> k for k < N): bounds each "
                        "group to N XLA programs in steady state; 1 = "
                        "every slab dispatches at the full row budget "
                        "[2]")
    p.add_argument("--no-warmup", action="store_true", dest="no_warmup",
                   help="disable the AOT warmup precompiler "
                        "(pipeline/warmup.py): compiles then block the "
                        "first dispatch of each shape instead of "
                        "overlapping ingest/prep")
    p.add_argument("--prep-threads", type=int, default=None,
                   dest="prep_threads", metavar="N",
                   help="overlapped prep plane (pipeline/prep_pool.py): "
                        "N background threads ingest + run the "
                        "orientation walk ahead of the admission "
                        "window, overlapping host prep with device "
                        "compute; 0 = inline prep (the old behavior). "
                        "Output bytes are identical either way "
                        "[auto-size to the host]")
    p.add_argument("--banded-impl", default="", dest="banded_impl",
                   choices=["", "scan", "pallas", "rotband"],
                   help="banded DP-fill implementation (consensus/"
                        "star.banded_impl): 'scan' = the lax.scan spec "
                        "(default), 'pallas' = the v1 band-local "
                        "kernel, 'rotband' = the v2 rotating-band "
                        "kernel.  Bit-identical output either way "
                        "(pinned); a pure performance A/B knob.  Also "
                        "settable as CCSX_BANDED_IMPL [scan]")
    p.add_argument("--prefilter", default="on", choices=["on", "off"],
                   dest="prefilter",
                   help="device pre-alignment screen (ops/sketch.py): "
                        "score each wave of strand_match pair "
                        "candidates in one batched dispatch and "
                        "reject hopeless ones before the banded DP. "
                        "Conservative: output bytes are identical on "
                        "or off (pinned); 'off' disables the screen "
                        "and the walk's fwd+RC speculation (the A/B "
                        "control — seeding routing is governed by "
                        "--seed-device-min-t alone) [on]")
    p.add_argument("--seed-device-min-t", type=int, default=None,
                   dest="seed_device_min_t", metavar="N",
                   help="host/device k-mer seeding crossover: pairs "
                        "whose template is >= N bases use the batched "
                        "device seeder (bit-equal to the host "
                        "sort-join, ops/seed_device.py); shorter "
                        "pairs keep the cached host path.  0 "
                        "disables device seeding [16384]")
    p.add_argument("--fastq", action="store_true", dest="fastq",
                   help="Write FASTQ with per-base vote-margin qualities "
                        "instead of FASTA (extension; the reference "
                        "emits FASTA only)")
    p.add_argument("--bam", action="store_true", dest="bam_out",
                   help="Write unaligned BAM (qual fields + rq aux tag; "
                        "implies --fastq's quality computation)")
    p.add_argument("--window-growth", default="flush",
                   choices=["flush", "grow"],
                   help="When no breakpoint is found at max-window: "
                        "'flush' forces a flush (bounded kernel shapes), "
                        "'grow' keeps growing like the reference [flush]")
    p.add_argument("--batch", default="auto",
                   choices=["auto", "on", "off"],
                   help="Batched device pipeline: many holes per TPU "
                        "dispatch [auto: on for TPU backends]")
    p.add_argument("--inflight", type=int, default=None,
                   help="Pin the batched pipeline's admission window "
                        "to exactly N holes.  Default (or <= 0): the "
                        "adaptive window — starts at zmw_microbatch/16 "
                        "and grows x4 per filled round up to "
                        "zmw_microbatch (the reference's chunk policy, "
                        "main.c:686-691)")
    p.add_argument("--journal", default=None,
                   help="Progress journal path for resumable runs")
    p.add_argument("--metrics", default=None,
                   help="Append JSON-lines metrics events to this path")
    p.add_argument("--trace", default=None,
                   help="Dispatch flight recorder: write span JSONL "
                        "here (+ a Chrome trace-event export at close; "
                        "utils/trace.py).  Device spans use the "
                        "forced-execution close, and the per-group "
                        "compile/execute table rides every metrics "
                        "event")
    p.add_argument("--stall-timeout", type=float, default=120.0,
                   dest="stall_timeout", metavar="SEC",
                   help="Hang watchdog: dump thread stacks + the "
                        "in-flight shape group when a device dispatch "
                        "stays open this long, and mark the run "
                        "degraded (0 disables; the first dispatch of "
                        "each shape gets 10x this budget — cold XLA "
                        "compiles are not hangs) [120]")
    p.add_argument("--telemetry-port", type=int, default=0,
                   dest="telemetry_port", metavar="PORT",
                   help="Serve live telemetry for this run on a daemon "
                        "thread: GET /metrics (Prometheus text), "
                        "/healthz (ok|degraded + stall/fallback "
                        "detail), /progress (JSON, windowed-rate ETA). "
                        "The port auto-bumps when taken; sharded runs "
                        "offset per rank.  0 = off [0]")
    p.add_argument("--profile", default=None,
                   help="Write a jax.profiler trace to this directory")
    # multi-host (parallel/distributed.py): run one process per host with
    # --hosts N --host-id R, then merge with --merge-shards N
    p.add_argument("--hosts", type=int, default=None,
                   help="Total hosts in a sharded run")
    p.add_argument("--host-id", type=int, default=None,
                   help="This host's rank in [0, --hosts)")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator address host:port "
                        "(optional; enables cross-host collectives)")
    p.add_argument("--merge-shards", type=int, default=None, metavar="N",
                   help="Merge OUTPUT.shard0..N-1 into OUTPUT and exit")
    p.add_argument("--merge-unmarked", action="store_true",
                   help="With --merge-shards: merge a shard set that has "
                        "NO completion markers at all (a legacy set "
                        "predating markers; indistinguishable from a "
                        "node-wide mid-run kill, so never assumed)")
    p.add_argument("--make-index", action="store_true",
                   help="Build INPUT's BGZF hole index sidecar "
                        "(<INPUT>.ccsx_idx) for byte-range sharded "
                        "multi-host ingest, then exit")
    # elastic fleet plane (pipeline/fleet.py): pull workers over a
    # leased work-range queue; normally launched by
    # `ccsx-tpu shepherd --fleet-ranges M`, not by hand
    p.add_argument("--fleet-dir", default=None, dest="fleet_dir",
                   metavar="DIR",
                   help="Run as a fleet pull worker against this "
                        "fleet directory (<out>.fleet): acquire a "
                        "range lease, stream it, retire it, pull the "
                        "next until the queue drains")
    p.add_argument("--fleet-worker", default=None, dest="fleet_worker",
                   metavar="NAME",
                   help="Worker name recorded in leases and range "
                        "done markers (with --fleet-dir; defaults to "
                        "w<pid>)")
    # resilient execution (pipeline/resilience.py)
    p.add_argument("--dispatch-deadline", type=float, default=0.0,
                   dest="dispatch_deadline", metavar="SEC",
                   help="Bounded-wait device dispatch: abandon a call "
                        "open past this deadline (thread parked, "
                        "result discarded) and replay its group on the "
                        "bit-exact host path; the first call of each "
                        "shape gets 10x for cold compiles.  0 = off — "
                        "a wedged dispatch stalls the run forever, "
                        "with the watchdog observing only [0]")
    p.add_argument("--breaker-strikes", type=int, default=None,
                   dest="breaker_strikes", metavar="N",
                   help="Backend circuit breaker: N device failures "
                        "(hangs, OOM ladder-bottoms, compile failures) "
                        "within 60s trip it open — remaining work runs "
                        "on the host path.  0 disables [3]")
    p.add_argument("--breaker-probe-s", type=float, default=None,
                   dest="breaker_probe_s", metavar="SEC",
                   help="Half-open re-probe interval for a tripped "
                        "breaker: one group dispatches as a probe and "
                        "success closes it.  0 = stay open for the "
                        "rest of the run [0]")
    p.add_argument("--max-failed-holes", default=None,
                   dest="max_failed_holes", metavar="V",
                   help="Failure-rate abort: an integer count (>= 0, "
                        "checked per failure) or a fraction of "
                        "processed holes in (0, 1) (checked at end of "
                        "run).  Exceeding it exits rc 2 instead of "
                        "emitting a near-empty output at rc 0 "
                        "[unbounded]")
    # hostile-input ingest plane (io/corruption.py)
    p.add_argument("--salvage", action="store_true", dest="salvage",
                   help="Salvage-mode ingest: classified input "
                        "corruption (io/corruption.py taxonomy) is "
                        "counted + resynced past — BGZF rescans for "
                        "the next valid block, BAM for the next "
                        "plausible record, FASTA/Q for the next "
                        "'>'/'@' line — instead of killing the run; "
                        "every undamaged hole still emits, the run is "
                        "marked degraded, and corrupt holes spend the "
                        "--max-failed-holes budget.  Default off: "
                        "fail-fast rc 1 on the first corrupt byte")
    p.add_argument("--max-record-bytes", type=int, default=None,
                   dest="max_record_bytes", metavar="N",
                   help="Allocation bound on one BAM alignment record "
                        "(enforced BEFORE allocating; a corrupt int32 "
                        "length must not drive a multi-GB allocation) "
                        "[268435456]")
    p.add_argument("--inject-faults", default=None, metavar="SPEC",
                   help="Deterministic fault injection for testing "
                        "recovery paths: point@N[+],... with points "
                        "ingest, compute, device_oom, stall, "
                        "device_hang, rank_death, write, journal, "
                        "input_corrupt, disk_full, sigterm "
                        "(utils/faultinject.py; CCSX_FAULTS env "
                        "equivalent)")
    return p


def config_from_args(args) -> CcsConfig:
    if args.min_count < 3:
        # mirror main.c:786-789
        print(f"Error! min fulllen count=[{args.min_count}] (>=3) !",
              file=sys.stderr)
        raise SystemExit(-1)
    exclude = None
    if args.exclude:
        exclude = frozenset(x for x in args.exclude.split(",") if x)
    mesh_shape = None
    if getattr(args, "mesh", None):
        try:
            mesh_shape = tuple(int(x) for x in args.mesh.split(","))
            if len(mesh_shape) != 2 or min(mesh_shape) < 1:
                raise ValueError
        except ValueError:
            print(f"Error: --mesh expects D,P integers, got {args.mesh!r}",
                  file=sys.stderr)
            raise SystemExit(1)
    pass_buckets = None
    if getattr(args, "pass_buckets", None):
        try:
            pass_buckets = tuple(
                int(x) for x in args.pass_buckets.split(","))
            if (not pass_buckets or min(pass_buckets) < 1
                    or list(pass_buckets) != sorted(set(pass_buckets))):
                raise ValueError
        except ValueError:
            print("Error: --pass-buckets expects ascending positive "
                  f"integers, got {args.pass_buckets!r}", file=sys.stderr)
            raise SystemExit(1)
        if pass_buckets[-1] < args.max_passes:
            # an undersized bucket list would silently defeat shape
            # bucketing: holes above the last bucket ship with their raw
            # pass count, one XLA compile per distinct count
            print(f"Error: --pass-buckets last bucket "
                  f"{pass_buckets[-1]} must cover --max-passes "
                  f"{args.max_passes}", file=sys.stderr)
            raise SystemExit(1)
    slab_rows = getattr(args, "slab_rows", None)
    if slab_rows is not None and slab_rows < 1:
        print(f"Error: --slab-rows must be >= 1, got {slab_rows}",
              file=sys.stderr)
        raise SystemExit(1)
    slab_ladder = getattr(args, "slab_shape_ladder", None)
    if slab_ladder is not None and not 1 <= slab_ladder <= 8:
        # > 8 heights would walk below budget/128 — that is the r7
        # compile storm with extra steps, refuse it
        print(f"Error: --slab-shape-ladder must be in [1, 8], got "
              f"{slab_ladder}", file=sys.stderr)
        raise SystemExit(1)
    stall_timeout = getattr(args, "stall_timeout", 120.0)
    if stall_timeout < 0:
        print(f"Error: --stall-timeout must be >= 0, got "
              f"{stall_timeout}", file=sys.stderr)
        raise SystemExit(1)
    telemetry_port = getattr(args, "telemetry_port", 0) or 0
    if not 0 <= telemetry_port <= 65535:
        print(f"Error: --telemetry-port must be in [0, 65535], got "
              f"{telemetry_port}", file=sys.stderr)
        raise SystemExit(1)
    prep_threads = getattr(args, "prep_threads", None)
    if prep_threads is not None and not 0 <= prep_threads <= 64:
        print(f"Error: --prep-threads must be in [0, 64], got "
              f"{prep_threads}", file=sys.stderr)
        raise SystemExit(1)
    seed_device_min_t = getattr(args, "seed_device_min_t", None)
    if seed_device_min_t is not None and seed_device_min_t < 0:
        print(f"Error: --seed-device-min-t must be >= 0, got "
              f"{seed_device_min_t}", file=sys.stderr)
        raise SystemExit(1)
    dispatch_deadline = getattr(args, "dispatch_deadline", 0.0) or 0.0
    if dispatch_deadline < 0:
        print(f"Error: --dispatch-deadline must be >= 0, got "
              f"{dispatch_deadline}", file=sys.stderr)
        raise SystemExit(1)
    breaker_strikes = getattr(args, "breaker_strikes", None)
    if breaker_strikes is not None and breaker_strikes < 0:
        print(f"Error: --breaker-strikes must be >= 0, got "
              f"{breaker_strikes}", file=sys.stderr)
        raise SystemExit(1)
    breaker_probe = getattr(args, "breaker_probe_s", None)
    if breaker_probe is not None and breaker_probe < 0:
        print(f"Error: --breaker-probe-s must be >= 0, got "
              f"{breaker_probe}", file=sys.stderr)
        raise SystemExit(1)
    max_failed = getattr(args, "max_failed_holes", None)
    if max_failed is not None:
        import math

        try:
            max_failed = float(max_failed)
            # reject what the semantics cannot honor: non-finite values
            # (would crash int()/comparisons mid-run), negatives, and
            # non-integer counts > 1 (int() would silently truncate
            # 1.5 to a tighter budget than asked).  0 is a valid count:
            # "no failures tolerated".
            if (not math.isfinite(max_failed) or max_failed < 0
                    or (max_failed >= 1
                        and max_failed != int(max_failed))):
                raise ValueError
        except ValueError:
            print("Error: --max-failed-holes expects an integer count "
                  ">= 0 or a fraction in (0, 1), got "
                  f"{args.max_failed_holes!r}", file=sys.stderr)
            raise SystemExit(1)
    banded_impl = getattr(args, "banded_impl", "") or ""
    if banded_impl:
        import os

        # dispatch reads the env (consensus/star.banded_impl) so the
        # knob reaches every jitted aligner without threading the config
        # through; an explicit flag wins over an inherited env var
        os.environ["CCSX_BANDED_IMPL"] = banded_impl
    max_record_bytes = getattr(args, "max_record_bytes", None)
    if max_record_bytes is not None and max_record_bytes < 4096:
        # a bound below any real record would reject every input; 4096
        # still lets tests drive the oversize classification cheaply
        print(f"Error: --max-record-bytes must be >= 4096, got "
              f"{max_record_bytes}", file=sys.stderr)
        raise SystemExit(1)
    return CcsConfig(
        min_subread_len=args.min_len,
        max_subread_len=args.max_len,
        min_fulllen_count=args.min_count,
        split_subread=not args.primitive,
        is_bam=not args.fastx,
        exclude_holes=exclude,
        threads=args.threads,
        verbose=args.verbose,
        refine_iters=args.refine_iters,
        max_passes=args.max_passes,
        emit_quality=args.fastq or args.bam_out,
        bam_out=args.bam_out,
        window_growth=args.window_growth,
        mesh_shape=mesh_shape,
        device=args.device,
        metrics_path=args.metrics,
        trace_path=getattr(args, "trace", None),
        stall_timeout_s=stall_timeout,
        telemetry_port=telemetry_port,
        # an explicit bucket list selects the bucketed-grouping control
        # path; the default is ragged pass packing (pipeline/pack.py)
        pass_packing=pass_buckets is None,
        warmup_compile=not getattr(args, "no_warmup", False),
        prep_threads=prep_threads,
        dispatch_deadline_s=dispatch_deadline,
        max_failed_holes=max_failed,
        salvage=bool(getattr(args, "salvage", False)),
        prefilter=getattr(args, "prefilter", "on") != "off",
        banded_impl=banded_impl,
        **({"seed_device_min_t": seed_device_min_t}
           if seed_device_min_t is not None else {}),
        **({"max_record_bytes": max_record_bytes}
           if max_record_bytes is not None else {}),
        **({"breaker_strikes": breaker_strikes}
           if breaker_strikes is not None else {}),
        **({"breaker_probe_s": breaker_probe}
           if breaker_probe is not None else {}),
        **({"pass_buckets": pass_buckets} if pass_buckets else {}),
        **({"slab_rows": slab_rows} if slab_rows else {}),
        **({"slab_shape_ladder": slab_ladder}
           if slab_ladder is not None else {}),
    )


def main(argv: Optional[list] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "shepherd":
        # rank supervisor for sharded runs: subprocess ranks, heartbeat
        # monitoring, restart-with-backoff, auto-merge
        from ccsx_tpu.pipeline.supervisor import shepherd_main

        return shepherd_main(argv[1:])
    if argv and argv[0] == "stats":
        # trace/metrics JSONL summarizer subcommand (no jax import, no
        # backend init — safe on a host whose accelerator is hung)
        from ccsx_tpu.utils import trace as trace_mod

        return trace_mod.stats_main(argv[1:])
    if argv and argv[0] == "top":
        # live telemetry dashboard (same no-jax discipline as stats)
        from ccsx_tpu.utils import telemetry

        return telemetry.top_main(argv[1:])
    if argv and argv[0] == "report":
        # static HTML run report from trace/metrics JSONL artifacts
        from ccsx_tpu.utils import report as report_mod

        return report_mod.report_main(argv[1:])
    if argv and argv[0] == "serve":
        # resident multi-tenant consensus server (pipeline/serve.py)
        from ccsx_tpu.pipeline.serve import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "gateway":
        # serve-fleet balancer/aggregator (pipeline/gateway.py) — the
        # same no-jax discipline as stats/top: it must keep routing
        # while every replica's accelerator is wedged
        from ccsx_tpu.pipeline.gateway import gateway_main

        return gateway_main(argv[1:])
    if argv and argv[0] == "blackbox":
        # crash-persistent flight-recorder dump renderer (utils/
        # blackbox.py) — no jax: the whole point is reading a DEAD
        # process' last events from a possibly-wedged host
        from ccsx_tpu.utils import blackbox

        return blackbox.blackbox_main(argv[1:])
    if argv and argv[0] == "lint":
        # repo-native static analysis (ccsx_tpu/lint/) — pure ast, no
        # jax by contract: it gates tier-1 on the 1-core box in
        # seconds (tests/test_lint.py asserts the no-jax discipline)
        from ccsx_tpu.lint.core import lint_main

        return lint_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.help:
        return usage()  # rc 1, like the reference (main.c:761)
    try:
        cfg = config_from_args(args)
    except SystemExit as e:
        return int(e.code or 0)

    if args.inject_faults:
        from ccsx_tpu.utils import faultinject

        try:
            faultinject.arm(args.inject_faults)
        except ValueError as e:
            print(f"Error: --inject-faults: {e}", file=sys.stderr)
            return 1

    if args.fleet_dir is not None:
        # fleet pull worker (pipeline/fleet.py): the fleet dir's
        # state file is the authority on input/output/ranges; the
        # scheduler topology flags cannot combine with it
        if (args.hosts is not None or args.host_id is not None
                or args.merge_shards is not None or args.make_index):
            print("Error: --fleet-dir is a pull worker; it cannot "
                  "combine with --hosts/--host-id/--merge-shards/"
                  "--make-index (the fleet scheduler owns those)",
                  file=sys.stderr)
            return 1
        if args.bam_out:
            print("Error: --bam is not supported with --fleet-dir "
                  "(use --fastq and convert the merged output)",
                  file=sys.stderr)
            return 1
        if args.batch == "off":
            print("Error: --batch off is not supported with "
                  "--fleet-dir", file=sys.stderr)
            return 1
        from ccsx_tpu.pipeline.fleet import run_fleet_worker

        return run_fleet_worker(args.fleet_dir, cfg,
                                worker=args.fleet_worker,
                                inflight=args.inflight)

    # imports deferred so --help stays fast and backend selection happens
    # after the config is known
    if args.make_index:
        if not cfg.is_bam:
            print("Error: --make-index requires BAM input (BGZF "
                  "container)", file=sys.stderr)
            return 1
        from ccsx_tpu.io import bam as bam_mod
        from ccsx_tpu.io import bamindex

        try:
            idx = bamindex.build_index(
                args.input,
                max_record_bytes=getattr(cfg, "max_record_bytes", 0))
        except (OSError, bam_mod.BamError) as e:
            print(f"Error: --make-index failed: {e}", file=sys.stderr)
            return 1
        print(f"[ccsx-tpu] indexed {idx['n_holes']} holes / "
              f"{idx['n_records']} records -> "
              f"{args.input}{bamindex.INDEX_SUFFIX}", file=sys.stderr)
        return 0

    if args.merge_shards is not None:
        from ccsx_tpu.parallel.distributed import merge_shards

        try:
            n = merge_shards(args.output, args.merge_shards,
                             allow_unmarked=args.merge_unmarked)
        except (OSError, ValueError) as e:
            # incomplete/dead shards or unreadable files: a designed,
            # expected operational refusal — clean rc 1, no traceback
            print(f"Error: {e}", file=sys.stderr)
            return 1
        print(f"[ccsx-tpu] merged {n} records from {args.merge_shards} "
              "shards", file=sys.stderr)
        return 0

    if args.bam_out and args.fastq:
        print("Error: --fastq and --bam are mutually exclusive",
              file=sys.stderr)
        return 1
    if args.bam_out and args.journal is not None:
        # the BGZF container is written whole at close, so a journal
        # could never be resumed — reject the trap up front
        print("Error: --bam does not support --journal (the BAM "
              "container cannot be appended on resume)", file=sys.stderr)
        return 1
    sharded = args.hosts is not None and args.hosts > 1
    if sharded:
        if args.host_id is None:
            print("Error: --hosts requires --host-id", file=sys.stderr)
            return 1
        if args.bam_out:
            # shard files are text FASTA/FASTQ merged by merge_shards;
            # write FASTQ shards and convert after the merge instead
            print("Error: --bam is not supported with --hosts "
                  "(use --fastq and convert the merged output)",
                  file=sys.stderr)
            return 1
        if args.batch == "off":
            # the sharded driver is built on the batched scheduler (its
            # shard writer needs per-hole ordinals); honoring 'off' would
            # silently run batched anyway, so reject it instead
            print("Error: --batch off is not supported with --hosts",
                  file=sys.stderr)
            return 1
        if args.coordinator is not None:
            from ccsx_tpu.parallel.distributed import init_distributed

            init_distributed(args.coordinator, args.hosts, args.host_id)

    # Resolve the backend FIRST (honoring --device cpu before any backend
    # initializes) and decide --batch auto from the resolved backend.
    from ccsx_tpu.utils.device import resolve_device

    backend = resolve_device(cfg.device)
    batch = args.batch
    if batch == "auto":
        batch = "on" if backend == "tpu" else "off"
    if cfg.mesh_shape is not None and batch == "off" and not sharded:
        # (sharded runs always use the batched executor, mesh included)
        print("[ccsx-tpu] --mesh has no effect with --batch off",
              file=sys.stderr)

    def _run():
        if sharded:
            from ccsx_tpu.parallel.distributed import run_pipeline_sharded

            return run_pipeline_sharded(
                args.input, args.output, cfg, args.host_id, args.hosts,
                journal_path=args.journal, inflight=args.inflight)
        if batch == "on":
            from ccsx_tpu.pipeline.batch import run_pipeline_batched

            return run_pipeline_batched(args.input, args.output, cfg,
                                        journal_path=args.journal,
                                        inflight=args.inflight)
        from ccsx_tpu.pipeline.run import run_pipeline

        return run_pipeline(args.input, args.output, cfg,
                            journal_path=args.journal)

    if args.profile:
        import jax

        with jax.profiler.trace(args.profile):
            return _run()
    return _run()


if __name__ == "__main__":
    sys.exit(main())
