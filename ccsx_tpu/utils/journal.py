"""Resume journal for long runs.

The reference has no checkpointing (SURVEY.md §5.4): a crash means a full
rerun.  Because output is strictly input-ordered, resumability only needs
one cursor: how many filtered holes have been fully written.  On resume the
pipeline skips that many holes and appends to the output.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional


@dataclasses.dataclass
class Journal:
    path: str
    input_id: str
    holes_done: int = 0

    @classmethod
    def load_or_create(cls, path: Optional[str], input_id: str) -> "Journal":
        j = cls(path=path or "", input_id=input_id)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    d = json.load(f)
                if d.get("input_id") == input_id:
                    j.holes_done = int(d.get("holes_done", 0))
            except (OSError, ValueError):
                pass  # unreadable journal: start over
        return j

    def advance(self, n: int = 1) -> None:
        self.holes_done += n
        if self.path:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"input_id": self.input_id,
                           "holes_done": self.holes_done}, f)
            os.replace(tmp, self.path)
