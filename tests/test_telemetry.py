"""Live telemetry plane (utils/telemetry.py + report.py): endpoint
scrape during a real run, /healthz degradation under an injected stall,
`top` multi-rank aggregation, the HTML report's golden structure on the
committed r8 artifacts, the schema-drift guard, and the satellite
behaviors (filter counts, resource gauges, watchdog rate-limiting).
"""

import io
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.utils import faultinject, synth, telemetry, trace
from ccsx_tpu.utils import report as report_mod
from ccsx_tpu.utils.metrics import (HIST_BUCKETS, Metrics, hist_quantile,
                                    merge_hist, resource_gauges, size_class)

BENCH_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks")
R8_TRACE = os.path.join(BENCH_DIR, "trace_r08_scale64.jsonl")
R8_METRICS = os.path.join(BENCH_DIR, "metrics_r08_scale64.jsonl")


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faultinject.disarm()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_fasta(tmp_path, rng, n_holes=3, tlen=700, n_passes=5):
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=n_passes,
                         movie="mv", hole=str(h)) for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


class _Buf(io.StringIO):
    """A StringIO Metrics.report() can 'close' while the test still
    reads it afterwards."""

    def close(self):
        pass


def _get(port, path, timeout=1.0):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        # 503 (degraded healthz) still carries a JSON body
        return e.code, e.read().decode()


# ---- endpoint server over a live run ---------------------------------------


@pytest.mark.slow  # ~11s: full-CLI on/off A/B scrape (r20 budget
# audit); the endpoint unit tests here and the live-HTTP pins in
# test_serve.py (liveness/readiness against a running core) keep the
# serving surface tier-1
def test_endpoint_scrape_during_real_run(tmp_path, rng):
    """The acceptance path: /progress + /metrics + /healthz answer
    during a real batched CPU run, counters are monotone across
    scrapes, and the OUTPUT IS BYTE-IDENTICAL with telemetry on vs
    off."""
    _, fa = _write_fasta(tmp_path, rng, n_holes=4)
    out_on = str(tmp_path / "on.fa")
    out_off = str(tmp_path / "off.fa")
    port = _free_port()
    res = {}

    def run():
        res["rc"] = cli.main(["-A", "-m", "1000", "--batch", "on",
                              "--telemetry-port", str(port),
                              str(fa), out_on])

    t = threading.Thread(target=run)
    t.start()
    scrapes, prom, health = [], None, None
    while t.is_alive():
        try:
            _, body = _get(port, "/progress", timeout=0.5)
            scrapes.append(json.loads(body))
            _, prom = _get(port, "/metrics", timeout=0.5)
            code, hbody = _get(port, "/healthz", timeout=0.5)
            health = (code, json.loads(hbody))
        except (urllib.error.URLError, OSError, ValueError):
            pass
        time.sleep(0.02)
    t.join()
    assert res["rc"] == 0
    assert scrapes, "run finished before a single scrape landed"
    # counters monotone across scrapes
    for key in ("holes_in", "holes_out", "windows", "device_dispatches"):
        seq = [s[key] for s in scrapes]
        assert seq == sorted(seq), (key, seq)
    assert all("progress" in s for s in scrapes)
    assert scrapes[-1]["status"] == "ok"
    # healthy run: /healthz said ok with the rc-relevant detail
    assert health is not None
    assert health[0] == 200 and health[1]["status"] == "ok"
    assert set(telemetry.HEALTH_DETAIL_KEYS) == set(health[1]["detail"])
    # prometheus text carries the north-star counters
    assert prom is not None
    assert "ccsx_holes_out " in prom or "ccsx_holes_out{" in prom
    assert "# TYPE ccsx_holes_out counter" in prom
    # the server is down after the run
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(port, "/healthz", timeout=0.5)
    # byte-identity: same input without telemetry
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), out_off]) == 0
    assert open(out_on, "rb").read() == open(out_off, "rb").read()


def test_healthz_flips_degraded_under_injected_stall(tmp_path, rng,
                                                     monkeypatch,
                                                     capsys):
    """/healthz must flip to degraded (HTTP 503) WHILE the stalled
    dispatch is still open — within one watchdog interval — and the
    run must still complete (degraded, never killed)."""
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "4.5")
    _, fa = _write_fasta(tmp_path, rng)
    port = _free_port()
    res = {}

    def run():
        res["rc"] = cli.main(
            ["-A", "-m", "1000", "--batch", "on",
             "--stall-timeout", "0.2", "--inject-faults", "stall@1",
             "--telemetry-port", str(port),
             "--metrics", str(tmp_path / "m.jsonl"),
             str(fa), str(tmp_path / "o.fa")])

    t = threading.Thread(target=run)
    t.start()
    flipped_at = None
    t0 = time.monotonic()
    while t.is_alive() and time.monotonic() - t0 < 30:
        try:
            code, body = _get(port, "/healthz", timeout=0.5)
        except (urllib.error.URLError, OSError):
            time.sleep(0.05)
            continue
        h = json.loads(body)
        if h["status"] == "degraded":
            flipped_at = time.monotonic() - t0
            assert code == 503
            assert h["detail"]["stalls"] >= 1
            break
        time.sleep(0.05)
    t.join()
    assert res["rc"] == 0                    # degraded, never killed
    assert flipped_at is not None, "/healthz never reported degraded"
    events = [json.loads(ln)
              for ln in open(tmp_path / "m.jsonl") if ln.strip()]
    assert events[-1]["event"] == "final"
    assert events[-1]["degraded"].startswith("stall watchdog")


def test_port_auto_bump_when_taken():
    port = _free_port()
    blocker = socket.socket()
    blocker.bind(("0.0.0.0", port))
    blocker.listen(1)
    try:
        m = Metrics()
        srv = telemetry.TelemetryServer(m, port, host="127.0.0.1")
        try:
            assert port < srv.port < port + telemetry.PORT_TRIES
            code, body = _get(srv.port, "/progress")
            assert code == 200 and json.loads(body)["holes_out"] == 0
        finally:
            srv.close()
    finally:
        blocker.close()


# ---- `top` aggregation -----------------------------------------------------


def _mk_metrics(holes_out, total=None, degraded=None):
    m = Metrics()
    m.holes_in = m.holes_out = holes_out
    m._ticked = holes_out
    m.windows = holes_out * 3
    m.device_dispatches = holes_out * 2
    m.holes_total = total
    m.degraded = degraded
    m._rate_ring.extend([(0.0, 0), (10.0, holes_out)])
    return m


def test_top_aggregates_two_rank_endpoints(capsys):
    """The acceptance aggregate: two per-rank endpoints sum their
    counters, progress is the MIN rank pct, and one degraded rank
    degrades the whole."""
    m0 = _mk_metrics(60, total=100)
    m1 = _mk_metrics(30, total=100, degraded="stall watchdog fired: x")
    # per-rank latency histograms: `top` must merge them by SUMMING
    # per-`le` bucket counts (quantiles do not compose)
    for v in (0.2, 0.2, 0.4):
        m0.observe("queue_wait_s", v, "small")
    for v in (0.9, 0.9, 0.9):
        m1.observe("queue_wait_s", v, "small")
    s0 = telemetry.TelemetryServer(m0, _free_port(), host="127.0.0.1")
    s1 = telemetry.TelemetryServer(m1, _free_port(), host="127.0.0.1")
    try:
        srcs = [telemetry.read_source(f"127.0.0.1:{s0.port}"),
                telemetry.read_source(f"127.0.0.1:{s1.port}")]
        agg = telemetry.aggregate(srcs)
        assert agg["holes_out"] == 90          # summed
        assert agg["windows"] == 270
        assert agg["pct"] == 30.0              # min rank progress
        assert agg["total"] == 200
        assert agg["any_degraded"] is True
        assert srcs[1]["status"] == "degraded"
        # summed buckets: 6 observations total, and the fleet p50 is
        # computed from the MERGED distribution (0.5 — the bucket where
        # the combined cumulative count crosses 3), not from averaging
        # the two per-rank medians
        merged = agg["hist"]["queue_wait_s"]["small"]
        assert merged["count"] == 6
        assert agg["queue_wait_p50"] == 0.5
        # the rendered frame carries the aggregate + the degraded mark
        rc = cli.main(["top", "--once", "--no-color",
                       f"127.0.0.1:{s0.port}", f"127.0.0.1:{s1.port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "DEGRADED" in out
        assert "out 90" in out
        assert "stall watchdog fired: x" in out
        assert "latency:" in out               # fleet quantile headline
        assert "qw50/95" in out                # per-source columns
    finally:
        s0.close()
        s1.close()


def test_top_unreachable_endpoint_degrades_aggregate():
    port = _free_port()   # nothing listening
    src = telemetry.read_source(f"127.0.0.1:{port}", timeout=0.3)
    assert src["status"] == "unreachable"
    agg = telemetry.aggregate([src])
    assert agg["any_degraded"] is True and agg["live"] == 0


def test_top_tails_metrics_jsonl(tmp_path, capsys):
    """Endpoint-less mode: `top` renders from the last event of a
    --metrics JSONL file."""
    buf = io.StringIO()
    m = _mk_metrics(7, total=10)
    m.stream = buf
    m.emit("progress")
    p = tmp_path / "m.jsonl"
    p.write_text(buf.getvalue() + "not json\n")   # torn tail tolerated
    src = telemetry.read_source(str(p))
    assert src["status"] == "ok" and src["snap"]["holes_out"] == 7
    assert cli.main(["top", "--once", "--no-color", str(p)]) == 0
    assert "out 7" in capsys.readouterr().out


def test_top_finished_run_from_final_event(tmp_path):
    buf = _Buf()
    m = _mk_metrics(5)
    m.stream = buf
    m.report()
    p = tmp_path / "m.jsonl"
    p.write_text(buf.getvalue())
    src = telemetry.read_source(str(p))
    assert src["status"] == "finished"
    agg = telemetry.aggregate([src])
    assert agg["finished"] is True


# ---- `report` --------------------------------------------------------------


def test_report_golden_structure_on_r8_artifacts(tmp_path, capsys):
    """The committed r8 scale-64 artifacts render into a report whose
    structure carries every section the ISSUE names."""
    out = str(tmp_path / "r8.html")
    rc = cli.main(["report", R8_TRACE, R8_METRICS, "-o", out])
    assert rc == 0
    page = open(out, encoding="utf-8").read()
    assert page.startswith("<!DOCTYPE html>")
    # sections
    for section in ("Timeline", "Stage self-time breakdown",
                    "Shape-group compile/execute table",
                    "Occupancy &amp; fill", "Progress: ETA vs actual",
                    "Stall &amp; recovery log"):
        assert section in page, section
    assert "<svg" in page                       # timeline strip rendered
    assert "packed:" in page                    # r8's packed groups
    assert "healthy run" in page                # r8 ran clean
    # r8 predates the progress estimator: the ETA section must degrade
    # gracefully, not lie
    assert "no ETA samples" in page
    # self-contained: no external fetches of any kind
    assert "http://" not in page and "https://" not in page
    assert "<script" not in page


def test_report_renders_progress_and_stalls(tmp_path):
    """A metrics stream WITH progress events and a stall renders the
    ETA curve and the incident log."""
    buf = _Buf()
    m = _mk_metrics(50, total=100)
    m.t0 = time.monotonic() - 20.0    # a deterministic nonzero elapsed
    m.stream = buf
    m.emit("progress")
    m.degraded = "stall watchdog fired: dispatch x"
    m.stalls = 1
    m.emit("stall", span="refine_packed", group="packed:q1", open_s=9.9)
    m.report()
    mp = tmp_path / "m.jsonl"
    mp.write_text(buf.getvalue())
    out = str(tmp_path / "r.html")
    assert cli.main(["report", str(mp), "-o", out]) == 0
    page = open(out, encoding="utf-8").read()
    assert "DEGRADED" in page
    assert "predicted remaining" in page        # ETA curve rendered
    assert "ETA samples" in page


def test_report_default_out_path():
    assert (report_mod.default_out_path("x/t.jsonl")
            == "x/t.report.html")


def test_collect_fleet_tolerates_torn_records(tmp_path):
    """A cid whose every span record is malformed (a torn JSONL line
    missing 'dur' — exactly what a killed replica leaves behind) must
    be dropped, not crash the alignment with an empty span list; good
    jobs in the same dir still stitch."""
    d = tmp_path / "spool"
    d.mkdir()
    good = {"ev": "span", "name": "refine", "cat": "device",
            "ts": 100.0, "dur": 0.5, "tid": "T", "cid": "cgood"}
    torn = {"ev": "span", "name": "refine", "cat": "device",
            "ts": 101.0, "tid": "T", "cid": "ctorn"}   # no 'dur'
    (d / "a.jsonl").write_text(
        json.dumps(good) + "\n" + json.dumps(torn) + "\n")
    data = report_mod.collect_fleet(str(d))
    assert set(data["jobs"]) == {"cgood"}
    assert data["jobs"]["cgood"]["t_end"] == 0.5


# ---- schema-drift guard ----------------------------------------------------


def _populated_snapshot():
    """A Metrics snapshot with every optional field forced present, so
    key-set comparisons see the full schema."""
    m = Metrics()
    for f in ("holes_in", "holes_out", "holes_failed", "holes_filtered",
              "stalls", "windows", "pair_alignments",
              "pairs_screened", "pairs_prefiltered",
              "pairs_seeded_device", "pairs_seeded_host",
              "device_dispatches", "refine_overflows", "oom_resplits",
              "host_fallbacks", "compile_fallbacks", "dp_cells_real",
              "dp_cells_padded", "dp_round_cells_real",
              "dp_round_cells_padded", "dp_rowcells_real",
              "dp_rowcells_cap", "dp_rows_real", "dp_rows_dispatched",
              "packed_dispatches", "packed_holes",
              "distinct_slab_shapes", "fused_waves",
              "fused_slabs_real", "fused_slots", "ingest_bytes",
              "device_hangs", "breaker_trips", "breaker_probes",
              "holes_corrupt"):
        setattr(m, f, 7)
    m.filtered_reasons["few_passes"] = 7
    m.corrupt_reasons["bgzf_bad_deflate"] = 7
    m.banded_dispatches["scan"] = 7
    m.holes_total = 100
    m.degraded = "x"
    m.breaker_state = "open"
    m.breaker_strike_log = [{"ts": 1.0, "kind": "hang", "group": "g"}]
    m.group_stats["g"] = {"compiles": 1, "compile_s": 0.1,
                          "execute_s": 0.2, "dispatches": 3,
                          "dp_cells": 40, "exec_cells": 30}
    m.job = "j0007"
    m.cid = "cfeedfacecafe"
    # one observation into EVERY latency family, so the key-set guards
    # and the exposition test cover the full histogram contract
    m.observe("queue_wait_s", 0.3, "small")
    m.observe("job_wall_s", 70.0, "large")
    m.observe("first_dispatch_s", 0.1, "small")
    m.observe("device_execute_s", 0.02, "g")
    m.observe("lease_acquire_s", 0.001, "job")
    return m.snapshot()


def test_schema_guard_every_consumed_key_exists():
    """Every counter name consumed by stats, top, and report exists in
    Metrics.snapshot() — a rename cannot silently zero a column."""
    snap = _populated_snapshot()
    for name, keys in [
            ("prometheus counters", telemetry.PROM_COUNTERS),
            ("prometheus gauges", telemetry.PROM_GAUGES),
            ("top sum keys", telemetry.TOP_SUM_KEYS),
            ("healthz detail", telemetry.HEALTH_DETAIL_KEYS),
            ("stats occupancy", trace.OCCUPANCY_KEYS),
            ("stats resilience", trace.RESILIENCE_KEYS),
            ("report tiles", report_mod.REPORT_TILE_KEYS),
            ("report header", report_mod.REPORT_HEADER_KEYS)]:
        missing = set(keys) - set(snap)
        assert not missing, f"{name} consume unknown keys: {missing}"
    # the progress sub-schema (total known -> pct/eta_s present)
    assert set(telemetry.PROGRESS_KEYS) == set(snap["progress"])
    # the per-group sub-schema (the ONE shared finalizer's output)
    assert set(telemetry.GROUP_FIELDS) == set(snap["groups"]["g"])


def test_schema_guard_every_snapshot_key_documented():
    """...and vice versa: every key snapshot() can emit is exported by
    /metrics (or explicitly structured) — a NEW counter cannot be
    invisible to the dashboard by accident."""
    snap = _populated_snapshot()
    documented = (set(telemetry.PROM_COUNTERS)
                  | set(telemetry.PROM_GAUGES)
                  | set(telemetry.PROM_STRUCTURED))
    undocumented = set(snap) - documented
    assert not undocumented, (
        f"snapshot keys invisible to the telemetry plane: "
        f"{undocumented} — add them to PROM_COUNTERS/PROM_GAUGES (or "
        f"PROM_STRUCTURED with a renderer) in utils/telemetry.py")


def test_prometheus_render_wellformed():
    snap = _populated_snapshot()
    # a second group + a second filter reason: labeled families must
    # still emit exactly ONE TYPE line per metric name (strict
    # exposition-format parsers reject duplicate TYPE lines)
    snap["groups"]["h"] = dict(snap["groups"]["g"])
    snap["filtered_reasons"]["too_short"] = 3
    text = telemetry.render_prometheus(snap, resource_gauges())
    assert text.endswith("\n")
    type_lines = []
    for line in text.splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE ccsx_")
            type_lines.append(line)
            continue
        name, _, value = line.rpartition(" ")
        assert name.startswith("ccsx_")
        float(value)                      # every sample parses
    assert len(type_lines) == len(set(type_lines))
    assert 'ccsx_group_dispatches{group="g"} 3' in text
    assert 'ccsx_group_dispatches{group="h"} 3' in text
    assert "ccsx_degraded 1" in text
    assert "ccsx_peak_rss_bytes" in text
    assert "ccsx_progress_pct" in text


# ---- latency histograms + SLO burn gauges ----------------------------------


def test_hist_schema_guard_both_directions():
    """HIST_FAMILIES <-> snapshot, both ways: a family renamed in
    Metrics cannot silently vanish from /metrics, and a new snapshot
    family cannot ship unrendered.  The SLO gauges must also reference
    real families and EXACT bucket bounds (the burn fraction is read
    off a cumulative bucket, never interpolated)."""
    snap = _populated_snapshot()
    fams = {f for f, _, _ in telemetry.HIST_FAMILIES}
    assert fams == set(snap["hist"]), (
        "histogram families drifted between Metrics.observe call sites "
        "and telemetry.HIST_FAMILIES")
    for _gauge, fam, threshold, objective in telemetry.SLO_BURN_GAUGES:
        assert fam in fams
        assert threshold in HIST_BUCKETS
        assert 0 < objective < 1


def test_prometheus_histogram_exposition_wellformed():
    """Every family renders the exposition shape promtool and
    histogram_quantile() expect: cumulative nondecreasing `le` buckets
    over the shared ladder, a +Inf bucket equal to _count, and _sum —
    all under the family's declared label key."""
    snap = _populated_snapshot()
    text = telemetry.render_prometheus(snap, resource_gauges())
    samples = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        samples[name] = float(value)
    labels = {"queue_wait_s": "small", "job_wall_s": "large",
              "first_dispatch_s": "small", "device_execute_s": "g",
              "lease_acquire_s": "job"}
    for fam, label_key, prom in telemetry.HIST_FAMILIES:
        assert f"# TYPE ccsx_{prom} histogram" in text, prom
        base = f'{label_key}="{labels[fam]}"'
        cum = [samples[f'ccsx_{prom}_bucket{{{base},le="{format(b, "g")}"}}']
               for b in HIST_BUCKETS]
        inf = samples[f'ccsx_{prom}_bucket{{{base},le="+Inf"}}']
        cum.append(inf)
        assert cum == sorted(cum), f"{prom}: buckets not cumulative"
        assert inf == samples[f"ccsx_{prom}_count{{{base}}}"] == 1
        assert f"ccsx_{prom}_sum{{{base}}}" in samples


def test_slo_burn_gauge_math():
    """burn = (fraction over threshold) / (1 - objective): 19 waits
    under the 1s queue-wait threshold + 1 over, at a 95% objective, is
    exactly burn 1.0 (spending the error budget at the sustainable
    rate).  A family with NO observations emits nothing — an idle
    fleet has no burn, not a fake 0."""
    m = Metrics()
    for _ in range(19):
        m.observe("queue_wait_s", 0.5, "small")
    m.observe("queue_wait_s", 70.0, "small")
    text = "\n".join(telemetry.slo_burn_lines(m.hist_snapshot()))
    assert "ccsx_slo_queue_wait_burn 1.0" in text
    assert "slo_job_wall_burn" not in text
    assert telemetry.slo_burn_lines({}) == []


def test_hist_merge_and_quantile_math():
    """merge_hist sums per-`le` counts elementwise; hist_quantile
    interpolates inside the crossing bucket (Prometheus-style) and
    answers the top bound for +Inf-landing targets."""
    a, b = Metrics(), Metrics()
    for v in (0.2, 0.2, 0.4):
        a.observe("queue_wait_s", v, "small")
    for v in (0.9, 0.9, 0.9):
        b.observe("queue_wait_s", v, "small")
    sa = a.hist_snapshot()["queue_wait_s"]["small"]
    sb = b.hist_snapshot()["queue_wait_s"]["small"]
    m = merge_hist([sa, sb])
    assert m["count"] == 6
    assert m["counts"] == [x + y for x, y in zip(sa["counts"],
                                                 sb["counts"])]
    assert hist_quantile(m, 0.5) == 0.5
    # torn/foreign snapshots are skipped, not fatal
    assert merge_hist([sa, None, {"counts": [1]}, "x"])["count"] == 3
    assert hist_quantile({"counts": [], "count": 0}, 0.5) is None
    # everything past the ladder top: the top bound is the honest p99
    top = Metrics()
    top.observe("job_wall_s", 9999.0, "large")
    s = top.hist_snapshot()["job_wall_s"]["large"]
    assert hist_quantile(s, 0.99) == HIST_BUCKETS[-1]


def test_size_class_bands():
    assert size_class(None) == "unknown"
    assert size_class(0) == "unknown"
    assert size_class(16) == "small"
    assert size_class(17) == "medium"
    assert size_class(256) == "medium"
    assert size_class(257) == "large"


def test_merge_hists_folds_job_snapshot_into_core():
    """serve's _finish path: a finished job's hist snapshot folds into
    the server-lifetime Metrics by summed buckets."""
    core, job = Metrics(), Metrics()
    core.observe("first_dispatch_s", 0.1, "small")
    job.observe("first_dispatch_s", 0.2, "small")
    job.observe("device_execute_s", 0.05, "g")
    core.merge_hists(job.hist_snapshot())
    snap = core.hist_snapshot()
    assert snap["first_dispatch_s"]["small"]["count"] == 2
    assert snap["device_execute_s"]["g"]["count"] == 1
    core.merge_hists({"first_dispatch_s": {"small": {"bad": 1}},
                      "junk": "x"})     # malformed entries are skipped
    assert core.hist_snapshot()["first_dispatch_s"]["small"]["count"] == 2


def test_port_range_clamped_at_65535():
    """A rank-offset base near the top of the port space degrades
    (OSError start() turns into a warning) instead of crashing the
    run with an uncaught OverflowError from socket."""
    m = Metrics()
    with pytest.raises(OSError):
        telemetry.TelemetryServer(m, 65536)
    assert telemetry.start(m, 70000) is None    # warns, never raises


def test_top_finished_degraded_headline(tmp_path, capsys):
    """A run that FINISHED with a tripped watchdog must not headline
    green: degraded outranks finished."""
    buf = _Buf()
    m = _mk_metrics(5, total=5, degraded="stall watchdog fired: x")
    m.stream = buf
    m.report()
    p = tmp_path / "m.jsonl"
    p.write_text(buf.getvalue())
    assert cli.main(["top", "--once", "--no-color", str(p)]) == 0
    out = capsys.readouterr().out
    assert "FINISHED DEGRADED" in out


# ---- progress/ETA estimator ------------------------------------------------


def test_progress_eta_estimator_math():
    m = Metrics()
    m._ticked = 50
    m.holes_total = 100
    # ring: 40 holes over the last 10 s -> 4.0/s windowed rate
    m._rate_ring.extend([(100.0, 10), (110.0, 50)])
    p = m.progress_snapshot()
    assert p["done"] == 50 and p["total"] == 100
    assert p["rate_zmws_per_sec"] == 4.0
    assert p["pct"] == 50.0
    assert p["eta_s"] == 12.5             # 50 remaining / 4 per sec


def test_progress_unknown_total_rate_only():
    m = Metrics()
    m._ticked = 5
    p = m.progress_snapshot()
    assert p["total"] is None
    assert "pct" not in p and "eta_s" not in p
    assert p["rate_zmws_per_sec"] >= 0


def test_periodic_interval_emission():
    buf = io.StringIO()
    m = Metrics(stream=buf, progress_every=0, progress_interval_s=0.05)
    m._last_interval_emit = time.monotonic() - 1.0   # overdue
    m.holes_in = m.holes_out = 1
    m.tick()
    events = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert [e["event"] for e in events] == ["progress"]
    assert events[0]["progress"]["done"] == 1


# ---- satellite: filter counts (both ingest paths) --------------------------


def test_filter_counts_surface_in_metrics(tmp_path, rng):
    """A run whose input contains sub-threshold holes reports them in
    holes_filtered + reason buckets — on whichever ingest path the
    driver picked (native in-library counts at EOF, or the pure-Python
    per-hole path)."""
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(h)) for h in range(3)]
    # 2 holes with too few passes (min_pass_count = 3+2)
    zs += [synth.make_zmw(rng, template_len=700, n_passes=3, movie="mv",
                          hole=str(10 + h)) for h in range(2)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    mpath = tmp_path / "m.jsonl"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--metrics", str(mpath),
                     str(fa), str(tmp_path / "o.fa")]) == 0
    fin = [json.loads(ln) for ln in open(mpath) if ln.strip()][-1]
    assert fin["event"] == "final"
    assert fin["holes_out"] == 3
    assert fin["holes_filtered"] == 2
    assert fin["filtered_reasons"] == {"few_passes": 2}


def test_native_streamer_reports_filter_counts(tmp_path, rng):
    """The native C++ streamer's in-library filter counts reach
    Metrics (the r7 span-table blind spot)."""
    from ccsx_tpu import native

    if not native.available():
        pytest.skip("native IO library unavailable")
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.native.io import stream_zmws_native

    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole="keep")]
    zs += [synth.make_zmw(rng, template_len=700, n_passes=2, movie="mv",
                          hole=f"few{h}") for h in range(3)]
    zs += [synth.make_zmw(rng, template_len=100, n_passes=6, movie="mv",
                          hole="short")]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    cfg = CcsConfig(is_bam=False, min_subread_len=1000)
    m = Metrics()
    out = list(stream_zmws_native(str(fa), cfg, metrics=m))
    assert [z.hole for z in out] == ["keep"]
    assert m.holes_filtered == 4
    assert m.filtered_reasons == {"few_passes": 3, "too_short": 1}


# ---- satellite: resource gauges -------------------------------------------


def test_resource_gauges_on_final():
    g = resource_gauges()
    assert set(g) == {"peak_rss_bytes", "device_buffer_bytes"}
    assert g["peak_rss_bytes"] > 0        # Linux: ru_maxrss available
    buf = _Buf()
    m = Metrics(stream=buf)
    m.report()
    fin = json.loads(buf.getvalue().splitlines()[-1])
    assert fin["event"] == "final"
    assert fin["peak_rss_bytes"] > 0
    assert "device_buffer_bytes" in fin


# ---- satellite: watchdog dump rate limiting --------------------------------


def test_stall_dumps_rate_limited(tmp_path, capsys):
    """One FULL stack dump, then compact one-line repeats — a long
    hang stalling span after span cannot flood stderr/trace/metrics
    with megabytes of identical stacks."""
    buf = io.StringIO()
    m = Metrics(stream=buf)
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p, stall_timeout=0.1, metrics=m)
    with tr.device_span("refine", group="g", shape="A"):
        pass                               # consume compile grace
    for _ in range(3):
        with tr.device_span("refine", group="g", shape="A"):
            time.sleep(0.5)
    tr.close()
    err = capsys.readouterr().err
    assert err.count("dumping state") == 1          # ONE full dump
    assert err.count('File "') >= 1
    assert err.count("compact repeat") == 2
    assert m.stalls == 3
    stalls = [json.loads(ln) for ln in open(p) if ln.strip()]
    stalls = [r for r in stalls if r.get("ev") == "stall"]
    assert len(stalls) == 3
    assert "stacks" in stalls[0]
    assert all("stacks" not in r and r.get("repeat")
               for r in stalls[1:])
    events = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    stall_events = [e for e in events if e["event"] == "stall"]
    assert len(stall_events) == 3
    assert m.degraded
