"""Minimal-fix sibling for the bare-write checker: the same writes
through the crash-safe idioms.  MUST produce no findings."""

import json
import os


def renew_lease(path, obj):
    # stage + fsync + atomic replace (the write_json_atomic shape):
    # the bare open is exempt because the SAME function publishes
    # atomically
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def try_acquire(path, payload):
    # O_EXCL acquire: creation IS the publish
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    try:
        os.write(fd, payload)
    finally:
        os.close(fd)
