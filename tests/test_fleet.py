"""Elastic fleet plane (pipeline/fleet.py + supervisor.fleet_run):
leased work-ranges, crash-safe lease arbitration, rank-loss
rebalancing, and mid-run fleet membership.

THE acceptance cases pinned here: a K-worker leased-range run merges
byte-identical to the unsharded reference with (a) no faults, (b) one
worker SIGKILLed mid-run and ZERO restart budget (its ranges requeue
to the survivors), (c) one worker SIGTERM-draining mid-run (voluntary
leave), and (d) one worker joining mid-run (`shepherd --join`).

Lease crash-consistency (satellite): torn leases (SIGKILL between
O_EXCL create and the owner write), duplicate acquisition races, and
expired-then-renewed leases all resolve to EXACTLY one owner.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.io import bamindex
from ccsx_tpu.parallel import distributed
from ccsx_tpu.pipeline import fleet, supervisor
from ccsx_tpu.utils import synth
from ccsx_tpu.utils.journal import write_json_atomic

import test_lease  # the shared lease crash-consistency scenario bodies

# fleet.py's integer-range lease API, adapted to the shared checkers:
# r16 extracted the state machine into utils/lease.py, and running the
# SAME scenario bodies through both key domains is the
# behavior-preservation proof for that refactor.
FLEET_OPS = test_lease.LeaseOps(
    path=fleet.lease_path, read=fleet.read_lease,
    acquire=fleet.try_acquire, renew=fleet.renew,
    expire=fleet.expire_lease, release=fleet.release,
    graveyard=fleet.GRAVEYARD)


# ---------- range split + table identity ----------

def test_split_ranges_partitions_and_degenerates():
    # M ranges tile [0, n) exactly, in order, no overlap
    rs = bamindex.split_ranges(10, 4)
    assert rs[0][0] == 0 and rs[-1][1] == 10
    for (a, b), (c, _) in zip(rs, rs[1:]):
        assert b == c and a <= b
    # M == N degenerates to exactly the static shard split
    assert bamindex.split_ranges(10, 2) == [
        bamindex.hole_range(10, r, 2) for r in range(2)]
    # M > n_holes keeps m rows (empty ranges are legal, zero-cost)
    rs = bamindex.split_ranges(2, 5)
    assert len(rs) == 5 and rs[0][0] == 0 and rs[-1][1] == 2
    assert sum(b - a for a, b in rs) == 2


def test_table_hash_pins_split_identity(tmp_path):
    rs4 = bamindex.split_ranges(8, 4)
    h = fleet.table_hash("in.fa", 8, rs4)
    assert h != fleet.table_hash("in.fa", 8, bamindex.split_ranges(8, 3))
    assert h != fleet.table_hash("other.fa", 8, rs4)
    # basename only: the same input reached via a different mount point
    # is the same split
    assert h == fleet.table_hash("/elsewhere/in.fa", 8, rs4)


def test_init_fleet_refuses_foreign_table(tmp_path):
    d = str(tmp_path / "f")
    st = fleet.init_fleet(d, "in.fa", "out.fa", 8, 4, 5.0, ["-A"])
    # same split: resume, state preserved
    again = fleet.init_fleet(d, "in.fa", "out.fa", 8, 4, 5.0)
    assert again["table"] == st["table"] and again["forward"] == ["-A"]
    # different M: loud refusal, not silent inheritance
    with pytest.raises(ValueError, match="different range table"):
        fleet.init_fleet(d, "in.fa", "out.fa", 8, 3, 5.0)


# ---------- lease crash-consistency (satellite) ----------

def test_write_json_exclusive_exactly_one_winner(tmp_path):
    test_lease.check_exclusive_retirement_single_winner(
        str(tmp_path / "marker"))


def test_try_acquire_race_admits_exactly_one(tmp_path):
    test_lease.check_acquire_race_admits_exactly_one(
        FLEET_OPS, str(tmp_path), 0)


def test_torn_lease_expires_by_mtime_and_readmits_one(tmp_path):
    """SIGKILL between O_EXCL create and the owner write leaves an
    empty lease file: it must age by mtime, expire, and be re-acquired
    by exactly one of any number of racers."""
    test_lease.check_torn_lease_expires_by_mtime(FLEET_OPS, str(tmp_path), 0)


def test_expired_then_renewed_lease_stays_owned(tmp_path):
    """A renewal that lands before the scheduler's expiry check keeps
    the lease: expiry reads the HEARTBEAT, not the acquire time."""
    test_lease.check_expired_then_renewed_stays_owned(
        FLEET_OPS, str(tmp_path), 0)


def test_release_ignores_foreign_lease(tmp_path):
    test_lease.check_release_ignores_foreign(FLEET_OPS, str(tmp_path), 0)


def test_reclaim_worker_leases_frees_only_that_pid(tmp_path):
    d = str(tmp_path)
    rec0 = fleet.try_acquire(d, 0, "dead")
    rec2 = fleet.try_acquire(d, 2, "dead")
    fleet.try_acquire(d, 1, "alive")
    write_json_atomic(fleet.lease_path(d, 0), dict(rec0, pid=987654))
    write_json_atomic(fleet.lease_path(d, 2), dict(rec2, pid=987654))
    assert fleet.reclaim_worker_leases(d, 3, 987654) == [0, 2]
    assert fleet.read_lease(d, 0) is None
    assert fleet.read_lease(d, 1) is not None   # the survivor's lease
    assert fleet.read_lease(d, 2) is None


def test_queue_state_counts(tmp_path):
    d = str(tmp_path)
    out = str(tmp_path / "o.fa")
    fleet.try_acquire(d, 1, "w0")
    write_json_atomic(distributed.done_path(out, 2), {"rank": 2})
    assert fleet.queue_state(d, out, 4) == {
        "done": 1, "leased": 1, "queued": 2}


# ---------- merge refusals (satellite) ----------

def _lease_shard(out, i, m, table, name="mv/100/ccs", ordinal=0):
    with open(distributed.shard_path(out, i), "w") as f:
        f.write(f">{name}\nACGT\n")
    with open(distributed.shard_path(out, i) + ".idx", "w") as f:
        f.write(f"#mode=lease/{table}\n{ordinal}\n")
    write_json_atomic(distributed.done_path(out, i),
                      {"rank": i, "hosts": m, "records": 1,
                       "holes_done": 1, "table": table})


def test_merge_refuses_static_lease_mix(tmp_path):
    out = str(tmp_path / "o.fa")
    _lease_shard(out, 0, 2, "aaaa", ordinal=0)
    # shard1 is a static round-robin shard with a marker
    with open(distributed.shard_path(out, 1), "w") as f:
        f.write(">mv/101/ccs\nACGT\n")
    with open(distributed.shard_path(out, 1) + ".idx", "w") as f:
        f.write("#mode=rr\n1\n")
    write_json_atomic(distributed.done_path(out, 1),
                      {"rank": 1, "hosts": 2, "records": 1,
                       "holes_done": 1})
    with pytest.raises(ValueError, match="don't merge across schedulers"):
        distributed.merge_shards(out, 2)


def test_merge_refuses_stale_table_marker(tmp_path):
    """A done marker recorded under a DIFFERENT split cannot vouch for
    bytes written under this one."""
    out = str(tmp_path / "o.fa")
    _lease_shard(out, 0, 2, "aaaa", ordinal=0)
    _lease_shard(out, 1, 2, "aaaa", name="mv/101/ccs", ordinal=1)
    marker = distributed.done_path(out, 1)
    with open(marker) as f:
        obj = json.load(f)
    write_json_atomic(marker, dict(obj, table="bbbb"))
    with pytest.raises(ValueError, match="stale marker"):
        distributed.merge_shards(out, 2)


def test_merge_refuses_foreign_expect_table(tmp_path):
    out = str(tmp_path / "o.fa")
    _lease_shard(out, 0, 1, "aaaa")
    with pytest.raises(ValueError, match="different -M split"):
        distributed.merge_shards(out, 1, expect_table="bbbb")
    # and a static set can never satisfy an expected lease table
    out2 = str(tmp_path / "p.fa")
    with open(distributed.shard_path(out2, 0), "w") as f:
        f.write(">mv/100/ccs\nACGT\n")
    with open(distributed.shard_path(out2, 0) + ".idx", "w") as f:
        f.write("#mode=rr\n0\n")
    write_json_atomic(distributed.done_path(out2, 0),
                      {"rank": 0, "hosts": 1, "records": 1,
                       "holes_done": 1})
    with pytest.raises(ValueError, match="expected a leased-range"):
        distributed.merge_shards(out2, 1, expect_table="aaaa")


def test_merge_accepts_consistent_lease_set(tmp_path):
    out = str(tmp_path / "o.fa")
    _lease_shard(out, 0, 2, "aaaa", name="mv/100/ccs", ordinal=0)
    _lease_shard(out, 1, 2, "aaaa", name="mv/101/ccs", ordinal=1)
    assert distributed.merge_shards(out, 2, expect_table="aaaa") == 2
    body = open(out).read()
    assert body.index("mv/100") < body.index("mv/101")


# ---------- bench gate (vs_prev fleet leg) ----------

def test_bench_compare_fleet_gates(monkeypatch):
    import bench

    arts = [("fleet_r13.json", {"scaleout_k4": 1.0,
                                "kill_overhead_x": 1.2, "ok": True}),
            ("fleet_r12.json", {"scaleout_k4": 1.5,
                                "kill_overhead_x": 1.1, "ok": True})]
    monkeypatch.setattr(bench, "latest_fleet_artifacts",
                        lambda *a, **k: arts)
    line, vp, regressed = {}, {}, []
    bench.compare_fleet(line, None, vp, regressed)
    # 1.5 -> 1.0 is a >20% scale-out drop: tripped
    assert line["fleet"]["artifact"] == "fleet_r13.json"
    assert vp["fleet_scaleout_k4"] == {"prev": 1.5, "cur": 1.0,
                                       "prev_source": "fleet_r12.json"}
    assert any("scaleout" in r for r in regressed)
    # within 20%: clean — and the prev bench line outranks artifact #2
    arts[0] = ("fleet_r13.json", {"scaleout_k4": 1.45,
                                  "kill_overhead_x": 1.2, "ok": True})
    line, vp, regressed = {}, {"fleet": {"scaleout_k4": 1.5}}, []
    bench.compare_fleet(line, {"fleet": {"scaleout_k4": 1.5}}, vp,
                        regressed)
    assert not regressed
    assert vp["fleet_scaleout_k4"]["prev_source"] == "prev bench line"
    # a soak with ANY non-byte-identical trial trips regardless of perf
    arts[0] = ("fleet_r13.json", {"scaleout_k4": 2.0,
                                  "kill_overhead_x": 1.0, "ok": False})
    line, vp, regressed = {}, {}, []
    bench.compare_fleet(line, None, vp, regressed)
    assert any("non-byte-identical" in r for r in regressed)


# ---------- CLI surface ----------

def test_fleet_worker_flag_validation(tmp_path, capsys):
    d = str(tmp_path / "f")
    # a pull worker cannot also be a static shard rank / merger / indexer
    assert cli.main(["--fleet-dir", d, "--hosts", "2",
                     "in.fa", "o.fa"]) == 1
    assert "fleet scheduler owns those" in capsys.readouterr().err
    assert cli.main(["--fleet-dir", d, "--batch", "off",
                     "in.fa", "o.fa"]) == 1
    capsys.readouterr()
    # a worker pointed at a dir with no fleet state fails loudly
    assert cli.main(["--fleet-dir", d, "in.fa", "o.fa"]) == 1
    assert "fleet.json" in capsys.readouterr().err
    # --join with no fleet state is the same story
    assert supervisor.shepherd_main(
        ["--join", d, "--hosts", "1", "in.fa", "o.fa"]) == 1


# ---------- end-to-end: K workers, faults, byte-identity ----------

@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """6 holes / M=4 ranges: every worker holds >1 range over the run,
    so mid-run kills and drains land while ranges are genuinely
    outstanding.  Same 700 bp / 5-pass geometry as the other fault
    suites."""
    tmp = tmp_path_factory.mktemp("fleet")
    rng = np.random.default_rng(0)
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(6)]
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    return fa, ref


def _fleet(fa, out, hosts, **kw):
    fwd = ["-A", "-m", "1000", "--batch", "on", str(fa), str(out)]
    cfg = cli.config_from_args(cli.build_parser().parse_args(fwd))
    kw.setdefault("env", dict(os.environ, CCSX_JOURNAL_FSYNC_S="0"))
    return supervisor.fleet_run(
        str(fa), str(out), cfg, hosts, fwd,
        ranges=4, lease_timeout=5.0, poll_s=0.1, backoff_s=0.1, **kw)


@pytest.mark.slow  # ~24s: the fault-free e2e; the SIGKILL-rebalance
# case below keeps the leased-range byte pin tier-1 (r13 budget audit)
def test_fleet_run_no_faults_byte_identical(corpus6, tmp_path, capsys):
    fa, ref = corpus6
    out = tmp_path / "o.fa"
    rc = _fleet(fa, out, 2)
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    assert "merged 6 records from 4 leased ranges" in err
    # the fleet dir is cleaned up after a successful merge
    assert not os.path.exists(fleet.fleet_dir_for(str(out)))


def test_fleet_run_sigkilled_worker_rebalances(corpus6, tmp_path,
                                               capsys):
    """THE rank-loss case: worker 1 is SIGKILLed mid-range with ZERO
    restart budget — the scheduler reclaims its leases immediately
    (reap-time rebalance, no lease-timeout wait) and the survivor
    absorbs them; merged bytes stay identical."""
    fa, ref = corpus6
    out = tmp_path / "o.fa"
    rc = _fleet(fa, out, 2, max_restarts=0,
                first_launch_env={1: {"CCSX_FAULTS": "rank_death@2"}})
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    assert "requeued range(s)" in err


@pytest.mark.slow
def test_fleet_run_sigterm_drain_is_voluntary_leave(corpus6, tmp_path,
                                                    capsys):
    """A worker that drains (rc 75) leaves the fleet voluntarily: no
    restart is spent, its unfinished ranges stay queued, the survivors
    finish, and the merge is byte-identical."""
    fa, ref = corpus6
    out = tmp_path / "o.fa"
    rc = _fleet(fa, out, 2, max_restarts=0,
                # @1: the drain fires at worker 1's FIRST retirement —
                # every worker acquires and finishes at least one
                # (non-empty) range, so the fault cannot be outrun
                first_launch_env={1: {"CCSX_FAULTS": "sigterm@1"}})
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    # the drained worker's own log records the rc-75 leave (the
    # scheduler's "voluntary leave" line is racy: the queue can empty
    # before the drained child is reaped); zero restarts were spent
    # either way, so the fault must have fired and the run still merged
    log1 = (out.parent / "o.fa.fleet.w1.log").read_text()
    assert "sigterm" in log1
    assert "drained" in log1 or "voluntary leave" in err


@pytest.mark.slow
def test_fleet_join_mid_run(corpus6, tmp_path, capsys):
    """Mid-run membership: a second worker joins a 1-worker fleet via
    the --join path and the merged output is unchanged."""
    fa, ref = corpus6
    out = tmp_path / "o.fa"
    d = fleet.fleet_dir_for(str(out))
    join_rc = []

    def joiner():
        for _ in range(400):
            if fleet.load_fleet(d):
                break
            time.sleep(0.05)
        join_rc.append(supervisor.fleet_join(
            d, 1, poll_s=0.1,
            env=dict(os.environ, CCSX_JOURNAL_FSYNC_S="0")))

    t = threading.Thread(target=joiner)
    t.start()
    rc = _fleet(fa, out, 1)
    t.join()
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    assert "joined worker" in err
    assert join_rc == [0]


@pytest.mark.slow
def test_fleet_run_whole_fleet_drained_resumes(corpus6, tmp_path,
                                               capsys):
    """Every worker draining before the queue empties is rc 75 — and
    re-running the same command RESUMES: the per-range journals carry
    the durable cursors, so the finish run recomputes only the tails
    and the final bytes are identical."""
    fa, ref = corpus6
    out = tmp_path / "o.fa"
    rc = _fleet(fa, out, 1, max_restarts=0,
                first_launch_env={0: {"CCSX_FAULTS": "sigterm@2"}})
    err = capsys.readouterr().err
    assert rc == exitcodes.RC_INTERRUPTED, err
    assert "re-run the same command to resume" in err
    assert os.path.exists(fleet.fleet_dir_for(str(out)))
    rc = _fleet(fa, out, 1)
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
