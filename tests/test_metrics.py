"""Observability: stage timers, window counters, periodic progress
events, the -v ladder, and metrics-stream lifecycle.

The reference has no observability beyond -v stderr prints (SURVEY.md
§5.1/§5.5); these tests pin the framework's replacement so the fields
can't silently rot into fiction.
"""

import io
import json

import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io import fastx
from ccsx_tpu.utils import synth
from ccsx_tpu.utils.metrics import Metrics


def _write_fasta(tmp_path, rng, n_holes=3, tlen=700, n_passes=5):
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=n_passes,
                         movie="mv", hole=str(h)) for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def _final_event(path):
    events = [json.loads(line) for line in open(path)]
    finals = [e for e in events if e["event"] == "final"]
    assert len(finals) == 1
    return finals[0], events


@pytest.mark.parametrize("batch", ["off", "on"])
def test_stage_timers_and_windows_are_written(tmp_path, rng, batch):
    """t_ingest/t_compute/t_write and the window counters must be fed by
    both drivers — they were once defined but never updated anywhere."""
    _, fa = _write_fasta(tmp_path, rng)
    out = tmp_path / "o.fa"
    mpath = tmp_path / "m.jsonl"
    assert cli.main(["-A", "-m", "1000", "--batch", batch,
                     "--metrics", str(mpath), str(fa), str(out)]) == 0
    final, _ = _final_event(mpath)
    assert final["holes_out"] == 3
    assert final["ingest_s"] > 0
    assert final["compute_s"] > 0
    assert final["write_s"] > 0
    # each hole runs >= 1 window refinement (the unit of device work
    # since the fused-refine protocol: one RefineRequest per window)
    assert final["windows"] >= 3
    assert final["device_dispatches"] > 0


def test_progress_events_every_n_holes():
    buf = io.StringIO()
    m = Metrics(stream=buf, progress_every=2)
    for _ in range(5):
        m.holes_out += 1
        m.tick()
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    progress = [e for e in events if e["event"] == "progress"]
    assert len(progress) == 2
    assert progress[0]["holes_out"] == 2
    assert progress[1]["holes_out"] == 4


def test_report_closes_file_stream(tmp_path):
    p = tmp_path / "m.jsonl"
    f = open(p, "a")
    m = Metrics(stream=f)
    m.report()
    assert f.closed
    assert m.stream is None
    final, _ = _final_event(p)
    assert final["event"] == "final"


def test_verbose_ladder(tmp_path, rng, capsys):
    """-v levels: 1 = oriented segment dump (main.c:477-479), 2 = consensus
    begin/end per hole (main.c:466-467), 3 = per-window breakpoint stats
    (main.c:619-620)."""
    from ccsx_tpu.pipeline.run import run_pipeline

    _, fa = _write_fasta(tmp_path, rng, n_holes=1, tlen=1500)
    cfg = CcsConfig(is_bam=False, min_subread_len=1000, verbose=3,
                    window_init=512, window_add=512, window_minlen=256,
                    max_window=2048)
    out = tmp_path / "o.fa"
    assert run_pipeline(str(fa), str(out), cfg) == 0
    err = capsys.readouterr().err
    assert "segment offs=" in err          # level 1
    assert "consensus begin mv/0" in err   # level 2
    assert "consensus end mv/0" in err
    assert "window size=" in err           # level 3
    assert "breakpoint=" in err


def test_verbose_level1_only(tmp_path, rng, capsys):
    _, fa = _write_fasta(tmp_path, rng, n_holes=1)
    out = tmp_path / "o.fa"
    assert cli.main(["-A", "-m", "1000", "-v", str(fa), str(out)]) == 0
    err = capsys.readouterr().err
    assert "segment offs=" in err
    assert "consensus begin" not in err
    assert "window size=" not in err


def test_negative_inflight_is_clamped(tmp_path, rng):
    """--inflight <= 0 once spun the batched scheduler forever."""
    _, fa = _write_fasta(tmp_path, rng, n_holes=2)
    out = tmp_path / "o.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--inflight", "-5", str(fa), str(out)]) == 0
    assert len(list(fastx.read_fastx(str(out)))) == 2


def test_batch_off_with_hosts_rejected(tmp_path):
    rc = cli.main(["-A", "--hosts", "2", "--host-id", "0",
                   "--batch", "off", "in.fa", "out.fa"])
    assert rc == 1


def test_dp_occupancy_counters(tmp_path, rng):
    """The batched run reports padding occupancy (SURVEY §7.3 item 2):
    counters present, occupancy in (0, 1], and — because all four
    round-only counters are in cell units — the factorization
    round_occupancy == length_fill * pass_fill * z_fill holds EXACTLY
    (up to the 4-digit rounding of the reported fields), even across
    heterogeneous shape-group dispatches."""
    import json

    _, fa = _write_fasta(tmp_path, rng, n_holes=3)
    out = tmp_path / "o.fa"
    m = tmp_path / "m.jsonl"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--metrics", str(m), str(fa), str(out)]) == 0
    fin = [json.loads(ln) for ln in m.read_text().splitlines()][-1]
    assert fin["event"] == "final"
    assert fin["dp_cells_padded"] >= fin["dp_cells_real"] > 0
    assert 0 < fin["dp_occupancy"] <= 1
    assert 0 < fin["dp_round_occupancy"] <= 1
    assert 0 < fin["dp_length_fill"] <= 1
    assert 0 < fin["dp_pass_fill"] <= 1
    assert 0 < fin["dp_z_fill"] <= 1
    prod = (fin["dp_length_fill"] * fin["dp_pass_fill"]
            * fin["dp_z_fill"])
    assert abs(prod - fin["dp_round_occupancy"]) < 2e-3, (
        prod, fin["dp_round_occupancy"])
    # overall occupancy additionally includes PairExecutor cells, which
    # have no Z/P bucket structure and are excluded from the factors
