"""Checker framework: discovery, findings, suppression, baseline, CLI.

Findings format
---------------
One finding = (check, path, line, col, message, text) where ``text``
is the stripped source line.  ``text`` — not the line NUMBER — is the
baseline match key, so a baseline survives unrelated edits above the
suppressed line and goes stale (reported, not fatal) when the line
itself changes or disappears.

Suppression, two mechanisms
---------------------------
- inline pragma on the flagged line::

      metrics.holes_in += 1  # lint: ok[metrics-lock] single-writer loop

  The bracketed check id is required to match (a bare ``lint: ok``
  suppresses every check on that line — use the bracketed form).

- the committed baseline (``lint_baseline.json`` at the repo root):
  entries ``{check, file, match, reason}`` where ``match`` is the
  stripped source line.  Every entry MUST carry a one-line reason;
  entries that no longer match anything are reported as stale so the
  baseline only shrinks.

Exit status: 0 iff no unsuppressed findings (parse errors count as
findings — an unparseable file cannot be vouched for).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(r"lint:\s*ok(?:\[([a-z0-9,\s-]+)\])?")
BASELINE_NAME = "lint_baseline.json"
PACKAGE_DIR = "ccsx_tpu"


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str
    path: str          # tree-root-relative, forward slashes
    line: int          # 1-based
    col: int           # 0-based
    message: str
    text: str          # stripped source line (baseline match key)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]                 # unsuppressed
    suppressed_pragma: int = 0
    suppressed_baseline: int = 0
    stale_baseline: List[dict] = dataclasses.field(default_factory=list)
    files_scanned: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.check] = out.get(f.check, 0) + 1
        return out


# ---- checker registry ------------------------------------------------------
# Per-file checkers: fn(tree, src, lines, relpath) -> iterable of Finding.
# Tree checkers: fn(scan_root, rel_prefix) -> iterable of Finding (cross-
# file invariants that need several modules at once, e.g. schema-drift).

FileChecker = Callable[[ast.AST, str, Sequence[str], str], Iterable[Finding]]
TreeChecker = Callable[[Path, str], Iterable[Finding]]

FILE_CHECKS: List[Tuple[str, FileChecker]] = []
TREE_CHECKS: List[Tuple[str, TreeChecker]] = []


def _register() -> None:
    # deferred so the checker modules can import core's Finding without
    # a cycle at package-import time
    if FILE_CHECKS:
        return
    from ccsx_tpu.lint import (
        checks_concurrency, checks_crashsafe, checks_numeric,
        checks_schema, checks_spans,
    )

    FILE_CHECKS.extend([
        (checks_numeric.CHECK, checks_numeric.check),
        (checks_crashsafe.CHECK, checks_crashsafe.check),
        (checks_concurrency.CHECK_LOCK, checks_concurrency.check_metrics_lock),
        (checks_concurrency.CHECK_CVAR, checks_concurrency.check_contextvar),
        (checks_spans.CHECK, checks_spans.check),
    ])
    TREE_CHECKS.append((checks_schema.CHECK, checks_schema.check_tree))


# ---- per-file run ----------------------------------------------------------


def lint_source(src: str, relpath: str,
                select: Optional[set] = None) -> List[Finding]:
    """All findings for one file's source, pragma suppression NOT yet
    applied (the runner applies it so it can count suppressions)."""
    _register()
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:
        return [Finding("parse-error", relpath, e.lineno or 1,
                        (e.offset or 1) - 1, f"cannot parse: {e.msg}", "")]
    lines = src.splitlines()
    findings: List[Finding] = []
    for check_id, fn in FILE_CHECKS:
        if select and check_id not in select:
            continue
        findings.extend(fn(tree, src, lines, relpath))
    return findings


def _pragma_suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    if not (1 <= finding.line <= len(lines)):
        return False
    m = PRAGMA_RE.search(lines[finding.line - 1])
    if not m:
        return False
    ids = m.group(1)
    if ids is None:
        return True
    return finding.check in {s.strip() for s in ids.split(",")}


def lint_file(path: Path, relpath: str,
              select: Optional[set] = None) -> Tuple[List[Finding], int]:
    """-> (findings, pragma_suppressed_count) for one file on disk."""
    src = path.read_text(encoding="utf-8", errors="replace")
    lines = src.splitlines()
    raw = lint_source(src, relpath, select)
    kept = [f for f in raw if not _pragma_suppressed(f, lines)]
    return kept, len(raw) - len(kept)


# ---- discovery -------------------------------------------------------------


def iter_py_files(scan_root: Path) -> List[Path]:
    return sorted(p for p in scan_root.rglob("*.py")
                  if "__pycache__" not in p.parts)


def _scan_root(root: Path) -> Path:
    """The real tree lints the package dir; a fixture mini-tree (no
    ``ccsx_tpu/`` inside) lints the given root itself."""
    pkg = root / PACKAGE_DIR
    return pkg if pkg.is_dir() else root


# ---- baseline --------------------------------------------------------------


def load_baseline(path: Path) -> List[dict]:
    if not path.is_file():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    entries = data.get("entries", [])
    for e in entries:
        for field in ("check", "file", "match", "reason"):
            if not isinstance(e.get(field), str) or not e[field].strip():
                raise ValueError(
                    f"baseline entry missing/empty {field!r}: {e} — every "
                    "suppression needs a check, file, match line, and a "
                    "one-line reason")
    return entries


def apply_baseline(findings: List[Finding], entries: List[dict],
                   ) -> Tuple[List[Finding], int, List[dict]]:
    """-> (unsuppressed, suppressed_count, stale_entries)."""
    used = [False] * len(entries)
    kept: List[Finding] = []
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if (e["check"] == f.check and e["file"] == f.path
                    and e["match"] == f.text):
                used[i] = True
                hit = True
        if not hit:
            kept.append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return kept, len(findings) - len(kept), stale


# ---- runner ----------------------------------------------------------------


def run_lint(root: Path, baseline: Optional[List[dict]] = None,
             select: Optional[set] = None,
             paths: Optional[Sequence[Path]] = None) -> LintResult:
    """Lint the tree under ``root`` (or just ``paths`` within it)."""
    _register()
    root = Path(root).resolve()
    scan = _scan_root(root)
    files = [Path(p).resolve() for p in paths] if paths \
        else iter_py_files(scan)
    findings: List[Finding] = []
    pragma_n = 0
    for path in files:
        rel = path.relative_to(root).as_posix()
        got, n = lint_file(path, rel, select)
        findings.extend(got)
        pragma_n += n
    if not paths:  # cross-file invariants need the whole tree
        prefix = "" if scan == root else scan.name + "/"
        for check_id, fn in TREE_CHECKS:
            if select and check_id not in select:
                continue
            findings.extend(fn(scan, prefix))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    kept, base_n, stale = apply_baseline(findings, baseline or [])
    return LintResult(findings=kept, suppressed_pragma=pragma_n,
                      suppressed_baseline=base_n, stale_baseline=stale,
                      files_scanned=len(files))


# ---- CLI -------------------------------------------------------------------


def _default_root() -> Path:
    # lint/core.py -> lint -> ccsx_tpu -> repo root
    return Path(__file__).resolve().parents[2]


def lint_main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ccsx-tpu lint",
        description="repo-native static analysis (see ccsx_tpu/lint/)")
    ap.add_argument("paths", nargs="*", help="specific files (default: "
                    "the whole ccsx_tpu package under --root)")
    ap.add_argument("--root", default=None,
                    help="tree root (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help=f"suppression baseline (default: "
                         f"<root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--select", default=None,
                    help="comma-separated checker ids to run")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append current findings to the baseline as "
                         "unreviewed entries (then justify them)")
    ap.add_argument("--gauge-file", default=None,
                    help="write a {lint_findings: N} gauge JSON "
                         "(atomic) for dashboard scrapers")
    args = ap.parse_args(list(argv) if argv is not None else None)

    root = Path(args.root).resolve() if args.root else _default_root()
    bpath = Path(args.baseline) if args.baseline else root / BASELINE_NAME
    try:
        entries = [] if args.no_baseline else load_baseline(bpath)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"ccsx-lint: bad baseline {bpath}: {e}", file=sys.stderr)
        return 2
    select = ({s.strip() for s in args.select.split(",")}
              if args.select else None)
    res = run_lint(root, baseline=entries, select=select,
                   paths=[Path(p) for p in args.paths] or None)

    n = len(res.findings)
    if args.gauge_file:
        # dogfood the crash-safe helper this linter enforces
        from ccsx_tpu.utils.journal import write_json_atomic

        write_json_atomic(args.gauge_file, {"lint_findings": n})
    if args.write_baseline and res.findings:
        entries = entries + [
            {"check": f.check, "file": f.path, "match": f.text,
             "reason": "unreviewed (auto-added; replace with a "
                       "justification)"}
            for f in res.findings]
        from ccsx_tpu.utils.journal import write_json_atomic

        write_json_atomic(str(bpath), {"version": 1, "entries": entries})
        print(f"ccsx-lint: wrote {len(res.findings)} entries to {bpath}")

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in res.findings],
            "counts": res.counts(),
            "suppressed": {"pragma": res.suppressed_pragma,
                           "baseline": res.suppressed_baseline},
            "stale_baseline": res.stale_baseline,
            "files_scanned": res.files_scanned,
            "gauge": {"lint_findings": n},
        }, indent=1, sort_keys=True))
    else:
        for f in res.findings:
            print(f.format())
        for e in res.stale_baseline:
            print(f"ccsx-lint: stale baseline entry (no longer matches): "
                  f"{e['file']}: {e['match']!r}", file=sys.stderr)
        print(f"ccsx-lint: {n} finding(s), "
              f"{res.suppressed_baseline} baseline-suppressed, "
              f"{res.suppressed_pragma} pragma-suppressed, "
              f"{res.files_scanned} files")
    return 1 if res.findings else 0


if __name__ == "__main__":
    sys.exit(lint_main())
