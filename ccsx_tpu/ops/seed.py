"""Host-side k-mer diagonal seeding (NumPy).

The reference's pairwise aligner is k-mer seeded
(kmer_striped_seqedit_pairwise with k=13, main.c:264): shared 13-mers locate
the alignment diagonal before the banded DP runs.  We keep that division of
labor: seeding runs on the host (tiny, latency-bound, irregular — wrong shape
for the TPU), and its output is the nominal-line hint consumed by the banded
device kernel (ops/banded.py `line=`).

Seeding is sort-join based: O((Q+T) log T) per pair, no hash tables.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

DEFAULT_K = 13          # main.c:264
MAX_HITS_PER_KMER = 4   # repeat guard
DIAG_BIN = 32           # diagonal histogram bin width


class SeedHit(NamedTuple):
    diag: int        # qpos - tpos of the dominant diagonal
    votes: int       # supporting k-mer hits
    line: np.ndarray  # (4,) int32 nominal line for banded_align


def kmer_codes(seq: np.ndarray, k: int = DEFAULT_K) -> np.ndarray:
    """Packed 2-bit k-mer codes; positions containing N yield code -1."""
    seq = np.asarray(seq, dtype=np.int64)
    n = len(seq) - k + 1
    if n <= 0:
        return np.empty(0, dtype=np.int64)
    # rolling pack via strided cumulative shifts
    codes = np.zeros(n, dtype=np.int64)
    bad = np.zeros(n, dtype=bool)
    for i in range(k):
        w = seq[i:i + n]
        codes = (codes << 2) | (w & 3)
        bad |= w >= 4
    codes[bad] = -1
    return codes


def seed_diagonal(
    q: np.ndarray,
    t: np.ndarray,
    k: int = DEFAULT_K,
    min_votes: int = 3,
) -> Optional[SeedHit]:
    """Find the dominant alignment diagonal (qpos - tpos) by k-mer voting.

    Returns None when fewer than ``min_votes`` k-mer hits support any
    diagonal band — the caller can reject the pair without running the DP
    (the reference gets the same early-out from a seedless k-mer alignment).
    """
    qk = kmer_codes(q, k)
    tk = kmer_codes(t, k)
    if len(qk) == 0 or len(tk) == 0:
        return None
    order = np.argsort(tk, kind="stable")
    tks = tk[order]
    left = np.searchsorted(tks, qk, side="left")
    right = np.searchsorted(tks, qk, side="right")
    cnt = np.minimum(right - left, MAX_HITS_PER_KMER)
    cnt[qk < 0] = 0
    total = int(cnt.sum())
    if total == 0:
        return None
    qpos = np.repeat(np.arange(len(qk)), cnt)
    starts = np.repeat(left, cnt)
    # within-run offsets 0..cnt-1
    run_ids = np.repeat(np.cumsum(cnt) - cnt, cnt)
    offs = np.arange(total) - run_ids
    tpos = order[starts + offs]
    diags = qpos - tpos

    lo = -len(t)
    nbins = (len(q) + len(t)) // DIAG_BIN + 2
    binned = (diags - lo) // DIAG_BIN
    hist = np.bincount(binned, minlength=nbins)
    # sum adjacent bins so a diagonal straddling a boundary still wins
    paired = hist[:-1] + hist[1:]
    best = int(np.argmax(paired))
    votes = int(paired[best])
    if votes < min_votes:
        return None
    in_best = (binned == best) | (binned == best + 1)
    diag = int(np.median(diags[in_best]))

    Q, T = len(q), len(t)
    i0 = max(diag, 0)
    j0 = i0 - diag
    i1 = min(Q, T + diag)
    j1 = i1 - diag
    line = np.array([i0, j0, i1, j1], dtype=np.int32)
    return SeedHit(diag=diag, votes=votes, line=line)
