"""Tier-1 gate for the static-analysis plane (ccsx_tpu/lint/).

Three contracts:

- the TREE IS CLEAN: the repo-native checkers over ccsx_tpu/ against
  the committed baseline produce zero unsuppressed findings, in a
  subprocess that also proves the no-jax discipline (the linter must
  cost seconds of the 870s tier-1 budget, not a jax import);
- the FIXTURE CORPUS pins each checker both ways: the known-bad twin
  (including BOTH historical int32-wrap expressions, verbatim) MUST
  flag, the minimal-fix sibling MUST NOT — false-negative and
  false-positive guards in one parametrized table;
- the SUPPRESSION machinery is itself tested: inline pragmas, baseline
  matching (by stripped line text, not line number), stale-entry
  detection, and the every-entry-needs-a-reason rule.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from ccsx_tpu.lint import checks_schema, core
from ccsx_tpu.lint.core import Finding

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "lint_fixtures"


def _lint_fixture(relfile: str, check: str):
    findings, _ = core.lint_file(FIXTURES / relfile, relfile)
    return [f for f in findings if f.check == check]


# ---- the tree is clean (and the linter is jax-free) ------------------------


def test_tree_clean_no_jax_subprocess():
    code = (
        "import sys\n"
        "from ccsx_tpu.lint.core import lint_main\n"
        "rc = lint_main([])\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"unsuppressed lint findings (or jax import) — fix them or "
        f"baseline with a justification:\n{proc.stdout}{proc.stderr}")


def test_committed_baseline_valid_and_not_stale():
    entries = core.load_baseline(ROOT / core.BASELINE_NAME)
    assert entries, "committed baseline missing or empty"
    res = core.run_lint(ROOT, baseline=entries)
    assert res.clean, [f.format() for f in res.findings]
    assert not res.stale_baseline, (
        f"baseline entries that no longer match anything — delete "
        f"them: {res.stale_baseline}")


def test_real_tree_schema_contract():
    found = list(checks_schema.check_tree(ROOT / "ccsx_tpu",
                                          "ccsx_tpu/"))
    assert found == [], [f.format() for f in found]


# ---- fixture corpus: bad twin flags, fixed sibling doesn't -----------------

CORPUS = [
    ("ops/overflow_bad.py", "int32-overflow", 3),
    ("ops/overflow_ok.py", "int32-overflow", 0),
    ("crashsafe/lease.py", "bare-write", 2),
    ("crashsafe/spool_writer_bad.py", "bare-write", 1),
    ("crashsafe_ok/lease.py", "bare-write", 0),
    ("concurrency/metrics_bad.py", "metrics-lock", 2),
    ("concurrency/metrics_bad.py", "contextvar-restore", 1),
    ("concurrency/metrics_ok.py", "metrics-lock", 0),
    ("concurrency/metrics_ok.py", "contextvar-restore", 0),
    ("spans/span_bad.py", "span-force", 1),
    ("spans/span_ok.py", "span-force", 0),
]


@pytest.mark.parametrize("relfile,check,expected", CORPUS)
def test_fixture_corpus(relfile, check, expected):
    findings = _lint_fixture(relfile, check)
    assert len(findings) == expected, [f.format() for f in findings]


def test_historical_wrap_expressions_flag_verbatim():
    """Both shipped int32 wraps — the pre-r11 _line_interp product and
    the pre-r14 compute_offsets re-derivation — must flag as written."""
    texts = {f.text for f in _lint_fixture("ops/overflow_bad.py",
                                           "int32-overflow")}
    assert "return ip * span // denom" in texts
    assert ("nom_j = lj0 + (i - li0) * (lj1 - lj0) "
            "// jnp.maximum(li1 - li0, 1)") in texts


def test_schema_fixture_both_directions():
    bad = list(checks_schema.check_tree(FIXTURES / "schema_bad"))
    msgs = " | ".join(f.message for f in bad)
    assert len(bad) == 2, [f.format() for f in bad]
    assert "missing_key" in msgs      # consumed but never emitted
    assert "orphan_key" in msgs       # emitted but never exported
    assert checks_schema.check_tree(FIXTURES / "schema_ok") == []


# ---- suppression machinery -------------------------------------------------


def test_pragma_suppresses_only_named_check(tmp_path):
    src = (
        "import contextvars\n"
        "_v = contextvars.ContextVar('v')\n\n\n"
        "def set_only(x):\n"
        "    _v.set(x)  # lint: ok[contextvar-restore] fixture pragma\n\n\n"
        "def set_wrong_id(x):\n"
        "    _v.set(x)  # lint: ok[span-force] wrong id\n"
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    findings, pragma_n = core.lint_file(p, "mod.py")
    assert pragma_n == 1
    assert [f.line for f in findings
            if f.check == "contextvar-restore"] == [10]


def test_baseline_matches_by_line_text_and_reports_stale():
    f1 = Finding("metrics-lock", "a.py", 3, 0, "m", "metrics.x += 1")
    f2 = Finding("metrics-lock", "a.py", 9, 0, "m", "metrics.y += 1")
    entries = [
        {"check": "metrics-lock", "file": "a.py",
         "match": "metrics.x += 1", "reason": "single writer"},
        {"check": "metrics-lock", "file": "gone.py",
         "match": "metrics.z += 1", "reason": "stale"},
    ]
    kept, n, stale = core.apply_baseline([f1, f2], entries)
    assert kept == [f2] and n == 1
    assert [e["file"] for e in stale] == ["gone.py"]


def test_baseline_entry_requires_reason(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": [
        {"check": "bare-write", "file": "x.py", "match": "open(p)",
         "reason": " "}]}))
    with pytest.raises(ValueError):
        core.load_baseline(p)


# ---- CLI surfaces ----------------------------------------------------------


def test_cli_lint_json_and_gauge(tmp_path, capsys):
    from ccsx_tpu import cli

    gauge = tmp_path / "lint_gauge.json"
    rc = cli.main(["lint", "--json", "--gauge-file", str(gauge),
                   "--root", str(ROOT)])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["gauge"]["lint_findings"] == 0
    assert data["suppressed"]["baseline"] >= 1  # the committed triage
    assert json.loads(gauge.read_text()) == {"lint_findings": 0}


def test_lint_findings_prometheus_gauge():
    """The dashboard path: a populated lint_findings rides snapshot()
    into the /metrics rendering like any other gauge."""
    from ccsx_tpu.utils import telemetry
    from ccsx_tpu.utils.metrics import Metrics

    m = Metrics()
    assert m.snapshot()["lint_findings"] is None  # clean: no sample
    m.bump(lint_findings=5)
    text = telemetry.render_prometheus(m.snapshot())
    assert "ccsx_lint_findings 5" in text
    assert "# TYPE ccsx_lint_findings gauge" in text
