"""Serving plane (pipeline/serve.py): resident multi-tenant server.

Load-bearing guarantees pinned here:

* N concurrent jobs through one warm ServeCore produce outputs
  BYTE-IDENTICAL to the sequential CLI run of the same input, and a
  second wave of jobs books ZERO new XLA compiles in the server
  tracer's group table (the steady-state-recompile criterion).
* The queue-depth cap answers HTTP 429 with a Retry-After header.
* DELETE cancels a mid-flight job through the drivers' drain path
  (rc 75) without touching its siblings.
* A server drain with in-flight work exits resumable (rc 75,
  "interrupted"), and a restarted core requeues the job from
  state.json and completes it byte-identically via its journal.
* A tenant-induced device hang degrades ONLY that job to the host
  rung: the faulted job completes byte-identically with its own
  device_hangs/host_fallbacks counters, the clean sibling shows none,
  and the server stays ready throughout.
* /healthz is LIVENESS (200 while serving) and /readyz is READINESS
  (503 + reason while draining); the per-job Prometheus series
  conforms to the telemetry schema tuples.

The corpus reuses the 700 bp / 5-pass geometry of tests/test_faults.py
and tests/test_resilience.py so tier-1's process-wide jit cache is
shared across the three files.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.pipeline.serve import (FairWindow, ServeCore, QueueFull,
                                     _serve_handler)
from ccsx_tpu.utils import faultinject, synth, telemetry


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(autouse=True)
def _fast_grace(monkeypatch):
    # unit-scale budgets: no 10x first-of-shape deadline grace, bounded
    # hang parks, short injected stalls
    monkeypatch.setenv("CCSX_DEADLINE_GRACE", "1")
    monkeypatch.setenv("CCSX_FAULT_HANG_S", "60")
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "4")


def _cfg(extra=()):
    args = cli.build_parser().parse_args(["-A", "-m", "1000", *extra])
    return cli.config_from_args(args)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(3-hole input, its CLI reference output, 8-hole input, its CLI
    reference output) — references computed by the plain CLI BEFORE
    any ServeCore exists (the server owns the installed tracer)."""
    tmp = tmp_path_factory.mktemp("serve")
    rng = np.random.default_rng(0)

    def make(n, path):
        zs = [synth.make_zmw(rng, template_len=700, n_passes=5,
                             movie="mv", hole=str(100 + h))
              for h in range(n)]
        path.write_text(synth.make_fasta(zs))

    fa3, fa8 = tmp / "in3.fa", tmp / "in8.fa"
    make(3, fa3)
    make(8, fa8)
    ref3, ref8 = tmp / "ref3.fa", tmp / "ref8.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa3), str(ref3)]) == 0
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa8), str(ref8)]) == 0
    return (str(fa3), ref3.read_bytes(), str(fa8), ref8.read_bytes())


@pytest.fixture
def core_factory(tmp_path):
    cores = []

    def make(spool="spool", extra=(), **kw):
        c = ServeCore(_cfg(extra), spool=str(tmp_path / spool), **kw)
        cores.append(c)
        return c

    yield make
    for c in cores:
        c.close()


def _http(srv):
    base = f"http://127.0.0.1:{srv.port}"

    def req(method, path, data=None, ctype="application/json"):
        r = urllib.request.Request(base + path, data=data, method=method)
        if data is not None:
            r.add_header("Content-Type", ctype)
        try:
            with urllib.request.urlopen(r, timeout=30) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    return req


@pytest.fixture
def served(core_factory):
    """(core, req) — a ServeCore mounted on an ephemeral-port HTTP
    server through the telemetry stack, torn down after the test."""
    servers = []

    def make(**kw):
        core = core_factory(**kw)
        srv = telemetry.TelemetryServer(
            core.metrics, 0, host="127.0.0.1",
            handler=_serve_handler(),
            attrs={"ccsx_core": core, "ccsx_ready": core.readiness})
        servers.append(srv)
        return core, _http(srv)

    yield make
    for s in servers:
        s.close()


# ---------- units: the fair shared admission window ----------

def test_fair_window_semantics():
    w = FairWindow(4)
    w.register("a")
    # a lone tenant gets the whole window
    assert all(w.try_acquire("a") for _ in range(4))
    assert not w.try_acquire("a")          # capacity, not share
    # a second tenant arrives and is denied (window full): it is now
    # "wanting", so the incumbent is capped at its fair share
    # (ceil(4/2) = 2) until the newcomer gets a slot
    w.register("b")
    assert not w.try_acquire("b")
    w.release("a")                         # a holds 3: above its share
    assert not w.try_acquire("a")          # capped while b wants
    assert w.try_acquire("b")              # the freed slot goes to b
    # b's success clears its "wanting" mark: nobody is being starved,
    # so a may grow back into whatever capacity is free
    w.release("a")
    w.release("a")                         # a holds 1, b holds 1
    assert w.try_acquire("a") and w.try_acquire("a")
    # b leaves: the lone tenant may take the whole window again
    w.release_all("b")
    w.unregister("b")
    assert w.try_acquire("a")
    assert not w.try_acquire("a")          # back at capacity (4)
    w.release_all("a")
    w.unregister("a")


# ---------- concurrency: byte identity + zero steady-state compiles --------

def test_concurrent_jobs_byte_identical_no_recompiles(corpus,
                                                      core_factory):
    fa3, ref3, _, _ = corpus
    core = core_factory(max_active=3)
    first = [core.submit(input_path=fa3) for _ in range(3)]
    for j in first:
        assert core.wait(j.id, 180) == "done", (j.state, j.error)
        assert open(j.out_path, "rb").read() == ref3

    def compiles():
        groups = core.metrics.snapshot().get("groups") or {}
        return sum(g["compiles"] for g in groups.values())

    warm = compiles()
    # steady state: a second concurrent wave books ZERO new compiles
    # in the server tracer's cumulative group table
    second = [core.submit(input_path=fa3) for _ in range(3)]
    for j in second:
        assert core.wait(j.id, 180) == "done", (j.state, j.error)
        assert open(j.out_path, "rb").read() == ref3
    assert compiles() == warm, "steady-state serve run recompiled"
    # per-job fault-domain accounting stayed per job
    snaps = core.job_snapshots()
    assert all(snaps[j.id]["job"] == j.id for j in first + second)
    assert all(snaps[j.id]["holes_out"] == 3 for j in first + second)


# ---------- the HTTP job API ----------

@pytest.mark.slow  # ~12s: HTTP rendering of the queue cap;
# test_queue_full_core_raises keeps the cap tier-1 and the gateway
# suite pins the HTTP Retry-After family (r16 budget audit)
def test_queue_cap_429_with_retry_after(corpus, served):
    fa3, ref3, _, _ = corpus
    core, req = served(max_active=1, max_queue=1)
    # occupy the one runner with a stalled job, fill the one queue slot
    code, body, _ = req("POST", "/jobs", json.dumps(
        {"input": fa3, "faults": "stall@1"}).encode())
    assert code == 201
    held = json.loads(body)["id"]
    code, body, _ = req("POST", "/jobs",
                        json.dumps({"input": fa3}).encode())
    assert code == 201
    queued = json.loads(body)["id"]
    # the cap: 429 + Retry-After, and /readyz flips to "queue full"
    code, body, headers = req("POST", "/jobs",
                              json.dumps({"input": fa3}).encode())
    assert code == 429
    assert int(headers.get("Retry-After", 0)) >= 1
    code, body, _ = req("GET", "/readyz")
    assert code == 503 and json.loads(body)["reason"] == "queue full"
    # liveness is unaffected by a full queue
    code, body, _ = req("GET", "/healthz")
    assert code == 200 and json.loads(body)["status"] == "alive"
    # the held jobs still complete byte-identically
    for jid in (held, queued):
        assert core.wait(jid, 180) == "done"
        assert open(core.job(jid).out_path, "rb").read() == ref3
    code, body, _ = req("GET", "/readyz")
    assert code == 200


def test_submit_validation(served):
    _, req = served()
    code, body, _ = req("POST", "/jobs", json.dumps(
        {"input": "/nonexistent", "bogus_knob": 1}).encode())
    assert code == 400 and b"bogus_knob" in body
    code, body, _ = req("POST", "/jobs", b"{}")
    assert code == 400
    code, body, _ = req("GET", "/jobs/zzz")
    assert code == 404


@pytest.mark.slow  # ~7s: solo-serve cancel blast radius; the fleet
# suite's cancel-at-renewal + sibling-byte-identity tests keep the
# cancel drain path tier-1 (r16 budget audit)
def test_cancel_mid_job_leaves_sibling_untouched(corpus, served):
    fa3, ref3, _, _ = corpus
    core, req = served(max_active=2)
    code, body, _ = req("POST", "/jobs", json.dumps(
        {"input": fa3, "faults": "stall@1"}).encode())
    victim = json.loads(body)["id"]
    code, body, _ = req("POST", "/jobs",
                        json.dumps({"input": fa3}).encode())
    sibling = json.loads(body)["id"]
    time.sleep(0.5)  # stall@1 holds the victim mid-flight
    code, body, _ = req("DELETE", f"/jobs/{victim}")
    assert code == 200 and json.loads(body)["cancelled"]
    assert core.wait(victim, 60) == "cancelled"
    assert core.job(victim).rc == exitcodes.RC_INTERRUPTED
    # cancelling again is a no-op conflict, not an error
    code, body, _ = req("DELETE", f"/jobs/{victim}")
    assert code == 409
    # blast radius: the sibling is untouched
    assert core.wait(sibling, 180) == "done"
    assert open(core.job(sibling).out_path, "rb").read() == ref3


# ---------- drain + restart resume ----------

@pytest.mark.slow  # ~31s: two full serve lifecycles; the CLI
# drain->resume pin (test_salvage.py::test_sigterm_drain_then_resume_
# byte_identical) and the fleet requeue-from-journal pin
# (test_serve_fleet.py::test_dead_replica_job_requeues_to_survivor)
# keep drain/resume tier-1 (r20 budget audit)
def test_drain_rc75_and_restart_resumes_byte_identical(corpus, tmp_path):
    _, _, fa8, ref8 = corpus
    spool = str(tmp_path / "spool")
    core = ServeCore(_cfg(), spool=spool, max_active=1)
    try:
        # inflight=1 bounds ingest-ahead to 4 holes, so a drain during
        # the stalled first dispatch leaves real work for the resume
        j = core.submit(input_path=fa8,
                        overrides={"faults": "stall@1", "inflight": 1})
        time.sleep(0.8)  # mid-flight inside the stalled dispatch
        rc = core.drain(timeout=120)
        assert rc == exitcodes.RC_INTERRUPTED
        job = core.job(j.id)
        assert job.state == "interrupted"
        assert job.rc == exitcodes.RC_INTERRUPTED
        # the drain settled a PARTIAL journal (the resume has work)
        done = json.loads(open(job.journal_path).read())["holes_done"]
        assert 0 < done < 8
    finally:
        core.close()
    # restart: the job requeues from state.json and resumes from its
    # journal to the byte-identical output
    core2 = ServeCore(_cfg(), spool=spool, max_active=1)
    try:
        assert core2.wait(j.id, 180) == "done"
        assert open(core2.job(j.id).out_path, "rb").read() == ref8
    finally:
        core2.close()


# ---------- per-job fault isolation ----------

def test_device_hang_degrades_only_the_faulted_job(corpus, served):
    fa3, ref3, _, _ = corpus
    core, req = served(max_active=2)
    # tenant A wedges its first dispatch; its own 1.5 s dispatch
    # deadline abandons the call and replays on the host rung
    bad = core.submit(input_path=fa3, overrides={
        "faults": "device_hang@1", "dispatch_deadline_s": 1.5})
    good = core.submit(input_path=fa3)
    assert core.wait(good.id, 180) == "done"
    assert core.wait(bad.id, 180) == "done", (bad.state, bad.error)
    # both byte-identical (the host path is the bit-exact spec)
    assert open(bad.out_path, "rb").read() == ref3
    assert open(good.out_path, "rb").read() == ref3
    # the fault domain: the hang + fallback booked ONLY in A
    snaps = core.job_snapshots()
    assert snaps[bad.id]["device_hangs"] >= 1
    assert snaps[bad.id]["host_fallbacks"] >= 1
    assert snaps[good.id]["device_hangs"] == 0
    assert snaps[good.id]["host_fallbacks"] == 0
    # the server stayed routable the whole time
    code, body, _ = req("GET", "/readyz")
    assert code == 200
    # and the per-job series carries the isolation story: the faulted
    # tenant's hang counter moved, the clean tenant's sits at 0
    code, body, _ = req("GET", "/metrics")
    text = body.decode()
    assert f'ccsx_job_device_hangs{{job="{good.id}"}} 0' in text
    bad_line = f'ccsx_job_device_hangs{{job="{bad.id}"}}'
    assert bad_line in text
    assert f"{bad_line} 0" not in text


# ---------- liveness/readiness split + schema ----------

def test_liveness_vs_readiness_split(served):
    core, req = served()
    code, body, _ = req("GET", "/healthz")
    assert code == 200 and json.loads(body)["status"] == "alive"
    code, body, _ = req("GET", "/readyz")
    assert code == 200 and json.loads(body)["ready"] is True
    assert core.drain(timeout=10) == exitcodes.RC_OK  # idle drain
    code, body, _ = req("GET", "/healthz")
    assert code == 200  # liveness survives the drain
    code, body, _ = req("GET", "/readyz")
    assert code == 503 and json.loads(body)["reason"] == "draining"
    code, body, _ = req("POST", "/jobs", b"{}")
    assert code == 503  # draining refuses new jobs


def test_job_prom_schema_matches_snapshot(corpus, core_factory):
    fa3, _, _, _ = corpus
    core = core_factory()
    j = core.submit(input_path=fa3)
    assert core.wait(j.id, 180) == "done"
    snap = core.job_snapshots()[j.id]
    # both directions: every schema key exists in a populated snapshot,
    # and the rendered series carries every family for this job
    missing = [k for k in (telemetry.JOB_PROM_COUNTERS
                           + telemetry.JOB_PROM_GAUGES) if k not in snap]
    assert not missing, f"schema keys absent from snapshot: {missing}"
    text = telemetry.render_job_series({j.id: snap})
    for key in telemetry.JOB_PROM_COUNTERS:
        assert f'ccsx_job_{key}{{job="{j.id}"}}' in text
    assert f'# TYPE ccsx_job_holes_out counter' in text


def test_queue_full_core_raises(corpus, core_factory, monkeypatch):
    fa3, _, _, _ = corpus
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "2")
    core = core_factory(max_active=1, max_queue=1)
    held = core.submit(input_path=fa3, overrides={"faults": "stall@1"})
    queued = core.submit(input_path=fa3)
    with pytest.raises(QueueFull):
        core.submit(input_path=fa3)
    # settle before teardown: close() must not rip the warm plane out
    # from under running job threads
    for j in (held, queued):
        core.wait(j.id, 180)
