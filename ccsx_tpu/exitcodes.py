"""Process exit-code taxonomy — THE reference for what a ccsx-tpu rc
means, pinned by tests/test_resilience.py and documented in README +
ARCHITECTURE.md "Failure domains" so the codes cannot drift silently.

Codes:

* ``RC_OK`` (0) — the run completed and the output is trustworthy.
  NOTE: rc 0 does NOT mean the run was incident-free — quarantined
  holes, OOM resplits, host fallbacks, abandoned (hung) dispatches, and
  an open circuit breaker all still exit 0, because the output bytes
  are correct either way (the host path is the bit-exact spec).  The
  *degradation* story rides Metrics/"degraded", /healthz (503), and
  the counters (holes_failed, device_hangs, breaker_trips, ...).
* ``RC_FATAL`` (1) — a designed, clean operational refusal or failure:
  invalid input stream, unwritable output/trace path, refused journal
  resume handled by recompute, refused merge (dead/mixed shards), bad
  flags, a shepherd rank exhausting its restart budget.
* ``RC_FAILED_HOLES`` (2) — the --max-failed-holes budget was
  exceeded: too many holes quarantined (or, under --salvage, lost to
  input corruption) for the output to be worth emitting as a
  "success" (the near-empty-FASTA-at-rc-0 trap).
* ``RC_INTERRUPTED`` (75, EX_TEMPFAIL) — a graceful drain: the run
  received SIGTERM/SIGINT, stopped admission, finished its in-flight
  groups, flushed the writer and settled the journal, then exited.
  The run is RESUMABLE: re-run the same command (with the same
  --journal) and it continues to a byte-identical output.  75 is
  sysexits' EX_TEMPFAIL ("temporary failure, retry"), which is
  exactly the contract.  ``ccsx-tpu serve`` reuses the code for a
  server drain with unfinished jobs (pipeline/serve.py): restarting
  the same command requeues them from <spool>/state.json and their
  per-job journals resume them byte-identically.
* ``RC_INJECTED_KILL`` (57) — a fault-injection hard exit
  (utils/faultinject.py write/journal/rank_death points, os._exit);
  distinctive so tests and operators can tell an injected kill from a
  real crash.  Mirrors faultinject.EXIT_CODE.
"""

from ccsx_tpu.utils.faultinject import EXIT_CODE as RC_INJECTED_KILL

RC_OK = 0
RC_FATAL = 1
RC_FAILED_HOLES = 2
RC_INTERRUPTED = 75

__all__ = ["RC_OK", "RC_FATAL", "RC_FAILED_HOLES", "RC_INTERRUPTED",
           "RC_INJECTED_KILL"]
