"""Elastic fleet plane: leased work-ranges, pull workers, rebalancing.

The static shepherd (pipeline/supervisor.py) freezes the reference's
work-stealing idea (kt_for's steal-on-idle, kthread.c:48-65) at launch
time: the input is carved into exactly ``--hosts`` shard ranges, so a
dead or slow rank strands its whole 1/N until an in-place restart
replays it.  This module lifts work-stealing to fleet scale by making
the SHARD-RANGE the unit of scheduling, not the rank:

* the raw-hole ordinal space is split into M >> N contiguous ranges
  (io/bamindex.py ``split_ranges``; the range table and its hash live
  in ``<out>.fleet/fleet.json``);
* each range is guarded by a crash-safe file lease: acquire is
  ``O_CREAT|O_EXCL`` (exactly one winner per free lease, kernel-
  arbitrated), renewal is a fully-fsynced atomic replace
  (utils/journal.py ``write_json_atomic``) bumping the heartbeat, and
  expiry is SCHEDULER-ONLY — SIGKILL the local holder first (the
  kill-before-steal invariant: no two writers may ever touch one
  range's shard files), then atomically rename the lease into the
  ``expired/`` graveyard so the range is re-acquirable;
* ranks are pull workers: acquire a lease, stream the range through
  the existing batched driver (per-range journal in the fleet dir, so
  a requeued range RESUMES from its predecessor's durable cursor
  rather than recomputing), retire it with an EXCLUSIVE range done
  marker (``write_json_exclusive`` — the second fence: even a zombie
  that survived expiry cannot double-commit), release, and pull the
  next;
* range outputs are ordinary ``<out>.shard<i>`` files whose idx mode
  header carries the range-table hash (``#mode=lease/<hash>``), so the
  final merge is the existing ``merge_shards(out, M)`` heap-restore —
  and a static/leased mix or a stale-table marker hits its loud
  refusals (parallel/distributed.py).

Why M >> N: a lost rank re-queues only its currently-leased range(s)
— bounded by M's granularity — instead of 1/N of the run, and a
straggler naturally takes fewer ranges while fast ranks take more;
with M == N the fleet degenerates to exactly the static shard split.

The scheduler half (lease expiry, worker supervision, mid-run --join,
merge) lives in pipeline/supervisor.py ``fleet_run``; this module is
everything a WORKER needs plus the lease/queue primitives both share.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import List, Optional, Tuple

from ccsx_tpu import exitcodes
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.parallel import distributed
from ccsx_tpu.utils import lease as leaselib
from ccsx_tpu.utils.journal import Journal, write_json_atomic
from ccsx_tpu.utils.metrics import Metrics

FLEET_STATE = "fleet.json"
GRAVEYARD = leaselib.GRAVEYARD


# ---------- fleet state (the range table) ----------

def fleet_dir_for(out_path: str) -> str:
    return out_path + ".fleet"


def table_hash(in_path: str, n_holes: int,
               ranges: List[Tuple[int, int]]) -> str:
    """Identity of ONE split of ONE input: any change to M, the hole
    count, or the input name yields a different hash, so markers and
    journals from a different split can never vouch for this run's
    bytes (short digest: it rides in every idx header)."""
    blob = json.dumps({"input": os.path.basename(in_path),
                       "n_holes": n_holes,
                       "ranges": [list(r) for r in ranges]},
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def init_fleet(d: str, in_path: str, out_path: str, n_holes: int,
               m: int, lease_timeout: float,
               forward_args: Optional[list] = None,
               cid: Optional[str] = None) -> dict:
    """Create (or re-open) the fleet directory and its state file.

    Re-opening requires an identical range table — a leftover fleet
    dir from a different split must be removed by the operator, not
    silently inherited (its journals and markers describe other
    ranges).  ``cid`` is the submitting job's correlation id: it rides
    the state file so every worker pulling a range of this fan-out —
    including sibling replicas helping — stamps its spans/metrics with
    the SAME id the gateway minted (deliberately outside the table
    hash: correlation is observability, not range identity)."""
    from ccsx_tpu.io import bamindex

    ranges = bamindex.split_ranges(n_holes, m)
    state = {"version": 1, "input": in_path, "output": out_path,
             "n_holes": n_holes, "ranges": [list(r) for r in ranges],
             "table": table_hash(in_path, n_holes, ranges),
             "lease_timeout": lease_timeout,
             "forward": list(forward_args or [])}
    if cid:
        state["cid"] = cid
    os.makedirs(os.path.join(d, GRAVEYARD), exist_ok=True)
    path = os.path.join(d, FLEET_STATE)
    if os.path.exists(path):
        prev = load_fleet(d)
        if prev is None or prev.get("table") != state["table"]:
            raise ValueError(
                f"fleet dir {d} holds state for a different range "
                "table; remove it (or merge/resume that run) before "
                "starting a new split")
        return prev   # resume: leases/journals/markers stay valid
    write_json_atomic(path, state)
    return state


def load_fleet(d: str) -> Optional[dict]:
    try:
        with open(os.path.join(d, FLEET_STATE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------- lease primitives ----------
#
# The state machine itself lives in utils/lease.py (factored out in
# PR 16 so serve jobs and shard ranges share one audited primitive);
# these wrappers pin the fleet plane's integer-keyed API and its
# on-disk layout (``lease.<i>``, owner records carrying ``range``)
# exactly as PR 13 shipped them.

def lease_path(d: str, i: int) -> str:
    return leaselib.lease_path(d, str(i))


def read_lease(d: str, i: int) -> Optional[dict]:
    """The lease's owner record, {} for a torn lease (crash between
    O_EXCL create and the owner write), None when free."""
    return leaselib.read_lease(d, str(i))


def try_acquire(d: str, i: int, worker: str,
                cid: Optional[str] = None) -> Optional[dict]:
    """Acquire lease i, or None if it is held.  ``O_CREAT|O_EXCL`` is
    the arbitration: of any number of racers the kernel admits exactly
    one, with no read-check-write window.  The owner record (worker,
    pid, heartbeat, the fan-out's correlation id when known) is
    fsynced into the fresh file; a SIGKILL between create and write
    leaves a TORN lease, which the scheduler ages by file mtime and
    expires like any stale one."""
    extra = {"range": i}
    if cid:
        extra["cid"] = cid
    return leaselib.try_acquire(d, str(i), worker, extra=extra,
                                kind="range")


def renew(d: str, i: int, rec: dict) -> bool:
    """Re-assert ownership by bumping the heartbeat.  Returns False —
    and the caller must STOP renewing — when the lease is gone or owned
    by someone else (the scheduler expired us).  The read-then-replace
    window is closed by the kill-before-steal invariant, not by this
    function: the scheduler SIGKILLs a local holder before renaming its
    lease away, so a holder that can still run this code has not been
    stolen from."""
    return leaselib.renew(d, str(i), rec)


def release(d: str, i: int, rec: dict) -> None:
    """Free the lease (after the done marker is durable, or on drain).
    Losing a steal race (FileNotFoundError) is fine — released is
    released."""
    leaselib.release(d, str(i), rec)


def steal_lease(d: str, i: int, cur: dict, kill: bool = True,
                seq: int = 0) -> Optional[dict]:
    """Scheduler-side eviction.  KILL-BEFORE-STEAL: the local holder is
    SIGKILLed before its lease is renamed away, so no two writers ever
    touch one range's shard files (a survivor that could still renew
    past our read would otherwise clobber the next owner).  The rename
    into the graveyard is atomic; losing the rename race means someone
    else already freed it — not an error."""
    return leaselib.steal_lease(d, str(i), cur, kill=kill, seq=seq)


def expire_lease(d: str, i: int, timeout_s: float, kill: bool = True,
                 seq: int = 0) -> Optional[dict]:
    """Expire lease i if its heartbeat is older than ``timeout_s``.
    Torn leases (no readable owner record) age by file mtime — a crash
    between acquire and owner-write must not pin the range forever.
    Returns the evicted owner record, or None when live/free."""
    return leaselib.expire_lease(d, str(i), timeout_s, kill=kill, seq=seq)


def reclaim_worker_leases(d: str, m: int, pid: int) -> List[int]:
    """Fast rebalance: a worker the scheduler KNOWS is dead (its child
    was just reaped) frees every lease it held immediately — no
    timeout wait, no kill needed.  This is what keeps a mid-run
    SIGKILL's cost at ~one range of recompute instead of a full
    lease-timeout stall."""
    freed = leaselib.reclaim_pid_leases(d, (str(i) for i in range(m)),
                                        pid)
    return [int(k) for k in freed]


def queue_state(d: str, out_path: str, m: int) -> dict:
    """One scan of the queue: done (range marker present), leased, and
    queued (free) counts — the scheduler's gauges and its termination
    test."""
    done = leased = 0
    for i in range(m):
        if os.path.exists(distributed.done_path(out_path, i)):
            done += 1
        elif os.path.exists(lease_path(d, i)):
            leased += 1
    return {"done": done, "leased": leased, "queued": m - done - leased}


# ---------- the per-range run (one leased range through the driver) ----

def _open_range_stream(in_path: str, cfg: CcsConfig, lo: int, hi: int,
                       metrics: Metrics):
    from ccsx_tpu.io import fastx
    from ccsx_tpu.io import zmw as zmw_mod
    from ccsx_tpu.pipeline.run import slice_raw_holes

    if cfg.is_bam:
        from ccsx_tpu.io import bamindex

        idx = bamindex.load_index(in_path)
        if idx is None:
            raise OSError("fleet runs over BAM require a fresh hole "
                          "index (ccsx-tpu --make-index); the sidecar "
                          "is missing or stale")

        def _count(nbytes, m=metrics):
            m.ingest_bytes += nbytes

        return zmw_mod.stream_zmws(
            bamindex.read_hole_range(
                in_path, idx, lo, hi, counter=_count,
                max_record_bytes=getattr(cfg, "max_record_bytes", 0)),
            cfg, metrics=metrics)
    f = open(in_path, "rb")
    return zmw_mod.stream_zmws(slice_raw_holes(fastx.read_fastx(f),
                                               lo, hi),
                               cfg, metrics=metrics)


def run_range(d: str, state: dict, cfg: CcsConfig, i: int,
              worker: str, inflight: Optional[int] = None,
              shared=None) -> int:
    """Stream range i through the batched driver into ``out.shard<i>``,
    exactly the per-rank flow of run_pipeline_sharded but with the
    range table as the sharding authority: M is the 'host count' the
    marker records, the idx header carries the table hash, and the
    per-range journal (fleet dir) pins range identity in its input_id
    so a requeued range resumes its predecessor's durable cursor.

    ``shared`` is the resident server's warm runtime (pipeline/serve.py
    ``_JobRuntime``): a serve replica running a fan-out range passes it
    so the range reuses the replica's compiled executables and fair
    admission window instead of cold-starting a tracer per range."""
    from ccsx_tpu.utils import blackbox, trace

    cid = state.get("cid")
    with trace.cid_scope(cid):
        # the inflight/done pair is what names this range in a
        # SIGKILLed worker's black-box dump; the done note rides a
        # finally so an exception cannot leave the range open in a
        # live worker's ring
        blackbox.note("inflight", what="range", id=i,
                      **({"cid": cid} if cid else {}))
        rc: Optional[int] = None
        try:
            rc = _run_range(d, state, cfg, i, worker,
                            inflight=inflight, shared=shared)
            return rc
        finally:
            blackbox.note("done", what="range", id=i,
                          **({"rc": rc} if rc is not None
                             else {"error": True}))


def _run_range(d: str, state: dict, cfg: CcsConfig, i: int,
               worker: str, inflight: Optional[int] = None,
               shared=None) -> int:
    from ccsx_tpu.pipeline.batch import drive_batched, mesh_precheck
    from ccsx_tpu.utils.device import resolve_device

    in_path, out_path = state["input"], state["output"]
    m, table = len(state["ranges"]), state["table"]
    lo, hi = state["ranges"][i]
    metrics = Metrics(verbose=cfg.verbose, stream=cfg.metrics_stream())
    metrics.holes_total = hi - lo
    metrics.cid = state.get("cid")
    try:
        stream = _open_range_stream(in_path, cfg, lo, hi, metrics)
    except (OSError, RuntimeError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        return 1
    resolve_device(cfg.device)
    if mesh_precheck(cfg):
        return 1
    # range identity in the journal's input_id: a lease journal can
    # only resume THIS range of THIS split (utils/fingerprint.py covers
    # the code/config side)
    mode_id = f"{in_path}#lease{i}/{m}@{table}"
    sp = distributed.shard_path(out_path, i)
    journal = Journal.for_run(os.path.join(d, f"journal.{i}"), mode_id,
                              cfg, sp, sp + ".idx")
    # retract any stale marker BEFORE the writer can truncate the shard
    # (same crash-window ordering as the static sharded driver); a
    # CURRENT-table marker never reaches here — the worker loop skips
    # retired ranges
    try:
        os.unlink(distributed.done_path(out_path, i))
    except OSError:
        pass
    try:
        writer = distributed.ShardWriter(
            out_path, i, m, append=bool(journal.holes_done),
            start_ordinal=lo, mode_header=f"#mode=lease/{table}\n")
    except OSError:
        print("Cannot open file for write!", file=sys.stderr)
        return 1
    rc = drive_batched(stream, writer, cfg, journal, metrics, inflight,
                       shared=shared)
    if rc == 0:
        committed = distributed._write_done_marker(
            out_path, i, m, journal.holes_done,
            extra={"table": table, "worker": worker,
                   "range": [lo, hi]},
            exclusive=True)
        if not committed:
            # the exclusive fence lost: someone else already retired
            # this range (a zombie outrun by its replacement) — their
            # marker vouches, ours must not overwrite it
            print(f"[ccsx-tpu] fleet: range {i} was already retired by "
                  "another worker; yielding to its marker",
                  file=sys.stderr)
    return rc


# ---------- the pull worker ----------

def _renewer(d: str, i: int, rec: dict, interval: float,
             stop: threading.Event) -> None:
    while not stop.wait(interval):
        if not renew(d, i, rec):
            return   # stolen: the scheduler killed-or-will-kill us


def run_fleet_worker(d: str, cfg: CcsConfig,
                     worker: Optional[str] = None,
                     inflight: Optional[int] = None,
                     poll_s: float = 0.5) -> int:
    """The pull loop: acquire a lease, run the range, retire, release,
    pull the next; exit 0 when every range has a done marker.

    SIGTERM/SIGINT between ranges (the outer DrainGuard here) or
    during one (drive_batched's inner guard) both land on rc 75 with
    the current lease RELEASED and its journal durable — a voluntary
    leave the scheduler treats as lease release, not failure.  Any
    other failure rc is returned as-is with the lease released; the
    range's journal lets the next owner resume."""
    from ccsx_tpu.utils.drain import DrainGuard

    state = load_fleet(d)
    if state is None:
        print(f"Error: {d} has no readable {FLEET_STATE} (start the "
              "fleet with `ccsx-tpu shepherd --fleet-ranges M`)",
              file=sys.stderr)
        return 1
    out_path = state["output"]
    m = len(state["ranges"])
    renew_s = max(0.05, float(state.get("lease_timeout", 10.0)) / 3.0)
    worker = worker or f"w{os.getpid()}"
    guard = DrainGuard.install()
    try:
        while True:
            progressed = False
            all_done = True
            for i in range(m):
                if guard.requested:
                    print(f"[ccsx-tpu] fleet worker {worker}: drained "
                          "between ranges (rc 75)", file=sys.stderr)
                    return exitcodes.RC_INTERRUPTED
                if os.path.exists(distributed.done_path(out_path, i)):
                    continue
                all_done = False
                try:
                    rec = try_acquire(d, i, worker,
                                      cid=state.get("cid"))
                except FileNotFoundError:
                    # the fleet dir vanished: the scheduler retired the
                    # whole queue, merged, and cleaned up while we were
                    # scanning — a joined worker outliving the primary.
                    # Nothing left to pull; that is success, not error.
                    print(f"[ccsx-tpu] fleet worker {worker}: fleet "
                          "completed and was cleaned up; exiting",
                          file=sys.stderr)
                    return 0
                if rec is None:
                    continue
                stop = threading.Event()
                t = threading.Thread(target=_renewer,
                                     args=(d, i, rec, renew_s, stop),
                                     daemon=True)
                t.start()
                try:
                    rc = run_range(d, state, cfg, i, worker,
                                   inflight=inflight)
                finally:
                    stop.set()
                    t.join(timeout=renew_s * 2)
                release(d, i, rec)
                if rc == exitcodes.RC_INTERRUPTED:
                    return rc   # drained mid-range: journal resumable
                if rc != 0:
                    return rc   # real failure: the scheduler decides
                progressed = True
            if all_done:
                return 0
            if not progressed:
                # everything is leased by someone else: idle-wait for a
                # range to free up (steal or retire), or for the end
                time.sleep(poll_s)
    finally:
        guard.restore()
