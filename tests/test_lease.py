"""utils/lease.py: the audited file-lease primitive, extracted from
pipeline/fleet.py in r16 so work-ranges (fleet) and serve jobs
(pipeline/gateway.py spool) are two instantiations of ONE state
machine.

The crash-consistency scenarios here are the PR 13 suite — 8-racer
single-winner acquire, torn-lease mtime expiry, expired-then-renewed
exactly-one-owner, foreign-release no-op, exclusive retirement — but
written as reusable checkers parameterized over the primitive's
callables (`LeaseOps`).  tests/test_fleet.py runs the SAME checkers
through fleet.py's integer-range wrappers, which is what makes the
r16 extraction provably behavior-preserving: one scenario body, both
key domains.
"""

import json
import os
import threading
import time

from ccsx_tpu.utils import lease as leaselib
from ccsx_tpu.utils.journal import write_json_atomic, write_json_exclusive


class LeaseOps:
    """The five primitive callables a lease domain must provide, plus
    the key spelling for that domain (string job-ids, integer ranges).

    Each callable has the utils/lease.py signature with the key as the
    second argument; GRAVEYARD is the eviction subdirectory name."""

    def __init__(self, *, path, read, acquire, renew, expire, release,
                 graveyard=leaselib.GRAVEYARD):
        self.path = path
        self.read = read
        self.acquire = acquire
        self.renew = renew
        self.expire = expire
        self.release = release
        self.graveyard = graveyard


LEASELIB_OPS = LeaseOps(
    path=leaselib.lease_path, read=leaselib.read_lease,
    acquire=leaselib.try_acquire, renew=leaselib.renew,
    expire=leaselib.expire_lease, release=leaselib.release)


# ---------- the shared scenario bodies ----------

def check_acquire_race_admits_exactly_one(ops, d, key, racers=8):
    """N threads race the kernel-arbitrated O_EXCL acquire: exactly one
    wins, and the surviving record names that winner."""
    wins = []
    barrier = threading.Barrier(racers)

    def racer(k):
        barrier.wait()
        if ops.acquire(d, key, f"w{k}") is not None:
            wins.append(k)

    ts = [threading.Thread(target=racer, args=(k,)) for k in range(racers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(wins) == 1
    rec = ops.read(d, key)
    assert rec["worker"] == f"w{wins[0]}"


def check_torn_lease_expires_by_mtime(ops, d, key):
    """SIGKILL between O_EXCL create and the owner write leaves an
    empty lease file: it must age by mtime, expire into the graveyard,
    and be re-acquired by exactly one of any number of racers."""
    open(ops.path(d, key), "w").close()         # the torn lease
    assert ops.read(d, key) == {}               # unreadable != free
    # young torn lease: NOT expirable (the owner may still be mid-write)
    assert ops.expire(d, key, timeout_s=60.0) is None
    old = time.time() - 120
    os.utime(ops.path(d, key), (old, old))
    assert ops.expire(d, key, timeout_s=60.0) == {}
    # the graveyard holds the evidence; the key is free again
    assert os.listdir(os.path.join(d, ops.graveyard))
    wins = [w for w in range(4)
            if ops.acquire(d, key, f"w{w}") is not None]
    assert len(wins) == 1


def check_expired_then_renewed_stays_owned(ops, d, key):
    """A renewal that lands before the scheduler's expiry check keeps
    the lease: expiry reads the HEARTBEAT, not the acquire time — and
    once evicted, the old owner's renew must FAIL (stop-renewing
    contract), freeing the key for exactly one re-acquirer."""
    rec = ops.acquire(d, key, "w0")
    # age the acquire time far past any timeout...
    write_json_atomic(ops.path(d, key),
                      dict(rec, acquired=time.time() - 999,
                           renewed=time.time() - 999))
    # ...then renew: the heartbeat bump must rescue it
    assert ops.renew(d, key, rec) is True
    assert ops.expire(d, key, timeout_s=60.0) is None
    # now let the heartbeat itself go stale: expiry evicts (kill=False:
    # the holder is this test process)
    write_json_atomic(ops.path(d, key),
                      dict(rec, renewed=time.time() - 999))
    evicted = ops.expire(d, key, timeout_s=60.0, kill=False)
    assert evicted is not None and evicted["worker"] == "w0"
    assert ops.renew(d, key, rec) is False
    wins = [w for w in range(4)
            if ops.acquire(d, key, f"w{w}") is not None]
    assert len(wins) == 1


def check_release_ignores_foreign(ops, d, key):
    rec = ops.acquire(d, key, "w0")
    ops.release(d, key, dict(rec, worker="imposter"))
    assert ops.read(d, key) is not None         # still held
    ops.release(d, key, rec)
    assert ops.read(d, key) is None


def check_exclusive_retirement_single_winner(marker_path):
    """The done-marker fence both domains retire through: os.link
    publication admits exactly one writer; the loser must observe the
    winner's record and yield (the zombie-replica double-emit guard)."""
    assert write_json_exclusive(marker_path, {"who": "first"}) is True
    assert write_json_exclusive(marker_path, {"who": "second"}) is False
    with open(marker_path) as f:
        assert json.load(f)["who"] == "first"


# ---------- utils/lease.py instantiation (string keys) ----------

def test_acquire_race_admits_exactly_one(tmp_path):
    check_acquire_race_admits_exactly_one(LEASELIB_OPS, str(tmp_path), "j00001")


def test_torn_lease_expires_by_mtime(tmp_path):
    check_torn_lease_expires_by_mtime(LEASELIB_OPS, str(tmp_path), "j00001")


def test_expired_then_renewed_stays_owned(tmp_path):
    check_expired_then_renewed_stays_owned(LEASELIB_OPS, str(tmp_path), "j00001")


def test_release_ignores_foreign(tmp_path):
    check_release_ignores_foreign(LEASELIB_OPS, str(tmp_path), "j00001")


def test_exclusive_retirement_single_winner(tmp_path):
    check_exclusive_retirement_single_winner(str(tmp_path / "done.j1.json"))


# ---------- string-domain specifics ----------

def test_acquire_record_carries_extra(tmp_path):
    d = str(tmp_path)
    rec = leaselib.try_acquire(d, "j00007", "replica-a",
                               extra={"port": 8851, "host": "h1"})
    assert rec["key"] == "j00007" and rec["pid"] == os.getpid()
    assert rec["port"] == 8851 and rec["host"] == "h1"
    on_disk = leaselib.read_lease(d, "j00007")
    assert on_disk == rec                       # fsynced before visible


def test_renew_merges_extra_and_bumps_heartbeat(tmp_path):
    d = str(tmp_path)
    rec = leaselib.try_acquire(d, "r0", "replica-a", extra={"ready": False})
    time.sleep(0.01)
    assert leaselib.renew(d, "r0", rec, extra={"ready": True}) is True
    got = leaselib.read_lease(d, "r0")
    assert got["ready"] is True
    assert got["renewed"] > rec["renewed"]


def test_reclaim_pid_leases_frees_only_that_pid(tmp_path):
    d = str(tmp_path)
    rec0 = leaselib.try_acquire(d, "j00001", "dead")
    rec2 = leaselib.try_acquire(d, "j00003", "dead")
    leaselib.try_acquire(d, "j00002", "alive")
    write_json_atomic(leaselib.lease_path(d, "j00001"), dict(rec0, pid=987654))
    write_json_atomic(leaselib.lease_path(d, "j00003"), dict(rec2, pid=987654))
    keys = ("j00001", "j00002", "j00003")
    assert leaselib.reclaim_pid_leases(d, keys, 987654) == ["j00001", "j00003"]
    assert leaselib.read_lease(d, "j00001") is None
    assert leaselib.read_lease(d, "j00002") is not None
    assert leaselib.read_lease(d, "j00003") is None


def test_list_leases_skips_tmp_and_filters_prefix(tmp_path):
    d = str(tmp_path)
    leaselib.try_acquire(d, "j00001", "a")
    leaselib.try_acquire(d, "r0", "b")
    # a mid-write renew tmp file must never surface as a lease
    open(os.path.join(d, "lease.r1.tmp"), "w").close()
    allk = dict(leaselib.list_leases(d))
    assert set(allk) == {"j00001", "r0"}
    slots = dict(leaselib.list_leases(d, prefix="r"))
    assert set(slots) == {"r0"}


def test_graveyard_names_collide_safely(tmp_path):
    """Repeated evictions of the same key must not clobber each other's
    graveyard evidence (the `~k` collision suffix)."""
    d = str(tmp_path)
    for seq in range(3):
        rec = leaselib.try_acquire(d, "j00001", f"w{seq}")
        write_json_atomic(leaselib.lease_path(d, "j00001"),
                          dict(rec, renewed=time.time() - 999))
        assert leaselib.expire_lease(d, "j00001", timeout_s=1.0,
                                     kill=False, seq=0) is not None
    assert len(os.listdir(os.path.join(d, leaselib.GRAVEYARD))) == 3
