"""Per-hole consensus entry points shared by the per-hole and batched
pipelines: one function selects the consensus generator for a ZMW
(windowed by default, whole-read star MSA under -P — main.c:701-704), so
the two pipelines cannot drift apart in prep or mode selection.
"""

from __future__ import annotations

from typing import Optional

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import prepare as prep
from ccsx_tpu.consensus.star import StarMsa, run_rounds
from ccsx_tpu.consensus.windowed import windowed_gen
from ccsx_tpu.ops import encode as enc


def _traced(gen, tag: str):
    """Wrap a consensus generator with the reference's -v level-2 logs
    ('poa begin/end' per hole, main.c:466-467,521-522,645-646)."""
    import sys

    print(f"[ccsx-tpu] consensus begin {tag}", file=sys.stderr)
    result = yield from gen
    print(f"[ccsx-tpu] consensus end {tag}", file=sys.stderr)
    return result


def _consensus_gen_for_passes(passes, zmw, cfg: CcsConfig):
    if cfg.split_subread:
        gen = windowed_gen(passes, cfg)
    else:
        sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
        gen = sm.consensus_gen(
            passes, cfg.refine_iters, cfg.pass_buckets, cfg.max_passes,
            quality=((cfg.qv_coeffs, cfg.qv_cap)
                     if cfg.emit_quality else None))
    if cfg.verbose >= 2:
        gen = _traced(gen, f"{zmw.movie}/{zmw.hole}")
    return gen


def consensus_gen_for_zmw(zmw, aligner, cfg: CcsConfig):
    """The consensus generator for one hole, or None if it is skipped.
    Prep runs synchronously here (per-pair dispatches via `aligner`); the
    batched pipeline uses full_gen_for_zmw instead."""
    passes = prep.oriented_passes(zmw, aligner, cfg)
    if passes is None:
        return None
    return _consensus_gen_for_passes(passes, zmw, cfg)


def full_gen_for_zmw(zmw, cfg: CcsConfig):
    """Combined prep + consensus generator for one hole.

    Yields prepare.PairRequest during the orientation walk, then
    star.RefineRequest during consensus (the driver dispatches on type,
    batching each across holes); returns the consensus codes (or None
    for a skipped hole) via StopIteration.value.
    """
    if zmw.n_passes < 3:  # main.c:460,515
        return None
    codes = enc.encode(zmw.seqs)
    segments = yield from prep.ccs_prepare_gen(codes, zmw.lens, zmw.offs,
                                               cfg)
    passes = prep.passes_from_segments(codes, segments, zmw, cfg)
    result = yield from _consensus_gen_for_passes(passes, zmw, cfg)
    return result


def _counted(gen, stats: dict):
    """Count the generator's device requests (one RefineRequest per
    window attempt) into stats['windows']."""
    try:
        req = next(gen)
        while True:
            stats["windows"] = stats.get("windows", 0) + 1
            rr = yield req
            req = gen.send(rr)
    except StopIteration as e:
        return e.value


def ccs_hole(zmw, aligner, cfg: CcsConfig,
             stats: Optional[dict] = None):
    """Per-hole path: run the hole's generator with immediate rounds.
    Returns (seq_bytes, qual_bytes|None) per encode.to_record, or None
    for a skipped hole.

    stats, if given, receives per-hole counters ('windows': window
    refinements run) so the driver can aggregate them thread-safely on
    its own side.
    """
    gen = consensus_gen_for_zmw(zmw, aligner, cfg)
    if gen is None:
        return None
    if stats is not None:
        gen = _counted(gen, stats)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    return enc.to_record(run_rounds(gen, sm))
