"""Batched device pre-alignment screen (the prefilter's scoring op).

The orientation walk's strand_match pairs are the long-template
regime's hidden cost: at >= 50kb, a wrong-strand pairing shares enough
CHANCE 13-mers (plus the micro-repeats that indel mutation leaves in
every pass) that the host seed gate's fixed ``min_votes=3`` passes it
essentially always — measured 28-30/30 at 50-100kb — and every such
pair then pays a full banded DP (~2.6-5.3s on XLA:CPU at 100kb) whose
acceptance is hopeless.  The pre-alignment accelerator lineage
(PAPERS.md: RASSA's sliding-window similarity filter, SeGraM's
minimizer seeding) puts a cheap batched filter in front of the DP; this
module is that filter for PairExecutor's waves.

One dispatch screens a whole (qmax, tmax) bucket of pairs: the device
computes, per pair, EXACTLY the quantities the host seed gate reads —
the capped k-mer hit total and the best 2-bin diagonal-window vote
count of ops/seed.seed_diagonal (bit-equal by construction: same codes,
same stable sort, same searchsorted join, same MAX_HITS_PER_KMER cap
taking the first hits in sorted order, same DIAG_BIN histogram and
adjacent-bin pairing) — and the host applies the rejection rules below.

A note on the design space: a pure per-sequence profile sketch
(k-mer/minimizer count vectors scored by one cosine/intersection
matmul, the RASSA shape) was prototyped first and rejected: with D
hashable buckets the collision floor of the intersection bound is
Q*T/D, which at DNA scale (Q=T=100k, any practical D) is orders of
magnitude above every useful threshold, and an UNbucketed profile needs
4^13 slots.  Position-blind profiles cannot screen long DNA pairs; the
diagonal-windowed hit count — the same statistic the reference's k-mer
seeding trusts (main.c:264) — is the cheapest sketch that can.

Rejection rules (``reject_reason``), applied to the screen triple
(total, votes, best window):

(a) **Seed-gate parity** (provable): ``votes < MIN_VOTES`` or
    ``total == 0``.  seed_diagonal returns None for exactly these
    pairs, and the spec aligner (align_host.HostAligner.strand_match)
    returns ok=False without running the DP.  Rejecting them here is
    behavior-identical to today, just batched and off the host.

(b) **Noise gate** (statistical, margin-analyzed): ``votes <
    min(qlen, tlen) >> NOISE_GATE_SHIFT``.  An acceptance-eligible pair
    must put >= pct% matches inside the DP band, and the band holds the
    path within ~±64 diagonals of the seeded line (the offset tracker
    advances monotonically at <= maxshift/row around a slope-1 line, so
    a path drifting further exits the band — see the conservativeness
    note in ARCHITECTURE.md).  At the 75%-identity acceptance floor
    with independent errors that implies an expected
    (0.75)^13 * pct/200 * min(Q,T) ~ min(Q,T)/60 k-mer hits
    concentrated in a handful of diagonal windows — >= 8x above this
    gate at min(Q,T)/512 — while measured wrong-strand noise votes stay
    <= ~10 even at 100kb (~min/10000).  The gate deliberately
    degenerates to rule (a) below min(Q,T) = 4 * 512 = 2048, so short
    pairs (the pinned 64-hole scale config's regime) see the exact
    legacy gate.  Not information-theoretically provable — a
    worst-case 3-match-1-error pattern hides from every 13-mer
    statistic (q-gram lemma: k <= pct/(100-pct) would be needed) — but
    that adversary is ALREADY false-rejected by today's min_votes=3
    gate, so the gate introduces no new failure class; the filter-
    oracle fuzz sweep (tests/test_sketch.py) force-aligns every
    rejected pair and pins false rejects at 0, and the scale-config
    md5 is pinned prefilter on == off.

(c) **Band-overlap impossibility** (provable): when the seeded line
    would be used (|diag| > band/4), acceptance needs
    mat > min(Q,T)*pct/200 matched bases, every one inside the band
    around that line.  The band reaches at most
    ``overlap(d) = min(tlen, qlen - d)`` columns above a positive
    diagonal (the offset tracker is bounded by the line), plus — for
    negative diagonals — the crawl phase (offset starts at 0 and
    catches the line at maxshift/row, one match per row) and the
    boundary fringes.  If even the most generous bound cannot reach
    the acceptance floor, the DP cannot accept; rejecting costs
    nothing and is exact.

All three rules only ever reject pairs whose (ok, MatchResult) would
come back ok=False, and the walk discards the MatchResult payload of a
failed pair — so output bytes are invariant to the filter firing
(pinned by the 64-hole scale config md5 with --prefilter on/off/both
crossovers, tests/test_sketch.py + benchmarks).

``screen_host`` is the NumPy twin: the recovery ladder's host-replay
rung for a failed screen dispatch, and the differential-fuzz oracle.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

from ccsx_tpu.ops import seed as seed_mod

K = seed_mod.DEFAULT_K
MIN_VOTES = 3              # seed_diagonal's default gate
MAX_HITS = seed_mod.MAX_HITS_PER_KMER
DIAG_BIN = seed_mod.DIAG_BIN
SENTINEL = np.int32(1) << np.int32(2 * K)   # 4^13 fits int32
# noise gate: votes < min(qlen, tlen) >> NOISE_GATE_SHIFT (rule (b));
# identical to the legacy gate below min(Q,T) = MIN_VOTES << SHIFT
NOISE_GATE_SHIFT = 9
# screening floor: below min(Q, T) = (MIN_VOTES + 1) << NOISE_GATE_SHIFT
# rule (b) degenerates to the legacy seed gate, which host seeding
# applies anyway — screening such a pair spends a device row to learn
# nothing, so PairExecutor only screens (and the walk only speculates
# fwd+RC, prepare.PairBatch) at or above this length
SCREEN_MIN_QT = (MIN_VOTES + 1) << NOISE_GATE_SHIFT   # 2048
# fwd+RC speculation floor (prepare.PairBatch): a speculated WRONG arm
# must die in the screen or speculation pays a whole extra DP.  The
# noise gate's threshold is min(Q,T) >> 9 while measured wrong-strand
# noise stays ~<= 10-30 votes, so the margin is only decisive a few
# octaves above SCREEN_MIN_QT — at 16384 the gate wants >= 32 votes, ~3x
# the noise ceiling.  (Speculation is additionally restricted to
# IN-GROUP passes: an out-of-group read-through contains BOTH strands,
# so both its arms genuinely accept and even a perfect screen cannot
# save the second DP — measured 8kb A/B, benchmarks/long_molecule.py.)
SPECULATE_MIN_QT = 16384
# band-geometry slack for rule (c): covers the DIAG_BIN-resolution
# diagonal estimate vs the median the DP line would use (±64), the
# early/tail boundary fringes (~2 bands), and the offset tracker's
# maxshift catch-up — generous by design, the rule fires on
# order-min(Q,T)/8 margins
BAND_SLACK = 8 * 128
_MAXSHIFT = 4              # banded_align default, pinned by the fill


# ---- rejection rules (host-side ints; shared by the device screen's
# ---- finish path and the host twin) ---------------------------------------


def noise_gate(qlen: int, tlen: int) -> int:
    """The vote threshold of rules (a)+(b) for a (qlen, tlen) pair."""
    return max(MIN_VOTES, min(qlen, tlen) >> NOISE_GATE_SHIFT)


def _mat_upper_bound(diag: int, qlen: int, tlen: int) -> int:
    """Provable upper bound on matched bases the banded local DP can
    produce with its band following a slope-1 line on ``diag`` (rule
    (c)); see the module docstring for the geometry."""
    overlap = max(0, min(qlen - diag, tlen) - max(-diag, 0))
    bound = overlap + BAND_SLACK
    if diag < 0:
        # crawl phase: the band offset starts at 0 and closes on the
        # line at <= maxshift cols/row; one match per crawl row, and
        # the crawl spans at most |diag|/(maxshift-1) rows (the line
        # advances 1/row) and at most tlen/maxshift columns
        bound += min((-diag) // (_MAXSHIFT - 1),
                     min(qlen, tlen) // _MAXSHIFT) + _MAXSHIFT
    return bound


def reject_from_hit(hit, qlen: int, tlen: int, pct: int,
                    band: int) -> str:
    """'' (keep) or the rejection rule that fires for an already-seeded
    pair (a seed.SeedHit) — the ZERO-DISPATCH form of the filter, used
    below the device-screen floor where the seeding computation already
    holds every statistic the rules read.  hit.votes is the same best
    2-bin window count the screen computes, and hit.diag is the MEDIAN
    diagonal — the exact line the DP would run on, so rule (c) here is
    evaluated at the true line rather than the window edge (at least as
    conservative).  ``hit is None`` is rule (a) and handled by the
    caller exactly as today."""
    if hit.votes < noise_gate(qlen, tlen):
        return "noise_gate"         # rule (b): statistical
    if abs(int(hit.diag)) <= band // 4:
        return ""                   # corner-line case: full overlap
    minqt = min(qlen, tlen)
    if _mat_upper_bound(int(hit.diag), qlen, tlen) * 200 <= minqt * pct:
        return "band_overlap"       # rule (c): provable geometry
    return ""


def reject_reason(total: int, votes: int, win_lo: int, qlen: int,
                  tlen: int, pct: int, band: int) -> str:
    """'' (keep) or the rejection rule that fired for a screen triple.

    ``win_lo`` is the lower diagonal edge of the best 2-bin window (the
    window spans [win_lo, win_lo + 2*DIAG_BIN)).
    """
    if total <= 0 or votes < MIN_VOTES:
        return "seed_gate"          # rule (a): host parity, provable
    if votes < noise_gate(qlen, tlen):
        return "noise_gate"         # rule (b): statistical
    # rule (c): only when the DP would run on the hinted line — the
    # near-diagonal corner-line case has full overlap by construction.
    # Evaluate at the window's |d|-minimal edge: the bound is monotone
    # against |d|, so this is the most permissive diagonal the median
    # could land on (plus BAND_SLACK for the resolution gap).
    win_hi = win_lo + 2 * DIAG_BIN - 1
    d_best = min(max(0, win_lo), win_hi) if win_lo <= 0 <= win_hi \
        else (win_lo if win_lo > 0 else win_hi)
    if abs(d_best) <= band // 4:
        return ""
    minqt = min(qlen, tlen)
    # acceptance => aln*2 > minqt and mat*100 >= aln*pct
    #            => mat*200 > minqt*pct
    if _mat_upper_bound(int(d_best), qlen, tlen) * 200 <= minqt * pct:
        return "band_overlap"       # rule (c): provable geometry
    return ""


# ---- host twin -------------------------------------------------------------


def screen_host(q: np.ndarray, t: np.ndarray,
                t_index=None) -> Tuple[int, int, int]:
    """(total, votes, win_lo) for one pair, NumPy — the same counting
    path as seed_diagonal up to (and excluding) the median/line step.
    The recovery ladder's host rung and the device screen's oracle
    (pinned bit-equal by tests/test_sketch.py)."""
    qk = seed_mod.kmer_codes(q)
    if t_index is None:
        t_index = seed_mod.sorted_kmer_index(t)
    tks, order = t_index
    if len(qk) == 0 or len(tks) == 0:
        return (0, 0, 0)
    left = np.searchsorted(tks, qk, side="left")
    right = np.searchsorted(tks, qk, side="right")
    cnt = np.minimum(right - left, MAX_HITS)
    cnt[qk < 0] = 0
    total = int(cnt.sum())
    if total == 0:
        return (0, 0, 0)
    qpos = np.repeat(np.arange(len(qk)), cnt)
    starts = np.repeat(left, cnt)
    run_ids = np.repeat(np.cumsum(cnt) - cnt, cnt)
    offs = np.arange(total) - run_ids
    diags = qpos - order[starts + offs]
    lo = -len(t)
    nbins = (len(q) + len(t)) // DIAG_BIN + 2
    hist = np.bincount((diags - lo) // DIAG_BIN, minlength=nbins)
    paired = hist[:-1] + hist[1:]
    best = int(np.argmax(paired))
    return (total, int(paired[best]), best * DIAG_BIN + lo)


# ---- device screen ---------------------------------------------------------


def _codes_dev(seq, k: int):
    """Device twin of seed.kmer_codes on a PADDED code array: windows
    touching an N (code 4) or the PAD byte (5) come back -1, which
    covers the padded tail for free (PAD >= 4)."""
    import jax
    import jax.numpy as jnp

    n = seq.shape[0] - k + 1
    s = seq.astype(jnp.int32)
    code = jnp.zeros((n,), jnp.int32)
    bad = jnp.zeros((n,), bool)
    for i in range(k):
        w = jax.lax.dynamic_slice(s, (i,), (n,))
        code = (code << 2) | (w & 3)
        bad = bad | (w >= 4)
    return jnp.where(bad, -1, code)


def _t_index_dev(t):
    """Device twin of seed.sorted_kmer_index: bad/pad codes share the
    tail sentinel (their relative order is irrelevant — valid q codes
    never reach them), real codes keep the host's stable position
    order."""
    import jax.numpy as jnp

    tk = _codes_dev(t, K)
    vals = jnp.where(tk < 0, jnp.int32(SENTINEL), tk)
    order = jnp.argsort(vals, stable=True).astype(jnp.int32)
    return vals[order], order


def _hits_dev(q, t, qlen, tlen):
    """The shared capped-hit machinery: returns (cnt (Qn,), left,
    order, qpos, total) exactly as the host computes them.  Positions
    beyond qlen-K are bad by padding; tlen is unused beyond what the
    pad already encodes but kept for clarity."""
    import jax.numpy as jnp

    del tlen
    qk = _codes_dev(q, K)
    tks, order = _t_index_dev(t)
    left = jnp.searchsorted(tks, qk, side="left").astype(jnp.int32)
    right = jnp.searchsorted(tks, qk, side="right").astype(jnp.int32)
    cnt = jnp.minimum(right - left, MAX_HITS)
    cnt = jnp.where(qk < 0, 0, cnt)
    del qlen
    return cnt, left, order, jnp.arange(cnt.shape[0], dtype=jnp.int32)


def _diag_hist_dev(cnt, left, order, qpos, qlen, tlen, nb: int):
    """(hist (nb,), diags (Qn, MAX_HITS), inhit mask): the DIAG_BIN
    histogram over capped hits, host-bit-equal.  ``nb`` is the static
    bin budget >= any runtime (qlen+tlen)//DIAG_BIN + 2; bins beyond
    the runtime range stay zero, so argmax is unaffected."""
    import jax.numpy as jnp

    Tn = order.shape[0]
    lo = -tlen
    hist = jnp.zeros((nb + 1,), jnp.int32)
    diags_all = []
    mask_all = []
    for j in range(MAX_HITS):
        ok = j < cnt
        tpos = order[jnp.clip(left + j, 0, Tn - 1)]
        dj = qpos - tpos
        b = jnp.where(ok, (dj - lo) // DIAG_BIN, nb)
        hist = hist.at[b].add(1)
        diags_all.append(dj)
        mask_all.append(ok)
    del qlen
    return (hist[:nb], jnp.stack(diags_all, 1), jnp.stack(mask_all, 1),
            lo)


@functools.lru_cache(maxsize=32)
def screen_step(qmax: int, tmax: int):
    """Jitted batched screen: (N, qmax+tmax) uint8 codes + (N, 2) int32
    lengths -> (N, 3) int32 (total, votes, win_lo).  One dispatch
    scores a whole bucket of candidate pairings; PairExecutor routes it
    through the shared recovery ladder (host rung = screen_host)."""
    import jax
    import jax.numpy as jnp

    nb = (qmax + tmax) // DIAG_BIN + 2

    def one(row, lens):
        q = row[:qmax]
        t = row[qmax:]
        qlen, tlen = lens[0], lens[1]
        cnt, left, order, qpos = _hits_dev(q, t, qlen, tlen)
        total = cnt.sum()
        hist, _, _, lo = _diag_hist_dev(cnt, left, order, qpos,
                                        qlen, tlen, nb)
        paired = hist[:-1] + hist[1:]
        best = jnp.argmax(paired).astype(jnp.int32)
        votes = paired[best]
        win_lo = best * DIAG_BIN + lo
        empty = total == 0
        return jnp.stack([total,
                          jnp.where(empty, 0, votes),
                          jnp.where(empty, 0, win_lo)])

    return jax.jit(jax.vmap(one))
