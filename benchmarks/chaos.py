"""Chaos soak harness: randomized fault schedules, byte-identity oracle.

The resilience layer's whole claim is "a fault changes WHERE a request
computes, never WHAT it computes" — every recovery rung lands on the
bit-exact host spec, kills resume from the journal, and a shepherded
rank restart merges to the unsharded bytes.  That claim is only worth
anything under composition, so this harness drives RANDOMIZED fault
schedules end-to-end and asserts byte-identity against the fault-free
run for every trial:

* **In-process faults** (`device_oom`, `device_oom` storms, `stall`,
  `device_hang` — the latter under ``--dispatch-deadline``): armed via
  utils/faultinject.py at a seeded random call index, run through the
  full CLI, output compared byte-for-byte.
* **Kill/resume faults** (`write`, `journal`): the CLI runs in a
  subprocess, dies at the injected os._exit(57), and a clean resume
  must complete byte-identical with no duplicated or dropped holes.
* **Shepherd trials** (`rank_death`): a sharded run under
  `ccsx-tpu shepherd` with one rank SIGKILLed at a seeded retirement;
  the supervisor restarts it and the merged output must equal the
  unsharded run's bytes.

* **Input-plane faults** (`disk_full`, `input_corrupt`): an injected
  ENOSPC must exit through the clean rc-1 path with the journal
  consistent and resume byte-identical; an injected classified
  corruption under ``--salvage`` must complete rc 0 degraded with the
  byte-identity oracle restricted to UNDAMAGED holes (the salvage
  contract; real crafted-byte corruption is the corruption fuzzer's
  domain, benchmarks/corrupt.py).

Schedules are pure functions of ``--seed``, so any red trial is
replayable exactly.  Deliberately NOT injected here: ``compute`` and
``ingest`` faults — they are *designed* to change the output
(quarantine a hole / abort the run), so byte-identity is the wrong
oracle for them; tests/test_faults.py pins their contracts instead.

The fast deterministic slice of this harness runs in tier-1
(tests/test_chaos.py, `make chaos`); the full soak is the `slow` mark
and this CLI:

    python benchmarks/chaos.py --seed 0 --trials 12 --holes 6 \
        --json benchmarks/chaos_rNN.json

Fleet-membership churn (rank SIGKILL under the ELASTIC scheduler,
mid-run --join, SIGTERM drain, stragglers) is the fleet soak's domain
— benchmarks/fleet.py reuses this harness's corpus builder, reference
runner, and byte-identity oracle (`make fleet-chaos`).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.utils import faultinject, synth                # noqa: E402

# the same backend-pinning runner idiom as tests/test_faults.py — the
# kill trials must die in their OWN process
_RUNNER = ("import sys, jax; jax.config.update('jax_platforms', 'cpu'); "
           "from ccsx_tpu.cli import main; sys.exit(main(sys.argv[1:]))")

# in-process fault menu: (name, spec-template, extra CLI args).  The
# call index N is drawn per trial from the seeded rng.
INPROC_FAULTS = (
    ("device_oom", "device_oom@{n}", ()),
    ("device_oom_storm", "device_oom@{n}+", ()),
    ("stall", "stall@{n}", ("--stall-timeout", "0.2")),
    ("device_hang", "device_hang@{n}", ("--dispatch-deadline", "2")),
)
KILL_FAULTS = ("write", "journal")


def make_corpus(tmp: str, rng, holes: int, tlen: int = 700,
                n_passes: int = 5) -> str:
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=n_passes,
                         movie="mv", hole=str(100 + h))
          for h in range(holes)]
    p = os.path.join(tmp, "in.fa")
    with open(p, "w") as f:
        f.write(synth.make_fasta(zs))
    return p


def _base_args(in_fa: str, out: str, extra=()) -> list:
    return ["-A", "-m", "1000", "--batch", "on", *extra, in_fa, out]


def run_reference(in_fa: str, tmp: str) -> bytes:
    ref = os.path.join(tmp, "ref.fa")
    rc = cli.main(_base_args(in_fa, ref))
    assert rc == 0, f"fault-free reference run failed rc={rc}"
    return open(ref, "rb").read()


def trial_inproc(in_fa: str, tmp: str, ref: bytes, name: str,
                 spec: str, extra) -> dict:
    out = os.path.join(tmp, f"o_{name}.fa")
    m = os.path.join(tmp, f"m_{name}.jsonl")
    faultinject.arm(spec)
    try:
        rc = cli.main(_base_args(in_fa, out,
                                 (*extra, "--metrics", m)))
    finally:
        faultinject.disarm()
    got = open(out, "rb").read() if os.path.exists(out) else b""
    final = {}
    try:
        final = [json.loads(line) for line in open(m)][-1]
    except (OSError, IndexError, ValueError):
        pass
    return {"kind": name, "spec": spec, "rc": rc,
            "identical": got == ref,
            "ok": rc == 0 and got == ref,
            "counters": {k: final.get(k) for k in
                         ("device_hangs", "oom_resplits",
                          "host_fallbacks", "breaker_trips", "stalls")},
            "degraded": bool(final.get("degraded"))}


def trial_kill_resume(in_fa: str, tmp: str, ref: bytes, point: str,
                      n: int) -> dict:
    """Subprocess dies at the injected os._exit; the resume must finish
    byte-identical (journal v2 torn-tail contract)."""
    out = os.path.join(tmp, f"o_kill_{point}.fa")
    jp = os.path.join(tmp, f"j_{point}.json")
    args = _base_args(in_fa, out, ("--journal", jp))
    env = dict(os.environ, JAX_PLATFORMS="cpu", CCSX_SKIP_PROBE="1",
               XLA_FLAGS="", CCSX_FAULTS=f"{point}@{n}",
               CCSX_JOURNAL_FSYNC_S="0")
    r = subprocess.run([sys.executable, "-c", _RUNNER, *args], env=env,
                       cwd=_REPO, capture_output=True, text=True,
                       timeout=600)
    killed = r.returncode == faultinject.EXIT_CODE
    rc = cli.main(args)   # resume, no faults
    got = open(out, "rb").read() if os.path.exists(out) else b""
    return {"kind": f"kill_{point}", "spec": f"{point}@{n}",
            "killed_rc": r.returncode, "resume_rc": rc,
            "identical": got == ref,
            "ok": killed and rc == 0 and got == ref}


def trial_disk_full_resume(in_fa: str, tmp: str, ref: bytes,
                           n: int) -> dict:
    """ENOSPC (injected OSError in the synchronous writer) must exit
    through the clean rc-1 path with the journal consistent; the
    resume must complete byte-identical — the disk-full reality of
    long runs on shared scratch."""
    out = os.path.join(tmp, "o_diskfull.fa")
    jp = os.path.join(tmp, "j_diskfull.json")
    args = _base_args(in_fa, out, ("--journal", jp))
    os.environ["CCSX_JOURNAL_FSYNC_S"] = "0"
    try:
        faultinject.arm(f"disk_full@{n}")
        rc1 = cli.main(args)
        faultinject.disarm()
        rc2 = cli.main(args)   # disk "freed": resume, no faults
    finally:
        faultinject.disarm()
        os.environ.pop("CCSX_JOURNAL_FSYNC_S", None)
    got = open(out, "rb").read() if os.path.exists(out) else b""
    return {"kind": "disk_full_resume", "spec": f"disk_full@{n}",
            "enospc_rc": rc1, "resume_rc": rc2,
            "identical": got == ref,
            "ok": rc1 == 1 and rc2 == 0 and got == ref}


def trial_input_corrupt(in_fa: str, tmp: str, ref: bytes,
                        n: int) -> dict:
    """An injected classified corruption at the Nth ingested hole with
    --salvage: the run must complete rc 0 degraded with exactly that
    hole dropped — the byte-identity oracle restricted to UNDAMAGED
    holes (the salvage contract, io/corruption.py)."""
    out = os.path.join(tmp, "o_incorrupt.fa")
    m = os.path.join(tmp, "m_incorrupt.jsonl")
    faultinject.arm(f"input_corrupt@{n}")
    try:
        rc = cli.main(_base_args(in_fa, out,
                                 ("--salvage", "--metrics", m)))
    finally:
        faultinject.disarm()
    got = open(out, "rb").read() if os.path.exists(out) else b""
    # undamaged-holes oracle: every emitted record must be byte-equal
    # to its clean-run twin, and exactly one hole (the injected one)
    # may be missing
    def _by_hole(b):
        return {c.split("\n", 1)[0]: c
                for c in b.decode(errors="replace").split(">")[1:]}
    r, s = _by_hole(ref), _by_hole(got)
    sub_ok = all(s.get(k) == v for k, v in r.items() if k in s)
    final = {}
    try:
        final = [json.loads(line) for line in open(m)][-1]
    except (OSError, IndexError, ValueError):
        pass
    return {"kind": "input_corrupt", "spec": f"input_corrupt@{n}",
            "rc": rc, "holes_corrupt": final.get("holes_corrupt"),
            "degraded": bool(final.get("degraded")),
            "ok": (rc == 0 and len(s) == len(r) - 1 and sub_ok
                   and final.get("holes_corrupt") == 1
                   and bool(final.get("degraded")))}


def trial_shepherd_rank_death(in_fa: str, tmp: str, ref: bytes,
                              hosts: int, dead_rank: int,
                              n: int) -> dict:
    """A shepherded sharded run with one rank SIGKILLed at its Nth
    retirement: the supervisor restarts it (journal resume) and the
    merged output must equal the unsharded reference bytes."""
    from ccsx_tpu.pipeline.supervisor import shepherd_run

    out = os.path.join(tmp, "shep.fa")
    fwd = ["-A", "-m", "1000", "--hosts", str(hosts), in_fa, out]
    rc = shepherd_run(
        in_fa, out, hosts, fwd,
        max_restarts=2, backoff_s=0.1, poll_s=0.1,
        env=dict(os.environ, CCSX_JOURNAL_FSYNC_S="0"),
        first_launch_env={dead_rank: {
            "CCSX_FAULTS": f"rank_death@{n}"}})
    got = open(out, "rb").read() if os.path.exists(out) else b""
    return {"kind": "shepherd_rank_death",
            "spec": f"rank{dead_rank}:rank_death@{n}",
            "rc": rc, "identical": got == ref,
            "ok": rc == 0 and got == ref}


def run_trials(seed: int, trials: int, holes: int,
               include_kills: bool = True,
               include_shepherd: bool = True,
               include_input: bool = True,
               max_call: int = 4, tmp: str = None) -> dict:
    """The soak driver: ``trials`` seeded in-process fault trials plus
    (optionally) one kill/resume trial per kill point, one shepherd
    rank-death trial, and the input-plane trials (disk_full ENOSPC +
    resume; input_corrupt under --salvage with the undamaged-holes
    oracle).  Returns the summary dict; ``summary["ok"]`` is the
    one-bit verdict (every trial byte-identical / contract-clean)."""
    # unit-scale hang budgets unless the caller already chose: grace x1
    # (the chaos corpus compiles in seconds on CPU — 10x grace would
    # make every first-of-shape device_hang trial a ~20 s wait) and a
    # bounded hang sleep so abandoned daemon threads don't hold the
    # dispatch closures for an hour of soak
    os.environ.setdefault("CCSX_DEADLINE_GRACE", "1")
    os.environ.setdefault("CCSX_FAULT_HANG_S", "60")
    os.environ.setdefault("CCSX_FAULT_STALL_S", "0.3")
    rng = np.random.default_rng(seed)
    own_tmp = tmp is None
    tmp = tmp or tempfile.mkdtemp(prefix="ccsx_chaos_")
    t0 = time.monotonic()
    results = []
    try:
        in_fa = make_corpus(tmp, rng, holes)
        ref = run_reference(in_fa, tmp)
        for t in range(trials):
            name, spec_t, extra = INPROC_FAULTS[
                int(rng.integers(len(INPROC_FAULTS)))]
            n = int(rng.integers(1, max_call + 1))
            results.append(trial_inproc(in_fa, tmp, ref, name,
                                        spec_t.format(n=n), extra))
        if include_kills:
            for point in KILL_FAULTS:
                results.append(trial_kill_resume(
                    in_fa, tmp, ref, point,
                    int(rng.integers(1, max(holes, 2)))))
        if include_input:
            # the input failure domain mixed into the same soak: a
            # disk-full abort + resume, and an injected classified
            # corruption salvaged mid-run
            results.append(trial_disk_full_resume(
                in_fa, tmp, ref, int(rng.integers(1, max(holes, 2)))))
            results.append(trial_input_corrupt(
                in_fa, tmp, ref, int(rng.integers(1, holes + 1))))
        if include_shepherd:
            results.append(trial_shepherd_rank_death(
                in_fa, tmp, ref, hosts=2, dead_rank=1,
                n=int(rng.integers(1, max(holes // 2, 2)))))
    finally:
        if own_tmp:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    bad = [r for r in results if not r["ok"]]
    return {"seed": seed, "holes": holes, "trials": results,
            "n_trials": len(results), "n_failed": len(bad),
            "ok": not bad,
            "elapsed_s": round(time.monotonic() - t0, 1)}


def main():
    ap = argparse.ArgumentParser(
        description="Chaos soak: randomized fault schedules, "
                    "byte-identity oracle (seeded, replayable)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trials", type=int, default=12,
                    help="in-process fault trials [12]")
    ap.add_argument("--holes", type=int, default=6)
    ap.add_argument("--no-kills", action="store_true",
                    help="skip the subprocess kill/resume trials")
    ap.add_argument("--no-shepherd", action="store_true",
                    help="skip the shepherd rank-death trial")
    ap.add_argument("--no-input", action="store_true",
                    help="skip the input-plane trials (disk_full, "
                         "input_corrupt)")
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    summary = run_trials(a.seed, a.trials, a.holes,
                         include_kills=not a.no_kills,
                         include_shepherd=not a.no_shepherd,
                         include_input=not a.no_input)
    print(json.dumps(summary, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
