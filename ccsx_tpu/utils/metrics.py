"""Counters and structured logging.

The reference has no observability beyond -v stderr prints (SURVEY.md §5.5);
this is the framework's replacement: cheap counters, a ZMWs/sec rate (the
north-star metric, BASELINE.md), and optional JSON-lines emission.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import sys
import threading
import time
from typing import Optional, TextIO


def resource_gauges() -> dict:
    """Peak host RSS + per-device live-buffer bytes, best effort (0 when
    unknown) — the OOM-ladder postmortems previously had no memory
    signal at all.  Stamped on the metrics "final" event and served
    live by /metrics (utils/telemetry.py).  Never *imports* jax: a
    process that avoided backend init (stats/top/report subcommands on
    a host whose accelerator is hung) must stay backend-free."""
    peak = 0
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS
        if sys.platform != "darwin":
            peak *= 1024
    except (ImportError, OSError, ValueError):
        peak = 0
    dev = 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                if stats:
                    dev += int(stats.get("bytes_in_use", 0))
            if dev == 0:
                # backends without allocator stats (XLA:CPU): fall back
                # to the live-array census
                dev = sum(int(a.nbytes) for a in jax.live_arrays())
        except Exception:
            dev = 0
    return {"peak_rss_bytes": int(peak), "device_buffer_bytes": int(dev)}


# ---- latency histograms ----------------------------------------------------
#
# ONE fixed log-spaced bucket ladder for every latency family.  Fixed
# (not per-family) so multi-source aggregation can merge by summing
# per-`le` counts unconditionally — `ccsx-tpu top` and the gateway
# merge replica histograms without negotiating bucket layouts, and a
# replica restarted on a newer build still merges with its older
# peers.  Spans ~5ms (a warm lease acquire) to 5min (a cold-compile
# job wall); observations past the top land in +Inf only.
HIST_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class Histogram:
    """A fixed-bucket latency histogram (Prometheus-shaped: cumulative
    `le` buckets + sum + count).  NOT thread-safe on its own — callers
    go through Metrics.observe(), which serializes under _count_lock
    (the same discipline as bump())."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        # one slot per bucket bound + the +Inf overflow slot; stored
        # NON-cumulative (per-bucket increments) — the renderer
        # accumulates, which keeps merge() a plain elementwise sum
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = max(float(value), 0.0)
        i = 0
        for b in HIST_BUCKETS:
            if v <= b:
                break
            i += 1
        self.counts[i] += 1
        self.sum += v
        self.count += 1

    def snapshot(self) -> dict:
        return {"counts": list(self.counts),
                "sum": round(self.sum, 6), "count": self.count}


def merge_hist(snaps) -> dict:
    """Merge histogram SNAPSHOTS by summing per-`le` counts — never by
    averaging quantiles (quantiles do not compose; summed buckets do).
    Tolerates torn/foreign dicts by skipping them."""
    out = {"counts": [0] * (len(HIST_BUCKETS) + 1), "sum": 0.0,
           "count": 0}
    for s in snaps:
        try:
            counts = s["counts"]
            if len(counts) != len(out["counts"]):
                continue
            for i, c in enumerate(counts):
                out["counts"][i] += int(c)
            out["sum"] += float(s["sum"])
            out["count"] += int(s["count"])
        except (KeyError, TypeError, ValueError):
            continue
    out["sum"] = round(out["sum"], 6)
    return out


def hist_quantile(snap: dict, q: float):
    """Estimate the q-quantile from a histogram snapshot the way
    Prometheus' histogram_quantile does: find the bucket where the
    cumulative count crosses q*count and interpolate linearly inside
    it.  None when empty."""
    try:
        total = int(snap["count"])
        counts = snap["counts"]
    except (KeyError, TypeError, ValueError):
        return None
    if total <= 0:
        return None
    target = q * total
    cum = 0
    lo = 0.0
    for i, b in enumerate(HIST_BUCKETS):
        prev = cum
        cum += counts[i]
        if cum >= target:
            frac = ((target - prev) / counts[i]) if counts[i] else 0.0
            return round(lo + (b - lo) * frac, 6)
        lo = b
    # target lands in +Inf: the top bound is the honest answer
    return float(HIST_BUCKETS[-1])


def size_class(holes_total) -> str:
    """The per-size-class label for job latency families: queue-wait
    and wall distributions are only comparable within a size band (a
    large job legitimately waits and runs longer).  Bands are in RAW
    input holes; unknown totals get their own class rather than
    polluting a band."""
    if not holes_total:
        return "unknown"
    if holes_total <= 16:
        return "small"
    if holes_total <= 256:
        return "medium"
    return "large"


class FailureBudgetExceeded(RuntimeError):
    """Raised by check_failure_budget when --max-failed-holes is
    exceeded: the run aborts with RC_FAILED_HOLES (exitcodes.py)
    instead of quarantining its way to a near-empty output at rc 0."""


def check_failure_budget(metrics: "Metrics", cfg, final: bool = False):
    """Enforce cfg.max_failed_holes (None = unbounded, the historical
    behavior).  A value >= 1 is an absolute COUNT, checked the moment a
    hole fails (exceeding it aborts immediately); a value in (0, 1) is
    a FRACTION of processed holes (failed + emitted), checked at end of
    run — mid-run the denominator is still growing, so a fraction can
    only be judged early against a KNOWN total (the BGZF index
    sidecar's holes_total), where no future success can dilute it back
    under budget."""
    budget = getattr(cfg, "max_failed_holes", None)
    if budget is None:
        return
    # corrupt holes (salvage-mode input damage) spend the same budget
    # as quarantined ones: both are holes the output will not carry.
    # Structural-only events (corruption.NON_BUDGET_REASONS, e.g. a
    # missing BGZF EOF marker on an otherwise-complete file) degrade
    # the run but lose no hole, so they must not rc-2 a full output
    from ccsx_tpu.io.corruption import NON_BUDGET_REASONS

    corrupt = metrics.holes_corrupt - sum(
        metrics.corrupt_reasons.get(r, 0) for r in NON_BUDGET_REASONS)
    failed = metrics.holes_failed + max(corrupt, 0)
    if not 0 < budget < 1:   # absolute count (0 = abort on any failure)
        if failed > int(budget):
            raise FailureBudgetExceeded(
                f"failed-hole budget exceeded: {failed} holes failed "
                f"or corrupt (--max-failed-holes {int(budget)})")
        return
    total = metrics.holes_total
    if total and failed > budget * total:
        raise FailureBudgetExceeded(
            f"failed-hole budget exceeded: {failed} of {total} input "
            f"holes failed (> {budget:.0%}, --max-failed-holes "
            f"{budget:g})")
    if final:
        # the denominator spans the whole LOGICAL run: this session's
        # emissions plus prior sessions' journaled ones (holes_failed
        # is already cumulative via the journal restore — judging old
        # failures against only a short resume tail's successes would
        # spuriously abort an overwhelmingly-healthy run)
        done = (failed + metrics.holes_out
                + metrics.holes_prior_emitted)
        if done and failed > budget * done:
            raise FailureBudgetExceeded(
                f"failed-hole budget exceeded: {failed} of {done} "
                f"processed holes failed (> {budget:.0%}, "
                f"--max-failed-holes {budget:g})")


@dataclasses.dataclass
class Metrics:
    verbose: int = 0
    stream: Optional[TextIO] = None
    # multi-tenant label (pipeline/serve.py): the job id this Metrics
    # object accounts for.  None outside the serving plane.  Rides
    # every snapshot/event so a job's JSONL stream and its
    # ccsx_job_*{job="..."} series are attributable without relying on
    # file paths.
    job: Optional[str] = None
    # fleet-wide correlation id (ISSUE 18): minted at job submission
    # (gateway.submit_job / serve solo submit) and propagated through
    # replica leases, fan-out range leases, and every span/metrics
    # event — the key `ccsx-tpu report --fleet` stitches per-process
    # JSONL files by.  None outside the serving plane.
    cid: Optional[str] = None
    holes_in: int = 0
    holes_out: int = 0
    holes_failed: int = 0
    # holes dropped by the ingest filters (main.c:659-672 semantics),
    # with per-reason buckets (few_passes / too_short / too_long /
    # excluded).  Fed by BOTH ingest paths: io/zmw.stream_zmws counts
    # live, and the native C++ streamer — which filters in-library and
    # used to report nothing — surfaces its counts at stream EOF
    # (native/io.py, ccsx_filter_counts)
    holes_filtered: int = 0
    filtered_reasons: dict = dataclasses.field(default_factory=dict)
    # salvage-mode ingest (io/corruption.py, --salvage): classified
    # input-corruption events the readers resynced past (~ holes lost
    # to damage), with per-reason buckets from the pinned taxonomy.
    # Fed by both reader stacks (Python sinks live; the native reader
    # polls an atomic event count live + reason buckets at EOF) and by
    # the drivers' injected-fault rung.  Counts toward the
    # --max-failed-holes budget and marks the run degraded.
    holes_corrupt: int = 0
    corrupt_reasons: dict = dataclasses.field(default_factory=dict)
    windows: int = 0
    pair_alignments: int = 0   # batched prep strand_match pairs
    # pre-alignment plane (ISSUE 11, ops/sketch.py + ops/seed_device.py):
    # candidate pairs scored by the batched device screen, pairs it
    # rejected BEFORE seeding/DP (prefilter_share in snapshot() is
    # rejected/screened — the long-template regime's removed waste),
    # and the device-vs-host k-mer seeding split (--seed-device-min-t
    # crossover).  All bumped by PairExecutor, possibly from the pair
    # gate's pump thread.
    pairs_screened: int = 0
    pairs_prefiltered: int = 0
    pairs_seeded_device: int = 0
    pairs_seeded_host: int = 0
    device_dispatches: int = 0
    # per-implementation banded DP-fill attribution (consensus/star.
    # banded_impl dispatch): {"scan"|"pallas"|"rotband": dispatches}.
    # Makes an A/B run or a breaker/compile-forced scan pin visible in
    # top/stats//metrics (ccsx_banded_impl{impl=...}) without logs —
    # bumped at the round/refine/packed dispatch sites via bump_banded()
    banded_dispatches: dict = dataclasses.field(default_factory=dict)
    refine_overflows: int = 0  # fused windows replayed on host (rare)
    # fault-tolerance ladder counters (pipeline/batch.py recovery):
    # group bisections after a device OOM, per-request host replays
    # (ladder bottom / data errors), and scan-spec pins after a Pallas
    # compile failure (at most 1/process)
    oom_resplits: int = 0
    host_fallbacks: int = 0
    compile_fallbacks: int = 0
    # resilient execution (pipeline/resilience.py): dispatches abandoned
    # past --dispatch-deadline (each one recovered on the host path),
    # and the backend circuit breaker's state machine — trips (closed ->
    # open on N strikes in the window), half-open probes, the live
    # state string, and a bounded log of the qualifying strikes
    # (hang / compile / oom ladder-bottom, each {ts, kind, group})
    # prior sessions' emitted holes, restored from the journal on
    # resume (internal: feeds the --max-failed-holes fraction
    # denominator only — holes_out stays THIS session's emission count
    # so rates/progress are unaffected)
    holes_prior_emitted: int = 0
    device_hangs: int = 0
    breaker_trips: int = 0
    breaker_probes: int = 0
    breaker_state: str = "closed"
    breaker_strike_log: list = dataclasses.field(default_factory=list)
    # padding accounting for the batched device rounds (SURVEY §7.3
    # item 2 names padding waste the main throughput risk): real = DP
    # fill cells belonging to real pass-rows at their true qlen;
    # padded = cells actually dispatched (Z x P x qmax x band x iters,
    # i.e. including pad holes, pad rows, and qlen->qmax padding).
    # occupancy = real/padded is the fraction of device fill work that
    # was asked for.  Pair alignments (PairExecutor) are included.
    dp_cells_real: int = 0
    dp_cells_padded: int = 0
    # decomposition of the occupancy loss for the CONSENSUS-ROUND
    # dispatches (pair alignments excluded — they have no Z/P bucket
    # structure).  All four counters are in CELL units so the identity
    #   round_real/round_padded = length_fill x pass_fill x z_fill
    # holds EXACTLY even when dispatches with different (Z, P, qmax,
    # iters) aggregate (unweighted row/hole ratios misattribute padding
    # across heterogeneous shape groups):
    #   length_fill = round_cells_real / rowcells_real
    #   pass_fill   = rowcells_real   / rowcells_cap
    #   z_fill      = rowcells_cap    / round_cells_padded
    # where rowcells_real = real pass-rows at full qmax and
    # rowcells_cap = (real holes x P) rows at full qmax, both
    # x band x iters — bucket tuning can see WHICH bucket wastes.
    dp_round_cells_real: int = 0
    dp_round_cells_padded: int = 0
    dp_rowcells_real: int = 0
    dp_rowcells_cap: int = 0
    # ragged pass-packing (pipeline/pack.py): real (hole, pass) rows vs
    # slab rows dispatched — dp_row_fill = rows_real / rows_dispatched
    # is the packed analog of pass_fill x z_fill (a packed slab has no
    # Z axis, so its z_fill is identically 1 and its pass_fill is the
    # row fill; these plain row counts read the same story without the
    # qmax/iters cell weighting) — and holes co-dispatched per slab
    # (packed_holes_per_dispatch), the fragmentation counter that used
    # to read ~1.7 windows/dispatch under bucketed grouping
    dp_rows_real: int = 0
    dp_rows_dispatched: int = 0
    packed_dispatches: int = 0
    packed_holes: int = 0
    # compile-lean dispatch (r8): distinct (R, qmax, tmax, iters) slab
    # shapes the packed executor dispatched — the canonical-shape ladder
    # (pipeline/pack.py) bounds this to ~ladder x groups, and the r7
    # compile storm showed up here as ~5x groups.  The executor owns the
    # set; this is its size.
    distinct_slab_shapes: int = 0
    # fused multi-chip packed dispatch: waves issued, real slabs in
    # them, and total chip-slots (waves x D) — fused_slot_fill below is
    # the chip-utilization analog of dp_row_fill (idle chips in a wave
    # are padding dummy slabs that freeze at iteration 0, so they cost
    # ~nothing but chip time)
    fused_waves: int = 0
    fused_slabs_real: int = 0
    fused_slots: int = 0
    # compressed input bytes this process ingested (byte-range sharded
    # BAM ingest reports its ~1/N share; full-parse paths report the
    # file size).  0 when unknown (stdin / pure-stream inputs).
    ingest_bytes: int = 0
    # per-stage wall time (SURVEY.md §5.1: the reference has no stage
    # timing; the pipeline analog of its read/compute/write steps).
    # Attribution is at the driver loop — except ingest and prep, which
    # the prep plane (pipeline/prep_pool.py) runs on background threads
    # when it is on: t_ingest/t_prep then sum WORK seconds across those
    # threads (overlapped with device compute, so not comparable with
    # an inline-mode run's critical-path seconds), while t_prep_blocked
    # below keeps the critical-path story.  Ingest gets no blocked twin:
    # it is measured ~0% of wall on every artifact, and a driver starved
    # by it shows up in prep_blocked (the pool delivers nothing).
    t_ingest: float = 0.0
    t_prep: float = 0.0     # host orientation/clip (ccs_prepare analog)
    t_compute: float = 0.0
    t_write: float = 0.0
    # prep plane (ISSUE 8): driver wall spent BLOCKED on prep — inline
    # prep when the pool is off (t_prep_blocked == t_prep there), or
    # waiting on the pool's ready queue with nothing dispatchable when
    # it is on.  prep_share = t_prep_blocked / elapsed is the
    # critical-path prep share the <= 0.10 acceptance bar reads;
    # prep_overlap_share = 1 - blocked/worked is how much of the prep
    # work the overlap hid.
    t_prep_blocked: float = 0.0
    # live prep-plane gauges: holes prepped-and-waiting for the driver
    # (current + high-water) and the pool width (0 = inline prep)
    prep_queue_depth: int = 0
    prep_queue_peak: int = 0
    prep_threads: int = 0
    # elastic fleet plane (pipeline/fleet.py + supervisor.fleet_run):
    # the scheduler's view of the leased-range queue.  ranges_total is
    # M (the -M split); queued/leased are live gauges over the lease
    # files; retired counts .done markers observed.  steals counts
    # expired/reclaimed leases moved to the graveyard (each is a range
    # another worker may now pick up); rebalances counts reap-time
    # reclaim sweeps that freed at least one lease (rank loss events
    # absorbed by the survivors).  All zero outside fleet mode.
    fleet_ranges_total: int = 0
    fleet_ranges_queued: int = 0
    fleet_ranges_leased: int = 0
    fleet_ranges_retired: int = 0
    fleet_ranks_alive: int = 0
    fleet_steals: int = 0
    fleet_rebalances: int = 0
    # latency histograms (ISSUE 18): family name -> label value ->
    # Histogram.  Families and their label keys are enumerated in
    # telemetry.HIST_FAMILIES (schema-guarded both directions); all
    # share the ONE fixed HIST_BUCKETS ladder so merges sum per-`le`.
    hists: dict = dataclasses.field(default_factory=dict)
    # a "progress" JSONL event is emitted every progress_every retired
    # holes (0 disables); "final" is always emitted at report().  The
    # live-telemetry plane also emits one every progress_interval_s
    # seconds of wall (0 disables) so slow runs still produce a usable
    # ETA-vs-actual series (`ccsx-tpu report`) and a tailable stream
    # (`ccsx-tpu top` on endpoint-less runs)
    progress_every: int = 512
    progress_interval_s: float = 30.0
    # progress/ETA estimator: total holes this run will retire when
    # knowable (the BGZF hole index sidecar / a rank's hole range —
    # RAW holes, so filtered holes count toward done), else None =
    # unknown-total mode (rate only, no pct/ETA)
    holes_total: Optional[int] = None
    # windowed-rate ring buffer of (monotonic, holes retired): the
    # instantaneous zmws/sec over the last <= _RATE_WINDOW samples
    # (sampled at >= _RATE_SAMPLE_S spacing), robust to the cold-start
    # compile minutes that make the whole-run average useless for ETA
    _rate_ring: collections.deque = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=128),
        repr=False)
    _last_interval_emit: float = dataclasses.field(
        default_factory=time.monotonic, repr=False)
    # per-shape-group dispatch attribution (utils/trace.py fills this:
    # compiles, compile_s, execute_s, dispatches, dp_cells per group
    # key) — rendered into every event by snapshot() so recompile
    # storms and slow groups are visible in any metrics JSONL
    group_stats: dict = dataclasses.field(default_factory=dict)
    # set by the stall watchdog (utils/trace.py) when a device dispatch
    # hangs past --stall-timeout: the run completed (or died) degraded,
    # and every later event — including "final" — says so.  stalls
    # counts the watchdog's reports (full + compact) — the /healthz
    # detail an operator triages by
    degraded: Optional[str] = None
    stalls: int = 0
    # unsuppressed ccsx-lint findings (ccsx_tpu/lint/): populated by a
    # supervisor that runs `ccsx-tpu lint --gauge-file` (or bump()s it
    # directly) so fleet dashboards watch static-analysis drift the
    # same way they watch stalls; 0 = clean tree, never populated on
    # the pipeline's own hot path
    lint_findings: int = 0
    # set by the Tracer: True when device spans used the forced-
    # execution close (--trace), i.e. the group table's seconds are
    # real chip walls; False means dispatch-queue bookkeeping on an
    # async backend (counts exact, seconds unreliable)
    groups_forced: Optional[bool] = None
    _ticked: int = 0
    t0: float = dataclasses.field(default_factory=time.monotonic)
    # emit() runs on the driver thread AND the stall-watchdog thread
    _emit_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False)
    # counter/stage updates arrive from the driver, the prep-pool
    # workers, and the pair-gate pump concurrently; += on an attribute
    # is a racy read-modify-write, so concurrent writers go through
    # bump()/add_stage() under this lock
    _count_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False)

    def bump(self, **deltas) -> None:
        """Atomically add deltas to counter fields (thread-safe +=)."""
        with self._count_lock:
            for k, v in deltas.items():
                prev = getattr(self, k)
                setattr(self, k, prev + v)
                # time-to-first-dispatch: the 0 -> nonzero crossing of
                # device_dispatches is the first device work this run
                # issued — observed here (the one choke point every
                # dispatch site already funnels through) so no driver
                # needs its own first-dispatch bookkeeping
                if (k == "device_dispatches" and prev == 0
                        and getattr(self, k) > 0):
                    self._observe_locked(
                        "first_dispatch_s", time.monotonic() - self.t0,
                        size_class(self.holes_total))

    def _observe_locked(self, name: str, value: float,
                        label: str = "") -> None:
        """observe() body; caller holds _count_lock."""
        fam = self.hists.setdefault(name, {})
        h = fam.get(label)
        if h is None:
            h = fam[label] = Histogram()
        h.observe(value)

    def observe(self, name: str, value: float, label: str = "") -> None:
        """Record one latency observation into a histogram family
        (thread-safe; dispatch closures and lease acquires run on
        executor/pump threads)."""
        with self._count_lock:
            self._observe_locked(name, value, label)

    def hist_snapshot(self) -> dict:
        """family -> label -> {counts, sum, count}, copied under the
        lock (scraper threads race live observes)."""
        with self._count_lock:
            return {name: {lbl: h.snapshot() for lbl, h in fam.items()}
                    for name, fam in self.hists.items()}

    def merge_hists(self, hist: dict) -> None:
        """Absorb another Metrics' hist snapshot — summing per-`le`
        counts, the only legal histogram merge.  This is how serve
        folds each finished job's fault-domain observations (first
        dispatch, per-job families) into the server-lifetime snapshot
        its /progress and /metrics expose."""
        if not hist:
            return
        with self._count_lock:
            for name, fam in hist.items():
                if not isinstance(fam, dict):
                    continue
                for label, s in fam.items():
                    try:
                        counts = s["counts"]
                        add_sum = float(s["sum"])
                        add_count = int(s["count"])
                    except (KeyError, TypeError, ValueError):
                        continue
                    dst = self.hists.setdefault(name, {})
                    h = dst.get(label)
                    if h is None:
                        h = dst[label] = Histogram()
                    if len(counts) != len(h.counts):
                        continue
                    for i, c in enumerate(counts):
                        h.counts[i] += int(c)
                    h.sum += add_sum
                    h.count += add_count

    def bump_banded(self, impl: str, n: int = 1) -> None:
        """Attribute n banded DP-fill dispatches to an implementation
        (thread-safe; dispatch closures run on executor threads)."""
        with self._count_lock:
            self.banded_dispatches[impl] = (
                self.banded_dispatches.get(impl, 0) + n)

    def add_stage(self, stage: str, seconds: float) -> None:
        """Thread-safe accumulation into t_<stage>."""
        attr = "t_" + stage
        with self._count_lock:
            setattr(self, attr, getattr(self, attr) + seconds)

    @contextlib.contextmanager
    def timer(self, stage: str):
        """Accumulate a with-block's wall time into t_<stage>."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_stage(stage, time.perf_counter() - t0)

    # windowed-rate sampling: coalesce ring samples closer than this
    # (a fast run must not shrink the window to microseconds), and keep
    # at most _rate_ring.maxlen of them (~32 s+ of history)
    _RATE_SAMPLE_S = 0.25

    def tick(self) -> None:
        """Called once per retired hole; feeds the windowed-rate ring
        and emits periodic progress events (every progress_every holes
        AND every progress_interval_s seconds of wall)."""
        self._ticked += 1
        now = time.monotonic()
        ring = self._rate_ring
        if not ring or now - ring[-1][0] >= self._RATE_SAMPLE_S:
            # sample RETIRED holes (+ filtered, which retire at zero
            # cost) — the same basis progress_snapshot reports.
            # Ingested-but-in-flight holes must NOT count: the batched
            # scheduler admits a whole inflight window up front, which
            # would read as instant-100% progress on small runs
            ring.append((now, self._ticked + self.holes_filtered))
        due = (self.progress_every
               and self._ticked % self.progress_every == 0)
        if (self.progress_interval_s
                and now - self._last_interval_emit
                >= self.progress_interval_s):
            due = True
        if due:
            self._last_interval_emit = now
            self.emit("progress")
            if self.verbose:
                print(f"[ccsx-tpu] progress {json.dumps(self.snapshot())}",
                      file=sys.stderr)

    def heartbeat(self) -> None:
        """Called from the driver loops between retirements: emits the
        interval-driven progress event even when no hole has retired
        for a while — a single-admission-batch run (holes <= inflight)
        retires everything in its final drain, and tick()-only emission
        would leave the metrics stream silent for the whole middle of
        the run."""
        if not self.progress_interval_s:
            return
        now = time.monotonic()
        if now - self._last_interval_emit >= self.progress_interval_s:
            self._last_interval_emit = now
            self.emit("progress")

    @property
    def elapsed(self) -> float:
        return max(time.monotonic() - self.t0, 1e-9)

    @property
    def zmws_per_sec(self) -> float:
        return self.holes_out / self.elapsed

    def progress_snapshot(self) -> dict:
        """The streaming progress/ETA estimate: retired-hole count,
        windowed rate, and — when holes_total is knowable — percent
        done and ETA seconds.  Unknown-total mode reports rate only.
        Rides every metrics event (snapshot()) and the /progress +
        /metrics endpoints (utils/telemetry.py)."""
        # retired holes + filtered holes (retired at zero cost).  NOT
        # holes_in: in-flight admissions are unfinished work.  Resumed
        # holes skip tick(), so a resumed run's pct undercounts by the
        # prior run's share — conservative, never optimistic
        done = self._ticked + self.holes_filtered
        ring = list(self._rate_ring)
        if len(ring) >= 2 and ring[-1][0] > ring[0][0]:
            rate = (ring[-1][1] - ring[0][1]) / (ring[-1][0] - ring[0][0])
        else:
            rate = done / self.elapsed
        prog = {
            "done": done,
            "total": self.holes_total,
            "rate_zmws_per_sec": round(rate, 3),
            "elapsed_s": round(self.elapsed, 3),
        }
        if self.holes_total:
            prog["pct"] = round(min(done / self.holes_total, 1.0) * 100,
                                2)
            remaining = max(self.holes_total - done, 0)
            prog["eta_s"] = (round(remaining / rate, 1) if rate > 0
                             else None)
        return prog

    def _group_table(self) -> dict:
        """Render group_stats for events, via the one shared finalizer
        in utils/trace.py (summarize() uses the same one, so the table
        from a metrics file and from a trace file cannot drift)."""
        from ccsx_tpu.utils import trace

        # dict() copy: the watchdog thread snapshots while the driver
        # thread may be inserting a new group
        return trace.finalize_group_table(dict(self.group_stats))

    def snapshot(self) -> dict:
        snap = {
            "holes_in": self.holes_in,
            "holes_out": self.holes_out,
            "holes_failed": self.holes_failed,
            "holes_filtered": self.holes_filtered,
            "holes_corrupt": self.holes_corrupt,
            "stalls": self.stalls,
            "windows": self.windows,
            "pair_alignments": self.pair_alignments,
            "pairs_screened": self.pairs_screened,
            "pairs_prefiltered": self.pairs_prefiltered,
            "prefilter_share": round(self.pairs_prefiltered
                                     / self.pairs_screened, 4)
                               if self.pairs_screened else None,
            "pairs_seeded_device": self.pairs_seeded_device,
            "pairs_seeded_host": self.pairs_seeded_host,
            "device_dispatches": self.device_dispatches,
            "refine_overflows": self.refine_overflows,
            "oom_resplits": self.oom_resplits,
            "host_fallbacks": self.host_fallbacks,
            "compile_fallbacks": self.compile_fallbacks,
            "device_hangs": self.device_hangs,
            "breaker_state": self.breaker_state,
            "breaker_trips": self.breaker_trips,
            "breaker_probes": self.breaker_probes,
            "dp_cells_real": self.dp_cells_real,
            "dp_cells_padded": self.dp_cells_padded,
            "dp_occupancy": round(self.dp_cells_real
                                  / self.dp_cells_padded, 4)
                            if self.dp_cells_padded else None,
            "dp_round_occupancy": round(self.dp_round_cells_real
                                        / self.dp_round_cells_padded, 4)
                                  if self.dp_round_cells_padded else None,
            "dp_length_fill": round(self.dp_round_cells_real
                                    / self.dp_rowcells_real, 4)
                              if self.dp_rowcells_real else None,
            "dp_pass_fill": round(self.dp_rowcells_real
                                  / self.dp_rowcells_cap, 4)
                            if self.dp_rowcells_cap else None,
            "dp_z_fill": round(self.dp_rowcells_cap
                               / self.dp_round_cells_padded, 4)
                         if self.dp_round_cells_padded else None,
            "dp_row_fill": round(self.dp_rows_real
                                 / self.dp_rows_dispatched, 4)
                           if self.dp_rows_dispatched else None,
            "packed_holes_per_dispatch": round(self.packed_holes
                                               / self.packed_dispatches,
                                               2)
                                         if self.packed_dispatches
                                         else None,
            "distinct_slab_shapes": self.distinct_slab_shapes or None,
            "fused_waves": self.fused_waves or None,
            "fused_slot_fill": round(self.fused_slabs_real
                                     / self.fused_slots, 4)
                               if self.fused_slots else None,
            "ingest_bytes": self.ingest_bytes,
            "ingest_s": round(self.t_ingest, 6),
            "prep_s": round(self.t_prep, 6),
            "compute_s": round(self.t_compute, 6),
            "write_s": round(self.t_write, 6),
            # prep plane: critical-path prep exposure + overlap quality
            # (None overlap until any prep work exists).  prep_share is
            # the acceptance counter: blocked-on-prep wall / elapsed
            "prep_blocked_s": round(self.t_prep_blocked, 6),
            "prep_share": round(self.t_prep_blocked / self.elapsed, 4),
            "prep_overlap_share": round(
                1.0 - min(self.t_prep_blocked / self.t_prep, 1.0), 4)
                                  if self.t_prep else None,
            "prep_queue_depth": self.prep_queue_depth,
            "prep_queue_peak": self.prep_queue_peak,
            "prep_threads": self.prep_threads,
            "fleet_ranges_total": self.fleet_ranges_total,
            "fleet_ranges_queued": self.fleet_ranges_queued,
            "fleet_ranges_leased": self.fleet_ranges_leased,
            "fleet_ranges_retired": self.fleet_ranges_retired,
            "fleet_ranks_alive": self.fleet_ranks_alive,
            "fleet_steals": self.fleet_steals,
            "fleet_rebalances": self.fleet_rebalances,
            "elapsed_s": round(self.elapsed, 3),
            "zmws_per_sec": round(self.zmws_per_sec, 3),
            "progress": self.progress_snapshot(),
        }
        if self.filtered_reasons:
            # dict() copy: the telemetry thread snapshots while the
            # ingest loop may be inserting a new reason bucket
            snap["filtered_reasons"] = dict(self.filtered_reasons)
        if self.corrupt_reasons:
            snap["corrupt_reasons"] = dict(self.corrupt_reasons)
        if self.banded_dispatches:
            snap["banded_dispatches"] = dict(self.banded_dispatches)
        if self.breaker_strike_log:
            # list() copy: the breaker publishes a fresh list per
            # strike, but a scraper could catch the reassignment
            snap["breaker_strike_log"] = list(self.breaker_strike_log)
        if self.group_stats:
            snap["groups"] = self._group_table()
            snap["groups_forced"] = bool(self.groups_forced)
            # compile share of wall: how much of this run's elapsed
            # time went to XLA compiles (warmup-thread compiles overlap
            # the stream, so a healthy warmed run shows compile_s high
            # but compile blocking ~nothing — compare against the
            # per-group tables; dict() copy: watchdog-thread safety)
            comp = sum(st.get("compile_s", 0.0)
                       for st in dict(self.group_stats).values())
            snap["compile_s"] = round(comp, 4)
            snap["compile_share"] = round(comp / self.elapsed, 4)
        if self.hists:
            snap["hist"] = self.hist_snapshot()
        if self.job:
            snap["job"] = self.job
        if self.cid:
            snap["cid"] = self.cid
        # always present (None when clean) so the schema guards see the
        # key; the renderer drops None-valued samples
        snap["lint_findings"] = self.lint_findings or None
        if self.degraded:
            snap["degraded"] = self.degraded
        # degraded-relevant detail: a FAILED native .so auto-rebuild
        # silently disables the C++ IO path (pure-Python fallback, same
        # bytes, much slower ingest) — surface it in every event so a
        # mysteriously slow run is diagnosable from its metrics alone.
        # Read lazily from the loader (no jax, no rebuild attempt — the
        # loader caches its one try).
        try:
            from ccsx_tpu import native as native_mod

            err = native_mod.build_error()
        except Exception:
            err = None
        if err:
            snap["native_build_error"] = err
        return snap

    def emit(self, event: str, **kw) -> None:
        if self.stream is not None:
            # "ts" is the wall clock: elapsed_s alone cannot merge
            # multi-host/sharded JSONL streams onto a common timeline
            rec = {"event": event, "ts": round(time.time(), 6),
                   **self.snapshot(), **kw}
            with self._emit_lock:
                if self.stream is None:  # closed under our feet
                    return
                self.stream.write(json.dumps(rec) + "\n")
                self.stream.flush()

    def close_stream(self) -> None:
        """Close the metrics stream WITHOUT emitting a final event —
        the drivers' early-exit error paths (stream/writer open
        failed): a run that never started must not leave a 'final'
        record, but must not leak the open file either."""
        if self.stream is not None and self.stream not in (sys.stdout,
                                                           sys.stderr):
            with self._emit_lock:
                try:
                    self.stream.close()
                except OSError:
                    pass
                self.stream = None

    def report(self) -> None:
        if self.verbose:
            print(f"[ccsx-tpu] {json.dumps(self.snapshot())}", file=sys.stderr)
        # final carries the resource gauges (peak RSS, device buffers):
        # sampled once at close rather than in snapshot() — the
        # live-array census is not cheap enough for every event
        self.emit("final", **resource_gauges())
        if self.stream is not None and self.stream not in (sys.stdout,
                                                           sys.stderr):
            with self._emit_lock:
                try:
                    self.stream.close()
                except OSError:
                    pass
                self.stream = None
