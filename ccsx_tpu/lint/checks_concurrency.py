"""metrics-lock + contextvar-restore: the concurrency disciplines.

``metrics-lock``: Metrics counters take concurrent writers (driver,
prep-pool workers, pair-gate pump, watchdog), and ``x += 1`` on an
attribute is a racy read-modify-write — updates vanish under load and
the books stop balancing (holes_in != holes_out + failed + filtered).
Every cross-thread increment must go through ``bump()`` /
``add_stage()`` / ``observe()``, which serialize under
``Metrics._count_lock``.  Rule: flag augmented assignment
(``+=``/``-=``/…) on an attribute reached through a ``metrics`` /
``_metrics`` / ``self.metrics`` base, anywhere outside
``utils/metrics.py`` itself.  Plain ``=`` publishes of gauges
(supervisor fleet gauges, queue depths) are a single-writer pattern
and stay legal.  Single-writer hot-loop ``+=`` sites that are provably
race-free may be baselined — with the justification in the entry.

``contextvar-restore``: the r17 cid cross-stamp — a ``ContextVar``
set without restoring the returned token leaks the value into every
later job on that thread (spans and metrics stamped with a dead job's
correlation id).  Rule: a call to ``<var>.set(...)`` on a module-level
ContextVar must either (a) be returned to the caller (token-handoff
API like ``faultinject.scope_arm``), or (b) sit in a function whose
``finally`` calls ``<var>.reset(...)`` (the ``trace.cid_scope``
shape).  Anything else is flagged.
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ccsx_tpu.lint.core import Finding

CHECK_LOCK = "metrics-lock"
CHECK_CVAR = "contextvar-restore"

METRICS_NAMES = {"metrics", "_metrics"}

MESSAGE_LOCK = ("read-modify-write on a Metrics attribute outside "
                "bump()/add_stage() — concurrent writers lose updates; "
                "use metrics.bump(...) (locked) or baseline a provably "
                "single-writer site with its justification")
MESSAGE_CVAR = ("ContextVar.set() without a token restore — return the "
                "token to the caller or reset it in a finally "
                "(trace.cid_scope shape); a leaked value cross-stamps "
                "every later job on this thread (the r17 cid bug)")


def _line_text(lines: Sequence[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""


# ---- metrics-lock ----------------------------------------------------------


def _metrics_attr_target(node: ast.AST) -> bool:
    """True for ``metrics.X`` / ``_metrics.X`` / ``<expr>.metrics.X``."""
    if not isinstance(node, ast.Attribute):
        return False
    base = node.value
    if isinstance(base, ast.Name) and base.id in METRICS_NAMES:
        return True
    if isinstance(base, ast.Attribute) and base.attr in METRICS_NAMES:
        return True
    return False


def check_metrics_lock(tree: ast.AST, src: str, lines: Sequence[str],
                       relpath: str) -> Iterable[Finding]:
    if PurePosixPath(relpath).name == "metrics.py":
        return []  # the locked methods themselves live here
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AugAssign) and _metrics_attr_target(
                node.target):
            out.append(Finding(CHECK_LOCK, relpath, node.lineno,
                               node.col_offset, MESSAGE_LOCK,
                               _line_text(lines, node.lineno)))
    return out


# ---- contextvar-restore ----------------------------------------------------


def _contextvar_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        value = None
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        if not (isinstance(target, ast.Name)
                and isinstance(value, ast.Call)):
            continue
        fn = value.func
        if (isinstance(fn, ast.Name) and fn.id == "ContextVar") or (
                isinstance(fn, ast.Attribute)
                and fn.attr == "ContextVar"):
            names.add(target.id)
    return names


def _is_var_call(node: ast.AST, var: Set[str], method: str) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == method
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in var)


def check_contextvar(tree: ast.AST, src: str, lines: Sequence[str],
                     relpath: str) -> Iterable[Finding]:
    cvars = _contextvar_names(tree)
    if not cvars:
        return []
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not _is_var_call(node, cvars, "set"):
            continue
        if isinstance(parents.get(node), ast.Return):
            continue  # token handed to the caller (scope_arm shape)
        fn = enclosing_function(node)
        restored = False
        if fn is not None:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Try):
                    for final_stmt in sub.finalbody:
                        for leaf in ast.walk(final_stmt):
                            if _is_var_call(leaf, cvars, "reset"):
                                restored = True
        if not restored:
            out.append(Finding(CHECK_CVAR, relpath, node.lineno,
                               node.col_offset, MESSAGE_CVAR,
                               _line_text(lines, node.lineno)))
    return out
