import numpy as np

from ccsx_tpu.ops import encode as enc, seed
from ccsx_tpu.utils import synth


def test_kmer_codes_basic():
    s = enc.encode("ACGTACGTACGTACGT")
    k = seed.kmer_codes(s, 4)
    assert len(k) == 13
    assert k[0] == (0 << 6) | (1 << 4) | (2 << 2) | 3
    assert k[0] == k[4]  # periodic sequence


def test_kmer_codes_n_invalid():
    s = enc.encode("ACGTNACGTACGT")
    k = seed.kmer_codes(s, 4)
    assert (k[1:5] == -1).all()  # windows covering the N
    assert k[0] != -1 and k[5] != -1


def test_seed_diagonal_identity(rng):
    t = rng.integers(0, 4, 500).astype(np.uint8)
    hit = seed.seed_diagonal(t, t)
    assert hit is not None
    assert abs(hit.diag) <= seed.DIAG_BIN


def test_seed_diagonal_offset(rng):
    t = rng.integers(0, 4, 400).astype(np.uint8)
    q = np.concatenate([rng.integers(0, 4, 300).astype(np.uint8), t])
    hit = seed.seed_diagonal(q, t)
    assert hit is not None
    assert abs(hit.diag - 300) <= seed.DIAG_BIN
    # line endpoints lie on the diagonal
    i0, j0, i1, j1 = hit.line
    assert i0 - j0 == hit.diag and i1 - j1 == hit.diag


def test_seed_diagonal_noisy(rng):
    t = rng.integers(0, 4, 600).astype(np.uint8)
    q = synth.mutate(rng, t, 0.03, 0.05, 0.05)
    hit = seed.seed_diagonal(q, t)
    assert hit is not None
    assert abs(hit.diag) <= 2 * seed.DIAG_BIN


def test_seed_diagonal_unrelated(rng):
    q = rng.integers(0, 4, 300).astype(np.uint8)
    t = rng.integers(0, 4, 300).astype(np.uint8)
    hit = seed.seed_diagonal(q, t)
    # random 300-mers share few 13-mers; votes must be tiny or absent
    assert hit is None or hit.votes <= 5


def test_seed_short_sequences():
    assert seed.seed_diagonal(np.zeros(5, np.uint8), np.zeros(5, np.uint8)) is None or True
    # shorter than k: no crash, returns None
    out = seed.seed_diagonal(np.zeros(3, np.uint8), np.zeros(30, np.uint8))
    assert out is None
