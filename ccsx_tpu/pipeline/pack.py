"""Ragged pass-packing: variable-pass holes into fixed row slabs.

The r5 scale run decomposed the batched pipeline's occupancy loss
(utils/metrics.py cell-exact counters): length buckets are nearly free
(0.96) but the coarse {4,8,16,32} pass buckets (pass_fill 0.727) and
partial Z groups (z_fill 0.852) together waste ~40% of every dispatch,
and the (P, qmax, tmax, iters) shape-group explosion leaves ~1.7 windows
per dispatch.  Finer pass buckets trade occupancy for MORE groups and
compiles (r5 A/B, ARCHITECTURE.md).  The structural fix is to stop
bucketing the pass dimension entirely: flatten each hole's passes into
(hole, pass) ROWS and pack rows from many holes into fixed (R, qmax)
slabs — the inter-task batching move gpuPairHMM uses to pack
variable-length DP problems onto fixed accelerator tiles, and the ragged
analog of sequence packing in LLM training stacks.

This module is the HOST-side planner (pure Python/NumPy, no jax import —
it must stay importable in milliseconds for tests/test_pack.py's fast
unit tier).  The device side lives in pipeline/batch.py
(`_refine_step_packed`): a row->hole segment-id vector rides along, the
column vote becomes a masked segment-sum (ops/msa.make_segment_voter)
and the breakpoint scan a segment reduction
(ops/breakpoint.make_bp_advance_packed).

Packing discipline (all deterministic — same inputs, same plan):

* first-fit-decreasing by hole: holes sorted by (-rows, index), each
  placed into the earliest open slab with row room AND a free hole slot;
  otherwise a new slab opens.  FFD keeps tail fragmentation low without
  the grouping explosion of exact bin packing.
* a slab's device shape is (R, qmax) rows plus (H, tmax) per-hole state,
  R a power of two (bounds jit retraces exactly like the Z bucket it
  replaces) and H = R // SEG_DIV the static segment capacity
  (`num_segments` of the device segment reductions).  The capacity is a
  packing constraint, not a truncation: plan_slabs never assigns more
  than H holes to a slab.
* the LAST slab of a group (and every slab re-packed by the OOM-resplit
  ladder, pipeline/batch._recover_group) snaps to the smallest of at
  most ``ladder`` CANONICAL heights that fits — budget, budget/2, ...
  (see slab_shape) — so a (qmax, tmax, iters) group compiles at most
  ``ladder`` XLA programs ever.  The r7 flight recorder measured the
  finer budget/8 ladder paying 4-5 compiles per packed group (one per
  distinct tail R) — through a tens-of-seconds-per-shape compiler that
  ladder bought back its tail-waste savings many times over, so r8
  collapses it: worst-case tail waste rises to just under budget/2
  rows of masked (cheap, but dispatched) fill, and the shape set per
  group drops from <=12 to <=2, each precompilable by the AOT warmup
  thread (pipeline/warmup.py) before the first dispatch needs it.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

# rows per hole slot: a slab of R rows exposes H = R // SEG_DIV segment
# slots.  4 is below the realistic minimum passes per hole (the count
# filter keeps holes at >= min_fulllen_count + 2 = 5 subreads), so the
# capacity almost never binds; when it does (many tiny holes) the packer
# simply opens another slab.
SEG_DIV = 4

# canonical tail heights per group: budget and budget/2 (cfg
# slab_shape_ladder / --slab-shape-ladder; 1 = every slab full-height)
DEFAULT_LADDER = 2


def pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    p = 1
    while p < n:
        p *= 2
    return p


def canonical_heights(slab_rows: int, ladder: int = DEFAULT_LADDER) -> list:
    """The allowed slab row counts at or below the budget: budget >> k
    for k in [0, ladder), descending, never below 1.  ladder=1 means
    every slab dispatches full-height; the default 2 adds budget/2 for
    small tails.  Oversize holes (rows > budget) still grow past the
    budget on the pow2 ladder — they get dedicated slabs and are the
    only way a group can exceed ``ladder`` distinct shapes."""
    budget = pow2(max(1, slab_rows))
    return [max(1, budget >> k) for k in range(max(1, int(ladder)))]


def slab_shape(rows: Sequence[int], slab_rows: int,
               seg_div: int = SEG_DIV,
               ladder: int = DEFAULT_LADDER) -> tuple:
    """(R, H) device shape for ONE slab holding holes with ``rows`` real
    rows each.

    R covers the row total, the segment capacity floor (seg_div rows per
    hole slot keeps H = R // seg_div >= len(rows)), and the largest
    single hole; oversize holes grow past the budget on the pow2
    ladder.  Everything else SNAPS UP to the smallest of the
    ``ladder`` canonical heights (canonical_heights) that covers it —
    at most 2 distinct XLA programs per (qmax, tmax, iters) group by
    default, each predictable (and so AOT-warmable) before any slab of
    the group exists.  The r7 budget/8 shrink ladder held tail waste
    under budget/8 rows but paid 4-5 compiles per group (trace-
    measured, BENCH r7) — masked tail rows are cheap fill, compiles
    are tens of seconds each, so the trade inverts."""
    if not rows:
        raise ValueError("empty slab")
    budget = pow2(max(1, slab_rows))
    need = max(sum(rows), seg_div * len(rows), max(rows))
    if need > budget:
        R = pow2(need)
    else:
        R = budget
        for h in canonical_heights(slab_rows, ladder):
            if h >= need:
                R = h
            else:
                break
    return R, max(1, R // seg_div)


def plan_slabs(rows: Sequence[int], slab_rows: int,
               seg_div: int = SEG_DIV) -> List[List[int]]:
    """First-fit-decreasing hole->slab assignment.

    Returns slabs as lists of item indices (into ``rows``), in slab
    creation order; within a slab, items are in placement (descending
    rows, index-tiebroken) order — the executor stacks rows in exactly
    this order, so the plan IS the device layout.  A hole larger than
    the row budget gets a dedicated slab (slab_shape grows it to the
    covering power of two); nothing else can join it, since the fit
    check is against the shared budget.
    """
    budget = pow2(max(1, slab_rows))
    cap = max(1, budget // seg_div)
    order = sorted(range(len(rows)), key=lambda i: (-rows[i], i))
    slabs: List[List[int]] = []
    used: List[int] = []
    for i in order:
        r = rows[i]
        for s in range(len(slabs)):
            if used[s] + r <= budget and len(slabs[s]) < cap:
                slabs[s].append(i)
                used[s] += r
                break
        else:
            slabs.append([i])
            used.append(r)
    return slabs


def segment_ids(rows: Sequence[int], R: int) -> np.ndarray:
    """(R,) int32 row->hole segment vector for a slab packed in ``rows``
    order: hole k's rows occupy the next rows[k] positions.  Padding
    rows at the tail carry the LAST segment id, keeping the vector
    sorted (the device segment-sums pass indices_are_sorted) — their
    contributions are masked to zero by row_mask, so the id only has to
    be in range."""
    total = int(sum(rows))
    if total > R:
        raise ValueError(f"{total} rows exceed slab of {R}")
    seg = np.repeat(np.arange(len(rows), dtype=np.int32),
                    np.asarray(rows, dtype=np.int64))
    pad = np.full(R - total, max(len(rows) - 1, 0), np.int32)
    return np.concatenate([seg, pad])
