"""Pallas TPU kernel v2: rotating-band lane layout (global+moves mode).

Same op as ops/banded_pallas.py — the banded affine-gap DP fill that
replaces bsalign's banded-striped SIMD POA kernel (main.c:492, band=128
at main.c:849) — but with the one structural attack the v1 docstring
documented and never built: lane k holds column j === k mod B instead of
band-local position j - offs[i].  The lax.scan implementation in
ops/banded.py remains the spec and differential oracle; this kernel is
bit-exact against it (tests/test_banded_pallas.py three-way fuzz).

THE LAYOUT.  v1 keys lanes by band-local position: lane k of row i holds
column offs[i] + k, so when the band advances by d = offs[i] - offs[i-1]
every carried value must MOVE d lanes.  d differs per problem inside a
G-block, so the move is a maxshift+2-way chain of static shifts and
selects (~24 tile ops/row) — irreducible in that layout, as the v1
docstring proves.  Here lanes are keyed by column residue: lane k holds
column j with j === k (mod B), the band-parallel layout family gpuPairHMM
uses (PAPERS.md).  The column -> lane map is row-INDEPENDENT, so the
carry never moves at all:

  krel = (k - offs[i]) & (B-1)      lane k's position inside the band
  j    = offs[i] + krel             the column lane k holds at row i

* vertical predecessor (H_up/E_up): column j of row i-1 lives in the
  SAME lane; it existed in the previous band iff krel < B - d
  (otherwise the lane was just recycled for a new column -> NEG fill,
  exactly _pad_prev's semantics).
* diagonal predecessor: column j-1 lives in lane k-1 (cyclic), one
  STATIC jnp.roll(+1) shared by every problem in the G-block; it
  existed iff krel <= B - d and not (krel == 0 and d == 0).
* the Hillis-Steele F prefix scan runs in krel order: each step's
  static roll(+step) lands lane k on the value at krel-step, masked
  NEG where krel < step — the SAME roll+cmp+select per step as v1,
  with krel substituting karr one-for-one in the masks (the v1
  docstring's "+14 ops" estimate for these wrap masks was wrong: the
  legacy scan pays the identical edge masks against karr).

Static per-row tile-op audit ((G, B)-tile ops, slim with_stats=False
carry, maxshift=4 — same counting convention as the v1 docstring's
~24/~21/~15 ~= 60 budget):

  stage                       v1 (band-local)      v2 (rotating)
  predecessor views           ~24  select chain    ~11
    krel = (k-OFF) & (B-1)          --              2
    up:   cmp + 2 selects           --              3   (same lane)
    diag: roll + ~4 mask + sel      --              6   (one static roll)
    d-chain: 3x(roll+mask) x2ch     12              --
    4x select x2ch + derive up      12              --
  F prefix scan (7 steps)     ~21                  ~21  (unchanged)
  recurrence + moves byte     ~15                  ~13  (j from krel)
  TOTAL                       ~60                  ~45

The select chain is eliminated; nothing else grew.  The moves come out
lane-rotated, un-rotated OUTSIDE the kernel by one batched
take_along_axis gather (same cost class as the ismatch gather already
on the host side, amortized over the whole fill, and it keeps
ops/traceback.py and every consumer byte-identical).  The documented
LOSER is the in-kernel post-rotate: d is per-problem, so restoring the
legacy layout inside the kernel is a 7-step barrel shifter (~21 tile
ops/row) — strictly worse than the ~24-op chain it was meant to kill.
A rotated-aware projector (lane = j & (B-1) in traceback.py) remains a
further option if the epilogue gather ever shows up on hardware
profiles; it is not needed for the promotion decision.

PROMOTION STATUS (r14): bit-exactness vs the scan spec is pinned in
interpret mode on CPU (tier-1) and the interpret=False path is armed in
benchmarks/pallas_ab.py --mode check for the first tunnel-live run.
All three arms (scan / pallas / rotband) are timed by pallas_ab.py
under the forced-execution marginal method only — the per-iteration
block_until_ready numbers that polluted r3/r5 are rejected by
construction — and the harness emits a machine-readable decision
record (winner, margin, backend, method) that bench.py vs_prev gates.
ROADMAP item 1 settles on that record, not on another bespoke session.

G-blocking, the with_stats channels, the offset schedule
(banded_pallas.compute_offsets, shared), the lane-0 scalar bit-pack,
the qmax/gblock gates and the OOM/compile-recovery ladder semantics all
carry over from v1 unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops.banded import (
    BandedResult, EBIT_EXT, FBIT_EXT, MOVE_DIAG, MOVE_LEFT, MOVE_UP, NEG, PAD,
)
from ccsx_tpu.ops.banded_pallas import (
    GBLOCK, PALLAS_MAX_QMAX, ROWBLOCK, compute_offsets,
)


def compute_ismatch_rot(q, t, offs, band: int, maxshift: int):
    """(Qmax, band) int8 match indicators in ROTATED lane order: row i-1
    lane k compares q[i-1] with the base entering column
    offs[i] + ((k - offs[i]) & (band-1)) (PAD-safe).  Same tpad gather as
    banded_pallas.compute_ismatch, rotated index."""
    tpad = jnp.concatenate([
        jnp.full((1,), PAD, jnp.uint8), t.astype(jnp.uint8),
        jnp.full((band + maxshift,), PAD, jnp.uint8),
    ])
    karr = jnp.arange(band, dtype=jnp.int32)[None, :]
    krel = (karr - offs[:, None]) & (band - 1)
    j = offs[:, None] + krel
    tb = tpad[j]
    qi = q[:, None]
    ismatch = (qi == tb) & (qi < 4) & (tb < 4)
    return ismatch.astype(jnp.int8)


# rows of the G-batched carry: H, E, [mat, aln, Emat, Ealn]; the band
# offset rides a separate (G, 1) scratch column (off_ref) — keeping it
# out of the (G, B) carry saves the per-row OFF tile-add v1 pays
_CHG_ROT = 6      # with_stats carry rows (stats-free carry is 2)


def _kernel_rot(tlen_ref, ismatch_ref, moves_ref, fin_ref,
                ch_ref, off_ref, *, qmax: int, band: int, maxshift: int,
                params: AlignParams, with_stats: bool, gblock: int):
    """G-batched rotating-band DP fill: GBLOCK alignments per grid step.

    Mirrors banded_pallas._kernel_g's structure (G-block sublane
    stacking, lane-0 scalar bit-pack, row-0 init / fin-write pl.when
    epilogues, int32 carries) with the predecessor select chain replaced
    by the residue-lane masks derived in the module docstring.  The
    carry is column-anchored and NEVER physically rotates; the band
    offset is a (G, 1) scratch column (off_ref), not a carry row.

    Inputs (blocks):
      tlen_ref    (G, 1) int32
      ismatch_ref (G, ROWBLOCK, B) int32 — bit 0 match (rotated lane
                  order); lane 0 carries d at bits 1-3 and live at bit 4
    Outputs: moves (G, ROWBLOCK, B) uint8 (ROTATED lane order — the
    host epilogue un-rotates); fin (G, 8, B) int32 rows 0/1/2 = final
    H/mat/aln bands in rotated order (mat/aln zero when stats are off).
    """
    M, X = params.match, params.mismatch
    O, E = params.gap_open, params.gap_extend
    B = band
    G = gblock
    r = pl.program_id(1)
    karr = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    tlen_col = tlen_ref[:, 0:1]                      # (G, 1)

    def roll1(x):
        # out[..., k] = x[..., k-1] (cyclic): the diagonal-predecessor
        # lane map, one STATIC rotate shared by all problems/shifts
        return jnp.roll(x, 1, axis=1)

    # ---- row 0 init (off = 0 -> krel == karr), exactly banded.py carry0
    @pl.when(r == 0)
    def _():
        j0 = jnp.broadcast_to(karr, (G, B))
        H0 = jnp.where(j0 <= tlen_col,
                       jnp.where(j0 == 0, 0, O + E * j0), NEG)
        E0 = jnp.full((G, B), NEG, jnp.int32)
        z = jnp.zeros((G, B), jnp.int32)
        rows0 = ([H0, E0, z, j0, z, j0] if with_stats
                 else [H0, E0])
        ch_ref[:] = jnp.stack(rows0, axis=0)
        off_ref[:] = jnp.zeros((G, 1), jnp.int32)

    # int32 throughout: i8 sublane slices hit Mosaic relayout limits
    packed_tile = ismatch_ref[...].astype(jnp.int32)   # (G, ROWBLOCK, B)
    ismatch_tile = packed_tile & 1
    ch = ch_ref[:]
    off_col = off_ref[:]                             # (G, 1)
    moves_rows = []
    for s in range(ROWBLOCK):
        i = r * ROWBLOCK + s + 1
        lane0 = packed_tile[:, s, 0:1]               # (G, 1) packed scalars
        d_col = (lane0 >> 1) & 7
        live_col = ((lane0 >> 4) & 1) != 0           # (G, 1) bool

        OFF = off_col + d_col                        # (G, 1) row offset
        krel = (karr - OFF) & (B - 1)                # (G, B) band position
        j = OFF + krel                               # (G, B) column

        # predecessor validity (see module docstring; NEG fill matches
        # _pad_prev semantics, stats rows included)
        up_bad = krel >= (B - d_col)                 # recycled lane
        diag_bad = (krel > (B - d_col)) | ((krel == 0) & (d_col == 0))

        H_up = jnp.where(up_bad, NEG, ch[0])
        E_up = jnp.where(up_bad, NEG, ch[1])
        Hd_diag = jnp.where(diag_bad, NEG, roll1(ch[0]))
        if with_stats:
            mat_up = jnp.where(up_bad, NEG, ch[2])
            aln_up = jnp.where(up_bad, NEG, ch[3])
            Emat_up = jnp.where(up_bad, NEG, ch[4])
            Ealn_up = jnp.where(up_bad, NEG, ch[5])
            mat_diag = jnp.where(diag_bad, NEG, roll1(ch[2]))
            aln_diag = jnp.where(diag_bad, NEG, roll1(ch[3]))

        im = ismatch_tile[:, s, :]                   # (G, B) int32 0/1
        sub = X + (M - X) * im

        # E (vertical)
        e_ext = E_up + E
        e_open = H_up + O + E
        e_is_open = e_open >= e_ext
        Enew = jnp.maximum(e_ext, e_open)
        if with_stats:
            Emat = jnp.where(e_is_open, mat_up, Emat_up)
            Ealn = jnp.where(e_is_open, aln_up, Ealn_up) + 1

        # Hd = best of diag / E
        diag_term = Hd_diag + sub
        d_wins = diag_term >= Enew
        Hd = jnp.maximum(diag_term, Enew)
        if with_stats:
            Hmat = jnp.where(d_wins, mat_diag + im, Emat)
            Haln = jnp.where(d_wins, aln_diag, Ealn - 1) + 1

        # boundary lane j == 0 (global mode)
        at0 = j == 0
        b_H = O + E * i
        Hd = jnp.where(at0, b_H, Hd)
        Enew = jnp.where(at0, b_H, Enew)
        if with_stats:
            Hmat = jnp.where(at0, 0, Hmat)
            Haln = jnp.where(at0, i, Haln)
            Emat = jnp.where(at0, 0, Emat)
            Ealn = jnp.where(at0, i, Ealn)

        # invalid lanes beyond the template
        invalid = j > tlen_col
        Hd = jnp.where(invalid, NEG, Hd)
        Enew = jnp.where(invalid, NEG, Enew)

        # F (horizontal) max-plus prefix scan, Hillis-Steele in krel
        # order: static roll(+step) + wrap mask (krel < step -> NEG) —
        # krel substitutes karr one-for-one in v1's edge masks; combine
        # keeps right on ties (ops/banded.py _combine_rightmax)
        v = Hd + O - E * krel
        if with_stats:
            fm = Hmat
            fa = Haln - krel
        step = 1
        while step < B:
            vs = jnp.where(krel < step, NEG, jnp.roll(v, step, axis=1))
            keep = v >= vs
            if with_stats:
                ms = jnp.where(krel < step, NEG,
                               jnp.roll(fm, step, axis=1))
                as_ = jnp.where(krel < step, NEG,
                                jnp.roll(fa, step, axis=1))
                fm = jnp.where(keep, fm, ms)
                fa = jnp.where(keep, fa, as_)
            v = jnp.where(keep, v, vs)
            step *= 2
        # exclusive: shift right by one in krel order (score fill NEG,
        # stats fill 0)
        v = jnp.where(krel < 1, NEG, roll1(v))
        F = v + E * krel
        if with_stats:
            Fmat = jnp.where(krel < 1, 0, roll1(fm))
            Faln = jnp.where(krel < 1, 0, roll1(fa)) + krel

        hd_wins = Hd >= F
        Hnew = jnp.maximum(Hd, F)
        if with_stats:
            mat_new = jnp.where(hd_wins, Hmat, Fmat)
            aln_new = jnp.where(hd_wins, Haln, Faln)

        # moves byte
        choice = jnp.where(
            hd_wins & d_wins, MOVE_DIAG,
            jnp.where(hd_wins, MOVE_UP, MOVE_LEFT)).astype(jnp.uint8)
        ebit = jnp.where(e_is_open, 0, EBIT_EXT).astype(jnp.uint8)
        H_left = jnp.where(krel < 1, NEG, roll1(Hnew))
        f_is_open = F == (H_left + O + E)
        fbit = jnp.where(f_is_open, 0, FBIT_EXT).astype(jnp.uint8)
        moves_rows.append((choice | ebit | fbit)[:, None, :])

        rows_new = ([Hnew, Enew, mat_new, aln_new, Emat, Ealn]
                    if with_stats else [Hnew, Enew])
        ch_new = jnp.stack(rows_new, axis=0)
        ch = jnp.where(live_col[None], ch_new, ch)
        off_col = jnp.where(live_col, OFF, off_col)

    moves_ref[...] = jnp.concatenate(moves_rows, axis=1)
    ch_ref[:] = ch
    off_ref[:] = off_col

    @pl.when(r == pl.num_programs(1) - 1)
    def _():
        fin_ref[:, 0, :] = ch[0]
        if with_stats:
            fin_ref[:, 1, :] = ch[2]
            fin_ref[:, 2, :] = ch[3]
            fin_ref[:, 3:8, :] = jnp.zeros((G, 5, band), jnp.int32)
        else:
            fin_ref[:, 1:8, :] = jnp.zeros((G, 7, band), jnp.int32)


def batched_align_global_moves(
    qs: jnp.ndarray,
    qlens: jnp.ndarray,
    ts: jnp.ndarray,
    tlens: jnp.ndarray,
    params: AlignParams = AlignParams(),
    band: int | None = None,
    maxshift: int = 4,
    interpret: bool = False,
    with_stats: bool = True,
    gblock: int | None = None,
):
    """Batched global banded alignment with move emission (rotband v2).

    Drop-in for banded_pallas.batched_align_global_moves (same argument
    shapes, same (BandedResult, moves, offs) tuple, same gblock /
    CCSX_PALLAS_GBLOCK resolution outside the jit boundary); the moves
    come back un-rotated into the legacy band-local layout, so
    ops/traceback.py and every downstream consumer are byte-identical.
    """
    if gblock is None:
        import os

        raw = os.environ.get("CCSX_PALLAS_GBLOCK", "")
        try:
            gblock = int(raw) if raw else GBLOCK
        except ValueError:
            raise ValueError(
                f"CCSX_PALLAS_GBLOCK={raw!r}: expected an integer >= 1")
    if gblock < 1:
        raise ValueError(
            f"gblock/CCSX_PALLAS_GBLOCK must be >= 1, got {gblock}")
    return _batched_align_impl(
        qs, qlens, ts, tlens, params=params, band=band, maxshift=maxshift,
        interpret=interpret, with_stats=with_stats, gblock=gblock)


@functools.partial(
    jax.jit,
    static_argnames=("params", "band", "maxshift", "interpret",
                     "with_stats", "gblock"))
def _batched_align_impl(
    qs: jnp.ndarray,
    qlens: jnp.ndarray,
    ts: jnp.ndarray,
    tlens: jnp.ndarray,
    params: AlignParams,
    band: int | None,
    maxshift: int,
    interpret: bool,
    with_stats: bool,
    gblock: int,
):
    B = band if band is not None else params.band
    if B & (B - 1):
        # krel arithmetic is a bitwise mod; every real config is 128
        raise ValueError(f"rotband requires a power-of-two band, got {B}")
    if maxshift > 7:
        # d rides lane 0 of the ismatch tile in bits 1-3 (see _kernel_rot)
        raise ValueError(f"maxshift={maxshift} exceeds the 3-bit pack limit")
    lead = qs.shape[:-1]
    qmax = qs.shape[-1]
    if qmax > PALLAS_MAX_QMAX:
        raise ValueError(
            f"qmax={qmax} exceeds PALLAS_MAX_QMAX={PALLAS_MAX_QMAX}; "
            "use the scan aligner")
    n = 1
    for s in lead:
        n *= s
    qs_f = qs.reshape(n, qmax)
    qlens_f = qlens.reshape(n).astype(jnp.int32)
    ts_f = ts.reshape(n, ts.shape[-1])
    tlens_f = tlens.reshape(n).astype(jnp.int32)

    # pad the problem axis to a gblock multiple (pad rows: qlen 0, tlen 0)
    npad = -(-n // gblock) * gblock
    if npad != n:
        pad = npad - n
        qs_f = jnp.concatenate(
            [qs_f, jnp.full((pad, qmax), PAD, qs_f.dtype)])
        qlens_f = jnp.concatenate([qlens_f, jnp.zeros((pad,), jnp.int32)])
        ts_f = jnp.concatenate(
            [ts_f, jnp.full((pad, ts_f.shape[-1]), PAD, ts_f.dtype)])
        tlens_f = jnp.concatenate([tlens_f, jnp.zeros((pad,), jnp.int32)])

    offs = jax.vmap(
        lambda ql, tl: compute_offsets(ql, tl, qmax, B, maxshift)
    )(qlens_f, tlens_f)
    ismatch = jax.vmap(
        lambda q, t, o: compute_ismatch_rot(q, t, o, B, maxshift)
    )(qs_f, ts_f, offs)

    if qmax % ROWBLOCK != 0:
        raise ValueError(f"qmax={qmax} must be a multiple of {ROWBLOCK}")
    dmat = offs - jnp.concatenate(
        [jnp.zeros((npad, 1), jnp.int32), offs[:, :-1]], axis=1)
    rows = jnp.arange(1, qmax + 1, dtype=jnp.int32)
    live = (rows[None, :] <= qlens_f[:, None]).astype(jnp.int32)
    # bit-pack the per-row scalars into lane 0 of the ismatch tile (bit 0
    # match, bits 1-3 d, bit 4 live): bit 0 stays the match indicator on
    # every lane — including the rotated column lane 0 happens to hold
    aux = (((dmat & 7) << 1) | (live << 4)).astype(jnp.int8)
    lane_is0 = (jnp.arange(B, dtype=jnp.int32) == 0)[None, None, :]
    ismatch = jnp.where(lane_is0, ismatch | aux[:, :, None], ismatch)

    kern = functools.partial(
        _kernel_rot, qmax=qmax, band=B, maxshift=maxshift, params=params,
        with_stats=with_stats, gblock=gblock)
    nb = qmax // ROWBLOCK
    moves, fin = pl.pallas_call(
        kern,
        grid=(npad // gblock, nb),
        in_specs=[
            pl.BlockSpec((gblock, 1), lambda i, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gblock, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((gblock, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gblock, 8, B), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((npad, qmax, B), jnp.uint8),
            jax.ShapeDtypeStruct((npad, 8, B), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_CHG_ROT if with_stats else 2, gblock, B),
                       jnp.int32),
            pltpu.VMEM((gblock, 1), jnp.int32),
        ],
        interpret=interpret,
    )(tlens_f[:, None], ismatch)
    moves = moves[:n]
    fin = fin[:n]
    offs = offs[:n]
    qlens_f = qlens_f[:n]
    tlens_f = tlens_f[:n]

    # un-rotate the moves into the legacy band-local layout: legacy lane
    # kk of row i is column offs[i] + kk, which the kernel wrote to lane
    # (offs[i] + kk) & (B-1) — one batched gather, amortized over the
    # fill (the documented winner of the ISSUE's layout choice; the
    # in-kernel alternative is a per-problem barrel shifter, see module
    # docstring)
    idx = ((offs[:, :, None]
            + jnp.arange(B, dtype=jnp.int32)[None, None, :]) & (B - 1))
    moves = jnp.take_along_axis(moves, idx, axis=2)

    # final-row extraction: column tlen lives in lane tlen & (B-1)
    # (residue map), masked by band reachability as in ops/banded.py
    off_fin = offs[:, -1]
    laneT = tlens_f - off_fin
    reachable = (laneT >= 0) & (laneT < B)
    lane = tlens_f & (B - 1)
    take = jax.vmap(lambda f, l: f[:, l])(fin, lane)  # (n, 8)
    zeros = jnp.zeros(lead, jnp.int32)
    res = BandedResult(
        score=jnp.where(reachable, take[:, 0], NEG).reshape(lead),
        qb=jnp.zeros(lead, jnp.int32),
        qe=qlens_f.reshape(lead),
        tb=jnp.zeros(lead, jnp.int32),
        te=tlens_f.reshape(lead),
        aln=jnp.where(reachable, take[:, 2], 0).reshape(lead)
        if with_stats else zeros,
        mat=jnp.where(reachable, take[:, 1], 0).reshape(lead)
        if with_stats else zeros,
    )
    moves = moves.reshape(lead + (qmax, B))
    offs = offs.reshape(lead + (qmax,))
    return res, moves, offs
