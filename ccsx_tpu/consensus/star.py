"""Shared star-MSA machinery: one alignment+projection+vote round.

Both consensus paths build on this:
  * whole-read (consensus/whole_read.py) loops rounds and materializes;
  * windowed (consensus/windowed.py) additionally consumes the per-column
    stats for breakpoint detection and cursor bookkeeping.

A "round" aligns every pass (globally, banded) to the current draft,
projects each alignment onto draft coordinates, and votes per column.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Sequence

import jax
import numpy as np

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, banded_pallas, banded_rotband, msa, traceback


def pass_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def quantize_len(n: int, q: int) -> int:
    return max(q, -(-n // q) * q)


def bucket_len(n: int, q: int) -> int:
    """Geometric length bucket (~1.25x steps, q-aligned).

    Every distinct padded shape costs an XLA compile (tens of seconds on
    TPU); linear q-quantization makes the shape count linear in sequence
    length, this caps it at ~log.  Padding is masked, so results are
    shape-invariant; both the per-hole round and the batched executor use
    this SAME function, keeping their shapes (and jit caches) aligned.
    """
    b = q
    while b < n:
        b = max(b + q, (int(b * 1.25) // q) * q)
    return b


def pad_to(x: np.ndarray, n: int) -> np.ndarray:
    out = np.full(n, banded.PAD, np.uint8)
    out[: len(x)] = x
    return out


# set by force_scan_fallback when a Pallas lowering/compile failure is
# caught at dispatch (pipeline/batch.py recovery): the scan spec is
# always available, so a broken Mosaic toolchain degrades to the
# interpretable implementation instead of killing the run
_FORCE_SCAN = False


def force_scan_fallback(reason: str) -> bool:
    """Pin the banded fill to the lax.scan spec for the rest of this
    process (overriding CCSX_BANDED_IMPL=pallas/rotband).  Returns True
    the first time — the caller should retry its dispatch — and False if
    the scan was already forced (the failure is not the kernel's)."""
    global _FORCE_SCAN
    if _FORCE_SCAN:
        return False
    _FORCE_SCAN = True
    import sys

    print("[ccsx-tpu] Pallas kernel failed to lower/compile; falling "
          f"back to the banded-scan spec for this run ({reason})",
          file=sys.stderr)
    return True


def banded_impl() -> str:
    """Banded DP-fill implementation choice: 'scan' (the lax.scan spec,
    default), 'pallas' (the v1 band-local G-batched kernel,
    ops/banded_pallas.py) or 'rotband' (the v2 rotating-band kernel,
    ops/banded_rotband.py).  CCSX_BANDED_IMPL selects; the
    compile-failure fallback (force_scan_fallback) overrides everything.
    All three are bit-identical in global+moves mode — the scan is the
    spec, both kernels are differential-tested against it
    (tests/test_banded_pallas.py three-way fuzz, interpret mode on CPU;
    the v1 kernel additionally proven on real v5e 2026-07-29 with
    interpret=False) — so the knob is non-semantic
    (fingerprint._NON_SEMANTIC) and free to A/B.

    PROMOTION PROTOCOL (r14, supersedes the r5 timing discussion that
    used to live here): every pre-r14 hardware timing — scan ahead of
    the kernel in all of them — was taken with per-iteration
    block_until_ready loops, which the lazy axon runtime turns into
    RPC-latency readings (bench.py docstring); they order the arms
    consistently but none is a chip time.  The decision now rests on
    benchmarks/pallas_ab.py, which times all three arms under the
    forced-execution marginal method only and emits a machine-readable
    decision record (winner, margin, backend, method) that bench.py
    vs_prev gates.  The scan stays the default until a decision record
    from a real device backend names a kernel the winner; the rotband
    kernel is the structural attack on why v1 lost (the ~24-op per-row
    select chain is replaced by residue-lane masks, ~60 -> ~45 tile
    ops/row — audit in the banded_rotband.py docstring).  Per-dispatch
    attribution is visible as the ccsx_banded_impl counter in /metrics
    and the :b<impl> trace-group suffix."""
    if _FORCE_SCAN:
        return "scan"
    impl = os.environ.get("CCSX_BANDED_IMPL", "")
    if impl not in ("", "scan", "pallas", "rotband"):
        raise ValueError(
            f"CCSX_BANDED_IMPL={impl!r}: expected 'scan', 'pallas' or "
            "'rotband'")
    return impl or "scan"


def banded_impl_effective(qmax: int) -> str:
    """The implementation _aligner actually dispatches at this qmax: the
    kernels gate on the qmax cap and row-block alignment and fall back
    to the scan spec (same guard for v1 and v2)."""
    impl = banded_impl()
    if impl != "scan" and (qmax > banded_pallas.PALLAS_MAX_QMAX
                           or qmax % banded_pallas.ROWBLOCK != 0):
        return "scan"
    return impl


def use_pallas() -> bool:
    """True iff a Pallas kernel (v1 or v2) is selected — kept for the
    profiler/battery reports; dispatch goes through banded_impl()."""
    return banded_impl() != "scan"


@functools.lru_cache(maxsize=8)
def _aligner(params: AlignParams):
    # one jitted aligner per scoring config; shape specialization is
    # handled by jit's own trace cache, so distinct (qmax, tmax) buckets
    # reuse this callable instead of rebuilding it.  The impl choice is
    # re-evaluated per call so CCSX_BANDED_IMPL works after first use.
    # with_stats=False: the consensus rounds use only (moves, offs); the
    # slim carry drops the dead mat/aln channels from the DP scan
    scan_f = banded.make_batched("global", params, with_moves=True,
                                 with_stats=False)

    def f(qs, qlens, ts, tlens):
        impl = banded_impl_effective(qs.shape[-1])
        if impl == "scan":
            return scan_f(qs, qlens, ts, tlens)
        # with_stats=False for the kernels too: the rounds read only
        # (moves, offs), and the slim carry (3 rows vs 7 / 2 vs 6, a
        # 1-array F scan vs 3) cuts most of the per-cell op count
        mod = banded_rotband if impl == "rotband" else banded_pallas
        return mod.batched_align_global_moves(
            qs, qlens, ts, tlens, params, with_stats=False,
            interpret=jax.default_backend() != "tpu")

    return f


@functools.lru_cache(maxsize=64)
def _projector(tmax: int, max_ins: int):
    projector = traceback.make_projector(tmax, max_ins)
    return jax.jit(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)))


@functools.lru_cache(maxsize=8)
def _voter(max_ins: int):
    return msa.make_voter(max_ins)


@dataclasses.dataclass
class RoundRequest:
    """One star-MSA round of device work, requested by a consensus
    generator (windowed.windowed_gen / StarMsa.consensus_gen).

    The per-hole path satisfies these one at a time (run_rounds); the
    batched pipeline (pipeline/batch.py) stacks requests of equal shape
    from many holes into one (Z, P, W) device dispatch.
    """

    qs: np.ndarray        # (P, qmax) uint8 padded passes
    qlens: np.ndarray     # (P,) int32
    row_mask: np.ndarray  # (P,) bool
    draft: np.ndarray     # (tlen,) uint8 codes — alignment target


@dataclasses.dataclass
class RefineRequest:
    """One WINDOW's entire refinement loop (iters speculative rounds +
    the final strict round), requested as a single unit of device work.

    The per-hole path satisfies it with the host loop (refine_host — the
    spec); the batched pipeline runs it as ONE fused device dispatch
    whose intermediate speculative drafts never leave the chip — the
    dominant dispatch-count reduction of the framework (one launch per
    window instead of iters+1).  By default the executor strips the
    pass-bucket padding back off and packs only the row_mask rows into a
    shared slab with other holes' rows (pipeline/pack.py +
    batch._refine_step_packed); the (P, qmax) request shape with its
    padded rows is still what the host replay, the bucketed
    --pass-buckets control (batch._refine_step), and the --mesh
    shardings consume, and the result's ``advance`` always comes back in
    this request's (P,) pass order whichever executor ran."""

    qs: np.ndarray        # (P, qmax) uint8 padded passes
    qlens: np.ndarray     # (P,) int32
    row_mask: np.ndarray  # (P,) bool
    draft: np.ndarray     # (tlen,) uint8 codes — initial alignment target
    iters: int            # speculative refinement rounds before the final


@dataclasses.dataclass
class RefineResult:
    """Result of one window's refinement: the final round, plus the
    strict draft materialized LAZILY — non-final windows consume only
    ``rr`` (materialize(upto=bp) + advance), so they never pay for the
    full-draft materialization."""

    rr: "RoundResult"     # the final round (windowed needs bp/advance)
    _draft: "np.ndarray | None" = dataclasses.field(
        default=None, repr=False)

    @property
    def draft(self) -> np.ndarray:
        if self._draft is None:
            self._draft = self.rr.materialize(speculative=False)
        return self._draft


def run_rounds(gen, sm: "StarMsa"):
    """Drive a consensus generator with immediate per-hole device work."""
    try:
        req = next(gen)
        while True:
            if isinstance(req, RefineRequest):
                res = refine_host(sm.round, req.qs, req.qlens,
                                  req.row_mask, req.draft, req.iters)
                req = gen.send(res)
            else:
                rr = sm.round(req.qs, req.qlens, req.row_mask, req.draft)
                req = gen.send(rr)
    except StopIteration as e:
        return e.value


def refine_host(round_fn, qs, qlens, row_mask, draft, iters: int) -> "RefineResult":
    """THE refinement-loop spec: iters speculative rounds + a final one,
    with a fixpoint early-exit.

    When a speculative round leaves the draft unchanged, a re-round on
    it would return the same RoundResult (the round is a pure function
    of its request), so the remaining speculative rounds are no-ops and
    the final strict output is this round's strict materialization —
    the rounds are skipped, bit-identically (tested in
    test_consensus.py).  The strict draft itself is lazy
    (RefineResult.draft), so callers that consume only the final round
    never materialize it.  The fused device step replicates exactly this
    loop (per-hole fixpoint masking included) and is differential-tested
    against it (tests/test_refine_fused.py)."""
    rr = None
    it = 0
    while True:
        rr = round_fn(qs, qlens, row_mask, draft)
        if it == iters:
            break
        new_draft = rr.materialize(speculative=True)
        if np.array_equal(new_draft, draft):
            break
        draft = new_draft
        it += 1
    return RefineResult(rr=rr)


def refine_rounds_gen(qs, qlens, row_mask, draft, iters: int):
    """Request one window's refinement from the driving executor; returns
    the RefineResult (final round + lazy strict draft), whichever
    executor (per-hole host loop or fused batched device step)
    satisfies it."""
    res = yield RefineRequest(qs, qlens, row_mask, draft, iters)
    return res


@dataclasses.dataclass
class RoundResult:
    """Device arrays from one star-MSA round (draft coordinates).

    The per-hole path fills every field (host breakpoint scan needs the
    per-pass tensors).  The batched pipeline computes the breakpoint and
    cursor advance ON DEVICE (ops/breakpoint.py) and transfers only the
    small fields, leaving match/aligned/ins_cnt/lead_ins as None and
    setting bp/advance instead — consumers must branch on bp (the
    windowed generator does)."""

    cons: np.ndarray      # (T,) uint8: 0-3 base, 4 gap
    ins_base: np.ndarray  # (T, R) uint8 majority inserted base per slot/rank
    ins_votes: np.ndarray  # (T, R) int32 supporting passes per slot/rank
    ncov: np.ndarray      # (T,) int32 covering passes
    tlen: int
    nwin: np.ndarray | None = None     # (T,) int32 winning-cell votes
    match: np.ndarray | None = None    # (P, T) bool: pass matches consensus
    aligned: np.ndarray | None = None  # (P, T) uint8 projection
    ins_cnt: np.ndarray | None = None  # (P, T) int32 insertion counts
    lead_ins: np.ndarray | None = None  # (P,) int32 bases before column 0
    bp: int | None = None              # device breakpoint (-1 = none)
    advance: np.ndarray | None = None  # (P,) int32 bases consumed @ bp_eff

    def ins_out(self, speculative: bool = False) -> np.ndarray:
        return msa.emit_insertions(self.ins_base, self.ins_votes,
                                   self.ncov, speculative)

    def materialize(self, upto: int | None = None,
                    speculative: bool = False) -> np.ndarray:
        n = self.tlen if upto is None else upto
        return msa.materialize(self.cons, self.ins_out(speculative), n)

    def materialize_with_qual(self, upto: int | None = None,
                              speculative: bool = False,
                              qv_coeffs: tuple = (8.0, 3.0, 6.0, 5, 1.0,
                                                  7.0, 4),
                              qmax: int = 60):
        """(codes, quals): the materialized consensus plus a per-base
        Phred-scale confidence from the coverage-conditioned vote margin.

        Q = clip(round(base + per_s*min(s, knee)
                       + per_s_tail*max(s - knee, 0) - per_d*d), 1, qmax)
        with qv_coeffs = (base, per_s, per_d, knee, per_s_tail[, per_hp,
        hp_cap]).  The homopolymer coefficients (positions 5-6) are NOT
        applied here: run lengths must be computed on the FINAL
        assembled consensus, and the windowed path materializes one
        chunk at a time (a run spanning a window breakpoint would be
        split and under-penalized) — callers apply
        ``apply_hp_penalty`` after assembly (windowed_gen in windowed.py,
        consensus_gen below).  Here a
        base column's support s is nwin (passes voting the winning cell)
        out of ncov covering passes and d = ncov - s dissent; an
        insertion column's s is its ins_votes rank count.  The shape is
        fitted to the measured per-(s, d) error table on the synthetic
        pass distribution (r4 study): one dissenting pass costs ~8 Q at
        fixed support while each supporter adds only ~3, and the
        unanimous-column error plateaus near Q27-28 at s=6-7 (correlated
        homopolymer/stitch errors extra coverage cannot vote away) —
        hence the knee.  The earlier single net-vote slope (2.5 per net
        vote) conflated "low-coverage unanimous" (much better than
        predicted) with "high-coverage with dissent" (worse), producing
        a non-monotone mid-range (VERDICT r3 weak 7).  This is a
        vote-margin confidence, NOT a calibrated HiFi QV model; the
        reference emits no qualities at all (FASTA only, main.c:714).
        """
        n = self.tlen if upto is None else upto
        ins = self.ins_out(speculative)
        cons = np.asarray(self.cons)[:n]
        m = np.concatenate([cons[:, None], np.asarray(ins)[:n]],
                           axis=1)
        ncov = np.asarray(self.ncov).astype(np.int32)[:n, None]
        support = np.concatenate(
            [np.asarray(self.nwin).astype(np.int32)[:n, None],
             np.asarray(self.ins_votes).astype(np.int32)[:n]], axis=1)
        dissent = ncov - support
        base, per_s, per_d, knee, per_s_tail = qv_coeffs[:5]
        sterm = (per_s * np.minimum(support, knee)
                 + per_s_tail * np.maximum(support - knee, 0))
        q = base + sterm - per_d * dissent
        keep = m.ravel() < 4
        codes = m.ravel()[keep].astype(np.uint8)
        return (codes, np.clip(np.rint(q.ravel()[keep]),
                               1, qmax).astype(np.uint8))


def apply_hp_penalty(codes: np.ndarray, quals: np.ndarray,
                     qv_coeffs: tuple) -> np.ndarray:
    """Homopolymer-run QV penalty on the FINAL assembled consensus.

    Q -= per_hp * min(run - 1, hp_cap) with `run` the homopolymer run
    length containing each emitted base (insertions included), then
    re-clipped to >= 1.  Homopolymer indels are correlated across
    passes, so a unanimous column in a long run can be unanimously
    wrong — the r5 correlated-error study (benchmarks/quality.py)
    measures ~6-9 observed Q lost per run unit at fixed vote margin
    (config.py qv_per_hp discussion).  Applied after chunk assembly —
    NOT inside materialize_with_qual — so runs spanning window
    breakpoints are penalized at their true length; the whole-read and
    windowed paths therefore agree on quals for the same sequence.
    The penalty applies to the already-qv_cap-clipped Q; with the
    default coefficients raw Q maxes at 50 (s=32: 8 + 3*5 + 1*27) below
    qv_cap=60, so pre- vs post-cap order is indistinguishable there.
    A 5-tuple qv_coeffs (r4 behavior) is a no-op."""
    per_hp, hp_cap = qv_coeffs[5:7] if len(qv_coeffs) > 5 else (0.0, 0)
    if not per_hp or not len(codes):
        return quals
    # vectorized run lengths: each run's length broadcast to its members
    change = np.flatnonzero(np.diff(codes)) + 1
    bounds = np.concatenate([[0], change, [len(codes)]])
    runs = np.repeat(np.diff(bounds), np.diff(bounds))
    q = quals.astype(np.int32) - np.rint(
        per_hp * np.minimum(runs - 1, hp_cap)).astype(np.int32)
    return np.maximum(q, 1).astype(np.uint8)


class StarMsa:
    def __init__(self, params: AlignParams, max_ins: int = 4,
                 len_quant: int = 512):
        self.params = params
        self.max_ins = max_ins
        self.len_quant = len_quant

    def round(self, qs: np.ndarray, qlens: np.ndarray, row_mask: np.ndarray,
              draft: np.ndarray) -> RoundResult:
        """qs: (P, qmax) uint8 padded passes; draft: (tlen,) codes."""
        P, qmax = qs.shape
        tlen = len(draft)
        tmax = bucket_len(tlen, self.len_quant)
        aligner = _aligner(self.params)
        projector_b = _projector(tmax, self.max_ins)
        voter = _voter(self.max_ins)
        ts = np.ascontiguousarray(
            np.broadcast_to(pad_to(draft, tmax), (P, tmax)))
        tlens = np.full(P, tlen, np.int32)
        _, moves, offs = aligner(qs, qlens, ts, tlens)
        aligned, ins_cnt, ins_b, lead_ins = projector_b(
            moves, offs, qs, qlens, np.int32(tlen))
        cons, ins_base, ins_votes, ncov, match, nwin = voter(
            aligned, ins_cnt, ins_b, row_mask)
        return RoundResult(
            cons=np.asarray(cons), ins_base=np.asarray(ins_base),
            ins_votes=np.asarray(ins_votes),
            ncov=np.asarray(ncov), nwin=np.asarray(nwin),
            match=np.asarray(match),
            aligned=np.asarray(aligned), ins_cnt=np.asarray(ins_cnt),
            lead_ins=np.asarray(lead_ins), tlen=tlen,
        )

    def pack(self, passes: List[np.ndarray], pass_buckets: Sequence[int],
             max_passes: int, qmax: int | None = None):
        """Pad a pass list to (P, qmax) + lens + row mask."""
        if len(passes) > max_passes:
            passes = passes[:max_passes]
        P = pass_bucket(len(passes), pass_buckets)
        # an undersized bucket list must fail loudly here, not ship a
        # raw-pass-count shape that silently defeats bucketing (one XLA
        # compile per distinct count); the CLI validates buckets vs
        # max_passes up front — this guards library callers
        if P < len(passes):
            raise ValueError(
                f"pass_buckets {tuple(pass_buckets)} do not cover "
                f"{len(passes)} passes (max_passes={max_passes})")
        if qmax is None:
            qmax = bucket_len(max(len(p) for p in passes), self.len_quant)
        qs = np.stack(
            [pad_to(p, qmax) for p in passes]
            + [np.full(qmax, banded.PAD, np.uint8)] * (P - len(passes)))
        qlens = np.array(
            [len(p) for p in passes] + [0] * (P - len(passes)), np.int32)
        return qs, qlens, qlens > 0

    def consensus_gen(self, passes: List[np.ndarray], iters: int,
                      pass_buckets: Sequence[int], max_passes: int,
                      quality: "tuple | None" = None):
        """Generator form of consensus(): yields one RefineRequest,
        receives a RefineResult, returns the final draft — or
        (draft, phred_quals) when ``quality=(qv_coeffs, qv_cap)``
        — via StopIteration.value."""
        qs, qlens, row_mask = self.pack(passes, pass_buckets, max_passes)
        res = yield from refine_rounds_gen(
            qs, qlens, row_mask, passes[0], iters)
        if quality is not None:
            codes, quals = res.rr.materialize_with_qual(
                speculative=False, qv_coeffs=quality[0],
                qmax=quality[1])
            return codes, apply_hp_penalty(codes, quals, quality[0])
        return res.draft

    def consensus(self, passes: List[np.ndarray], iters: int,
                  pass_buckets: Sequence[int], max_passes: int,
                  quality: "tuple | None" = None):
        """iters+1 rounds; intermediate rounds insert speculatively (see
        msa.emit_insertions), the final round applies strict majority."""
        return run_rounds(
            self.consensus_gen(passes, iters, pass_buckets, max_passes,
                               quality), self)
