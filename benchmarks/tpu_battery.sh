#!/bin/sh
# End-of-round TPU measurement battery (r5b order).  Run when the
# tunnel is healthy; each step is its own process.  ALL timing uses the
# forced-execution marginal method (bench.py docstring): the lazy axon
# runtime neither blocks in block_until_ready nor executes unfetched
# dispatches, so only fori_loop+checksum+fetch numbers are real.
#
# Every battery entry now runs with the dispatch flight recorder
# (--trace / CCSX_BENCH_TRACE, utils/trace.py) and a LIVE stall
# watchdog, so a mid-battery hang leaves thread stacks + the in-flight
# shape group behind instead of another diagnostics-free dead tunnel
# (the r5 failure mode).  Entries that bypass the CLI (round_profile,
# pallas_ab) get a process-level `timeout` so a hang cannot block the
# rest of the battery; summarize any trace afterwards with
#   python -m ccsx_tpu.cli stats benchmarks/trace_r06_*.jsonl
#
#   sh benchmarks/tpu_battery.sh            # full battery
set -x
cd "$(dirname "$0")/.."

# (1) the honest round number + compile-cache warm for the driver's
# end-of-round bench; every e2e config records its span trace and the
# per-shape-group compile/execute table rides the JSON artifact
CCSX_BENCH_WATCHDOG=2400 CCSX_BENCH_TRACE=benchmarks/trace_r06_bench \
    python bench.py | tee benchmarks/bench_tpu_r06.json

# (2) e2e at scale over the packed transfer protocol (the CLI writes
# real output files, so its wall-clock numbers are honest everywhere);
# --trace gives the Perfetto-loadable dispatch timeline and the default
# 120 s stall watchdog is live through the CLI
python benchmarks/e2e_scale.py --holes 256 --inflight 64 \
    --trace benchmarks/trace_r06_scale.jsonl \
    --json benchmarks/e2e_scale_r06_packed.json

# (2b) AOT-warmup A/B (r8): same scale config with the warmup
# precompiler on (default) vs --no-warmup.  The warmup arm's trace
# must show warmup spans booking the compiles and first dispatches
# booking as execute; the wall-clock delta is the cold-compile time
# the overlap hid.  Untraced so the async dispatch overlap is the
# thing measured; the watchdog stays live regardless.
python benchmarks/e2e_scale.py --holes 128 --inflight 64 \
    --skip-round --floor-holes 0 \
    --json benchmarks/e2e_scale_r08_warmup_on.json
python benchmarks/e2e_scale.py --holes 128 --inflight 64 \
    --skip-round --floor-holes 0 --no-warmup \
    --json benchmarks/e2e_scale_r08_warmup_off.json

# (3) honest per-stage round profile + op-level jax.profiler trace
# (the artifact the roofline claim is checked against), then the
# scan-projector A/B.  These harnesses bypass the CLI, so the hang
# guard is a hard process timeout (rc 124 = the step hung)
timeout -k 30 2400 \
    python benchmarks/round_profile.py --trace-dir benchmarks/trace_r06 \
    --json benchmarks/round_profile_r06.json
CCSX_PROJECTOR=scan timeout -k 30 2400 \
    python benchmarks/round_profile.py \
    --json benchmarks/round_profile_r06_scanproj.json

# (4) DP-kernel promotion harness with the honest marginal method:
# three interleaved arms (scan / band-local pallas v1 / rotating-band
# rotband v2), hardware bit-exactness for BOTH kernels first, then
# the timed run whose "decision" record (winner, margin, backend,
# method) is what bench.py's vs_prev dp-kernel leg gates and what the
# promotion protocol in consensus/star.py acts on
timeout -k 30 1200 python benchmarks/pallas_ab.py --mode check
timeout -k 30 2400 python benchmarks/pallas_ab.py --mode time \
    --gblocks 8,16,32 --json benchmarks/pallas_ab_tpu_r07.json
