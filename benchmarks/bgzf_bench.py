"""BGZF ingest micro-benchmark (SURVEY §7.3 item 6; VERDICT r2 item 7).

Writes a synthetic BGZF subreads.bam and times the native reader's full
ingest path (block-parallel inflate + BAM record parse + nibble decode)
at several thread counts, plus Python gzip decompression as a floor
reference.  Reports uncompressed MB/s.

Usage: python benchmarks/bgzf_bench.py [--mb N] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu.io import bam as bam_mod                       # noqa: E402


def make_bam(path, target_mb: int):
    rng = np.random.default_rng(0)
    recs = []
    seqlen = 20000
    total = 0
    i = 0
    while total < target_mb * (1 << 20):
        seq = rng.choice(list(b"ACGT"), seqlen).astype(np.uint8).tobytes()
        recs.append((f"mv/{i // 8}/{i}_{i + seqlen}", seq,
                     b"\x28" * seqlen))
        total += seqlen
        i += 1
    bam_mod.write_bam(path, recs, bgzf=True)
    return len(recs), total


def time_native(path, threads: int):
    from ccsx_tpu.native.io import read_records_native

    os.environ["CCSX_BGZF_THREADS"] = str(threads)
    t0 = time.perf_counter()
    n = 0
    nbytes = 0
    for r in read_records_native(path, is_bam=True):
        n += 1
        nbytes += len(r.seq)
    dt = time.perf_counter() - t0
    del os.environ["CCSX_BGZF_THREADS"]
    return {"threads": threads, "records": n,
            "mb_per_s": round(nbytes / dt / (1 << 20), 1),
            "seconds": round(dt, 3)}


def time_pool(path, threads: int, iters: int = 3):
    """Decoupled pool measurement: all compressed blocks pre-read into
    memory, `threads` workers inflate them with atomic work-claiming —
    no file IO, no record parse, no ordered hand-off (VERDICT r3 item 6:
    measure the pool, not the reader)."""
    from ccsx_tpu import native

    L = native.lib()
    if L is None:
        return None
    v = L.ccsx_bgzf_pool_bench(path.encode(), threads, iters)
    return {"threads": threads, "mb_per_s": round(v, 1)} if v > 0 else None


def time_python_gzip(path):
    import gzip

    t0 = time.perf_counter()
    with gzip.open(path, "rb") as f:
        n = len(f.read())
    dt = time.perf_counter() - t0
    return {"mb_per_s": round(n / dt / (1 << 20), 1),
            "seconds": round(dt, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64)
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    res = {"uncompressed_mb": a.mb,
           "host_cores": os.cpu_count()}
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "bench.bam")
        nrec, nbytes = make_bam(p, a.mb)
        res["bam_compressed_mb"] = round(os.path.getsize(p) / (1 << 20), 1)
        res["python_gzip_inflate_only"] = time_python_gzip(p)
        for t in (1, 2, 4, 8):
            res[f"native_t{t}"] = time_native(p, t)
        for t in (1, 2, 4, 8):
            res[f"pool_t{t}"] = time_pool(p, t)
    if res.get("host_cores") == 1:
        res["note"] = (
            "host has 1 core: no inflate parallelism is physically "
            "available, so flat/negative scaling here measures the host, "
            "not the pool; the pool_t* decoupled curve is the number to "
            "read on a multi-core host")
    print(json.dumps(res, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
