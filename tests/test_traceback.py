"""Differential tests: device traceback projection vs oracle projection."""

import numpy as np
import pytest

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, oracle, traceback
from ccsx_tpu.utils import synth

P = AlignParams()
SCORES = dict(match=P.match, mismatch=P.mismatch,
              gap_open=P.gap_open, gap_extend=P.gap_extend)
QMAX = TMAX = 256
MAXINS = 4


def _pad(x, n):
    out = np.full(n, banded.PAD, dtype=np.uint8)
    out[: len(x)] = x
    return out


def project_device(q, t):
    res, moves, offs = banded.banded_align(
        _pad(q, QMAX), np.int32(len(q)), _pad(t, TMAX), np.int32(len(t)),
        mode="global", with_moves=True,
    )
    proj = traceback.make_projector(TMAX, MAXINS)
    aligned, ins_cnt, ins_b, lead = proj(moves, offs, _pad(q, QMAX),
                                         np.int32(len(q)), np.int32(len(t)))
    return (int(res.score), np.array(aligned), np.array(ins_cnt),
            np.array(ins_b), int(lead))


def project_oracle(q, t):
    rs = oracle.align(q, t, mode="global", **SCORES)
    aligned, ins_len, ins_bases, _ = oracle.project_to_template(
        rs, q, len(t), MAXINS)
    return rs.score, aligned, ins_len, ins_bases


def check_consistency(q, t, aligned, ins_cnt, ins_b, lead=0):
    """Structural invariants that hold for ANY valid global alignment."""
    T = len(t)
    # every template column consumed exactly once
    assert (aligned[:T] != traceback.PAD).all()
    assert (aligned[T:] == traceback.PAD).all()
    # query bases conserved: matches/mismatches + insertions == len(q)
    consumed = int((aligned[:T] < 4).sum() + ins_cnt[:T].sum() + lead)
    assert consumed == len(q)
    assert ins_cnt[T:].sum() == 0
    # stored insertion cells agree with counts
    used = np.minimum(ins_cnt[:T], MAXINS)
    stored = (ins_b[:T] != traceback.PAD).sum(axis=1)
    assert np.array_equal(stored, used)


def test_identical_projection():
    t = np.array([0, 1, 2, 3] * 10, dtype=np.uint8)
    score, aligned, ins_cnt, ins_b, lead = project_device(t, t)
    assert np.array_equal(aligned[: len(t)], t)
    assert ins_cnt.sum() == 0


@pytest.mark.parametrize("trial", range(6))
def test_projection_matches_oracle(trial):
    rng = np.random.default_rng(100 + trial)
    t = rng.integers(0, 4, int(rng.integers(60, 200))).astype(np.uint8)
    q = synth.mutate(rng, t, 0.03, 0.05, 0.05)
    if len(q) > QMAX:
        q = q[:QMAX]
    d_score, d_al, d_cnt, d_b, d_lead = project_device(q, t)
    o_score, o_al, o_cnt, o_b = project_oracle(q, t)
    assert d_score == o_score
    check_consistency(q, t, d_al, d_cnt, d_b, d_lead)
    # projections may differ between co-optimal paths; they must agree on
    # the vast majority of columns
    T = len(t)
    agree = (d_al[:T] == o_al).mean()
    assert agree > 0.9, agree


def test_insertion_content():
    rng = np.random.default_rng(7)
    t = rng.integers(0, 4, 100).astype(np.uint8)
    # insert a known 2-base motif after column 50
    q = np.concatenate([t[:50], np.array([2, 2], np.uint8), t[50:]])
    _, aligned, ins_cnt, ins_b, _lead = project_device(q, t)
    assert ins_cnt[:100].sum() == 2
    slot = int(np.nonzero(ins_cnt[:100])[0][0])
    n = int(ins_cnt[slot])
    assert (ins_b[slot, :n] == 2).all()


def test_deletion_marked():
    rng = np.random.default_rng(8)
    t = rng.integers(0, 4, 100).astype(np.uint8)
    q = np.delete(t, 60)
    _, aligned, ins_cnt, ins_b, _lead = project_device(q, t)
    assert (aligned[:100] == 4).sum() == 1


@pytest.mark.parametrize("trial", range(10))
def test_scan_projector_bit_exact_vs_reference(trial):
    """The row-scan projector must reproduce the cell-walk reference
    BIT-EXACTLY on every output (aligned, ins_cnt, ins_b, lead) — the
    fused batch path is pinned bit-exact downstream, so the projector
    swap must be invisible.  Trials cover heavy indel rates (long gap
    runs), insertion bursts past max_ins (rank truncation), short
    templates, and the qlen=0 padding row."""
    rng = np.random.default_rng(500 + trial)
    if trial == 9:
        q = np.zeros(0, np.uint8)          # padding row
        t = rng.integers(0, 4, 80).astype(np.uint8)
    else:
        t = rng.integers(0, 4, int(rng.integers(20, 220))).astype(np.uint8)
        sub, ins, dele = [(0.02, 0.04, 0.04), (0.05, 0.20, 0.05),
                          (0.05, 0.05, 0.20), (0.1, 0.15, 0.15)][trial % 4]
        q = synth.mutate(rng, t, sub, ins, dele)[:QMAX]
        if trial == 8:  # insertion burst: 7 bases at one spot (> max_ins)
            q = np.concatenate([t[:10],
                                rng.integers(0, 4, 7).astype(np.uint8),
                                t[10:]])[:QMAX]
    _, moves, offs = banded.banded_align(
        _pad(q, QMAX), np.int32(len(q)), _pad(t, TMAX), np.int32(len(t)),
        mode="global", with_moves=True)
    fast = traceback.make_projector_scan(TMAX, MAXINS)
    ref = traceback.make_projector_reference(TMAX, MAXINS)
    args = (moves, offs, _pad(q, QMAX), np.int32(len(q)), np.int32(len(t)))
    a1, c1, b1, l1 = (np.asarray(x) for x in fast(*args))
    a2, c2, b2, l2 = (np.asarray(x) for x in ref(*args))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(b1, b2)
    assert int(l1) == int(l2)
