"""Resilient execution layer (ISSUE 9, ARCHITECTURE.md "Resilient
execution"): dispatch deadlines with host-fallback recovery, the
backend circuit breaker, the failure-rate abort, and the exit-code
taxonomy.

Load-bearing guarantees pinned here: a PERMANENT injected device hang
(device_hang fault + --dispatch-deadline) completes with output
byte-identical to the fault-free run at rc 0 with the degraded mark —
no human intervention, no infinite stall; a tripped breaker completes
the run on the host path byte-identically; a half-open probe closes
the breaker on success and re-opens it on failure; --max-failed-holes
exits rc 2 instead of emitting a near-empty output at rc 0; and the
documented exit codes cannot drift silently.

The CLI tests share the SAME synthetic corpus geometry as
tests/test_faults.py (700 bp, 5 passes) so the process-wide jit cache
is shared across the two files in tier-1.
"""

import json
import os
import time
import types

import numpy as np
import pytest

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.pipeline import batch as batch_mod
from ccsx_tpu.pipeline.batch import _run_groups_recovering, classify_failure
from ccsx_tpu.pipeline.resilience import (CircuitBreaker, DeadlineExpired,
                                          Resilience, bounded_call)
from ccsx_tpu.utils import faultinject, synth
from ccsx_tpu.utils.metrics import (FailureBudgetExceeded, Metrics,
                                    check_failure_budget)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.disarm()
    yield
    faultinject.disarm()


@pytest.fixture(autouse=True)
def _fast_grace(monkeypatch):
    """Unit-scale deadline budgets: grace x1 (a 2 s deadline means 2 s
    even for first-of-shape calls) and a bounded hang sleep so the
    abandoned daemon threads don't outlive the suite by an hour."""
    monkeypatch.setenv("CCSX_DEADLINE_GRACE", "1")
    monkeypatch.setenv("CCSX_FAULT_HANG_S", "60")


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(input fasta, fault-free reference output) — identical geometry
    to tests/test_faults.py's corpus (shared jit cache)."""
    tmp = tmp_path_factory.mktemp("resil")
    rng = np.random.default_rng(0)
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(3)]
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    return fa, ref


def _final(mpath):
    return [json.loads(line) for line in mpath.read_text().splitlines()][-1]


# ---------- units: bounded calls + taxonomy ----------

def test_bounded_call_semantics():
    assert bounded_call(lambda: 42, 0) == 42          # inline fast path
    assert bounded_call(lambda: 42, 5.0) == 42        # bounded, in time
    with pytest.raises(ValueError, match="boom"):     # exceptions surface
        bounded_call(lambda: (_ for _ in ()).throw(ValueError("boom")),
                     5.0)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExpired, match="exceeded"):
        bounded_call(lambda: time.sleep(30), 0.2, "g", "dispatch")
    # the waiter returns promptly; the wedged thread is left parked
    assert time.monotonic() - t0 < 5.0


def test_classify_failure_hang():
    assert classify_failure(
        DeadlineExpired("packed:q1024", "dispatch", 2.0)) == "hang"
    # the existing classes are untouched
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: x")) == "oom"
    assert classify_failure(ValueError("bad draft")) == "data"


def test_deadline_grace_first_call_only():
    cfg = types.SimpleNamespace(dispatch_deadline_s=2.0,
                                breaker_strikes=3, breaker_window_s=60.0,
                                breaker_probe_s=0.0)
    r = Resilience(cfg)
    assert r.grace == 1.0  # _fast_grace fixture
    b1 = r.budget("g", "dispatch")
    b2 = r.budget("g", "dispatch")
    assert b1 == b2 == 2.0
    os.environ["CCSX_DEADLINE_GRACE"] = "10"
    try:
        r = Resilience(cfg)
        assert r.budget("g", "dispatch") == 20.0   # first: compile grace
        assert r.budget("g", "dispatch") == 2.0    # steady state
        assert r.budget("g", "materialize") == 20.0  # per-phase first
    finally:
        os.environ["CCSX_DEADLINE_GRACE"] = "1"


# ---------- units: circuit breaker ----------

def test_breaker_trips_and_probes():
    m = Metrics()
    b = CircuitBreaker(strikes=2, window_s=60.0, probe_s=0.05, metrics=m)
    assert b.admit() == "closed" and b.state == "closed"
    b.strike("oom", "g")
    assert b.admit() == "closed"          # one strike: still closed
    b.strike("hang", "g")
    assert b.state == "open" and m.breaker_trips == 1
    assert b.admit() == "host"            # open, probe not due yet
    time.sleep(0.06)
    assert b.admit() == "probe"           # half-open probe admitted
    assert b.state == "half-open" and m.breaker_probes == 1
    assert b.admit() == "host"            # only ONE probe in flight
    b.strike("oom", "g", probe=True)      # probe failed: re-open
    assert b.state == "open"
    time.sleep(0.06)
    assert b.admit() == "probe"           # next probe
    b.probe_succeeded()                   # THE probe succeeded: closed
    assert b.state == "closed" and m.breaker_state == "closed"
    assert b.admit() == "closed"
    # strike log is bounded and rides Metrics
    assert len(m.breaker_strike_log) == 3
    assert {s["kind"] for s in m.breaker_strike_log} == {"oom", "hang"}


def test_breaker_probe_verdict_is_token_bound():
    """A pre-trip group finishing mid-probe must not close the breaker
    (stale evidence), and a non-probe data failure must not steal the
    probe's settlement."""
    b = CircuitBreaker(strikes=1, window_s=60.0, probe_s=0.05)
    b.strike("oom", "g")
    assert b.state == "open"
    time.sleep(0.06)
    assert b.admit() == "probe"
    # non-probe strike while the probe is in flight: counted/ignored,
    # but the probe stays outstanding (state half-open)
    b.strike("oom", "other")
    assert b.state == "half-open"
    # the probe's own failure is what re-opens
    b.strike("hang", "g", probe=True)
    assert b.state == "open"


def test_breaker_probe_settles_on_data_failure():
    """A probe group that fails with a per-hole `data` error strikes
    nothing — but the probe token must still be released, or the
    breaker wedges half-open forever (admit() refuses everything and
    success() can then never run)."""
    m = Metrics()
    cfg = types.SimpleNamespace(dispatch_deadline_s=0.0,
                                breaker_strikes=1, breaker_window_s=60.0,
                                breaker_probe_s=0.05)
    resil = Resilience(cfg, metrics=m)
    calls = {"n": 0}

    def dispatch(idxs, key):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")  # trip
        if calls["n"] == 2:
            raise ValueError("bad draft")                # data probe
        return np.zeros(2)

    def finish(idxs, key, out):
        results[0] = "device"

    def run_one():
        _run_groups_recovering({"g": [0]}, dispatch, finish,
                               lambda i: "host", results, m,
                               label=lambda k: "grp", resil=resil)

    results = [None]
    run_one()                       # OOM ladder-bottom: trip open
    assert m.breaker_state == "open"
    time.sleep(0.06)
    results = [None]
    run_one()                       # probe fails with a DATA error
    assert results[0] == "host"
    # not wedged half-open: back to open with a re-armed probe timer
    assert m.breaker_state == "open"
    time.sleep(0.06)
    results = [None]
    run_one()                       # next probe succeeds: closed
    assert results[0] == "device" and m.breaker_state == "closed"


def test_breaker_disabled_and_window():
    b = CircuitBreaker(strikes=0)
    for _ in range(10):
        b.strike("oom", "g")
        assert b.admit() and b.state == "closed"   # disabled: inert
    b = CircuitBreaker(strikes=2, window_s=0.05)
    b.strike("oom", "g")
    time.sleep(0.08)
    b.strike("oom", "g")          # first strike aged out of the window
    assert b.state == "closed"


def test_breaker_probe_recovers_through_recovery_ladder():
    """Half-open re-probe at the _run_groups_recovering level: trip on
    a ladder-bottom OOM, host-path completion while open, then a
    successful probe closes the breaker and device dispatch resumes."""
    m = Metrics()
    cfg = types.SimpleNamespace(dispatch_deadline_s=0.0,
                                breaker_strikes=1, breaker_window_s=60.0,
                                breaker_probe_s=0.05)
    resil = Resilience(cfg, metrics=m)
    calls = {"n": 0}

    def dispatch(idxs, key):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        return np.zeros(2)

    def finish(idxs, key, out):
        for i in idxs:
            results[i] = "device"

    def host_one(i):
        return "host"

    def run_one():
        _run_groups_recovering({"g": [0]}, dispatch, finish, host_one,
                               results, m, label=lambda k: "grp",
                               resil=resil)

    # 1-request group OOMs -> ladder bottom -> strike -> trip (strikes=1)
    results = [None]
    run_one()
    assert results[0] == "host" and m.breaker_state == "open"
    assert m.breaker_trips == 1 and m.host_fallbacks == 1
    # while open: host path, the device is never touched
    results = [None]
    run_one()
    assert results[0] == "host" and calls["n"] == 1
    # probe due: one device dispatch, success closes the breaker
    time.sleep(0.06)
    results = [None]
    run_one()
    assert results[0] == "device" and calls["n"] == 2
    assert m.breaker_state == "closed" and m.breaker_probes == 1


# ---------- CLI: hang recovery (THE acceptance case) ----------

def test_injected_permanent_hang_completes_byte_identical(
        corpus, tmp_path, capsys):
    """A permanently wedged dispatch (device_hang sleeps 60 s, far past
    any budget here) is abandoned at the --dispatch-deadline and its
    group replays on the host path: the run completes byte-identical
    to the fault-free run at rc 0, marked degraded, with no human
    intervention and no infinite stall."""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    m = tmp_path / "m.jsonl"
    faultinject.arm("device_hang@1")
    t0 = time.monotonic()
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--dispatch-deadline", "2",
                   "--metrics", str(m), str(fa), str(out)])
    assert rc == 0
    assert time.monotonic() - t0 < 60  # did NOT wait out the hang
    assert out.read_bytes() == ref.read_bytes()
    final = _final(m)
    assert final["device_hangs"] >= 1
    assert final["degraded"]
    assert final["host_fallbacks"] >= 1
    err = capsys.readouterr().err
    assert "dispatch deadline" in err and "host path" in err


def test_deadline_off_is_todays_behavior(corpus, tmp_path):
    """Resilience off (--dispatch-deadline 0, the default): output is
    byte-identical and no resilience counters move.  (The transient
    `stall` fault still completes without a deadline — it sleeps and
    returns, it does not wedge.)"""
    fa, ref = corpus
    out = tmp_path / "o.fa"
    m = tmp_path / "m.jsonl"
    faultinject.arm("stall@1")
    os.environ["CCSX_FAULT_STALL_S"] = "0.1"
    try:
        rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                       "--metrics", str(m), str(fa), str(out)])
    finally:
        del os.environ["CCSX_FAULT_STALL_S"]
    assert rc == 0
    assert out.read_bytes() == ref.read_bytes()
    final = _final(m)
    assert final["device_hangs"] == 0
    assert final["breaker_trips"] == 0
    assert final["breaker_state"] == "closed"


# ---------- CLI: breaker trip -> host-path completion ----------

def test_breaker_trip_completes_on_host_path(corpus, tmp_path, capsys):
    fa, ref = corpus
    out = tmp_path / "o.fa"
    m = tmp_path / "m.jsonl"
    faultinject.arm("device_oom@1+")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--breaker-strikes", "2",
                   "--metrics", str(m), str(fa), str(out)])
    faultinject.disarm()
    assert rc == 0
    assert out.read_bytes() == ref.read_bytes()
    final = _final(m)
    assert final["breaker_trips"] >= 1
    assert final["breaker_state"] == "open"   # no probe configured
    assert final["host_fallbacks"] >= 1
    assert len(final["breaker_strike_log"]) >= 2
    assert "CIRCUIT BREAKER OPEN" in capsys.readouterr().err


# ---------- CLI: failure-rate abort (--max-failed-holes) ----------

def test_failed_hole_count_budget_aborts_rc2(corpus, tmp_path, capsys):
    fa, _ = corpus
    for batch in ("on", "off"):
        out = tmp_path / f"o_{batch}.fa"
        faultinject.arm("compute@1+")
        rc = cli.main(["-A", "-m", "1000", "--batch", batch,
                       "--max-failed-holes", "1", str(fa), str(out)])
        faultinject.disarm()
        assert rc == exitcodes.RC_FAILED_HOLES == 2
        assert "failed-hole budget exceeded" in capsys.readouterr().err


def test_failed_hole_fraction_budget(corpus, tmp_path, capsys):
    """Fraction form: settled at end of run against processed holes —
    1 failure in 3 holes passes a 0.5 budget, fails a 0.1 budget."""
    fa, _ = corpus
    out = tmp_path / "o.fa"
    faultinject.arm("compute@2")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--max-failed-holes", "0.5", str(fa), str(out)])
    faultinject.disarm()
    assert rc == 0
    faultinject.arm("compute@2")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--max-failed-holes", "0.1", str(fa),
                   str(tmp_path / "o2.fa")])
    faultinject.disarm()
    assert rc == exitcodes.RC_FAILED_HOLES
    assert "failed-hole budget exceeded" in capsys.readouterr().err


def test_failure_budget_units():
    cfg = types.SimpleNamespace(max_failed_holes=None)
    m = Metrics()
    m.holes_failed = 10 ** 6
    check_failure_budget(m, cfg)                     # unbounded: never
    cfg.max_failed_holes = 0.0                       # count 0: any fails
    with pytest.raises(FailureBudgetExceeded):
        check_failure_budget(m, cfg)
    m = Metrics()
    m.holes_failed, m.holes_out = 2, 8
    cfg.max_failed_holes = 2.0
    check_failure_budget(m, cfg, final=True)         # at budget: ok
    m.holes_failed = 3
    with pytest.raises(FailureBudgetExceeded):
        check_failure_budget(m, cfg)                 # past it: abort
    m.holes_failed = 2
    cfg.max_failed_holes = 0.25
    check_failure_budget(m, cfg, final=True)         # 2/10 <= 25%
    cfg.max_failed_holes = 0.1
    with pytest.raises(FailureBudgetExceeded):
        check_failure_budget(m, cfg, final=True)     # 2/10 > 10%
    # fraction judged mid-run only against a KNOWN total
    m2 = Metrics()
    m2.holes_failed, m2.holes_total = 5, 10
    cfg.max_failed_holes = 0.2
    with pytest.raises(FailureBudgetExceeded):
        check_failure_budget(m2, cfg)
    # resumed runs: the fraction denominator spans the whole logical
    # run (prior sessions' journaled emissions included) — 2 failures
    # against 90 prior + 8 current successes is 2%, not 20%
    m3 = Metrics()
    m3.holes_failed, m3.holes_out, m3.holes_prior_emitted = 2, 8, 90
    cfg.max_failed_holes = 0.05
    check_failure_budget(m3, cfg, final=True)
    m3.holes_prior_emitted = 0
    with pytest.raises(FailureBudgetExceeded):
        check_failure_budget(m3, cfg, final=True)


def test_resilience_knobs_do_not_invalidate_resume():
    """Deadline/breaker/budget knobs choose WHERE a request computes
    (or the rc), never output bytes — adding them on a resume (the
    canonical 'it hung, re-run WITH --dispatch-deadline' move) must
    not refuse the journal as a config change."""
    import dataclasses as dc

    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.utils.fingerprint import config_fingerprint

    a = CcsConfig()
    b = dc.replace(a, dispatch_deadline_s=30.0, breaker_strikes=5,
                   breaker_window_s=10.0, breaker_probe_s=60.0,
                   max_failed_holes=0.1)
    assert config_fingerprint(a) == config_fingerprint(b)
    # ...while an output-shaping field still invalidates
    c = dc.replace(a, refine_iters=3)
    assert config_fingerprint(a) != config_fingerprint(c)


def test_cli_rejects_bad_budget(tmp_path, capsys):
    # 1.5: a non-integer count would be silently int()-truncated to a
    # tighter budget than asked — rejected at parse time instead
    for bad in ("-3", "inf", "nan", "x", "1.5"):
        rc = cli.main(["--max-failed-holes", bad, "x.fa",
                       str(tmp_path / "y.fa")])
        assert rc == 1, bad
        assert "--max-failed-holes" in capsys.readouterr().err


def test_failure_budget_survives_journal_resume(corpus, tmp_path):
    """The budget is judged over the whole LOGICAL run: journaled
    failures are restored on resume (journal v2 holes_failed), so a
    resume cannot silently grant a fresh failure budget and complete
    rc 0 with the near-empty output the flag refuses."""
    fa, _ = corpus
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    args = ["-A", "-m", "1000", "--batch", "on", "--journal", str(jp),
            "--max-failed-holes", "2", str(fa), str(out)]
    os.environ["CCSX_JOURNAL_FSYNC_S"] = "0"
    try:
        faultinject.arm("compute@1+")
        rc = cli.main(args)        # holes 1-2 fail within budget, 3 over
        faultinject.disarm()
        assert rc == exitcodes.RC_FAILED_HOLES
        assert json.loads(jp.read_text())["holes_failed"] == 2
        # the resume restores the 2 journaled failures: one more
        # failure is over budget again — NOT a fresh budget of 2
        faultinject.arm("compute@1+")
        rc = cli.main(args)
        faultinject.disarm()
        assert rc == exitcodes.RC_FAILED_HOLES
    finally:
        del os.environ["CCSX_JOURNAL_FSYNC_S"]


# ---------- exit-code taxonomy: pinned so it cannot drift ----------

def test_exit_code_taxonomy_pinned():
    assert exitcodes.RC_OK == 0
    assert exitcodes.RC_FATAL == 1
    assert exitcodes.RC_FAILED_HOLES == 2
    assert exitcodes.RC_INTERRUPTED == 75
    assert exitcodes.RC_INJECTED_KILL == faultinject.EXIT_CODE == 57


def test_exit_codes_documented():
    """README and ARCHITECTURE.md carry the taxonomy table: every
    documented code row must exist, so a code change forces a doc
    change (and vice versa)."""
    readme = open(os.path.join(_REPO, "README.md")).read()
    arch = open(os.path.join(_REPO, "ARCHITECTURE.md")).read()
    for doc, name in ((readme, "README"), (arch, "ARCHITECTURE")):
        for row in ("| 0 |", "| 1 |", "| 2 |", "| 75 |", "| 57 |"):
            assert row in doc, f"{name} is missing exit-code row {row}"
    assert "--max-failed-holes" in readme
    assert "--dispatch-deadline" in readme
    assert "--salvage" in readme
    assert "--max-record-bytes" in readme
    assert "shepherd" in readme
