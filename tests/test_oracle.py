import numpy as np
import pytest

from ccsx_tpu.ops import encode as enc
from ccsx_tpu.ops import oracle
from ccsx_tpu.utils import synth


def _cigar_consumes(rs):
    qc = sum(l for op, l in rs.cigar if op in "MI")
    tc = sum(l for op, l in rs.cigar if op in "MD")
    return qc, tc


def test_global_identical():
    q = enc.encode("ACGTACGTAC")
    rs = oracle.align(q, q, mode="global")
    assert rs.mat == 10 and rs.mis == 0 and rs.ins == 0 and rs.del_ == 0
    assert rs.score == 20
    assert rs.qb == 0 and rs.qe == 10 and rs.tb == 0 and rs.te == 10


def test_global_single_mismatch():
    q = enc.encode("ACGTACGTAC")
    t = q.copy()
    t[4] = (t[4] + 1) % 4
    rs = oracle.align(q, t, mode="global")
    assert rs.mat == 9 and rs.mis == 1
    assert rs.score == 9 * 2 - 6


def test_global_gap_costs():
    q = enc.encode("ACGTACGTAC")
    t = np.concatenate([q[:5], q[7:]])  # delete 2 bases from template
    rs = oracle.align(q, t, mode="global")
    assert rs.ins == 2  # two query-only bases
    assert rs.score == 8 * 2 + (-3 + 2 * -2)


def test_traceback_consumes_spans():
    rng = np.random.default_rng(2)
    for _ in range(10):
        q = rng.integers(0, 4, rng.integers(5, 60)).astype(np.uint8)
        t = rng.integers(0, 4, rng.integers(5, 60)).astype(np.uint8)
        for mode in ("global", "qfree", "local"):
            rs = oracle.align(q, t, mode=mode)
            qc, tc = _cigar_consumes(rs)
            assert qc == rs.qe - rs.qb
            assert tc == rs.te - rs.tb
            assert rs.aln == rs.mat + rs.mis + rs.ins + rs.del_
            if mode == "global":
                assert (rs.qb, rs.qe, rs.tb, rs.te) == (0, len(q), 0, len(t))


def test_qfree_clips_query():
    rng = np.random.default_rng(3)
    t = rng.integers(0, 4, 80).astype(np.uint8)
    junk1 = rng.integers(0, 4, 30).astype(np.uint8)
    junk2 = rng.integers(0, 4, 25).astype(np.uint8)
    q = np.concatenate([junk1, t, junk2])
    rs = oracle.align(q, t, mode="qfree")
    assert rs.tb == 0 and rs.te == 80
    # clipped query span should recover the embedded template closely
    assert abs(rs.qb - 30) <= 3 and abs(rs.qe - 110) <= 3
    assert rs.identity > 0.9


def test_local_finds_common_core():
    rng = np.random.default_rng(4)
    core = rng.integers(0, 4, 50).astype(np.uint8)
    q = np.concatenate([rng.integers(0, 4, 20).astype(np.uint8), core])
    t = np.concatenate([core, rng.integers(0, 4, 15).astype(np.uint8)])
    rs = oracle.align(q, t, mode="local")
    assert rs.mat >= 45
    assert rs.qb >= 15 and rs.te <= 55


def test_strand_match_oracle_accepts_same_strand():
    rng = np.random.default_rng(5)
    z = synth.make_zmw(rng, template_len=300, n_passes=2, first_strand=0)
    fwd = z.passes[0]
    rev = z.passes[1]  # reverse strand pass
    ok, rs = oracle.strand_match_oracle(fwd, z.template, 75)
    assert ok and rs.identity >= 0.85
    ok_rc, _ = oracle.strand_match_oracle(enc.revcomp_codes(rev), z.template, 75)
    assert ok_rc
    ok_wrong, _ = oracle.strand_match_oracle(rev, z.template, 75)
    assert not ok_wrong


def test_projection_roundtrip_identical():
    q = enc.encode("ACGTACGT")
    rs = oracle.align(q, q, mode="global")
    aligned, ins_len, ins_bases, covered = oracle.project_to_template(rs, q, len(q))
    assert np.array_equal(aligned, q)
    assert ins_len.sum() == 0
    assert covered.all()


def test_projection_insertion_and_deletion():
    t = enc.encode("ACGTACGT")
    # query: insert two bases after template pos 3, delete template pos 6
    q = np.concatenate([t[:4], enc.encode("GG"), t[4:6], t[7:]])
    rs = oracle.align(q, t, mode="global")
    aligned, ins_len, ins_bases, covered = oracle.project_to_template(rs, q, len(t))
    assert ins_len.sum() == 2
    assert (aligned == 4).sum() == 1
    # non-gap cells must equal the template where no errors were introduced
    assert np.array_equal(aligned[:4], t[:4])


def test_projection_query_base_conservation():
    rng = np.random.default_rng(6)
    t = rng.integers(0, 4, 120).astype(np.uint8)
    q = synth.mutate(rng, t, 0.05, 0.05, 0.05)
    rs = oracle.align(q, t, mode="global")
    aligned, ins_len, ins_bases, covered = oracle.project_to_template(rs, q, len(t))
    consumed = int((aligned < 4).sum() + ins_len.sum())
    assert consumed == len(q)


@pytest.mark.parametrize("n_passes", [3, 5])
def test_synth_passes_identity(n_passes):
    rng = np.random.default_rng(7)
    z = synth.make_zmw(rng, template_len=200, n_passes=n_passes)
    for p, strand in zip(z.passes, z.strands):
        oriented = enc.revcomp_codes(p) if strand else p
        assert synth.identity(oriented, z.template) > 0.8
