"""Corruption taxonomy + salvage plumbing for the ingest plane.

The readers used to be all-or-nothing: any corrupt byte in a BAM/FASTA/
BGZF stream raised a bare error and the whole run died with every
healthy hole unemitted.  This module pins the failure taxonomy — every
way the Python AND native readers can fail gets a stable reason code,
shared by both stacks (io_native.cpp mirrors REASONS verbatim; the
differential fuzz tests hold the two stacks to the same classification
on the same mutant) — and carries the salvage-mode accounting.

Reason codes (pinned; tests/test_salvage.py::test_reason_codes_pinned):

  bam_bad_header       BAM magic/header region unparseable
  bgzf_bad_block       malformed BGZF block header (magic/BC/BSIZE)
  bgzf_bad_deflate     a BGZF block's payload failed inflate/CRC/ISIZE
  bgzf_torn_tail       BGZF stream truncated mid-block
  bgzf_missing_eof     the 28-byte BGZF EOF marker is absent at stream
                       end.  Booked + degrades the run, but EXEMPT from
                       the --max-failed-holes budget: a healthy file
                       that merely lost its marker emits every hole
                       intact, and spending budget on it would rc-2 a
                       complete output (a truncation exactly at a block
                       boundary is indistinguishable — that risk is
                       inherent to the marker's design)
  gzip_truncated       plain-gzip stream truncated or corrupt (no block
                       structure to resync on: the rest of the stream
                       is lost)
  bam_bad_record       corrupt alignment-record fields (bad length,
                       negative l_seq, fields overflowing the block)
  bam_record_oversize  record length exceeds --max-record-bytes — the
                       allocation bound (a corrupt int32 must not
                       drive a multi-GB allocation)
  fastx_qual_mismatch  FASTQ quality length != sequence length
  fastx_truncated      FASTA/Q stream ended mid-record
  zmw_bad_name         subread name not movie/hole/region
  injected             the ``input_corrupt`` fault point
                       (utils/faultinject.py)

Salvage semantics (``--salvage``): a classified corruption drops the
damaged bytes and the reader RESYNCS — BGZF: scan forward for the next
valid block header (magic + BC subfield + a BSIZE that chains to
another block header or EOF); BAM records: scan the inflated stream
for the next plausible record start (see ``record_plausible``); FASTA/
Q: skip to the next '>'/'@' line anchor.  Surviving records flow on:
a hole that lost records emits a consensus from its surviving passes
(it is damaged either way — the oracle only constrains UNDAMAGED
holes), every event books into Metrics.holes_corrupt with per-reason
buckets, the run is marked degraded, and corrupt events feed the
--max-failed-holes budget.  Salvage OFF (the default) preserves the
historical fail-fast behavior byte-for-byte: first classified
corruption raises and the run exits rc 1.
"""

from __future__ import annotations

import struct

REASONS = (
    "bam_bad_header",
    "bgzf_bad_block",
    "bgzf_bad_deflate",
    "bgzf_torn_tail",
    "bgzf_missing_eof",
    "gzip_truncated",
    "bam_bad_record",
    "bam_record_oversize",
    "fastx_qual_mismatch",
    "fastx_truncated",
    "zmw_bad_name",
    "injected",
)

# reasons that degrade the run but do NOT spend the --max-failed-holes
# budget (no hole is provably lost; see the taxonomy notes above)
NON_BUDGET_REASONS = ("bgzf_missing_eof",)

# allocation bound on a single BAM alignment record (--max-record-bytes):
# checked BEFORE allocating, so a corrupt int32 length cannot drive a
# multi-GB allocation.  256 MiB is far above any real subread record
# (a 500 kb subread is ~0.75 MB of block) but far below damage.
DEFAULT_MAX_RECORD_BYTES = 256 * 1024 * 1024


class CorruptionError(ValueError):
    """A classified ingest corruption.  ``reason`` is one of REASONS —
    the stable code both reader stacks report for this failure mode."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


class SalvageSink:
    """Salvage-mode accounting shared by the Python readers: every
    classified corruption books one event here.  ``metrics`` (optional,
    a utils.metrics.Metrics) receives holes_corrupt / corrupt_reasons
    live plus the degraded mark — the native reader books the same
    counters from its in-library counts (native/io.py)."""

    def __init__(self, metrics=None, max_record_bytes: int = 0):
        self.metrics = metrics
        self.max_record_bytes = max_record_bytes or DEFAULT_MAX_RECORD_BYTES
        self.events = 0
        self.reasons: dict = {}

    def record(self, reason: str, detail: str = "") -> None:
        self.events += 1
        self.reasons[reason] = self.reasons.get(reason, 0) + 1
        m = self.metrics
        if m is not None:
            m.bump(holes_corrupt=1)
            with m._count_lock:
                m.corrupt_reasons[reason] = (
                    m.corrupt_reasons.get(reason, 0) + 1)
            if not m.degraded:
                m.degraded = "input corruption (salvaged)"


# ---- BAM record plausibility (the record-resync scan contract) ----------
#
# After a BGZF gap or a corrupt record, salvage scans the inflated
# stream byte-by-byte for the next plausible alignment-record start.
# The predicate below IS the contract — io_native.cpp implements the
# same checks with the same constants, and the differential fuzz test
# holds both stacks to the same salvaged record set.  A candidate at
# offset p (p points at the record's 4-byte block_size) passes iff:
#
#   * 34 <= block_size <= max_record_bytes   (32 fixed + 2-byte name)
#   * refid == -1 or 0 <= refid < 100000
#   * pos >= -1
#   * l_read_name >= 2                        (1+ chars + NUL)
#   * l_seq >= 0
#   * 32 + l_read_name + 4*n_cigar + (l_seq+1)//2 + l_seq <= block_size
#   * name bytes are printable ASCII (0x21..0x7E) ending in NUL
#
# SCAN_LOOKAHEAD bytes suffice to evaluate any candidate (4 + 32 fixed
# + 255-byte max name).

SCAN_LOOKAHEAD = 4 + 32 + 255
MIN_RECORD_BLOCK = 34


def record_plausible(buf, p: int, max_record_bytes: int) -> bool:
    """True when ``buf[p:]`` plausibly starts a BAM alignment record
    (the salvage resync predicate; see the contract above).  ``buf``
    must hold at least SCAN_LOOKAHEAD bytes past p, or reach the true
    end of the stream."""
    if len(buf) - p < 36:
        return False
    (block_size,) = struct.unpack_from("<i", buf, p)
    if not MIN_RECORD_BLOCK <= block_size <= max_record_bytes:
        return False
    refid, pos = struct.unpack_from("<ii", buf, p + 4)
    if not (refid == -1 or 0 <= refid < 100000) or pos < -1:
        return False
    lrn = buf[p + 12]
    if lrn < 2:
        return False
    (n_cigar,) = struct.unpack_from("<H", buf, p + 16)
    (l_seq,) = struct.unpack_from("<i", buf, p + 20)
    if l_seq < 0:
        return False
    if 32 + lrn + 4 * n_cigar + (l_seq + 1) // 2 + l_seq > block_size:
        return False
    name = buf[p + 36:p + 36 + lrn]
    if len(name) < lrn:
        return False
    if name[-1] != 0:
        return False
    for b in name[:-1]:
        if not 0x21 <= b <= 0x7E:
            return False
    return True
