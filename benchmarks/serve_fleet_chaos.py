"""Replica-fleet churn soak: N warm servers, one spool, zero lost jobs.

The r16 fleet's claim (pipeline/serve.py fleet mode +
pipeline/gateway.py spool protocol) is that the SPOOL, not any
replica, owns the jobs: every queued job is leased with the same
audited O_EXCL + heartbeat + kill-before-steal machinery as the PR 13
range queue, and completed work is fenced by an exclusive done marker.
Replica churn must therefore cost availability only — never a job,
never a duplicate emission, never a byte.

This soak drives a real 3-replica subprocess fleet through:

  warm wave     W small jobs through the gateway -> all done,
                byte-identical, per-replica compile tables recorded
  churn wave    W small jobs + 1 fan-out job (>= --fanout-holes, split
                through the range queue across replicas); one replica
                is SIGKILLed mid-wave while holding job leases, and a
                4th replica JOINS mid-run.  Every job must end done
                with EXACTLY one done marker; the killed replica's
                leased jobs must be completed by survivors;
                every output byte-identical to the solo CLI reference
  steady wave   W jobs timed across the surviving fleet -> sustained
                fleet zmws/s (the number bench.py's SERVE-FLEET leg
                gates with the 20% rule) and ZERO new compiles summed
                over every live replica's /metrics group table
  drain         SIGTERM fans out; every replica exits rc 0/75 with
                its leases released

Schedules are pure functions of ``--seed`` (replayable); the corpus
builder and reference runner are benchmarks/chaos.py's.  The fast
deterministic slices of this story are tier-1
(tests/test_serve_fleet.py, tests/test_lease.py); this soak is the
composition proof:

    python benchmarks/serve_fleet_chaos.py --seed 0 \
        --json benchmarks/serve_fleet_rNN.json   (`make serve-fleet-chaos`)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CCSX_JOURNAL_FSYNC_S"] = "0"
os.environ["CCSX_DEADLINE_GRACE"] = "1"

import numpy as np                                            # noqa: E402

from ccsx_tpu import exitcodes                                # noqa: E402
from ccsx_tpu.pipeline import gateway as spoolproto           # noqa: E402
from ccsx_tpu.utils import lease as leaselib                  # noqa: E402
from benchmarks.chaos import make_corpus, run_reference       # noqa: E402

# the replica runner: backend-pinned like the shepherd's children
# (accelerator plugins can override JAX_PLATFORMS at import time)
_PRELUDE = "import jax; jax.config.update('jax_platforms', 'cpu'); "
_RUNNER = ("import sys; from ccsx_tpu.cli import main; "
           "sys.exit(main(sys.argv[1:]))")


def _spawn_replica(spool: str, name: str, base_port: int,
                   fanout_holes: int, fanout_ranges: int, log_dir: str,
                   lease_timeout: float):
    # the lease timeout must tolerate heartbeat stalls from CPU
    # oversubscription (N replicas warming on few cores) — too tight
    # and kill-before-steal turns contention into fratricide
    cmd = [sys.executable, "-c", _PRELUDE + _RUNNER, "serve",
           "--fleet", spool, "-A", "-m", "1000",
           "--port", str(base_port), "--replica-name", name,
           "--lease-timeout", str(lease_timeout), "--poll", "0.1",
           "--fanout-holes", str(fanout_holes),
           "--fanout-ranges", str(fanout_ranges),
           "--max-active", "2"]
    log = open(os.path.join(log_dir, f"{name}.log"), "ab")
    proc = subprocess.Popen(cmd, env=dict(os.environ), stdout=log,
                            stderr=subprocess.STDOUT)
    return proc, log


def _probe_ready(rep: dict) -> bool:
    """Live readiness by actually asking the replica (a SIGKILLed
    replica's stale slot lease still LOOKS ready for one timeout)."""
    if not rep.get("port"):
        return False
    url = f"http://{rep['addr']}:{rep['port']}/readyz"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return bool(json.loads(resp.read() or b"{}").get("ready"))
    except (OSError, ValueError):
        return False


def _wait_ready(spool: str, want: int, timeout: float = 600.0) -> list:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reps = [r for r in spoolproto.discover_replicas(spool)
                if _probe_ready(r)]
        if len(reps) >= want:
            return reps
        time.sleep(0.5)
    raise RuntimeError(
        f"fleet never reached {want} ready replicas: "
        f"{spoolproto.discover_replicas(spool)}")


def _scrape_compiles(spool: str) -> dict:
    """{replica_name: summed ccsx_group_compiles} over every live
    replica's /metrics — the per-replica steady-state recompile
    ledger."""
    out = {}
    for r in spoolproto.discover_replicas(spool):
        url = f"http://{r['addr']}:{r['port']}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                text = resp.read().decode()
        except (OSError, ValueError):
            continue
        total = 0
        for ln in text.splitlines():
            if ln.startswith("ccsx_group_compiles{"):
                try:
                    total += int(float(ln.rsplit(None, 1)[1]))
                except (IndexError, ValueError):
                    pass
        out[r["name"]] = total
    return out


def _submit_wave(gw, in_fa: str, n: int) -> list:
    return [gw.submit(input_path=in_fa) for _ in range(n)]


def _wait_jobs(spool: str, jids: list, timeout: float = 900.0) -> dict:
    views = {}
    deadline = time.monotonic() + timeout
    pending = set(jids)
    while pending and time.monotonic() < deadline:
        for jid in sorted(pending):
            v = spoolproto.job_view(spool, jid)
            if v and v["state"] in ("done", "failed", "cancelled",
                                    "interrupted"):
                views[jid] = v
                pending.discard(jid)
        time.sleep(0.2)
    for jid in pending:
        views[jid] = spoolproto.job_view(spool, jid)  # lost / stuck
    return views


def _bytes(path) -> bytes:
    try:
        return open(path, "rb").read()
    except (OSError, TypeError):
        return b""


def _marker_count(spool: str, jid: str) -> int:
    return sum(1 for n in os.listdir(spool)
               if n == f"done.{jid}.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--holes", type=int, default=6)
    ap.add_argument("--big-holes", type=int, default=10,
                    help="fan-out job size (>= --fanout-holes) [10]")
    ap.add_argument("--fanout-holes", type=int, default=8)
    ap.add_argument("--fanout-ranges", type=int, default=3)
    ap.add_argument("--wave-jobs", type=int, default=4)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--lease-timeout", type=float, default=30.0,
                    help="replica job-lease heartbeat timeout; sized "
                         "for CPU-oversubscribed soak boxes [30]")
    ap.add_argument("--base-port", type=int, default=8901)
    ap.add_argument("--json", default=None,
                    help="write the artifact here "
                         "(benchmarks/serve_fleet_rNN.json)")
    a = ap.parse_args(argv)
    rng = np.random.default_rng(a.seed)
    t_start = time.time()
    trials = []
    procs = {}
    logs = []

    with tempfile.TemporaryDirectory() as tmp:
        small_fa = make_corpus(tmp, rng, a.holes)
        ref_small = run_reference(small_fa, tmp)
        big_dir = os.path.join(tmp, "big")
        os.makedirs(big_dir)
        big_fa = make_corpus(big_dir, rng, a.big_holes)
        ref_big = run_reference(big_fa, big_dir)
        spool = os.path.join(tmp, "spool")
        os.makedirs(spool)

        def spawn(name):
            procs[name] = _spawn_replica(
                spool, name, a.base_port, a.fanout_holes,
                a.fanout_ranges, tmp, a.lease_timeout)
            logs.append(procs[name][1])

        try:
            for k in range(a.replicas):
                spawn(f"r{k}")
            _wait_ready(spool, a.replicas)
            gw = spoolproto.Gateway(spool, max_queue=64, probe_s=0.2)

            # ---- warm wave ----
            jids = _submit_wave(gw, small_fa, a.wave_jobs)
            views = _wait_jobs(spool, jids)
            ident = [_bytes((views[j] or {}).get("output")) == ref_small
                     for j in jids]
            warm_compiles = _scrape_compiles(spool)
            t = {"kind": "warm_wave", "jobs": len(jids),
                 "states": [(views[j] or {}).get("state") for j in jids],
                 "identical": ident,
                 "compiles": warm_compiles,
                 "ok": all((views[j] or {}).get("state") == "done"
                           for j in jids) and all(ident)}
            trials.append(t)

            # ---- churn wave: SIGKILL mid-wave + mid-run join ----
            jids = _submit_wave(gw, small_fa, a.wave_jobs)
            big = gw.submit(input_path=big_fa)
            jids.append(big)
            # the victim is the first replica OBSERVED holding a job
            # lease — the kill always lands with work genuinely in
            # flight, never on an idle bystander
            pid_to_name = {p.pid: name
                           for name, (p, _) in procs.items()}
            vic_pid, held = None, []
            deadline = time.monotonic() + 120
            while not held and time.monotonic() < deadline:
                for k, rec in leaselib.list_leases(spool):
                    pid = (rec or {}).get("pid")
                    if k.startswith("j") and pid in pid_to_name:
                        vic_pid = pid
                        held = [k2 for k2, r2
                                in leaselib.list_leases(spool)
                                if r2 and r2.get("pid") == vic_pid
                                and k2.startswith("j")]
                        break
                time.sleep(0.05)
            if vic_pid is None:
                raise RuntimeError("no replica ever held a job lease")
            victim = pid_to_name[vic_pid]
            os.kill(vic_pid, signal.SIGKILL)
            procs[victim][0].wait(timeout=30)
            # a 4th replica joins the running fleet mid-churn
            joiner = f"r{a.replicas}"
            spawn(joiner)
            views = _wait_jobs(spool, jids)
            lost = [j for j in jids
                    if not views[j]
                    or views[j]["state"] not in ("done",)]
            dup = [j for j in jids if _marker_count(spool, j) != 1]
            ident = [_bytes((views[j] or {}).get("output"))
                     == (ref_big if j == big else ref_small)
                     for j in jids]
            stolen = {j: (views[j] or {}).get("replica") for j in held}
            t = {"kind": "churn_wave", "jobs": len(jids),
                 "killed": victim, "killed_pid": vic_pid,
                 "killed_held_leases": held, "joined": joiner,
                 "fanout_job": big,
                 "completed_by": {j: (views[j] or {}).get("replica")
                                  for j in jids},
                 "lost": lost, "duplicated": dup, "identical": ident,
                 "ok": (not lost and not dup and all(ident)
                        and bool(held)
                        and all(r and r != victim
                                for r in stolen.values()))}
            trials.append(t)

            # ---- rewarm: saturate every survivor (incl. the joiner)
            # so the steady wave's zero-recompile claim covers the
            # WHOLE fleet.  2*max_active*survivors jobs exceed the two
            # incumbents' capacity, forcing work onto the joiner.
            _wait_ready(spool, a.replicas)       # joiner up, victim out
            jids = _submit_wave(gw, small_fa, 2 * a.replicas)
            views = _wait_jobs(spool, jids)
            rewarm_ok = all((views[j] or {}).get("state") == "done"
                            for j in jids)
            trials.append({"kind": "rewarm", "jobs": len(jids),
                           "by": sorted({(views[j] or {}).get("replica")
                                         for j in jids}),
                           "ok": rewarm_ok})

            # ---- steady wave: sustained fleet rate, zero compiles ----
            pre = _scrape_compiles(spool)
            t0 = time.monotonic()
            jids = _submit_wave(gw, small_fa, a.wave_jobs)
            views = _wait_jobs(spool, jids)
            wall = time.monotonic() - t0
            post = _scrape_compiles(spool)
            recompiles = sum(post.get(r, 0) - pre.get(r, 0)
                             for r in post)
            ident = [_bytes((views[j] or {}).get("output")) == ref_small
                     for j in jids]
            steady = {"kind": "steady_wave", "jobs": a.wave_jobs,
                      "wall_s": round(wall, 2),
                      "zmws_per_sec":
                      round(a.wave_jobs * a.holes / wall, 3),
                      "recompiles": recompiles,
                      "per_replica_compiles": post,
                      "ok": (all((views[j] or {}).get("state") == "done"
                                 for j in jids)
                             and all(ident) and recompiles == 0)}
            trials.append(steady)

            # ---- drain: SIGTERM fans out, leases released ----
            for name, (p, _) in procs.items():
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            rcs = {}
            for name, (p, _) in procs.items():
                if name == victim:
                    continue
                try:
                    rcs[name] = p.wait(timeout=120)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rcs[name] = "hung"
            job_leases = [k for k, _ in leaselib.list_leases(spool)
                          if k.startswith("j")]
            t = {"kind": "drain", "rcs": rcs,
                 "job_leases_left": job_leases,
                 "ok": (all(rc in (0, exitcodes.RC_INTERRUPTED)
                            for rc in rcs.values())
                        and not job_leases)}
            trials.append(t)
        finally:
            for name, (p, _) in procs.items():
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
            for log in logs:
                try:
                    log.close()
                except OSError:
                    pass

    churn = next(t for t in trials if t["kind"] == "churn_wave")
    n_failed = sum(1 for t in trials if not t.get("ok"))
    out = {"seed": a.seed, "holes": a.holes,
           "big_holes": a.big_holes, "replicas": a.replicas,
           "steady": next(t for t in trials
                          if t["kind"] == "steady_wave"),
           "lost_jobs": len(churn["lost"]),
           "duplicated_jobs": len(churn["duplicated"]),
           "byte_identical": all(
               all(t.get("identical", [True]))
               for t in trials if "identical" in t),
           "trials": trials, "n_trials": len(trials),
           "n_failed": n_failed, "ok": n_failed == 0,
           "elapsed_s": round(time.time() - t_start, 1)}
    blob = json.dumps(out, indent=1)
    print(blob)
    if a.json:
        with open(a.json, "w") as f:
            f.write(blob)
    return 0 if n_failed == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
